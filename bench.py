"""Headline benchmark: virtual-node SWIM protocol rounds simulated per second.

Simulates a BASELINE config-3-class cluster (10k nodes, 1% packet loss) on
one chip and measures protocol rounds (node-ticks) per wall-clock second.

``vs_baseline``: the reference executes the protocol in real time — every
node runs 5 protocol periods per second (200 ms minProtocolPeriod,
lib/swim/gossip.js:127-129), so a tick-cluster of N real processes
advances 5*N node-rounds per second. ``vs_baseline`` is the speedup of
the TPU simulation over that real-time rate at equal N (i.e. how many
seconds of real-cluster protocol time one TPU-second simulates).

Robustness contract (the driver runs this unattended): the parent process
NEVER touches JAX. It probes the accelerator and runs the measurement in
subprocesses under hard timeouts, falls back to a clearly-labeled CPU
number if the TPU tunnel is broken or hangs, and always prints exactly
ONE JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...[, "platform": ..., "error": ...]}
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

# The dispatch ledger (ringpop_tpu/obs/ledger.py) is jax-free at import,
# so the parent can record forensics rows without touching a backend.
from ringpop_tpu.obs.ledger import ENV_VAR as LEDGER_ENV
from ringpop_tpu.obs.ledger import default_ledger

# Every probe and rung leaves a JSON line here (overridable via
# RINGPOP_LEDGER): the next "accelerator probe timed out after 240s"
# failure ships its own forensics instead of needing a repro session.
DEFAULT_LEDGER_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "bench_ledger.jsonl"
)

REFERENCE_ROUNDS_PER_NODE_SEC = 5.0  # 200 ms protocol period
TICKS_PER_CALL = 20
# The delta tick is ~10-100x cheaper than a dense tick, so its batch is
# longer: at ~15 ms/tick a 20-tick batch would give the ~70 ms tunnel
# sync a 20% share of the measurement.
DELTA_TICKS_PER_CALL = 100
REPEATS = 3

PROBE_TIMEOUT_S = 240
TPU_BENCH_TIMEOUT_S = 900
# The delta programs are the ones whose first compile can legitimately
# run long on the tunneled platform (remote compile); with the
# persistent compilation cache below, a warm run is fast.
TPU_DELTA_TIMEOUT_S = 1500
# How many timed-out TPU attempts may continue past a successful
# re-probe before giving up on the TPU phase entirely: a half-sick
# tunnel (trivial probe works, real programs hang) must not turn the
# unattended bench into hours of serial timeouts.  Budgeted for the
# ladder shape: the two speculative rungs above 65,536 may legitimately
# time out on a cold compile and must not starve the known-good
# 65,536 rungs of their original two-timeout allowance.
MAX_TPU_TIMEOUTS = 4
CPU_BENCH_TIMEOUT_S = 600


class CapacityOverflow(RuntimeError):
    """A delta run dropped updates (capacity overflow): the simulated
    protocol degraded, so the measurement must not become the headline —
    the caller falls through to the next (larger-capacity) attempt."""

# (layout, n) attempts, first success wins.  The delta layout
# (models/swim_delta.py, O(N*C) state) is the 65k+ north-star path; the
# dense N x N layout is the fallback.  OOM shrinks the cluster.
# ``delta@CAP`` pins the table capacity: the headline scenario's
# measured occupancy is ~1 slot/viewer (steady state + 1% loss), and
# every per-tick sort/searchsorted scales with the static capacity, so
# the bench uses C=64 (still 64x the observed occupancy; overflow_drops
# is asserted zero) with C=256 as the robustness fallback.
TPU_DELTA_LADDER = (
    # ASCENDING: the round-5 tunnel session showed the 65,536 delta
    # program can CRASH the TPU worker outright ("UNAVAILABLE: TPU
    # worker process crashed or restarted"), wedging the tunnel for
    # 10+ minutes — a descending walk then banks NOTHING on-chip.
    # Climbing banks every rung as it goes; the headline is the
    # LARGEST rung clearing vs_baseline >= 1.0 (the last, since n
    # ascends), and a crash stops the climb with the prior rungs
    # already in hand.
    #
    # The banked rungs below 65,536 run STREAMED (``+stream``): the
    # tick batch is dispatched as STREAM_SEGMENTS back-to-back
    # segment-sized delta_run programs (the scenarios/stream.py
    # segment-dispatch shape) instead of one monolithic 100-tick
    # scan.  Each compiled program is 4x smaller — itself a plausible
    # fix for the worker crash, and it keeps the banked ladder's
    # programs disjoint from the flagship one under suspicion.  The
    # 65,536+ rungs stay monolithic: they measure the exact program
    # whose footprint analysis/budgets.py pins.
    ("delta@64+stream", 8192),
    ("delta@64+stream", 16384),
    ("delta@64+stream", 32768),
    ("delta@64", 65536),
    ("delta@256", 65536),
    ("delta@64", 131072),
    ("delta@64", 262144),
)
TPU_DENSE_ATTEMPTS = (
    # safety net, descending (first green wins), only when no delta
    # rung produced any result at all
    ("dense", 32768),
    ("dense", 16384),
    ("dense", 10240),
    ("dense", 8192),
    ("dense", 4096),
    ("dense", 2048),
    ("dense", 1024),
)
# The delta layout is also the better CPU fallback: its O(N*C) tick
# clears real time on the single-core host at n=8192 (the dense sizes
# remain as safety nets).
CPU_ATTEMPTS = (
    ("delta@64", 8192),
    ("dense", 2048),
    ("dense", 1024),
    ("dense", 512),
)
# A tunnel-dead round should still record the LARGEST n the host can
# demonstrate, not a fixed 8,192 (sub-1.0 vs_baseline accepted and
# labeled): each rung runs in its own child under its own watchdog —
# ~1 s/tick at 65k on the single core — with a shortened measurement
# (see bench_once's big-n branch).  Falls through to CPU_ATTEMPTS.
CPU_LADDER = (
    ("delta@64", 65536, 1500),
    ("delta@64+stream", 32768, 600),
)

# ``+stream`` rungs split each tick batch into this many back-to-back
# segment dispatches (scenarios/stream.py's shape, applied to the raw
# delta_run hot loop): same ticks, 4x-smaller compiled programs.
STREAM_SEGMENTS = 4


def _stream_plan(batch_ticks: int) -> tuple[int, int]:
    """(segments, ticks_per_segment) for a ``+stream`` rung's batch.

    Pure so the banked-ladder shape is testable without a backend;
    segments * ticks_per_segment may round below batch_ticks (the rate
    math uses the product, so the measurement stays exact)."""
    seg_ticks = max(1, batch_ticks // STREAM_SEGMENTS)
    return batch_ticks // seg_ticks, seg_ticks


# ---------------------------------------------------------------------------
# child: the actual measurement (runs with a live JAX backend)
# ---------------------------------------------------------------------------


def _sync(metrics) -> int:
    """Force completion by pulling a scalar to the host.

    ``jax.block_until_ready`` is NOT sufficient on the tunneled TPU
    platform — it returns before execution finishes, which silently turns
    the timing into a dispatch-latency measurement (observed: "1e9
    node-rounds/s", ~300x above the HBM-bandwidth bound).  A host
    transfer is an unfakeable barrier."""
    return int(metrics["pings_sent"])


def bench_once(n: int, layout: str = "dense") -> float:
    """Node-rounds/sec of an n-node simulation (best of REPEATS)."""
    import jax

    from ringpop_tpu.models import swim_sim as sim

    repeats = REPEATS
    if layout.startswith("delta"):
        from ringpop_tpu.models import swim_delta as sd

        _, _, cap = layout.partition("@")
        streamed = cap.endswith("+stream")
        if streamed:
            cap = cap[: -len("+stream")]
        params = sd.DeltaParams(
            swim=sim.SwimParams(loss=0.01), wire_cap=16, claim_grid=64
        )
        state = sd.init_delta(n, capacity=int(cap) if cap else 256)

        delta_ticks = DELTA_TICKS_PER_CALL
        if jax.default_backend() == "cpu" and n > 8192:
            # Large-n CPU fallback rung (CPU_LADDER): the full 500-tick
            # measurement at ~1 s/tick (65k single-core) would blow the
            # watchdog; short batches and one repeat trade precision for
            # existence — the JSON is labeled cpu-fallback either way.
            delta_ticks = 20
            repeats = 1

        # The delta state is ~10 bytes/(node*slot) (~170 MB at 65k), so
        # a lax.scan batch fits even double-buffered: one dispatch +
        # one host sync per batch, vs per-tick dispatch whose ~70 ms
        # tunnel sync would dominate a ~15 ms tick.
        if streamed:
            # Segment dispatches (see TPU_DELTA_LADDER): the batch is
            # STREAM_SEGMENTS async back-to-back delta_run programs,
            # still one host sync per batch.  Overflow/occupancy are
            # reduced across segments so the CapacityOverflow guard
            # keeps batch-wide scope.
            import jax.numpy as jnp

            segs, seg_ticks = _stream_plan(delta_ticks)

            def step(st, nt, k, p):
                m = None
                for sk in jax.random.split(k, segs):
                    st, seg_m = sd.delta_run(st, nt, sk, p, seg_ticks)
                    if m is None:
                        m = dict(seg_m)
                    else:
                        m = dict(
                            seg_m,
                            overflow_drops=m["overflow_drops"]
                            + seg_m["overflow_drops"],
                            max_occupancy=jnp.maximum(
                                m["max_occupancy"], seg_m["max_occupancy"]
                            ),
                        )
                return st, m

            ticks_per_step = segs * seg_ticks
        else:
            def step(st, nt, k, p):
                return sd.delta_run(st, nt, k, p, delta_ticks)

            ticks_per_step = delta_ticks
    else:
        params = sim.SwimParams(loss=0.01)
        state = sim.init_state(n)
        # Python-level tick loop over the donated step: async dispatch
        # amortizes the tunnel latency across TICKS_PER_CALL enqueued
        # steps (one host sync per batch), and — unlike lax.scan —
        # donation keeps the state strictly in-place: the scan carry
        # double-buffered the 4 GB view tensor, the difference between
        # fitting 32k nodes and OOM.
        step = sim.swim_step
        ticks_per_step = 1
    key = jax.random.PRNGKey(0)
    net = sim.make_net(n)
    ticks_per_batch = max(TICKS_PER_CALL, ticks_per_step)
    calls_per_batch = ticks_per_batch // ticks_per_step
    keys = jax.random.split(key, (repeats + 1) * calls_per_batch)
    print(f"# compiling {layout} n={n}", file=sys.stderr, flush=True)
    t_cold = time.perf_counter()
    state, metrics = step(state, net, keys[0], params)
    _sync(metrics)
    cold_s = time.perf_counter() - t_cold
    it = iter(keys[1:])
    for _ in range(calls_per_batch - 1):  # warm the steady-state timing
        state, metrics = step(state, net, next(it), params)
    _sync(metrics)
    best = 0.0
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(calls_per_batch):
            state, metrics = step(state, net, next(it), params)
        _sync(metrics)
        dt = time.perf_counter() - t0
        best = max(best, ticks_per_batch * n / dt)
        print(f"# {layout} n={n}: {best:.0f} node-rounds/s", file=sys.stderr, flush=True)
    # per-rung ledger row: the compile-vs-execute split the round-5
    # triage lacked.  cold_total_s is the measured first dispatch
    # (compile + one call's execution + sync); execute_s the warm
    # per-call time at the best rate, so compile_s is their difference
    # — an estimate, but one measured on the production call path
    # instead of an AOT replay that would double-compile on TPU.
    warm_call_s = ticks_per_step * n / best
    default_ledger().record(
        {
            "program": "bench_rung",
            "backend": layout,
            "platform": jax.default_backend(),
            "n": n,
            "ticks": ticks_per_step,
            "replicas": 1,
            "cold": True,
            "cold_total_s": round(cold_s, 3),
            "compile_s": round(max(cold_s - warm_call_s, 0.0), 3),
            "execute_s": round(warm_call_s, 6),
            "node_rounds_per_sec": round(best, 1),
        }
    )
    if layout.startswith("delta"):
        drops = int(metrics["overflow_drops"])
        print(
            f"# delta occupancy max={int(metrics['max_occupancy'])}"
            f" overflow_drops={drops}",
            file=sys.stderr,
            flush=True,
        )
        if drops:
            # A capacity overflow degrades the simulated protocol; the
            # headline number must not come from a degraded run.  Abort
            # the child so the parent falls through to the next attempt
            # (the larger-capacity delta config, then dense).
            raise CapacityOverflow(
                f"delta capacity overflow: {drops} dropped updates at {layout}"
            )
    _device_kernel_checks(state, n, layout)
    return best


def _device_kernel_checks(state, n: int, layout: str = "dense") -> None:
    """Exercise the device kernels on the benched backend (stderr only).

    (a) Pallas farmhash32 against golden vectors — its scheduled
    on-hardware execution (tests run it in interpret mode on CPU);
    (b) the on-device reference-format checksum of live view rows
    against the threaded C kernel at the benched cluster size.
    Failures surface loudly but never corrupt the JSON contract.
    """
    import numpy as np

    try:
        import jax

        if jax.default_backend() != "cpu":
            from ringpop_tpu.ops import ring_ops
            from ringpop_tpu.ops.farmhash import farmhash32
            from ringpop_tpu.ops.farmhash_pallas import farmhash32_batch_pallas

            vecs = [b"test", b"", b"127.0.0.1:3000", b"x" * 100]
            bufs, lens = ring_ops.encode_strings([v.decode() for v in vecs], pad_to=128)
            got = np.asarray(farmhash32_batch_pallas(bufs, lens))
            want = np.array([farmhash32(v) for v in vecs], dtype=np.uint32)
            assert (got == want).all(), f"pallas farmhash mismatch: {got} != {want}"
            print("# pallas farmhash32 on-chip: ok", file=sys.stderr, flush=True)

        from ringpop_tpu.models import checksum as cksum
        from ringpop_tpu.models.cluster import DEFAULT_BASE_INC
        from ringpop_tpu.ops import checksum_device as ckdev

        rows = list(range(0, n, max(1, n // 8)))[:8]
        book_addrs = cksum.default_addresses(n)
        dev_book = ckdev.DeviceBook(book_addrs, DEFAULT_BASE_INC)
        import jax.numpy as jnp

        if layout.startswith("delta"):
            from ringpop_tpu.models import swim_delta as sd

            keys = sd.materialize_rows(state, jnp.asarray(rows))
        else:
            keys = state.view_key[jnp.asarray(rows)]
        dev = np.asarray(ckdev.view_checksums_device(dev_book, keys))
        want = cksum.view_checksums_packed(
            cksum.AddressBook(book_addrs), np.asarray(keys), DEFAULT_BASE_INC
        )
        assert (dev == want).all(), "device checksum mismatch vs C kernel"
        print(
            f"# device checksum vs C kernel at n={n}: ok ({len(rows)} rows)",
            file=sys.stderr,
            flush=True,
        )
    except Exception as e:  # noqa: BLE001 — diagnostics must not kill the bench
        print(f"# device kernel check FAILED: {e!r}", file=sys.stderr, flush=True)


def child_main(attempts: list[tuple[str, int]]) -> None:
    """Measure at the first (layout, size) that fits; print one JSON line.

    Only the first attempt is tried per process on TPU: an OOM on the
    tunneled backend leaves the client unusable (observed: every
    subsequent allocation fails RESOURCE_EXHAUSTED), so the parent
    retries smaller sizes in fresh processes.
    """
    from ringpop_tpu.utils import enable_compilation_cache, pin_cpu_if_requested

    pin_cpu_if_requested()
    enable_compilation_cache()

    def _measure(n: int, layout: str) -> float:
        profile_dir = os.environ.get("RINGPOP_PROFILE_DIR")
        if not profile_dir:
            return bench_once(n, layout)
        from ringpop_tpu.obs.annotate import profile_trace

        # per-attempt run directories so a retried size doesn't clobber
        # the trace of the one that worked
        with profile_trace(os.path.join(profile_dir, f"{layout}_n{n}")):
            return bench_once(n, layout)

    last_err = None
    for layout, n in attempts:
        try:
            value = _measure(n, layout)
        except Exception as e:
            # Recoverable per-attempt failures fall through to the next
            # attempt: OOM (shrink the cluster) and delta capacity
            # overflow (the CPU path runs every attempt in ONE child, so
            # the dense safety nets must still get their turn).
            msg = str(e)
            recoverable = (
                isinstance(e, CapacityOverflow)
                or "RESOURCE_EXHAUSTED" in msg
                or "out of memory" in msg.lower()
            )
            if not recoverable:
                raise
            last_err = e
            print(f"# {layout} n={n}: {msg[:120]}; next attempt",
                  file=sys.stderr, flush=True)
            continue
        baseline = REFERENCE_ROUNDS_PER_NODE_SEC * n
        name = "swim_delta" if layout.startswith("delta") else "swim_sim"
        print(
            json.dumps(
                {
                    "metric": f"{name}_node_rounds_per_sec_n{n}",
                    "value": round(value, 1),
                    "unit": "node-rounds/s",
                    "vs_baseline": round(value / baseline, 2),
                }
            ),
            flush=True,
        )
        return
    raise SystemExit(f"benchmark failed at every size: {last_err}")


# ---------------------------------------------------------------------------
# parent: orchestration under watchdogs (never imports jax)
# ---------------------------------------------------------------------------


def _run_child(args: list[str], env: dict, timeout: int) -> tuple[int | None, str, str]:
    """Run a subprocess; returns (rc, stdout, stderr); rc None on timeout."""
    try:
        p = subprocess.run(
            [sys.executable, *args],
            capture_output=True,
            text=True,
            timeout=timeout,
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)) or ".",
        )
        return p.returncode, p.stdout, p.stderr
    except subprocess.TimeoutExpired as e:
        out = e.stdout.decode() if isinstance(e.stdout, bytes) else (e.stdout or "")
        err = e.stderr.decode() if isinstance(e.stderr, bytes) else (e.stderr or "")
        return None, out, err


def _probe_tpu() -> str | None:
    """Can the ambient accelerator initialize and run a matmul? -> error or None.

    Every probe (initial and post-timeout re-probes) leaves a ledger
    row with its measured duration: a wedged tunnel's 240 s timeout is
    then a recorded fact, not a line lost in CI stderr."""
    t0 = time.perf_counter()
    rc, out, err = _run_child(
        [
            "-c",
            "import jax, jax.numpy as jnp; x = jnp.ones((128, 128));"
            "print('devices:', jax.devices(), float((x @ x).sum()))",
        ],
        env=dict(os.environ),
        timeout=PROBE_TIMEOUT_S,
    )
    duration = time.perf_counter() - t0
    if rc == 0:
        result = None
    elif rc is None:
        result = f"accelerator probe timed out after {PROBE_TIMEOUT_S}s"
    else:
        tail = (err or out).strip().splitlines()[-1:] or ["no output"]
        result = f"accelerator probe failed (rc={rc}): {tail[0][:300]}"
    default_ledger().record(
        {
            "program": "accelerator_probe",
            "platform": "parent",
            "execute_s": round(duration, 3),
            "timeout_s": PROBE_TIMEOUT_S,
            "ok": rc == 0,
            "error": result,
        }
    )
    return result


def _is_worker_crash(err: str | None) -> bool:
    """The round-5 failure signature, anchored to the TPU runtime's own
    error text ("UNAVAILABLE: TPU worker process crashed or restarted")
    instead of bare substring matches over all of stderr — an unrelated
    log line containing "crashed" or an "UNAVAILABLE" from some other
    RPC must not abandon the delta climb and the dense safety net
    (ADVICE round 5)."""
    text = err or ""
    return "UNAVAILABLE: TPU worker" in text or "worker process crashed" in text


def _echo_child_stderr(err: str | None) -> None:
    """Surface the measuring child's diagnostics (occupancy, on-chip
    kernel checks, per-rep rates) in the parent's stderr, uniformly
    "# "-prefixed like every other bench.py diagnostic."""
    for line in (err or "").strip().splitlines():
        print(line if line.startswith("#") else f"# {line}", file=sys.stderr, flush=True)


def _extract_json(stdout: str) -> dict | None:
    for line in reversed(stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def _emit(result: dict) -> None:
    """The one JSON line of the bench contract, now carrying the path
    to its own forensics (the dispatch ledger)."""
    result.setdefault("ledger", default_ledger().path)
    print(json.dumps(result), flush=True)


def main() -> None:
    errors = []

    # Ledger first: the probe row below must land in it, and children
    # inherit the path via the environment.  The default file is
    # truncated per run — "the" probe timeout must be THIS run's, not a
    # mix of stale rows (a user-supplied RINGPOP_LEDGER is theirs to
    # manage and is appended to).
    ledger_path = os.environ.get(LEDGER_ENV)
    if not ledger_path:
        ledger_path = DEFAULT_LEDGER_PATH
        open(ledger_path, "w").close()
    os.environ[LEDGER_ENV] = ledger_path
    default_ledger().enable(ledger_path)

    tpu_err = _probe_tpu()
    if tpu_err is None:
        # One attempt per child: a TPU OOM or worker crash poisons the
        # tunneled client, so each (layout, size) gets a fresh process.
        # The delta ladder ASCENDS, banking each rung (see
        # TPU_DELTA_LADDER); a worker-crash signature stops the climb
        # with the prior rungs in hand.
        timeouts_seen = 0
        best_pass: dict | None = None  # largest rung with vs >= 1.0
        fallback: dict | None = None  # best sub-1.0 rung
        banked_n: set[int] = set()  # sizes with any banked result
        tunnel_dead = False  # crash or failed re-probe ended the climb
        for layout, n in TPU_DELTA_LADDER:
            if layout == "delta@256" and n in banked_n:
                # the robustness rung exists for capacity overflows at
                # its size; skip it when the C=64 rung already banked
                continue
            rc, out, err = _run_child(
                [os.path.abspath(__file__), "--child", f"{layout}:{n}"],
                env=dict(os.environ),
                timeout=TPU_DELTA_TIMEOUT_S,
            )
            result = _extract_json(out)
            if rc == 0 and result is not None:
                _echo_child_stderr(err)
                banked_n.add(n)
                vs = result.get("vs_baseline", 0.0)
                if vs >= 1.0 and (
                    best_pass is None
                    or n > best_pass.get("_n", 0)
                    or (n == best_pass.get("_n", 0)
                        and vs > best_pass.get("vs_baseline", 0.0))
                ):
                    best_pass = dict(result, _n=n)
                elif vs < 1.0 and (
                    fallback is None or vs > fallback.get("vs_baseline", 0.0)
                ):
                    fallback = result
                print(
                    f"# {layout} n={n}: vs_baseline {vs} banked; climbing",
                    file=sys.stderr,
                    flush=True,
                )
                continue
            reason = (
                f"timed out after {TPU_DELTA_TIMEOUT_S}s"
                if rc is None
                else f"rc={rc}"
            )
            tail = (err or "").strip().splitlines()[-1:] or ["no stderr"]
            errors.append(f"tpu bench {layout} n={n} {reason}: {tail[0][:160]}")
            print(f"# {errors[-1]}", file=sys.stderr, flush=True)
            crash = _is_worker_crash(err)
            if crash:
                # The round-5 failure mode: the program killed the TPU
                # worker; further children would hang on init for the
                # 10+ minute recovery.  Keep what the climb banked.
                print(
                    "# worker-crash signature; stopping the climb",
                    file=sys.stderr,
                    flush=True,
                )
                tunnel_dead = True
                break
            if rc is None:
                # A timeout is ambiguous: a sick tunnel (give up on TPU)
                # or one oversized program compiling slowly (keep going).
                # Distinguish by re-probing with a trivial computation,
                # and cap how often we accept the probe's optimism: a
                # half-sick tunnel (probe works, real programs hang)
                # must not serialize hours of timeouts.
                timeouts_seen += 1
                probe_err = (
                    None if timeouts_seen > MAX_TPU_TIMEOUTS else _probe_tpu()
                )
                if timeouts_seen > MAX_TPU_TIMEOUTS or probe_err is not None:
                    why = (
                        f"{timeouts_seen} TPU timeouts (cap {MAX_TPU_TIMEOUTS})"
                        if probe_err is None
                        else f"re-probe after timeout: {probe_err}"
                    )
                    errors.append(why)
                    print(f"# stopping TPU attempts: {why}",
                          file=sys.stderr, flush=True)
                    tunnel_dead = True
                    break
                print("# tunnel re-probe ok; trying the next size",
                      file=sys.stderr, flush=True)
        if best_pass is None and not tunnel_dead:
            # no delta rung cleared 1.0 (a sub-1.0 delta fallback may be
            # banked) but the tunnel still answers — dense safety net,
            # descending, first green wins, with the same timeout
            # re-probe discipline as the climb; a sub-1.0 dense result
            # only replaces a sub-1.0 delta fallback when it is BETTER
            # (report the best of the two ladders — the old fall-through
            # behavior, ADVICE round 5)
            for layout, n in TPU_DENSE_ATTEMPTS:
                rc, out, err = _run_child(
                    [os.path.abspath(__file__), "--child", f"{layout}:{n}"],
                    env=dict(os.environ),
                    timeout=TPU_BENCH_TIMEOUT_S,
                )
                result = _extract_json(out)
                if rc == 0 and result is not None:
                    _echo_child_stderr(err)
                    vs = result.get("vs_baseline", 0.0)
                    if vs >= 1.0:
                        best_pass = result
                    elif fallback is None or vs > fallback.get(
                        "vs_baseline", 0.0
                    ):
                        fallback = result
                    break
                reason = (
                    f"timed out after {TPU_BENCH_TIMEOUT_S}s"
                    if rc is None
                    else f"rc={rc}"
                )
                tail = (err or "").strip().splitlines()[-1:] or ["no stderr"]
                errors.append(
                    f"tpu bench {layout} n={n} {reason}: {tail[0][:160]}"
                )
                if _is_worker_crash(err):
                    break
                if rc is None:
                    timeouts_seen += 1
                    probe_err = (
                        None
                        if timeouts_seen > MAX_TPU_TIMEOUTS
                        else _probe_tpu()
                    )
                    if timeouts_seen > MAX_TPU_TIMEOUTS or probe_err is not None:
                        errors.append("dense safety net: tunnel gone")
                        break
        if best_pass is not None:
            best_pass.pop("_n", None)
            _emit(best_pass)
            return
        if fallback is not None:
            # No rung cleared 1.0; report the best on-chip number rather
            # than falling through to CPU.
            _emit(fallback)
            return
    else:
        errors.append(tpu_err)
    print(f"# falling back to CPU: {errors[-1]}", file=sys.stderr, flush=True)

    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS=os.environ.get("XLA_FLAGS", ""),
    )
    # Large-n ladder first (VERDICT r4 item 8): report the largest n the
    # host can demonstrate, even sub-1.0, each rung in its own child so
    # one timeout doesn't forfeit the round's fallback entirely.
    for layout, n, rung_timeout in CPU_LADDER:
        rc, out, err = _run_child(
            [os.path.abspath(__file__), "--child", f"{layout}:{n}"],
            env=env,
            timeout=rung_timeout,
        )
        result = _extract_json(out)
        if rc == 0 and result is not None:
            _echo_child_stderr(err)
            result["platform"] = "cpu-fallback"
            result["note"] = (
                "large-n CPU rung: shortened measurement (20-tick batch, "
                "1 repeat); real-time parity is a TPU claim, this records "
                "scale reached on the fallback host.  r06: the TPU ladder "
                "banks its 8192->32768 rungs as +stream layouts (4 "
                "back-to-back segment dispatches) before the monolithic "
                "65536 program, whose compiled footprint re-pinned at "
                "575688560 peak bytes (-36.2% vs the round-5 "
                "worker-killer's 902967088)"
            )
            result["error"] = "; ".join(errors)
            _emit(result)
            return
        reason = (
            f"timed out after {rung_timeout}s" if rc is None else f"rc={rc}"
        )
        tail = (err or "").strip().splitlines()[-1:] or ["no stderr"]
        errors.append(f"cpu ladder {layout} n={n} {reason}: {tail[0][:160]}")
        print(f"# {errors[-1]}", file=sys.stderr, flush=True)

    rc, out, err = _run_child(
        [
            os.path.abspath(__file__),
            "--child",
            ",".join(f"{lo}:{n}" for lo, n in CPU_ATTEMPTS),
        ],
        env=env,
        timeout=CPU_BENCH_TIMEOUT_S,
    )
    result = _extract_json(out)
    if rc == 0 and result is not None:
        _echo_child_stderr(err)
        result["platform"] = "cpu-fallback"
        result["error"] = "; ".join(errors)
        _emit(result)
        return

    reason = f"timed out after {CPU_BENCH_TIMEOUT_S}s" if rc is None else f"rc={rc}"
    tail = (err or "").strip().splitlines()[-1:] or ["no stderr"]
    errors.append(f"cpu bench {reason}: {tail[0][:300]}")
    _emit(
        {
            "metric": "swim_sim_node_rounds_per_sec",
            "value": 0,
            "unit": "node-rounds/s",
            "vs_baseline": 0.0,
            "error": "; ".join(errors),
        }
    )


def _parse_attempt(s: str) -> tuple[str, int]:
    layout, _, n = s.partition(":")
    return (layout, int(n)) if n else ("dense", int(layout))


def _pop_flag(argv: list[str], name: str) -> str | None:
    """Extract ``--name VALUE`` from argv (the bench's arg surface is
    deliberately tiny; argparse would impose structure the --child
    protocol doesn't have)."""
    if name in argv:
        i = argv.index(name)
        if i + 1 < len(argv):
            value = argv[i + 1]
            del argv[i:i + 2]
            return value
    return None


if __name__ == "__main__":
    _argv = sys.argv[1:]
    _profile_dir = _pop_flag(_argv, "--profile-dir")
    if _profile_dir:
        # children do the actual measuring, so they write the traces
        os.environ["RINGPOP_PROFILE_DIR"] = os.path.abspath(_profile_dir)
    if len(_argv) > 1 and _argv[0] == "--child":
        child_main([_parse_attempt(s) for s in _argv[1].split(",")])
    else:
        main()

"""Headline benchmark: virtual-node SWIM protocol rounds simulated per second.

Simulates a BASELINE config-3-class cluster (10k nodes, 1% packet loss) on
one chip and measures protocol rounds (node-ticks) per wall-clock second.

``vs_baseline``: the reference executes the protocol in real time — every
node runs 5 protocol periods per second (200 ms minProtocolPeriod,
lib/swim/gossip.js:127-129), so a tick-cluster of N real processes
advances 5*N node-rounds per second. ``vs_baseline`` is the speedup of
the TPU simulation over that real-time rate at equal N (i.e. how many
seconds of real-cluster protocol time one TPU-second simulates).

Prints ONE JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}
"""

from __future__ import annotations

import json
import time

import jax

from ringpop_tpu.models import swim_sim as sim

REFERENCE_ROUNDS_PER_NODE_SEC = 5.0  # 200 ms protocol period
TICKS_PER_CALL = 20
REPEATS = 3


def _sync(metrics) -> int:
    """Force completion by pulling a scalar to the host.

    ``jax.block_until_ready`` is NOT sufficient on the tunneled TPU
    platform — it returns before execution finishes, which silently turns
    the timing into a dispatch-latency measurement (observed: "1e9
    node-rounds/s", ~300x above the HBM-bandwidth bound).  A host
    transfer is an unfakeable barrier."""
    return int(metrics["pings_sent"])


def bench_once(n: int) -> float:
    """Node-rounds/sec of an n-node simulation (best of REPEATS)."""
    params = sim.SwimParams(loss=0.01)
    key = jax.random.PRNGKey(0)
    state = sim.init_state(n)
    net = sim.make_net(n)
    # Compile + warm up (state is donated; keep the chain alive).
    key, sub = jax.random.split(key)
    state, metrics = sim.swim_run(state, net, sub, params, TICKS_PER_CALL)
    _sync(metrics)
    best = 0.0
    for _ in range(REPEATS):
        key, sub = jax.random.split(key)
        t0 = time.perf_counter()
        state, metrics = sim.swim_run(state, net, sub, params, TICKS_PER_CALL)
        _sync(metrics)
        dt = time.perf_counter() - t0
        best = max(best, TICKS_PER_CALL * n / dt)
    return best


def main() -> None:
    last_err = None
    for n in (10240, 8192, 4096, 2048, 1024):
        try:
            value = bench_once(n)
        except Exception as e:  # OOM on smaller chips: shrink the cluster
            msg = str(e)
            if "RESOURCE_EXHAUSTED" not in msg and "Out of memory" not in msg.lower():
                raise
            last_err = e
            continue
        baseline = REFERENCE_ROUNDS_PER_NODE_SEC * n
        print(
            json.dumps(
                {
                    "metric": f"swim_sim_node_rounds_per_sec_n{n}",
                    "value": round(value, 1),
                    "unit": "node-rounds/s",
                    "vs_baseline": round(value / baseline, 2),
                }
            )
        )
        return
    raise SystemExit(f"benchmark failed at every size: {last_err}") from last_err


if __name__ == "__main__":
    main()

#!/usr/bin/env bash
# CPU tune smoke: the compile-once knob plane end to end through the
# Pareto tuner.  Runs benchmarks/tune.py on its --micro grid (tiny
# n/ticks, 2-point axes — same five arms, same dispatch shape as the
# full run) and asserts the two contracts the tuner exists to prove:
#
#   * the whole incident x traffic x knob grid fits the declared
#     dispatch budget (tune.py exits non-zero when it doesn't);
#   * the in-memory dispatch ledger holds ZERO recompile_cause rows —
#     every knob value rode a traced operand, nothing re-specialized.
#
# This is the CI tune-smoke job's body; run it locally the same way:
#   tools/tune_smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

workdir=$(mktemp -d /tmp/ringpop-tune.XXXXXX)
trap 'rm -rf "$workdir"' EXIT

echo "== tuner micro grid (five arms, budget 10)"
JAX_PLATFORMS=cpu timeout -k 10 900 \
  python benchmarks/tune.py --micro --json "$workdir/tune.json" \
  | tee "$workdir/run.log"

# the tuner already hard-fails on a blown budget or a recompile row;
# re-assert both from the JSON so the smoke does not silently pass on
# a future refactor that drops the in-script checks
python - "$workdir" <<'EOF'
import json
import sys

with open(f"{sys.argv[1]}/tune.json") as fh:
    out = json.load(fh)

assert out["dispatches"] <= out["dispatch_budget"], out
assert out["recompile_rows"] == 0, out
# the five arms all reported
for key in ("grid", "frontier", "boundary", "pingreq", "admission"):
    assert key in out, f"tuner output missing {key!r}"
assert out["frontier"]["front"], "empty Pareto frontier"
print(
    f"tune smoke OK: {out['dispatches']} dispatches "
    f"(budget {out['dispatch_budget']}), 0 recompile rows, "
    f"{len(out['frontier']['front'])} frontier points"
)
EOF

grep -q "recompile rows: 0" "$workdir/run.log"
echo "tune smoke passed"

#!/usr/bin/env bash
# CPU provenance smoke: the gossip provenance plane end to end through
# the CLI.  Replays thundering_rejoin (half the cluster dies at once —
# every slot's suspect rumor CONFIRMS) at the golden configuration with
# 8 rumor slots armed and the Perfetto exporter on, then asserts the
# exported trace-event JSON is structurally valid and carries what the
# plane promises: a nonzero infection wavefront per rumor, flow arrows
# along the propagation tree, and a complete suspect→confirmed
# detection-causality chain for a killed node (origin prober + witness
# window + resolution tick).
# This is the CI provenance-smoke job's body; run it locally the same
# way:  tools/provenance_smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

workdir=$(mktemp -d /tmp/ringpop-prov.XXXXXX)
trap 'rm -rf "$workdir"' EXIT

echo "== traced incident run (golden configuration)"
JAX_PLATFORMS=cpu timeout -k 10 600 \
  python -m ringpop_tpu tick-cluster --backend tpu-sim -n 16 --seed 3 \
  --incident thundering_rejoin --trace-rumors 8 \
  --spans-out "$workdir/spans.json" \
  | tee "$workdir/run.log"

grep -q "provenance: 8/8 rumor slots armed" "$workdir/run.log"
grep -q "rumors 8" "$workdir/run.log"

JAX_PLATFORMS=cpu python - "$workdir" <<'EOF'
import json
import sys

workdir = sys.argv[1]
with open(f"{workdir}/spans.json") as f:
    doc = json.load(f)

events = doc["traceEvents"]
summary = doc["otherData"]["summary"]
n = doc["otherData"]["n"]
assert n == 16, doc["otherData"]

# every rumor armed, every one a CONFIRMED suspect→faulty chain (the
# killed half cannot refute), full wavefront reach
assert summary["rumors"] == 8, summary
assert summary["confirmed"] == 8 and summary["refuted"] == 0, summary
assert summary["infected_min"] == n, summary

by_phase = {}
for e in events:
    by_phase.setdefault(e["ph"], []).append(e)
assert set(by_phase) <= {"M", "X", "s", "f"}, set(by_phase)

# one detection window per rumor, each a complete confirmed chain
det = [e for e in by_phase["X"] if e.get("cat") == "detection"]
assert len(det) == 8, len(det)
for e in det:
    assert e["name"] == "suspect→confirmed", e["name"]
    a = e["args"]
    assert 0 <= a["origin_prober"] < n, a
    assert a["resolution"] == "confirmed", a
    assert a["resolution_tick"] > e["ts"] // doc["otherData"]["tick_us"], a
    assert e["dur"] > 0, e

# a nonzero infection wavefront: one 1-tick slice per heard node
inf = [e for e in by_phase["X"] if e.get("cat") == "infection"]
assert len(inf) == 8 * n, len(inf)

# flow arrows pair up along the propagation tree
starts = {e["id"] for e in by_phase.get("s", [])}
ends = {e["id"] for e in by_phase.get("f", [])}
assert starts and starts == ends, (len(starts), len(ends))

print(
    f"provenance smoke OK: {summary['rumors']} rumors confirmed, "
    f"wavefront {summary['infected_min']}/{n}, depth "
    f"{summary['depth_max']}, {len(events)} trace events"
)
EOF

echo "provenance smoke passed"

#!/usr/bin/env bash
# CPU obs smoke: a compiled scenario driven through the tick-cluster
# CLI must (a) leave a dispatch-ledger entry with compile/execute and
# peak-bytes populated, (b) emit a --stats-out stream whose key set is
# a superset of the reference-parity bridge keys, and (c) write a
# profiler trace directory with the named protocol-phase scopes active.
# This is the CI obs-smoke job's body; run it locally the same way:
#   tools/obs_smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

workdir=$(mktemp -d /tmp/ringpop-obs.XXXXXX)
trap 'rm -rf "$workdir"' EXIT
spec="$workdir/spec.json"
stats="$workdir/stats.jsonl"
ledger="$workdir/ledger.jsonl"
profdir="$workdir/profile"

cat > "$spec" <<'EOF'
{
  "ticks": 40,
  "events": [
    {"at": 5,  "op": "kill", "node": 3},
    {"at": 10, "op": "loss", "p": 0.05},
    {"at": 25, "op": "loss", "p": 0.0}
  ]
}
EOF

JAX_PLATFORMS=cpu RINGPOP_LEDGER="$ledger" timeout -k 10 600 \
  python -m ringpop_tpu tick-cluster --backend tpu-sim -n 16 \
  --scenario "$spec" --stats-out "$stats" --profile-dir "$profdir" \
  | tee "$workdir/out.log"

grep -q "one dispatch" "$workdir/out.log"

JAX_PLATFORMS=cpu python - "$stats" "$ledger" "$profdir" <<'EOF'
import json
import pathlib
import sys

from ringpop_tpu.obs.bridge import DEFAULT_PREFIX, REFERENCE_KEYS
from ringpop_tpu.obs.ledger import DispatchLedger

stats_path, ledger_path, profdir = sys.argv[1:4]

# (b) reference-shaped, non-empty key namespace
keys = {json.loads(line)["key"] for line in open(stats_path)}
assert keys, "stats stream is empty"
missing = [k for k in REFERENCE_KEYS if f"{DEFAULT_PREFIX}.{k}" not in keys]
assert not missing, f"missing reference keys: {missing}"

# (a) the scenario's ledger row with forensics populated
all_rows = DispatchLedger.load_rows(ledger_path)
rows = [r for r in all_rows if r["program"] == "run_scenario"]
assert len(rows) == 1, rows
row = rows[0]
assert row["cold"] and row["compile_s"] > 0 and row["execute_s"] > 0
assert row["peak_bytes"] > 0 and row["n"] == 16 and row["ticks"] == 40

# (a2) recompile-regression gate: the pinned compile-once contract —
# EXACTLY one cold compile per (program, signature), and no dispatch
# carries a recompile_cause (a second cold for the same program means
# some static/shape drifted mid-run; the row names the culprit)
from collections import Counter
sigs = Counter((r["program"], r.get("sig")) for r in all_rows if "sig" in r)
colds = Counter((r["program"], r.get("sig"))
                for r in all_rows if r.get("cold") and "sig" in r)
for key, n_cold in colds.items():
    assert n_cold == 1, f"{n_cold} cold compiles for one signature: {key}"
# every signature dispatched must own its one cold row (a warm row
# with no cold sibling would mean the AOT cache was pre-seeded)
missing = [key for key in sigs if key not in colds]
assert not missing, f"signatures with warm rows but no cold row: {missing}"
recompiled = [r for r in all_rows if r.get("recompile_cause")]
assert not recompiled, (
    "unexpected recompile(s): "
    + "; ".join(f"{r['program']}: {r['recompile_cause']}" for r in recompiled)
)

# (c) the profiler trace directory exists and is non-empty
files = [p for p in pathlib.Path(profdir).rglob("*") if p.is_file()]
assert files, "profiler trace directory is empty"

print(f"obs smoke OK: {len(keys)} stat keys, ledger row "
      f"(compile {row['compile_s']:.2f}s, execute {row['execute_s']:.3f}s, "
      f"peak {row['peak_bytes']} B), {len(files)} trace files")
EOF

#!/bin/bash
# Build the farmhash golden-oracle verifier by extracting the farmhashmk
# (Fingerprint32) section from the FarmHash copy vendored by TensorFlow.
# Usage: tools/build_verify_farmhash.sh <output-binary>
# Exits non-zero (quietly) if the TF header is unavailable.
set -e
OUT="${1:-/tmp/verify_farmhash}"
HDR=$(python3 - <<'EOF'
import glob, sys
hits = glob.glob('/opt/venv/lib/python*/site-packages/tensorflow/include/external/farmhash_gpu_archive/src/farmhash_gpu.h')
if not hits:
    sys.exit(1)
print(hits[0])
EOF
)
[ -n "$HDR" ] || exit 1
WORK=$(mktemp -d)
trap 'rm -rf "$WORK"' EXIT

# Locate the farmhashmk namespace block and the Murmur helper block by markers
# rather than line numbers so header revisions don't break us.
python3 - "$HDR" "$WORK/golden_mk.cc" <<'EOF'
import sys
hdr, out = sys.argv[1], sys.argv[2]
text = open(hdr).read().splitlines()

# Helpers: from the c1/c2 constants comment through the end of Mur's body.
pre = next(i for i, l in enumerate(text) if "// Magic numbers for 32-bit hashing" in l)
mur_start = next(i for i, l in enumerate(text) if "STATIC_INLINE uint32_t Mur" in l)
mur_end = next(i for i in range(mur_start, len(text)) if text[i].startswith("}"))
helpers = "\n".join(text[pre:mur_end + 1])

mk_start = next(i for i, l in enumerate(text) if l.strip() == "namespace farmhashmk {")
mk_end = next(i for i, l in enumerate(text) if "// namespace farmhashmk" in l)
mk = "\n".join(text[mk_start:mk_end + 1])
# Drop the Fetch/Rotate/Bswap macro redefinitions at the head of the block.
mk = "\n".join(l for l in mk.splitlines()
               if not l.startswith(("#undef", "#define")))

open(out, "w").write(f"""
#include <cstdint>
#include <cstring>
namespace golden {{
#define STATIC_INLINE static inline
static inline uint32_t Fetch(const char *p) {{
  uint32_t v; memcpy(&v, p, 4); return v;
}}
static inline uint32_t Rotate(uint32_t val, int shift) {{
  return shift == 0 ? val : ((val >> shift) | (val << (32 - shift)));
}}
#define Rotate32 Rotate
{helpers}
{mk}
}}  // namespace golden
""")
EOF

cat > "$WORK/main.cc" <<'EOF'
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <vector>
#include "golden_mk.cc"
extern "C" {
#include "_farmhash.c"
}
static int unhex(int c) {
  if (c >= '0' && c <= '9') return c - '0';
  if (c >= 'a' && c <= 'f') return c - 'a' + 10;
  return -1;
}
int main() {
  char buf[1 << 16];
  while (fgets(buf, sizeof(buf), stdin)) {
    size_t n = strlen(buf);
    while (n && (buf[n - 1] == '\n' || buf[n - 1] == '\r')) buf[--n] = 0;
    std::vector<uint8_t> bytes;
    for (size_t i = 0; i + 1 < n; i += 2)
      bytes.push_back((uint8_t)((unhex(buf[i]) << 4) | unhex(buf[i + 1])));
    const char *p = bytes.empty() ? "" : (const char *)bytes.data();
    uint32_t golden = golden::farmhashmk::Hash32(p, bytes.size());
    uint32_t ours = rp_farmhash32(
        bytes.empty() ? (const uint8_t *)"" : bytes.data(), bytes.size());
    printf("%u %u\n", ours, golden);
  }
  return 0;
}
EOF

SCRIPT_DIR=$(cd "$(dirname "$0")" && pwd)
cp "$SCRIPT_DIR/../ringpop_tpu/ops/_farmhash.c" "$WORK/"
g++ -O2 -o "$OUT" "$WORK/main.cc" -I "$WORK"

#!/usr/bin/env bash
# Tier-1 verify: the CPU test suite minus slow soaks, exactly as
# ROADMAP.md specifies it (this script IS the roadmap command; keep the
# two in sync).  Extra args pass through to pytest, e.g.:
#   tools/t1.sh -k recv_merge
#   tools/t1.sh -m slow        # opt in to the slow parity soaks
set -o pipefail
cd "$(dirname "$0")/.."
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
  "$@" 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit $rc

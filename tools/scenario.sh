#!/usr/bin/env bash
# CPU scenario smoke: a small kill+partition+heal+loss-ramp chaos
# scenario must run as one compiled dispatch via the tick-cluster CLI,
# converge, and emit a schema-valid per-tick trace.  This is the CI
# smoke job's body (see .github/workflows/ci.yml); run it locally the
# same way:  tools/scenario.sh
set -euo pipefail
cd "$(dirname "$0")/.."

workdir=$(mktemp -d /tmp/ringpop-scenario.XXXXXX)
trap 'rm -rf "$workdir"' EXIT
spec="$workdir/spec.json"
trace="$workdir/trace.npz"

cat > "$spec" <<'EOF'
{
  "ticks": 60,
  "events": [
    {"at": 5,  "op": "kill", "node": 3},
    {"at": 10, "op": "partition", "groups": [[0,1,2,3,4,5,6,7],
                                             [8,9,10,11,12,13,14,15]]},
    {"at": 10, "op": "loss", "p": 0.05},
    {"at": 25, "op": "heal"},
    {"at": 30, "op": "loss_ramp", "until": 40, "to": 0.0}
  ]
}
EOF

JAX_PLATFORMS=cpu timeout -k 10 600 python -m ringpop_tpu tick-cluster \
  --backend tpu-sim -n 16 --scenario "$spec" --trace-out "$trace" \
  | tee "$workdir/out.log"

grep -q "one dispatch" "$workdir/out.log"

JAX_PLATFORMS=cpu python - "$trace" <<'EOF'
import sys
from ringpop_tpu.scenarios.trace import Trace

trace = Trace.load(sys.argv[1]).validate()
assert trace.ticks == 60, trace.ticks
assert trace.converged[-1], "scenario did not converge"
assert int(trace.live[-1]) == 15, int(trace.live[-1])
assert trace.loss[-1] == 0.0
assert "pings_sent" in trace.metrics
print("scenario smoke OK: converged, trace schema valid")
EOF

# --- failure-model smoke: asymmetric link + flap storm ----------------
# One-way link loss toward a victim plus a flap storm must (a) run as
# one compiled dispatch, (b) produce detection events (the victim and
# the flappers get declared faulty at least once), and (c) stream the
# reference-parity bridge keys to --stats-out.

faults_spec="$workdir/faults.json"
faults_trace="$workdir/faults_trace.npz"
stats_out="$workdir/faults_stats.jsonl"

cat > "$faults_spec" <<'EOF'
{
  "ticks": 80,
  "events": [
    {"at": 5,  "op": "link_loss", "src": [0,1,2,3,4,5,6,7],
     "dst": [14], "p": 0.97, "until": 55},
    {"at": 6,  "op": "kill", "node": 15},
    {"at": 8,  "op": "flap", "nodes": [12, 13], "until": 40,
     "down": 4, "up": 5, "stagger": 2},
    {"at": 10, "op": "gray", "node": 11, "factor": 5, "until": 60}
  ]
}
EOF

JAX_PLATFORMS=cpu timeout -k 10 600 python -m ringpop_tpu tick-cluster \
  --backend tpu-sim -n 16 --scenario "$faults_spec" \
  --trace-out "$faults_trace" --stats-out "$stats_out" \
  | tee "$workdir/faults_out.log"

grep -q "one dispatch" "$workdir/faults_out.log"

JAX_PLATFORMS=cpu python - "$faults_trace" "$stats_out" <<'EOF'
import json
import sys
from ringpop_tpu.obs import bridge
from ringpop_tpu.scenarios.trace import Trace

trace = Trace.load(sys.argv[1]).validate()
assert trace.ticks == 80, trace.ticks
# the asymmetric incidents produce real detections: the flappers get
# suspected (and refute on revive), the permanent kill behind the
# blackhole escalates to faulty
assert int(trace.metrics["suspects_declared"].sum()) > 0, "no suspects"
assert int(trace.metrics["faulty_declared"].sum()) > 0, "no detections"
# every flap kill revived and the blackhole lifted: the cluster heals
# around the one genuinely dead node
assert trace.converged[-1], "failure-model scenario did not re-converge"
assert int(trace.live[-1]) == 15, int(trace.live[-1])

keys = {json.loads(line)["key"] for line in open(sys.argv[2])}
assert keys, "stats stream is empty"
missing = [
    k for k in bridge.REFERENCE_KEYS
    if f"{bridge.DEFAULT_PREFIX}.{k}" not in keys
]
assert not missing, f"bridge keys missing from --stats-out: {missing}"
print("failure-model smoke OK: detections present, bridge keys complete")
EOF

#!/usr/bin/env bash
# CPU scenario smoke: a small kill+partition+heal+loss-ramp chaos
# scenario must run as one compiled dispatch via the tick-cluster CLI,
# converge, and emit a schema-valid per-tick trace.  This is the CI
# smoke job's body (see .github/workflows/ci.yml); run it locally the
# same way:  tools/scenario.sh
set -euo pipefail
cd "$(dirname "$0")/.."

workdir=$(mktemp -d /tmp/ringpop-scenario.XXXXXX)
trap 'rm -rf "$workdir"' EXIT
spec="$workdir/spec.json"
trace="$workdir/trace.npz"

cat > "$spec" <<'EOF'
{
  "ticks": 60,
  "events": [
    {"at": 5,  "op": "kill", "node": 3},
    {"at": 10, "op": "partition", "groups": [[0,1,2,3,4,5,6,7],
                                             [8,9,10,11,12,13,14,15]]},
    {"at": 10, "op": "loss", "p": 0.05},
    {"at": 25, "op": "heal"},
    {"at": 30, "op": "loss_ramp", "until": 40, "to": 0.0}
  ]
}
EOF

JAX_PLATFORMS=cpu timeout -k 10 600 python -m ringpop_tpu tick-cluster \
  --backend tpu-sim -n 16 --scenario "$spec" --trace-out "$trace" \
  | tee "$workdir/out.log"

grep -q "one dispatch" "$workdir/out.log"

JAX_PLATFORMS=cpu python - "$trace" <<'EOF'
import sys
from ringpop_tpu.scenarios.trace import Trace

trace = Trace.load(sys.argv[1]).validate()
assert trace.ticks == 60, trace.ticks
assert trace.converged[-1], "scenario did not converge"
assert int(trace.live[-1]) == 15, int(trace.live[-1])
assert trace.loss[-1] == 0.0
assert "pings_sent" in trace.metrics
print("scenario smoke OK: converged, trace schema valid")
EOF

#!/bin/bash
# Round-5 endgame watcher: when the long-running CPU benches finish,
# append their JSON rows to BASELINE.md and commit — so results landing
# after the interactive session's turns run out still make the round's
# record (the driver commits loose work at round end either way; this
# makes the rows legible in BASELINE.md rather than buried in /tmp).
set -u
cd "$(dirname "$0")/.."
LOG=tools/r5_result_watcher.log
: > "$LOG"
say() { echo "[$(date +%H:%M:%S)] $*" >> "$LOG"; }

heal_done=0
pingreq_done=0
for i in $(seq 1 200); do  # up to ~5.5 h of 100 s polls
  if [ $heal_done -eq 0 ] && grep -q '"metric": "delta_partition_heal_sided_n65536"' /tmp/r5_heal65k.log 2>/dev/null; then
    {
      echo ""
      echo '## Round 5: BASELINE config 4 at n=65,536 — sided heal (CPU completion)'
      echo ""
      echo 'From `tools/heal65k_cpu.py 65536 2048` (capacity n/32, wire 64,'
      echo 'suspicion 8, heal mid-transition; single-core CPU host, run to'
      echo 'completion per VERDICT item 2 "any platform"):'
      echo ""
      echo '```'
      grep '"metric"' /tmp/r5_heal65k.log
      echo '```'
    } >> BASELINE.md
    git add BASELINE.md && git commit -q -m "Record the 65,536-node sided netsplit heal (BASELINE config 4, CPU completion)" || true
    say "heal65k row recorded"
    heal_done=1
  fi
  if [ $pingreq_done -eq 0 ] && grep -q 'pingreq_piggyback_deviation_ratio' /tmp/r5_pingreq1024.log 2>/dev/null; then
    {
      echo ""
      echo '## Round 5: ping-req deviation regression at n=1,024 (VERDICT item 7)'
      echo ""
      echo '```'
      grep -v '^#' /tmp/r5_pingreq1024.log | grep -v WARNING
      echo '```'
    } >> BASELINE.md
    git add BASELINE.md && git commit -q -m "Record the n=1,024 ping-req piggyback regression rows" || true
    say "pingreq rows recorded"
    pingreq_done=1
  fi
  [ $heal_done -eq 1 ] && [ $pingreq_done -eq 1 ] && break
  sleep 100
done
say "watcher exiting (heal=$heal_done pingreq=$pingreq_done)"

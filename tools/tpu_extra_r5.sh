#!/bin/bash
# Post-triage TPU stages for round 5 — run manually once
# tools/tpu_triage_r5.sh has established which ladder rungs work.
# Order reflects the round's lessons: the 65536 delta program crashed
# the tunneled worker (15+ min recovery per crash), so risky stages sit
# last and everything has its own timeout.
# Usage: tools/tpu_extra_r5.sh [logfile]
set -u
cd "$(dirname "$0")/.."
LOG=${1:-tools/tpu_extra_r5.log}
: > "$LOG"
say() { echo "[$(date +%H:%M:%S)] $*" >> "$LOG"; }

say "=== A/B: slot-base carry (RINGPOP_CARRY_SLOTBASE) at 32768"
timeout 1200 python -u bench.py --child delta@64:32768 >> "$LOG" 2>&1
say "carry=0 rc=$?"
RINGPOP_CARRY_SLOTBASE=1 timeout 1200 python -u bench.py --child delta@64:32768 >> "$LOG" 2>&1
say "carry=1 rc=$?"

say "=== wide-lowering race at 32768 (RINGPOP_WIDE_METHOD)"
RINGPOP_WIDE_METHOD=pallas timeout 1200 python -u bench.py --child delta@64:32768 >> "$LOG" 2>&1
say "pallas rc=$?"
RINGPOP_WIDE_METHOD=sort timeout 1200 python -u bench.py --child delta@64:32768 >> "$LOG" 2>&1
say "sort rc=$?"

say "=== delta scale: 262144 and 1M existence (VERDICT item 5)"
timeout 2400 python -u benchmarks/bench_delta_scale.py 262144 20 >> "$LOG" 2>&1
say "scale 262144 rc=$?"
timeout 3600 python -u benchmarks/bench_delta_scale.py 1048576 5 >> "$LOG" 2>&1
say "scale 1M rc=$?"

say "=== crash hypothesis: 65536 under the ALTERNATE wide lowerings"
# the default scan_unrolled does log2(C) data-dependent batched gathers;
# if the 65k worker crash is a codegen fault in that lowering, sort or
# compare_all at the same size should run (each risks one ~15 min
# worker recovery — run only after the safe rungs are banked)
RINGPOP_WIDE_METHOD=sort timeout 1800 python -u bench.py --child delta@64:65536 >> "$LOG" 2>&1
say "65536 wide=sort rc=$?"
RINGPOP_WIDE_METHOD=pallas timeout 1800 python -u bench.py --child delta@64:65536 >> "$LOG" 2>&1
say "65536 wide=pallas rc=$?"

say "=== config-4 heals on chip"
timeout 3600 python -u benchmarks/bench_partition_heal_delta.py 8192 --sided >> "$LOG" 2>&1
say "heal 8192 sided rc=$?"
timeout 5400 python -u benchmarks/bench_partition_heal_delta.py 65536 --sided >> "$LOG" 2>&1
say "heal 65536 sided (config-4 north star) rc=$?"

say "done"

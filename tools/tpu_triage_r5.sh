#!/bin/bash
# Round-5 TPU triage: the tunnel answered this round but the first
# delta@64:65536 attempt CRASHED the TPU worker (UNAVAILABLE: worker
# process crashed or restarted), which then wedged the tunnel for 10+
# minutes.  So: capture the on-chip ladder BOTTOM-UP first (every rung
# is a real on-chip datapoint we have never had for the delta backend),
# and only then retry 65k / bisect the crash — each crash costs ~15 min
# of worker recovery, so risky stages go last and re-probe after.
# Usage: tools/tpu_triage_r5.sh [logfile]
set -u
cd "$(dirname "$0")/.."
LOG=${1:-tools/tpu_triage_r5.log}
: > "$LOG"
say() { echo "[$(date +%H:%M:%S)] $*" >> "$LOG"; }

# Pause the round's CPU benches while the TPU owns the host core (the
# box is single-core; compile + dispatch contend).  Bracket patterns so
# pkill -f never matches this script's own argv.
pause_cpu() { pkill -STOP -f "bench_[p]hase_offset|bench_[s]ided_bound|bench_[p]ingreq" 2>/dev/null; }
resume_cpu() { pkill -CONT -f "bench_[p]hase_offset|bench_[s]ided_bound|bench_[p]ingreq" 2>/dev/null; }

probe() {
  timeout 120 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((256,256))
print('probe ok', float((x@x).sum()))" >> "$LOG" 2>&1
}

wait_up() {  # $1 = max probes, 120s apart
  for i in $(seq 1 "$1"); do
    if probe; then say "tunnel up after $i probes"; return 0; fi
    say "probe $i failed; sleeping 120s"
    sleep 120
  done
  return 1
}

say "waiting for TPU worker to recover from the 65k crash"
if ! wait_up 90; then say "tunnel never recovered; giving up"; resume_cpu; exit 1; fi

pause_cpu
say "=== ladder bottom-up: every rung is a first-ever on-chip delta datapoint"
for n in 8192 16384 32768; do
  say "--- delta@64:$n"
  timeout 1200 python -u bench.py --child delta@64:$n >> "$LOG" 2>&1
  rc=$?
  say "delta@64:$n rc=$rc"
  if [ $rc -ne 0 ]; then
    say "rung $n failed; re-probing before continuing"
    resume_cpu
    if ! wait_up 20; then say "worker did not recover; stopping ladder"; exit 1; fi
    pause_cpu
  fi
done

say "=== risky: retry the 65536 headline on a fresh worker"
timeout 1800 python -u bench.py --child delta@64:65536 >> "$LOG" 2>&1
rc65=$?
say "delta@64:65536 retry rc=$rc65"

if [ $rc65 -ne 0 ]; then
  resume_cpu
  say "=== 65k failed again: wait for recovery, then bisect the phase"
  if ! wait_up 20; then say "worker did not recover post-65k; giving up"; exit 1; fi
  pause_cpu
  say "--- profile_delta_bisect 65536 64 (finds the crashing phase)"
  timeout 2400 python -u -m benchmarks.profile_delta_bisect 65536 64 >> "$LOG" 2>&1
  say "bisect rc=$?"
fi
resume_cpu
say "done"

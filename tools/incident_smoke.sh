#!/usr/bin/env bash
# CPU incident smoke: one fast incident end to end through the CLI.
# Asserts the incident library's whole chain — named builder ->
# compiled scenario+traffic scan (streamed) -> detect/heal/serve
# summary — produces real detections, re-convergence, and a summary
# BIT-IDENTICAL to the pinned golden (tests/golden/incidents/).
# This is the CI incident-smoke job's body; run it locally the same
# way:  tools/incident_smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

workdir=$(mktemp -d /tmp/ringpop-incident.XXXXXX)
trap 'rm -rf "$workdir"' EXIT

# the catalog lists every incident without starting a cluster
JAX_PLATFORMS=cpu python -m ringpop_tpu tick-cluster --list-incidents \
  | tee "$workdir/catalog.txt"
grep -q "cascading_overload" "$workdir/catalog.txt"
grep -q "region_partition_asym_heal" "$workdir/catalog.txt"

# region_partition_asym_heal at the GOLDEN configuration (n=16 seed=3,
# streamed by default): detections fire through the lossy one-way
# heal, the cluster re-converges, and the summary matches the pin
echo "== incident run (golden configuration)"
JAX_PLATFORMS=cpu timeout -k 10 600 \
  python -m ringpop_tpu tick-cluster --backend tpu-sim -n 16 --seed 3 \
  --incident region_partition_asym_heal \
  --trace-out "$workdir/trace.npz" \
  | tee "$workdir/run.log"

grep -q "incident region_partition_asym_heal:" "$workdir/run.log"

JAX_PLATFORMS=cpu python - "$workdir" <<'EOF'
import json
import sys

from ringpop_tpu.scenarios import library as lib
from ringpop_tpu.scenarios.trace import Trace

workdir = sys.argv[1]
trace = Trace.load(f"{workdir}/trace.npz")
summary = lib.incident_summary(trace)

# nonzero detections: the asymmetric heal produced faulty declarations
assert summary["detect_tick"] >= 0, summary
assert summary["faulty_declared"] > 0, summary
# re-convergence: the cluster healed and stayed healed
assert summary["heal_tick"] >= 0, summary
assert summary["final_live"] == lib.GOLDEN_N, summary
# golden-summary match: the CLI run IS the golden configuration
with open("tests/golden/incidents/region_partition_asym_heal.dense.json") as f:
    want = json.load(f)
assert summary == want, (
    f"incident summary diverged from the golden pin:\n got {summary}\n"
    f"want {want}\nre-pin with tools/pin_incidents.py if intentional"
)
print("incident smoke OK:", lib.format_summary("region_partition_asym_heal",
                                               summary))
EOF

echo "incident smoke passed"

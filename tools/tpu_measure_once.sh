#!/bin/bash
# Wait for the tunneled TPU to come back, then take the round's on-chip
# measurements in one pass, HEADLINE FIRST (the tunnel can die again at
# any time — the bar for the round is the first stage).  Each stage has
# its own hard timeout; everything logs to $LOG.
# Usage: tools/tpu_measure_once.sh [logfile]
set -u
cd "$(dirname "$0")/.."
LOG=${1:-/tmp/tpu_measure.log}
: > "$LOG"
say() { echo "[$(date +%H:%M:%S)] $*" >> "$LOG"; }

probe() {
  timeout 120 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((256,256))
print('probe ok', float((x@x).sum()))" >> "$LOG" 2>&1
}

say "waiting for TPU tunnel"
for i in $(seq 1 120); do   # up to 10 h of 5-min waits
  if probe; then say "tunnel up after $i probes"; break; fi
  say "probe $i failed; sleeping 300s"
  sleep 300
done
if ! probe; then say "tunnel never came back; giving up"; exit 1; fi

say "=== stage 1: HEADLINE bench child delta@64:65536"
timeout 1800 python -u bench.py --child delta@64:65536 >> "$LOG" 2>&1
say "stage 1 rc=$?"

say "=== stage 2: ladder rungs above 65536"
timeout 1800 python -u bench.py --child delta@64:131072 >> "$LOG" 2>&1
say "stage 2a rc=$?"
timeout 1800 python -u bench.py --child delta@64:262144 >> "$LOG" 2>&1
say "stage 2b rc=$?"

say "=== stage 3: delta phase bisect (n=65536, C=64) — incl. exchange"
timeout 2400 python -u -m benchmarks.profile_delta_bisect 65536 64 >> "$LOG" 2>&1
say "stage 3 rc=$?"

say "=== stage 4: searchsorted lowering race (n=65536)"
timeout 2400 python -u -m benchmarks.profile_searchsorted 65536 >> "$LOG" 2>&1
say "stage 4 rc=$?"

say "=== stage 5: delta scale 262144 and 1M (VERDICT item 5)"
timeout 2400 python -u benchmarks/bench_delta_scale.py 262144 20 >> "$LOG" 2>&1
say "stage 5a rc=$?"
timeout 3600 python -u benchmarks/bench_delta_scale.py 1048576 5 >> "$LOG" 2>&1
say "stage 5b rc=$?"

say "=== stage 6: config-4 netsplit heal on the delta backend"
timeout 3600 python -u benchmarks/bench_partition_heal_delta.py 8192 --sided >> "$LOG" 2>&1
say "stage 6a rc=$?"
timeout 5400 python -u benchmarks/bench_partition_heal_delta.py 65536 --sided >> "$LOG" 2>&1
say "stage 6b (SIDED 65k, the config-4 north star) rc=$?"
timeout 3600 python -u benchmarks/bench_partition_heal_delta.py 32768 >> "$LOG" 2>&1
say "stage 6c (unsided 32k, exact trajectory) rc=$?"

say "done"

#!/usr/bin/env python
"""(Re)pin the golden incident summaries + reference specs.

Runs every (incident, backend) pair of the library at the golden
configuration (scenarios/library.py GOLDEN_*) and writes the summary
JSON under tests/golden/incidents/, plus re-renders the reference
specs under ringpop_tpu/scenarios/specs/.  Run after an INTENTIONAL
protocol or serving change; the nightly golden lane
(tests/test_incidents.py::test_golden_incident_grid) compares against
these files bit-for-bit.

The policy-armed grid (library.policy_golden_grid: cascading_overload
under every remediation policy on both backends + every other
incident under the winning policy) is pinned in the same pass as
``{incident}+{policy}.{backend}.json`` files; ``--policies`` pins
ONLY that grid (after a policies/ change that leaves the bare
incident trajectories untouched).

    JAX_PLATFORMS=cpu python tools/pin_incidents.py [--policies] [NAME ...]
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN_DIR = os.path.join(REPO, "tests", "golden", "incidents")


def _pin(lib, name, backend, policy=None):
    t0 = time.time()
    summary = lib.run_golden(name, backend, policy=policy)
    path = lib.golden_path(name, backend, GOLDEN_DIR, policy=policy)
    with open(path, "w") as f:
        json.dump(summary, f, indent=2, sort_keys=True)
        f.write("\n")
    label = f"{name}+{policy}" if policy else name
    print(f"{label}.{backend}: {time.time() - t0:.1f}s -> {path}")


def main(argv: list[str]) -> None:
    sys.path.insert(0, REPO)
    from ringpop_tpu.scenarios import library as lib

    policies_only = "--policies" in argv
    argv = [a for a in argv if a != "--policies"]
    names = argv or lib.incident_names()
    os.makedirs(GOLDEN_DIR, exist_ok=True)
    if not policies_only:
        for name in names:
            for backend in lib.INCIDENTS[name].backends:
                _pin(lib, name, backend)
    for name, policy, backend in lib.policy_golden_grid():
        if name in names:
            _pin(lib, name, backend, policy=policy)
    if not policies_only:
        written = lib.write_specs()
        print(f"re-rendered {len(written)} reference specs")


if __name__ == "__main__":
    main(sys.argv[1:])

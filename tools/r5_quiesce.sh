#!/bin/bash
# Round-5 endgame: at the scheduled time, SIGSTOP every long-running
# CPU job so the driver's round-end bench.py measures an idle box (the
# rehearsal showed the 65,536 CPU-ladder rung misses its watchdog under
# 3-way contention but nearly completes idle).  STOP not KILL: the
# processes stay inspectable and the result watcher can still harvest
# their logs if they finished first.
# Usage: tools/r5_quiesce.sh <epoch-seconds-to-fire>
set -u
AT=${1:?fire time (epoch seconds)}
while [ "$(date +%s)" -lt "$AT" ]; do sleep 30; done
pkill -STOP -f "heal65k_[c]pu" 2>/dev/null
pkill -STOP -f "bench_[p]ingreq" 2>/dev/null
pkill -STOP -f "bench_[s]ided_bound" 2>/dev/null
pkill -STOP -f "bench_[p]hase_offset" 2>/dev/null
echo "[$(date +%H:%M:%S)] quiesced for the driver bench" >> tools/r5_quiesce.log

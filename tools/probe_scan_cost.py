"""Separate the delta scan's fixed cost from steady-state activity:
time delta_run(100 ticks) at loss=0 (no failed probes, no claims,
every gate closed forever) vs loss=0.01 (the bench's steady state).

Run: JAX_PLATFORMS=cpu python tools/probe_scan_cost.py [n]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ringpop_tpu.utils import pin_cpu_if_requested

pin_cpu_if_requested()

import jax

from ringpop_tpu.models import swim_delta as sd
from ringpop_tpu.models import swim_sim as sim


def run_case(n: int, loss: float, ticks: int = 100, reps: int = 3) -> float:
    params = sd.DeltaParams(
        swim=sim.SwimParams(loss=loss), wire_cap=16, claim_grid=64
    )
    st = sd.init_delta(n, capacity=64)
    net = sim.make_net(n)
    keys = jax.random.split(jax.random.PRNGKey(0), reps + 1)
    st, m = sd.delta_run(st, net, keys[0], params, ticks)  # compile+warm
    int(m["pings_sent"])
    best = 0.0
    for r in range(reps):
        t0 = time.perf_counter()
        st, m = sd.delta_run(st, net, keys[r + 1], params, ticks)
        int(m["pings_sent"])
        dt = time.perf_counter() - t0
        best = max(best, ticks * n / dt)
    return best


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 8192
    for loss in (0.0, 0.01):
        v = run_case(n, loss)
        print(
            f"n={n} loss={loss}: {v:,.0f} node-rounds/s "
            f"({n / v * 1e3:.2f} ms/tick)",
            flush=True,
        )


if __name__ == "__main__":
    main()

"""Quick CPU smoke of the delta step after an edit (run with
JAX_PLATFORMS=cpu; pins at the jax-config level like the benches)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ringpop_tpu.utils import pin_cpu_if_requested

pin_cpu_if_requested()

import jax

from ringpop_tpu.models import swim_delta as sd
from ringpop_tpu.models import swim_sim as sim


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    params = sd.DeltaParams(
        swim=sim.SwimParams(loss=0.05), wire_cap=16, claim_grid=64
    )
    st = sd.init_delta(n, capacity=64)
    net = sim.make_net(n)
    key = jax.random.PRNGKey(0)
    m = None
    for _ in range(12):
        key, sub = jax.random.split(key)
        st, m = sd.delta_step(st, net, sub, params)
    print(
        "12 ticks ok; occupancy",
        int(m["max_occupancy"]),
        "pings",
        int(m["pings_sent"]),
        "suspects",
        int(m["suspects_declared"]),
        flush=True,
    )


if __name__ == "__main__":
    main()

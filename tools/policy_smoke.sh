#!/usr/bin/env bash
# CPU policy smoke: the remediation policy plane end to end through
# the CLI.  Replays the cascading_overload incident at the golden
# configuration with the winning policy armed (--policy combined); the
# CLI's control arm (an identically-seeded no-policy sibling) replays
# first, so the printed before/after line is a true A/B.  Asserts the
# policy-armed summary is BIT-IDENTICAL to its pinned golden
# (tests/golden/incidents/cascading_overload+combined.dense.json) and
# that the remediation actually beats the incident: goodput within the
# acceptance band of no-fault, amplification under 1.5x, the gray
# cascade never forms — against the CONTROL numbers read from the bare
# incident pin (same seed, same configuration).
# This is the CI policy-smoke job's body; run it locally the same
# way:  tools/policy_smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

workdir=$(mktemp -d /tmp/ringpop-policy.XXXXXX)
trap 'rm -rf "$workdir"' EXIT

# the catalog lists every policy (with concrete defaults) without
# starting a cluster
JAX_PLATFORMS=cpu python -m ringpop_tpu tick-cluster --list-policies \
  -n 16 | tee "$workdir/catalog.txt"
for p in admission retry_budget quarantine combined; do
  grep -q "$p" "$workdir/catalog.txt"
done

# cascading_overload + combined at the GOLDEN configuration (n=16
# seed=3, streamed by default): control arm replays first, the policy
# arm must print a recovery line, and the summary matches the pin
echo "== policy-armed incident run (golden configuration)"
JAX_PLATFORMS=cpu timeout -k 10 600 \
  python -m ringpop_tpu tick-cluster --backend tpu-sim -n 16 --seed 3 \
  --incident cascading_overload --policy combined \
  --trace-out "$workdir/trace.npz" \
  | tee "$workdir/run.log"

grep -q "incident cascading_overload:" "$workdir/run.log"
grep -q "policy combined: goodput" "$workdir/run.log"

JAX_PLATFORMS=cpu python - "$workdir" <<'EOF'
import json
import sys

from ringpop_tpu.scenarios import library as lib
from ringpop_tpu.scenarios.trace import Trace

workdir = sys.argv[1]
trace = Trace.load(f"{workdir}/trace.npz")
summary = lib.incident_summary(trace)

# golden-summary match: the CLI run IS the pinned policy-armed golden
with open("tests/golden/incidents/cascading_overload+combined.dense.json") as f:
    want = json.load(f)
assert summary == want, (
    f"policy summary diverged from the golden pin:\n got {summary}\n"
    f"want {want}\nre-pin with tools/pin_incidents.py --policies if "
    "intentional"
)

# the control numbers are the bare incident's own pin (same seed/config)
with open("tests/golden/incidents/cascading_overload.dense.json") as f:
    control = json.load(f)

goodput = 100.0 * summary["delivered"] / summary["lookups"]
amp = summary["sends"] / max(summary["delivered"], 1)
g_ctl = 100.0 * control["delivered"] / control["lookups"]
a_ctl = control["sends"] / max(control["delivered"], 1)
# the acceptance bar (ROADMAP item 3): goodput within ~5% of no-fault,
# amplification < 1.5, and the cascade visibly beaten vs control
assert goodput >= 95.0, (goodput, summary)
assert amp < 1.5, (amp, summary)
assert goodput > g_ctl and amp < a_ctl, (goodput, g_ctl, amp, a_ctl)
assert summary["ov_gray_peak"] < control["ov_gray_peak"], summary
# the remediation plane really engaged (not a no-op win)
assert summary["policy_quar_peak"] > 0 or summary["policy_shed"] > 0, summary
print(
    f"policy smoke OK: goodput {g_ctl:.1f}% -> {goodput:.1f}%, "
    f"amplification {a_ctl:.2f}x -> {amp:.2f}x, "
    f"gray peak {control['ov_gray_peak']} -> {summary['ov_gray_peak']}"
)
EOF

echo "policy smoke passed"

#!/usr/bin/env bash
# CPU soak-resume smoke: a segmented tick-cluster soak must survive a
# SIGKILL.  Three acts:
#   1. reference: an uninterrupted streamed run (seed 1) — final
#      checksums + full trace npz.
#   2. victim: the IDENTICAL run started fresh, SIGKILL'd as soon as
#      its first checkpoint lands on disk.
#   3. resume: `tick-cluster --resume` continues the victim from its
#      checkpoint; its final checksums and assembled trace must be
#      BIT-IDENTICAL to the reference's (the checkpoint-v5 cursor +
#      segment-exact key schedule contract, scenarios/stream.py).
# The soak is INCIDENT-SHAPED: a zipf workload with the SLO latency
# plane co-runs in the scan and the spec carries an overload feedback
# window, so the checkpoint round-trips the ov_cnt/ov_gray tensors and
# the resumed run's serving + overload series must bit-match too.
# It is also POLICY-ARMED (--policy combined): the remediation plane
# rides the same scan, so the kill/resume additionally round-trips the
# po_* tensors (pressure, hysteresis flags, amp windows, retry cap)
# and the resumed policy series must bit-match mid-window.
# This is the CI soak-resume-smoke job's body; run it locally the
# same way:  tools/soak_smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

workdir=$(mktemp -d /tmp/ringpop-soak.XXXXXX)
trap 'rm -rf "$workdir"' EXIT
spec="$workdir/spec.json"

# enough segments (30) that the first checkpoint lands well before the
# run finishes — the kill window is real, not a race we usually lose
cat > "$spec" <<'EOF'
{
  "ticks": 600,
  "events": [
    {"at": 40,  "op": "kill", "node": 23},
    {"at": 80,  "op": "loss", "p": 0.05},
    {"at": 300, "op": "loss", "p": 0.0},
    {"at": 60,  "op": "overload", "until": 560, "capacity": 2,
     "threshold": 12, "recover": 3, "factor": 5}
  ]
}
EOF

run_args=(--backend tpu-sim -n 24 --seed 1 --scenario "$spec"
          --traffic zipf:96 --latency-buckets 8 --policy combined
          --segment-ticks 20 --checkpoint-every 1)

echo "== act 1: uninterrupted reference run"
JAX_PLATFORMS=cpu timeout -k 10 600 \
  python -m ringpop_tpu tick-cluster "${run_args[@]}" \
  --checkpoint "$workdir/ref.npz" --trace-out "$workdir/ref_trace.npz" \
  | tee "$workdir/ref.log"
grep "final checksums:" "$workdir/ref.log" > "$workdir/ref.sum"

echo "== act 2: identical run, SIGKILL'd after its first checkpoint"
JAX_PLATFORMS=cpu RINGPOP_LEDGER="$workdir/ledger.jsonl" \
  python -m ringpop_tpu tick-cluster "${run_args[@]}" \
  --checkpoint "$workdir/victim.npz" \
  > "$workdir/victim.log" 2>&1 &
victim=$!
for _ in $(seq 1 4000); do  # poll up to 200 s for the first checkpoint
  [ -f "$workdir/victim.npz" ] && break
  sleep 0.05
done
[ -f "$workdir/victim.npz" ] || {
  echo "victim never checkpointed"; cat "$workdir/victim.log"; exit 1; }
kill -9 "$victim" 2>/dev/null || true
wait "$victim" 2>/dev/null || true
if grep -q "final checksums:" "$workdir/victim.log"; then
  echo "note: victim finished before the kill landed (fast machine);"
  echo "      resume still exercises the completed-cursor path"
else
  echo "victim killed mid-soak (as intended)"
fi

echo "== act 3: resume from the victim's checkpoint"
JAX_PLATFORMS=cpu RINGPOP_LEDGER="$workdir/ledger.jsonl" timeout -k 10 600 \
  python -m ringpop_tpu tick-cluster --resume "$workdir/victim.npz" \
  --trace-out "$workdir/res_trace.npz" \
  | tee "$workdir/resume.log"
grep "final checksums:" "$workdir/resume.log" > "$workdir/res.sum"

echo "== verify: checksums + trace bit-identical, ledger soak rows"
diff "$workdir/ref.sum" "$workdir/res.sum"

JAX_PLATFORMS=cpu python - "$workdir" <<'EOF'
import sys

import numpy as np

from ringpop_tpu.obs.ledger import DispatchLedger, summarize_runs
from ringpop_tpu.scenarios.trace import Trace

workdir = sys.argv[1]
ref = Trace.load(f"{workdir}/ref_trace.npz")
res = Trace.load(f"{workdir}/res_trace.npz")
assert ref.ticks == res.ticks == 600
np.testing.assert_array_equal(ref.converged, res.converged)
np.testing.assert_array_equal(ref.live, res.live)
np.testing.assert_array_equal(ref.loss, res.loss)
assert set(ref.metrics) == set(res.metrics)
for k in ref.metrics:
    np.testing.assert_array_equal(ref.metrics[k], res.metrics[k], err_msg=k)
# the incident shape really ran: serving + overload series present,
# the feedback loop fired, and the latency plane reassembled bit-equal
assert ref.metrics["ov_gray_nodes"].max() > 0, "overload never degraded a node"
# the remediation plane really ran: the policy series resumed
# bit-equal (checked in the loop above) and its meter saw pressure
assert "policy_shed" in ref.metrics and "policy_retry_cap" in ref.metrics
assert ref.metrics["policy_pressure_max"].max() > 0, "policy meter stayed idle"
assert set(ref.planes) == set(res.planes) and "lat_hist_ms" in ref.planes
for k in ref.planes:
    np.testing.assert_array_equal(ref.planes[k], res.planes[k], err_msg=k)

# the victim + resume shared one run_id; per-segment rows carry the
# pipelining forensics the obs-ledger summarizer reads
rows = DispatchLedger.load_rows(f"{workdir}/ledger.jsonl")
seg_rows = [r for r in rows if r.get("run_id")]
assert seg_rows, "no per-segment ledger rows"
assert len({r["run_id"] for r in seg_rows}) == 1, "run_id not shared"
assert all("drain_overlap_s" in r for r in seg_rows)
runs = summarize_runs(rows)
# a SIGKILL between a segment's ledger record and its checkpoint write
# makes resume legitimately re-run (and re-record) that one segment,
# so the summed ticks may exceed the horizon by up to one segment
assert len(runs) == 1 and 600 <= runs[0]["ticks"] <= 620
print(
    f"resume smoke OK: {len(seg_rows)} segment rows, "
    f"drain overlap {runs[0]['overlap_pct']}%"
)
EOF

echo "soak-resume smoke passed"

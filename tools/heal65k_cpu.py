"""Round-5 insurance for BASELINE config 4 at n=65,536: the sided heal
to one checksum group, run to completion on the CPU host (VERDICT r4
item 2 allows any platform — the staged TPU config re-times it when the
tunnel cooperates).

Capacity rides at n/32 (=2,048) instead of the bench default n/16: the
sided fold keeps the live front far below either bound, per-tick sort
cost scales ~C log C, and the round has a wall-clock budget — drops (if
any) are recorded in the row and the config-4 metric (ticks to
groups=1) is drop-tolerant the same way the 1,024-node cap-256 row
converged through 130k drops.

Run: JAX_PLATFORMS=cpu python tools/heal65k_cpu.py [n] [capacity]
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ringpop_tpu.utils import pin_cpu_if_requested

pin_cpu_if_requested()

from benchmarks.bench_partition_heal_delta import run


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 65536
    cap = int(sys.argv[2]) if len(sys.argv) > 2 else max(256, n // 32)
    for row in run(n, sided=True, capacity=cap):
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()

"""Mechanical re-pin helper for every analysis/budgets.py table.

Runs the auditor in budget-printing mode and emits READY-TO-PASTE rows
for all three budget kinds — carry-dtype multisets, collective
censuses of the sharded entries, and compiled byte footprints — so
"re-pin by hand after every intentional change" (the CHANGES.md chore
since PR 13) becomes one command:

    python tools/pin_budgets.py                 # all three tables
    python tools/pin_budgets.py --kinds carry
    python tools/pin_budgets.py --kinds bytes --flagship   # + n=65,536

The byte rows compile ``run_scenario`` dense+delta at n=4096 (~20 s on
a CPU host); ``--flagship`` adds the delta n=65,536 row (the round-5
worker-killer, ~30 s to compile — the ROADMAP item 2 progress ledger).
Collective rows need >= 4 local devices; the script provisions CPU
virtual devices itself.

Paste the emitted rows over the matching entries in
``ringpop_tpu/analysis/budgets.py`` and re-run
``python -m ringpop_tpu audit`` to confirm a clean board.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from ringpop_tpu.utils import provision_virtual_devices  # noqa: E402

provision_virtual_devices(4)

BYTE_FIELDS = ("argument_bytes", "output_bytes", "temp_bytes", "peak_bytes")


def _carry_multiset(report) -> dict[str, int]:
    from collections import Counter

    ms: Counter = Counter()
    for leaves in report.carries.values():
        for leaf in leaves:
            ms[leaf.split("[")[0]] += 1
    return dict(sorted(ms.items()))


def _delta_comment(before: dict | None, after: dict) -> str:
    """One-line before→after column for a row that is being RE-pinned
    (absent for brand-new rows) — the machine-readable trajectory of a
    perf PR's claim, emitted as a trailing comment so the paste itself
    stays a valid table row."""
    if before is None:
        return ""
    parts = []
    for k in sorted(set(before) | set(after), key=str):
        b, v = before.get(k, 0), after.get(k, 0)
        if b == v or k == "ticks":
            continue
        pct = f" ({(v - b) / b:+.1%})" if b else ""
        parts.append(f"{k} {b}->{v}{pct}")
    return "  # was: " + "; ".join(parts) if parts else "  # unchanged"


def pin_carry(n: int, ticks: int) -> None:
    from ringpop_tpu.analysis import budgets
    from ringpop_tpu.analysis.contracts import audit_all

    print("# CARRY_BUDGETS rows (audit fixtures; shape-independent):")
    reports, _ = audit_all(n=n, ticks=ticks, compile_programs=False)
    for r in reports:
        ms = _carry_multiset(r)
        before = budgets.CARRY_BUDGETS.get((r.entry, r.backend))
        print(f'    ("{r.entry}", "{r.backend}"): {ms},'
              f"{_delta_comment(before, ms)}")


def pin_collectives(n: int, ticks: int) -> None:
    from ringpop_tpu.analysis.contracts import audit_all
    from ringpop_tpu.analysis.partitioning import collective_counts

    print(f"# COLLECTIVE_BUDGETS rows (sharded entries, n={n}):")
    reports, _ = audit_all(
        names=("sharded_step", "sharded_step@4", "sharded_delta_step",
               "sharded_step+gather", "run_sweep+shard"),
        n=n, ticks=ticks,
    )
    for r in reports:
        counts = collective_counts(r.collectives)
        # a remote-copy (p2p_only) entry pins member-gather to ZERO by
        # omission — a clean census has no member-gather key at all, so
        # surface the count where the paste happens to make the zero an
        # explicit claim rather than an absence
        mg = counts.get("member-gather", 0)
        note = (f"  # member-gather {mg} — NOT pasteable on a p2p_only entry"
                if mg else "  # member-gather 0 (p2p clean)")
        print(f'    ("{r.entry}", "{r.backend}", {r.mesh_size}): '
              f'{{"n": {r.n}, "counts": {counts}}},{note}')


def pin_bytes(n: int, ticks: int, flagship: bool) -> None:
    from ringpop_tpu.analysis import budgets
    from ringpop_tpu.analysis.contracts import audit_entry

    shapes = [("run_scenario", "dense", n), ("run_scenario", "delta", n)]
    if flagship:
        shapes.append(("run_scenario", "delta", 65536))
    print(f"# BYTE_BUDGETS rows (cpu platform, ticks={ticks}):")
    for entry, backend, nn in shapes:
        r = audit_entry(entry, backend, n=nn, ticks=ticks,
                        force_compile=True)
        row = {f: int(r.mem_bytes[f]) for f in BYTE_FIELDS}
        fields = ", ".join(f'"{f}": {v}' for f, v in row.items())
        before = budgets.BYTE_BUDGETS.get((entry, backend, nn))
        print(f'    ("{entry}", "{backend}", {nn}): '
              f'{{"ticks": {ticks}, {fields}}},'
              f"{_delta_comment(before, row)}")


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--kinds", default="carry,collectives,bytes",
                    help="comma list of carry,collectives,bytes")
    ap.add_argument("--n", type=int, default=64,
                    help="fixture n for carry/collective rows (the "
                         "audit default; collective budgets are "
                         "compared at their pinned n)")
    ap.add_argument("--ticks", type=int, default=4)
    ap.add_argument("--n-bytes", type=int, default=4096,
                    help="n for the byte-budget rows")
    ap.add_argument("--flagship", action="store_true",
                    help="also pin the delta n=65,536 byte row "
                         "(ROADMAP item 2's ledger; ~30 s compile)")
    args = ap.parse_args()

    kinds = set(args.kinds.split(","))
    unknown = kinds - {"carry", "collectives", "bytes"}
    if unknown:
        sys.exit(f"pin_budgets: unknown kind(s) {sorted(unknown)}")
    from ringpop_tpu.utils.jaxpin import PINNED_JAX_VERSION, jax_version

    if jax_version() != PINNED_JAX_VERSION:
        print(f"# WARNING: jax {jax_version()} != pinned "
              f"{PINNED_JAX_VERSION} — also bump "
              "ringpop_tpu/utils/jaxpin.py if this re-pin is the "
              "version migration")
    if "carry" in kinds:
        pin_carry(args.n, args.ticks)
    if "collectives" in kinds:
        pin_collectives(args.n, args.ticks)
    if "bytes" in kinds:
        pin_bytes(args.n_bytes, args.ticks, args.flagship)


if __name__ == "__main__":
    main()

#!/usr/bin/env bash
# CPU traffic smoke: a compiled scenario CO-RUN with a key workload
# through the tick-cluster CLI must (a) execute as ONE compiled
# dispatch whose ledger row carries the workload batch size, (b) emit
# a non-empty misroute trace while the kill event's divergence window
# is open, and (c) stream the serving-plane stat keys (lookup,
# requestProxy.*) through --stats-out alongside the protocol namespace.
# This is the CI traffic-smoke job's body; run it locally the same way:
#   tools/traffic_smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

workdir=$(mktemp -d /tmp/ringpop-traffic.XXXXXX)
trap 'rm -rf "$workdir"' EXIT
spec="$workdir/spec.json"
stats="$workdir/stats.jsonl"
ledger="$workdir/ledger.jsonl"

cat > "$spec" <<'EOF'
{
  "ticks": 30,
  "events": [
    {"at": 5, "op": "kill", "node": 3}
  ]
}
EOF

JAX_PLATFORMS=cpu RINGPOP_LEDGER="$ledger" timeout -k 10 600 \
  python -m ringpop_tpu tick-cluster --backend tpu-sim -n 16 \
  --scenario "$spec" --traffic zipf:128 --stats-out "$stats" \
  | tee "$workdir/out.log"

grep -q "one dispatch" "$workdir/out.log"
grep -q "traffic:" "$workdir/out.log"

JAX_PLATFORMS=cpu python - "$stats" "$ledger" <<'EOF'
import json
import sys

from ringpop_tpu.obs.bridge import DEFAULT_PREFIX, REFERENCE_KEYS, TRAFFIC_KEYS
from ringpop_tpu.obs.ledger import DispatchLedger

stats_path, ledger_path = sys.argv[1:3]

# (a) ONE compiled dispatch, carrying the workload batch size
rows = [r for r in DispatchLedger.load_rows(ledger_path)
        if r["program"] == "run_scenario"]
assert len(rows) == 1, rows
row = rows[0]
assert row["cold"] and row["compile_s"] > 0 and row["execute_s"] > 0
assert row["n"] == 16 and row["ticks"] == 30 and row["traffic_m"] == 128

# (b) the misroute trace is non-empty under the kill event
lines = [json.loads(line) for line in open(stats_path)]
misroutes = sum(
    line["value"] for line in lines
    if line["key"] == f"{DEFAULT_PREFIX}.sim.misroutes"
)
assert misroutes > 0, "no misroutes traced during the kill window"

# (c) serving-plane keys alongside the protocol namespace
keys = {line["key"] for line in lines}
wanted = [*REFERENCE_KEYS, *(k for k in TRAFFIC_KEYS if k != "lookupn")]
missing = [k for k in wanted if f"{DEFAULT_PREFIX}.{k}" not in keys]
assert not missing, f"missing stat keys: {missing}"
lookups = sum(
    line["value"] for line in lines
    if line["key"] == f"{DEFAULT_PREFIX}.lookup"
    and line["value"] is not None
)
assert lookups > 0, "no lookup increments streamed"

print(f"traffic smoke OK: one dispatch (compile {row['compile_s']:.2f}s, "
      f"execute {row['execute_s']:.3f}s), {int(lookups)} lookups, "
      f"{int(misroutes)} misroutes traced, {len(keys)} stat keys")
EOF

# --- SLO latency plane: delay + gray under traffic -------------------------
# A second scenario exercises the latency plane end to end: a delay rule
# plus a gray window must put real mass in the request-latency histogram
# (requestProxy.send timing stream), amplify retries above 1 under gray
# (sends per delivered request), and surface the new requestProxy keys
# in --stats-out.
spec2="$workdir/spec_slo.json"
stats2="$workdir/stats_slo.jsonl"

cat > "$spec2" <<'EOF'
{
  "ticks": 24,
  "events": [
    {"at": 3, "op": "gray", "nodes": [1, 2, 3, 4], "factor": 6, "until": 20},
    {"at": 4, "op": "delay", "src": [5, 6, 7], "dst": [8, 9, 10],
     "delay": 1, "jitter": 2, "until": 20},
    {"at": 5, "op": "kill", "node": 11}
  ]
}
EOF

JAX_PLATFORMS=cpu timeout -k 10 600 \
  python -m ringpop_tpu tick-cluster --backend tpu-sim -n 16 \
  --scenario "$spec2" --traffic zipf:128 --latency-buckets 16 \
  --stats-out "$stats2" \
  | tee "$workdir/out_slo.log"

grep -q "latency: p50=" "$workdir/out_slo.log"

JAX_PLATFORMS=cpu python - "$stats2" <<'EOF'
import json
import sys

from ringpop_tpu.obs.bridge import (
    DEFAULT_PREFIX, TRAFFIC_KEYS, TRAFFIC_LATENCY_KEYS,
)

lines = [json.loads(line) for line in open(sys.argv[1])]
keys = {line["key"] for line in lines}

# (a) the latency namespace joins the serving namespace
wanted = [*(k for k in TRAFFIC_KEYS if k != "lookupn"), *TRAFFIC_LATENCY_KEYS]
missing = [k for k in wanted if f"{DEFAULT_PREFIX}.{k}" not in keys]
assert not missing, f"missing SLO stat keys: {missing}"

# (b) nonzero latency-histogram mass: real timing samples streamed,
# some of them nonzero (the delay rule's link RTTs / retry backoff)
timings = [line["value"] for line in lines
           if line["type"] == "timing"
           and line["key"] == f"{DEFAULT_PREFIX}.requestProxy.send"]
assert timings, "no requestProxy.send timing samples streamed"
assert any(v > 0 for v in timings), "latency histogram mass is all-zero"

# (c) retry amplification > 1 under gray: sends per delivered request
def total(key, type_):
    return sum(line.get("value") or 0 for line in lines
               if line["key"] == f"{DEFAULT_PREFIX}.{key}"
               and line["type"] == type_)

sends = (total("requestProxy.send.success", "increment")
         + total("requestProxy.retry.attempted", "increment")
         + total("sim.handled-local", "gauge"))
delivered = total("sim.delivered", "gauge")
amp = sends / max(delivered, 1)
assert amp > 1.0, f"retry amplification {amp:.3f} not > 1 under gray"
gray = total("sim.gray-timeouts", "gauge")
assert gray > 0, "no gray timeouts under the gray window"

print(f"SLO smoke OK: amplification {amp:.2f} sends/delivered, "
      f"{gray} gray timeouts, {len(timings)} timing samples "
      f"(max {max(timings):.0f}ms)")
EOF

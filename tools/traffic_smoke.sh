#!/usr/bin/env bash
# CPU traffic smoke: a compiled scenario CO-RUN with a key workload
# through the tick-cluster CLI must (a) execute as ONE compiled
# dispatch whose ledger row carries the workload batch size, (b) emit
# a non-empty misroute trace while the kill event's divergence window
# is open, and (c) stream the serving-plane stat keys (lookup,
# requestProxy.*) through --stats-out alongside the protocol namespace.
# This is the CI traffic-smoke job's body; run it locally the same way:
#   tools/traffic_smoke.sh
set -euo pipefail
cd "$(dirname "$0")/.."

workdir=$(mktemp -d /tmp/ringpop-traffic.XXXXXX)
trap 'rm -rf "$workdir"' EXIT
spec="$workdir/spec.json"
stats="$workdir/stats.jsonl"
ledger="$workdir/ledger.jsonl"

cat > "$spec" <<'EOF'
{
  "ticks": 30,
  "events": [
    {"at": 5, "op": "kill", "node": 3}
  ]
}
EOF

JAX_PLATFORMS=cpu RINGPOP_LEDGER="$ledger" timeout -k 10 600 \
  python -m ringpop_tpu tick-cluster --backend tpu-sim -n 16 \
  --scenario "$spec" --traffic zipf:128 --stats-out "$stats" \
  | tee "$workdir/out.log"

grep -q "one dispatch" "$workdir/out.log"
grep -q "traffic:" "$workdir/out.log"

JAX_PLATFORMS=cpu python - "$stats" "$ledger" <<'EOF'
import json
import sys

from ringpop_tpu.obs.bridge import DEFAULT_PREFIX, REFERENCE_KEYS, TRAFFIC_KEYS
from ringpop_tpu.obs.ledger import DispatchLedger

stats_path, ledger_path = sys.argv[1:3]

# (a) ONE compiled dispatch, carrying the workload batch size
rows = [r for r in DispatchLedger.load_rows(ledger_path)
        if r["program"] == "run_scenario"]
assert len(rows) == 1, rows
row = rows[0]
assert row["cold"] and row["compile_s"] > 0 and row["execute_s"] > 0
assert row["n"] == 16 and row["ticks"] == 30 and row["traffic_m"] == 128

# (b) the misroute trace is non-empty under the kill event
lines = [json.loads(line) for line in open(stats_path)]
misroutes = sum(
    line["value"] for line in lines
    if line["key"] == f"{DEFAULT_PREFIX}.sim.misroutes"
)
assert misroutes > 0, "no misroutes traced during the kill window"

# (c) serving-plane keys alongside the protocol namespace
keys = {line["key"] for line in lines}
wanted = [*REFERENCE_KEYS, *(k for k in TRAFFIC_KEYS if k != "lookupn")]
missing = [k for k in wanted if f"{DEFAULT_PREFIX}.{k}" not in keys]
assert not missing, f"missing stat keys: {missing}"
lookups = sum(
    line["value"] for line in lines
    if line["key"] == f"{DEFAULT_PREFIX}.lookup"
    and line["value"] is not None
)
assert lookups > 0, "no lookup increments streamed"

print(f"traffic smoke OK: one dispatch (compile {row['compile_s']:.2f}s, "
      f"execute {row['execute_s']:.3f}s), {int(lookups)} lookups, "
      f"{int(misroutes)} misroutes traced, {len(keys)} stat keys")
EOF

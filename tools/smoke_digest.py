"""Invariant smoke for the rolling digest (DeltaState.digest): after
every tick of an event-heavy run, the carried value must equal the
from-scratch oracle (compute_digest).  Exercises matched updates,
insertions + capacity drops, self refutations, full syncs, phase-6
expiry, declarations, the ping-req exchange, admin join/revive, and
(second scenario) the sided netsplit flips + anti-entropy rebase.

Run: JAX_PLATFORMS=cpu python tools/smoke_digest.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ringpop_tpu.utils import pin_cpu_if_requested

pin_cpu_if_requested()

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.models import swim_delta as sd
from ringpop_tpu.models import swim_sim as sim
from ringpop_tpu.ops import bitpack


def check(st, where):
    got = np.asarray(st.digest)
    want = np.asarray(sd.compute_digest(st))
    assert (got == want).all(), (
        f"digest drift at {where}: {np.flatnonzero(got != want)[:8]} "
        f"(of {got.shape[0]})"
    )
    if st.d_bpmask is not None:  # RINGPOP_CARRY_SLOTBASE=1 states
        bpm_want, bpr_want = sd.compute_slot_base(st)
        got_bpm = bitpack.unpack_bits(st.d_bpmask, st.capacity)
        assert (np.asarray(got_bpm) == np.asarray(bpm_want)).all(), (
            f"d_bpmask drift at {where}"
        )
        assert (np.asarray(st.d_bprank) == np.asarray(bpr_want)).all(), (
            f"d_bprank drift at {where}"
        )


def scenario_unsided() -> None:
    n = 48
    # tiny wire + capacity force drops, full syncs, and window churn
    params = sd.DeltaParams(
        swim=sim.SwimParams(loss=0.05, suspicion_ticks=4),
        wire_cap=4,
        claim_grid=16,
    )
    st = sd.init_delta(n, capacity=12)
    check(st, "init")
    net = sim.make_net(n)
    key = jax.random.PRNGKey(3)
    net = net._replace(up=net.up.at[5].set(False))  # a death to detect
    for t in range(40):
        key, sub = jax.random.split(key)
        st, m = sd.delta_step(st, net, sub, params)
        check(st, f"unsided tick {t}")
    st = sd.admin_join(st, joiner=7, seed=1)
    check(st, "admin_join")
    st = sd.revive_and_join(st, 5, inc=9, seed=2)
    check(st, "revive_and_join")
    st = sd.admin_leave(st, 11)
    check(st, "admin_leave")
    st = sd.rebase(st)
    check(st, "rebase")
    print(
        "unsided ok: drops",
        int(st.overflow_drops),
        "occupancy",
        int(jnp.max(jnp.sum(st.d_subj < sd.SENTINEL, axis=1))),
    )


def scenario_sided() -> None:
    n = 64
    params = sd.DeltaParams(
        swim=sim.SwimParams(loss=0.01, suspicion_ticks=4),
        wire_cap=8,
        claim_grid=32,
    )
    st = sd.init_delta(n, capacity=24)
    net = sim.make_net(n)
    key = jax.random.PRNGKey(5)
    for t in range(2):
        key, sub = jax.random.split(key)
        st, _ = sd.delta_step(st, net, sub, params)
    gid = (np.arange(n) >= n // 2).astype(np.int32)
    st = sd.make_sides(st, gid)
    check(st, "make_sides")
    net = net._replace(adj=jnp.asarray(gid))
    for t in range(10):
        key, sub = jax.random.split(key)
        st, _ = sd.delta_step(st, net, sub, params)
        check(st, f"split tick {t}")
        if t % 5 == 4:
            st = sd.rebase(st, anti_entropy=True)
            check(st, f"anti-entropy rebase @ {t}")
    net = net._replace(adj=jnp.zeros((n,), jnp.int32))  # heal
    for t in range(25):
        key, sub = jax.random.split(key)
        st, _ = sd.delta_step(st, net, sub, params)
        check(st, f"heal tick {t}")
        if t % 5 == 4:
            st = sd.rebase(st, anti_entropy=True)
            check(st, f"post-heal rebase @ {t}")
    st = sd.fold_to_single(sd.rebase(st))
    check(st, "fold_to_single")
    print("sided ok: drops", int(st.overflow_drops))


if __name__ == "__main__":
    scenario_unsided()
    scenario_sided()
    print("rolling digest invariant: OK")

"""Memory-footprint census regression: the program HBM shapes, pinned.

``benchmarks/mem_census.py`` is the instrument the round-5 worker
crash was missing — AOT ``memory_analysis()`` of the compiled
programs.  This test pins the two facts the instrument exists to
state:

* every censused program (swim_run / delta_run / run_scenario /
  run_sweep) reports positive argument / temp / peak bytes;
* at a fixed shape the dense backend's peak is STRICTLY larger than
  the delta backend's (the entire reason swim_delta exists), and the
  sweep's argument bytes scale ~R x the single-scenario program's
  (the donated carry gains a replica axis — sweep.py's memory model).

Slow-marked: each row is a full AOT compile.  Ceil-free assertions
only (orderings and scalings, not absolute byte budgets — XLA's
allocator is allowed to improve).
"""

from __future__ import annotations

import pytest

from benchmarks import mem_census as mc

N = 1024
R = 2
TICKS = 2


@pytest.fixture(scope="module")
def rows():
    dense = mc.run(
        backends=("dense",), ns=(N,), ticks=TICKS, capacity=64,
        replicas=R, programs=("run", "scenario", "sweep"),
    )
    delta = mc.run(
        backends=("delta",), ns=(N,), ticks=TICKS, capacity=64,
        replicas=R, programs=("run",),
    )
    return {(r["program"], r["backend"]): r for r in dense + delta}


@pytest.mark.slow
def test_census_emits_all_programs(rows):
    expected = [
        ("swim_run", "dense"),
        ("run_scenario", "dense"),
        ("run_sweep", "dense"),
        ("delta_run", "delta"),
    ]
    for key in expected:
        row = rows[key]
        for field in ("argument_bytes", "temp_bytes", "peak_bytes"):
            assert row[field] > 0, (key, field)
        assert row["n"] == N


@pytest.mark.slow
def test_census_pins_dense_vs_delta_peak_ordering(rows):
    """At n=1024, C=64 the dense scan's peak must dominate the delta
    scan's — measured ~4x apart (57 MB vs 13 MB on CPU jax 0.4.37),
    asserted with margin.  A flip here means one backend's memory
    shape changed out from under its scaling story."""
    dense = rows[("swim_run", "dense")]
    delta = rows[("delta_run", "delta")]
    assert dense["peak_bytes"] > 2 * delta["peak_bytes"]
    assert dense["argument_bytes"] > 4 * delta["argument_bytes"]


@pytest.mark.slow
def test_census_segmented_scenario_peak_flat_in_total_ticks():
    """The streamed runner's CPU-side footprint deliverable (ROADMAP
    item 2 / the streaming rework): the S-tick segment program's peak
    bytes are a function of (backend, n, S) ONLY — censusing it under
    a 4x longer total horizon reports byte-identical footprints, while
    the whole-trace program's output bytes grow linearly with T (the
    stacked telemetry).  This is what makes a 1M-tick soak
    memory-feasible: the host holds O(segment), the device holds one
    segment's program."""
    # small n so the [T]-stacked telemetry dominates the fixed-size
    # final state in the output accounting (at large n the N^2 state
    # swamps it and the T term would hide in the noise)
    n, s = 32, 8
    seg_short = mc.census_scenario("dense", n, 64, 64, segment_ticks=s)
    seg_long = mc.census_scenario("dense", n, 1024, 64, segment_ticks=s)
    for field in ("argument_bytes", "output_bytes", "temp_bytes",
                  "peak_bytes"):
        assert seg_short[field] == seg_long[field], field
    whole_short = mc.census_scenario("dense", n, 64, 64)
    whole_long = mc.census_scenario("dense", n, 1024, 64)
    # the whole-trace program hoards [T]-stacked outputs: 16x the
    # ticks grows the output bytes severalfold (plus the T-shaped
    # key/loss inputs), while the segment program never saw T at all
    assert whole_long["output_bytes"] > 2 * whole_short["output_bytes"]
    assert whole_long["argument_bytes"] > whole_short["argument_bytes"]
    assert seg_long["output_bytes"] < whole_long["output_bytes"]


@pytest.mark.slow
def test_census_sweep_arguments_scale_with_replicas(rows):
    """The sweep's donated carry is R x the single-scenario state (the
    broadcast replica axis), so its argument bytes must be ~R x the
    scenario program's — the 'R x state, not R x programs' claim in a
    checkable form.  Temporaries are allowed to scale worse (vmap
    batches the per-tick scratch too); peak must at least cover R x
    the single program's arguments."""
    sweep_row = rows[("run_sweep", "dense")]
    scen = rows[("run_scenario", "dense")]
    lo = (R - 0.5) * scen["argument_bytes"]
    hi = (R + 0.5) * scen["argument_bytes"]
    assert lo < sweep_row["argument_bytes"] < hi
    assert sweep_row["peak_bytes"] > R * scen["argument_bytes"]
    assert sweep_row["replicas"] == R

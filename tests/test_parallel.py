"""Multi-chip sharding tests on the virtual 8-device CPU mesh.

The sharded step must (a) compile and execute with state rows distributed
across devices, and (b) be semantically identical to the single-device
step — sharding is a layout decision, not a semantics change.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ringpop_tpu import parallel
from ringpop_tpu.models import swim_sim as sim

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)


def test_mesh_and_placement():
    mesh = parallel.make_mesh(8)
    state, net = parallel.shard_cluster(
        sim.init_state(64), sim.make_net(64, partitioned=True), mesh
    )
    # Rows really are distributed: 8 shards of 8 rows each.
    shard_shapes = {s.data.shape for s in state.view_key.addressable_shards}
    assert shard_shapes == {(8, 64)}
    assert len(net.adj.addressable_shards) == 8


def test_sharded_step_matches_single_device():
    n = 64
    params = sim.SwimParams(loss=0.0)
    key = jax.random.PRNGKey(7)

    ref_state, _ = sim.swim_step(sim.init_state(n, mode="self"), sim.make_net(n), key, params)

    mesh = parallel.make_mesh(8)
    state, net = parallel.shard_cluster(
        sim.init_state(n, mode="self"), sim.make_net(n), mesh
    )
    step = parallel.sharded_step(mesh)
    sh_state, _ = step(state, net, key, params)

    np.testing.assert_array_equal(
        np.asarray(ref_state.view_status), np.asarray(sh_state.view_status)
    )
    np.testing.assert_array_equal(
        np.asarray(ref_state.view_inc), np.asarray(sh_state.view_inc)
    )
    np.testing.assert_array_equal(np.asarray(ref_state.pb), np.asarray(sh_state.pb))


def test_sharded_run_converges():
    # A 64-node cluster where node 0 knows everyone (post-join seed) must
    # converge under the sharded scan just like the single-device one.
    n = 64
    params = sim.SwimParams()
    state = sim.init_state(n, mode="self")
    for j in range(1, n):
        state = sim.admin_join(state, j, 0)
    mesh = parallel.make_mesh(8)
    state, net = parallel.shard_cluster(state, sim.make_net(n), mesh)
    run = parallel.sharded_run(mesh)
    state, _ = run(state, net, jax.random.PRNGKey(0), params, 40)
    vs = np.asarray(state.view_status)
    vi = np.asarray(state.view_inc)
    assert (vs == vs[0]).all() and (vi == vi[0]).all()
    assert (np.diagonal(vs) == sim.ALIVE).all()


def test_uneven_shard_rejected():
    mesh = parallel.make_mesh(8)
    with pytest.raises(ValueError):
        parallel.shard_cluster(sim.init_state(12), sim.make_net(12), mesh)

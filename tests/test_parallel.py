"""Multi-chip sharding tests on the virtual 8-device CPU mesh.

The sharded step must (a) compile and execute with state rows distributed
across devices, and (b) be semantically identical to the single-device
step — sharding is a layout decision, not a semantics change.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ringpop_tpu import parallel
from ringpop_tpu.models import swim_sim as sim

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)


def test_mesh_and_placement():
    mesh = parallel.make_mesh(8)
    state, net = parallel.shard_cluster(
        sim.init_state(64), sim.make_net(64, partitioned=True), mesh
    )
    # Rows really are distributed: 8 shards of 8 rows each.
    shard_shapes = {s.data.shape for s in state.view_key.addressable_shards}
    assert shard_shapes == {(8, 64)}
    assert len(net.adj.addressable_shards) == 8


def test_sharded_step_matches_single_device():
    n = 64
    params = sim.SwimParams(loss=0.0)
    key = jax.random.PRNGKey(7)

    ref_state, _ = sim.swim_step(sim.init_state(n, mode="self"), sim.make_net(n), key, params)

    mesh = parallel.make_mesh(8)
    state, net = parallel.shard_cluster(
        sim.init_state(n, mode="self"), sim.make_net(n), mesh
    )
    step = parallel.sharded_step(mesh)
    sh_state, _ = step(state, net, key, params)

    np.testing.assert_array_equal(
        np.asarray(ref_state.view_status), np.asarray(sh_state.view_status)
    )
    np.testing.assert_array_equal(
        np.asarray(ref_state.view_inc), np.asarray(sh_state.view_inc)
    )
    np.testing.assert_array_equal(np.asarray(ref_state.pb), np.asarray(sh_state.pb))


def test_sharded_run_converges():
    # A 64-node cluster where node 0 knows everyone (post-join seed) must
    # converge under the sharded scan just like the single-device one.
    n = 64
    params = sim.SwimParams()
    state = sim.init_state(n, mode="self")
    for j in range(1, n):
        state = sim.admin_join(state, j, 0)
    mesh = parallel.make_mesh(8)
    state, net = parallel.shard_cluster(state, sim.make_net(n), mesh)
    run = parallel.sharded_run(mesh)
    state, _ = run(state, net, jax.random.PRNGKey(0), params, 40)
    vs = np.asarray(state.view_status)
    vi = np.asarray(state.view_inc)
    assert (vs == vs[0]).all() and (vi == vi[0]).all()
    assert (np.diagonal(vs) == sim.ALIVE).all()


def test_sharded_step_pallas_env_falls_back(monkeypatch):
    """RINGPOP_RECV_MERGE="pallas" must not break the mesh path: the
    Pallas kernel has no SPMD partitioning rule, so the dense sharded
    step falls back to the (bit-identical) sorted lowering at trace
    time (parallel.mesh._mesh_recv_merge)."""
    n = 64
    params = sim.SwimParams(loss=0.0)
    key = jax.random.PRNGKey(7)
    ref_state, _ = sim.swim_step(
        sim.init_state(n, mode="self"), sim.make_net(n), key, params
    )
    ref_vk = np.asarray(ref_state.view_key)

    monkeypatch.setattr(sim, "_RECV_MERGE", "pallas")
    jax.clear_caches()
    try:
        mesh = parallel.make_mesh(8)
        state, net = parallel.shard_cluster(
            sim.init_state(n, mode="self"), sim.make_net(n), mesh
        )
        step = parallel.sharded_step(mesh)
        sh_state, _ = step(state, net, key, params)
        sh_vk = np.asarray(sh_state.view_key)
    finally:
        # executables traced under the patched global must not outlive it
        jax.clear_caches()
    np.testing.assert_array_equal(ref_vk, sh_vk)


def test_uneven_shard_rejected():
    mesh = parallel.make_mesh(8)
    with pytest.raises(ValueError):
        parallel.shard_cluster(sim.init_state(12), sim.make_net(12), mesh)


# -- delta backend on the mesh ----------------------------------------------


def test_sharded_delta_step_bit_parity():
    """Row-sharding the delta tables is a layout decision: a lossy
    trajectory through a kill must match the single-device delta step
    bit for bit."""
    from ringpop_tpu.models import swim_delta as sd

    n = 64
    params = sd.DeltaParams(
        swim=sim.SwimParams(loss=0.05, suspicion_ticks=6),
        wire_cap=8,
        claim_grid=16,
    )
    net = sim.make_net(n)
    net = net._replace(up=net.up.at[9].set(False))
    keys = jax.random.split(jax.random.PRNGKey(4), 12)

    ref = sd.init_delta(n, capacity=32)
    step_ref = jax.jit(sd.delta_step_impl, static_argnames=("params", "upto"))
    mesh = parallel.make_mesh(8)
    sh = parallel.shard_delta(sd.init_delta(n, capacity=32), mesh)
    step_sh = parallel.sharded_delta_step(mesh)

    for t, k in enumerate(keys):
        ref, m_ref = step_ref(ref, net, k, params)
        sh, m_sh = step_sh(sh, net, k, params)
        for name in ("d_subj", "d_key", "d_pb", "d_sl", "base_key"):
            np.testing.assert_array_equal(
                np.asarray(getattr(ref, name)),
                np.asarray(getattr(sh, name)),
                err_msg=f"{name} tick {t}",
            )
        assert int(m_ref["pings_sent"]) == int(m_sh["pings_sent"])
    # shards really are distributed
    shard_shapes = {s.data.shape for s in sh.d_subj.addressable_shards}
    assert shard_shapes == {(8, 32)}


@pytest.mark.slow
def test_sharded_delta_run_scan():
    from ringpop_tpu.models import swim_delta as sd

    n = 64
    params = sd.DeltaParams(swim=sim.SwimParams(loss=0.01))
    mesh = parallel.make_mesh(8)
    sh = parallel.shard_delta(sd.init_delta(n, capacity=32), mesh)
    run = parallel.sharded_delta_run(mesh)
    sh, m = run(sh, sim.make_net(n), jax.random.PRNGKey(1), params, 10)
    assert int(sh.tick) == 10
    assert int(m["pings_sent"]) > 0


def test_sharded_delta_rejects_dense_adjacency():
    from ringpop_tpu.models import swim_delta as sd

    mesh = parallel.make_mesh(8)
    net = sim.make_net(64, partitioned=True)
    step = parallel.sharded_delta_step(mesh)
    state = parallel.shard_delta(sd.init_delta(64, capacity=16), mesh)
    with pytest.raises(NotImplementedError):
        step(state, net, jax.random.PRNGKey(0), sd.DeltaParams())


@pytest.mark.slow
def test_sharded_delta_partition_bit_parity():
    """Group-id netsplit on the 8-way mesh == the single-device delta
    trajectory (which test_bit_identical_partition_split_and_heal pins
    to dense) — the partition form the 65k config-4 scenario uses."""
    from ringpop_tpu.models import swim_delta as sd

    n = 64
    params = sd.DeltaParams(
        swim=sim.SwimParams(loss=0.02, suspicion_ticks=5),
        wire_cap=n,
        claim_grid=2 * n,
    )
    gid = (jnp.arange(n) >= n // 2).astype(jnp.int32)
    net = sim.make_net(n)._replace(adj=gid)
    mesh = parallel.make_mesh(8)
    step = parallel.sharded_delta_step(mesh, net_like=net)
    sh = parallel.shard_delta(sd.init_delta(n, capacity=n), mesh)
    ref = sd.init_delta(n, capacity=n)
    keys = jax.random.split(jax.random.PRNGKey(7), 15)
    for t in range(15):
        sh, _ = step(sh, net, keys[t], params)
        ref, _ = jax.jit(sd.delta_step_impl, static_argnames=("params",))(
            ref, net, keys[t], params
        )
    np.testing.assert_array_equal(
        np.asarray(sd.densify(sh).view_key), np.asarray(sd.densify(ref).view_key)
    )


@pytest.mark.slow
def test_sharded_sided_delta_bit_parity():
    """The sided (structured-netsplit) state shards too: [G, N] base
    rows / flip table / side vector replicate, tables row-shard — and
    the mesh trajectory matches the single-device sided one bit for
    bit.  (References are built fresh per run: device_put may alias
    replicated buffers, so a donated sharded step can delete the
    original state's arrays.)"""
    from ringpop_tpu.models import swim_delta as sd

    n = 64

    def mk():
        return sd.make_sides(
            sd.init_delta(n, capacity=16),
            (np.arange(n) >= n // 2).astype(np.int32),
        )

    gid = (jnp.arange(n) >= n // 2).astype(jnp.int32)
    net = sim.make_net(n)._replace(adj=gid)
    params = sd.DeltaParams(
        swim=sim.SwimParams(loss=0.0, suspicion_ticks=5), wire_cap=8,
        claim_grid=64,
    )
    ref = mk()
    key = jax.random.PRNGKey(0)
    stp = jax.jit(sd.delta_step_impl, static_argnames=("params",))
    for _ in range(6):
        key, sub = jax.random.split(key)
        ref, _ = stp(ref, net, sub, params)

    mesh = parallel.make_mesh(8)
    st = mk()
    step = parallel.sharded_delta_step(mesh, net_like=net, state_like=st)
    sh = parallel.shard_delta(st, mesh)
    key = jax.random.PRNGKey(0)
    for _ in range(6):
        key, sub = jax.random.split(key)
        sh, _ = step(sh, net, sub, params)
    np.testing.assert_array_equal(
        np.asarray(sd.densify(sh).view_key), np.asarray(sd.densify(ref).view_key)
    )

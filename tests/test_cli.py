"""CLI & tooling tests (reference: scripts/tick-cluster.js,
scripts/generate-hosts.js).

The sim-mode driver runs entirely on virtual time; the proc-mode test
spawns real worker processes over the TCP transport (the reference's
process-per-node shape, tick-cluster.js:352-416) and is marked slow.
"""

from __future__ import annotations

import io
import sys

import pytest

from ringpop_tpu.cli.generate_hosts import generate
from ringpop_tpu.cli.tick_cluster import (
    SimCluster,
    group_by_checksum,
    run_script,
)


def test_generate_hosts():
    hosts = generate(["127.0.0.1", "10.0.0.2"], 3000, 3)
    assert hosts == [
        "127.0.0.1:3000", "127.0.0.1:3001", "127.0.0.1:3002",
        "10.0.0.2:3000", "10.0.0.2:3001", "10.0.0.2:3002",
    ]


def test_group_by_checksum():
    groups = group_by_checksum({"a": 1, "b": 1, "c": 2})
    assert sorted(groups[1]) == ["a", "b"]
    assert groups[2] == ["c"]


def capture(fn) -> str:
    old = sys.stdout
    sys.stdout = buf = io.StringIO()
    try:
        fn()
    finally:
        sys.stdout = old
    return buf.getvalue()


def test_sim_tick_cluster_script_converges_and_survives_faults():
    driver = SimCluster(size=5, base_port=24400, seed=7)
    out = capture(lambda: run_script(
        driver, "j,w3000,t,s,k,w1000,t,K,w10000,t,l,w1000,L,q"))
    driver.shutdown()
    lines = [l for l in out.splitlines() if l.startswith("tick:")]
    assert lines[0].startswith("tick: CONVERGED [5]")
    assert lines[1].startswith("tick: CONVERGED [4]")  # after kill
    assert lines[2].startswith("tick: CONVERGED [5]")  # after revive
    assert "suspended" in out and "resumed" in out


@pytest.mark.slow
def test_proc_tick_cluster_three_real_processes():
    from ringpop_tpu.cli.tick_cluster import ProcCluster

    cluster = ProcCluster(3, 24500, log_level="error")
    try:
        cluster.wait_healthy(90)
        out = capture(lambda: run_script(cluster, "j,w4000,t"))
        assert "join: 3 nodes joined" in out
        assert "tick: CONVERGED [3]" in out
    finally:
        cluster.shutdown()


def test_tpu_sim_tick_cluster_backend():
    """The tensor-simulation backend behind the tick-cluster command
    surface: kill -> faulty convergence at N-1, revive -> N."""
    from ringpop_tpu.cli.tick_cluster import TpuSimCluster

    driver = TpuSimCluster(size=24, seed=5, loss=0.02)
    out = capture(lambda: run_script(
        driver, "j,t,k,w6000,t,s,K,w8000,t,q"))
    driver.shutdown()
    lines = [l for l in out.splitlines() if l.startswith("tick:")]
    assert lines[0].startswith("tick: CONVERGED [24]")
    assert lines[1].startswith("tick: CONVERGED [23]")
    assert lines[2].startswith("tick: CONVERGED [24]")

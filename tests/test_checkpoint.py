"""Checkpoint/resume determinism: save -> load -> tick == tick
(a capability the reference lacks entirely, SURVEY §5.4)."""

from __future__ import annotations

import numpy as np
import pytest

from ringpop_tpu import checkpoint
from ringpop_tpu.models import swim_sim as sim
from ringpop_tpu.models.cluster import SimCluster


def states_equal(a, b) -> bool:
    return all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(a, b)
    )


def test_roundtrip_identity(tmp_path):
    cluster = SimCluster(32, sim.SwimParams(loss=0.05), seed=9)
    cluster.tick(7)
    cluster.kill(3)
    cluster.suspend(5)
    path = str(tmp_path / "ckpt.npz")
    checkpoint.save(cluster, path)
    restored = checkpoint.load(path)
    assert states_equal(cluster.state, restored.state)
    assert states_equal(cluster.net, restored.net)
    assert restored.params == cluster.params
    assert restored.book.addresses == cluster.book.addresses


def test_resume_is_bit_deterministic(tmp_path):
    cluster = SimCluster(24, sim.SwimParams(loss=0.1), seed=4)
    cluster.tick(5)
    path = str(tmp_path / "ckpt.npz")
    checkpoint.save(cluster, path)

    cluster.tick(6)  # original continues
    resumed = checkpoint.load(path)
    resumed.tick(6)  # restored continues from the same point

    assert states_equal(cluster.state, resumed.state)
    assert cluster.checksums() == resumed.checksums()


@pytest.mark.slow
def test_checkpoint_then_fault_injection(tmp_path):
    cluster = SimCluster(16, sim.SwimParams(), seed=2)
    cluster.tick(3)
    path = str(tmp_path / "ckpt.npz")
    checkpoint.save(cluster, path)
    resumed = checkpoint.load(path)
    resumed.kill(1)
    resumed.tick(40)
    live = resumed.live_indices()
    status = np.asarray(resumed.state.view_status[:, 1])
    assert (status[live] == sim.FAULTY).all()


@pytest.mark.slow
def test_delta_backend_roundtrip_and_resume(tmp_path):
    """v3 checkpoints carry the delta backend: DeltaState leaves plus
    the resource caps, and resume stays bit-deterministic.

    Nightly lane: at ~55 s (three delta-program compiles) this was the
    single heaviest fast-lane test while the whole tier-1 run pushes
    the ROADMAP's 870 s watchdog; the delta checkpoint family keeps
    tier-1 representatives (`test_load_backfills_predigest_delta_
    checkpoint`, `test_roundtrip_telemetry`)."""
    n = 16
    cluster = SimCluster(
        n, sim.SwimParams(loss=0.05), seed=7, backend="delta",
        capacity=n, wire_cap=n, claim_grid=2 * n,
    )
    cluster.kill(3)
    cluster.tick(5)
    path = str(tmp_path / "delta.npz")
    checkpoint.save(cluster, path)

    cluster.tick(6)
    resumed = checkpoint.load(path)
    assert resumed.backend == "delta"
    assert resumed.state.capacity == n
    assert resumed.dparams.wire_cap == n
    resumed.tick(6)  # the kill is part of the checkpointed net

    for name in ("base_key", "d_subj", "d_key", "d_pb", "d_sl"):
        np.testing.assert_array_equal(
            np.asarray(getattr(cluster.state, name)),
            np.asarray(getattr(resumed.state, name)),
            err_msg=name,
        )
    assert cluster.checksums() == resumed.checksums()


def test_roundtrip_telemetry(tmp_path):
    """v4 checkpoints carry the telemetry: metrics_log entries (with
    their tick spans) and scenario traces resume with the run instead
    of restarting blind."""
    from ringpop_tpu.scenarios.trace import Trace

    cluster = SimCluster(8, sim.SwimParams(), seed=5)
    cluster.tick(2)
    cluster.tick()
    cluster.traces.append(
        Trace(
            metrics={"pings_sent": np.arange(4, dtype=np.int32)},
            converged=np.array([True, False, False, True]),
            live=np.array([8, 7, 7, 7], np.int32),
            loss=np.zeros(4, np.float32),
            n=8,
            backend="dense",
            start_tick=3,
            spec={"ticks": 4, "events": []},
        )
    )
    path = str(tmp_path / "ckpt.npz")
    checkpoint.save(cluster, path)
    restored = checkpoint.load(path)
    assert restored.metrics_log == cluster.metrics_log
    assert restored.metrics_log[0]["ticks"] == 2
    assert restored.metrics_log[1]["ticks"] == 1
    assert len(restored.traces) == 1
    back = restored.traces[0].validate()
    assert back.backend == "dense" and back.start_tick == 3
    assert back.spec == {"ticks": 4, "events": []}
    np.testing.assert_array_equal(
        back.metrics["pings_sent"], cluster.traces[0].metrics["pings_sent"]
    )
    np.testing.assert_array_equal(back.converged, cluster.traces[0].converged)


def test_load_backfills_pretelemetry_checkpoint(tmp_path):
    """Checkpoints written before v4 (no metrics_log/traces in meta)
    must load with empty telemetry — the backfill default, mirroring
    the delta carried-derivative pattern below."""
    import json

    cluster = SimCluster(8, sim.SwimParams(), seed=5)
    cluster.tick(2)
    path = str(tmp_path / "ckpt.npz")
    checkpoint.save(cluster, path)

    data = dict(np.load(path, allow_pickle=False))
    meta = json.loads(bytes(data["meta"]).decode())
    del meta["metrics_log"], meta["traces"]
    meta["version"] = 3
    data["meta"] = np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8)
    old_path = str(tmp_path / "old.npz")
    np.savez_compressed(old_path, **data)

    restored = checkpoint.load(old_path)
    assert restored.metrics_log == []
    assert restored.traces == []
    restored.tick(2)  # still resumes


def test_load_backfills_predigest_delta_checkpoint(tmp_path):
    """A v3 delta checkpoint written BEFORE the carried derivatives
    existed (no state.digest / state.d_bpmask keys in the .npz) must
    load with the rolling digest backfilled from the oracle — the
    compatibility case the load-time backfill exists for."""
    from ringpop_tpu.models import swim_delta as sd
    from ringpop_tpu.models import swim_sim as sim
    from ringpop_tpu.models.cluster import SimCluster

    n = 16
    c = SimCluster(
        n, sim.SwimParams(loss=0.05), seed=1, backend="delta", capacity=8,
        wire_cap=4, claim_grid=16,
    )
    c.tick(6)
    path = tmp_path / "new.npz"
    checkpoint.save(c, str(path))

    # strip the carried-derivative arrays, simulating the old format
    data = dict(np.load(str(path), allow_pickle=False))
    stripped = {
        k: v
        for k, v in data.items()
        if k not in ("state.digest", "state.d_bpmask", "state.d_bprank")
    }
    old_path = tmp_path / "old.npz"
    np.savez_compressed(str(old_path), **stripped)

    c2 = checkpoint.load(str(old_path))
    assert c2.state.digest is not None
    np.testing.assert_array_equal(
        np.asarray(c2.state.digest), np.asarray(sd.compute_digest(c2.state))
    )
    # resumed trajectory matches the original cluster's
    c.tick(4)
    c2.tick(4)
    assert c.checksums() == c2.checksums()


def test_packed_plane_roundtrip_and_unpacked_backfill(tmp_path):
    """v5 checkpoints store the bit-packed lattice planes (uint32 word
    tensors under the historical names); a checkpoint written by the
    unpacked format (bool tensors, same keys) must load with the planes
    re-packed at load time — the .npz is self-describing by dtype, so
    FORMAT_VERSION stays 5."""
    from ringpop_tpu.models import swim_delta as sd
    from ringpop_tpu.ops import bitpack

    n = 16
    c = SimCluster(
        n, sim.SwimParams(loss=0.05), seed=5, backend="delta", capacity=8,
        wire_cap=4, claim_grid=16,
    )
    c.tick(5)
    path = tmp_path / "packed.npz"
    checkpoint.save(c, str(path))

    # the on-disk plane is the packed word tensor
    data = dict(np.load(str(path), allow_pickle=False))
    assert data["state.bp_mask"].dtype == np.uint32
    assert data["state.bp_mask"].shape == (bitpack.packed_width(n),)

    # packed round trip
    c2 = checkpoint.load(str(path))
    assert c2.state.bp_mask.dtype == np.uint32
    np.testing.assert_array_equal(
        np.asarray(c2.state.bp_mask), np.asarray(c.state.bp_mask)
    )

    # old unpacked checkpoint: same keys, bool tensors -> packed on load
    unpacked = dict(data)
    unpacked["state.bp_mask"] = np.asarray(
        bitpack.unpack_bits(data["state.bp_mask"], n)
    )
    if "state.d_bpmask" in data and data["state.d_bpmask"].dtype == np.uint32:
        unpacked["state.d_bpmask"] = np.asarray(
            bitpack.unpack_bits(
                data["state.d_bpmask"], c.state.capacity
            )
        )
    old_path = tmp_path / "unpacked.npz"
    np.savez_compressed(str(old_path), **unpacked)
    c3 = checkpoint.load(str(old_path))
    assert c3.state.bp_mask.dtype == np.uint32
    np.testing.assert_array_equal(
        np.asarray(c3.state.bp_mask), np.asarray(c.state.bp_mask)
    )
    if c3.state.d_bpmask is not None:
        assert c3.state.d_bpmask.dtype == np.uint32

    # both resumes stay bit-deterministic with the original
    c.tick(4)
    c2.tick(4)
    c3.tick(4)
    assert c.checksums() == c2.checksums() == c3.checksums()
    np.testing.assert_array_equal(
        np.asarray(c.state.digest), np.asarray(sd.compute_digest(c.state))
    )

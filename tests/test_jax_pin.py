"""The jax version pin guard (ringpop_tpu/utils/jaxpin.py).

One fast, loud failure when the environment's jax is not the pinned
build — instead of dozens of inscrutable bit-diff failures across the
golden lanes (incident goldens, seeded golden traces, carry /
collective / byte budget tables), this names exactly what to do: bump
the pin, re-pin the goldens (tools/pin_incidents.py) and the budgets
(tools/pin_budgets.py).  The golden-lane tests themselves consult
``golden_skip_reason()`` and SKIP with the same instruction, so a jax
bump degrades the suite visibly rather than explosively.
"""

import jax

from ringpop_tpu.utils.jaxpin import (
    PINNED_JAX_VERSION,
    golden_skip_reason,
    jax_version_matches,
)


def test_running_jax_is_the_pinned_build():
    assert jax.__version__ == PINNED_JAX_VERSION, (
        f"jax {jax.__version__} != pinned {PINNED_JAX_VERSION}.  The "
        "golden lanes (tests/golden/incidents, the seeded golden "
        "traces) and every analysis budget table (carry dtypes, "
        "collective censuses, byte footprints) were pinned under "
        f"{PINNED_JAX_VERSION}'s threefry + partitioner.  On an "
        "intentional bump: update ringpop_tpu/utils/jaxpin.py, then "
        "re-pin via tools/pin_incidents.py and tools/pin_budgets.py."
    )


def test_skip_reason_contract():
    # under the pinned build the guard is silent; the skip message —
    # whenever it fires — must carry the re-pin instruction, because
    # it is the only thing a CI log will show
    if jax_version_matches():
        assert golden_skip_reason() is None
    else:
        reason = golden_skip_reason()
        assert reason and "re-pin" in reason
        assert "pin_budgets" in reason and "pin_incidents" in reason


def test_partitioning_budget_checks_degrade_on_mismatch(monkeypatch):
    # the auditor's budget comparisons must turn into ONE warning per
    # check under a foreign jax, not a wall of drift errors
    from ringpop_tpu.analysis import partitioning

    monkeypatch.setattr(partitioning, "jax_version_matches", lambda: False)
    guard = partitioning._version_guard("fx", "collective-census")
    (f,) = guard
    assert f.severity == "warning" and "re-pin" in f.message

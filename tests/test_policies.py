"""The remediation policy plane: compiled operator loop vs host oracle.

The acceptance oracle extends ``tests/test_overload.py``'s per-tick
host walk with the three policy mechanisms, consumed with the same
one-tick causality as the compiled scan (serve at ``t`` reads the
planes the fold produced at ``t-1``):

* **admission** — a request whose first resolved holder is shedding is
  rejected at arrival: one landed send on that holder, zero retries,
  counted as ``policy_shed`` (never delivered, never proxy_failed);
* **quarantine** — pressured nodes are steered out of every viewer's
  served ring (the damped-mask mechanism), so the host rings exclude
  them at construction;
* **retry budget** — the origin retry gate compares against
  ``min(max_retries, po_retry_cap)``, the cap the trailing
  amplification window set.

The post-serve fold is THE SAME ``policies.core.policy_update``
function executed on np arrays — parity is equality, not tolerance,
on both backends.  Fast lane: pure-host units + precheck rejections +
checkpoint v5 round trip + the dense oracle per policy (one compiled
program for all four: mechanism enables are knob VALUES, so the
parametrization recompiles nothing).  Delta twin, streamed/SIGKILL
resume, and the knob-axis sweep parity ride the slow lane.
"""

from __future__ import annotations

from collections import defaultdict

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ringpop_tpu.hashring import HashRing
from ringpop_tpu.models import swim_delta as sdelta
from ringpop_tpu.models import swim_sim as sim
from ringpop_tpu.models.cluster import SimCluster
from ringpop_tpu.models.swim_sim import SwimParams
from ringpop_tpu.ops import ring_ops
from ringpop_tpu.policies import core as pol
from ringpop_tpu.scenarios import compile as scompile
from ringpop_tpu.scenarios import faults as sfaults
from ringpop_tpu.scenarios.spec import ScenarioSpec
from ringpop_tpu.traffic import engine as tengine
from ringpop_tpu.traffic import latency as tlat

N = 10
LEAN = SwimParams(suspicion_ticks=8, ping_req_size=1)
B = 10
# exact-window workload (test_overload.py): host rings and the masked
# walk agree on every key, so the oracle is equality with no residue
PO_WL = {"kind": "zipf", "keys_per_tick": 24, "pool": 256, "zipf_s": 1.2,
         "window": N * ring_ops.DEFAULT_REPLICA_POINTS,
         "latency_buckets": B}

# the overload incident the policies remediate: gray seeds duty
# timeouts (retry pressure for the amp governor), the feedback loop
# grays hot holders, zipf skew concentrates load (shed/quarantine prey)
PO_SPEC = {
    "ticks": 12,
    "events": [
        {"at": 1, "op": "gray", "nodes": [1, 2], "factor": 4, "until": 10},
        {"at": 3, "op": "kill", "node": 9},
        {"at": 1, "op": "overload", "until": 12, "capacity": 1,
         "threshold": 5, "recover": 1, "factor": 4},
    ],
}

SLO_COUNTERS = ("lookups", "dropped", "handled_local", "delivered",
                "proxy_retries", "proxy_failed", "send_errors",
                "retry_succeeded", "gray_timeouts", "lat_count",
                "lat_sum_ms", "lat_max_ms", "policy_shed")

# aggressive operating points so every enabled mechanism demonstrably
# fires at N=10 within 12 ticks (the defaults are tuned for incident
# scale; the oracle wants engagement, not recovery)
ORACLE_KNOBS = {
    "admission": dict(admit_capacity=2, shed_hi=3, shed_lo=1),
    "retry_budget": dict(admit_capacity=2, amp_threshold_x16=20),
    "quarantine": dict(admit_capacity=2, quar_hi=3, quar_lo=1),
    "combined": dict(admit_capacity=2, shed_hi=3, shed_lo=1,
                     quar_hi=4, quar_lo=1, amp_threshold_x16=20),
}


def _oracle_policy(name: str) -> pol.CompiledPolicy:
    return pol.compile_policy(name, n=N, m=PO_WL["keys_per_tick"],
                              **ORACLE_KNOBS[name])


# ---------------------------------------------------------------------------
# fast: pure-host units
# ---------------------------------------------------------------------------


def test_policy_parse_and_catalog():
    assert pol.parse_policy_arg("combined") == ("combined", {})
    name, kv = pol.parse_policy_arg("admission:shed_hi=4, shed_lo=1")
    assert name == "admission" and kv == {"shed_hi": 4, "shed_lo": 1}
    with pytest.raises(ValueError, match="unknown policy"):
        pol.parse_policy_arg("bogus")
    with pytest.raises(ValueError, match="bad policy knob"):
        pol.parse_policy_arg("combined:nope=3")
    with pytest.raises(ValueError, match="bad policy knob"):
        pol.parse_policy_arg("combined:shed_hi")  # no '='
    assert pol.list_policies() == sorted(pol.POLICIES)
    text = pol.format_catalog(16, 128)
    for name in pol.POLICIES:
        assert name in text


def test_policy_compile_defaults_and_round_trip():
    m = PO_WL["keys_per_tick"]
    # a single-mechanism policy keeps the OTHER mechanisms at INF (off)
    cp = pol.compile_policy("admission", n=N, m=m)
    assert cp.knobs.quar_hi == pol.INF and cp.knobs.quar_lo == pol.INF
    assert cp.knobs.amp_threshold_x16 == pol.INF
    assert cp.knobs.shed_hi < pol.INF
    cq = pol.compile_policy("quarantine", n=N, m=m)
    assert cq.knobs.shed_hi == pol.INF and cq.knobs.quar_hi < pol.INF
    cr = pol.compile_policy("retry_budget", n=N, m=m)
    assert cr.knobs.shed_hi == pol.INF and cr.knobs.quar_hi == pol.INF
    assert cr.knobs.amp_threshold_x16 < pol.INF
    cc = pol.compile_policy("combined", n=N, m=m)
    assert cc.knobs.shed_hi < pol.INF
    assert cc.knobs.quar_hi < pol.INF
    assert cc.knobs.amp_threshold_x16 < pol.INF
    # knob override + amp_window (compile-time) override
    co = pol.compile_policy("combined:shed_hi=7", n=N, m=m, amp_window=4)
    assert co.knobs.shed_hi == 7 and co.config.amp_window == 4
    with pytest.raises(ValueError, match="amp_window"):
        pol.compile_policy("combined", n=N, m=m, amp_window=0)
    # cursor round trip is bit-exact (no scale rederivation)
    for cand in (cp, cq, cr, cc, co):
        assert pol.from_dict(pol.to_dict(cand)) == cand
    # an already-compiled policy passes through compile_policy untouched
    assert pol.compile_policy(cc, n=99, m=1) == cc
    assert pol.compile_policy(pol.to_dict(cc), n=99, m=1) == cc


def test_policy_update_hysteresis_and_amp_window():
    cfg = pol.PolicyConfig(amp_window=4)
    knobs = pol.PolicyKnobs(admit_capacity=2, shed_hi=6, shed_lo=2,
                            quar_hi=4, quar_lo=1, amp_threshold_x16=24,
                            retry_floor=0)
    press = np.zeros(3, np.int32)
    shed = np.zeros(3, bool)
    quar = np.zeros(3, bool)
    sw = np.zeros(4, np.int32)
    dw = np.zeros(4, np.int32)

    def tick(t, sends, tick_sends, delivered):
        nonlocal press, shed, quar, sw, dw
        press, shed, quar, sw, dw, cap, amp = pol.policy_update(
            cfg, knobs, press, shed, quar, sw, dw,
            np.asarray(sends, np.int32), np.int32(tick_sends),
            np.int32(delivered), t, 3)
        return int(cap), int(amp)

    # node 0 hammered at 5/tick: leaky bucket fills +3/tick
    cap, amp = tick(0, [5, 2, 0], 7, 7)
    assert list(press) == [3, 0, 0] and not shed.any() and not quar.any()
    assert cap == 3 and amp == 16  # sends == delivered: amp = 1.0 x16
    tick(1, [5, 2, 0], 7, 7)
    assert list(press) == [6, 0, 0]
    assert shed[0] and quar[0]  # both latched at their hi marks
    # drain: shed clears when press stops exceeding shed_lo, quarantine
    # (lower lo) holds longer — hysteresis, not threshold-crossing
    tick(2, [0, 0, 0], 0, 7)
    assert list(press) == [4, 0, 0] and shed[0] and quar[0]
    tick(3, [0, 0, 0], 0, 7)
    assert list(press) == [2, 0, 0] and not shed[0] and quar[0]
    tick(4, [0, 0, 0], 0, 7)
    assert list(press) == [0, 0, 0] and not quar[0]
    # amp governor: a storm tick (80 sends / 10 delivered, landing in
    # a window still holding the quiet ticks above) pushes trailing
    # amp past the threshold -> cap collapses to the floor; four quiet
    # ticks roll the storm out of the ring -> restored
    cap, amp = tick(5, [0, 0, 0], 80, 10)
    assert amp >= 24 and cap == 0
    for t in range(6, 10):
        cap, amp = tick(t, [0, 0, 0], 7, 7)
    assert amp == 16 and cap == 3


def test_policy_requires_traffic_and_clear():
    c = SimCluster(N, LEAN, seed=2)
    # a policy with no workload has nothing to meter: rejected before
    # any PRNG key is drawn
    with pytest.raises(ValueError, match="serve plane"):
        c.run_scenario(PO_SPEC, policy="combined")
    # leftover policy state from a previous run is rejected loudly
    c.net = c.net._replace(
        po_press=jnp.ones(N, jnp.int32),
        po_shed=jnp.zeros(N, bool), po_quar=jnp.zeros(N, bool),
        po_sends_w=jnp.zeros(8, jnp.int32),
        po_deliv_w=jnp.zeros(8, jnp.int32),
        po_retry_cap=jnp.int32(3),
    )
    with pytest.raises(ValueError, match="clear_policy"):
        c.run_scenario(PO_SPEC, traffic=PO_WL, policy="combined")
    c.clear_policy()
    assert c.net.po_press is None and c.net.po_retry_cap is None
    # an amp-window mismatch against checkpointed windows is rejected
    # (zeros pass the leftover check; the SHAPE is still wrong)
    c.net = c.net._replace(
        po_press=jnp.zeros(N, jnp.int32),
        po_shed=jnp.zeros(N, bool), po_quar=jnp.zeros(N, bool),
        po_sends_w=jnp.zeros(4, jnp.int32),
        po_deliv_w=jnp.zeros(4, jnp.int32),
        po_retry_cap=jnp.int32(3),
    )
    with pytest.raises(ValueError, match="amp window"):
        c.run_scenario(PO_SPEC, traffic=PO_WL, policy="combined")


def test_policy_checkpoint_round_trip(tmp_path):
    """Checkpoint v5 carries the six ``po_*`` tensors bit-exactly, and
    a policy-less net keeps them None (the optional-field contract —
    no format bump)."""
    from ringpop_tpu import checkpoint as ckpt

    c = SimCluster(N, LEAN, seed=4)
    fields = dict(
        po_press=np.arange(N, dtype=np.int32) * 3,
        po_shed=(np.arange(N) % 3 == 0),
        po_quar=(np.arange(N) % 4 == 1),
        po_sends_w=np.arange(8, dtype=np.int32) * 7,
        po_deliv_w=np.arange(8, dtype=np.int32) * 5,
        po_retry_cap=np.int32(1),
    )
    c.net = c.net._replace(
        **{k: jnp.asarray(v) for k, v in fields.items()}
    )
    path = str(tmp_path / "po.npz")
    ckpt.save(c, path)
    d = ckpt.load(path)
    for k, v in fields.items():
        np.testing.assert_array_equal(np.asarray(getattr(d.net, k)), v, k)
    c2 = SimCluster(N, LEAN, seed=4)
    path2 = str(tmp_path / "none.npz")
    ckpt.save(c2, path2)
    d2 = ckpt.load(path2)
    for k in fields:
        assert getattr(d2.net, k) is None, k


# ---------------------------------------------------------------------------
# the host walk (test_overload.py's oracle + the three policy hooks)
# ---------------------------------------------------------------------------


def _host_policy_tick_loads(cluster, ct, t, shed, quar, cap):
    """One policy-armed SLO tick on the host.  ``shed``/``quar``/``cap``
    are LAST tick's policy planes (the causality the scan enforces):
    quarantined nodes are excluded from every host ring at
    construction (the ``mask_all &= ~po_quar`` twin), a request whose
    first resolved holder is shedding lands one send there and is
    counted as ``policy_shed`` (neither delivered nor failed), and the
    retry gate compares against ``min(max_retries, cap)``.  Returns
    (counters, hist int64[B], loads int64[N])."""
    st = ct.static
    m = st.m
    idx, viewers = tengine.sample_tick(ct.tensors, jnp.int32(t), m)
    idx, viewers = np.asarray(idx), np.asarray(viewers)
    bo_ms = tlat.backoff_ms_schedule(st.max_retries)
    bo_ticks = tlat.backoff_tick_offsets(st.max_retries, st.period_ms)
    cap_eff = min(int(st.max_retries), int(cap))

    net = cluster.net
    period = (
        np.asarray(net.period) if net.period is not None
        else np.ones(cluster.n, np.int32)
    )

    def duty(h, te):
        per = max(int(period[h]), 1)
        return te % per == (h * (0x9E37 | 1)) % per

    live = set(int(i) for i in cluster.live_indices())
    keys = ct.spec.pool_keys()
    addr_index = cluster.book.index
    rings: dict[int, object] = {}

    def ring_of(node):
        # ring_for + the policy quarantine mask: a quarantined member
        # is steered out of every viewer's ring exactly like a damped
        # one (liveness truth untouched — it still serves arrivals)
        if node not in rings:
            damped_row = (
                np.asarray(cluster.state.damped[node])
                if getattr(cluster.state, "damped", None) is not None
                else None
            )
            servers = [
                mb["address"]
                for mb in cluster.members(node)
                if mb["status"] in ("alive", "suspect")
                and (damped_row is None
                     or not damped_row[addr_index[mb["address"]]])
                and not quar[addr_index[mb["address"]]]
            ]
            ring = HashRing()
            ring.add_remove_servers(servers, [])
            rings[node] = (ring, bool(servers))
        return rings[node]

    def masked_lookup(node, key):
        ring, nonempty = ring_of(node)
        if not nonempty:
            return None
        addr = ring.lookup(key)
        return None if addr is None else addr_index[addr]

    counts = {k: 0 for k in SLO_COUNTERS}
    hist = np.zeros(st.latency_buckets, np.int64)
    loads = np.zeros(cluster.n, np.int64)

    def deliver(lat, retries):
        counts["delivered"] += 1
        counts["lat_count"] += 1
        counts["lat_sum_ms"] += lat
        counts["lat_max_ms"] = max(counts["lat_max_ms"], lat)
        if retries > 0:
            counts["retry_succeeded"] += 1
        hist[int(tlat.bucket_index(np.int64(lat), st.latency_buckets))] += 1

    for k in range(m):
        v = int(viewers[k])
        if v not in live:
            counts["dropped"] += 1
            continue
        counts["lookups"] += 1
        key = keys[int(idx[k])]
        owner0 = masked_lookup(v, key)
        if owner0 is None:
            continue  # unresolved at arrival: no load, never settled
        if shed[owner0]:
            # admission control: rejected AT the pressured holder —
            # the rejection still costs its inbox one landed send
            counts["policy_shed"] += 1
            loads[owner0] += 1
            continue
        if owner0 == v:
            counts["handled_local"] += 1
            loads[v] += 1
            deliver(0, 0)
            continue
        h, retries = owner0, 0
        lat = 0  # no delay rules in the oracle spec: zero link legs
        settled, unres = False, False
        for _ in range(st.max_retries + 1):
            loads[h] += 1  # the attempt lands on h's inbox either way
            te = t + int(bo_ticks[min(retries, st.max_retries)])
            alive_h = h in live
            if not alive_h or not duty(h, te):
                counts["send_errors"] += 1
                if alive_h:
                    counts["gray_timeouts"] += 1
                if retries < cap_eff:
                    lat += int(bo_ms[retries])
                    retries += 1
                    continue
                break
            nxt = masked_lookup(h, key)
            if nxt is None:
                unres = True
                break
            if nxt == h:
                settled = True
                break
            if retries < cap_eff:
                lat += int(bo_ms[retries])
                h = nxt
                retries += 1
                continue
            break
        counts["proxy_retries"] += retries
        if settled:
            deliver(lat, retries)
        elif not unres:
            counts["proxy_failed"] += 1
    return counts, hist, loads


def _host_policy_walk(backend, spec_obj, wl, seed, cp, **kw):
    """The policy twin of ``_host_overload_walk``: per-tick protocol
    step with the effective period row, the policy-armed host serve,
    then BOTH feedback folds (overload + policy) over the same load
    vector — the scan's exact tick body on the host."""
    c = SimCluster(N, LEAN, seed=seed, backend=backend, **kw)
    ct = c.compile_traffic(wl)
    cfg = sfaults.overload_config(spec_obj)
    compiled = scompile.compile_spec(spec_obj, c.n, base_loss=c.params.loss)
    keys = scompile.key_schedule(c._split, compiled)
    switches = sfaults.period_switches(spec_obj, c.n)
    by_tick = defaultdict(list)
    for at, op, arg in scompile.expand_events(spec_obj, c.params.loss):
        by_tick[at].append((op, arg))
    pressure = np.zeros(c.n, np.int32)
    gray = np.zeros(c.n, bool)
    max_retries = int(ct.static.max_retries)
    w = cp.config.amp_window
    po_press = np.zeros(c.n, np.int32)
    po_shed = np.zeros(c.n, bool)
    po_quar = np.zeros(c.n, bool)
    po_sw = np.zeros(w, np.int32)
    po_dw = np.zeros(w, np.int32)
    po_cap = np.int32(max_retries)
    rows = []
    for t in range(spec_obj.ticks):
        ops = sorted(by_tick.get(t, ()), key=lambda x: scompile._OP_RANK[x[0]])
        for op, arg in ops:
            if op == "kill":
                c.kill(arg)
            elif op == "suspend":
                c.suspend(arg)
            elif op == "resume":
                c.resume(arg)
            elif op == "loss":
                c.set_loss(arg)
        row = np.ones(c.n, np.int32)
        for at, r in switches:
            if at <= t:
                row = r
        per_eff = np.where(gray, np.maximum(row, cfg.factor), row)
        c.net = c.net._replace(period=jnp.asarray(per_eff.astype(np.int32)))
        if backend == "delta":
            c.state, _ = sdelta.delta_step(
                c.state, c.net, keys[t], params=c.dparams
            )
        else:
            c.state, _ = sim.swim_step(c.state, c.net, keys[t], params=c.params)
        counts, hist, loads = _host_policy_tick_loads(
            c, ct, t, po_shed, po_quar, po_cap
        )
        in_win = cfg.start <= t < cfg.end
        pressure, gray = sfaults.overload_update(
            cfg, in_win, pressure, gray, loads.astype(np.int32)
        )
        (po_press, po_shed, po_quar, po_sw, po_dw, po_cap,
         amp_x16) = pol.policy_update(
            cp.config, cp.knobs, po_press, po_shed, po_quar, po_sw,
            po_dw, loads.astype(np.int32), np.int32(loads.sum()),
            np.int32(counts["delivered"]), t, max_retries)
        rows.append((counts, hist, int(gray.sum()), int(pressure.max()),
                     int(po_shed.sum()), int(po_quar.sum()),
                     int(po_press.max()), int(po_cap), int(amp_x16)))
    po_final = (po_press, po_shed, po_quar, po_sw, po_dw, po_cap)
    return c, pressure, gray, po_final, rows


def _assert_policy_parity(backend, name, **kw):
    cp = _oracle_policy(name)
    spec_obj = ScenarioSpec.from_dict(PO_SPEC)
    a = SimCluster(N, LEAN, seed=11, backend=backend, **kw)
    ct = a.compile_traffic(PO_WL)
    trace = a.run_scenario(spec_obj, traffic=ct, policy=cp)
    b, pressure, gray, po_final, rows = _host_policy_walk(
        backend, spec_obj, PO_WL, seed=11, cp=cp, **kw
    )
    for t, (counts, hist, gray_n, p_max, shed_n, quar_n, po_max, cap,
            amp) in enumerate(rows):
        for cname, value in counts.items():
            got = int(trace.metrics[cname][t])
            assert got == value, (t, cname, got, value)
        np.testing.assert_array_equal(
            trace.planes["lat_hist_ms"][t], hist, err_msg=f"tick {t}"
        )
        assert int(trace.metrics["ov_gray_nodes"][t]) == gray_n, t
        assert int(trace.metrics["ov_pressure_max"][t]) == p_max, t
        assert int(trace.metrics["policy_shed_nodes"][t]) == shed_n, t
        assert int(trace.metrics["policy_quarantined"][t]) == quar_n, t
        assert int(trace.metrics["policy_pressure_max"][t]) == po_max, t
        assert int(trace.metrics["policy_retry_cap"][t]) == cap, t
        assert int(trace.metrics["policy_amp_x16"][t]) == amp, t
    # both feedback states round-trip onto the final net
    np.testing.assert_array_equal(np.asarray(a.net.ov_cnt), pressure)
    np.testing.assert_array_equal(np.asarray(a.net.ov_gray), gray)
    for field, want in zip(
        ("po_press", "po_shed", "po_quar", "po_sends_w", "po_deliv_w",
         "po_retry_cap"), po_final,
    ):
        np.testing.assert_array_equal(
            np.asarray(getattr(a.net, field)), want, err_msg=field
        )
    # state + net + checksum parity (the trajectory the policy steered
    # is identical)
    for la, lb in zip(
        jax.tree_util.tree_leaves(a.state), jax.tree_util.tree_leaves(b.state)
    ):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    np.testing.assert_array_equal(np.asarray(a.net.up), np.asarray(b.net.up))
    np.testing.assert_array_equal(
        np.asarray(a.net.responsive), np.asarray(b.net.responsive)
    )
    assert a.checksums() == b.checksums()
    # every ENABLED mechanism demonstrably fired, every DISABLED one
    # stayed silent (INF thresholds are really off — the single-program
    # guarantee has observable teeth)
    mechs = pol.POLICIES[name][1]
    max_retries = int(ct.static.max_retries)
    shed_total = int(trace.metrics["policy_shed"].sum())
    quar_peak = int(trace.metrics["policy_quarantined"].max())
    cap_min = int(trace.metrics["policy_retry_cap"].min())
    if "admission" in mechs:
        assert shed_total > 0
    else:
        assert shed_total == 0
    if "quarantine" in mechs:
        assert quar_peak > 0
    else:
        assert quar_peak == 0
    if "retry_budget" in mechs:
        assert cap_min < max_retries
    else:
        assert cap_min == max_retries


@pytest.mark.parametrize("name", sorted(pol.POLICIES))
def test_policy_parity_dense(name):
    """Tier-1 acceptance oracle, one parametrization per policy:
    compiled scan == per-tick host walk, bit for bit — counters
    (``policy_shed`` included), histogram, overload AND policy
    telemetry, final state/net/checksums.  All four share ONE compiled
    program (knobs are traced); only the knob values differ."""
    _assert_policy_parity("dense", name)


@pytest.mark.slow
def test_policy_parity_delta():
    """The delta twin of the acceptance oracle (own XLA compile of the
    policy-armed scenario program, so it rides the nightly lane)."""
    _assert_policy_parity(
        "delta", "combined", capacity=N, wire_cap=N, claim_grid=3 * N * N
    )


# ---------------------------------------------------------------------------
# slow: execution-strategy + sweep-axis contracts
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_policy_streamed_and_resume_bit_identical(tmp_path):
    """Streaming a policy-armed run is an execution strategy (same
    trace, same final policy state), and a SIGKILL mid-run resumes
    from the checkpoint v5 ``po_*`` tensors + the cursor's exact
    compiled knobs to a bit-identical end state."""
    from ringpop_tpu.scenarios import stream as sstream

    spec = {
        "ticks": 24,
        "events": [
            {"at": 2, "op": "overload", "until": 24, "capacity": 1,
             "threshold": 5, "recover": 1, "factor": 4},
        ],
    }
    cp = _oracle_policy("combined")
    a = SimCluster(N, LEAN, seed=7)
    ta = a.run_scenario(spec, traffic=PO_WL, policy=cp)
    assert int(ta.metrics["policy_shed"].sum()) > 0
    b = SimCluster(N, LEAN, seed=7)
    tb = b.run_scenario(spec, traffic=PO_WL, policy=cp, segment_ticks=7)
    for k in ta.metrics:
        np.testing.assert_array_equal(ta.metrics[k], tb.metrics[k], err_msg=k)
    np.testing.assert_array_equal(
        np.asarray(a.net.po_press), np.asarray(b.net.po_press)
    )
    np.testing.assert_array_equal(
        np.asarray(a.net.po_sends_w), np.asarray(b.net.po_sends_w)
    )

    # killed-after-first-checkpoint + resume == uninterrupted
    ckpt_path = str(tmp_path / "po.npz")
    cv = SimCluster(N, LEAN, seed=7)
    with pytest.raises(sstream.StreamInterrupted):
        sstream.run_streamed(
            cv, spec, segment_ticks=7, traffic=PO_WL, policy=cp,
            checkpoint_path=ckpt_path, interrupt_after=1,
        )
    # the checkpoint carries the mid-run policy tensors
    from ringpop_tpu import checkpoint as ckpt

    mid = ckpt.load(ckpt_path)
    assert mid.net.po_press is not None
    assert mid.net.po_sends_w.shape == (cp.config.amp_window,)
    cr, tr = sstream.resume(ckpt_path)
    for k in ta.metrics:
        np.testing.assert_array_equal(ta.metrics[k], tr.metrics[k], err_msg=k)
    for field in ("po_press", "po_shed", "po_quar", "po_sends_w",
                  "po_deliv_w", "po_retry_cap"):
        np.testing.assert_array_equal(
            np.asarray(getattr(a.net, field)),
            np.asarray(getattr(cr.net, field)), err_msg=field,
        )
    assert a.checksums() == cr.checksums()


@pytest.mark.slow
def test_policy_sweep_axes_parity():
    """Policy knobs as traced batch axes: replica r of a
    ``policy_axes`` sweep is bit-identical to a standalone
    ``run_scenario`` armed with ``sweep.replica_policy``'s effective
    knobs — and an INF axis value really turns the mechanism off in
    that replica only (one compiled program for the whole grid)."""
    from ringpop_tpu.scenarios import sweep as ssweep

    spec = {
        "ticks": 16,
        "events": [
            {"at": 1, "op": "overload", "until": 16, "capacity": 1,
             "threshold": 5, "recover": 1, "factor": 4},
        ],
    }
    cp = _oracle_policy("admission")
    axes = {"shed_hi": [ORACLE_KNOBS["admission"]["shed_hi"], pol.INF]}
    c = SimCluster(N, LEAN, seed=9)
    ct = c.compile_traffic(PO_WL)
    strace = c.run_sweep(spec, 2, traffic=ct, policy=cp, policy_axes=axes)
    rep0, rep1 = strace.replica(0), strace.replica(1)
    # replica 0 sheds; replica 1's INF threshold never latches
    assert int(rep0.metrics["policy_shed"].sum()) > 0
    assert int(rep1.metrics["policy_shed"].sum()) == 0
    # replica 1 standalone from its replica key + its effective knobs
    d = SimCluster(N, LEAN, seed=9)
    d.key = jnp.asarray(strace.replica_keys[1])
    td = d.run_scenario(
        spec, traffic=ct, policy=ssweep.replica_policy(cp, axes, 1)
    )
    for k in td.metrics:
        np.testing.assert_array_equal(rep1.metrics[k], td.metrics[k],
                                      err_msg=k)
    np.testing.assert_array_equal(
        rep1.planes["lat_hist_ms"], td.planes["lat_hist_ms"]
    )
    for field in ("po_press", "po_shed", "po_quar", "po_sends_w",
                  "po_deliv_w", "po_retry_cap"):
        np.testing.assert_array_equal(
            np.asarray(strace.final_nets[field][1]
                       if isinstance(strace.final_nets, dict)
                       else getattr(strace.final_nets, field)[1]),
            np.asarray(getattr(d.net, field)), err_msg=field,
        )

"""Changeset/join-response merge tests (reference:
test/membership-changeset-merge-test.js, test/join-response-merge-test.js)
plus join group selection (test/join-sender-test.js)."""

import random

from ringpop_tpu.changeset_merge import merge_membership_changesets
from ringpop_tpu.harness import test_ringpop
from ringpop_tpu.swim.join_response_merge import merge_join_responses
from ringpop_tpu.swim.join_sender import JoinCluster


def ch(addr, inc, status="alive"):
    return {"address": addr, "status": status, "incarnationNumber": inc}


def test_changeset_merge_max_incarnation_wins():
    merged = merge_membership_changesets(
        "me:1",
        [[ch("a:1", 5), ch("b:2", 3)], [ch("a:1", 9)], [ch("a:1", 7), ch("c:3", 1)]],
    )
    by_addr = {c["address"]: c for c in merged}
    assert by_addr["a:1"]["incarnationNumber"] == 9
    assert by_addr["b:2"]["incarnationNumber"] == 3
    assert set(by_addr) == {"a:1", "b:2", "c:3"}


def test_changeset_merge_excludes_self():
    merged = merge_membership_changesets("me:1", [[ch("me:1", 5), ch("a:1", 1)]])
    assert [c["address"] for c in merged] == ["a:1"]


def test_join_response_merge_same_checksum_takes_first():
    members = [ch("a:1", 1), ch("b:2", 2)]
    responses = [
        {"checksum": 42, "members": members},
        {"checksum": 42, "members": [ch("a:1", 99)]},
    ]
    assert merge_join_responses("me:1", responses) is members


def test_join_response_merge_mixed_checksums():
    responses = [
        {"checksum": 42, "members": [ch("a:1", 1)]},
        {"checksum": 43, "members": [ch("a:1", 9), ch("b:2", 2)]},
    ]
    merged = merge_join_responses("me:1", responses)
    by_addr = {c["address"]: c for c in merged}
    assert by_addr["a:1"]["incarnationNumber"] == 9
    assert merge_join_responses("me:1", []) == []


def _joiner(bootstrap, host_port="10.0.0.1:3000", **opts):
    rp = test_ringpop(host_port=host_port)
    rp.bootstrap_hosts = bootstrap
    rp.rng = random.Random(7)
    return JoinCluster(rp, **opts)


def test_group_selection_prefers_other_hosts():
    """join-sender.js:165-183,478-484: nodes on other physical hosts first."""
    bootstrap = ["10.0.0.1:3000", "10.0.0.1:3001", "10.0.0.2:3000", "10.0.0.3:3000"]
    joiner = _joiner(bootstrap)
    joiner.init([])
    assert set(joiner.preferred_nodes) == {"10.0.0.2:3000", "10.0.0.3:3000"}
    assert set(joiner.non_preferred_nodes) == {"10.0.0.1:3001"}
    # join_size=3, parallelism 2 -> asks for 6, only 3 available
    group = joiner.select_group([])
    assert len(group) == 3
    assert set(group[:2]) == set(joiner.preferred_nodes)


def test_group_excludes_self_and_joined():
    bootstrap = ["10.0.0.1:3000", "10.0.0.2:3000", "10.0.0.3:3000"]
    joiner = _joiner(bootstrap)
    assert "10.0.0.1:3000" not in joiner.potential_nodes
    assert set(joiner.collect_potential_nodes(["10.0.0.2:3000"])) == {"10.0.0.3:3000"}


def test_join_size_capped_by_cluster_size():
    joiner = _joiner(["10.0.0.1:3000", "10.0.0.2:3000"], join_size=10)
    assert joiner.join_size == 1

"""Failure-model subsystem: asymmetric links, latency/jitter, flap
storms, gray failures, rolling deploys (scenarios/faults.py).

Fast lane: the spec/compiler host logic (validation, JSON round trips,
flap/rolling expansion, link-rule / period-row / delay-depth lowering)
plus ONE compiled run of a spec combining every family (a single scan
compile covers the in-scan smoke for all five ops) and the streamed+
sharded sweep composition test (PR 8 follow-up).  The per-family
compiled-scan vs host-loop bit-parity oracles — the acceptance
criterion — compile many programs on CPU and ride the slow lane, like
the PR 2 parity grids.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax

from ringpop_tpu.models import swim_sim as sim
from ringpop_tpu.models.cluster import SimCluster
from ringpop_tpu.scenarios import compile as scompile
from ringpop_tpu.scenarios import faults as sfaults
from ringpop_tpu.scenarios import runner
from ringpop_tpu.scenarios import sweep as ssweep
from ringpop_tpu.scenarios.spec import (
    Event,
    ScenarioSpec,
    expand_fault_primitives,
)

FAST = sim.SwimParams(suspicion_ticks=8)
# The two FAST-lane compiled tests use a 1-witness relay: the ping-req
# exchange unrolls 4 stages x k slots, so k=1 compiles a ~3x smaller
# program (the tier-1 suite runs against a fixed wall-clock watchdog);
# the slow parity oracles keep the default k=3.
LEAN = sim.SwimParams(suspicion_ticks=8, ping_req_size=1)
N = 10

# One spec exercising every failure-model family (plus a partition, so
# composition with the first-generation events is covered): the fast
# smoke compiles it ONCE; the slow oracle replays it against the host
# loop bit for bit.
MIXED = ScenarioSpec.from_dict(
    {
        "ticks": 30,
        "events": [
            {"at": 2, "op": "link_loss", "src": [0, 1], "dst": [4, 5],
             "p": 0.9, "until": 20},
            {"at": 3, "op": "gray", "node": 2, "factor": 4, "until": 25},
            {"at": 4, "op": "flap", "node": 7, "until": 16, "down": 2, "up": 3},
            {"at": 5, "op": "rolling_restart", "nodes": [8, 9], "down": 2,
             "every": 4},
            {"at": 6, "op": "delay", "src": [3], "dst": [6], "delay": 2,
             "jitter": 1, "until": 22},
            {"at": 10, "op": "partition",
             "groups": [[0, 1, 2, 3, 4], [5, 6, 7, 8, 9]]},
            {"at": 18, "op": "heal"},
        ],
    }
)


def _states_equal(a, b) -> bool:
    return all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(a, b)
        if x is not None
    )


def _nets_equal(a, b) -> bool:
    """Field-wise NetState equality, adj excluded (scenario runs
    normalize adj to the group-id form; the host loop keeps None for a
    never-partitioned net — the pre-existing convention)."""
    for f, x, y in zip(a._fields, a, b):
        if f == "adj":
            continue
        if (x is None) != (y is None):
            return False
        if x is not None and not np.array_equal(np.asarray(x), np.asarray(y)):
            return False
    return True


# -- fast: spec round trips + validation ------------------------------------


def test_new_ops_json_roundtrip(tmp_path):
    path = str(tmp_path / "spec.json")
    MIXED.save(path)
    assert ScenarioSpec.load(path) == MIXED
    # and through the Event dict form each way
    for e in MIXED.events:
        assert Event.from_dict(e.to_dict()) == e


def test_fault_op_validation_errors():
    def bad(events, match, ticks=20, n=8):
        with pytest.raises(ValueError, match=match):
            ScenarioSpec.from_dict({"ticks": ticks, "events": events}).validate(n)

    bad([{"at": 1, "op": "link_loss", "src": [0], "dst": [1], "p": 1.0}],
        "p in \\[0, 1\\)")
    bad([{"at": 1, "op": "link_loss", "src": [], "dst": [1], "p": 0.5}],
        "src nodes")
    bad([{"at": 1, "op": "link_loss", "src": [0], "dst": [9], "p": 0.5}],
        "dst nodes")
    bad([{"at": 5, "op": "link_loss", "src": [0], "dst": [1], "p": 0.5,
          "until": 5}], "at < until")
    bad([{"at": 1, "op": "delay", "src": [0], "dst": [1]}],
        "delay \\+ jitter >= 1")
    bad([{"at": 1, "op": "flap", "node": 2, "until": 10, "down": 0, "up": 3}],
        "down >= 1")
    bad([{"at": 1, "op": "flap", "node": 2, "until": 19, "down": 3, "up": 2}],
        "last revive")
    bad([{"at": 1, "op": "gray", "node": 2, "factor": 0}], "factor >= 1")
    bad([{"at": 1, "op": "gray", "node": 2, "factor": 3, "until": 10},
         {"at": 5, "op": "gray", "node": 2, "factor": 5}],
        "gray windows overlap")
    bad([{"at": 1, "op": "rolling_restart", "nodes": [0, 1], "down": 9,
          "every": 10}], "outside")
    # expansion collisions join the (tick, node) conflict check
    bad([{"at": 1, "op": "flap", "node": 2, "until": 10, "down": 2, "up": 3},
         {"at": 3, "op": "kill", "node": 2}], "conflicting node events")
    bad([{"at": 1, "op": "flap", "nodes": [2, 3], "until": 10, "down": 2,
          "up": 3},
         {"at": 1, "op": "flap", "nodes": [3], "until": 10, "down": 2,
          "up": 3}], "conflicting node events")


def test_sametick_revive_and_kill_now_canonical():
    """Same-tick revive + kill on DIFFERENT nodes is legal now: both
    sides apply bit edits before revives (the canonical order), so the
    outcome is defined.  Same (tick, node) stays rejected."""
    ScenarioSpec(
        ticks=5,
        events=(
            Event(at=1, op="revive", node=2),
            Event(at=1, op="kill", node=0),
        ),
    ).validate(4)
    with pytest.raises(ValueError, match="conflicting node events"):
        ScenarioSpec(
            ticks=5,
            events=(
                Event(at=1, op="kill", node=2),
                Event(at=1, op="revive", node=2),
            ),
        ).validate(4)


def test_flap_expansion():
    e = Event.from_dict(
        {"at": 2, "op": "flap", "nodes": [5, 6], "until": 12, "down": 2,
         "up": 3, "stagger": 1}
    )
    prim = expand_fault_primitives(e, 20)
    # node 5 cycles at 2 (kill) / 4 (revive) / 7 / 9; node 6 shifts by 1
    assert [(p.at, p.op, p.node) for p in prim] == [
        (2, "kill", 5), (4, "revive", 5),
        (7, "kill", 5), (9, "revive", 5),
        (3, "kill", 6), (5, "revive", 6),
        (8, "kill", 6), (10, "revive", 6),
    ]
    # every kill has its matching revive: the storm always heals itself
    kills = sum(1 for p in prim if p.op == "kill")
    revives = sum(1 for p in prim if p.op == "revive")
    assert kills == revives


def test_rolling_restart_expansion():
    e = Event.from_dict(
        {"at": 3, "op": "rolling_restart", "nodes": [1, 4, 7], "down": 2,
         "every": 3}
    )
    prim = expand_fault_primitives(e, 20)
    assert [(p.at, p.op, p.node) for p in prim] == [
        (3, "kill", 1), (5, "revive", 1),
        (6, "kill", 4), (8, "revive", 4),
        (9, "kill", 7), (11, "revive", 7),
    ]


# -- fast: the faults compiler (host-side) ----------------------------------


def test_link_rules_and_delay_depth():
    rules = sfaults.link_rules(MIXED)
    assert len(rules) == 2
    assert rules[0] == sfaults.LinkRule(
        start=2, end=20, src=(0, 1), dst=(4, 5), p=0.9, delay=0, jitter=0
    )
    assert rules[1].delay == 2 and rules[1].jitter == 1 and rules[1].p == 0.0
    assert sfaults.delay_depth(MIXED) == 4  # max(d) + max(j) + 1
    assert sfaults.delay_depth(ScenarioSpec(ticks=5)) == 0
    # overlapping rules combine as max(d) + max(j) (the step takes the
    # maxima separately), so the depth must cover their SUM even when
    # no single rule reaches it — a per-rule max(d + j) would wrap the
    # ring buffer and deliver early
    split = ScenarioSpec.from_dict(
        {
            "ticks": 20,
            "events": [
                {"at": 1, "op": "delay", "src": [0], "dst": [1], "delay": 3},
                {"at": 2, "op": "delay", "src": [0], "dst": [1], "delay": 0,
                 "jitter": 2},
            ],
        }
    )
    assert sfaults.delay_depth(split) == 3 + 2 + 1


def test_period_switches_fold():
    spec = ScenarioSpec.from_dict(
        {
            "ticks": 30,
            "events": [
                {"at": 2, "op": "gray", "node": 1, "factor": 4, "until": 10},
                {"at": 5, "op": "gray", "nodes": [3, 4], "factor": 2,
                 "until": 12},
            ],
        }
    )
    switches = dict(
        (t, row.tolist()) for t, row in sfaults.period_switches(spec, 6)
    )
    assert set(switches) == {2, 5, 10, 12}
    assert switches[2] == [1, 4, 1, 1, 1, 1]
    assert switches[5] == [1, 4, 1, 2, 2, 1]
    assert switches[10] == [1, 1, 1, 2, 2, 1]
    assert switches[12] == [1, 1, 1, 1, 1, 1]
    # adjacent windows sharing a tick (one ends where the next starts):
    # the new factor wins at the shared tick, regardless of the order
    # the spec LISTS the events (same-tick restores apply before sets)
    adjacent = ScenarioSpec.from_dict(
        {
            "ticks": 40,
            "events": [
                {"at": 20, "op": "gray", "node": 0, "factor": 6, "until": 30},
                {"at": 10, "op": "gray", "node": 0, "factor": 4, "until": 20},
            ],
        }
    )
    sw = dict((t, row.tolist()) for t, row in sfaults.period_switches(adjacent, 2))
    assert sw[10] == [4, 1]
    assert sw[20] == [6, 1]  # the restore of [10, 20) must not clobber
    assert sw[30] == [1, 1]


def test_compile_faults_tensors_and_boundaries():
    compiled = scompile.compile_spec(MIXED, N)
    ft = compiled.faults
    assert ft is not None
    assert ft.lr_src.shape == (2, N) and ft.lr_p.shape == (2,)
    assert compiled.has_delay and compiled.delay_depth == 4
    assert compiled.has_gray and ft.pe_tick.shape == (2,)
    # link-window edges and gray switches are key-schedule boundaries
    for t in (2, 20, 3, 25, 6, 22):
        assert t in compiled.boundaries, t
    # flap/rolling expansion landed in the node-event tensors
    kinds = np.asarray(compiled.ev_kind)
    assert (kinds == scompile.EV_KILL).sum() >= 5
    assert (kinds == scompile.EV_REVIVE).sum() >= 5
    assert compiled.has_revive
    # a failure-model-free spec compiles with no fault tensors at all
    legacy = scompile.compile_spec(
        ScenarioSpec.from_dict(
            {"ticks": 5, "events": [{"at": 1, "op": "kill", "node": 0}]}
        ),
        N,
    )
    assert legacy.faults is None and not legacy.has_delay


def test_rules_arrays_activity_masking():
    rules = sfaults.link_rules(MIXED)
    src, dst, p, d, j = sfaults.rules_arrays(rules, N, at=21)
    # at tick 21 the loss rule's window [2, 20) has closed, the delay
    # rule's [6, 22) is still open
    assert p[0] == 0.0 and d[1] == 2 and j[1] == 1
    src2, dst2, p2, _, _ = sfaults.rules_arrays(rules, N, at=10)
    assert p2[0] == np.float32(0.9)
    np.testing.assert_array_equal(src, src2)  # masks never change


def test_replica_spec_flap_jitter():
    spec = ScenarioSpec.from_dict(
        {
            "ticks": 30,
            "events": [
                {"at": 4, "op": "flap", "node": 2, "until": 16, "down": 2,
                 "up": 3},
            ],
        }
    )
    shifted = ssweep.replica_spec(spec, flap_jitter=3)
    (e,) = shifted.events
    assert e.at == 7 and e.until == 19
    # the window length is preserved, so the expansion count matches
    assert len(expand_fault_primitives(e, 30)) == len(
        expand_fault_primitives(spec.events[0], 30)
    )
    with pytest.raises(ValueError, match="flap jitter"):
        ssweep.replica_spec(spec, flap_jitter=20)


def test_cluster_fault_surface_guards():
    c = SimCluster(4, FAST, seed=0)
    with pytest.raises(ValueError, match="enable_delay"):
        c.set_link_rules(
            np.ones((1, 4), bool), np.ones((1, 4), bool), [0.0], d=[2], j=[0]
        )
    with pytest.raises(ValueError, match="depth must be >= 2"):
        c.enable_delay(1)
    # the delta backend now carries per-link delay via the in-flight
    # claim lanes (swim_delta.install_pending); enable_delay installs
    # them, and a mismatched standing depth is rejected BEFORE any key
    # draw (precheck contract)
    d = SimCluster(4, FAST, seed=0, backend="delta", capacity=4)
    d.enable_delay(4)
    assert d.state.pend_subj.shape[0] == 4
    assert d.state.pend_subj.shape[1] == 2 * 3  # 2 * (depth - 1) lanes
    with pytest.raises(ValueError, match="already installed"):
        d.enable_delay(5)
    spec = ScenarioSpec.from_dict(
        {"ticks": 6, "events": [{"at": 1, "op": "delay", "src": [0],
                                 "dst": [1], "delay": 2}]}
    )  # delay_depth 3 != the standing 4-deep lanes
    key_before = np.asarray(d.key).copy()
    with pytest.raises(ValueError, match="depth 4"):
        d.run_scenario(spec)
    np.testing.assert_array_equal(np.asarray(d.key), key_before)


def test_standing_config_rejected_on_compiled_runs():
    """A compiled scenario applies only spec-declared fault config: an
    operator-installed ACTIVE link rule (or a non-lockstep set_period
    row colliding with gray events) would be silently ignored in-scan
    while the host-loop oracle kept applying it — rejected before any
    key draw instead.  Zeroed standing rules (a finished scenario's
    mirror) stay legal."""
    c = SimCluster(6, FAST, seed=0)
    src = np.zeros((1, 6), bool)
    src[0, 0] = True
    c.set_link_rules(src, src, [0.5])
    key_before = np.asarray(c.key).copy()
    plain = {"ticks": 4, "events": [{"at": 1, "op": "kill", "node": 5}]}
    with pytest.raises(ValueError, match="standing link rules"):
        c.run_scenario(plain)
    np.testing.assert_array_equal(np.asarray(c.key), key_before)
    c.set_link_rules(src, src, [0.0])  # a zeroed mirror is inert: legal
    runner.precheck(
        c.state, c.net, scompile.compile_spec(ScenarioSpec.from_dict(plain), 6)
    )
    c.clear_link_rules()
    c.set_period(np.array([1, 1, 4, 1, 1, 1], np.int32))
    gray = {
        "ticks": 6,
        "events": [{"at": 1, "op": "gray", "node": 0, "factor": 3}],
    }
    with pytest.raises(ValueError, match="clobber the standing"):
        c.run_scenario(gray)
    # a standing row composes fine with gray-free scenarios (threaded
    # through the carry) and an all-ones row with gray ones
    runner.precheck(
        c.state, c.net, scompile.compile_spec(ScenarioSpec.from_dict(plain), 6)
    )
    c.set_period(np.ones(6, np.int32))
    runner.precheck(
        c.state, c.net, scompile.compile_spec(ScenarioSpec.from_dict(gray), 6)
    )


# -- fast: ONE compiled smoke covering every family -------------------------


def test_mixed_families_single_dispatch_smoke():
    """All five families in one compiled program: one dispatch, events
    visibly land (flap/rolling dips, delayed claims counted), and the
    post-run net mirrors the end-of-scenario configuration."""
    before = runner.dispatch_count()
    c = SimCluster(N, LEAN, seed=3)
    trace = c.run_scenario(MIXED)
    assert runner.dispatch_count() - before == 1
    live = trace.live.tolist()
    assert live[4] == N - 1  # the flap's first kill
    assert min(live[5:12]) <= N - 2  # flap + rolling overlap
    assert live[-1] == N  # every storm healed itself
    assert int(trace.metrics["delayed_claims"].sum()) > 0
    assert "matured_applied" in trace.metrics
    assert trace.converged[-1]
    # end-of-run config mirrored into the cluster net: every window
    # closed before the final tick, so the rules are present but zeroed
    assert c.net.link_src is not None
    assert float(np.asarray(c.net.link_p).max()) == 0.0
    assert np.asarray(c.net.period).tolist() == [1] * N
    # the in-flight buffer stays installed (network-resident residue)
    assert c.state.pending is not None
    assert c.state.pending.shape == (4, N, N)


@pytest.mark.slow
def test_sweep_streamed_sharded_matches_unstreamed():
    """PR 8 follow-up: run_sweep(segment_ticks=S, shard=True) — the
    sharded replica axis persists across segment dispatches and the
    telemetry is bit-identical to the unstreamed sharded sweep.

    Slow lane by wall-clock budget, not by nature: the 2-core CI host
    swings the tier-1 suite by ~25% against its 870 s watchdog, and
    this test compiles two vmapped 8-replica programs (~19 s).  The
    compiled failure-model representative in tier-1 is the
    mixed-family smoke above; the sharded-stream machinery itself is
    exercised fast by test_stream/test_sweep on their single axes."""
    if jax.local_device_count() < 2:
        pytest.skip("needs the multi-device CPU mesh")
    r = jax.local_device_count()
    spec = {"ticks": 6, "events": [{"at": 1, "op": "kill", "node": 5}]}
    a = SimCluster(6, LEAN, seed=11)
    plain = a.run_sweep(spec, r, shard=True)
    b = SimCluster(6, LEAN, seed=11)
    # S=3 over T=6: both segments share the [R, 3] shape, so the
    # streamed arm costs ONE extra compile next to the whole-run arm
    streamed = b.run_sweep(spec, r, shard=True, segment_ticks=3)
    np.testing.assert_array_equal(plain.converged, streamed.converged)
    np.testing.assert_array_equal(plain.live, streamed.live)
    for k in plain.metrics:
        np.testing.assert_array_equal(plain.metrics[k], streamed.metrics[k])
    assert _states_equal(
        jax.tree_util.tree_map(np.asarray, plain.final_states),
        jax.tree_util.tree_map(np.asarray, streamed.final_states),
    )


# -- slow: compiled-scan vs host-loop bit-parity oracles --------------------
# (the acceptance criterion: one oracle per family + the composition)


def _parity(spec_dict, n=N, backend="dense", seed=7, **kw):
    spec = ScenarioSpec.from_dict(spec_dict)
    a = SimCluster(n, FAST, seed=seed, backend=backend, **kw)
    trace = a.run_scenario(spec)
    b = SimCluster(n, FAST, seed=seed, backend=backend, **kw)
    runner.run_host_loop(b, spec)
    assert _states_equal(a.state, b.state)
    assert _nets_equal(a.net, b.net)
    assert a.checksums() == b.checksums()
    return trace


@pytest.mark.slow
def test_link_loss_parity_and_asymmetry():
    """Directed loss: compiled == host loop bit for bit, and the
    asymmetry is real — a one-way blackhole from most of the cluster
    toward one node still converges (the victim's own pings get out)."""
    trace = _parity(
        {
            "ticks": 25,
            "events": [
                {"at": 2, "op": "link_loss", "src": [0, 1, 2],
                 "dst": [5, 6, 7], "p": 0.8, "until": 18},
                {"at": 4, "op": "link_loss", "src": [5], "dst": [0], "p": 0.5},
            ],
        }
    )
    assert trace.converged[-1]


@pytest.mark.slow
def test_gray_failure_parity_and_slow_probing():
    """Per-node periods: parity, plus the behavioral signature — a
    gray cluster (every node slowed) sends fewer pings per tick."""
    trace = _parity(
        {
            "ticks": 25,
            "events": [
                {"at": 2, "op": "gray", "node": 3, "factor": 5, "until": 20},
                {"at": 5, "op": "gray", "nodes": [6, 7], "factor": 3},
            ],
        }
    )
    # while 3 nodes are gray, fewer probes are initiated than nodes
    window = trace.metrics["pings_sent"][6:19]
    assert window.min() < N


@pytest.mark.slow
def test_flap_storm_parity():
    _parity(
        {
            "ticks": 24,
            "events": [
                {"at": 2, "op": "flap", "nodes": [8, 9], "until": 15,
                 "down": 2, "up": 3, "stagger": 1},
            ],
        }
    )


@pytest.mark.slow
def test_rolling_restart_parity():
    trace = _parity(
        {
            "ticks": 24,
            "events": [
                {"at": 2, "op": "rolling_restart", "nodes": [5, 6, 7],
                 "down": 2, "every": 3},
            ],
        }
    )
    assert trace.live[-1] == N  # the wave revived everyone


@pytest.mark.slow
def test_delay_jitter_parity():
    trace = _parity(
        {
            "ticks": 25,
            "events": [
                {"at": 2, "op": "delay", "src": [0, 1, 2, 3],
                 "dst": [4, 5, 6, 7], "delay": 2, "jitter": 2, "until": 20},
                {"at": 3, "op": "loss", "p": 0.05},
            ],
        }
    )
    assert int(trace.metrics["delayed_claims"].sum()) > 0


@pytest.mark.slow
def test_mixed_families_parity():
    _parity(MIXED.to_dict())


@pytest.mark.slow
def test_delta_link_and_gray_parity():
    """The delta backend supports the loss-only link rules and gray
    periods in-scan: scan == host loop, and dense == delta on the
    shared telemetry (ample caps => bit parity)."""
    spec_dict = {
        "ticks": 25,
        "events": [
            {"at": 2, "op": "link_loss", "src": [0, 1, 2], "dst": [5, 6, 7],
             "p": 0.8, "until": 18},
            {"at": 3, "op": "gray", "node": 3, "factor": 5, "until": 20},
            {"at": 5, "op": "kill", "node": 9},
        ],
    }
    kw = dict(capacity=N, wire_cap=N, claim_grid=3 * N * N)
    td = _parity(spec_dict, backend="delta", **kw)
    a = SimCluster(N, FAST, seed=7, backend="delta", **kw)
    a.run_scenario(ScenarioSpec.from_dict(spec_dict))
    c = SimCluster(N, FAST, seed=7)
    tc = c.run_scenario(ScenarioSpec.from_dict(spec_dict))
    np.testing.assert_array_equal(td.converged, tc.converged)
    np.testing.assert_array_equal(td.live, tc.live)
    assert a.checksums() == c.checksums()


@pytest.mark.slow
def test_period_row_subsumes_phase_mod_both_backends():
    """The gray model's per-node period tensor reproduces the static
    phase_mod stagger value for value: a row of P == phase_mod=P, on
    the dense AND the (newly ported, VERDICT item 4) delta backend."""
    P = 4
    p4 = sim.SwimParams(suspicion_ticks=32, phase_mod=P)
    base = sim.SwimParams(suspicion_ticks=32)
    for backend, kw in (
        ("dense", {}),
        ("delta", dict(capacity=N, wire_cap=N, claim_grid=3 * N * N)),
    ):
        a = SimCluster(N, p4, seed=5, backend=backend, **kw)
        a.tick(20)
        b = SimCluster(N, base, seed=5, backend=backend, **kw)
        b.set_period(np.full(N, P, np.int32))
        b.tick(20)
        assert _states_equal(a.state, b.state), backend
        assert a.checksums() == b.checksums(), backend


@pytest.mark.slow
def test_sweep_flap_jitter_per_replica_parity():
    """flap_jitter batches storm phases: replica r of the sweep is
    bit-identical to a standalone run_scenario of its shifted spec."""
    spec = ScenarioSpec.from_dict(
        {
            "ticks": 20,
            "events": [
                {"at": 3, "op": "flap", "node": 5, "until": 12, "down": 2,
                 "up": 2},
            ],
        }
    )
    c = SimCluster(8, FAST, seed=9)
    strace = c.run_sweep(spec, 2, flap_jitter=[0, 3])
    for r in range(2):
        solo = SimCluster(8, FAST, seed=9)
        solo.key = jax.numpy.asarray(strace.replica_keys[r])
        t = solo.run_scenario(
            ssweep.replica_spec(spec, flap_jitter=strace.flap_jitter[r])
        )
        np.testing.assert_array_equal(t.live, strace.live[r], err_msg=f"r={r}")
        np.testing.assert_array_equal(
            t.converged, strace.converged[r], err_msg=f"r={r}"
        )


@pytest.mark.slow
def test_streamed_mixed_scenario_bit_identical():
    """The failure-model tensors stream: a segmented mixed-family run
    (tick0-offset windows, carried period row, persistent in-flight
    buffer) equals the one-dispatch run bit for bit."""
    a = SimCluster(N, FAST, seed=3)
    whole = a.run_scenario(MIXED)
    b = SimCluster(N, FAST, seed=3)
    streamed = b.run_scenario(MIXED, segment_ticks=7)
    np.testing.assert_array_equal(whole.converged, streamed.converged)
    np.testing.assert_array_equal(whole.live, streamed.live)
    for k in whole.metrics:
        np.testing.assert_array_equal(whole.metrics[k], streamed.metrics[k])
    assert _states_equal(a.state, b.state)
    assert _nets_equal(a.net, b.net)


@pytest.mark.slow
def test_relay_full_sync_fires_and_heals():
    """VERDICT item 5 (the relay full-sync omission), closed behind
    SwimParams.relay_full_sync: with the flag on, a divergence-heavy
    run answers relay acks with full rows (metric > 0) and still
    converges; with it off the metric stays 0 (the historical
    convention, pinned)."""
    spec = {
        "ticks": 60,
        "events": [
            {"at": 2, "op": "kill", "node": 11},
            {"at": 4, "op": "loss", "p": 0.3},
            {"at": 8, "op": "link_loss", "src": [0, 1, 2, 3],
             "dst": [8, 9, 10], "p": 0.95, "until": 40},
            {"at": 40, "op": "loss", "p": 0.0},
        ],
    }
    on = SimCluster(
        12, sim.SwimParams(suspicion_ticks=8, relay_full_sync=True), seed=2
    )
    t_on = on.run_scenario(spec)
    assert int(t_on.metrics["relay_full_syncs"].sum()) > 0
    assert t_on.converged[-1]
    off = SimCluster(12, FAST, seed=2)
    t_off = off.run_scenario(spec)
    assert int(t_off.metrics["relay_full_syncs"].sum()) == 0

"""Cross-backend parity: the TPU simulation must produce membership
checksums bit-identical to the host library (and therefore to the
reference's farmhash32 format, lib/membership.js:41-93) for the same
cluster history.  This is BASELINE.json's north-star invariant and the
"minimum end-to-end slice" of SURVEY §7.
"""


from ringpop_tpu.harness import Cluster
from ringpop_tpu.models.cluster import SimCluster
from ringpop_tpu.models.swim_sim import SwimParams


def _host_cluster_converged(size: int):
    cluster = Cluster(size=size)
    cluster.bootstrap_all()
    assert cluster.run_until_converged(), "host cluster failed to converge"
    return cluster


def test_bootstrap_checksum_parity_5_nodes():
    host = _host_cluster_converged(5)
    host_sums = set(host.checksums().values())
    assert len(host_sums) == 1
    members = host.nodes[0].membership.get_stats()["members"]

    # adopt the host cluster's exact member list (addresses + incarnations)
    simc = SimCluster(
        5,
        addresses=[m["address"] for m in members],
        base_inc=min(m["incarnationNumber"] for m in members),
        inc=[m["incarnationNumber"] for m in members],
        init="converged",
    )
    sim_sums = set(simc.checksums().values())
    assert sim_sums == host_sums
    host.destroy_all()


def test_faulty_transition_checksum_parity():
    # Kill one node in both backends; after convergence both must agree
    # on the same member list (dead node faulty at its old incarnation)
    # and therefore the same checksum.
    host = _host_cluster_converged(4)
    members = host.nodes[0].membership.get_stats()["members"]
    victim_addr = host.host_ports[2]

    simc = SimCluster(
        4,
        SwimParams(suspicion_ticks=25),
        addresses=[m["address"] for m in members],
        base_inc=min(m["incarnationNumber"] for m in members),
        inc=[m["incarnationNumber"] for m in members],
        init="converged",
    )
    assert set(simc.checksums().values()) == set(host.checksums().values())

    host.kill(2)
    host.run(60000)
    assert host.run_until_converged(), "host did not reconverge after kill"
    host_sums = set(host.checksums().values())
    assert len(host_sums) == 1

    victim_idx = simc.book.index[victim_addr]
    simc.kill(victim_idx)
    simc.tick(3 * 25)
    assert simc.run_until_converged(600) > 0
    sim_sums = set(simc.checksums().values())

    assert sim_sums == host_sums
    host.destroy_all()


def test_member_list_shape_matches_host():
    host = _host_cluster_converged(3)
    members = host.nodes[0].membership.get_stats()["members"]
    simc = SimCluster(
        3,
        addresses=[m["address"] for m in members],
        base_inc=min(m["incarnationNumber"] for m in members),
        inc=[m["incarnationNumber"] for m in members],
    )
    assert simc.members(0) == members
    host.destroy_all()


def test_trajectory_parity_bootstrap_from_scratch():
    """Both backends bootstrap from zero knowledge through their own join
    paths and must converge to bit-identical reference-format checksums.

    Host side: five RingPops bootstrap over the in-process transport
    (join-sender.js semantics -> full-sync join responses -> gossip).
    Sim side: five virtual nodes start mode='self' (each knows only
    itself, at the same incarnations the host nodes booted with), join
    through admin_join (join-handler.js full-sync semantics), and gossip
    to convergence with swim_step.  This is SURVEY §7's minimum
    end-to-end slice proven end to end, not from a seeded state.
    """
    host = _host_cluster_converged(5)
    host_sums = set(host.checksums().values())
    assert len(host_sums) == 1
    members = host.nodes[0].membership.get_stats()["members"]
    by_addr = {m["address"]: m for m in members}
    assert all(m["status"] == "alive" for m in members)

    simc = SimCluster(
        5,
        addresses=host.host_ports,
        base_inc=min(m["incarnationNumber"] for m in members),
        inc=[by_addr[a]["incarnationNumber"] for a in host.host_ports],
        init="self",
    )
    # Pre-join: nobody agrees (each node sees only itself).
    assert not simc.converged()
    # tick-cluster 'j': every node admin-joins against the first
    # bootstrap host; the seed answers with a full sync
    # (join-handler.js:90-97) and gossip spreads the rest.
    for j in range(1, 5):
        simc.join(j, 0)
    assert simc.run_until_converged(200) > 0
    sim_sums = set(simc.checksums().values())
    assert sim_sums == host_sums

    # Same member list content, not just same hash.
    assert simc.members(0) == members
    host.destroy_all()

"""Flap damping (EXTENSION — documented by the reference at
docs/architecture_design.md:73-82, never implemented there)."""

from __future__ import annotations

from ringpop_tpu.harness import test_ringpop
from ringpop_tpu.member import Status


def make_rp(**damping_options):
    return test_ringpop(
        host_port="10.0.0.1:3000",
        damping_enabled=True,
        damping_options=damping_options,
    )


def flap(rp, addr: str, times: int, inc: int = 1) -> int:
    """Drive alive<->suspect transitions through membership.update."""
    for _ in range(times):
        rp.membership.update(
            {"address": addr, "status": Status.suspect, "incarnationNumber": inc}
        )
        inc += 1
        rp.membership.update(
            {"address": addr, "status": Status.alive, "incarnationNumber": inc}
        )
        inc += 1
    return inc


def test_flapping_member_gets_damped_and_leaves_ring():
    rp = make_rp()
    addr = "10.0.0.2:3000"
    rp.membership.make_alive(addr, 1)
    assert rp.ring.has_server(addr)

    events = []
    rp.on("memberDamped", lambda a: events.append(a))
    flap(rp, addr, times=4)  # 8 flaps x 500 penalty > 2500 suppress limit

    assert rp.damping.is_damped(addr)
    assert events == [addr]
    assert not rp.ring.has_server(addr)
    # ...but membership still tracks it (damping is a ring-level quarantine)
    assert rp.membership.find_member_by_address(addr) is not None


def test_stable_member_never_damped():
    rp = make_rp()
    addr = "10.0.0.3:3000"
    rp.membership.make_alive(addr, 1)
    # Repeated same-status updates (fresh incarnations) are not flaps.
    for inc in range(2, 20):
        rp.membership.update(
            {"address": addr, "status": Status.alive, "incarnationNumber": inc}
        )
    assert rp.damping.score_of(addr) == 0.0
    assert rp.ring.has_server(addr)


def test_score_decays_and_member_reinstated():
    rp = make_rp(decay_half_life_ms=1000.0)
    addr = "10.0.0.4:3000"
    rp.membership.make_alive(addr, 1)
    inc = flap(rp, addr, times=4)
    assert rp.damping.is_damped(addr)

    # Half-life 1s: after ~4s the score is ~1/16 of ~4000 < reuse limit.
    rp.clock.advance(5000)
    undamped = []
    rp.on("memberUndamped", lambda a: undamped.append(a))
    # Any ordinary update triggers re-evaluation via decay_tick.
    rp.membership.update(
        {"address": addr, "status": Status.alive, "incarnationNumber": inc + 1}
    )
    assert not rp.damping.is_damped(addr)
    assert undamped == [addr]
    assert rp.ring.has_server(addr)


def test_damping_off_by_default_preserves_reference_behavior():
    rp = test_ringpop(host_port="10.0.0.1:3000")
    assert rp.damping is None
    addr = "10.0.0.5:3000"
    rp.membership.make_alive(addr, 1)
    flap(rp, addr, times=10)
    assert rp.ring.has_server(addr)  # never evicted without damping


def test_damping_stats_surface():
    rp = make_rp()
    addr = "10.0.0.6:3000"
    rp.membership.make_alive(addr, 1)
    flap(rp, addr, times=4)
    stats = rp.get_stats()["damping"]
    assert stats["damped"] == [addr]
    assert stats["scores"][addr] > 0


def test_quiet_cluster_reinstates_via_protocol_period():
    """Regression: reinstatement must not require new membership updates —
    the protocol-period hook re-evaluates decayed scores."""
    rp = make_rp(decay_half_life_ms=1000.0)

    class DroppingChannel:  # the fixture has no transport; pings just fail
        destroyed = False

        def request(self, host, endpoint, head, body, timeout_ms, cb):
            rp.clock.call_soon(lambda: cb(Exception("no transport")))

    rp.channel = DroppingChannel()
    addr = "10.0.0.7:3000"
    rp.membership.make_alive(addr, 1)
    flap(rp, addr, times=4)
    assert rp.damping.is_damped(addr)

    rp.clock.advance(6000)       # quiet: no updates at all
    rp.ping_member_now()         # one protocol period fires decay_tick
    assert not rp.damping.is_damped(addr)
    assert rp.ring.has_server(addr)


def test_damping_ring_changes_emit_ring_changed():
    rp = make_rp(decay_half_life_ms=1000.0)
    addr = "10.0.0.8:3000"
    rp.membership.make_alive(addr, 1)
    ring_events = []
    rp.on("ringChanged", lambda *a: ring_events.append(1))
    flap(rp, addr, times=4)
    assert rp.damping.is_damped(addr)
    assert ring_events, "damping eviction did not emit ringChanged"

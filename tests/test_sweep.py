"""Vmapped scenario sweeps: R replicas in one dispatch, per-replica
bit-parity with standalone ``run_scenario``, SweepTrace plumbing.

Fast lane: the host-side sweep compiler (per-replica spec derivation,
loss scaling, kill jitter), the key-schedule equivalence (the vmapped
schedule path must equal the per-replica host chain bit for bit), the
``SweepTrace`` object on synthetic series, and ONE minimal compiled
sweep asserting the single-dispatch contract plus replica-0 parity at
tiny n (the scenario-scan side of that parity shares its compile with
test_scenario's fast smoke).

Slow lane: the acceptance grid — per-replica bit-parity (trace, final
state, reference checksums) against standalone ``run_scenario`` from
the same replica key on BOTH backends, the jitter/scale axes with a
nonzero base loss, replica-axis sharding across the virtual 8-device
mesh, and the CLI ``--sweep`` end to end.
"""

from __future__ import annotations

import jax
import numpy as np
import pytest

from ringpop_tpu.models import swim_sim as sim
from ringpop_tpu.models.cluster import SimCluster
from ringpop_tpu.scenarios import compile as scompile
from ringpop_tpu.scenarios import runner, sweep
from ringpop_tpu.scenarios.spec import Event, ScenarioSpec
from ringpop_tpu.scenarios.trace import Trace
from ringpop_tpu.stats import Histogram

FAST = sim.SwimParams(suspicion_ticks=8)
N = 12
TICKS = 40
# the acceptance scenario shared with test_scenario.py
SPEC = ScenarioSpec.from_dict(
    {
        "ticks": TICKS,
        "events": [
            {"at": 5, "op": "kill", "node": 3},
            {"at": 10, "op": "partition",
             "groups": [list(range(6)), list(range(6, 12))]},
            {"at": 10, "op": "loss", "p": 0.08},
            {"at": 20, "op": "heal"},
            {"at": 25, "op": "loss_ramp", "until": 30, "to": 0.0},
        ],
    }
)


def _states_equal(a, b) -> bool:
    return all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(a, b)
        if x is not None
    )


def _replica_state(states, r):
    return jax.tree_util.tree_map(lambda a: a[r], states)


def _assert_replica_parity(strace, r, cluster_factory, spec_r):
    """Replica r of a sweep == a standalone run_scenario from the same
    replica key: trace series, final state, reference checksums."""
    c2 = cluster_factory()
    c2.key = jax.numpy.asarray(strace.replica_keys[r])
    trace = c2.run_scenario(spec_r)
    np.testing.assert_array_equal(strace.converged[r], trace.converged)
    np.testing.assert_array_equal(strace.live[r], trace.live)
    np.testing.assert_array_equal(strace.loss[r], trace.loss)
    for k in trace.metrics:
        np.testing.assert_array_equal(strace.metrics[k][r], trace.metrics[k])
    assert _states_equal(_replica_state(strace.final_states, r), c2.state)
    probe = cluster_factory()
    probe.state = _replica_state(strace.final_states, r)
    probe.net = jax.tree_util.tree_map(lambda a: a[r], strace.final_nets)
    assert probe.checksums() == c2.checksums()


# -- fast: per-replica spec derivation (host-only) --------------------------


def test_replica_spec_shifts_kills_and_scales_loss():
    spec_r = sweep.replica_spec(SPEC, kill_jitter=3, loss_scale=0.5)
    kills = [e for e in spec_r.events if e.op == "kill"]
    assert [e.at for e in kills] == [8]  # 5 + 3
    # non-kill node events / partitions keep their ticks
    assert [e.at for e in spec_r.events if e.op == "partition"] == [10]
    losses = {e.at: e.p for e in spec_r.events if e.op == "loss"}
    assert losses[10] == pytest.approx(0.04)
    ramps = [e for e in spec_r.events if e.op == "loss_ramp"]
    assert ramps[0].p == pytest.approx(0.0)
    # identity fast path returns the same object
    assert sweep.replica_spec(SPEC) is SPEC


def test_replica_spec_rejects_out_of_range_jitter():
    with pytest.raises(ValueError, match="outside"):
        sweep.replica_spec(SPEC, kill_jitter=TICKS)
    with pytest.raises(ValueError, match="outside"):
        sweep.replica_spec(SPEC, kill_jitter=-6)


def test_compile_sweep_stacks_and_validates():
    cs = sweep.compile_sweep(
        SPEC, N, replicas=3, base_loss=0.0,
        loss_scales=[1.0, 0.5, 1.0], kill_jitter=[0, 0, 2],
    )
    assert cs.replicas == 3
    assert cs.ev_tick.shape[0] == 3 and cs.loss.shape == (3, TICKS)
    # scale halves the loss schedule of replica 1 only
    loss = np.asarray(cs.loss)
    assert loss[1, 10] == pytest.approx(loss[0, 10] / 2)
    # jitter moves replica 2's kill (and with it the boundary set)
    assert 7 in cs.boundaries[2] and 5 not in cs.boundaries[2]
    assert cs.boundaries[0] == cs.boundaries[1]
    with pytest.raises(ValueError, match="one entry per replica"):
        sweep.compile_sweep(SPEC, N, replicas=3, loss_scales=[1.0])
    with pytest.raises(ValueError, match="replica 1"):
        sweep.compile_sweep(SPEC, N, replicas=2, kill_jitter=[0, TICKS])
    with pytest.raises(ValueError, match="replicas must be"):
        sweep.compile_sweep(SPEC, N, replicas=0)


def test_sweep_key_schedule_matches_host_chain():
    """The vmapped schedule path (equal boundaries) and the per-replica
    fallback (jittered boundaries) must both equal the host-side
    key_schedule over a SimCluster._split chain from the replica key —
    the contract per-replica parity stands on."""
    rkeys = list(jax.random.split(jax.random.PRNGKey(3), 2))

    def host_schedule(rkey, compiled):
        state = {"key": rkey}

        def split():
            state["key"], sub = jax.random.split(state["key"])
            return sub

        return scompile.key_schedule(split, compiled)

    # equal boundaries -> one vmapped dispatch
    cs = sweep.compile_sweep(SPEC, N, replicas=2, base_loss=0.0)
    keys = sweep.sweep_key_schedule(rkeys, cs)
    assert keys.shape == (2, TICKS, 2)
    for r, rkey in enumerate(rkeys):
        np.testing.assert_array_equal(
            np.asarray(keys[r]), np.asarray(host_schedule(rkey, cs.base))
        )
    # per-replica boundaries (kill jitter) -> host fallback, same contract
    cs2 = sweep.compile_sweep(
        SPEC, N, replicas=2, base_loss=0.0, kill_jitter=[0, 2]
    )
    keys2 = sweep.sweep_key_schedule(rkeys, cs2)
    for r, rkey in enumerate(rkeys):
        np.testing.assert_array_equal(
            np.asarray(keys2[r]),
            np.asarray(
                host_schedule(
                    rkey, cs2.base._replace(boundaries=cs2.boundaries[r])
                )
            ),
        )
    with pytest.raises(ValueError, match="replica keys"):
        sweep.sweep_key_schedule(rkeys[:1], cs)


# -- fast: SweepTrace on synthetic series -----------------------------------


def _synthetic_sweep(r: int = 3, t: int = 6) -> sweep.SweepTrace:
    conv = np.zeros((r, t), bool)
    conv[0, 4:] = True  # heals at tick 4
    conv[1, 2] = True  # converged once, then diverges again -> no heal
    fd = np.zeros((r, t), np.int32)
    fd[0, 3] = 1  # detects at tick 3
    fd[2, 1] = 2  # detects at tick 1
    return sweep.SweepTrace(
        metrics={"faulty_declared": fd,
                 "pings_sent": np.ones((r, t), np.int32)},
        converged=conv,
        live=np.full((r, t), 7, np.int32),
        loss=np.zeros((r, t), np.float32),
        n=8,
        backend="dense",
        replica_keys=np.arange(2 * r, dtype=np.uint32).reshape(r, 2),
        loss_scales=[1.0] * r,
        kill_jitter=[0] * r,
        start_tick=5,
        spec={"ticks": t, "events": []},
    )


def test_sweep_trace_outcome_ticks():
    st = _synthetic_sweep()
    assert st.detect_ticks().tolist() == [3, -1, 1]
    assert st.heal_ticks().tolist() == [4, -1, -1]


def test_sweep_trace_summary_is_stats_key_compatible():
    st = _synthetic_sweep()
    summary = st.summary()
    hist_keys = set(Histogram().print_obj().keys())
    assert set(summary["detect_tick"].keys()) == hist_keys
    assert set(summary["heal_tick"].keys()) == hist_keys
    assert summary["detect_tick"]["min"] == 1.0
    assert summary["detect_tick"]["max"] == 3.0
    assert summary["heal_tick"]["median"] == 4.0
    assert summary["replicas"] == {
        "count": 3, "detected": 2, "healed": 1, "converged_final": 1
    }


def test_sweep_trace_npz_roundtrip(tmp_path):
    st = _synthetic_sweep()
    path = str(tmp_path / "sweep.npz")
    st.save(path)
    back = sweep.SweepTrace.load(path).validate()
    assert back.replicas == 3 and back.ticks == 6
    assert back.backend == "dense" and back.n == 8 and back.start_tick == 5
    assert back.loss_scales == (1.0, 1.0, 1.0)
    assert back.kill_jitter == (0, 0, 0)
    assert back.spec == st.spec
    np.testing.assert_array_equal(back.converged, st.converged)
    np.testing.assert_array_equal(back.replica_keys, st.replica_keys)
    np.testing.assert_array_equal(
        back.metrics["faulty_declared"], st.metrics["faulty_declared"]
    )
    # a sweep npz is not a Trace npz and vice versa
    with pytest.raises(ValueError, match="not a sweep trace"):
        trace_path = str(tmp_path / "trace.npz")
        Trace(
            metrics={}, converged=np.ones(3, bool), live=np.full(3, 8),
            loss=np.zeros(3), n=8, backend="dense",
        ).save(trace_path)
        sweep.SweepTrace.load(trace_path)


def test_sweep_trace_replica_extraction():
    st = _synthetic_sweep()
    tr = st.replica(2).validate()
    assert isinstance(tr, Trace)
    assert tr.ticks == 6 and tr.backend == "dense"
    np.testing.assert_array_equal(
        tr.metrics["faulty_declared"], st.metrics["faulty_declared"][2]
    )


def test_sweep_trace_validate_rejects_ragged():
    st = _synthetic_sweep()
    st.metrics["pings_sent"] = np.zeros((3, 4), np.int32)
    with pytest.raises(ValueError, match="not .*-shaped"):
        st.validate()


# -- fast: one minimal compiled sweep (the single-dispatch contract) --------


def test_sweep_single_dispatch_and_replica_parity(monkeypatch):
    """R=2 replicas in ONE vmapped dispatch: no swim_step/swim_run
    dispatch, the sweep counter advances once, the cluster itself does
    not move, and replica 0 is bit-identical to a standalone
    run_scenario from the same replica key (same tiny shape as
    test_scenario's fast smoke, so the scenario-scan compile is
    shared in-process)."""

    def boom(*a, **k):  # pragma: no cover - would mean a host round-trip
        raise AssertionError("host-loop dispatch inside run_sweep")

    monkeypatch.setattr(sim, "swim_step", boom)
    monkeypatch.setattr(sim, "swim_run", boom)
    spec = {"ticks": 4, "events": [{"at": 1, "op": "kill", "node": 5}]}
    params = sim.SwimParams(suspicion_ticks=5)
    before = sweep.dispatch_count()
    before_scan = runner.dispatch_count()
    c = SimCluster(6, params, seed=1)
    state_before = jax.tree_util.tree_map(np.asarray, c.state)
    strace = c.run_sweep(spec, 2)
    assert sweep.dispatch_count() - before == 1
    assert runner.dispatch_count() == before_scan  # no per-replica scan
    assert strace.replicas == 2 and strace.ticks == 4
    assert strace.live.tolist() == [[6, 5, 5, 5]] * 2
    assert all(arr.shape == (2, 4) for arr in strace.metrics.values())
    # the sweep is a measurement fan-out: the cluster did not advance,
    # nothing was appended to the telemetry log, only the key moved
    assert _states_equal(c.state, state_before)
    assert c.metrics_log == [] and c.traces == []
    monkeypatch.undo()
    _assert_replica_parity(
        strace, 0, lambda: SimCluster(6, params, seed=1),
        ScenarioSpec.from_dict(spec),
    )


def test_sweep_revive_rejected_on_delta_without_key_burn():
    spec = ScenarioSpec(ticks=4, events=(Event(at=1, op="revive", node=0),))
    c = SimCluster(8, FAST, seed=0, backend="delta", capacity=8)
    key_before = np.asarray(c.key).copy()
    with pytest.raises(NotImplementedError, match="dense-backend-only"):
        c.run_sweep(spec, 2)
    np.testing.assert_array_equal(np.asarray(c.key), key_before)


# -- slow: the acceptance grid ----------------------------------------------


@pytest.mark.slow
def test_sweep_dense_parity_every_replica():
    """Each of R=3 replicas of the acceptance scenario is bit-identical
    (trace, final state, reference checksums) to a standalone
    run_scenario from that replica's key."""
    c = SimCluster(N, FAST, seed=3)
    strace = c.run_sweep(SPEC, 3)
    for r in range(3):
        _assert_replica_parity(
            strace, r, lambda: SimCluster(N, FAST, seed=3), SPEC
        )


@pytest.mark.slow
def test_sweep_delta_parity_every_replica():
    """The same contract on the delta backend (ample caps, the
    test_swim_delta netsplit convention)."""

    def factory():
        return SimCluster(
            N, FAST, seed=3, backend="delta",
            capacity=N, wire_cap=N, claim_grid=3 * N * N,
        )

    c = factory()
    strace = c.run_sweep(SPEC, 2)
    assert strace.backend == "delta"
    for r in range(2):
        _assert_replica_parity(strace, r, factory, SPEC)


@pytest.mark.slow
def test_sweep_jitter_and_scale_parity():
    """The per-replica batch axes: replica r with loss scale s and kill
    jitter j equals a standalone run_scenario of replica_spec(spec, j,
    s) with base loss scaled by s — including a nonzero base loss."""
    base = sim.SwimParams(suspicion_ticks=8, loss=0.02)
    scales, jitters = [1.0, 0.5, 2.0], [0, 2, -1]
    c = SimCluster(N, base, seed=7)
    strace = c.run_sweep(SPEC, 3, loss_scales=scales, kill_jitter=jitters)
    assert strace.loss_scales == (1.0, 0.5, 2.0)
    assert strace.kill_jitter == (0, 2, -1)
    for r, (s, j) in enumerate(zip(scales, jitters)):
        spec_r = sweep.replica_spec(SPEC, kill_jitter=j, loss_scale=s)

        def factory(s=s):
            c2 = SimCluster(N, base, seed=7)
            c2.set_loss(base.loss * s)
            return c2

        _assert_replica_parity(strace, r, factory, spec_r)


@pytest.mark.slow
def test_sweep_sharded_matches_unsharded():
    """shard=True splits the replica axis across the virtual 8-device
    mesh (conftest) — replicas are data-parallel, so the sharded run is
    bit-identical to the unsharded one."""
    if jax.local_device_count() < 2:
        pytest.skip("needs the multi-device CPU mesh")
    r = jax.local_device_count()
    a = SimCluster(N, FAST, seed=5)
    plain = a.run_sweep(SPEC, r)
    b = SimCluster(N, FAST, seed=5)
    sharded = b.run_sweep(SPEC, r, shard=True)
    np.testing.assert_array_equal(plain.converged, sharded.converged)
    np.testing.assert_array_equal(plain.live, sharded.live)
    for k in plain.metrics:
        np.testing.assert_array_equal(plain.metrics[k], sharded.metrics[k])
    assert _states_equal(
        jax.tree_util.tree_map(np.asarray, plain.final_states),
        jax.tree_util.tree_map(np.asarray, sharded.final_states),
    )


def test_sweep_shard_rejects_indivisible_replicas_without_key_burn():
    """The static shard rejection fires BEFORE the replica keys draw
    (the run_scenario failed-call contract): a corrected retry on the
    same cluster must replay from an unmoved key."""
    if jax.local_device_count() < 2:
        pytest.skip("needs the multi-device CPU mesh")
    c = SimCluster(N, FAST, seed=5)
    key_before = np.asarray(c.key).copy()
    with pytest.raises(ValueError, match="divisible"):
        c.run_sweep(SPEC, jax.local_device_count() + 1, shard=True)
    np.testing.assert_array_equal(np.asarray(c.key), key_before)


@pytest.mark.slow
def test_cli_sweep_end_to_end(tmp_path, capsys):
    """tick-cluster --scenario F --sweep R: one vmapped dispatch,
    summary line, SweepTrace npz export."""
    from ringpop_tpu.cli.tick_cluster import main

    spec_path = str(tmp_path / "spec.json")
    trace_path = str(tmp_path / "sweep.npz")
    ScenarioSpec.from_dict(
        {"ticks": 10, "events": [{"at": 2, "op": "kill", "node": 3}]}
    ).save(spec_path)
    before = sweep.dispatch_count()
    main([
        "--backend", "tpu-sim", "-n", "8",
        "--scenario", spec_path, "--sweep", "3",
        "--sweep-loss-scales", "1.0,1.0,0.5",
        "--trace-out", trace_path,
    ])
    assert sweep.dispatch_count() - before == 1
    out = capsys.readouterr().out
    assert "one vmapped dispatch" in out
    strace = sweep.SweepTrace.load(trace_path).validate()
    assert strace.replicas == 3 and strace.ticks == 10
    assert strace.loss_scales == (1.0, 1.0, 0.5)
    assert strace.live[:, -1].tolist() == [7, 7, 7]

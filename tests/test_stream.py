"""Streaming chunked-scan runner (scenarios/stream.py): segmented
dispatch parity, the segment store, pipelined-drain ledger rows, and
kill-a-soak-mid-flight resume.

The two contracts everything here pins:

* a streamed run of ANY segment size is bit-identical to the
  unsegmented ``run_scenario`` — same key schedule, same trajectory,
  same trace (segmentation is an execution strategy, not semantics);
* a SIGKILL'd streamed soak resumed from its last checkpoint produces
  bit-identical final checksums and traces to the uninterrupted run
  (checkpoint v5 cursor + segment-exact key schedule re-derivation).

Fast lane: tiny-n dense + delta (three scan compiles total — the
dense whole-run arm, the dense segment program, the delta segment
program; every other fast test reuses those executables or is
host-only).  The extended grid — partitions + ramps, traffic co-runs,
streamed sweeps, multi-point interrupts with checkpoint cadence > 1 —
rides the slow lane.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from ringpop_tpu.models import swim_sim as sim
from ringpop_tpu.models.cluster import SimCluster
from ringpop_tpu.obs.emitters import CaptureEmitter
from ringpop_tpu.scenarios import runner as srunner
from ringpop_tpu.scenarios import stream as sstream
from ringpop_tpu.scenarios.trace import Trace

FAST = sim.SwimParams(suspicion_ticks=5)
# IDENTICAL shapes/params to test_scenario's fast smoke (n=6, T=4, one
# kill, suspicion_ticks=5): under the tier-1 run the whole-horizon
# scan program is already jit-cached by that module, so the parity
# test here pays only the segment program's compile.  Richer specs
# (loss events, partitions, ramps, traffic) ride the slow grid.
N, TICKS, SEG = 6, 4, 2
SPEC = {"ticks": TICKS, "events": [{"at": 1, "op": "kill", "node": 5}]}
# the delta fast shapes (one segment-program compile serves both the
# uninterrupted and the resumed run)
DN, DTICKS, DSEG = 8, 8, 4
DSPEC = {"ticks": DTICKS, "events": [{"at": 2, "op": "kill", "node": 7}]}


def _dense(seed: int = 3) -> SimCluster:
    return SimCluster(N, FAST, seed=seed)


def _delta(seed: int = 3) -> SimCluster:
    return SimCluster(
        DN, FAST, seed=seed, backend="delta",
        capacity=DN, wire_cap=DN, claim_grid=2 * DN,
    )


def _traces_equal(a: Trace, b: Trace) -> None:
    assert set(a.metrics) == set(b.metrics)
    np.testing.assert_array_equal(a.converged, b.converged)
    np.testing.assert_array_equal(a.live, b.live)
    np.testing.assert_array_equal(a.loss, b.loss)
    for k in a.metrics:
        np.testing.assert_array_equal(a.metrics[k], b.metrics[k], err_msg=k)


# -- fast: streamed == unsegmented (the semantic-identity contract) ---------


def test_streamed_matches_whole_run_dense():
    a = _dense()
    whole = a.run_scenario(SPEC)
    before = srunner.dispatch_count()
    b = _dense()
    streamed = b.run_scenario(SPEC, segment_ticks=SEG)
    assert srunner.dispatch_count() - before == TICKS // SEG
    _traces_equal(whole, streamed)
    assert a.checksums() == b.checksums()
    # the cluster key advanced identically: reruns stay in lockstep
    np.testing.assert_array_equal(np.asarray(a.key), np.asarray(b.key))
    # run_scenario bookkeeping holds on the streamed path too
    assert b.traces[-1] is streamed
    assert b.metrics_log[-1]["ticks"] == TICKS


# -- fast: the segment store (host-only) ------------------------------------


def _slab(start_tick: int, ticks: int, base: int = 0) -> Trace:
    rng = np.arange(ticks, dtype=np.int32) + base
    return Trace(
        metrics={"pings_sent": rng, "acks": rng * 2},
        converged=(rng % 2 == 0),
        live=np.full(ticks, 5, np.int32),
        loss=np.zeros(ticks, np.float32),
        n=6,
        backend="dense",
        start_tick=start_tick,
    )


def test_segment_store_roundtrip_and_lazy_iter(tmp_path):
    path = str(tmp_path / "store")
    meta = {"kind": "trace", "run_id": "r1", "n": 6, "backend": "dense",
            "segment_ticks": 4, "ticks": 10, "start_tick": 0,
            "spec": {"ticks": 10, "events": []}}
    store = sstream.SegmentStore.create(path, meta)
    store.append(_slab(0, 4, 0), segment=0, tick0=0)
    store.append(_slab(4, 4, 4), segment=1, tick0=4)
    store.append(_slab(8, 2, 8), segment=2, tick0=8)

    back = sstream.SegmentStore.open(path)
    assert back.segments == 3 and back.ticks_stored == 10
    # the lazy reader hands back one bounded slab at a time — the
    # O(segment) loader the memory contract is asserted through
    for slab in back.iter_traces():
        assert slab.ticks <= 4
    full = back.assemble()
    assert full.ticks == 10
    np.testing.assert_array_equal(
        full.metrics["pings_sent"], np.arange(10, dtype=np.int32)
    )
    assert full.spec == meta["spec"]

    # truncate to a checkpoint cursor: the uncommitted tail drops
    back.truncate(8)
    assert back.ticks_stored == 8
    reopened = sstream.SegmentStore.open(path)
    assert reopened.ticks_stored == 8

    # a different run may not reuse the directory
    with pytest.raises(ValueError, match="refusing to mix runs"):
        sstream.SegmentStore.create(path, {**meta, "run_id": "r2"})


def test_trace_concat_rejects_gaps_and_mismatch():
    with pytest.raises(ValueError, match="not contiguous"):
        Trace.concat([_slab(0, 4), _slab(6, 4)])
    odd = _slab(4, 4)
    odd.metrics["extra"] = np.zeros(4, np.int32)
    with pytest.raises(ValueError, match="metric series"):
        Trace.concat([_slab(0, 4), odd])
    with pytest.raises(ValueError, match="no slabs"):
        Trace.concat([])


def test_stream_api_validation(tmp_path):
    c = _dense()
    with pytest.raises(ValueError, match="streaming options"):
        c.run_scenario(SPEC, store=str(tmp_path / "s"))
    with pytest.raises(ValueError, match="segment store"):
        c.run_scenario(SPEC, segment_ticks=4, assemble=False)
    with pytest.raises(ValueError, match="segment_ticks"):
        sstream.segment_bounds(8, 0)
    with pytest.raises(ValueError, match="checkpoint_every"):
        sstream.run_streamed(c, SPEC, segment_ticks=4, checkpoint_every=0)


def test_failed_stream_does_not_advance_key(tmp_path):
    """A raising streamed call (here: store refusal) may not advance
    cluster.key — the rerun-lockstep invariant runner.precheck
    documents for the unsegmented path."""
    store = str(tmp_path / "st")
    c0 = _dense()
    c0.run_scenario(SPEC, segment_ticks=SEG, store=store)
    c1 = _dense(seed=4)
    before = np.asarray(c1.key).copy()
    with pytest.raises(ValueError, match="refusing to mix runs"):
        c1.run_scenario(SPEC, segment_ticks=SEG, store=store)
    np.testing.assert_array_equal(before, np.asarray(c1.key))
    with pytest.raises(ValueError, match="refusing to mix runs"):
        c1.run_sweep(SPEC, 2, segment_ticks=SEG, store=store)
    np.testing.assert_array_equal(before, np.asarray(c1.key))


# -- fast: kill-a-soak-mid-flight resume (dense + delta) --------------------


def test_kill_resume_bit_identical_dense(tmp_path):
    # the uninterrupted twin, streamed with checkpoints (same segment
    # executable as test_streamed_matches_whole_run_dense — warm)
    a = _dense()
    ckpt_a = str(tmp_path / "a.npz")
    whole = a.run_scenario(SPEC, segment_ticks=SEG, checkpoint_path=ckpt_a)

    # the killed run: SIGKILL simulated right after the first
    # checkpoint lands (the in-flight segment is abandoned)
    b = _dense()
    ckpt_b = str(tmp_path / "b.npz")
    with pytest.raises(sstream.StreamInterrupted):
        sstream.run_streamed(
            b, SPEC, segment_ticks=SEG, checkpoint_path=ckpt_b,
            interrupt_after=1,
        )
    cur = sstream.SegmentStore.open(ckpt_b + ".segments")
    assert cur.ticks_stored >= SEG  # the completed prefix persisted

    b2, resumed = sstream.resume(ckpt_b)
    _traces_equal(whole, resumed)
    assert a.checksums() == b2.checksums()
    np.testing.assert_array_equal(np.asarray(a.key), np.asarray(b2.key))
    # the resumed cluster's bookkeeping matches the uninterrupted one's
    assert b2.metrics_log[-1] == a.metrics_log[-1]

    # the finished checkpoint's cursor is complete: resume is a no-op
    # reload that reassembles from the store
    a2, again = sstream.resume(ckpt_a)
    _traces_equal(whole, again)

    # checkpoint v5 cursor shape (what a resume runs on)
    from ringpop_tpu import checkpoint

    mid = checkpoint.load(ckpt_b)
    cur = mid.stream_cursor
    assert cur is not None and cur["ticks_done"] == TICKS
    for field in ("run_id", "spec", "segment_ticks", "start_key",
                  "base_loss", "store", "checkpoint_every"):
        assert field in cur, field


def test_streamed_store_memory_contract(tmp_path):
    """assemble=False never materializes a whole-run series: the
    result is the store handle, and every slab the loader yields is
    segment-bounded (the acceptance's O(segment) assertion)."""
    c = _dense()
    store = c.run_scenario(
        SPEC, segment_ticks=SEG, store=str(tmp_path / "st"), assemble=False
    )
    assert isinstance(store, sstream.SegmentStore)
    seen = 0
    for slab in store.iter_traces():
        assert slab.ticks <= SEG
        seen += slab.ticks
    assert seen == TICKS
    # metrics_log still records the run (from the last slab)
    assert c.metrics_log[-1]["ticks"] == TICKS


@pytest.mark.slow
def test_kill_resume_bit_identical_delta(tmp_path):
    """Delta-backend kill-mid-flight resume (the ~30 s heavyweight of
    the fast lane; moved to the nightly slow lane in the PR 10 tier-1
    rebalance — the wall-clock budget absorbed the failure-model fast
    smokes).  The resume family keeps its tier-1 representative:
    ``test_kill_resume_bit_identical_dense`` runs the identical
    interrupt/resume machinery on the dense backend every push."""
    a = _delta()
    ckpt_a = str(tmp_path / "a.npz")
    whole = a.run_scenario(DSPEC, segment_ticks=DSEG, checkpoint_path=ckpt_a)

    b = _delta()
    ckpt_b = str(tmp_path / "b.npz")
    with pytest.raises(sstream.StreamInterrupted):
        sstream.run_streamed(
            b, DSPEC, segment_ticks=DSEG, checkpoint_path=ckpt_b,
            interrupt_after=1,
        )
    b2, resumed = sstream.resume(ckpt_b)
    assert b2.backend == "delta"
    _traces_equal(whole, resumed)
    assert a.checksums() == b2.checksums()
    np.testing.assert_array_equal(np.asarray(a.key), np.asarray(b2.key))


# -- fast: ledger segment rows + pipelining summary -------------------------


def test_ledger_segment_rows_and_run_summary():
    from ringpop_tpu.obs.ledger import default_ledger, summarize_runs

    led = default_ledger()
    led.enable(None)
    led.clear()
    try:
        c = _dense()
        c.run_scenario(SPEC, segment_ticks=SEG)  # warm executable
        rows = [r for r in led.rows if r.get("run_id")]
        assert len(rows) == TICKS // SEG
        assert len({r["run_id"] for r in rows}) == 1
        assert [r["segment"] for r in rows] == list(range(TICKS // SEG))
        # exactly one cold row per (backend, segment shape) — here zero
        # or one depending on whether the AOT cache saw the shape yet
        assert sum(r["cold"] for r in rows) <= 1
        for r in rows:
            assert r["ticks"] == SEG and r["segment_ticks"] == SEG
            assert "dispatch_s" in r and "drain_s" in r
            assert "drain_overlap_s" in r
        # every drain except the last overlapped the next dispatch
        assert all(r["drain_overlap_s"] > 0 for r in rows[:-1])
        assert rows[-1]["drain_overlap_s"] == 0.0
        runs = summarize_runs(led.rows)
        assert len(runs) == 1
        assert runs[0]["segments"] == TICKS // SEG
        assert runs[0]["ticks"] == TICKS
        assert 0.0 < runs[0]["overlap_pct"] <= 100.0
    finally:
        led.disable()
        led.clear()


def test_ledger_launch_disabled_is_passthrough():
    from ringpop_tpu.obs.ledger import DispatchLedger

    led = DispatchLedger()
    out, row = led.launch("x", lambda v: v + 1, 1)
    assert out == 2 and row is None
    assert led.rows == []


# -- fast: bridge continuation (host-only) ----------------------------------


def test_replay_trace_prev_live_continuation():
    """Slab-by-slab replay (declare once, prev_live threaded) emits the
    exact stat stream the whole-trace replay does."""
    from ringpop_tpu.obs import bridge as obs_bridge

    full = Trace(
        metrics={"pings_sent": np.array([3, 3, 3, 3, 3, 3], np.int32)},
        converged=np.ones(6, bool),
        live=np.array([4, 4, 5, 5, 6, 6], np.int32),
        loss=np.zeros(6, np.float32),
        n=6,
        backend="dense",
    )
    whole = CaptureEmitter()
    obs_bridge.replay_trace(full, whole, checksum=None)

    slabs = [
        Trace(
            metrics={"pings_sent": full.metrics["pings_sent"][a:b]},
            converged=full.converged[a:b],
            live=full.live[a:b],
            loss=full.loss[a:b],
            n=6,
            backend="dense",
            start_tick=a,
        )
        for a, b in ((0, 2), (2, 4), (4, 6))
    ]
    seg = CaptureEmitter()
    prev = None
    for i, slab in enumerate(slabs):
        obs_bridge.replay_trace(
            slab, seg, checksum=None,
            declare_namespace=(i == 0), prev_live=prev,
        )
        prev = int(slab.live[-1])
    assert whole.calls == seg.calls


def test_checkpoint_v4_loads_without_cursor(tmp_path):
    """Pre-v5 checkpoints (no stream meta) load with a None cursor and
    resume() rejects them with a clear error."""
    from ringpop_tpu import checkpoint

    c = _dense()  # no tick: the version shim needs no compiled program
    path = str(tmp_path / "old.npz")
    checkpoint.save(c, path)
    data = dict(np.load(path, allow_pickle=False))
    meta = json.loads(bytes(data["meta"]).decode())
    meta["version"] = 4
    data["meta"] = np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8)
    np.savez_compressed(path, **data)

    back = checkpoint.load(path)
    assert back.stream_cursor is None
    with pytest.raises(ValueError, match="no stream cursor"):
        sstream.resume(path)


# -- slow: the extended grid ------------------------------------------------


@pytest.mark.slow
def test_streamed_grid_partitions_ramps_and_checkpoint_cadence(tmp_path):
    """Dense acceptance scenario (kill + partition + heal + loss ramp)
    streamed at a ragged segment size, with checkpoint_every=2 and a
    late interrupt — still bit-identical to the unsegmented run."""
    n, ticks = 12, 40
    spec = {
        "ticks": ticks,
        "events": [
            {"at": 5, "op": "kill", "node": 3},
            {"at": 10, "op": "partition",
             "groups": [list(range(6)), list(range(6, 12))]},
            {"at": 10, "op": "loss", "p": 0.08},
            {"at": 20, "op": "heal"},
            {"at": 25, "op": "loss_ramp", "until": 30, "to": 0.0},
        ],
    }
    params = sim.SwimParams(suspicion_ticks=8)
    a = SimCluster(n, params, seed=7)
    whole = a.run_scenario(spec)

    b = SimCluster(n, params, seed=7)
    streamed = b.run_scenario(spec, segment_ticks=7)  # ragged tail of 5
    _traces_equal(whole, streamed)
    assert a.checksums() == b.checksums()

    c = SimCluster(n, params, seed=7)
    ckpt = str(tmp_path / "grid.npz")
    with pytest.raises(sstream.StreamInterrupted):
        sstream.run_streamed(
            c, spec, segment_ticks=7, checkpoint_path=ckpt,
            checkpoint_every=2, interrupt_after=2,
        )
    c2, resumed = sstream.resume(ckpt)
    _traces_equal(whole, resumed)
    assert a.checksums() == c2.checksums()


@pytest.mark.slow
def test_streamed_traffic_rides_the_same_path(tmp_path):
    """A chaos+traffic soak streams too: serving counters in every
    slab, the assembled trace bit-identical to the unsegmented
    traffic co-run, and a kill+resume preserving it all."""
    n, ticks = 12, 24
    spec = {"ticks": ticks,
            "events": [{"at": 4, "op": "kill", "node": 11}]}
    traffic = {"kind": "uniform", "keys_per_tick": 8, "pool": 32}
    params = sim.SwimParams(suspicion_ticks=8)

    a = SimCluster(n, params, seed=5)
    whole = a.run_scenario(spec, traffic=traffic)
    assert "lookups" in whole.metrics

    b = SimCluster(n, params, seed=5)
    streamed = b.run_scenario(spec, traffic=traffic, segment_ticks=8)
    _traces_equal(whole, streamed)
    assert a.checksums() == b.checksums()

    c = SimCluster(n, params, seed=5)
    ckpt = str(tmp_path / "traffic.npz")
    with pytest.raises(sstream.StreamInterrupted):
        sstream.run_streamed(
            c, spec, traffic=traffic, segment_ticks=8,
            checkpoint_path=ckpt, interrupt_after=1,
        )
    c2, resumed = sstream.resume(ckpt)
    _traces_equal(whole, resumed)
    assert a.checksums() == c2.checksums()
    # the resumed run recompiled the workload from the cursor
    assert resumed.metrics["lookups"].sum() == whole.metrics["lookups"].sum()


@pytest.mark.slow
def test_sweep_streamed_matches_whole(tmp_path):
    """A streamed sweep (R replicas x S-tick segments) reproduces the
    whole-horizon vmapped sweep bit-for-bit, and its slabs land in a
    kind='sweep' store that reassembles."""
    n, ticks, r = 8, 9, 2
    spec = {"ticks": ticks, "events": [{"at": 2, "op": "kill", "node": 7}]}
    params = sim.SwimParams(suspicion_ticks=5)

    a = SimCluster(n, params, seed=9)
    whole = a.run_sweep(spec, r)
    b = SimCluster(n, params, seed=9)
    streamed = b.run_sweep(spec, r, segment_ticks=4)  # ragged tail of 1
    assert streamed.replicas == r and streamed.ticks == ticks
    np.testing.assert_array_equal(whole.converged, streamed.converged)
    np.testing.assert_array_equal(whole.live, streamed.live)
    np.testing.assert_array_equal(whole.replica_keys, streamed.replica_keys)
    for k in whole.metrics:
        np.testing.assert_array_equal(
            whole.metrics[k], streamed.metrics[k], err_msg=k
        )
    # final per-replica states ride along like run_sweep's
    assert streamed.final_states is not None

    c = SimCluster(n, params, seed=9)
    store = str(tmp_path / "sweepstore")
    handle = c.run_sweep(
        spec, r, segment_ticks=4, store=store, assemble=False
    )
    assert isinstance(handle, sstream.SegmentStore)
    assert handle.kind == "sweep"
    for slab in handle.iter_traces():
        assert slab.ticks <= 4 and slab.replicas == r
    back = handle.assemble()
    np.testing.assert_array_equal(whole.converged, back.converged)

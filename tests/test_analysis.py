"""Trace-contract auditor tests (ringpop_tpu/analysis).

Two lanes:

* known-bad fixture programs — one per contract — each asserting the
  SPECIFIC violation is reported: a host-sync scan (contract 1), a
  dropped donation (2), an f64 carry and a budget drift (3), a shared
  key lineage and a key drawn twice (4), an [N, N] temporary landing
  in the census (5), an all-gathering "sharded gossip" program (6), a
  dropped output sharding (7), an over-budget widened carry tripping
  the byte contract (8);
* the clean lane: a well-formed program yields ZERO findings, and the
  real registry entry points audit clean (the fast representatives are
  ``swim_run`` and the mesh-2 ``sharded_step``; the full registry —
  including the n=4096 / n=65,536 byte pins — runs in the CI audit job
  and the slow lane).
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ringpop_tpu.analysis import budgets, lint_source, partitioning
from ringpop_tpu.analysis.contracts import (
    EntryReport,
    _lower_text,
    _trace_and_lower,
    audit_entry,
    check_carry_dtypes,
    check_donation,
    check_host_transfers,
    temp_census,
)
from ringpop_tpu.analysis.jaxpr_walk import (
    key_lineage,
    primary_scans,
    scan_carry_avals,
)
from ringpop_tpu.analysis.registry import Built, build_entry
from ringpop_tpu.obs.ledger import DispatchLedger


def _fixture_built(jitted, args, statics=None, *, donates=False,
                   min_aliased=0, key_roots=None, name="fixture",
                   dims=None, **extra):
    return Built(
        name=name, backend="dense", jitted=jitted, args=args,
        statics=statics or {}, key_roots=key_roots or {},
        donates=donates, min_aliased=min_aliased,
        census_min_elems=1 << 30, dims=dims or {}, **extra,
    )


# ---------------------------------------------------------------------------
# contract 1: host transfers
# ---------------------------------------------------------------------------


def test_host_sync_scan_detected():
    from jax.experimental import io_callback

    def hostfn(x):
        return x

    def body(c, x):
        c = io_callback(hostfn, jax.ShapeDtypeStruct(c.shape, c.dtype), c)
        return c + x, c.sum()

    def bad(init, xs):
        return jax.lax.scan(body, init, xs)

    closed = jax.make_jaxpr(bad)(
        jnp.zeros((4,), jnp.int32), jnp.zeros((8, 4), jnp.int32)
    )
    findings, hits = check_host_transfers(closed, "bad-host-sync")
    assert hits == 1
    (f,) = findings
    assert f.contract == "host-transfer" and f.severity == "error"
    assert "io_callback" in f.message and "scan body" in f.message


def test_clean_scan_no_host_prims():
    def ok(init, xs):
        return jax.lax.scan(lambda c, x: (c + x, c), init, xs)

    closed = jax.make_jaxpr(ok)(
        jnp.zeros((4,), jnp.int32), jnp.zeros((8, 4), jnp.int32)
    )
    findings, hits = check_host_transfers(closed, "ok")
    assert hits == 0 and findings == []


# ---------------------------------------------------------------------------
# contract 2: donation
# ---------------------------------------------------------------------------


def test_dropped_donation_detected():
    # the donated input's dtype never reaches an output: lowering warns
    # and emits no aliasing — both halves of the check must fire
    f = jax.jit(
        lambda a: (a.astype(jnp.int32) * 0).sum(), donate_argnums=(0,)
    )
    built = _fixture_built(
        f, (jnp.zeros((64,), jnp.float32),), donates=True, min_aliased=1,
        name="bad-donation",
    )
    text, warns = _lower_text(built)
    findings, aliased = check_donation(built, text, warns)
    assert aliased == 0
    assert any("donation dropped" in f.message for f in findings)
    assert any("aliases only 0" in f.message for f in findings)
    assert all(f.severity == "error" for f in findings)


def test_applied_donation_clean():
    f = jax.jit(lambda a: a + 1, donate_argnums=(0,))
    built = _fixture_built(
        f, (jnp.zeros((64,), jnp.float32),), donates=True, min_aliased=1
    )
    text, warns = _lower_text(built)
    findings, aliased = check_donation(built, text, warns)
    assert aliased >= 1 and findings == []


# ---------------------------------------------------------------------------
# contract 3: carry dtypes
# ---------------------------------------------------------------------------


def test_f64_carry_detected():
    jax.config.update("jax_enable_x64", True)
    try:
        def run(init, xs):
            return jax.lax.scan(lambda c, x: (c + x, c.sum()), init, xs)

        closed = jax.make_jaxpr(run)(
            jnp.zeros((4,), jnp.float64), jnp.zeros((8, 4), jnp.float64)
        )
    finally:
        jax.config.update("jax_enable_x64", False)
    built = _fixture_built(jax.jit(lambda: 0), (), name="bad-f64-carry")
    findings, carries = check_carry_dtypes(closed, built)
    wide = [f for f in findings
            if f.severity == "error" and "8 bytes/elem" in f.message]
    assert wide, findings
    assert "float64" in wide[0].message
    assert any("float64[4]" in leaf for leaves in carries.values()
               for leaf in leaves)


def test_budget_drift_detected(monkeypatch):
    # a pinned budget of {int8: 1} against an int32 carry = the
    # "widened int slot" review gate
    def run(init, xs):
        return jax.lax.scan(lambda c, x: (c + x, c), init, xs)

    closed = jax.make_jaxpr(run)(
        jnp.zeros((4,), jnp.int32), jnp.zeros((8, 4), jnp.int32)
    )
    built = _fixture_built(jax.jit(lambda: 0), (), name="drift")
    monkeypatch.setitem(
        budgets.CARRY_BUDGETS, ("drift", "dense"), {"int8": 1}
    )
    findings, _ = check_carry_dtypes(closed, built)
    drift = [f for f in findings if "budget drift" in f.message]
    assert drift and drift[0].severity == "error"
    assert "int8" in drift[0].message and "int32" in drift[0].message


def test_pinned_budget_match_clean(monkeypatch):
    def run(init, xs):
        return jax.lax.scan(lambda c, x: (c + x, c), init, xs)

    closed = jax.make_jaxpr(run)(
        jnp.zeros((4,), jnp.int32), jnp.zeros((8, 4), jnp.int32)
    )
    built = _fixture_built(jax.jit(lambda: 0), (), name="pinned")
    monkeypatch.setitem(
        budgets.CARRY_BUDGETS, ("pinned", "dense"), {"int32": 1}
    )
    findings, _ = check_carry_dtypes(closed, built)
    assert findings == []


# ---------------------------------------------------------------------------
# contract 4: PRNG key lineage
# ---------------------------------------------------------------------------


def test_shared_key_lineage_detected():
    # two declared streams combined into one key: lineage shared
    def bad(k1, k2):
        return jax.random.uniform(k1 ^ k2, (4,))

    k1, k2 = jax.random.PRNGKey(0), jax.random.PRNGKey(1)
    closed = jax.make_jaxpr(bad)(k1, k2)
    findings, _ = key_lineage(
        closed, {"protocol": [0], "workload": [1]}, "bad-mixed"
    )
    mixing = [f for f in findings if "prng-mixing" in f.message]
    assert mixing and mixing[0].severity == "error"
    assert "protocol" in mixing[0].message
    assert "workload" in mixing[0].message


def test_key_reuse_detected():
    # the same key value drawn twice: two "independent" streams read
    # the same bits
    def bad(key):
        a = jax.random.uniform(key, (4,))
        b = jax.random.normal(key, (4,))
        return a + b

    closed = jax.make_jaxpr(bad)(jax.random.PRNGKey(0))
    findings, _ = key_lineage(closed, {"protocol": [0]}, "bad-reuse")
    reuse = [f for f in findings if "prng-reuse" in f.message]
    assert reuse and reuse[0].severity == "error"


def test_fold_in_fanout_clean():
    # the repo's sanctioned idiom: domain-tag fold_in + per-tick fold,
    # every draw on its own derived key — zero findings
    def ok(key, t):
        ka = jax.random.fold_in(key, 0x5A10)
        kb = jax.random.fold_in(key, t)
        k1, k2 = jax.random.split(kb)
        return (jax.random.uniform(ka, (2,)),
                jax.random.uniform(k1, (2,)),
                jax.random.uniform(k2, (2,)))

    closed = jax.make_jaxpr(ok)(jax.random.PRNGKey(0), jnp.int32(3))
    findings, summary = key_lineage(closed, {"workload": [0]}, "ok")
    assert findings == []
    assert summary["roots"]["workload"] == 3


def test_carry_threaded_key_reuse_detected():
    # the classic scan reuse: key rides the carry unchanged and is
    # drawn every iteration — one draw SITE, T draws of one value
    def bad(key, xs):
        def body(k, x):
            return k, jax.random.uniform(k, ()) + x

        return jax.lax.scan(body, key, xs)

    closed = jax.make_jaxpr(bad)(
        jax.random.PRNGKey(0), jnp.zeros((6,), jnp.float32)
    )
    findings, _ = key_lineage(closed, {"protocol": [0]}, "bad-carry")
    assert any(
        "threaded unchanged" in f.message and f.severity == "error"
        for f in findings
    ), [str(f) for f in findings]

    # the sanctioned carry pattern: split per iteration — clean
    def ok(key, xs):
        def body(k, x):
            k, sub = jax.random.split(k)
            return k, jax.random.uniform(sub, ()) + x

        return jax.lax.scan(body, key, xs)

    closed = jax.make_jaxpr(ok)(
        jax.random.PRNGKey(0), jnp.zeros((6,), jnp.float32)
    )
    findings, _ = key_lineage(closed, {"protocol": [0]}, "ok-carry")
    assert findings == [], [str(f) for f in findings]


def test_cond_branch_draws_not_reuse():
    # mutually exclusive branches each drawing the same key once is ONE
    # draw at runtime — must not be flagged; a single branch drawing
    # twice still must be
    def ok(pred, key):
        return jax.lax.cond(
            pred,
            lambda k: jax.random.uniform(k, (2,)),
            lambda k: jax.random.normal(k, (2,)),
            key,
        )

    closed = jax.make_jaxpr(ok)(jnp.bool_(True), jax.random.PRNGKey(0))
    findings, summary = key_lineage(closed, {"protocol": [1]}, "ok-cond")
    assert [f for f in findings if "prng-reuse" in f.message] == []
    assert summary["roots"]["protocol"] == 1

    def bad(pred, key):
        def left(k):
            return jax.random.uniform(k, (2,)) + jax.random.normal(k, (2,))

        return jax.lax.cond(pred, left, lambda k: jax.random.uniform(k, (2,)), key)

    closed = jax.make_jaxpr(bad)(jnp.bool_(True), jax.random.PRNGKey(0))
    findings, _ = key_lineage(closed, {"protocol": [1]}, "bad-cond")
    assert any("prng-reuse" in f.message for f in findings)


def test_scan_threaded_key_lineage():
    # a per-tick key row sliced from a [T, 2] schedule inside a scan —
    # the entry points' shape — must stay clean and count its draws
    def ok(init, keys):
        def body(c, key):
            return c + jax.random.uniform(key, c.shape), c.sum()

        return jax.lax.scan(body, init, keys)

    keys = jax.random.split(jax.random.PRNGKey(0), 6)
    closed = jax.make_jaxpr(ok)(jnp.zeros((4,), jnp.float32), keys)
    findings, summary = key_lineage(closed, {"protocol": [1]}, "ok-scan")
    assert findings == []
    assert summary["roots"]["protocol"] >= 1


# ---------------------------------------------------------------------------
# contract 5: temporary-tensor census
# ---------------------------------------------------------------------------


def test_census_lists_nxn_intermediate():
    n = 32

    def prog(a):
        big = a[:, None] * a[None, :]  # the [N, N] temporary
        return big.sum()

    closed = jax.make_jaxpr(prog)(jnp.arange(n, dtype=jnp.float32))
    rows = temp_census(closed, dims={"N": n}, min_elems=n * n, entry="fx")
    assert rows, "census missed the [N, N] intermediate"
    tags = {r["tag"] for r in rows}
    assert "NxN" in tags
    for r in rows:
        assert r["dtype"] and r["primitive"] and r["elems_each"] >= n * n


def test_census_ambiguous_dim_tagged_with_both_names():
    # n == capacity at small fixture shapes: the tag must keep every
    # candidate name, not silently pick one
    n = 16

    def prog(a):
        return (a[:, None] * a[None, :]).sum()

    closed = jax.make_jaxpr(prog)(jnp.arange(n, dtype=jnp.float32))
    rows = temp_census(
        closed, dims={"N": n, "C": n}, min_elems=n * n, entry="fx"
    )
    assert rows and all("N|C" in r["tag"] for r in rows), rows


def test_census_threshold_respected():
    def prog(a):
        return (a[:, None] * a[None, :]).sum()

    closed = jax.make_jaxpr(prog)(jnp.arange(8, dtype=jnp.float32))
    # min_elems above 8x8 and N declared as something else: no rows
    rows = temp_census(closed, dims={"N": 999}, min_elems=1000, entry="fx")
    assert rows == []


# ---------------------------------------------------------------------------
# the clean lane: fixture + real entry point
# ---------------------------------------------------------------------------


def test_clean_program_zero_findings(monkeypatch):
    @partial(jax.jit, donate_argnums=(0,))
    def clean(carry, keys):
        def body(c, key):
            return c + jax.random.uniform(key, c.shape), c.sum()

        out, ys = jax.lax.scan(body, carry, keys)
        return out, ys

    keys = jax.random.split(jax.random.PRNGKey(0), 4)
    args = (jnp.zeros((8,), jnp.float32), keys)
    built = _fixture_built(
        clean, args, donates=True, min_aliased=1,
        key_roots={"protocol": [1]}, name="clean",
    )
    monkeypatch.setitem(
        budgets.CARRY_BUDGETS, ("clean", "dense"), {"float32": 1}
    )
    from ringpop_tpu.analysis import contracts

    closed = contracts._trace(built)
    text, warns = contracts._lower_text(built)
    findings = []
    f1, hits = check_host_transfers(closed, built.name)
    f2, aliased = check_donation(built, text, warns)
    f3, _ = check_carry_dtypes(closed, built)
    f4, _ = key_lineage(closed, built.key_roots, built.name)
    findings = f1 + f2 + f3 + f4
    assert findings == [], [str(f) for f in findings]
    assert hits == 0 and aliased >= 1


def test_registry_swim_run_audits_clean():
    # the tier-1 representative of the CI audit job: the real dense
    # entry point at a tiny shape must satisfy every pinned contract
    report = audit_entry("swim_run", "dense", n=16, ticks=2)
    assert isinstance(report, EntryReport)
    assert [f for f in report.findings if f.severity != "info"] == [], [
        str(f) for f in report.findings
    ]
    assert report.aliased_outputs >= 1
    assert report.prng["roots"]["protocol"] > 0
    # the dense tick scan is found, with its pinned carry multiset
    assert any(report.carries.values())


def test_registry_builders_cover_declared_backends():
    # every registered (entry, backend) pair must at least BUILD — a
    # signature change in a model/scenario module breaks here first
    from ringpop_tpu.analysis.registry import iter_entries

    pairs = list(iter_entries())
    assert ("run_scenario", "delta") in pairs
    assert ("run_scenario+traffic", "dense") in pairs
    assert ("run_scenario+incident", "delta") in pairs
    assert ("sharded_step", "dense") in pairs
    assert ("sharded_step@4", "dense") in pairs
    assert ("run_sweep+shard", "delta") in pairs
    built = build_entry("run_scenario", "dense", n=8, ticks=2)
    assert built.key_roots["protocol"]
    assert built.donates
    assert built.mesh_size == 0
    sharded = build_entry("sharded_step", "dense", n=8)
    assert sharded.mesh_size == 2 and sharded.mesh_axis == "nodes"
    # the strict point-to-point contract: the ring gossip plane (PR 18)
    # declares it on the remote-copy entries; only the explicit
    # all-gather baseline entry opts out
    assert build_entry("run_sweep+shard", "dense", n=8, ticks=2).p2p_only
    assert sharded.p2p_only
    assert not build_entry("sharded_step+gather", "dense", n=8).p2p_only


@pytest.mark.slow
def test_full_registry_audits_clean():
    # the whole registry, both backends, at the PINNED fixture shape
    # (the CI audit job's assertion, kept out of the tier-1 wall):
    # n=64 is where the collective budgets compare for real
    from ringpop_tpu.analysis.contracts import audit_all

    reports, findings = audit_all(n=64, ticks=4)
    assert len(reports) == 15  # 11 + sharded_step{,@4} + 2x sweep+shard
    bad = [f for f in findings if f.severity in ("warning", "error")]
    assert bad == [], [str(f) for f in bad]
    sharded = {(r.entry, r.backend): r for r in reports if r.mesh_size}
    assert set(sharded) == {
        ("sharded_step", "dense"), ("sharded_step@4", "dense"),
        ("run_sweep+shard", "dense"), ("run_sweep+shard", "delta"),
    }
    # the data-parallel sweeps hold the strict contract TODAY: zero
    # member-gathers on both backends (delta is fully collective-free)
    for backend in ("dense", "delta"):
        counts = partitioning.collective_counts(
            sharded[("run_sweep+shard", backend)].collectives
        )
        assert counts.get("member-gather", 0) == 0, (backend, counts)


@pytest.mark.slow
def test_byte_budget_pins_match_at_4096():
    # the fast byte gate's shape: dense + delta run_scenario at n=4096
    # must sit inside the pinned band (a drift here is the ROADMAP
    # item 2 regression this contract exists for)
    for backend in ("dense", "delta"):
        report = audit_entry("run_scenario", backend, n=4096, ticks=4)
        assert report.mem_bytes is not None, backend
        bad = [f for f in report.findings
               if f.severity in ("warning", "error")]
        assert bad == [], [str(f) for f in bad]
        assert ("run_scenario", backend, 4096) in budgets.BYTE_BUDGETS


@pytest.mark.slow
def test_flagship_byte_budget_65536_delta():
    # the n=65,536 delta program (the round-5 worker-killer) pinned at
    # ~903 MB derived peak through r05; the r06 pass re-pinned it at
    # ~576 MB (-36.2%).  This is item 2a's progress ledger — a PR that
    # shrinks it re-pins DOWN, a PR that grows it fails here — and the
    # pin itself may never crawl back above the item 2a target
    # (<= ~632 MB, i.e. >= 30% below the pre-r06 902,967,088)
    report = audit_entry("run_scenario", "delta", n=65536, ticks=4)
    bad = [f for f in report.findings
           if f.severity in ("warning", "error")]
    assert bad == [], [str(f) for f in bad]
    pinned = budgets.BYTE_BUDGETS[("run_scenario", "delta", 65536)]
    assert pinned["peak_bytes"] <= int(902_967_088 * 0.70)
    assert report.mem_bytes["peak_bytes"] <= pinned["peak_bytes"] * (
        1 + budgets.BYTE_TOLERANCE
    )


@pytest.mark.slow
def test_delta_run_census_lists_nc_intermediates():
    # the acceptance shape: delta_run at n=4096 lists every >= [N, C]
    # intermediate with dtype + producing primitive
    report = audit_entry(
        "delta_run", "delta", n=4096, ticks=2, capacity=64,
        compile_programs=False,
    )
    assert report.census
    nc = [r for r in report.census if r["tag"] == "NxC"]
    assert nc, "no [N, C]-tagged rows at n=4096"
    for r in report.census:
        assert r["elems_each"] >= 4096 * 64 or "N" in r["tag"]
        assert r["dtype"] and r["primitive"]


# ---------------------------------------------------------------------------
# contracts 6-8: the partitioning contracts (analysis/partitioning.py)
# ---------------------------------------------------------------------------


def _mesh2():
    return Mesh(np.asarray(jax.devices()[:2]), ("nodes",))


def _audit_fixture(built, n):
    """The partitioning slice of audit_entry, on a hand-built fixture."""
    closed, _, _, compiled = _trace_and_lower(
        built, lower=False, compile_hlo=True
    )
    rows = partitioning.collective_census(compiled.as_text(),
                                          dims=built.dims)
    findings = partitioning.check_collectives(built, rows, n=n)
    findings += partitioning.check_sharding_propagation(
        built, compiled, closed
    )
    return findings, rows


def test_member_allgather_fixture_detected(monkeypatch):
    # the known-bad sharded "gossip" program: a row-sharded [N, K]
    # member table forced back to full replication — exactly the
    # all-gather shape the p2p-only contract bans
    n = 8
    mesh = _mesh2()
    row = NamedSharding(mesh, P("nodes", None))
    rep = NamedSharding(mesh, P())
    bad = jax.jit(lambda x: x * 2, in_shardings=(row,), out_shardings=rep)
    x = jax.device_put(jnp.zeros((n, 4), jnp.int32), row)
    built = _fixture_built(
        bad, (x,), name="bad-allgather", dims={"N": n},
        mesh_size=2, mesh_axis="nodes", p2p_only=True,
    )
    monkeypatch.setitem(
        budgets.COLLECTIVE_BUDGETS, ("bad-allgather", "dense", 2),
        {"n": n, "counts": {}},
    )
    findings, rows = _audit_fixture(built, n)
    member = [f for f in findings
              if "member-tensor all-gather" in f.message]
    assert member and member[0].severity == "error"
    assert member[0].contract == "collective-census"
    # the replicated output is flagged by the propagation check too
    repl = [f for f in findings if "FULLY REPLICATED" in f.message]
    assert repl and repl[0].severity == "error"
    # and the census rows carry the machine-readable evidence
    assert any(r["member"] and r["tag"] == "Nx4" for r in rows)
    # budget drift fires as well: the pinned empty census vs reality
    assert any("collective budget drift" in f.message for f in findings)


def test_dropped_output_sharding_detected():
    # sharding-propagation: a member-axis output pinned replicated
    # inside the program — propagation "survives" only as replication
    n = 8
    mesh = _mesh2()
    row = NamedSharding(mesh, P("nodes", None))
    rep = NamedSharding(mesh, P())

    def drops(x):
        return jax.lax.with_sharding_constraint(x + 1, rep)

    f = jax.jit(drops, in_shardings=(row,))
    x = jax.device_put(jnp.zeros((n, 4), jnp.float32), row)
    built = _fixture_built(
        f, (x,), name="bad-resharded", dims={"N": n},
        mesh_size=2, mesh_axis="nodes",
    )
    closed, _, _, compiled = _trace_and_lower(
        built, lower=False, compile_hlo=True
    )
    findings = partitioning.check_sharding_propagation(
        built, compiled, closed
    )
    (f1,) = [f for f in findings if f.severity == "error"]
    assert f1.contract == "sharding-propagation"
    assert "float32[8, 4]" in f1.message and "nodes" in f1.message
    assert f1.where == "output[0]"


def test_partitioned_output_sharding_clean():
    # the healthy twin: row sharding survives propagation untouched
    n = 8
    mesh = _mesh2()
    row = NamedSharding(mesh, P("nodes", None))
    f = jax.jit(lambda x: x + 1, in_shardings=(row,))
    x = jax.device_put(jnp.zeros((n, 4), jnp.float32), row)
    built = _fixture_built(
        f, (x,), name="ok-sharded", dims={"N": n},
        mesh_size=2, mesh_axis="nodes",
    )
    closed, _, _, compiled = _trace_and_lower(
        built, lower=False, compile_hlo=True
    )
    findings = partitioning.check_sharding_propagation(
        built, compiled, closed
    )
    assert findings == [], [str(f) for f in findings]


def test_byte_budget_drift_detected(monkeypatch):
    # the widened-carry fixture: an int64 carry doubles every byte
    # field past the pinned band -> the byte contract trips (and the
    # wide-dtype carry rule fires alongside, as in a real regression)
    def run(init, xs):
        return jax.lax.scan(lambda c, x: (c + x, c.sum()), init, xs)

    jitted32 = jax.jit(run)
    args32 = (jnp.zeros((256,), jnp.int32), jnp.zeros((8, 256), jnp.int32))
    built32 = _fixture_built(jitted32, args32, name="bb-fx")
    _, _, _, c32 = _trace_and_lower(built32, lower=False, compile_hlo=True)
    from ringpop_tpu.obs.ledger import memory_row

    baseline = memory_row(c32)
    monkeypatch.setitem(
        budgets.BYTE_BUDGETS, ("bb-fx", "dense", 256),
        {"ticks": 8, **{k: baseline[k] for k in (
            "argument_bytes", "output_bytes", "temp_bytes", "peak_bytes")}},
    )
    # in-band: clean
    ok = partitioning.check_byte_budget(built32, baseline, n=256, ticks=8)
    assert ok == [], [str(f) for f in ok]
    # the widened carry (int64 state) blows through the +10% band
    jax.config.update("jax_enable_x64", True)
    try:
        args64 = (jnp.zeros((256,), jnp.int64),
                  jnp.zeros((8, 256), jnp.int64))
        built64 = _fixture_built(jax.jit(run), args64, name="bb-fx")
        _, _, _, c64 = _trace_and_lower(built64, lower=False,
                                        compile_hlo=True)
        widened = memory_row(c64)
    finally:
        jax.config.update("jax_enable_x64", False)
    findings = partitioning.check_byte_budget(
        built64, widened, n=256, ticks=8
    )
    over = [f for f in findings if f.severity == "error"]
    assert over, [str(f) for f in findings]
    assert any("grew past the pinned budget" in f.message for f in over)
    # a mismatched horizon is an explicit skip, not a bogus comparison
    skip = partitioning.check_byte_budget(built64, widened, n=256, ticks=4)
    assert [f.severity for f in skip] == ["info"]


def test_byte_budget_underrun_prompts_repin(monkeypatch):
    def run(init, xs):
        return jax.lax.scan(lambda c, x: (c + x, c.sum()), init, xs)

    built = _fixture_built(
        jax.jit(run),
        (jnp.zeros((64,), jnp.int32), jnp.zeros((4, 64), jnp.int32)),
        name="bb-under",
    )
    _, _, _, c = _trace_and_lower(built, lower=False, compile_hlo=True)
    from ringpop_tpu.obs.ledger import memory_row

    mem = memory_row(c)
    monkeypatch.setitem(
        budgets.BYTE_BUDGETS, ("bb-under", "dense", 64),
        {"ticks": 4, "peak_bytes": mem["peak_bytes"] * 2},
    )
    findings = partitioning.check_byte_budget(built, mem, n=64, ticks=4)
    assert [f.severity for f in findings] == ["info"]
    assert "re-pin to lock the reduction in" in findings[0].message


def test_collective_census_parses_phases_and_bytes():
    # parser unit: phases from named_scope'd op_name metadata, bytes
    # from the result type, member classification from the dims
    hlo = "\n".join([
        '  %ag = s32[64,64]{1,0} all-gather(s32[32,64]{1,0} %x), '
        'metadata={op_name="jit(f)/jit(main)/swim.recv_merge/gather"}',
        '  %ar = f32[] all-reduce(f32[] %y), '
        'metadata={op_name="jit(f)/jit(main)/add"}',
        '  %cp = u32[16]{0} collective-permute(u32[16]{0} %z)',
        # XLA's DEFAULT instruction naming puts the opcode in the name
        # too — the result type must still be found after the "="
        '  %custom-call.7 = s32[64,8]{1,0} custom-call(s32[64,8]{1,0} '
        '%w, s32[999]{0} %big), custom_call_target="tpu_custom_call"',
    ])
    rows = partitioning.collective_census(hlo, dims={"N": 64})
    by_op = {r["op"]: r for r in rows}
    ag = by_op["all-gather"]
    assert ag["member"] and ag["phase"] == "swim.recv_merge"
    assert ag["bytes_each"] == 64 * 64 * 4 and ag["tag"] == "NxN"
    assert by_op["all-reduce"]["phase"] == "unscoped"
    # DMA-flavored custom calls are censused by their RESULT type only
    # (operand types later in the line must not inflate the bytes)
    dma = by_op["custom-call:tpu_custom_call"]
    assert dma["bytes_each"] == 64 * 8 * 4 and not dma["member"]
    assert partitioning.collective_counts(rows) == {
        "all-gather": 1, "all-reduce": 1, "collective-permute": 1,
        "custom-call:tpu_custom_call": 1, "member-gather": 1,
    }


def test_sharded_step_audits_clean():
    # the clean sharded lane's fast representative: the real mesh-2
    # sharded dense step at the PINNED budget shape must satisfy every
    # partitioning contract — collective census matching the pinned
    # budget, member-bearing outputs still row-sharded after
    # unconstrained propagation, donation via the compiled alias table.
    # Since PR 18 the default lowering is the p2p ring plane: ZERO
    # member-gathers (the fence), with the old 75-gather lowering
    # pinned separately on the sharded_step+gather baseline entry
    report = audit_entry("sharded_step", "dense", n=64)
    assert report.mesh_size == 2
    assert [f for f in report.findings if f.severity != "info"] == [], [
        str(f) for f in report.findings
    ]
    assert report.aliased_outputs >= 1
    counts = partitioning.collective_counts(report.collectives)
    assert counts.get("member-gather", 0) == 0  # the flipped fence
    # the ring hops ARE the cross-shard gossip now
    assert counts.get("collective-permute", 0) > 0


def test_registry_sharded_entries_skip_without_devices(monkeypatch):
    # a 1-device host must degrade to an info finding, not a crash
    from ringpop_tpu.analysis.contracts import audit_all
    from ringpop_tpu.analysis import registry as reg

    monkeypatch.setattr(
        reg, "_require_devices",
        lambda mesh, entry: (_ for _ in ()).throw(
            reg.EntryUnavailable(f"{entry} needs {mesh} devices")),
    )
    reports, findings = audit_all(
        names=("sharded_step",), compile_programs=False
    )
    assert reports == []
    (f,) = findings
    assert f.severity == "info" and "devices" in f.message
    # ...but the CLI fails CLOSED when the skip leaves ZERO audited
    # programs: an explicit mesh-entry selection on a capability-poor
    # host must not green-light the push
    from ringpop_tpu.analysis.cli import main

    with pytest.raises(SystemExit) as exc:
        main(["--entry", "sharded_step", "--no-lint"])
    assert "0 programs audited" in str(exc.value)


def test_lint_block_until_ready_flagged_and_pragma():
    src = "def drain(x):\n    return x.block_until_ready()\n"
    (f,) = lint_source(src, "lib.py", compiled_path=True)
    assert f.contract == "lint:RPL001" and "lib.py:2" in f.where
    src_ok = ("def drain(x):\n"
              "    return x.block_until_ready()  # audit: allow=RPL001\n")
    assert lint_source(src_ok, "lib.py", compiled_path=True) == []
    # the pragma may land on ANY line a wrapped call spans
    src_wrapped = ("def drain(x, y):\n"
                   "    return x.block_until_ready(\n"
                   "    )  # audit: allow=RPL001\n")
    assert lint_source(src_wrapped, "lib.py", compiled_path=True) == []
    # host-side modules are exempt
    assert lint_source(src, "host.py", compiled_path=False) == []


def test_lint_np_on_traced_flagged():
    src = ("import numpy as np\n"
           "def step_impl(state):\n"
           "    return np.asarray(state)\n")
    (f,) = lint_source(src, "m.py")
    assert f.contract == "lint:RPL002" and "step_impl" in f.message
    # host code: same call, no traced context, no finding
    host = "import numpy as np\ndef reader(x):\n    return np.asarray(x)\n"
    assert lint_source(host, "m.py") == []


def test_lint_traced_bool_if_flagged():
    src = ("import jax.numpy as jnp\n"
           "def step_impl(mask):\n"
           "    if jnp.any(mask):\n"
           "        return 1\n"
           "    return 0\n")
    (f,) = lint_source(src, "m.py")
    assert f.contract == "lint:RPL003"
    # static-shape branches stay legal
    ok = ("def step_impl(ev):\n"
          "    if ev.shape[0]:\n"
          "        return 1\n"
          "    return 0\n")
    assert lint_source(ok, "m.py") == []


def test_lint_wallclock_in_traced_flagged():
    src = ("import time\n"
           "def body_impl(c):\n"
           "    return c + time.time()\n")
    (f,) = lint_source(src, "m.py")
    assert f.contract == "lint:RPL004"
    # host code wall-clock reads are fine
    host = "import time\ndef stamp():\n    return time.time()\n"
    assert lint_source(host, "m.py") == []


def test_lint_nested_scan_body_inherits_traced_context():
    src = ("import numpy as np\n"
           "def run_impl(xs):\n"
           "    def body(c, x):\n"
           "        return c + np.asarray(x), c\n"
           "    return body\n")
    (f,) = lint_source(src, "m.py")
    assert f.contract == "lint:RPL002"


def test_lint_rpl005_device_put_and_shard_map():
    # the silent-replication footgun: bare device_put in a
    # sharding-path module
    src = ("import jax\n"
           "def place(x):\n"
           "    return jax.device_put(x)\n")
    (f,) = lint_source(src, "parallel/m.py", sharding_path=True)
    assert f.contract == "lint:RPL005" and "placement" in f.message
    # an explicit sharding (positional or keyword) passes
    ok = ("import jax\n"
          "def place(x, sh):\n"
          "    a = jax.device_put(x, sh)\n"
          "    return jax.device_put(x, device=sh)\n")
    assert lint_source(ok, "parallel/m.py", sharding_path=True) == []
    # outside the sharding dirs the same call is host plumbing
    assert lint_source(src, "obs/m.py", sharding_path=False) == []
    # the pragma wins, as everywhere
    allowed = ("import jax\n"
               "def place(x):\n"
               "    return jax.device_put(x)  # audit: allow=RPL005\n")
    assert lint_source(allowed, "parallel/m.py", sharding_path=True) == []
    # shard_map without explicit specs
    sm = ("from jax.experimental.shard_map import shard_map\n"
          "def build(f, mesh):\n"
          "    return shard_map(f, mesh)\n")
    (f2,) = lint_source(sm, "scenarios/m.py", sharding_path=True)
    assert f2.contract == "lint:RPL005" and "in_specs" in f2.message
    sm_ok = ("from jax.experimental.shard_map import shard_map\n"
             "from jax.sharding import PartitionSpec as P\n"
             "def build(f, mesh):\n"
             "    return shard_map(f, mesh, in_specs=P('x'), "
             "out_specs=P('x'))\n")
    assert lint_source(sm_ok, "scenarios/m.py", sharding_path=True) == []
    # mixed positional/keyword specs are fully explicit too
    sm_mixed = ("from jax.experimental.shard_map import shard_map\n"
                "from jax.sharding import PartitionSpec as P\n"
                "def build(f, mesh, inspec):\n"
                "    return shard_map(f, mesh, inspec, "
                "out_specs=P('x'))\n")
    assert lint_source(sm_mixed, "scenarios/m.py", sharding_path=True) == []


def test_lint_library_tree_clean():
    # the shipped compiled-path modules must lint clean (the CI audit
    # job's lint assertion)
    from pathlib import Path

    from ringpop_tpu.analysis.lint import lint_paths

    import ringpop_tpu

    findings = lint_paths(Path(ringpop_tpu.__file__).parent)
    assert findings == [], [str(f) for f in findings]


# ---------------------------------------------------------------------------
# ledger recompile attribution (obs/ledger.py)
# ---------------------------------------------------------------------------


def test_ledger_recompile_attribution_names_static():
    led = DispatchLedger().enable(None)
    f = jax.jit(lambda x, k: x * k, static_argnames=("k",))
    led.dispatch("prog", f, jnp.zeros((4,), jnp.float32), k=2)
    led.dispatch("prog", f, jnp.zeros((4,), jnp.float32), k=2)
    led.dispatch("prog", f, jnp.zeros((4,), jnp.float32), k=3)
    led.dispatch("prog", f, jnp.zeros((8,), jnp.float32), k=3)
    rows = led.rows
    assert [r["cold"] for r in rows] == [True, False, True, True]
    # warm row: same sig as its cold row, no cause
    assert rows[1]["sig"] == rows[0]["sig"]
    assert "recompile_cause" not in rows[0]
    assert "recompile_cause" not in rows[1]
    assert rows[2]["recompile_cause"] == ["static 'k' changed: 2 -> 3"]
    assert rows[3]["recompile_cause"] == [
        "arg leaf 0 shape changed: (4,) -> (8,)"
    ]
    # exactly one cold per signature
    sigs = [r["sig"] for r in rows if r["cold"]]
    assert len(sigs) == len(set(sigs))


def test_audit_cli_smoke(capsys):
    # the CLI lane end to end on a tiny entry (no SystemExit = exit 0)
    from ringpop_tpu.analysis.cli import main

    main(["--entry", "swim_run", "--n", "16", "--ticks", "2", "--no-lint"])
    out = capsys.readouterr().out
    assert "swim_run [dense]" in out and "clean" in out
    assert "lint skipped" in out


def test_audit_cli_rejects_unknown_entry():
    # a typo'd selection must fail CLOSED, not audit 0 programs
    from ringpop_tpu.analysis.cli import main

    with pytest.raises(SystemExit) as exc:
        main(["--entry", "delta_runn", "--no-lint"])
    assert "unknown entry point" in str(exc.value)
    with pytest.raises(SystemExit) as exc:
        main(["--entry", "recv_merge_pallas", "--backend", "delta",
              "--no-lint"])
    assert "matches no registered" in str(exc.value)

"""Trace-contract auditor tests (ringpop_tpu/analysis).

Two lanes:

* known-bad fixture programs — one per contract — each asserting the
  SPECIFIC violation is reported: a host-sync scan (contract 1), a
  dropped donation (2), an f64 carry and a budget drift (3), a shared
  key lineage and a key drawn twice (4), an [N, N] temporary landing
  in the census (5);
* the clean lane: a well-formed program yields ZERO findings, and the
  real registry entry points audit clean (the fast representative here
  is ``swim_run``; the full registry runs in the CI audit job and the
  slow lane).
"""

from functools import partial

import jax
import jax.numpy as jnp
import pytest

from ringpop_tpu.analysis import budgets, lint_source
from ringpop_tpu.analysis.contracts import (
    EntryReport,
    _lower_text,
    audit_entry,
    check_carry_dtypes,
    check_donation,
    check_host_transfers,
    temp_census,
)
from ringpop_tpu.analysis.jaxpr_walk import (
    key_lineage,
    primary_scans,
    scan_carry_avals,
)
from ringpop_tpu.analysis.registry import Built, build_entry
from ringpop_tpu.obs.ledger import DispatchLedger


def _fixture_built(jitted, args, statics=None, *, donates=False,
                   min_aliased=0, key_roots=None, name="fixture"):
    return Built(
        name=name, backend="dense", jitted=jitted, args=args,
        statics=statics or {}, key_roots=key_roots or {},
        donates=donates, min_aliased=min_aliased,
        census_min_elems=1 << 30, dims={},
    )


# ---------------------------------------------------------------------------
# contract 1: host transfers
# ---------------------------------------------------------------------------


def test_host_sync_scan_detected():
    from jax.experimental import io_callback

    def hostfn(x):
        return x

    def body(c, x):
        c = io_callback(hostfn, jax.ShapeDtypeStruct(c.shape, c.dtype), c)
        return c + x, c.sum()

    def bad(init, xs):
        return jax.lax.scan(body, init, xs)

    closed = jax.make_jaxpr(bad)(
        jnp.zeros((4,), jnp.int32), jnp.zeros((8, 4), jnp.int32)
    )
    findings, hits = check_host_transfers(closed, "bad-host-sync")
    assert hits == 1
    (f,) = findings
    assert f.contract == "host-transfer" and f.severity == "error"
    assert "io_callback" in f.message and "scan body" in f.message


def test_clean_scan_no_host_prims():
    def ok(init, xs):
        return jax.lax.scan(lambda c, x: (c + x, c), init, xs)

    closed = jax.make_jaxpr(ok)(
        jnp.zeros((4,), jnp.int32), jnp.zeros((8, 4), jnp.int32)
    )
    findings, hits = check_host_transfers(closed, "ok")
    assert hits == 0 and findings == []


# ---------------------------------------------------------------------------
# contract 2: donation
# ---------------------------------------------------------------------------


def test_dropped_donation_detected():
    # the donated input's dtype never reaches an output: lowering warns
    # and emits no aliasing — both halves of the check must fire
    f = jax.jit(
        lambda a: (a.astype(jnp.int32) * 0).sum(), donate_argnums=(0,)
    )
    built = _fixture_built(
        f, (jnp.zeros((64,), jnp.float32),), donates=True, min_aliased=1,
        name="bad-donation",
    )
    text, warns = _lower_text(built)
    findings, aliased = check_donation(built, text, warns)
    assert aliased == 0
    assert any("donation dropped" in f.message for f in findings)
    assert any("aliases only 0" in f.message for f in findings)
    assert all(f.severity == "error" for f in findings)


def test_applied_donation_clean():
    f = jax.jit(lambda a: a + 1, donate_argnums=(0,))
    built = _fixture_built(
        f, (jnp.zeros((64,), jnp.float32),), donates=True, min_aliased=1
    )
    text, warns = _lower_text(built)
    findings, aliased = check_donation(built, text, warns)
    assert aliased >= 1 and findings == []


# ---------------------------------------------------------------------------
# contract 3: carry dtypes
# ---------------------------------------------------------------------------


def test_f64_carry_detected():
    jax.config.update("jax_enable_x64", True)
    try:
        def run(init, xs):
            return jax.lax.scan(lambda c, x: (c + x, c.sum()), init, xs)

        closed = jax.make_jaxpr(run)(
            jnp.zeros((4,), jnp.float64), jnp.zeros((8, 4), jnp.float64)
        )
    finally:
        jax.config.update("jax_enable_x64", False)
    built = _fixture_built(jax.jit(lambda: 0), (), name="bad-f64-carry")
    findings, carries = check_carry_dtypes(closed, built)
    wide = [f for f in findings
            if f.severity == "error" and "8 bytes/elem" in f.message]
    assert wide, findings
    assert "float64" in wide[0].message
    assert any("float64[4]" in leaf for leaves in carries.values()
               for leaf in leaves)


def test_budget_drift_detected(monkeypatch):
    # a pinned budget of {int8: 1} against an int32 carry = the
    # "widened int slot" review gate
    def run(init, xs):
        return jax.lax.scan(lambda c, x: (c + x, c), init, xs)

    closed = jax.make_jaxpr(run)(
        jnp.zeros((4,), jnp.int32), jnp.zeros((8, 4), jnp.int32)
    )
    built = _fixture_built(jax.jit(lambda: 0), (), name="drift")
    monkeypatch.setitem(
        budgets.CARRY_BUDGETS, ("drift", "dense"), {"int8": 1}
    )
    findings, _ = check_carry_dtypes(closed, built)
    drift = [f for f in findings if "budget drift" in f.message]
    assert drift and drift[0].severity == "error"
    assert "int8" in drift[0].message and "int32" in drift[0].message


def test_pinned_budget_match_clean(monkeypatch):
    def run(init, xs):
        return jax.lax.scan(lambda c, x: (c + x, c), init, xs)

    closed = jax.make_jaxpr(run)(
        jnp.zeros((4,), jnp.int32), jnp.zeros((8, 4), jnp.int32)
    )
    built = _fixture_built(jax.jit(lambda: 0), (), name="pinned")
    monkeypatch.setitem(
        budgets.CARRY_BUDGETS, ("pinned", "dense"), {"int32": 1}
    )
    findings, _ = check_carry_dtypes(closed, built)
    assert findings == []


# ---------------------------------------------------------------------------
# contract 4: PRNG key lineage
# ---------------------------------------------------------------------------


def test_shared_key_lineage_detected():
    # two declared streams combined into one key: lineage shared
    def bad(k1, k2):
        return jax.random.uniform(k1 ^ k2, (4,))

    k1, k2 = jax.random.PRNGKey(0), jax.random.PRNGKey(1)
    closed = jax.make_jaxpr(bad)(k1, k2)
    findings, _ = key_lineage(
        closed, {"protocol": [0], "workload": [1]}, "bad-mixed"
    )
    mixing = [f for f in findings if "prng-mixing" in f.message]
    assert mixing and mixing[0].severity == "error"
    assert "protocol" in mixing[0].message
    assert "workload" in mixing[0].message


def test_key_reuse_detected():
    # the same key value drawn twice: two "independent" streams read
    # the same bits
    def bad(key):
        a = jax.random.uniform(key, (4,))
        b = jax.random.normal(key, (4,))
        return a + b

    closed = jax.make_jaxpr(bad)(jax.random.PRNGKey(0))
    findings, _ = key_lineage(closed, {"protocol": [0]}, "bad-reuse")
    reuse = [f for f in findings if "prng-reuse" in f.message]
    assert reuse and reuse[0].severity == "error"


def test_fold_in_fanout_clean():
    # the repo's sanctioned idiom: domain-tag fold_in + per-tick fold,
    # every draw on its own derived key — zero findings
    def ok(key, t):
        ka = jax.random.fold_in(key, 0x5A10)
        kb = jax.random.fold_in(key, t)
        k1, k2 = jax.random.split(kb)
        return (jax.random.uniform(ka, (2,)),
                jax.random.uniform(k1, (2,)),
                jax.random.uniform(k2, (2,)))

    closed = jax.make_jaxpr(ok)(jax.random.PRNGKey(0), jnp.int32(3))
    findings, summary = key_lineage(closed, {"workload": [0]}, "ok")
    assert findings == []
    assert summary["roots"]["workload"] == 3


def test_carry_threaded_key_reuse_detected():
    # the classic scan reuse: key rides the carry unchanged and is
    # drawn every iteration — one draw SITE, T draws of one value
    def bad(key, xs):
        def body(k, x):
            return k, jax.random.uniform(k, ()) + x

        return jax.lax.scan(body, key, xs)

    closed = jax.make_jaxpr(bad)(
        jax.random.PRNGKey(0), jnp.zeros((6,), jnp.float32)
    )
    findings, _ = key_lineage(closed, {"protocol": [0]}, "bad-carry")
    assert any(
        "threaded unchanged" in f.message and f.severity == "error"
        for f in findings
    ), [str(f) for f in findings]

    # the sanctioned carry pattern: split per iteration — clean
    def ok(key, xs):
        def body(k, x):
            k, sub = jax.random.split(k)
            return k, jax.random.uniform(sub, ()) + x

        return jax.lax.scan(body, key, xs)

    closed = jax.make_jaxpr(ok)(
        jax.random.PRNGKey(0), jnp.zeros((6,), jnp.float32)
    )
    findings, _ = key_lineage(closed, {"protocol": [0]}, "ok-carry")
    assert findings == [], [str(f) for f in findings]


def test_cond_branch_draws_not_reuse():
    # mutually exclusive branches each drawing the same key once is ONE
    # draw at runtime — must not be flagged; a single branch drawing
    # twice still must be
    def ok(pred, key):
        return jax.lax.cond(
            pred,
            lambda k: jax.random.uniform(k, (2,)),
            lambda k: jax.random.normal(k, (2,)),
            key,
        )

    closed = jax.make_jaxpr(ok)(jnp.bool_(True), jax.random.PRNGKey(0))
    findings, summary = key_lineage(closed, {"protocol": [1]}, "ok-cond")
    assert [f for f in findings if "prng-reuse" in f.message] == []
    assert summary["roots"]["protocol"] == 1

    def bad(pred, key):
        def left(k):
            return jax.random.uniform(k, (2,)) + jax.random.normal(k, (2,))

        return jax.lax.cond(pred, left, lambda k: jax.random.uniform(k, (2,)), key)

    closed = jax.make_jaxpr(bad)(jnp.bool_(True), jax.random.PRNGKey(0))
    findings, _ = key_lineage(closed, {"protocol": [1]}, "bad-cond")
    assert any("prng-reuse" in f.message for f in findings)


def test_scan_threaded_key_lineage():
    # a per-tick key row sliced from a [T, 2] schedule inside a scan —
    # the entry points' shape — must stay clean and count its draws
    def ok(init, keys):
        def body(c, key):
            return c + jax.random.uniform(key, c.shape), c.sum()

        return jax.lax.scan(body, init, keys)

    keys = jax.random.split(jax.random.PRNGKey(0), 6)
    closed = jax.make_jaxpr(ok)(jnp.zeros((4,), jnp.float32), keys)
    findings, summary = key_lineage(closed, {"protocol": [1]}, "ok-scan")
    assert findings == []
    assert summary["roots"]["protocol"] >= 1


# ---------------------------------------------------------------------------
# contract 5: temporary-tensor census
# ---------------------------------------------------------------------------


def test_census_lists_nxn_intermediate():
    n = 32

    def prog(a):
        big = a[:, None] * a[None, :]  # the [N, N] temporary
        return big.sum()

    closed = jax.make_jaxpr(prog)(jnp.arange(n, dtype=jnp.float32))
    rows = temp_census(closed, dims={"N": n}, min_elems=n * n, entry="fx")
    assert rows, "census missed the [N, N] intermediate"
    tags = {r["tag"] for r in rows}
    assert "NxN" in tags
    for r in rows:
        assert r["dtype"] and r["primitive"] and r["elems_each"] >= n * n


def test_census_ambiguous_dim_tagged_with_both_names():
    # n == capacity at small fixture shapes: the tag must keep every
    # candidate name, not silently pick one
    n = 16

    def prog(a):
        return (a[:, None] * a[None, :]).sum()

    closed = jax.make_jaxpr(prog)(jnp.arange(n, dtype=jnp.float32))
    rows = temp_census(
        closed, dims={"N": n, "C": n}, min_elems=n * n, entry="fx"
    )
    assert rows and all("N|C" in r["tag"] for r in rows), rows


def test_census_threshold_respected():
    def prog(a):
        return (a[:, None] * a[None, :]).sum()

    closed = jax.make_jaxpr(prog)(jnp.arange(8, dtype=jnp.float32))
    # min_elems above 8x8 and N declared as something else: no rows
    rows = temp_census(closed, dims={"N": 999}, min_elems=1000, entry="fx")
    assert rows == []


# ---------------------------------------------------------------------------
# the clean lane: fixture + real entry point
# ---------------------------------------------------------------------------


def test_clean_program_zero_findings(monkeypatch):
    @partial(jax.jit, donate_argnums=(0,))
    def clean(carry, keys):
        def body(c, key):
            return c + jax.random.uniform(key, c.shape), c.sum()

        out, ys = jax.lax.scan(body, carry, keys)
        return out, ys

    keys = jax.random.split(jax.random.PRNGKey(0), 4)
    args = (jnp.zeros((8,), jnp.float32), keys)
    built = _fixture_built(
        clean, args, donates=True, min_aliased=1,
        key_roots={"protocol": [1]}, name="clean",
    )
    monkeypatch.setitem(
        budgets.CARRY_BUDGETS, ("clean", "dense"), {"float32": 1}
    )
    from ringpop_tpu.analysis import contracts

    closed = contracts._trace(built)
    text, warns = contracts._lower_text(built)
    findings = []
    f1, hits = check_host_transfers(closed, built.name)
    f2, aliased = check_donation(built, text, warns)
    f3, _ = check_carry_dtypes(closed, built)
    f4, _ = key_lineage(closed, built.key_roots, built.name)
    findings = f1 + f2 + f3 + f4
    assert findings == [], [str(f) for f in findings]
    assert hits == 0 and aliased >= 1


def test_registry_swim_run_audits_clean():
    # the tier-1 representative of the CI audit job: the real dense
    # entry point at a tiny shape must satisfy every pinned contract
    report = audit_entry("swim_run", "dense", n=16, ticks=2)
    assert isinstance(report, EntryReport)
    assert [f for f in report.findings if f.severity != "info"] == [], [
        str(f) for f in report.findings
    ]
    assert report.aliased_outputs >= 1
    assert report.prng["roots"]["protocol"] > 0
    # the dense tick scan is found, with its pinned carry multiset
    assert any(report.carries.values())


def test_registry_builders_cover_declared_backends():
    # every registered (entry, backend) pair must at least BUILD — a
    # signature change in a model/scenario module breaks here first
    from ringpop_tpu.analysis.registry import iter_entries

    pairs = list(iter_entries())
    assert ("run_scenario", "delta") in pairs
    assert ("run_scenario+traffic", "dense") in pairs
    assert ("run_scenario+incident", "delta") in pairs
    built = build_entry("run_scenario", "dense", n=8, ticks=2)
    assert built.key_roots["protocol"]
    assert built.donates


@pytest.mark.slow
def test_full_registry_audits_clean():
    # the whole registry, both backends (the CI audit job's assertion,
    # kept out of the tier-1 wall)
    from ringpop_tpu.analysis.contracts import audit_all

    reports, findings = audit_all(n=32, ticks=3)
    assert len(reports) == 11  # + the (run_scenario+incident, *) pair
    bad = [f for f in findings if f.severity in ("warning", "error")]
    assert bad == [], [str(f) for f in bad]


@pytest.mark.slow
def test_delta_run_census_lists_nc_intermediates():
    # the acceptance shape: delta_run at n=4096 lists every >= [N, C]
    # intermediate with dtype + producing primitive
    report = audit_entry(
        "delta_run", "delta", n=4096, ticks=2, capacity=64,
        compile_programs=False,
    )
    assert report.census
    nc = [r for r in report.census if r["tag"] == "NxC"]
    assert nc, "no [N, C]-tagged rows at n=4096"
    for r in report.census:
        assert r["elems_each"] >= 4096 * 64 or "N" in r["tag"]
        assert r["dtype"] and r["primitive"]


# ---------------------------------------------------------------------------
# the AST lint layer
# ---------------------------------------------------------------------------


def test_lint_block_until_ready_flagged_and_pragma():
    src = "def drain(x):\n    return x.block_until_ready()\n"
    (f,) = lint_source(src, "lib.py", compiled_path=True)
    assert f.contract == "lint:RPL001" and "lib.py:2" in f.where
    src_ok = ("def drain(x):\n"
              "    return x.block_until_ready()  # audit: allow=RPL001\n")
    assert lint_source(src_ok, "lib.py", compiled_path=True) == []
    # the pragma may land on ANY line a wrapped call spans
    src_wrapped = ("def drain(x, y):\n"
                   "    return x.block_until_ready(\n"
                   "    )  # audit: allow=RPL001\n")
    assert lint_source(src_wrapped, "lib.py", compiled_path=True) == []
    # host-side modules are exempt
    assert lint_source(src, "host.py", compiled_path=False) == []


def test_lint_np_on_traced_flagged():
    src = ("import numpy as np\n"
           "def step_impl(state):\n"
           "    return np.asarray(state)\n")
    (f,) = lint_source(src, "m.py")
    assert f.contract == "lint:RPL002" and "step_impl" in f.message
    # host code: same call, no traced context, no finding
    host = "import numpy as np\ndef reader(x):\n    return np.asarray(x)\n"
    assert lint_source(host, "m.py") == []


def test_lint_traced_bool_if_flagged():
    src = ("import jax.numpy as jnp\n"
           "def step_impl(mask):\n"
           "    if jnp.any(mask):\n"
           "        return 1\n"
           "    return 0\n")
    (f,) = lint_source(src, "m.py")
    assert f.contract == "lint:RPL003"
    # static-shape branches stay legal
    ok = ("def step_impl(ev):\n"
          "    if ev.shape[0]:\n"
          "        return 1\n"
          "    return 0\n")
    assert lint_source(ok, "m.py") == []


def test_lint_wallclock_in_traced_flagged():
    src = ("import time\n"
           "def body_impl(c):\n"
           "    return c + time.time()\n")
    (f,) = lint_source(src, "m.py")
    assert f.contract == "lint:RPL004"
    # host code wall-clock reads are fine
    host = "import time\ndef stamp():\n    return time.time()\n"
    assert lint_source(host, "m.py") == []


def test_lint_nested_scan_body_inherits_traced_context():
    src = ("import numpy as np\n"
           "def run_impl(xs):\n"
           "    def body(c, x):\n"
           "        return c + np.asarray(x), c\n"
           "    return body\n")
    (f,) = lint_source(src, "m.py")
    assert f.contract == "lint:RPL002"


def test_lint_library_tree_clean():
    # the shipped compiled-path modules must lint clean (the CI audit
    # job's lint assertion)
    from pathlib import Path

    from ringpop_tpu.analysis.lint import lint_paths

    import ringpop_tpu

    findings = lint_paths(Path(ringpop_tpu.__file__).parent)
    assert findings == [], [str(f) for f in findings]


# ---------------------------------------------------------------------------
# ledger recompile attribution (obs/ledger.py)
# ---------------------------------------------------------------------------


def test_ledger_recompile_attribution_names_static():
    led = DispatchLedger().enable(None)
    f = jax.jit(lambda x, k: x * k, static_argnames=("k",))
    led.dispatch("prog", f, jnp.zeros((4,), jnp.float32), k=2)
    led.dispatch("prog", f, jnp.zeros((4,), jnp.float32), k=2)
    led.dispatch("prog", f, jnp.zeros((4,), jnp.float32), k=3)
    led.dispatch("prog", f, jnp.zeros((8,), jnp.float32), k=3)
    rows = led.rows
    assert [r["cold"] for r in rows] == [True, False, True, True]
    # warm row: same sig as its cold row, no cause
    assert rows[1]["sig"] == rows[0]["sig"]
    assert "recompile_cause" not in rows[0]
    assert "recompile_cause" not in rows[1]
    assert rows[2]["recompile_cause"] == ["static 'k' changed: 2 -> 3"]
    assert rows[3]["recompile_cause"] == [
        "arg leaf 0 shape changed: (4,) -> (8,)"
    ]
    # exactly one cold per signature
    sigs = [r["sig"] for r in rows if r["cold"]]
    assert len(sigs) == len(set(sigs))


def test_audit_cli_smoke(capsys):
    # the CLI lane end to end on a tiny entry (no SystemExit = exit 0)
    from ringpop_tpu.analysis.cli import main

    main(["--entry", "swim_run", "--n", "16", "--ticks", "2", "--no-lint"])
    out = capsys.readouterr().out
    assert "swim_run [dense]" in out and "clean" in out
    assert "lint skipped" in out


def test_audit_cli_rejects_unknown_entry():
    # a typo'd selection must fail CLOSED, not audit 0 programs
    from ringpop_tpu.analysis.cli import main

    with pytest.raises(SystemExit) as exc:
        main(["--entry", "delta_runn", "--no-lint"])
    assert "unknown entry point" in str(exc.value)
    with pytest.raises(SystemExit) as exc:
        main(["--entry", "recv_merge_pallas", "--backend", "delta",
              "--no-lint"])
    assert "matches no registered" in str(exc.value)

"""Observability subsystem (ringpop_tpu/obs/): emitters, dispatch
ledger, profiler scopes, and the Trace→stats bridge.

Covers the ISSUE-5 acceptance triangle on CPU:
  (a) one ``run_scenario`` leaves a ledger entry with compile/execute
      times and peak-bytes populated;
  (b) a bridged scenario's key set is a superset of the reference-
      parity bridge keys, and those keys are exactly ones the host
      facade itself emits (capture-emitter cross-check);
  (c) the protocol-phase named scopes survive into compiled HLO, and
      ``profile_trace`` writes a loadable trace directory.
"""

from __future__ import annotations

import json
import socket

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ringpop_tpu.harness import Cluster
from ringpop_tpu.harness import test_ringpop as make_node  # not a test
from ringpop_tpu.models import swim_sim as sim
from ringpop_tpu.models.cluster import SimCluster
from ringpop_tpu.obs import annotate
from ringpop_tpu.obs import bridge
from ringpop_tpu.obs.emitters import (
    CaptureEmitter,
    JsonlEmitter,
    StatsdEmitter,
    make_emitter,
)
from ringpop_tpu.obs.ledger import DispatchLedger, default_ledger, summarize
from ringpop_tpu.scenarios.trace import Trace


@pytest.fixture
def ledger():
    """The process-global ledger, enabled in-memory and restored."""
    led = default_ledger()
    led.enable(None)
    led.clear()
    yield led
    led.disable()
    led.clear()


# ---------------------------------------------------------------------------
# emitters
# ---------------------------------------------------------------------------


def test_statsd_line_protocol():
    srv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    srv.bind(("127.0.0.1", 0))
    srv.settimeout(5.0)
    host, port = srv.getsockname()
    emitter = StatsdEmitter(host, port)
    emitter.increment("ringpop.h.ping.send")
    emitter.increment("ringpop.h.ping.send", 3)
    emitter.gauge("ringpop.h.checksum", 123456)
    emitter.timing("ringpop.h.ping", 12.5)
    lines = [srv.recv(1024).decode() for _ in range(4)]
    assert lines == [
        "ringpop.h.ping.send:1|c",
        "ringpop.h.ping.send:3|c",
        "ringpop.h.checksum:123456|g",
        "ringpop.h.ping:12.5|ms",
    ]
    emitter.close()
    srv.close()


def test_jsonl_emitter_roundtrip(tmp_path):
    path = str(tmp_path / "stats.jsonl")
    emitter = JsonlEmitter(path)
    emitter.increment("a.b", 2)
    emitter.gauge("a.c", 7)
    emitter.timing("a.d", 1.5)
    emitter.close()
    rows = [json.loads(line) for line in open(path)]
    assert [(r["type"], r["key"], r.get("value")) for r in rows] == [
        ("increment", "a.b", 2),
        ("gauge", "a.c", 7),
        ("timing", "a.d", 1.5),
    ]
    emitter.close()  # idempotent (shared-emitter destroy contract)


def test_make_emitter_specs(tmp_path):
    assert isinstance(make_emitter("capture"), CaptureEmitter)
    statsd = make_emitter("statsd://127.0.0.1:8125")
    assert isinstance(statsd, StatsdEmitter) and statsd.port == 8125
    statsd.close()
    assert isinstance(make_emitter("udp://localhost:9125"), StatsdEmitter)
    jl = make_emitter(str(tmp_path / "s.jsonl"))
    assert isinstance(jl, JsonlEmitter)
    jl.close()
    with pytest.raises(ValueError):
        make_emitter("statsd://noport")


# ---------------------------------------------------------------------------
# RingPop facade: statsd slot end to end, key cache, timing percentiles
# ---------------------------------------------------------------------------


def test_ringpop_stat_key_cache_and_emitter():
    cap = CaptureEmitter()
    rp = make_node(statsd=cap)
    rp.stat("increment", "ping.send")
    rp.stat("increment", "ping.send")
    # key-cache fast path (index.js:561-575): the fq key is built once
    assert rp.stat_keys["ping.send"] == f"{rp.stat_prefix}.ping.send"
    assert cap.counters[f"{rp.stat_prefix}.ping.send"] == 2
    for ms in (10, 20, 30, 40):
        rp.stat("timing", "ping", ms)
    rp.stat("timing", "ping-req", 55)
    stats = rp.get_stats()
    ping = stats["protocol"]["ping"]
    assert ping["count"] == 4
    assert ping["min"] == 10 and ping["max"] == 40
    assert ping["p95"] >= ping["median"] >= ping["min"]
    assert stats["protocol"]["pingReq"]["count"] == 1
    # the timing also reached the emitter itself
    assert cap.timings[f"{rp.stat_prefix}.ping"] == [10, 20, 30, 40]


def test_ringpop_statsd_string_spec(tmp_path):
    path = str(tmp_path / "node.jsonl")
    rp = make_node(statsd=path)
    rp.stat("increment", "ping.send")
    rp.destroy()  # closes (flushes) the file-backed emitter
    keys = {json.loads(line)["key"] for line in open(path)}
    assert f"{rp.stat_prefix}.ping.send" in keys


# ---------------------------------------------------------------------------
# dispatch ledger
# ---------------------------------------------------------------------------


def test_ledger_jsonl_roundtrip_and_summary(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    led = DispatchLedger(path)
    for i in range(3):
        led.record(
            {
                "program": "swim_run",
                "backend": "dense",
                "platform": "cpu",
                "n": 64,
                "ticks": 8,
                "replicas": 1,
                "cold": i == 0,
                "compile_s": 1.5 if i == 0 else 0.0,
                "execute_s": 0.01 * (i + 1),
                "peak_bytes": 1000,
            }
        )
    rows = DispatchLedger.load_rows(path)
    assert len(rows) == 3 and all("ts" in r for r in rows)
    (group,) = summarize(rows)
    assert group["dispatches"] == 3 and group["cold"] == 1
    assert group["compile_s_total"] == pytest.approx(1.5)
    assert group["peak_bytes_max"] == 1000
    assert group["execute_s"]["count"] == 3


def test_ledger_summarizer_cli(tmp_path, capsys):
    from ringpop_tpu.obs import ledger as ledger_mod

    path = str(tmp_path / "ledger.jsonl")
    DispatchLedger(path).record(
        {"program": "p", "backend": "dense", "platform": "cpu", "n": 8,
         "ticks": 1, "replicas": 1, "cold": True, "compile_s": 0.5,
         "execute_s": 0.01, "peak_bytes": 2048}
    )
    ledger_mod.main([path])
    out = capsys.readouterr().out
    assert "1 dispatches" in out and "p [dense/cpu]" in out


def test_ledger_dispatch_cold_warm_parity(ledger):
    @jax.jit
    def double(x):
        return x * 2

    x = jnp.arange(8)
    out_cold = ledger.dispatch("double", double, x, _meta={"n": 8})
    out_warm = ledger.dispatch("double", double, x, _meta={"n": 8})
    assert np.array_equal(np.asarray(out_cold), np.arange(8) * 2)
    assert np.array_equal(np.asarray(out_warm), np.arange(8) * 2)
    rows = [r for r in ledger.rows if r["program"] == "double"]
    assert [r["cold"] for r in rows] == [True, False]
    assert rows[0]["compile_s"] > 0 and rows[1]["compile_s"] == 0
    assert all(r["execute_s"] > 0 for r in rows)


def test_ledger_disabled_is_call_through():
    led = DispatchLedger()  # never enabled, no env activation (explicit)
    led._explicit = True
    calls = []

    def fake(*args, **kwargs):
        calls.append((args, kwargs))
        return "out"

    assert led.dispatch("fake", fake, 1, k=2) == "out"
    assert calls == [((1,), {"k": 2})] and led.rows == []


def test_recv_merge_pallas_host_call_ledgered(ledger):
    from ringpop_tpu.ops.recv_merge_pallas import recv_merge_pallas

    n = 8
    t_safe = jnp.zeros((n,), jnp.int32)
    fwd_ok = jnp.ones((n,), bool)
    claims = jnp.ones((n, n), jnp.int32)
    in_key, inbound = recv_merge_pallas(t_safe, fwd_ok, claims, interpret=True)
    assert int(inbound[0]) == n
    (row,) = [r for r in ledger.rows if r["program"] == "recv_merge_pallas"]
    assert row["n"] == n and row["cold"]


# ---------------------------------------------------------------------------
# acceptance (a) + (b): one run_scenario -> ledger entry + bridged keys
# ---------------------------------------------------------------------------


def test_run_scenario_ledger_and_bridge_smoke(ledger):
    cap = CaptureEmitter()
    cluster = SimCluster(
        8, sim.SwimParams(loss=0.0, suspicion_ticks=3), seed=1,
        stats_emitter=cap,
    )
    trace = cluster.run_scenario(
        {"ticks": 6, "events": [{"at": 1, "op": "kill", "node": 7}]}
    )
    assert trace.ticks == 6

    # (a) the dispatch-ledger entry, compile/execute + footprint populated
    (row,) = [r for r in ledger.rows if r["program"] == "run_scenario"]
    assert row["backend"] == "dense" and row["n"] == 8 and row["ticks"] == 6
    assert row["cold"] is True
    assert row["compile_s"] > 0 and row["execute_s"] > 0
    assert row["peak_bytes"] > 0 and row["argument_bytes"] > 0

    # (b) the emitted key namespace is a superset of the reference-
    # parity bridge keys, under the sim prefix
    suffixes = cap.suffixes(bridge.DEFAULT_PREFIX)
    missing = [k for k in bridge.REFERENCE_KEYS if k not in suffixes]
    assert not missing, f"bridge keys missing from stream: {missing}"
    # replayed counters match the trace they came from
    fq = f"{bridge.DEFAULT_PREFIX}.ping.send"
    assert cap.counters[fq] == int(np.asarray(trace.metrics["pings_sent"]).sum())


def test_sim_cluster_tick_bridges_counters(ledger):
    cap = CaptureEmitter()
    cluster = SimCluster(8, sim.SwimParams(loss=0.0), seed=2,
                         stats_emitter=cap)
    metrics = cluster.tick()
    fq = f"{bridge.DEFAULT_PREFIX}.ping.send"
    assert cap.counters[fq] == metrics["pings_sent"]
    assert f"{bridge.DEFAULT_PREFIX}.num-members" in cap.gauges
    (row,) = [r for r in ledger.rows if r["program"] == "swim_step"]
    assert row["n"] == 8 and row["compile_s"] > 0


def test_emit_counters_multi_tick_entry_is_gauges_only():
    """A multi-tick metrics entry carries only the LAST tick's counters
    (swim_run discards the rest), so the bridge must not replay that
    sample as the whole span's increments; gauges still update
    (last-write-wins matches "latest tick")."""
    cap = CaptureEmitter()
    sink = bridge.StatSink(cap, "ringpop.t")
    metrics = {"pings_sent": 7, "full_syncs": 1, "faulty_declared": 0,
               "ping_changes_applied": 2, "ticks": 25}
    bridge.emit_counters(metrics, sink, live=6)
    assert cap.counters["ringpop.t.ping.send"] == 0
    assert cap.counters["ringpop.t.full-sync"] == 0
    assert cap.gauges["ringpop.t.changes.apply"] == 2
    assert cap.gauges["ringpop.t.num-members"] == 6
    # the same entry with ticks=1 replays exactly
    bridge.emit_counters(dict(metrics, ticks=1), sink, live=6)
    assert cap.counters["ringpop.t.ping.send"] == 7


def test_destroy_leaves_shared_emitter_open(tmp_path):
    """destroy() closes only emitters the node built from a spec string
    — a caller-injected emitter may be shared by other live nodes."""
    shared = JsonlEmitter(str(tmp_path / "shared.jsonl"))
    node_a = make_node(host_port="10.0.0.1:3000", statsd=shared)
    node_b = make_node(host_port="10.0.0.2:3000", statsd=shared)
    node_a.destroy()
    node_b.stat("increment", "ping.send")  # must not raise on closed file
    shared.close()
    assert shared.emitted >= 1
    node_b.destroy()


# ---------------------------------------------------------------------------
# bridge key parity against the host facade's own emissions
# ---------------------------------------------------------------------------


def test_bridge_keys_are_exactly_host_emitted_keys():
    """Every reference-parity key the bridge emits must be a key the
    host RingPop stack itself emits (same suffix under the node's
    ``ringpop.<host_port>`` prefix) — the namespace contract that makes
    simulated metrics drop into real dashboards."""
    cap = CaptureEmitter()
    c = Cluster(size=3, statsd=cap)
    c.bootstrap_all(run=False)
    assert c.run_until_converged(60000)
    c.kill(2)
    c.run(25000)  # ping.send/recv, ping-req.send, suspect -> faulty
    # manufacture a full sync: node 1 knows an extra member but has no
    # changes left to piggyback, so a ping to it answers with full-sync
    c.nodes[1].membership.make_alive("10.99.0.1:9999", 1)
    c.nodes[1].dissemination.clear_changes()
    c.run(10000)
    suffixes = set()
    for node in c.nodes:
        suffixes |= cap.suffixes(node.stat_prefix)
    missing = [k for k in bridge.REFERENCE_KEYS if k not in suffixes]
    assert not missing, f"bridge keys the host never emitted: {missing}"
    c.destroy_all()


def test_replay_trace_synthetic_counts():
    ticks = 4
    trace = Trace(
        metrics={
            "pings_sent": np.array([3, 3, 3, 3]),
            "acks": np.array([3, 2, 3, 3]),
            "ping_reqs": np.array([0, 1, 0, 0]),
            "full_syncs": np.array([0, 0, 1, 0]),
            "suspects_declared": np.array([0, 1, 0, 0]),
            "faulty_declared": np.array([0, 0, 1, 0]),
            "ping_changes_applied": np.array([0, 2, 1, 0]),
            "ack_changes_applied": np.array([0, 1, 0, 0]),
            "pingreq_changes_applied": np.array([0, 0, 0, 0]),
        },
        converged=np.array([True, False, False, True]),
        live=np.array([4, 3, 3, 3]),
        loss=np.zeros(ticks, np.float32),
        n=4,
        backend="dense",
    )
    cap = CaptureEmitter()
    bridge.replay_trace(trace, cap, prefix="ringpop.t", checksum=42)
    assert cap.counters["ringpop.t.ping.send"] == 12
    assert cap.counters["ringpop.t.ping.recv"] == 11
    assert cap.counters["ringpop.t.ping-req.send"] == 1
    assert cap.counters["ringpop.t.full-sync"] == 1
    assert cap.counters["ringpop.t.membership-update.suspect"] == 1
    assert cap.counters["ringpop.t.membership-update.faulty"] == 1
    # tick-0 baseline only: live never rises afterwards
    assert cap.counters["ringpop.t.membership-update.alive"] == 4
    assert cap.gauges["ringpop.t.num-members"] == 3
    assert cap.gauges["ringpop.t.checksum"] == 42
    # zero-count keys still declared (the superset guarantee)
    suffixes = cap.suffixes("ringpop.t")
    assert set(bridge.REFERENCE_KEYS) <= suffixes


# ---------------------------------------------------------------------------
# acceptance (c): profiler scopes + trace directory
# ---------------------------------------------------------------------------


def test_protocol_phase_scopes_in_compiled_hlo():
    state = sim.init_state(8)
    net = sim.make_net(8)
    params = sim.SwimParams(loss=0.01)
    txt = (
        sim.swim_step.lower(state, net, jax.random.PRNGKey(0), params)
        .compile()
        .as_text()
    )
    for scope_name in (
        "swim.phase01_select",
        "swim.recv_merge",
        "swim.merge_incoming",
        "swim.pingreq",
        "swim.pingreq_5a",
        "swim.expiry",
    ):
        assert scope_name in txt, f"scope {scope_name} missing from HLO"


def test_scope_composes_inside_and_outside_tracing():
    """`annotate.scope` is a plain name-stack push: legal around
    concrete ops and inside jit tracing alike.  The end-to-end
    profiler-trace-directory check (start/stop_trace costs ~15 s of
    xplane serialization on this host) lives in the CI obs-smoke step
    (tools/obs_smoke.sh), which drives `tick-cluster --profile-dir`
    for real."""
    with annotate.scope("swim.outer"):
        x = jnp.ones((4,)) + 1
    assert float(x[0]) == 2.0

    @annotate.scoped("swim.decorated")
    def body(v):
        return v * 3

    y = jax.jit(body)(jnp.ones((4,)))
    assert float(y[0]) == 3.0


# ---------------------------------------------------------------------------
# /admin endpoints
# ---------------------------------------------------------------------------


def test_admin_ledger_endpoint(ledger):
    from ringpop_tpu.server import RingpopServer

    class FakeChannel:
        def register(self, endpoints):
            self.endpoints = endpoints

    ledger.record(
        {"program": "swim_run", "backend": "dense", "platform": "cpu",
         "n": 8, "ticks": 4, "replicas": 1, "cold": True,
         "compile_s": 0.2, "execute_s": 0.01, "peak_bytes": 64}
    )
    rp = make_node()
    server = RingpopServer(rp, FakeChannel())
    results = []
    server.admin_ledger(None, None, "", lambda err, r1, r2: results.append((err, r2)))
    err, body = results[0]
    assert err is None
    payload = json.loads(body)
    assert payload["enabled"] and payload["dispatches"] == 1
    assert payload["summary"][0]["program"] == "swim_run"

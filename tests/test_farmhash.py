"""FarmHash32 parity: pure-Python vs C vs JAX vs Google's farmhashmk.

The membership checksum (lib/membership.js:41-64) and ring placement
(lib/ring.js:54-57) in the reference are farmhash32-based; every backend here
must agree bit-for-bit.
"""

import glob
import os
import random
import subprocess

import numpy as np
import pytest

from ringpop_tpu.ops import farmhash
from ringpop_tpu.ops.farmhash import farmhash32, farmhash32_py

# Golden values produced by Google's farmhashmk::Hash32 (Fingerprint32), via
# the TensorFlow-vendored FarmHash source (tools/build_verify_farmhash.sh).
KNOWN_VECTORS = {
    b"": 3696677242,
    b"a": 1016544589,
    b"test": 1633095781,
    b"hello world": 430397466,
    b"10.0.0.1:3000alive1414142122274": 1760338415,
    b"10.0.0.1:3000alive1414142122274;10.0.0.2:3000alive1414142122275": 128316843,
}


def random_cases(seed=1234, max_small=200):
    rng = random.Random(seed)
    cases = list(KNOWN_VECTORS)
    for n in list(range(0, 130)) + [max_small, 1000, 4096]:
        cases.append(bytes(rng.randrange(256) for _ in range(n)))
    return cases


def test_known_vectors_python():
    for data, expect in KNOWN_VECTORS.items():
        assert farmhash32_py(data) == expect


def test_known_vectors_dispatch():
    for data, expect in KNOWN_VECTORS.items():
        assert farmhash32(data) == expect


@pytest.mark.skipif(not farmhash.has_native(), reason="C extension unavailable")
def test_c_matches_python():
    for data in random_cases():
        assert farmhash._farmhash32_py(data) == farmhash._lib.rp_farmhash32(
            data, len(data)
        ), f"len={len(data)}"


@pytest.mark.skipif(not farmhash.has_native(), reason="C extension unavailable")
def test_c_batch():
    cases = random_cases(seed=7)
    buf = np.frombuffer(b"".join(cases), dtype=np.uint8)
    lens = np.array([len(c) for c in cases], dtype=np.int64)
    offsets = np.concatenate([[0], np.cumsum(lens)[:-1]])
    out = farmhash.farmhash32_batch(buf, offsets, lens)
    for c, h in zip(cases, out):
        assert farmhash32_py(c) == int(h)


def test_membership_checksum_packed():
    # addr\0status\0inc\0 per member, pre-sorted by address
    members = [
        ("10.0.0.1:3000", "alive", 1414142122274),
        ("10.0.0.2:3000", "alive", 1414142122275),
    ]
    packed = b"".join(
        f"{a}\x00{s}\x00{i}\x00".encode() for (a, s, i) in members
    )
    got = farmhash.membership_checksum_packed(packed, 2)
    expect = KNOWN_VECTORS[
        b"10.0.0.1:3000alive1414142122274;10.0.0.2:3000alive1414142122275"
    ]
    assert got == expect


def test_jax_matches_python():
    jnp = pytest.importorskip("jax.numpy")
    from ringpop_tpu.ops.farmhash_jax import farmhash32_batch_jax

    cases = [c for c in random_cases(seed=99) if len(c) <= 200]
    pad = 256
    bufs = np.zeros((len(cases), pad), dtype=np.uint8)
    lens = np.zeros(len(cases), dtype=np.int32)
    for i, c in enumerate(cases):
        bufs[i, : len(c)] = np.frombuffer(c, dtype=np.uint8)
        lens[i] = len(c)
    out = np.asarray(farmhash32_batch_jax(jnp.asarray(bufs), jnp.asarray(lens)))
    for c, h in zip(cases, out):
        assert farmhash32_py(c) == int(h), f"len={len(c)}"


TF_HEADERS = glob.glob(
    "/opt/venv/lib/python*/site-packages/tensorflow/include/external/"
    "farmhash_gpu_archive/src/farmhash_gpu.h"
)


@pytest.mark.skipif(not TF_HEADERS, reason="no TF farmhash")
def test_against_google_farmhash(tmp_path):
    """Bit-parity against Google's own farmhashmk source."""
    binary = tmp_path / "verify_farmhash"
    script = os.path.join(os.path.dirname(__file__), "..", "tools",
                          "build_verify_farmhash.sh")
    subprocess.run(["bash", script, str(binary)], check=True, timeout=120)
    cases = random_cases(seed=31337)
    inp = "\n".join(c.hex() for c in cases) + "\n"
    out = subprocess.run(
        [str(binary)], input=inp, capture_output=True, text=True, check=True
    ).stdout
    for c, line in zip(cases, out.strip().split("\n")):
        ours, golden = map(int, line.split())
        assert ours == golden, f"len={len(c)}"
        assert farmhash32_py(c) == golden


def test_view_checksums_native_matches_row_checksum():
    """The threaded C batch kernel (rp_view_checksums) must be
    bit-identical to the per-row path for random views."""
    import numpy as np
    from ringpop_tpu.models import checksum as cksum
    from ringpop_tpu.models.swim_sim import NONE

    from ringpop_tpu.models.swim_sim import ALIVE, FAULTY, LEAVE, SUSPECT

    n = 97
    book = cksum.AddressBook(cksum.default_addresses(n))
    rng = np.random.default_rng(7)
    vs = rng.choice(
        [ALIVE, SUSPECT, FAULTY, LEAVE, NONE],
        size=(n, n),
        p=[0.5, 0.15, 0.15, 0.05, 0.15],
    ).astype(np.int8)
    vi = rng.integers(0, 1 << 30, size=(n, n), dtype=np.int32)
    base = 1_400_000_000_000
    batched = cksum.view_checksums(book, vs, vi, base)
    for i in (0, 1, 13, 96):
        assert batched[i] == cksum.row_checksum(book, vs[i], vi[i], base)
    # Empty view row hashes the empty string deterministically.
    vs_empty = np.full((n, n), NONE, dtype=np.int8)
    empty = cksum.view_checksums(book, vs_empty, vi, base, [0])
    assert empty[0] == cksum.row_checksum(book, vs_empty[0], vi[0], base)

"""Traced protocol knobs (``sim.SwimKnobs``): bit-parity with the
compile-time programs, ``run_sweep(param_axes=...)``, and validation.

Fast lane: the host-side knob helpers and every validation rejection
(range, int8 digit budget at the axis max, backend/scenario
composition — all pre-key-draw, so a failed call never desyncs the
cluster key), ONE combo traced-vs-legacy parity run per backend plus
the damping-threshold knobs, the ``run_scenario(param_knobs=...)``
trajectory contract, replica parity for a dense ``param_axes`` sweep,
and the compile-once contract (a second knob grid re-dispatches the
SAME executable — ledger row warm, no ``recompile_cause``).

Slow lane: the per-knob acceptance grid — each traced knob
individually, traced program == legacy compile-time program at equal
values, on BOTH backends, plus delta-backend sweep replica parity.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ringpop_tpu.models import swim_delta as sdelta
from ringpop_tpu.models import swim_sim as sim
from ringpop_tpu.models.cluster import SimCluster
from ringpop_tpu.obs.ledger import default_ledger
from ringpop_tpu.scenarios import runner, sweep
from ringpop_tpu.scenarios.spec import ScenarioSpec

N = 12
TICKS = 20
SPEC = ScenarioSpec.from_dict(
    {
        "ticks": TICKS,
        "events": [
            {"at": 3, "op": "kill", "node": 3},
            {"at": 8, "op": "loss", "p": 0.05},
            {"at": 14, "op": "loss", "p": 0.0},
        ],
    }
)


@pytest.fixture
def ledger():
    led = default_ledger()
    led.enable(None)
    led.clear()
    yield led
    led.disable()
    led.clear()


def _eq_tree(a, b) -> bool:
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        bool((np.asarray(x) == np.asarray(y)).all()) for x, y in zip(la, lb)
    )


def _dense_run(params, knobs, *, n=8, ticks=14, damping=False):
    st = sim.init_state(n, damping=damping)
    net = sim.NetState(
        up=jnp.ones((n,), bool).at[3].set(False),
        responsive=jnp.ones((n,), bool),
        adj=None,
    )
    return sim.swim_run(
        st, net, jax.random.PRNGKey(0), params, ticks=ticks, knobs=knobs
    )


def _delta_run(params, knobs, *, n=8, ticks=14):
    st = sdelta.init_delta(n, capacity=16)
    net = sim.NetState(
        up=jnp.ones((n,), bool).at[3].set(False),
        responsive=jnp.ones((n,), bool),
        adj=None,
    )
    dp = sdelta.DeltaParams(swim=params)
    return sdelta.delta_run(
        st, net, jax.random.PRNGKey(0), dp, ticks=ticks, knobs=knobs
    )


def _assert_dense_parity(params, overrides=None, damping=False):
    knobs = sim.swim_knob_arrays(params, overrides)
    s1, m1 = _dense_run(params, None, damping=damping)
    s2, m2 = _dense_run(params, knobs, damping=damping)
    assert _eq_tree(s1, s2) and _eq_tree(m1, m2)


def _assert_delta_parity(params, overrides=None):
    knobs = sim.swim_knob_arrays(params, overrides)
    s1, m1 = _delta_run(params, None)
    s2, m2 = _delta_run(params, knobs)
    assert _eq_tree(s1, s2) and _eq_tree(m1, m2)


# -- fast: host-side knob helpers and validation ----------------------------


def test_knob_values_and_arrays_roundtrip():
    p = sim.SwimParams(suspicion_ticks=7, piggyback_factor=4)
    vals = sim.swim_knob_values(p)
    assert vals["suspicion_ticks"] == 7 and vals["piggyback_factor"] == 4
    knobs = sim.swim_knob_arrays(p, {"suspicion_ticks": 11})
    assert int(knobs.suspicion_ticks) == 11
    assert knobs.suspicion_ticks.dtype == jnp.int32
    assert knobs.damp_suppress.dtype == jnp.float16
    with pytest.raises(ValueError, match="unknown traced swim knob"):
        sim.swim_knob_arrays(p, {"nope": 1})


def test_knob_range_guards():
    p = sim.SwimParams(ping_req_size=3)
    with pytest.raises(ValueError, match="int8 countdown"):
        sim.check_knob_value("suspicion_ticks", 127, p)
    with pytest.raises(ValueError, match="compiled capacity"):
        sim.check_knob_value("ping_req_size", 4, p)
    with pytest.raises(ValueError, match="phase_mod"):
        sim.check_knob_value("phase_mod", 0, p)
    with pytest.raises(ValueError, match="relay_full_sync"):
        sim.check_knob_value("relay_full_sync", 2, p)


def test_validate_params_names_offending_axis_value():
    """Satellite fix: the int8 digit budgets hold at the axis MAX, and
    the error names the replica whose value broke them."""
    p = sim.SwimParams()
    # scalar default passes, replica 2's swept value does not
    sim._validate_params(1000, p)
    with pytest.raises(ValueError, match=r"param_axes replica 2"):
        sim._validate_params(
            1000, p, knob_values={"piggyback_factor": [2, 3, 40]}
        )
    with pytest.raises(ValueError, match=r"param_axes replica 1"):
        sim._validate_params(
            16, p, knob_values={"suspicion_ticks": [9, 200]}
        )


def test_composition_guards():
    p = sim.SwimParams()
    ok = dict(backend="dense", period_active=False, damping=True)
    runner.validate_param_knobs(16, p, {"suspicion_ticks": [3, 9]}, **ok)
    with pytest.raises(ValueError, match="phase_mod"):
        runner.validate_param_knobs(
            16, p, {"phase_mod": [1, 2]},
            backend="dense", period_active=True, damping=False,
        )
    with pytest.raises(ValueError, match="full-sync exchange arm"):
        runner.validate_param_knobs(
            16, p, {"relay_full_sync": [0, 1]},
            backend="delta", period_active=False, damping=False,
        )
    with pytest.raises(ValueError, match="no damping plane"):
        runner.validate_param_knobs(
            16, p, {"damp_penalty": [100.0]},
            backend="delta", period_active=False, damping=False,
        )
    with pytest.raises(ValueError, match="damping=True"):
        runner.validate_param_knobs(
            16, p, {"damp_suppress": [900.0]},
            backend="dense", period_active=False, damping=False,
        )


def test_param_axes_rejections_burn_no_key():
    p = sim.SwimParams(suspicion_ticks=5)
    c = SimCluster(8, p, seed=0, backend="delta", capacity=8)
    key_before = np.asarray(c.key).copy()
    with pytest.raises(ValueError, match="full-sync"):
        c.run_sweep(SPEC, 2, param_axes={"relay_full_sync": [0, 1]})
    with pytest.raises(ValueError, match="unknown param axes"):
        c.run_sweep(SPEC, 2, param_axes={"bogus": [1, 2]})
    with pytest.raises(ValueError, match="one value per"):
        c.run_sweep(SPEC, 2, param_axes={"suspicion_ticks": [1, 2, 3]})
    np.testing.assert_array_equal(np.asarray(c.key), key_before)


# -- fast: one traced-vs-legacy parity per backend + damping ----------------


def test_dense_combo_traced_matches_legacy():
    _assert_dense_parity(
        sim.SwimParams(suspicion_ticks=9, piggyback_factor=6, phase_mod=2)
    )


@pytest.mark.slow
def test_delta_combo_traced_matches_legacy():
    _assert_delta_parity(
        sim.SwimParams(suspicion_ticks=9, piggyback_factor=6, phase_mod=2)
    )


@pytest.mark.slow
def test_dense_damping_knobs_match_legacy():
    _assert_dense_parity(
        sim.SwimParams(
            damp_penalty=300.0, damp_suppress=1200.0, damp_reuse=400.0
        ),
        damping=True,
    )


# -- fast: scenario/sweep plumbing ------------------------------------------


@pytest.mark.slow
def test_run_scenario_param_knobs_pins_legacy_trajectory():
    p = sim.SwimParams(suspicion_ticks=6)
    a = SimCluster(N, p, seed=4)
    t1 = a.run_scenario(SPEC)
    b = SimCluster(N, p, seed=4)
    t2 = b.run_scenario(SPEC, param_knobs={"suspicion_ticks": 6})
    np.testing.assert_array_equal(t1.converged, t2.converged)
    np.testing.assert_array_equal(t1.live, t2.live)
    for k in t1.metrics:
        np.testing.assert_array_equal(t1.metrics[k], t2.metrics[k])
    assert _eq_tree(a.state, b.state)


@pytest.mark.slow
def test_run_sweep_param_axes_replica_parity():
    """Replica r of a suspicion_ticks knob grid == a standalone
    run_scenario(param_knobs=replica_param_knobs(...)) from the same
    replica key (the replica_spec contract, knob plane)."""
    p = sim.SwimParams(suspicion_ticks=8)
    axes = {"suspicion_ticks": [4, 8, 12]}
    c = SimCluster(N, p, seed=7)
    strace = c.run_sweep(SPEC, 3, param_axes=axes)
    for r in (0, 2):
        c2 = SimCluster(N, p, seed=7)
        c2.key = jnp.asarray(strace.replica_keys[r])
        trace = c2.run_scenario(
            SPEC, param_knobs=sweep.replica_param_knobs(axes, r)
        )
        np.testing.assert_array_equal(strace.converged[r], trace.converged)
        np.testing.assert_array_equal(strace.live[r], trace.live)
        for k in trace.metrics:
            np.testing.assert_array_equal(
                strace.metrics[k][r], trace.metrics[k]
            )
    assert sweep.replica_param_knobs(axes, 1) == {"suspicion_ticks": 8}
    assert sweep.replica_param_knobs(None, 0) is None


@pytest.mark.slow
def test_param_axes_grid_is_one_compile(ledger):
    """The compile-once contract: a second knob grid (same shapes, new
    values) re-dispatches the SAME executable — warm, no recompile —
    and program_tag renames the ledger program per tuner arm."""
    p = sim.SwimParams(suspicion_ticks=8)
    c = SimCluster(N, p, seed=9)
    c.run_sweep(SPEC, 3, param_axes={"suspicion_ticks": [4, 8, 12]})
    c.run_sweep(SPEC, 3, param_axes={"suspicion_ticks": [5, 9, 13]})
    c.run_sweep(
        SPEC, 3, param_axes={"piggyback_factor": [2, 4, 8]},
        program_tag="arm0",
    )
    rows = [r for r in ledger.rows if r["program"] == "run_sweep"]
    assert [r["cold"] for r in rows] == [True, False]
    # the tagged arm is its own ledger program: cold on first dispatch,
    # but NOT a recompile of run_sweep (attribution stays within-arm)
    assert all(not r.get("recompile_cause") for r in ledger.rows)
    tagged = [r for r in ledger.rows if r["program"] == "run_sweep:arm0"]
    assert len(tagged) == 1 and tagged[0]["cold"]
    assert rows[0]["param_axes"] == ["suspicion_ticks"]


# -- slow: the per-knob acceptance grid -------------------------------------

PER_KNOB = [
    ("suspicion", sim.SwimParams(suspicion_ticks=7), None),
    ("piggyback", sim.SwimParams(piggyback_factor=4), None),
    ("phase_mod", sim.SwimParams(phase_mod=3), None),
    ("rfs_off", sim.SwimParams(relay_full_sync=False), None),
    ("ping_req", sim.SwimParams(ping_req_size=3), None),
]


@pytest.mark.slow
@pytest.mark.parametrize(
    "name,params,overrides", PER_KNOB
    + [("rfs_on", sim.SwimParams(relay_full_sync=True), None)],
    ids=lambda v: v if isinstance(v, str) else "",
)
def test_dense_per_knob_parity(name, params, overrides):
    _assert_dense_parity(params, overrides)


@pytest.mark.slow
@pytest.mark.parametrize(
    "name,params,overrides", PER_KNOB,
    ids=lambda v: v if isinstance(v, str) else "",
)
def test_delta_per_knob_parity(name, params, overrides):
    _assert_delta_parity(params, overrides)


@pytest.mark.slow
def test_delta_sweep_param_axes_replica_parity():
    p = sim.SwimParams(suspicion_ticks=8)
    axes = {"suspicion_ticks": [5, 10], "piggyback_factor": [3, 5]}

    def factory():
        return SimCluster(
            N, p, seed=3, backend="delta",
            capacity=N, wire_cap=N, claim_grid=3 * N * N,
        )

    c = factory()
    strace = c.run_sweep(SPEC, 2, param_axes=axes)
    for r in range(2):
        c2 = factory()
        c2.key = jnp.asarray(strace.replica_keys[r])
        trace = c2.run_scenario(
            SPEC, param_knobs=sweep.replica_param_knobs(axes, r)
        )
        np.testing.assert_array_equal(strace.converged[r], trace.converged)
        for k in trace.metrics:
            np.testing.assert_array_equal(
                strace.metrics[k][r], trace.metrics[k]
            )

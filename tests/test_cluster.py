"""Multi-node integration tests on the in-process cluster harness.

Reference: test/integration/{join,swim}-test.js via
test/lib/test-ringpop-cluster.js — N real RingPops in one process, with
pre-bootstrap sabotage hooks and deterministic time.
"""

from ringpop_tpu.harness import Cluster
from ringpop_tpu.member import Status


def converged_cluster(size=3, **kw):
    c = Cluster(size=size, **kw)
    c.bootstrap_all(run=False)
    assert c.run_until_converged(60000)
    return c


def test_single_node_cluster_short_circuit():
    """join-sender.js:69-73,212-221."""
    c = Cluster(size=1)
    results = c.bootstrap_all()
    assert results == [[]]
    assert c.nodes[0].is_ready


def test_two_and_three_node_join():
    for size in (2, 3):
        c = converged_cluster(size)
        for node in c.nodes:
            assert node.is_ready
            assert node.membership.get_member_count() == size
        assert len(c.checksum_groups()) == 1
        c.destroy_all()


def test_mega_cluster_25_nodes():
    """join-test.js:109-119."""
    c = converged_cluster(25)
    counts = {n.membership.get_member_count() for n in c.nodes}
    assert counts == {25}
    c.destroy_all()


def test_join_with_dead_seed():
    """Bad node in bootstrap list does not prevent join (enough live seeds
    remain to satisfy joinSize=3)."""
    c = Cluster(size=6)
    c.kill(5)
    c.bootstrap_all(run=False)
    c.scheduler.advance(30000)
    live = c.live_nodes()
    assert all(n.is_ready for n in live)


def test_deny_joins():
    """index.js:697-704 + join-handler.js:44-50: all seeds denying ->
    bootstrap fails with join-attempts/duration error."""

    def tap(nodes):
        for node in nodes[1:]:
            node.deny_joins()

    c = Cluster(size=3, tap=tap)
    results = [None, None, None]

    def cb(i):
        return lambda err, joined=None: results.__setitem__(i, err or joined)

    c.nodes[0].bootstrap(list(c.host_ports), cb(0))
    c.scheduler.advance(150000)
    err = results[0]
    assert err is not None
    assert getattr(err, "type", "").startswith("ringpop.join-")


def test_kill_suspect_faulty_cycle():
    c = converged_cluster(5)
    victim = c.host_ports[4]
    c.kill(4)
    c.run(7000)
    statuses = {
        n.host_port: n.membership.find_member_by_address(victim).status
        for n in c.live_nodes()
    }
    assert all(s in (Status.suspect, Status.faulty) for s in statuses.values())
    c.run(15000)
    statuses = {
        n.host_port: n.membership.find_member_by_address(victim).status
        for n in c.live_nodes()
    }
    assert all(s == Status.faulty for s in statuses.values())
    # Faulty members are retained in the list but removed from the ring.
    node0 = c.nodes[0]
    assert node0.membership.get_member_count() == 5
    assert victim not in node0.ring.servers
    assert c.run_until_converged(30000)
    c.destroy_all()


def test_suspend_behaves_like_slow_node_then_recovers():
    """SIGSTOP analog (tick-cluster.js:432-446): suspended node times out
    (suspect) and recovers on resume via refutation."""
    c = converged_cluster(5)
    victim = c.host_ports[4]
    c.suspend(4)
    c.run(12000)
    statuses = {
        n.host_port: n.membership.find_member_by_address(victim).status
        for n in c.live_nodes()
    }
    assert all(s in (Status.suspect, Status.faulty) for s in statuses.values())
    c.resume(4)
    assert c.run_until_converged(90000)
    final = {
        n.host_port: n.membership.find_member_by_address(victim).status
        for n in c.nodes
    }
    assert all(s == Status.alive for s in final.values())
    c.destroy_all()


def test_partition_and_heal():
    """Netsplit: the stub the reference never finished
    (test/lib/partition-cluster.js) done properly with reachability masks."""
    c = converged_cluster(6)
    c.partition([[0, 1, 2], [3, 4, 5]])
    c.run(30000)
    # Each side declares the other faulty; two checksum groups among all.
    groups = c.checksum_groups()
    assert len(groups) == 2
    side_a = c.nodes[0]
    for idx in (3, 4, 5):
        assert (
            side_a.membership.find_member_by_address(c.host_ports[idx]).status
            == Status.faulty
        )
    c.heal_partition()
    assert c.run_until_converged(180000)
    # After heal every member is alive everywhere again (faulty members are
    # retained so splits can merge, docs/architecture_design.md:19).
    for node in c.nodes:
        for host in c.host_ports:
            assert node.membership.find_member_by_address(host).status == Status.alive
    c.destroy_all()


def test_leave_and_rejoin():
    """admin-leave + admin-join semantics (server/admin-*-handler.js)."""
    c = converged_cluster(3)
    node = c.nodes[2]
    results = []
    node.channel.request(
        c.host_ports[0], "/admin/leave", None, None, 5000,
        lambda err, r1=None, r2=None: results.append((err, r2)),
    )
    c.run(5000)
    assert results and results[0][0] is None
    # Node 0 left: gossip stopped, status leave spreads.
    c.run(20000)
    assert c.nodes[0].gossip.is_stopped
    for n in (c.nodes[1], c.nodes[2]):
        assert (
            n.membership.find_member_by_address(c.host_ports[0]).status == Status.leave
        )
        assert c.host_ports[0] not in n.ring.servers

    # Redundant leave errors.
    res2 = []
    node.channel.request(
        c.host_ports[0], "/admin/leave", None, None, 5000,
        lambda err, r1=None, r2=None: res2.append(err),
    )
    c.run(1000)
    assert getattr(res2[0], "type", None) == "ringpop.invalid-leave.redundant"

    # Rejoin via /admin/join.
    res3 = []
    node.channel.request(
        c.host_ports[0], "/admin/join", None, "{}", 5000,
        lambda err, r1=None, r2=None: res3.append((err, r2)),
    )
    c.run(2000)
    assert res3 and res3[0][0] is None
    assert c.run_until_converged(60000)
    for n in c.nodes:
        assert (
            n.membership.find_member_by_address(c.host_ports[0]).status == Status.alive
        )
    c.destroy_all()


def test_tick_and_admin_stats():
    """tick-cluster's convergence probe (/admin/tick, index.js:398-403)."""
    c = converged_cluster(3)
    out = c.tick_all()
    assert len(out) == 3
    import json

    checksums = {json.loads(v)["checksum"] for v in out.values()}
    assert len(checksums) == 1

    res = []
    c.nodes[0].channel.request(
        c.host_ports[1], "/admin/stats", None, None, 5000,
        lambda err, r1=None, r2=None: res.append((err, r2)),
    )
    c.run(100)
    stats = json.loads(res[0][1])
    assert stats["membership"]["checksum"] == c.nodes[1].membership.checksum
    assert len(stats["ring"]) == 3
    assert "protocol" in stats
    c.destroy_all()


def test_gossip_full_cycle_with_packet_loss():
    """1% packet loss does not prevent convergence (BASELINE config 3 analog)."""
    c = Cluster(size=8)
    c.network.set_drop_rate(0.01)
    c.bootstrap_all(run=False)
    assert c.run_until_converged(120000)
    c.destroy_all()

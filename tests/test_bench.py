"""Parent-side bench.py contracts, testable without any backend.

bench.py's parent process never imports jax, so these pins run in
milliseconds: the worker-crash signature that stops the TPU climb
(ADVICE round 5 — anchored to the runtime's own error text, not bare
substring matches), the ``+stream`` segment-dispatch plan of the
banked rungs, and the attempt-string protocol between parent and
child.
"""

from __future__ import annotations

import bench


def test_worker_crash_signature_positive():
    # the round-5 failure text, verbatim and embedded mid-stderr
    assert bench._is_worker_crash(
        "UNAVAILABLE: TPU worker process crashed or restarted"
    )
    assert bench._is_worker_crash(
        "blah\n... UNAVAILABLE: TPU worker exited ...\ntail"
    )
    assert bench._is_worker_crash("the worker process crashed hard")


def test_worker_crash_signature_rejects_lookalikes():
    # an unrelated UNAVAILABLE RPC or a log line with "crashed" must
    # NOT abandon the delta climb and the dense safety net
    assert not bench._is_worker_crash("UNAVAILABLE: connection reset by peer")
    assert not bench._is_worker_crash("the child crashed with rc=1")
    assert not bench._is_worker_crash("worker restarted cleanly")
    assert not bench._is_worker_crash("")
    assert not bench._is_worker_crash(None)


def test_stream_plan_shapes():
    # TPU batch: 100 ticks -> 4 x 25-tick segment programs
    assert bench._stream_plan(100) == (4, 25)
    # large-n CPU fallback batch: 20 ticks -> 4 x 5
    assert bench._stream_plan(20) == (4, 5)
    # degenerate batches never produce a zero-tick segment
    assert bench._stream_plan(3) == (3, 1)
    assert bench._stream_plan(1) == (1, 1)


def test_tpu_ladder_banked_rungs_are_streamed():
    rungs = list(bench.TPU_DELTA_LADDER)
    # ascending sizes: the climb banks as it goes
    sizes = [n for _, n in rungs]
    assert sizes == sorted(sizes)
    for layout, n in rungs:
        if n < 65536:
            # banked rungs dispatch segment-sized programs
            assert layout.endswith("+stream"), (layout, n)
        else:
            # 65,536+ measure the exact program budgets.py pins
            assert not layout.endswith("+stream"), (layout, n)


def test_parse_attempt_streamed_layout():
    assert bench._parse_attempt("delta@64+stream:8192") == (
        "delta@64+stream",
        8192,
    )
    assert bench._parse_attempt("delta@64:65536") == ("delta@64", 65536)
    assert bench._parse_attempt("2048") == ("dense", 2048)

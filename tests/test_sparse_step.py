"""Sparse dissemination path (SwimParams.sparse_cap) vs the dense step.

Contract (swim_sim.py): with the same PRNG keys, the sparse step is
bit-identical to the dense step whenever no row carries more than
``sparse_cap`` active changes; under overflow it degrades to
bounded-message semantics but must still converge.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ringpop_tpu.models import swim_sim as sim


def assert_states_equal(a: sim.ClusterState, b: sim.ClusterState, tick: int):
    np.testing.assert_array_equal(
        np.asarray(a.view_key), np.asarray(b.view_key), err_msg=f"view_key tick {tick}"
    )
    np.testing.assert_array_equal(
        np.asarray(a.pb), np.asarray(b.pb), err_msg=f"pb tick {tick}"
    )
    np.testing.assert_array_equal(
        np.asarray(a.suspect_left),
        np.asarray(b.suspect_left),
        err_msg=f"suspect_left tick {tick}",
    )


def run_both(n, ticks, dense_params, sparse_params, mutate=None, init="converged"):
    dense = sim.init_state(n, mode=init)
    sparse = sim.init_state(n, mode=init)
    net = sim.make_net(n)
    if mutate:
        dense, sparse, net = mutate(dense, sparse, net)
    key = jax.random.PRNGKey(42)
    for t in range(ticks):
        key, sub = jax.random.split(key)
        dense, md = sim.swim_step(dense, net, sub, dense_params)
        sparse, ms = sim.swim_step(sparse, net, sub, sparse_params)
        yield t, dense, sparse, md, ms


def test_bit_identical_steady_state_with_loss():
    """Converged cluster + 5% loss: suspects, refutations, ping-reqs and
    suspicion expiries all occur, and every tick must match bit-for-bit
    (active-change counts stay far below the cap)."""
    n = 24
    dense_p = sim.SwimParams(loss=0.05)
    sparse_p = dense_p._replace(sparse_cap=n)  # cap >= n: never overflows
    for t, dense, sparse, md, ms in run_both(n, 50, dense_p, sparse_p):
        assert_states_equal(dense, sparse, t)
        for k in md:
            if k == "damped_pairs":
                continue
            assert int(md[k]) == int(ms[k]), f"metric {k} tick {t}"


def test_bit_identical_through_kill_and_fault_detection():
    n = 16
    dense_p = sim.SwimParams(loss=0.0, suspicion_ticks=5)
    sparse_p = dense_p._replace(sparse_cap=n)

    def mutate(dense, sparse, net):
        net = net._replace(up=net.up.at[3].set(False))
        return dense, sparse, net

    last = None
    for t, dense, sparse, _, _ in run_both(n, 30, dense_p, sparse_p, mutate):
        assert_states_equal(dense, sparse, t)
        last = dense
    # the dead node was declared faulty everywhere (sanity)
    vs = np.asarray(last.view_key) & 7
    live = [i for i in range(n) if i != 3]
    assert all(vs[i, 3] == sim.FAULTY for i in live)


def test_overflow_still_converges():
    """cap far below the active-change count (bootstrap burst): messages
    truncate, but gossip + full-sync fallback still converge the views."""
    n = 32
    params = sim.SwimParams(loss=0.0, sparse_cap=4)
    state = sim.init_state(n, mode="self")
    for j in range(1, n):
        state = sim.admin_join(state, j, 0)
    net = sim.make_net(n)
    key = jax.random.PRNGKey(0)
    for _ in range(300):
        key, sub = jax.random.split(key)
        state, _ = sim.swim_step(state, net, sub, params)
        vk = np.asarray(state.view_key)
        if (vk == vk[0]).all() and ((vk[0] & 7) == sim.ALIVE).all():
            break
    vk = np.asarray(state.view_key)
    assert (vk == vk[0]).all(), "sparse overflow mode failed to converge"
    assert ((np.asarray(state.view_key[0]) & 7) == sim.ALIVE).all()


def test_full_sync_dense_fallback_fires():
    """A node with a stale view and nothing piggybacked gets repaired by
    a full-sync reply; the sparse step must take the dense reply branch
    (dissemination.js:100-118) and adopt the whole row."""
    n = 8
    params = sim.SwimParams(loss=0.0, sparse_cap=8)
    # cluster converged with node 5 at incarnation 50 ...
    inc = jnp.zeros((n,), jnp.int32).at[5].set(50)
    state = sim.init_state(n, inc)
    # ... except node 1 holds a stale inc-0 view of node 5, and no change
    # is recorded anywhere (pb=-1): only a full sync can repair node 1.
    state = state._replace(view_key=state.view_key.at[1, 5].set(0 * 8 + sim.ALIVE))
    want = 50 * 8 + sim.ALIVE
    net = sim.make_net(n)
    key = jax.random.PRNGKey(1)
    saw_full_sync = False
    for _ in range(60):
        key, sub = jax.random.split(key)
        state, m = sim.swim_step(state, net, sub, params)
        saw_full_sync = saw_full_sync or int(m["full_syncs"]) > 0
        if int(state.view_key[1, 5]) == want:
            break
    assert saw_full_sync, "no full sync occurred"
    assert int(state.view_key[1, 5]) == want, "stale view never repaired"


def test_sparse_rejects_damping():
    state = sim.init_state(8, damping=True)
    with pytest.raises(NotImplementedError):
        sim.swim_step_impl(
            state,
            sim.make_net(8),
            jax.random.PRNGKey(0),
            sim.SwimParams(sparse_cap=4),
        )


def test_sweep_probe_covers_every_member_each_round():
    """probe='sweep' restores the reference iterator's guarantee
    (membership-iterator.js:33-40): in any n consecutive ticks of a
    stable cluster, every viewer probes every other member.  Observable
    through the suspect trail: every live node must personally have
    probed (and therefore suspected) a dead node within one n-tick round
    — uniform sampling only guarantees that in expectation, with
    coupon-collector tails."""
    n = 12
    params = sim.SwimParams(loss=0.0, suspicion_ticks=126, probe="sweep")
    state = sim.init_state(n)
    net = sim.make_net(n)
    net = net._replace(up=net.up.at[4].set(False))
    key = jax.random.PRNGKey(0)
    for _ in range(n + 1):  # one full sweep round (+1 for phase offsets)
        key, sub = jax.random.split(key)
        state, _ = sim.swim_step(state, net, sub, params)
    vs = np.asarray(state.view_key) & 7
    live = [i for i in range(n) if i != 4]
    # every live node personally probed node 4 within the round and
    # (with no witnesses reaching it either) declared it suspect
    assert all(vs[i, 4] == sim.SUSPECT for i in live), vs[:, 4]


def test_sweep_probe_rejects_unknown_policy():
    with pytest.raises(ValueError):
        sim.swim_step_impl(
            sim.init_state(4),
            sim.make_net(4),
            jax.random.PRNGKey(0),
            sim.SwimParams(probe="banana"),
        )


# -- large-N memory-lean lowerings (forced small via _SPARSE_SMALL_N) -------


def _mask_fixture(key, rows=13, cols=200, p=0.3):
    return jax.random.uniform(jax.random.PRNGKey(key), (rows, cols)) < p


@pytest.mark.parametrize("cap", [1, 4, 64])
@pytest.mark.parametrize("seed", [0, 1])
def test_capped_within_large_path_matches_small(monkeypatch, cap, seed):
    mask = _mask_fixture(seed)
    want = np.asarray(sim._capped_within(mask, cap))
    monkeypatch.setattr(sim, "_SPARSE_SMALL_N", 1)
    got = np.asarray(sim._capped_within(mask, cap))
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("cap", [1, 4, 64])
@pytest.mark.parametrize("seed", [0, 1])
def test_compact_rows_large_path_matches_small(monkeypatch, cap, seed):
    mask = _mask_fixture(seed)
    want = np.asarray(sim._compact_rows(mask, cap))
    monkeypatch.setattr(sim, "_SPARSE_SMALL_N", 1)
    got = np.asarray(sim._compact_rows(mask, cap))
    np.testing.assert_array_equal(got, want)


def test_choose_targets_large_path_matches_small(monkeypatch):
    """The two-level rank lookup must pick the same targets/witnesses
    bit for bit as the int16-cumsum path (valid picks only; invalid
    picks are masked by the valid flags)."""
    pingable = np.asarray(_mask_fixture(3, rows=50, cols=50, p=0.4)).copy()
    np.fill_diagonal(pingable, False)
    key = jax.random.PRNGKey(9)
    t0, v0, w0, wv0 = (
        np.asarray(x)
        for x in sim._choose_targets_and_witnesses(jnp.asarray(pingable), 3, key)
    )
    monkeypatch.setattr(sim, "_SPARSE_SMALL_N", 1)
    t1, v1, w1, wv1 = (
        np.asarray(x)
        for x in sim._choose_targets_and_witnesses(jnp.asarray(pingable), 3, key)
    )
    np.testing.assert_array_equal(v0, v1)
    np.testing.assert_array_equal(wv0, wv1)
    np.testing.assert_array_equal(t0[v0], t1[v0])
    np.testing.assert_array_equal(w0[wv0], w1[wv0])


@pytest.mark.slow
def test_sparse_step_bitparity_on_large_path(monkeypatch):
    """A short sparse trajectory through a kill, with the large-N
    lowerings forced on: bit-identical to the small-N lowerings."""
    n = 24
    params = sim.SwimParams(loss=0.0, sparse_cap=8, suspicion_ticks=3)
    net = sim.make_net(n)
    net = net._replace(up=net.up.at[5].set(False))
    keys = jax.random.split(jax.random.PRNGKey(2), 10)

    def run():
        state = sim.init_state(n)
        out = []
        for k in keys:
            state, _ = sim.swim_step_impl(state, net, k, params)
            out.append(state)
        return out

    ref = run()
    monkeypatch.setattr(sim, "_SPARSE_SMALL_N", 1)
    got = run()
    for t, (a, b) in enumerate(zip(ref, got)):
        assert_states_equal(a, b, t)

"""Pallas farmhash kernel vs the C oracle and the jnp kernel
(interpret mode so CPU CI covers the kernel body)."""

from __future__ import annotations

import numpy as np
import pytest

from ringpop_tpu.ops.farmhash import farmhash32
from ringpop_tpu.ops.farmhash_jax import farmhash32_batch_jax
from ringpop_tpu.ops.farmhash_pallas import farmhash32_batch_pallas


def make_batch(lengths, L, seed=0):
    rng = np.random.default_rng(seed)
    bufs = np.zeros((len(lengths), L), dtype=np.uint8)
    for i, n in enumerate(lengths):
        bufs[i, :n] = rng.integers(0, 256, n, dtype=np.uint8)
    return bufs, np.array(lengths, dtype=np.int32)


@pytest.mark.parametrize("L", [25, 40, 64])
def test_pallas_matches_c_all_lengths(L):
    lengths = list(range(0, L + 1))
    bufs, lens = make_batch(lengths, L, seed=L)
    got = np.asarray(farmhash32_batch_pallas(bufs, lens, interpret=True))
    for i, n in enumerate(lengths):
        expect = farmhash32(bufs[i, :n].tobytes())
        assert got[i] == expect, (n, got[i], expect)


def test_pallas_matches_jnp_random_batch():
    rng = np.random.default_rng(9)
    L = 48
    lengths = rng.integers(0, L + 1, 300).tolist()
    bufs, lens = make_batch(lengths, L, seed=1)
    got = np.asarray(farmhash32_batch_pallas(bufs, lens, interpret=True))
    ref = np.asarray(farmhash32_batch_jax(bufs, lens))
    assert np.array_equal(got, ref)


def test_pallas_partial_block_padding():
    # batch not a multiple of the 128-row block
    bufs, lens = make_batch([7, 25, 33], 40, seed=2)
    got = np.asarray(farmhash32_batch_pallas(bufs, lens, interpret=True))
    for i in range(3):
        assert got[i] == farmhash32(bufs[i, : lens[i]].tobytes())


def test_pallas_rejects_short_buffers():
    bufs, lens = make_batch([3], 24, seed=3)
    with pytest.raises(ValueError):
        farmhash32_batch_pallas(bufs, lens, interpret=True)

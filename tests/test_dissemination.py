"""Dissemination tests (reference: test/dissemination-test.js)."""

from ringpop_tpu.harness import test_ringpop
from ringpop_tpu.member import Status


def make_rp(n_members=3):
    rp = test_ringpop(host_port="10.0.0.1:3000")
    for i in range(2, 2 + n_members):
        rp.membership.make_alive(f"10.0.0.{i}:3000", 1000 + i)
    return rp


def test_record_and_issue_as_sender():
    rp = make_rp()
    issued = rp.dissemination.issue_as_sender()
    addrs = {c["address"] for c in issued}
    assert "10.0.0.2:3000" in addrs
    for change in issued:
        assert set(change) == {
            "id", "source", "sourceIncarnationNumber", "address", "status",
            "incarnationNumber",
        }


def test_piggyback_eviction():
    """Changes are evicted after maxPiggybackCount issues
    (dissemination.js:138-177)."""
    rp = make_rp()
    max_pb = rp.dissemination.max_piggyback_count
    assert max_pb == 15  # 15 * ceil(log10(4+1)) = 15

    for _ in range(max_pb):
        assert rp.dissemination.issue_as_sender()
    assert rp.dissemination.issue_as_sender() == []
    assert rp.dissemination.changes == {}


def test_receiver_filters_senders_own_changes():
    """Anti-echo (dissemination-test.js:43-72)."""
    rp = make_rp()
    rp.dissemination.clear_changes()
    rp.dissemination.record_change(
        {
            "address": "10.0.0.9:3000",
            "status": Status.alive,
            "incarnationNumber": 1,
            "source": "10.0.0.2:3000",
            "sourceIncarnationNumber": 42,
        }
    )
    # Sender is the change's source with matching incarnation -> filtered,
    # and checksums match -> no full sync.
    issued = rp.dissemination.issue_as_receiver(
        "10.0.0.2:3000", 42, rp.membership.checksum
    )
    assert issued == []
    # Different incarnation -> not filtered.
    issued = rp.dissemination.issue_as_receiver(
        "10.0.0.2:3000", 43, rp.membership.checksum
    )
    assert len(issued) == 1


def test_full_sync_on_checksum_mismatch():
    """Empty piggyback + checksum mismatch -> full membership as changes
    (dissemination.js:100-118)."""
    rp = make_rp()
    rp.dissemination.clear_changes()
    issued = rp.dissemination.issue_as_receiver("10.0.0.2:3000", 42, 12345)
    assert len(issued) == rp.membership.get_member_count()
    assert all(c["source"] == rp.whoami() for c in issued)
    # Checksum match -> nothing.
    assert (
        rp.dissemination.issue_as_receiver("10.0.0.2:3000", 42, rp.membership.checksum)
        == []
    )


def test_adjust_max_piggyback_with_ring_size():
    rp = make_rp()
    # 3 members + self = 4 ring servers -> ceil(log10(5)) = 1 -> 15
    assert rp.dissemination.max_piggyback_count == 15
    for i in range(10, 20):
        rp.membership.make_alive(f"10.0.0.{i}:3000", 1)
    # 14 servers -> ceil(log10(15)) = 2 -> 30
    assert rp.dissemination.max_piggyback_count == 30

"""Gossip provenance plane: rumor tracing against a per-tick host oracle.

The acceptance oracle is the eager host walk (``_host_prov_walk``):
step the protocol per tick with the same key schedule, export the same
delivery-evidence bundle (``swim_step(..., prov=True)``), and fold it
through the SAME ``obs.provenance.prov_update`` the compiled scan
folds — slots, wavefronts, parents, resolutions, and the per-tick
``pv_heard`` plane must match bit for bit (the update is exact int
algebra shared by both callers, so parity is equality).

Fast lane: spec validation, the dense oracle, the report/spans
exporters, the prov-off == legacy equivalence pin, and the precheck
rejections.  The delta twin, streamed/resume bit-parity, and the sweep
replica contract ride the slow lane (each is its own XLA compile).
"""

from __future__ import annotations

import json
from collections import defaultdict

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ringpop_tpu.models import swim_delta as sdelta
from ringpop_tpu.models import swim_sim as sim
from ringpop_tpu.models.cluster import SimCluster
from ringpop_tpu.models.swim_sim import SwimParams
from ringpop_tpu.obs import provenance as pvn
from ringpop_tpu.obs import spans as pvspans
from ringpop_tpu.ops import bitpack
from ringpop_tpu.scenarios import compile as scompile
from ringpop_tpu.scenarios.spec import ScenarioSpec

N = 10
LEAN = SwimParams(suspicion_ticks=8, ping_req_size=1)
K = 3

# one reserved slot that never fires (node 1 stays healthy), one kill
# whose suspect rumor auto-arms a free slot and confirms at suspicion
# expiry — reservation passthrough + auto-arm + resolution in one spec
PV_SPEC = {
    "ticks": 18,
    "trace_rumors": K,
    "events": [
        {"at": 0, "op": "track", "node": 1},
        {"at": 3, "op": "kill", "node": 9},
    ],
}


@pytest.fixture(scope="module")
def traced():
    """One traced dense run shared by the fast lane (order-dependent:
    the precheck test clears its provenance state and runs LAST)."""
    c = SimCluster(N, LEAN, seed=11)
    trace = c.run_scenario(PV_SPEC)
    return c, trace


# ---------------------------------------------------------------------------
# fast: pure-host validation
# ---------------------------------------------------------------------------


def test_provenance_spec_validation():
    def bad(d, match=None):
        with pytest.raises(ValueError, match=match):
            ScenarioSpec.from_dict(d).validate(N)

    ok = dict(PV_SPEC)
    ScenarioSpec.from_dict(ok).validate(N)
    bad(dict(ok, trace_rumors=-1), "trace_rumors")
    bad(dict(ok, trace_rumors=pvn.MAX_RUMORS + 1), "trace_rumors")
    bad(dict(ok, ticks=pvn.MAX_TICKS + 1), "int16")
    # track needs a slot count, a valid subject, and no duplicates
    bad({"ticks": 8, "events": [{"at": 0, "op": "track", "node": 1}]},
        "trace_rumors")
    bad(dict(ok, events=[{"at": 0, "op": "track", "node": N}]), "track")
    bad(dict(ok, events=[{"at": 0, "op": "track", "node": 1},
                         {"at": 2, "op": "track", "node": 1}]),
        "duplicate track")
    # more reservations than slots
    bad(dict(ok, trace_rumors=1,
             events=[{"at": 0, "op": "track", "node": 1},
                     {"at": 0, "op": "track", "node": 2}]),
        "exceed")
    # JSON round trip keeps the plane config
    spec = ScenarioSpec.from_dict(ok)
    assert ScenarioSpec.from_json(spec.to_json()) == spec
    assert spec.trace_rumors == K
    # trace_rumors=0 is the default and stays out of the dict form
    assert "trace_rumors" not in ScenarioSpec(ticks=4).to_dict()


# ---------------------------------------------------------------------------
# the host oracle
# ---------------------------------------------------------------------------


def _host_prov_walk(backend, spec_obj, seed, **kw):
    """Step the protocol eagerly with the scan's key schedule, folding
    each tick's evidence bundle through ``prov_update`` exactly as the
    scan body does.  Returns (cluster, ProvCarry, heard rows)."""
    c = SimCluster(N, LEAN, seed=seed, backend=backend, **kw)
    compiled = scompile.compile_spec(spec_obj, c.n, base_loss=c.params.loss)
    keys = scompile.key_schedule(c._split, compiled)
    pvc = pvn.init_carry(c.n, spec_obj.trace_rumors, LEAN.ping_req_size)
    pv_at, pv_node = pvn.track_tensors(compiled.tracks, spec_obj.trace_rumors)
    by_tick = defaultdict(list)
    for at, op, arg in scompile.expand_events(spec_obj, c.params.loss):
        by_tick[at].append((op, arg))
    heards = []
    for t in range(spec_obj.ticks):
        for op, arg in sorted(by_tick.get(t, ()),
                              key=lambda x: scompile._OP_RANK[x[0]]):
            if op == "kill":
                c.kill(arg)
            elif op == "suspend":
                c.suspend(arg)
            elif op == "resume":
                c.resume(arg)
            elif op == "loss":
                c.set_loss(arg)
        if backend == "delta":
            c.state, m = sdelta.delta_step(
                c.state, c.net, keys[t], params=c.dparams, prov=True
            )
            view_post = lambda q: sdelta.view_lookup(c.state, q)  # noqa: E731
        else:
            c.state, m = sim.swim_step(
                c.state, c.net, keys[t], params=c.params, prov=True
            )
            view_post = lambda q: jnp.take_along_axis(  # noqa: E731
                c.state.view_key, q, axis=1
            )
        ev = {name: m[name] for name in pvn.EVIDENCE_KEYS}
        pvc, heard = pvn.prov_update(
            pvc, ev, t, view_post, pv_at, pv_node, c.n
        )
        heards.append(np.asarray(heard))
    return c, pvc, np.stack(heards)


def _assert_prov_parity(a, trace, b, pvc, heards):
    """Compiled scan == host fold, bit for bit, carry and telemetry."""
    np.testing.assert_array_equal(np.asarray(trace.planes["pv_heard"]),
                                  heards)
    for name, host in (
        ("pv_slot", pvc.slot), ("pv_tickv", pvc.tickv),
        ("pv_wits", pvc.wits), ("pv_first", pvc.first),
        ("pv_parent", pvc.parent), ("pv_knows", pvc.knows),
    ):
        np.testing.assert_array_equal(
            np.asarray(getattr(a.net, name)), np.asarray(host), err_msg=name
        )
    # the evidence export did not perturb the protocol trajectory
    for la, lb in zip(
        jax.tree_util.tree_leaves(a.state), jax.tree_util.tree_leaves(b.state)
    ):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    assert a.checksums() == b.checksums()


def test_provenance_dense_host_oracle(traced):
    """Tier-1 acceptance oracle (dense arm)."""
    a, trace = traced
    b, pvc, heards = _host_prov_walk(
        "dense", ScenarioSpec.from_dict(PV_SPEC), seed=11
    )
    _assert_prov_parity(a, trace, b, pvc, heards)


def test_provenance_report_and_spans(traced, tmp_path):
    """The report's causality chain is coherent and the Perfetto
    exporter writes structurally valid trace-event JSON from it."""
    a, _ = traced
    rep = a.provenance_report()
    assert rep["n"] == N and rep["log2_n"] == 4
    rumors = {r["subject"]: r for r in rep["rumors"]}
    assert 9 in rumors  # the kill's suspect rumor auto-armed
    r = rumors[9]
    assert r["slot"] != 0  # slot 0 stays reserved for node 1, unarmed
    assert all(x["slot"] != 0 for x in rep["rumors"])
    assert r["key"] % 8 == pvn._SUSPECT
    assert 0 <= r["origin"] < N and r["origin"] != 9
    assert r["origin_tick"] >= 3
    # a dead subject cannot refute: confirmed at suspicion expiry,
    # every live node heard, and the tree is rooted (origin at depth 0)
    assert r["resolution"] == pvn.RES_CONFIRMED
    assert r["resolution_tick"] > r["origin_tick"]
    assert r["infected"] == N - 1 and r["unheard"] == 1
    assert r["first_heard"][9] == pvn.UNHEARD
    assert r["parent"][r["origin"]] == pvn.P_ORIGIN
    assert r["depth_max"] >= 1
    assert r["infection_p50"] <= r["infection_p95"] <= r["infection_p99"]
    assert len(r["witnesses"]) <= LEAN.ping_req_size
    # knows plane == (first_heard >= 0): the packed carry agrees
    knows = bitpack.unpack_bits(jnp.asarray(a.net.pv_knows), N)
    np.testing.assert_array_equal(
        np.asarray(knows), np.asarray(a.net.pv_first) >= 0
    )
    # the summary block is all-int (golden-pinnable)
    block = pvn.summary_block(rep)
    assert block["rumors"] == len(rep["rumors"])
    assert all(isinstance(v, int) for v in block.values())

    path = str(tmp_path / "spans.json")
    count = pvspans.write_spans(rep, path)
    doc = json.loads(open(path).read())
    events = doc["traceEvents"]
    assert count == len(events) > 0
    assert {e["ph"] for e in events} <= {"M", "X", "s", "f"}
    # every flow-start has its matching flow-end
    starts = {e["id"] for e in events if e["ph"] == "s"}
    ends = {e["id"] for e in events if e["ph"] == "f"}
    assert starts and starts == ends
    # one infection event per heard node of each rumor
    infections = [e for e in events if e.get("cat") == "infection"]
    assert len(infections) == sum(x["infected"] for x in rep["rumors"])
    assert doc["otherData"]["summary"] == block


def test_provenance_off_is_legacy(traced):
    """The plane is observer-only: a traced run's protocol trajectory
    is bit-identical to the untraced run, and the untraced program
    carries no pv residue at all."""
    a, ta = traced
    spec_off = dict(PV_SPEC, trace_rumors=0)
    spec_off["events"] = [e for e in spec_off["events"]
                          if e["op"] != "track"]
    b = SimCluster(N, LEAN, seed=11)
    tb = b.run_scenario(spec_off)
    assert "pv_heard" in ta.planes and "pv_heard" not in tb.planes
    for k in tb.metrics:
        np.testing.assert_array_equal(ta.metrics[k], tb.metrics[k], err_msg=k)
    for la, lb in zip(
        jax.tree_util.tree_leaves(a.state), jax.tree_util.tree_leaves(b.state)
    ):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    assert a.checksums() == b.checksums()
    assert b.net.pv_slot is None
    with pytest.raises(ValueError, match="no provenance state"):
        b.provenance_report()


def test_provenance_precheck_rejections(traced):
    """Static rejections fire before any key is drawn.  Runs LAST in
    the fast lane: it clears the shared fixture's provenance state."""
    # the sparse fast path never materializes the evidence bundle
    c = SimCluster(N, LEAN._replace(sparse_cap=4), seed=2)
    with pytest.raises(NotImplementedError, match="sparse_cap"):
        c.run_scenario(PV_SPEC)
    # leftover tracked-rumor state from a finished run
    a, _ = traced
    with pytest.raises(ValueError, match="clear_provenance"):
        a.run_scenario(PV_SPEC)
    a.clear_provenance()
    assert a.net.pv_slot is None
    with pytest.raises(ValueError, match="no provenance state"):
        a.provenance_report()


# ---------------------------------------------------------------------------
# slow: the delta twin + execution-strategy contracts
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_provenance_delta_host_oracle():
    """The delta twin of the acceptance oracle (same ``prov_update``
    over ``view_lookup`` post-views; its own XLA compile)."""
    kw = dict(capacity=N, wire_cap=N, claim_grid=3 * N * N)
    a = SimCluster(N, LEAN, seed=11, backend="delta", **kw)
    trace = a.run_scenario(PV_SPEC)
    b, pvc, heards = _host_prov_walk(
        "delta", ScenarioSpec.from_dict(PV_SPEC), seed=11, **kw
    )
    _assert_prov_parity(a, trace, b, pvc, heards)
    # the wavefront report is backend-coherent too
    r = {x["subject"]: x for x in a.provenance_report()["rumors"]}[9]
    assert r["resolution"] == pvn.RES_CONFIRMED
    assert r["infected"] == N - 1


@pytest.mark.slow
def test_provenance_streamed_and_resume_bit_identical(tmp_path):
    """Streaming a traced run is an execution strategy (same pv
    tensors), and a SIGKILL mid-run resumes from the checkpoint v5 pv
    planes to a bit-identical end state."""
    from ringpop_tpu import checkpoint as ckpt
    from ringpop_tpu.scenarios import stream as sstream

    a = SimCluster(N, LEAN, seed=7)
    a.run_scenario(PV_SPEC)
    b = SimCluster(N, LEAN, seed=7)
    b.run_scenario(PV_SPEC, segment_ticks=7)
    for name in ("pv_slot", "pv_tickv", "pv_wits", "pv_first",
                 "pv_parent", "pv_knows"):
        np.testing.assert_array_equal(
            np.asarray(getattr(a.net, name)),
            np.asarray(getattr(b.net, name)), err_msg=name,
        )
    assert a.checksums() == b.checksums()

    ckpt_path = str(tmp_path / "pv.npz")
    cv = SimCluster(N, LEAN, seed=7)
    with pytest.raises(sstream.StreamInterrupted):
        sstream.run_streamed(
            cv, PV_SPEC, segment_ticks=7,
            checkpoint_path=ckpt_path, interrupt_after=1,
        )
    # the checkpoint carries the mid-flight planes
    mid = ckpt.load(ckpt_path)
    assert mid.net.pv_slot is not None
    cr, _ = sstream.resume(ckpt_path)
    for name in ("pv_slot", "pv_tickv", "pv_wits", "pv_first",
                 "pv_parent", "pv_knows"):
        np.testing.assert_array_equal(
            np.asarray(getattr(a.net, name)),
            np.asarray(getattr(cr.net, name)), err_msg=name,
        )
    assert a.checksums() == cr.checksums()
    assert cr.provenance_report()["rumors"]  # report works post-resume


@pytest.mark.slow
def test_provenance_sweep_replica_parity():
    """A traced sweep replica is bit-identical to the standalone run
    from its replica key, and the per-replica pv tensors land on
    ``final_nets`` (the cluster itself does not advance)."""
    c = SimCluster(N, LEAN, seed=9)
    strace = c.run_sweep(PV_SPEC, 2)
    assert c.net.pv_slot is None  # sweeps never advance the cluster
    assert strace.planes["pv_heard"].shape == (2, PV_SPEC["ticks"], K)
    strace.summary()  # pv planes are skipped, not summarized
    d = SimCluster(N, LEAN, seed=9)
    d.key = jnp.asarray(strace.replica_keys[1])
    td = d.run_scenario(PV_SPEC)
    np.testing.assert_array_equal(
        strace.planes["pv_heard"][1], np.asarray(td.planes["pv_heard"])
    )
    for name in ("pv_slot", "pv_tickv", "pv_wits", "pv_first",
                 "pv_parent", "pv_knows"):
        np.testing.assert_array_equal(
            np.asarray(getattr(strace.final_nets, name))[1],
            np.asarray(getattr(d.net, name)), err_msg=name,
        )

"""TCP transport unit tests: framing, timeout, refusal, full RingPop pair.

Mirrors the transport-level behaviors the reference gets from TChannel
(request/response, timeouts as typed errors, connection refusal) that the
in-process transport tests already cover for the sim path.
"""

from __future__ import annotations

import asyncio
import json


from ringpop_tpu.transport.tcp import (
    TcpChannel,
    TransportConnectionError,
    TransportTimeoutError,
)

BASE = 24300


def run(coro, timeout=20):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def make_echo_channel(host_port: str) -> TcpChannel:
    channel = TcpChannel(host_port)

    def echo(head, body, src, respond):
        respond(None, head, json.dumps({"echo": json.loads(body)["x"], "src": src}))

    def slow(head, body, src, respond):
        # Never responds: exercises the client-side timeout.
        pass

    channel.register({"/echo": echo, "/slow": slow})
    return channel


def test_request_response():
    async def scenario():
        a = TcpChannel(f"127.0.0.1:{BASE}")
        b = make_echo_channel(f"127.0.0.1:{BASE + 1}")
        await a.listen()
        await b.listen()
        fut = asyncio.get_event_loop().create_future()
        a.request(
            b.host_port, "/echo", "HEAD", json.dumps({"x": 42}), 5000,
            lambda err, res1, res2=None: fut.set_result((err, res1, res2)),
        )
        err, res1, res2 = await fut
        assert err is None
        assert res1 == "HEAD"
        parsed = json.loads(res2)
        assert parsed["echo"] == 42
        assert parsed["src"] == a.host_port  # identified reverse route
        a.close()
        b.close()

    run(scenario())


def test_timeout_is_typed():
    async def scenario():
        a = TcpChannel(f"127.0.0.1:{BASE + 10}")
        b = make_echo_channel(f"127.0.0.1:{BASE + 11}")
        await a.listen()
        await b.listen()
        fut = asyncio.get_event_loop().create_future()
        a.request(b.host_port, "/slow", None, None, 200,
                  lambda err, *res: fut.set_result(err))
        err = await fut
        assert isinstance(err, TransportTimeoutError)
        assert err.type == "ringpop.transport.timeout"
        a.close()
        b.close()

    run(scenario())


def test_connection_refused():
    async def scenario():
        a = TcpChannel(f"127.0.0.1:{BASE + 20}")
        await a.listen()
        fut = asyncio.get_event_loop().create_future()
        a.request(f"127.0.0.1:{BASE + 29}", "/echo", None, None, 5000,
                  lambda err, *res: fut.set_result(err))
        err = await fut
        assert isinstance(err, TransportConnectionError)
        a.close()

    run(scenario())


def test_no_handler_is_remote_error():
    async def scenario():
        a = TcpChannel(f"127.0.0.1:{BASE + 30}")
        b = make_echo_channel(f"127.0.0.1:{BASE + 31}")
        await a.listen()
        await b.listen()
        fut = asyncio.get_event_loop().create_future()
        a.request(b.host_port, "/nope", None, None, 5000,
                  lambda err, *res: fut.set_result(err))
        err = await fut
        assert err is not None
        assert "no handler" in str(err)
        a.close()
        b.close()

    run(scenario())


def test_two_ringpops_converge_over_tcp():
    """Two real RingPop nodes gossip to one checksum over localhost TCP."""
    from ringpop_tpu.clock import AsyncioScheduler
    from ringpop_tpu.ringpop import RingPop

    async def scenario():
        loop = asyncio.get_event_loop()
        hosts = [f"127.0.0.1:{BASE + 40}", f"127.0.0.1:{BASE + 41}"]
        nodes = []
        for host_port in hosts:
            channel = TcpChannel(host_port, loop)
            node = RingPop(app="tcp-test", host_port=host_port, channel=channel,
                           clock=AsyncioScheduler(loop))
            node.setup_channel()
            await channel.listen()
            nodes.append(node)
        boot = [loop.create_future() for _ in nodes]
        for node, fut in zip(nodes, boot):
            node.bootstrap(hosts, lambda err, joined=None, fut=fut:
                           fut.set_result(err))
        errs = await asyncio.gather(*boot)
        assert all(e is None for e in errs), errs
        for _ in range(100):
            checksums = {n.membership.checksum for n in nodes}
            if len(checksums) == 1 and None not in checksums:
                break
            await asyncio.sleep(0.1)
        assert len({n.membership.checksum for n in nodes}) == 1
        assert nodes[0].membership.get_member_count() == 2
        for node in nodes:
            node.destroy()

    run(scenario(), timeout=30)


def test_forwarding_over_tcp():
    """handleOrProxy end to end across real sockets: the non-owner
    forwards to the key's owner, which answers via the 'request' event
    (test/integration/proxy-test.js shape, on the TCP transport)."""
    from ringpop_tpu.clock import AsyncioScheduler
    from ringpop_tpu.request_proxy.http import ProxyRequest, ProxyResponse
    from ringpop_tpu.ringpop import RingPop

    async def scenario():
        loop = asyncio.get_event_loop()
        hosts = [f"127.0.0.1:{BASE + 50}", f"127.0.0.1:{BASE + 51}"]
        nodes = []
        for host_port in hosts:
            channel = TcpChannel(host_port, loop)
            node = RingPop(app="tcp-proxy", host_port=host_port,
                           channel=channel, clock=AsyncioScheduler(loop))
            node.setup_channel()
            await channel.listen()
            nodes.append(node)
        boot = [loop.create_future() for _ in nodes]
        for node, fut in zip(nodes, boot):
            node.bootstrap(hosts, lambda err, joined=None, fut=fut:
                           fut.set_result(err))
        assert all(e is None for e in await asyncio.gather(*boot))
        for _ in range(100):
            if len({n.membership.checksum for n in nodes}) == 1:
                break
            await asyncio.sleep(0.05)

        sender = nodes[0]
        key = next(f"k{i}" for i in range(1000)
                   if sender.lookup(f"k{i}") != sender.whoami())
        owner = next(n for n in nodes if n.whoami() == sender.lookup(key))

        def on_request(req, res, head):
            assert head["ringpopKeys"] == [key]
            res.status_code = 200
            res.end(f"handled:{req.body}")

        owner.on("request", on_request)

        done: asyncio.Future = loop.create_future()
        req = ProxyRequest(url="/data", method="PUT", body="payload")
        res = ProxyResponse(lambda err, resp: done.set_result((err, resp)))
        assert sender.handle_or_proxy(key, req, res) is None
        err, resp = await asyncio.wait_for(done, 10)
        assert err is None
        assert resp.body == "handled:payload"
        for node in nodes:
            node.destroy()

    run(scenario(), timeout=30)


def test_large_frame_roundtrip():
    """Frames far beyond asyncio's default 64 KiB stream limit survive.

    Join/full-sync/stats bodies exceed 64 KiB at a few hundred members
    (reference bodies are unbounded JSON); the stream limit must be the
    protocol's MAX_FRAME_BYTES, not asyncio's default."""
    async def scenario():
        a = TcpChannel(f"127.0.0.1:{BASE + 20}")
        b = make_echo_channel(f"127.0.0.1:{BASE + 21}")
        await a.listen()
        await b.listen()
        fut = asyncio.get_event_loop().create_future()
        big = "x" * (512 * 1024)  # 512 KiB body
        a.request(
            b.host_port, "/echo", "HEAD", json.dumps({"x": big}), 10000,
            lambda err, res1, res2=None: fut.set_result((err, res1, res2)),
        )
        err, res1, res2 = await fut
        assert err is None
        assert json.loads(res2)["echo"] == big
        a.close()
        b.close()

    run(scenario())

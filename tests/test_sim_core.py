"""Simulation-core behavior: gossip convergence, failure detection,
refutation, full sync, dissemination budget — the tensorized versions of
the reference's swim/dissemination semantics (SURVEY §3.2, §3.3)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ringpop_tpu.models import swim_sim as sim
from ringpop_tpu.models.cluster import SimCluster
from ringpop_tpu.models.swim_sim import SwimParams


FAST = SwimParams(suspicion_ticks=5)


def test_converged_start_stays_converged():
    c = SimCluster(8, FAST, seed=1)
    assert c.converged()
    c.tick(10)
    assert c.converged()
    assert len(c.checksum_groups()) == 1


def test_rumor_spreads_after_join():
    # One newcomer joins via one seed; gossip disseminates to all.
    c = SimCluster(16, FAST, seed=2, init="converged")
    n_new = 15
    c.state = sim.revive(c.state, n_new, int(1e6))
    # everyone else currently believes n_new alive at inc 0; the revived
    # node re-joins with a higher incarnation via node 0
    c.join(n_new, 0)
    ticks = c.run_until_converged(200)
    assert ticks > 0
    # all views agree on the new incarnation
    vi = np.asarray(c.state.view_inc)
    assert (vi[:, n_new] == int(1e6)).all()


def test_kill_leads_to_suspect_then_faulty_convergence():
    c = SimCluster(12, FAST, seed=3)
    c.kill(3)
    # views may transiently agree on "suspect"; run past the suspicion
    # deadline so every viewer's timer fires and faulty disseminates
    c.tick(3 * FAST.suspicion_ticks)
    ticks = c.run_until_converged(300)
    assert ticks > 0
    vs = np.asarray(c.state.view_status)
    live = c.live_indices()
    assert 3 not in live
    assert (vs[live, 3] == sim.FAULTY).all()
    # faulty members are retained in the list (architecture_design.md:19)
    assert any(m["address"] == c.book.addresses[3] and m["status"] == "faulty"
               for m in c.members(int(live[0])))


def test_suspect_refutation_restores_alive():
    # Partition one node away briefly: peers suspect it; heal before the
    # suspicion deadline; the node refutes with a higher incarnation.
    c = SimCluster(10, SwimParams(suspicion_ticks=50), seed=4)
    c.partition([[9], list(range(9))])
    c.tick(6)  # long enough for some peer to fail a probe and suspect 9
    vs = np.asarray(c.state.view_status)
    assert (vs[:9, 9] == sim.SUSPECT).any()
    c.heal_partition()
    ticks = c.run_until_converged(400)
    assert ticks > 0
    vs = np.asarray(c.state.view_status)
    vi = np.asarray(c.state.view_inc)
    assert (vs[:, 9] == sim.ALIVE).all()
    assert (vi[:, 9] > 0).all()  # incarnation bumped by refutation


def test_partition_healed_before_deadline_refutes():
    # Heal within the suspicion window: cross-side suspects refute via
    # incarnation bumps and the split repairs (BASELINE config 4 flow).
    c = SimCluster(16, SwimParams(suspicion_ticks=40), seed=5)
    c.partition([list(range(8)), list(range(8, 16))])
    c.tick(8)  # suspects accumulate on both sides
    vs = np.asarray(c.state.view_status)
    assert (vs[:8, 8:] == sim.SUSPECT).any()
    c.heal_partition()
    ticks = c.run_until_converged(600)
    assert ticks > 0
    vs = np.asarray(c.state.view_status)
    assert (vs[:, :] == sim.ALIVE).all()


def test_partition_to_mutual_faulty_heals_via_rejoin():
    # A split held past the suspicion deadline converges to mutual
    # faulty; like the reference (faulty members are never probed), the
    # repair is operational: restart/rejoin with fresh incarnations
    # (docs/architecture_design.md:19 — faulty members are retained so
    # merges stay possible).
    c = SimCluster(12, FAST, seed=5)
    c.partition([list(range(6)), list(range(6, 12))])
    c.tick(80)
    vs = np.asarray(c.state.view_status)
    assert (vs[0, 6:] == sim.FAULTY).all()
    assert (vs[6, :6] == sim.FAULTY).all()
    c.heal_partition()
    for i in range(6, 12):
        c.revive(i, seed=0)
    ticks = c.run_until_converged(800)
    assert ticks > 0
    vs = np.asarray(c.state.view_status)
    live = c.live_indices()
    assert len(live) == 12
    assert (vs[np.ix_(live, live)] == sim.ALIVE).all()


def test_leave_stops_gossip_and_disseminates():
    c = SimCluster(8, FAST, seed=6)
    c.leave(5)
    assert 5 not in c.live_indices()
    c.run_until_converged(200)
    vs = np.asarray(c.state.view_status)
    live = c.live_indices()
    assert (vs[live, 5] == sim.LEAVE).all()


def test_loss_still_converges():
    c = SimCluster(12, SwimParams(suspicion_ticks=8, loss=0.10), seed=7)
    c.kill(1)
    ticks = c.run_until_converged(500)
    assert ticks > 0


def test_piggyback_eviction_bounds_changes():
    c = SimCluster(8, FAST, seed=8)
    c.kill(2)
    c.run_until_converged(300)
    # after convergence + eviction, rumor buffers drain
    c.tick(200)
    pb = np.asarray(c.state.pb)
    live = c.live_indices()
    assert (pb[live] == -1).all(), "all changes evicted after quiescence"


def test_suspend_resume_rejoins_without_restart():
    # SIGSTOP analog: node keeps state, peers declare it faulty; on
    # resume it refutes and returns (tick-cluster.js:432-446).
    c = SimCluster(10, FAST, seed=9)
    c.suspend(4)
    c.tick(3 * FAST.suspicion_ticks)
    c.run_until_converged(300)
    vs = np.asarray(c.state.view_status)
    assert (vs[c.live_indices(), 4] == sim.FAULTY).all()
    c.resume(4)
    ticks = c.run_until_converged(500)
    assert ticks > 0
    vs = np.asarray(c.state.view_status)
    assert (vs[c.live_indices(), 4] == sim.ALIVE).all()


def test_metrics_shape():
    c = SimCluster(6, FAST, seed=10)
    m = c.tick()
    for k in ("pings_sent", "acks", "full_syncs", "suspects_declared"):
        assert k in m
    assert m["pings_sent"] == 6
    assert m["acks"] == 6


def test_swim_run_scan_matches_steps():
    # swim_run (lax.scan) and repeated swim_step agree given same keys.
    params = SwimParams(suspicion_ticks=5)
    net = sim.make_net(8)
    key = jax.random.PRNGKey(0)
    keys = jax.random.split(key, 4)
    # swim_step/swim_run donate their state argument, so each chain needs
    # its own freshly materialized state.
    st_a = sim.init_state(8)
    for k in keys:
        st_a, _ = sim.swim_step(st_a, net, k, params)
    st_b = sim.init_state(8)
    st_b, _ = sim.swim_step(st_b, net, keys[0], params)
    st_b, _ = sim.swim_run(st_b, net, key, params, 3)  # differing keys ok:
    # only assert structural invariants, not equality of random streams
    assert int(st_a.tick) == 4
    assert int(st_b.tick) == 4


def test_sim_damping_flapping_node_quarantined_then_reinstated():
    """Damping extension in the simulation: a node that flaps (driven by
    forced suspect declarations + refutations) accumulates damp score at
    its peers, crosses the suppress limit, disappears from derived rings,
    then decays back in (mirrors damping.py semantics)."""
    import numpy as np
    from ringpop_tpu.models import swim_sim as sim
    from ringpop_tpu.models.cluster import SimCluster

    params = sim.SwimParams(
        damp_penalty=1000.0,
        damp_suppress=2000.0,
        damp_reuse=400.0,
        damp_decay_per_tick=0.98,
    )
    c = SimCluster(12, params, seed=3, damping=True)
    flappy = 4

    # Force flaps: repeatedly suspend flappy until peers suspect it, then
    # resume so its refutation (alive) propagates — transitions touching
    # alive on every peer that applies them.
    for _ in range(8):
        c.suspend(flappy)
        c.tick(4)
        c.resume(flappy)
        c.tick(4)

    assert c.damped_pairs() > 0, "no damped pairs after repeated flapping"
    viewers = [i for i in range(12) if i != flappy]
    damped_row = np.asarray(c.state.damped)
    some_viewer = next(i for i in viewers if damped_row[i, flappy])
    ring = c.ring_for(some_viewer)
    assert not ring.has_server(c.book.addresses[flappy])

    # Quiet decay: scores fall below reuse, damped bits clear.
    c.tick(250)
    assert c.damped_pairs() == 0
    ring = c.ring_for(some_viewer)
    assert ring.has_server(c.book.addresses[flappy])


def test_sim_damping_checkpoint_roundtrip(tmp_path):
    from ringpop_tpu import checkpoint
    from ringpop_tpu.models import swim_sim as sim
    from ringpop_tpu.models.cluster import SimCluster
    import numpy as np

    c = SimCluster(8, sim.SwimParams(), seed=1, damping=True)
    c.tick(3)
    path = str(tmp_path / "damp.npz")
    checkpoint.save(c, path)
    r = checkpoint.load(path)
    assert r.state.damp is not None and r.state.damped is not None
    assert np.array_equal(np.asarray(c.state.damp), np.asarray(r.state.damp))
    r.tick(2); c.tick(2)
    assert np.array_equal(np.asarray(c.state.damped), np.asarray(r.state.damped))


def test_gid_partition_matches_mask_partition():
    """The int32[N] group-id adjacency form must produce the exact mask
    trajectory for block partitions (swim_sim._adj) — it exists so a
    65k netsplit never materializes the 17 GB N x N mask."""
    import jax

    n = 12
    half = n // 2
    params = sim.SwimParams(loss=0.02, suspicion_ticks=4)
    ids = np.arange(n)
    mask = jnp.asarray((ids[:, None] < half) == (ids[None, :] < half))
    gid = (jnp.arange(n, dtype=jnp.int32) >= half).astype(jnp.int32)
    ones = jnp.ones((n,), bool)
    net_m = sim.NetState(up=ones, responsive=ones, adj=mask)
    net_g = sim.NetState(up=ones, responsive=ones, adj=gid)
    st_m = sim.init_state(n)
    st_g = sim.init_state(n)
    key = jax.random.PRNGKey(3)
    keys = jax.random.split(key, 40)
    for t in range(40):
        if t == 20:  # heal, keeping each net's pytree structure
            net_m = net_m._replace(adj=jnp.ones((n, n), bool))
            net_g = net_g._replace(adj=jnp.zeros((n,), jnp.int32))
        st_m, _ = sim.swim_step(st_m, net_m, keys[t], params)
        st_g, _ = sim.swim_step(st_g, net_g, keys[t], params)
        np.testing.assert_array_equal(
            np.asarray(st_m.view_key), np.asarray(st_g.view_key), err_msg=f"tick {t}"
        )
        np.testing.assert_array_equal(
            np.asarray(st_m.suspect_left), np.asarray(st_g.suspect_left)
        )


@pytest.mark.slow
@pytest.mark.parametrize("small_n", [None, 16])
def test_receiver_merge_forms_trace_identical_trajectories(monkeypatch, small_n):
    """The sorted (sort + run-max doubling), scatter, and pallas
    (ops/recv_merge_pallas.py, interpret mode on CPU) receiver-merge
    lowerings produce bit-identical trajectories through kill + loss —
    covering the phase-3 merge and the phase-5a-5c stage merges (the
    kill forces failed probes into the ping-req exchange).  The
    ``small_n=16`` leg lowers _SPARSE_SMALL_N below n so the
    large-row block-prefix selection path runs under every form too.
    _RECV_MERGE / _SPARSE_SMALL_N are read at trace time, so each form
    is retraced from a cleared jit cache."""
    n = 48
    params = sim.SwimParams(loss=0.05, suspicion_ticks=8)
    if small_n is not None:
        monkeypatch.setattr(sim, "_SPARSE_SMALL_N", small_n)
    finals = []
    try:
        for form in ("sorted", "scatter", "pallas"):
            monkeypatch.setattr(sim, "_RECV_MERGE", form)
            jax.clear_caches()
            state = sim.init_state(n)
            net = sim.make_net(n)
            net = net._replace(up=net.up.at[5].set(False))
            keys = jax.random.split(jax.random.PRNGKey(9), 30)
            for t in range(30):
                state, _ = sim.swim_step(state, net, keys[t], params)
            finals.append(np.asarray(state.view_key))
    finally:
        # the last form's executables must not outlive the restored
        # module globals (later tests would silently run them)
        jax.clear_caches()
    np.testing.assert_array_equal(finals[0], finals[1])
    np.testing.assert_array_equal(finals[0], finals[2])


def test_pallas_recv_merge_short_trajectory_parity(monkeypatch):
    """Fast tier-1 representative of the slow grid above: the pallas
    lowering stays bit-identical to sorted through a kill + loss run
    long enough to exercise the ping-req stage merges."""
    n = 24
    params = sim.SwimParams(loss=0.05, suspicion_ticks=6)
    finals = []
    try:
        for form in ("sorted", "pallas"):
            monkeypatch.setattr(sim, "_RECV_MERGE", form)
            jax.clear_caches()
            state = sim.init_state(n)
            net = sim.make_net(n)
            net = net._replace(up=net.up.at[3].set(False))
            keys = jax.random.split(jax.random.PRNGKey(2), 10)
            for t in range(10):
                state, _ = sim.swim_step(state, net, keys[t], params)
            finals.append(np.asarray(state.view_key))
    finally:
        jax.clear_caches()
    np.testing.assert_array_equal(finals[0], finals[1])

"""Traffic plane: workloads, masked ring kernels, handle-or-forward.

The load-bearing oracle here is `test_scenario_traffic_misroute_oracle`:
per-tick misroute counts from the compiled scenario+traffic scan must
bit-match a host-side loop that steps the identical key schedule and
resolves the identical key batch through ``ring_for(viewer).lookup()``
(the reference's per-viewer host ring) against a ground-truth ring of
the actually-live nodes — on both backends.
"""

from __future__ import annotations

import random

import jax.numpy as jnp
import numpy as np
import pytest

from ringpop_tpu.hashring import HashRing
from ringpop_tpu.models import swim_delta as sdelta
from ringpop_tpu.models import swim_sim as sim
from ringpop_tpu.models.cluster import SimCluster
from ringpop_tpu.models.swim_sim import SwimParams
from ringpop_tpu.ops import ring_ops
from ringpop_tpu.ops.farmhash import farmhash32
from ringpop_tpu.traffic import engine as tengine
from ringpop_tpu.traffic.workloads import WorkloadSpec, compile_traffic

N = 10
ADDRS = [f"10.0.0.{i}:{3000 + i}" for i in range(N)]


# -- workloads ---------------------------------------------------------------


def test_workload_spec_parsing_and_validation():
    ws = WorkloadSpec.from_spec("zipf:512:2048")
    assert (ws.kind, ws.keys_per_tick, ws.pool) == ("zipf", 512, 2048)
    ws = WorkloadSpec.from_spec({"kind": "tenant", "tenants": 4, "viewers": [0, 2]})
    assert ws.viewers == (0, 2)
    with pytest.raises(ValueError):
        WorkloadSpec.from_spec("bogus:8").validate(N)
    with pytest.raises(ValueError):
        WorkloadSpec.from_spec({"viewers": [99]}).validate(N)
    with pytest.raises(ValueError):
        WorkloadSpec(every=0).validate(N)


def test_pool_hashes_match_host_farmhash():
    ct = compile_traffic({"pool": 64, "keys_per_tick": 8}, N, ADDRS)
    hashes = np.asarray(ct.tensors.pool)
    for i, key in enumerate(ct.spec.pool_keys()):
        assert int(hashes[i]) == farmhash32(key)


def test_sampler_replayable_and_skewed():
    ct = compile_traffic({"kind": "zipf", "pool": 256, "keys_per_tick": 512,
                          "zipf_s": 1.4}, N, ADDRS)
    t = jnp.int32(7)
    idx1, view1 = tengine.sample_tick(ct.tensors, t, ct.static.m)
    idx2, view2 = tengine.sample_tick(ct.tensors, t, ct.static.m)
    assert np.array_equal(np.asarray(idx1), np.asarray(idx2))
    assert np.array_equal(np.asarray(view1), np.asarray(view2))
    # different ticks draw different batches
    idx3, _ = tengine.sample_tick(ct.tensors, jnp.int32(8), ct.static.m)
    assert not np.array_equal(np.asarray(idx1), np.asarray(idx3))
    # zipf: rank-0 strictly hotter than the tail
    counts = np.bincount(np.asarray(idx1), minlength=256)
    assert counts[0] > counts[128:].max()
    assert np.asarray(view1).min() >= 0 and np.asarray(view1).max() < N


# -- masked lookup kernels ---------------------------------------------------


def _full_window(ring):
    return ring.hashes.shape[0]


def test_lookup_masked_parity_random_subsets():
    """Masked lookup over the global ring == a host HashRing built from
    exactly the masked server subset (full-window walk: exact)."""
    ring = ring_ops.build_ring(ADDRS)
    rng = random.Random(11)
    keys = [f"key-{rng.randrange(10 ** 9)}" for _ in range(200)]
    kh = jnp.asarray(np.array([farmhash32(k) for k in keys], dtype=np.uint32))
    for trial in range(4):
        alive = np.array([rng.random() < 0.6 for _ in range(N)])
        alive[trial % N] = True  # never empty
        host = HashRing()
        host.add_remove_servers(
            [a for a, ok in zip(ADDRS, alive) if ok], []
        )
        mask = jnp.broadcast_to(jnp.asarray(alive)[None, :], (len(keys), N))
        owners, found = tengine.lookup_masked_idx(
            ring.hashes, ring.owners, kh, mask, window=_full_window(ring)
        )
        assert bool(np.asarray(found).all())
        for k, o in zip(keys, np.asarray(owners)):
            assert ADDRS[o] == host.lookup(k), (trial, k)


def test_lookup_n_masked_parity():
    ring = ring_ops.build_ring(ADDRS)
    rng = random.Random(13)
    keys = [f"pref-{rng.randrange(10 ** 9)}" for _ in range(100)]
    kh = jnp.asarray(np.array([farmhash32(k) for k in keys], dtype=np.uint32))
    alive = np.ones(N, dtype=bool)
    alive[[2, 5, 6]] = False
    host = HashRing()
    host.add_remove_servers([a for a, ok in zip(ADDRS, alive) if ok], [])
    mask = jnp.broadcast_to(jnp.asarray(alive)[None, :], (len(keys), N))
    owners, complete = tengine.lookup_n_masked_idx(
        ring.hashes, ring.owners, kh, mask, 4, window=_full_window(ring)
    )
    assert bool(np.asarray(complete).all())
    for k, row in zip(keys, np.asarray(owners)):
        got = [ADDRS[i] for i in row if i >= 0]
        assert got == host.lookup_n(k, 4), k


def test_lookup_masked_reports_window_exhaustion():
    """A window too small to reach any in-mask replica must say so, not
    fabricate an owner."""
    ring = ring_ops.build_ring(ADDRS)
    only = np.zeros(N, dtype=bool)
    only[4] = True
    kh = jnp.asarray(
        np.array([farmhash32(f"k{i}") for i in range(64)], dtype=np.uint32)
    )
    mask = jnp.broadcast_to(jnp.asarray(only)[None, :], (64, N))
    owners, found = tengine.lookup_masked_idx(
        ring.hashes, ring.owners, kh, mask, window=2
    )
    f = np.asarray(found)
    assert not f.all()  # with 1/10 of replicas in-mask, W=2 misses some
    assert (np.asarray(owners)[~f] == -1).all()
    assert (np.asarray(owners)[f] == 4).all()


# -- handle-or-forward oracle ------------------------------------------------


def _host_serve_counts(cluster, ct, t):
    """The reference-semantics host model of one traffic tick: sample
    the identical batch, resolve through ``ring_for(viewer).lookup``,
    follow the forward chain on per-holder host rings, compare against
    a ground-truth ring of the actually-live nodes."""
    m = ct.static.m
    idx, viewers = tengine.sample_tick(ct.tensors, jnp.int32(t), m)
    idx, viewers = np.asarray(idx), np.asarray(viewers)
    keys = ct.spec.pool_keys()
    live = set(int(i) for i in cluster.live_indices())
    truth = HashRing()
    truth.add_remove_servers([cluster.book.addresses[i] for i in sorted(live)], [])
    addr_index = cluster.book.index
    rings: dict[int, HashRing] = {}

    def ring_of(node):
        if node not in rings:
            rings[node] = cluster.ring_for(node)
        return rings[node]

    counts = {k: 0 for k in ("lookups", "dropped", "handled_local",
                             "misroutes", "proxy_retries", "delivered",
                             "proxy_failed")}
    for kidx, v in zip(idx, viewers):
        v = int(v)
        if v not in live:
            counts["dropped"] += 1
            continue
        key = keys[int(kidx)]
        counts["lookups"] += 1
        owner0 = addr_index[ring_of(v).lookup(key)]
        if truth.lookup(key) != cluster.book.addresses[owner0]:
            counts["misroutes"] += 1
        if owner0 == v:
            counts["handled_local"] += 1
            counts["delivered"] += 1
            continue
        h, retries, settled = owner0, 0, False
        while True:
            if h not in live:
                # failed send; the origin's retry re-resolves the same
                # frozen view -> same holder
                if retries < ct.static.max_retries:
                    retries += 1
                    continue
                break
            nxt = addr_index[ring_of(h).lookup(key)]
            if nxt == h:
                settled = True
                break
            if retries < ct.static.max_retries:
                retries += 1
                h = nxt
                continue
            break
        counts["proxy_retries"] += retries
        if settled:
            counts["delivered"] += 1
        else:
            counts["proxy_failed"] += 1
    return counts


# The workload every scenario-coupled test shares: identical statics
# and tensor shapes mean ONE compiled scenario+traffic program per
# backend serves the whole module (the jit cache does the rest).
ORACLE_TICKS = 12
ORACLE_WL = {"kind": "uniform", "keys_per_tick": 24, "pool": 256,
             "window": N * ring_ops.DEFAULT_REPLICA_POINTS}  # exact walk


@pytest.mark.parametrize(
    "backend",
    ["dense", pytest.param("delta", marks=pytest.mark.slow)],
)
def test_scenario_traffic_misroute_oracle(backend):
    """Acceptance oracle: per-tick serving counters from the compiled
    scenario+traffic scan bit-match the host loop (same key schedule,
    same sampled batch, ``ring_for`` host rings, truth = live ring).

    Tier-1 runs the dense arm; the delta twin is identical machinery
    on the O(N*C) state and rides the nightly slow lane (suite budget:
    each backend's scenario+traffic program is its own XLA compile).
    """
    ticks, kill_at = ORACLE_TICKS, 3
    spec = {"ticks": ticks, "events": [{"at": kill_at, "op": "kill", "node": 2}]}
    a = SimCluster(N, SwimParams(), seed=5, backend=backend)
    ct = a.compile_traffic(ORACLE_WL)
    trace = a.run_scenario(spec, traffic=ct)

    from ringpop_tpu.scenarios import compile as scompile
    from ringpop_tpu.scenarios.spec import ScenarioSpec

    b = SimCluster(N, SwimParams(), seed=5, backend=backend)
    compiled = scompile.compile_spec(ScenarioSpec.from_dict(spec), N)
    keys = scompile.key_schedule(b._split, compiled)
    for t in range(ticks):
        if t == kill_at:
            b.kill(2)
        if backend == "delta":
            b.state, _ = sdelta.delta_step(
                b.state, b.net, keys[t], params=b.dparams
            )
        else:
            b.state, _ = sim.swim_step(
                b.state, b.net, keys[t], params=b.params
            )
        want = _host_serve_counts(b, ct, t)
        for name, value in want.items():
            got = int(trace.metrics[name][t])
            assert got == value, (t, name, got, value)
    # churn actually exercised the misroute path
    assert trace.metrics["misroutes"].sum() > 0


def test_traffic_does_not_perturb_protocol_and_bridges_serving_keys():
    """One scenario, run with and without traffic: (a) every protocol
    series is bit-identical (the workload PRNG is its own stream), and
    (b) the traffic-coupled trace streams the serving-plane keys
    through the stats bridge while the traffic-free one does not."""
    from ringpop_tpu.obs import bridge
    from ringpop_tpu.obs.emitters import CaptureEmitter

    # same shapes/statics as the oracle test -> the with-traffic program
    # is a jit-cache hit, not a fresh XLA compile
    spec = {"ticks": ORACLE_TICKS,
            "events": [{"at": 2, "op": "kill", "node": 1}]}
    cap_a, cap_b = CaptureEmitter(), CaptureEmitter()
    a = SimCluster(N, SwimParams(), seed=9, stats_emitter=cap_a)
    ta = a.run_scenario(spec, traffic=a.compile_traffic(ORACLE_WL))
    b = SimCluster(N, SwimParams(), seed=9, stats_emitter=cap_b)
    tb = b.run_scenario(spec)
    for name, series in tb.metrics.items():
        assert np.array_equal(ta.metrics[name], series), name
    assert np.array_equal(ta.converged, tb.converged)
    assert np.array_equal(ta.live, tb.live)
    suffixes_a = cap_a.suffixes(bridge.DEFAULT_PREFIX)
    suffixes_b = cap_b.suffixes(bridge.DEFAULT_PREFIX)
    for key in bridge.TRAFFIC_KEYS:
        if key == "lookupn":
            continue  # lookup_n disabled in this workload
        assert key in suffixes_a, key
        assert key not in suffixes_b, key
    assert "sim.misroutes" in suffixes_a
    assert "sim.ring-divergence" in suffixes_a
    assert set(bridge.REFERENCE_KEYS) <= suffixes_b


# -- satellites --------------------------------------------------------------


def test_lookup_batch_matches_host_loop():
    c = SimCluster(N, SwimParams(), seed=4)
    c.kill(3)
    c.tick(4)  # let some views diverge
    keys = [f"user:{i}" for i in range(50)]
    for viewer in (0, 7):
        got = c.lookup_batch(keys, viewer=viewer)
        want = [c.lookup(k, viewer=viewer) for k in keys]
        assert got == want
    # host-fallback path: a bootstrap-shaped view whose ring holds only
    # the viewer itself — with 1/N of the replicas in-mask the windowed
    # walk misses for some keys, and the fallback must keep parity
    s = SimCluster(N, SwimParams(), seed=0, init="self")
    got = s.lookup_batch(keys, viewer=2)
    assert got == [s.lookup(k, viewer=2) for k in keys]
    assert set(got) == {s.book.addresses[2]}


def test_ringpop_lookup_timing_stats():
    from ringpop_tpu.ringpop import RingPop

    rp = RingPop(app="t", host_port="127.0.0.1:3000")
    rp.ring.add_remove_servers(ADDRS, [])
    for i in range(20):
        rp.lookup(f"k{i}")
    rp.lookup_n("k0", 3)
    stats = rp.get_stats()
    assert stats["lookup"]["count"] == 20
    assert stats["lookupN"]["count"] == 1
    for agg in (stats["lookup"], stats["lookupN"]):
        for field in ("median", "p95", "p99"):
            assert field in agg
    rp.destroy()


def test_compiled_traffic_rejects_foreign_cluster():
    """A workload lowered against one cluster must not run on another:
    foreign viewer ids / ring tables would clamp silently inside jitted
    gathers and report bogus counters."""
    big = SimCluster(16, SwimParams(), seed=0)
    ct = big.compile_traffic({"keys_per_tick": 8, "pool": 32})
    small = SimCluster(N, SwimParams(), seed=0)
    with pytest.raises(ValueError, match="lowered for n=16"):
        small.compile_traffic(ct)


def test_damping_quarantine_parity():
    """Damped members are quarantined from served rings exactly like
    the host ``ring_for`` (damping extension): the engine's counters
    with the damped mask bit-match the host serve model, and the
    quarantined owner's keys misroute vs the (liveness-only) truth."""
    c = SimCluster(N, SwimParams(), seed=6, damping=True)
    c.state = c.state._replace(damped=c.state.damped.at[:, 4].set(True))
    ct = c.compile_traffic(ORACLE_WL)
    out = tengine.serve_once(
        c.state.view_key, c.net.up, c.net.responsive, ct.tensors,
        jnp.int32(0), static=ct.static, damped=c.state.damped,
    )
    want = _host_serve_counts(c, ct, 0)
    for name, value in want.items():
        assert int(out[name]) == value, name
    assert int(out["misroutes"]) > 0  # node 4's arcs route elsewhere


def test_serve_once_single_dispatch_smoke():
    """The standalone serving entry: one jitted dispatch against a
    state snapshot, counters consistent with the schema."""
    c = SimCluster(N, SwimParams(), seed=2)
    ct = c.compile_traffic({"keys_per_tick": 32, "pool": 128, "lookup_n": 3})
    out = tengine.serve_once(
        c.state.view_key, c.net.up, c.net.responsive, ct.tensors,
        jnp.int32(0), static=ct.static,
    )
    assert set(out.keys()) == set(tengine.counter_names(ct.static))
    vals = {k: int(v) for k, v in out.items()}
    assert vals["lookups"] + vals["dropped"] == ct.static.m
    assert vals["lookups"] == vals["delivered"]  # converged: all served
    assert vals["misroutes"] == 0
    assert vals["lookupns"] == vals["lookups"]
    assert vals["lookupn_incomplete"] == 0

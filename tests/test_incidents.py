"""Incident library: builders, reference specs, the golden lane.

Fast lane is host-only (builders validate across sizes, the checked-in
reference specs match the library's rendering, summary arithmetic,
catalog/CLI listing).  The golden grid — every (incident, backend)
pair run at the pinned configuration and bit-compared against
``tests/golden/incidents/*.json`` — compiles one scenario+traffic
program per pair and rides the nightly slow lane (re-pin after an
intentional change with ``python tools/pin_incidents.py``).
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from ringpop_tpu.scenarios import library as lib
from ringpop_tpu.scenarios.trace import Trace
from ringpop_tpu.utils.jaxpin import golden_skip_reason

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "golden", "incidents")

GOLDEN_PAIRS = [
    (name, backend)
    for name in lib.incident_names()
    for backend in lib.INCIDENTS[name].backends
]

POLICY_TRIPLES = lib.policy_golden_grid()


# ---------------------------------------------------------------------------
# fast: host-only
# ---------------------------------------------------------------------------


def test_catalog_shape():
    assert len(lib.INCIDENTS) >= 6
    both = [n for n, i in lib.INCIDENTS.items() if i.backends == ("dense", "delta")]
    # the acceptance floor: at least six incidents run on BOTH backends
    assert len(both) >= 6, both
    text = lib.format_catalog()
    for name in lib.incident_names():
        assert name in text


@pytest.mark.parametrize("name", lib.incident_names())
def test_builders_validate_across_sizes(name):
    for n in (8, 16, 64, 100):
        spec, wl = lib.build_incident(name, n)
        assert spec.ticks >= 40
        assert wl.latency_buckets == lib.LATENCY_BUCKETS
        # ticks override scales the windows without breaking validation
        spec2, _ = lib.build_incident(name, n, ticks=spec.ticks + 60)
        assert spec2.ticks == spec.ticks + 60
    with pytest.raises(ValueError):
        lib.build_incident(name, 4)  # too small
    with pytest.raises(ValueError):
        lib.build_incident("no_such_incident", 16)


def test_dense_only_incidents_reject_delta():
    dense_only = [
        n for n, i in lib.INCIDENTS.items() if i.backends == ("dense",)
    ]
    assert dense_only  # revive-bearing incidents exist and say so
    for name in dense_only:
        assert any(
            e.op in ("revive", "rolling_restart")
            for e in lib.build_incident(name, 16)[0].events
        )
        with pytest.raises(ValueError, match="dense"):
            lib.build_incident(name, 16, backend="delta")


def test_reference_specs_in_sync():
    """The checked-in scenarios/specs/*.json match the library's
    rendering — the JSON is a durable artifact, the builder is the
    source of truth (re-render via tools/pin_incidents.py)."""
    for name in lib.incident_names():
        path = os.path.join(lib.SPEC_DIR, f"{name}.json")
        assert os.path.exists(path), f"missing reference spec {path}"
        with open(path) as f:
            on_disk = json.load(f)
        assert on_disk == lib.spec_document(name), (
            f"{path} is stale; re-render with tools/pin_incidents.py"
        )


def test_incident_summary_arithmetic():
    ticks = 8
    conv = np.zeros(ticks, bool)
    conv[5:] = True
    metrics = {
        "faulty_declared": np.array([0, 0, 2, 0, 0, 0, 0, 0], np.int32),
        "suspects_declared": np.array([0, 1, 2, 0, 0, 0, 0, 0], np.int32),
        "lookups": np.full(ticks, 10, np.int32),
        "delivered": np.full(ticks, 9, np.int32),
        "dropped": np.zeros(ticks, np.int32),
        "misroutes": np.array([0, 0, 3, 1, 0, 0, 0, 0], np.int32),
        "proxy_failed": np.ones(ticks, np.int32),
        "handled_local": np.full(ticks, 4, np.int32),
        "proxy_sends": np.full(ticks, 5, np.int32),
        "proxy_retries": np.full(ticks, 2, np.int32),
        "gray_timeouts": np.full(ticks, 1, np.int32),
        "ov_gray_nodes": np.array([0, 1, 3, 2, 0, 0, 0, 0], np.int32),
        "ov_pressure_max": np.array([0, 9, 40, 12, 0, 0, 0, 0], np.int32),
    }
    hist = np.zeros((ticks, 4), np.int32)
    hist[:, 0] = 9
    trace = Trace(
        metrics=metrics, converged=conv, live=np.full(ticks, 9, np.int32),
        loss=np.zeros(ticks, np.float32), n=10, backend="dense",
        planes={"lat_hist_ms": hist},
    )
    s = lib.incident_summary(trace)
    assert s["detect_tick"] == 2
    assert s["heal_tick"] == 5
    assert s["final_live"] == 9
    assert s["sends"] == ticks * (4 + 5 + 2)
    assert s["ov_gray_peak"] == 3
    assert s["ov_pressure_peak"] == 40
    assert s["lat_p50_ms"] == 0
    assert all(isinstance(v, int) for v in s.values())
    # never-converged and never-detected report -1
    trace2 = Trace(
        metrics={k: np.zeros(ticks, np.int32) for k in
                 ("faulty_declared", "suspects_declared")},
        converged=np.zeros(ticks, bool), live=np.full(ticks, 10, np.int32),
        loss=np.zeros(ticks, np.float32), n=10, backend="dense",
    )
    s2 = lib.incident_summary(trace2)
    assert s2["detect_tick"] == -1 and s2["heal_tick"] == -1
    line = lib.format_summary("x", s)
    assert "goodput" in line and "amplification" in line


def test_overload_control_build():
    spec, _ = lib.build_incident("cascading_overload", 16, overload=False)
    assert not any(e.op == "overload" for e in spec.events)


def test_policy_golden_grid_shape_and_pins_exist():
    """The policy-armed grid covers cascading_overload under EVERY
    policy on both backends plus every other incident under the
    winning policy, each triple valid and its pin checked in (the
    nightly lane bit-compares; this fast check catches a missing or
    orphaned pin without compiling anything)."""
    from ringpop_tpu.policies import core as pol

    triples = lib.policy_golden_grid()
    casc = [(p, b) for n, p, b in triples if n == "cascading_overload"]
    assert sorted(casc) == sorted(
        (p, b) for p in pol.list_policies() for b in ("dense", "delta")
    )
    others = [(n, p) for n, p, b in triples if n != "cascading_overload"]
    assert sorted(n for n, _ in others) == sorted(
        n for n in lib.incident_names() if n != "cascading_overload"
    )
    assert all(p == lib.GOLDEN_POLICY for _, p in others)
    for name, policy, backend in triples:
        assert policy in pol.POLICIES
        assert backend in lib.INCIDENTS[name].backends
        path = lib.golden_path(name, backend, GOLDEN_DIR, policy=policy)
        assert os.path.exists(path), (
            f"missing policy golden {path}; pin with "
            "tools/pin_incidents.py --policies"
        )


def test_cli_list_incidents(capsys):
    from ringpop_tpu.cli import tick_cluster

    tick_cluster.main(["--list-incidents"])
    out = capsys.readouterr().out
    for name in lib.incident_names():
        assert name in out


def test_cli_incident_flag_validation():
    from ringpop_tpu.cli import tick_cluster

    with pytest.raises(SystemExit):
        tick_cluster.main(["--incident", "cascading_overload"])  # needs tpu-sim
    with pytest.raises(SystemExit):
        tick_cluster.main(
            ["--backend", "tpu-sim", "--incident", "cascading_overload",
             "--traffic", "zipf:64"]
        )


# ---------------------------------------------------------------------------
# slow: the golden regression grid (nightly lane)
# ---------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.skipif(
    golden_skip_reason() is not None, reason=str(golden_skip_reason())
)
@pytest.mark.parametrize("name,backend", GOLDEN_PAIRS)
def test_golden_incident_grid(name, backend):
    """Every incident's detect/heal/serve summary at the golden
    configuration matches the pinned file bit-for-bit, per backend —
    the outage suite every future perf/protocol PR is judged against.
    The goldens replay the pinned jax's CPU threefry; under any other
    build the grid SKIPS with the re-pin instruction
    (ringpop_tpu/utils/jaxpin.py) instead of bit-diffing 14 files."""
    path = lib.golden_path(name, backend, GOLDEN_DIR)
    assert os.path.exists(path), (
        f"missing golden {path}; pin with tools/pin_incidents.py"
    )
    with open(path) as f:
        want = json.load(f)
    got = lib.run_golden(name, backend)
    assert got == want, (
        f"{name}.{backend} diverged from its golden summary; if the "
        "change is intentional re-pin with tools/pin_incidents.py"
    )


@pytest.mark.slow
@pytest.mark.skipif(
    golden_skip_reason() is not None, reason=str(golden_skip_reason())
)
@pytest.mark.parametrize("name,policy,backend", POLICY_TRIPLES)
def test_golden_policy_grid(name, policy, backend):
    """The policy-armed golden grid: every pinned (incident, policy,
    backend) triple's remediated summary matches its file bit-for-bit
    — the scorecard that keeps a policy honest across ALL outages, not
    just the one it was tuned to beat (re-pin after an intentional
    change with ``tools/pin_incidents.py --policies``)."""
    path = lib.golden_path(name, backend, GOLDEN_DIR, policy=policy)
    assert os.path.exists(path), (
        f"missing policy golden {path}; pin with "
        "tools/pin_incidents.py --policies"
    )
    with open(path) as f:
        want = json.load(f)
    got = lib.run_golden(name, backend, policy=policy)
    assert got == want, (
        f"{name}+{policy}.{backend} diverged from its golden summary; "
        "if the change is intentional re-pin with "
        "tools/pin_incidents.py --policies"
    )

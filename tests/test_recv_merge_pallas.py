"""Bit-parity of the Pallas receiver merge against the XLA lowerings.

Runs the kernel in interpret mode so CPU CI covers it, same contract
as tests/test_searchsorted_pallas.py (on-hardware execution is raced
by benchmarks/profile_step.py).  The full-trajectory grid through the
dense step's five call sites is in tests/test_sim_core.py.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

from ringpop_tpu.ops.recv_merge_pallas import recv_merge_pallas


def _scatter_oracle(t_safe, fwd_ok, claim_rows):
    n = t_safe.shape[0]
    in_key = jnp.zeros((n, n), dtype=jnp.int32).at[t_safe].max(claim_rows)
    inbound = jnp.zeros((n,), jnp.int32).at[t_safe].add(fwd_ok.astype(jnp.int32))
    return in_key, inbound


def _case(n: int, seed: int, deliver: float):
    """A phase-3-shaped input: colliding receivers, masked claim rows."""
    rng = np.random.default_rng(seed)
    fwd_ok = rng.random((n,)) < deliver
    t_safe = np.where(fwd_ok, rng.integers(0, n, (n,)), 0).astype(np.int32)
    claims = (rng.integers(0, 1 << 20, (n, n)) * (rng.random((n, n)) < 0.4)).astype(
        np.int32
    )
    claims = np.where(fwd_ok[:, None], claims, 0)
    return jnp.asarray(t_safe), jnp.asarray(fwd_ok), jnp.asarray(claims)


# n values straddle the column-block divisibility paths: 7/130 pad to a
# 128 multiple, 48 pads, 128/256 hit the no-pad divisor path.
@pytest.mark.parametrize("n", [7, 48, 128, 130, 256])
@pytest.mark.parametrize("deliver", [0.15, 0.9])
def test_matches_scatter_form(n, deliver):
    t_safe, fwd_ok, claims = _case(n, 1000 * n + int(deliver * 10), deliver)
    got_k, got_i = recv_merge_pallas(t_safe, fwd_ok, claims, interpret=True)
    want_k, want_i = _scatter_oracle(t_safe, fwd_ok, claims)
    np.testing.assert_array_equal(np.asarray(got_i), np.asarray(want_i))
    np.testing.assert_array_equal(np.asarray(got_k), np.asarray(want_k))


def test_matches_sorted_form():
    from ringpop_tpu.models import swim_sim as sim

    t_safe, fwd_ok, claims = _case(96, 7, 0.8)
    with sim._force_recv_merge("sorted"):
        want_k, want_i = sim._receiver_merge(t_safe, fwd_ok, claims)
    got_k, got_i = recv_merge_pallas(t_safe, fwd_ok, claims, interpret=True)
    np.testing.assert_array_equal(np.asarray(got_i), np.asarray(want_i))
    np.testing.assert_array_equal(np.asarray(got_k), np.asarray(want_k))


def test_no_deliveries_all_zero():
    n = 16
    got_k, got_i = recv_merge_pallas(
        jnp.zeros((n,), jnp.int32),
        jnp.zeros((n,), bool),
        jnp.zeros((n, n), jnp.int32),
        interpret=True,
    )
    assert (np.asarray(got_k) == 0).all()
    assert (np.asarray(got_i) == 0).all()


def test_single_receiver_max_run():
    # every sender pings receiver 3: one run of length n (the longest
    # possible VMEM-resident accumulation), plus the garbage-flush path
    # for the untouched tail receiver n-1
    n = 24
    rng = np.random.default_rng(5)
    t_safe = jnp.full((n,), 3, jnp.int32)
    fwd_ok = jnp.ones((n,), bool)
    claims = jnp.asarray(rng.integers(0, 1 << 20, (n, n)).astype(np.int32))
    got_k, got_i = recv_merge_pallas(t_safe, fwd_ok, claims, interpret=True)
    want_k, want_i = _scatter_oracle(t_safe, fwd_ok, claims)
    np.testing.assert_array_equal(np.asarray(got_i), np.asarray(want_i))
    np.testing.assert_array_equal(np.asarray(got_k), np.asarray(want_k))

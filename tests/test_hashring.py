"""Hash ring tests (reference: test/hashring_test.js, test/ring-test.js)."""

from ringpop_tpu.hashring import HashRing
from ringpop_tpu.ops.farmhash import farmhash32


def test_replica_points_and_membership():
    ring = HashRing()
    ring.add_server("a:1")
    assert ring.has_server("a:1")
    assert ring.get_server_count() == 1
    assert len(ring._entries) == 100  # 100 replica points (ring.js:28)
    ring.remove_server("a:1")
    assert not ring.has_server("a:1")
    assert len(ring._entries) == 0


def test_checksum_order_independence():
    """hashring_test.js:130-158."""
    r1, r2 = HashRing(), HashRing()
    r1.add_remove_servers(["a:1", "b:2", "c:3"], [])
    r2.add_remove_servers(["c:3", "a:1", "b:2"], [])
    assert r1.checksum == r2.checksum
    assert r1.checksum == farmhash32(";".join(sorted(["a:1", "b:2", "c:3"])))


def test_checksum_computed_once_for_batch():
    ring = HashRing()
    count = [0]
    ring.on("checksumComputed", lambda *a: count.__setitem__(0, count[0] + 1))
    ring.add_remove_servers(["a:1", "b:2", "c:3"], [])
    assert count[0] == 1


def test_empty_ring_checksum_is_hash_of_empty_string():
    ring = HashRing()
    ring.compute_checksum()
    assert ring.checksum == farmhash32("")


def test_lookup_consistency():
    ring = HashRing()
    servers = [f"10.0.0.{i}:3000" for i in range(10)]
    ring.add_remove_servers(servers, [])
    # every key maps to a real server, deterministically
    for key in (str(i) for i in range(1000)):
        dest = ring.lookup(key)
        assert dest in servers
        assert ring.lookup(key) == dest


def test_lookup_successor_semantics():
    """lookup returns owner of first replica with hash >= hash(key), with
    wraparound (ring.js:138-147 + rbtree upperBound incl. equality)."""
    ring = HashRing()
    ring.add_remove_servers(["a:1", "b:2", "c:3"], [])
    entries = ring._entries
    # exact-hash key: find a key colliding is impractical; instead verify
    # the array invariant directly for a sample of hashes.
    for key in ("x", "y", "hello", "key0"):
        h = farmhash32(key)
        expect = None
        for eh, server in entries:
            if eh >= h:
                expect = server
                break
        if expect is None:
            expect = entries[0][1]
        assert ring.lookup(key) == expect


def test_lookup_n_unique_and_wrapping():
    ring = HashRing()
    servers = ["a:1", "b:2", "c:3", "d:4"]
    ring.add_remove_servers(servers, [])
    dests = ring.lookup_n("some-key", 3)
    assert len(dests) == 3
    assert len(set(dests)) == 3
    assert ring.lookup_n("some-key", 10) == ring.lookup_n("some-key", 4)
    assert ring.lookup("some-key") == dests[0]
    assert ring.lookup_n("some-key", 0) == []


def test_lookup_empty_ring():
    ring = HashRing()
    assert ring.lookup("k") is None
    assert ring.lookup_n("k", 3) == []


def test_removal_rebalances_only_affected_keys():
    """Consistent hashing: removing one server only moves its keys."""
    ring = HashRing()
    servers = [f"10.0.0.{i}:3000" for i in range(10)]
    ring.add_remove_servers(servers, [])
    keys = [f"key{i}" for i in range(2000)]
    before = {k: ring.lookup(k) for k in keys}
    victim = servers[3]
    ring.remove_server(victim)
    moved = 0
    for k in keys:
        after = ring.lookup(k)
        if before[k] != after:
            moved += 1
            assert before[k] == victim  # only the victim's keys may move
    assert moved > 0


def test_batch_duplicates_and_conflicts_are_idempotent():
    """Regression: duplicate adds in one batch must not insert replica
    entries twice (a later remove would leave stale entries routing keys
    to a departed server), and add+remove of the same server in one batch
    resolves like sequential add-then-remove."""
    from ringpop_tpu.hashring import HashRing

    ring = HashRing()
    ring.add_remove_servers(["a:1", "a:1", "b:1"], [])
    assert ring.get_server_count() == 2
    ring.remove_server("a:1")
    assert ring.get_server_count() == 1
    for _ in range(50):
        assert ring.lookup(f"key-{_}") == "b:1"

    ring2 = HashRing()
    ring2.add_remove_servers(["c:1"], ["c:1"])
    assert not ring2.has_server("c:1")
    assert ring2.lookup("x") is None


def test_transient_add_remove_of_absent_server_counts_as_change():
    """An absent server in both lists nets out, but sequential
    add-then-remove (ring.js:60-94) returns true and recomputes the
    checksum; the batch path must match."""
    ring = HashRing()
    ring.add_server("a:1")
    before = ring.checksum
    events = []
    ring.on("checksumComputed", lambda *a: events.append("checksum"))
    changed = ring.add_remove_servers(["b:2"], ["b:2"])
    assert changed is True
    assert events == ["checksum"]
    assert ring.checksum == before  # same membership, same checksum
    assert not ring.has_server("b:2")

"""Gossip/suspicion unit tests with virtual time (reference: test/swim_test.js)."""

from ringpop_tpu.harness import test_ringpop
from ringpop_tpu.member import Status


def test_gossip_start_stop_restart():
    rp = test_ringpop()
    assert rp.gossip.is_stopped
    rp.gossip.start()
    assert not rp.gossip.is_stopped
    rp.gossip.start()  # no-op
    rp.gossip.stop()
    assert rp.gossip.is_stopped
    rp.gossip.stop()  # no-op
    rp.gossip.start()
    assert not rp.gossip.is_stopped


def test_suspicion_timeout_makes_faulty():
    """Real-timeout faulty transition (swim_test.js:158-178), deterministic."""
    rp = test_ringpop(host_port="10.0.0.1:3000")
    rp.membership.make_alive("10.0.0.2:3000", 7)
    rp.membership.make_suspect("10.0.0.2:3000", 7)
    member = rp.membership.find_member_by_address("10.0.0.2:3000")
    assert member.status == Status.suspect
    assert "10.0.0.2:3000" in rp.suspicion.timers

    rp.clock.advance(4999)
    assert member.status == Status.suspect
    rp.clock.advance(2)
    assert member.status == Status.faulty


def test_suspicion_cancelled_by_alive():
    rp = test_ringpop(host_port="10.0.0.1:3000")
    rp.membership.make_alive("10.0.0.2:3000", 7)
    rp.membership.make_suspect("10.0.0.2:3000", 7)
    rp.membership.update(
        {"address": "10.0.0.2:3000", "status": Status.alive, "incarnationNumber": 8}
    )
    assert "10.0.0.2:3000" not in rp.suspicion.timers
    rp.clock.advance(10000)
    member = rp.membership.find_member_by_address("10.0.0.2:3000")
    assert member.status == Status.alive


def test_suspicion_never_for_local_member():
    rp = test_ringpop(host_port="10.0.0.1:3000")
    rp.suspicion.start(rp.membership.local_member)
    assert "10.0.0.1:3000" not in rp.suspicion.timers


def test_suspicion_stop_all_and_reenable():
    rp = test_ringpop(host_port="10.0.0.1:3000")
    rp.membership.make_alive("10.0.0.2:3000", 7)
    rp.suspicion.stop_all()
    rp.membership.make_suspect("10.0.0.2:3000", 7)
    assert "10.0.0.2:3000" not in rp.suspicion.timers  # gated
    rp.suspicion.reenable()
    rp.membership.make_suspect("10.0.0.2:3000", 8)
    assert "10.0.0.2:3000" in rp.suspicion.timers


def test_membership_iterator_visits_all_pingable():
    """membership-iterator-test.js semantics."""
    rp = test_ringpop(host_port="10.0.0.1:3000")
    for i in range(2, 6):
        rp.membership.make_alive(f"10.0.0.{i}:3000", 1)
    seen = set()
    for _ in range(4):
        m = rp.member_iterator.next()
        seen.add(m.address)
    assert seen == {f"10.0.0.{i}:3000" for i in range(2, 6)}


def test_membership_iterator_none_when_no_pingable():
    rp = test_ringpop(host_port="10.0.0.1:3000")
    assert rp.member_iterator.next() is None

"""The point-to-point gossip plane (ops/gossip_remote_copy.py).

Three layers of coverage, mirroring what each can prove on a CPU host:

* numeric — the ring primitives against their gather/scatter reference
  forms on virtual CPU meshes (the ppermute hop transport), including
  the ragged last-shard shapes;
* structural — the Pallas remote-copy hop must LOWER for the TPU
  platform (remote DMA has no CPU interpret emulation in the pinned
  jax), and the hop schedule's semaphore-pairing invariants hold;
* interpret — the Mosaic tile-padding math runs for real through a
  local ``make_async_copy`` kernel in interpret mode.

Plus the fast mesh-2 bit-parity checks (dense and delta at n=16) and
the sharding-spec completeness gate: a state field added without an
explicit layout in parallel/mesh.py's FIELD_SPECS maps must fail
loudly here, not silently replicate.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ringpop_tpu import parallel
from ringpop_tpu.models import swim_delta as sd
from ringpop_tpu.models import swim_sim as sim
from ringpop_tpu.ops import gossip_remote_copy as grc
from ringpop_tpu.parallel import mesh as pmesh

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)


# ---------------------------------------------------------------------------
# hop schedule invariants (the semaphore-pairing contract, host-side)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("d", [2, 3, 4, 8])
def test_hop_schedule_pairing_and_coverage(d):
    """Per hop every shard sends exactly once and receives exactly
    once — the one-send-semaphore/one-recv-semaphore pairing each
    kernel launch satisfies — and over the full D-1-hop schedule every
    shard has held every block (``block_origin`` is the ledger)."""
    sched = grc.hop_schedule(d)
    assert len(sched) == d - 1
    for perm in sched:
        assert sorted(s for s, _ in perm) == list(range(d))
        assert sorted(r for _, r in perm) == list(range(d))
    # replay the schedule: held[me] = origin of the block me holds
    held = list(range(d))
    for h, perm in enumerate(sched, start=1):
        held = [held[dict((r, s) for s, r in perm)[me]] for me in range(d)]
        for me in range(d):
            assert held[me] == grc.block_origin(me, h, d)
    # D-1 hops visit all D blocks (the initial hold counts)
    seen = {grc.block_origin(0, h, d) for h in range(d)}
    assert seen == set(range(d))


def test_hop_mode_env_validation(monkeypatch):
    monkeypatch.setenv("RINGPOP_GOSSIP_HOP", "nope")
    with pytest.raises(ValueError, match="RINGPOP_GOSSIP_HOP"):
        grc.hop_mode()
    monkeypatch.setenv("RINGPOP_GOSSIP_HOP", "auto")
    assert grc.hop_mode() == "ppermute"  # CPU host


def test_ring_context_required_and_divisibility():
    with pytest.raises(RuntimeError, match="ring_mesh"):
        grc.ring_fetch_rows(jnp.zeros((8, 4)), jnp.arange(8))
    with grc.ring_mesh(parallel.make_mesh(4)):
        assert grc.ring_devices() == 4
        with pytest.raises(ValueError, match="not divisible"):
            grc.ring_fetch_rows(jnp.zeros((6, 4)), jnp.arange(6))
    assert grc.active_ring() is None


# ---------------------------------------------------------------------------
# numeric parity of the primitives (ppermute transport, CPU mesh)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("d,n", [(2, 48), (4, 48), (8, 64)])
def test_ring_fetch_rows_matches_gather(d, n):
    """Row fetch == plain gather, including the ragged last-shard
    shapes (n=48 over 4 shards: 12-row blocks, no tile alignment)."""
    rng = np.random.default_rng(d * 100 + n)
    plane = jnp.asarray(rng.integers(0, 1 << 20, (n, 7), dtype=np.int32))
    idx1 = jnp.asarray(rng.integers(0, n, (n,), dtype=np.int32))
    idx2 = jnp.asarray(rng.integers(0, n, (n, 3), dtype=np.int32))
    with grc.ring_mesh(parallel.make_mesh(d)):
        got1 = jax.jit(grc.ring_fetch_rows)(plane, idx1)
        got2 = jax.jit(grc.ring_fetch_rows)(plane, idx2)
    np.testing.assert_array_equal(np.asarray(got1), np.asarray(plane[idx1]))
    np.testing.assert_array_equal(np.asarray(got2), np.asarray(plane[idx2]))


@pytest.mark.parametrize("d", [2, 4])
def test_ring_fetch_global_matches_gather(d):
    """Replicated-index fetch (the traffic plane's viewer lookups):
    every shard resolves the full index set, bool planes included."""
    n, m = 64, 23
    rng = np.random.default_rng(d)
    plane = jnp.asarray(rng.integers(0, 2, (n, n), dtype=np.int32) > 0)
    idx = jnp.asarray(rng.integers(0, n, (m,), dtype=np.int32))
    with grc.ring_mesh(parallel.make_mesh(d)):
        got = jax.jit(grc.ring_fetch_global)(plane, idx)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(plane[idx]))


@pytest.mark.parametrize("d", [2, 4, 8])
def test_ring_recv_merge_matches_scatter_reference(d):
    n = 64
    rng = np.random.default_rng(d + 7)
    t_safe = jnp.asarray(rng.integers(0, n, (n,), dtype=np.int32))
    fwd_ok = jnp.asarray(rng.integers(0, 2, (n,), dtype=np.int32) > 0)
    rows = jnp.asarray(rng.integers(0, 1 << 16, (n, n), dtype=np.int32))
    # the reference: scatter-max delivered rows per receiver
    ref_key = jnp.zeros((n, n), jnp.int32).at[
        jnp.where(fwd_ok, t_safe, n)
    ].max(jnp.where(fwd_ok[:, None], rows, 0), mode="drop")
    ref_inb = jnp.zeros((n,), jnp.int32).at[
        jnp.where(fwd_ok, t_safe, n)
    ].add(1, mode="drop")
    ref_key = jnp.where((ref_inb > 0)[:, None], ref_key, 0)
    with grc.ring_mesh(parallel.make_mesh(d)):
        in_key, inb = jax.jit(grc.ring_recv_merge)(t_safe, fwd_ok, rows)
    np.testing.assert_array_equal(np.asarray(in_key), np.asarray(ref_key))
    np.testing.assert_array_equal(np.asarray(inb), np.asarray(ref_inb))


def test_ring_per_row_take_and_update():
    n, d = 64, 4
    rng = np.random.default_rng(11)
    plane = jnp.asarray(rng.integers(0, 1 << 20, (n, n), dtype=np.int32))
    col = jnp.asarray(rng.integers(0, n, (n,), dtype=np.int32))
    vals = jnp.asarray(rng.integers(0, 1 << 20, (n,), dtype=np.int32))
    ids = jnp.arange(n, dtype=jnp.int32)
    with grc.ring_mesh(parallel.make_mesh(d)):
        take = jax.jit(grc.ring_take_per_row)(plane, col)
        upd_set = jax.jit(
            lambda p, c, v: grc.ring_update_per_row(p, c, v, op="set")
        )(plane, col, vals)
        upd_max = jax.jit(
            lambda p, c, v: grc.ring_update_per_row(p, c, v, op="max")
        )(plane, col, vals)
        with pytest.raises(ValueError, match="set|max"):
            grc.ring_update_per_row(plane, col, vals, op="mean")
    np.testing.assert_array_equal(np.asarray(take), np.asarray(plane[ids, col]))
    np.testing.assert_array_equal(
        np.asarray(upd_set),
        np.asarray(plane.at[ids, col].set(vals, unique_indices=True)),
    )
    np.testing.assert_array_equal(
        np.asarray(upd_max),
        np.asarray(plane.at[ids, col].max(vals, unique_indices=True)),
    )


# ---------------------------------------------------------------------------
# Pallas transport: padding math (interpret) + TPU lowering (structural)
# ---------------------------------------------------------------------------


def test_pad_tile_rounds_to_mosaic_tiles():
    assert grc._pad_tile(8, 128) == (8, 128)
    assert grc._pad_tile(6, 48) == (8, 128)  # ragged both ways
    assert grc._pad_tile(12, 64) == (16, 128)  # n=48 over 4 shards
    assert grc._pad_tile(1, 1) == (8, 128)
    for r, c in [(3, 5), (9, 129), (16, 256)]:
        pr, pc = grc._pad_tile(r, c)
        assert pr % grc._SUBLANE == 0 and pc % grc._LANE == 0
        assert pr >= r and pc >= c and pr - r < grc._SUBLANE


def test_local_async_copy_through_padded_tile_interpret():
    """The pad -> DMA-copy -> slice round trip of the hop wrapper,
    run for real in interpret mode with a LOCAL ``make_async_copy``
    (remote DMA has no CPU emulation; the padding math is identical)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def copy_kernel(in_ref, out_ref, sem):
        copy = pltpu.make_async_copy(in_ref, out_ref, sem)
        copy.start()
        copy.wait()

    r, c = 12, 33  # the ragged shard block shape class
    pr, pc = grc._pad_tile(r, c)
    x = jnp.arange(r * c, dtype=jnp.int32).reshape(r, c)
    x_pad = jnp.pad(x, ((0, pr - r), (0, pc - c)))
    out = pl.pallas_call(
        copy_kernel,
        out_shape=jax.ShapeDtypeStruct((pr, pc), jnp.int32),
        in_specs=[pl.BlockSpec(memory_space=grc._MEMSPACE_ANY)],
        out_specs=pl.BlockSpec(memory_space=grc._MEMSPACE_ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA],
        interpret=True,
    )(x_pad)
    np.testing.assert_array_equal(np.asarray(out[:r, :c]), np.asarray(x))


@pytest.mark.slow
def test_pallas_hop_lowers_for_tpu(monkeypatch):
    """The remote-copy hop must produce a TPU ``tpu_custom_call``
    module via cross-platform lowering — the structural half of the
    off-TPU contract (execution coverage needs a real TPU)."""
    import jax.export

    monkeypatch.setenv("RINGPOP_GOSSIP_HOP", "pallas")
    jax.clear_caches()
    d, n = 2, 64
    mesh = parallel.make_mesh(d)
    plane = jnp.zeros((n, 16), jnp.int32)
    idx = jnp.zeros((n,), jnp.int32)
    try:
        with grc.ring_mesh(mesh):
            exported = jax.export.export(
                jax.jit(grc.ring_fetch_rows), platforms=["tpu"]
            )(plane, idx)
        text = exported.mlir_module()
    finally:
        jax.clear_caches()  # drop programs traced under the forced env
    assert "tpu_custom_call" in text


# ---------------------------------------------------------------------------
# fast mesh-2 bit parity at n=16 (the ring plane as the default lowering)
# ---------------------------------------------------------------------------


def test_sharded_step_ring_bit_parity_n16():
    n = 16
    params = sim.SwimParams(loss=0.05)
    mesh = parallel.make_mesh(2)
    ref = sim.init_state(n, mode="self")
    sh, net = parallel.shard_cluster(sim.init_state(n, mode="self"),
                                     sim.make_net(n), mesh)
    step = parallel.sharded_step(mesh)
    keys = jax.random.split(jax.random.PRNGKey(3), 3)
    for k in keys:
        ref, m_ref = sim.swim_step(ref, sim.make_net(n), k, params)
        sh, m_sh = step(sh, net, k, params)
    np.testing.assert_array_equal(np.asarray(ref.view_key),
                                  np.asarray(sh.view_key))
    np.testing.assert_array_equal(np.asarray(ref.pb), np.asarray(sh.pb))
    for k in m_ref:
        np.testing.assert_array_equal(np.asarray(m_ref[k]),
                                      np.asarray(m_sh[k]), err_msg=k)


def test_sharded_delta_ring_bit_parity_n16():
    n = 16
    params = sd.DeltaParams(swim=sim.SwimParams(loss=0.05, suspicion_ticks=4),
                            wire_cap=4, claim_grid=8)
    net = sim.make_net(n)
    mesh = parallel.make_mesh(2)
    ref = sd.init_delta(n, capacity=8)
    sh = parallel.shard_delta(sd.init_delta(n, capacity=8), mesh)
    step_ref = jax.jit(sd.delta_step_impl, static_argnames=("params", "upto"))
    step_sh = parallel.sharded_delta_step(mesh)
    keys = jax.random.split(jax.random.PRNGKey(9), 3)
    for t, k in enumerate(keys):
        ref, _ = step_ref(ref, net, k, params)
        sh, _ = step_sh(sh, net, k, params)
        for name in ("d_subj", "d_key", "d_pb", "d_sl", "base_key", "digest"):
            np.testing.assert_array_equal(
                np.asarray(getattr(ref, name)),
                np.asarray(getattr(sh, name)),
                err_msg=f"{name} tick {t}",
            )


def test_sharded_delta_run_ring_bit_parity_n16():
    """The scanned form too: sharded ``delta_run`` over 2 devices is
    bit-identical to the unsharded scan (state AND summed metrics)."""
    n, ticks = 16, 6
    params = sd.DeltaParams(swim=sim.SwimParams(loss=0.02), wire_cap=4,
                            claim_grid=8)
    net = sim.make_net(n)
    key = jax.random.PRNGKey(5)
    ref, m_ref = jax.jit(
        sd.delta_run_impl, static_argnames=("params", "ticks")
    )(sd.init_delta(n, capacity=8), net, key, params, ticks)
    mesh = parallel.make_mesh(2)
    run = parallel.sharded_delta_run(mesh)
    sh, m_sh = run(parallel.shard_delta(sd.init_delta(n, capacity=8), mesh),
                   net, key, params, ticks)
    for name in ("d_subj", "d_key", "base_key", "digest", "tick"):
        np.testing.assert_array_equal(np.asarray(getattr(ref, name)),
                                      np.asarray(getattr(sh, name)), err_msg=name)
    for k in m_ref:
        np.testing.assert_array_equal(np.asarray(m_ref[k]),
                                      np.asarray(m_sh[k]), err_msg=k)


def test_gossip_gather_fallback_matches_ring():
    """RINGPOP_GOSSIP=gather (the PR-15 lowering) stays bit-identical
    to the ring default — the fallback matrix's exactness row."""
    n = 16
    params = sim.SwimParams(loss=0.05)
    mesh = parallel.make_mesh(2)
    key = jax.random.PRNGKey(1)
    outs = {}
    for mode in ("ring", "gather"):
        sh, net = parallel.shard_cluster(sim.init_state(n, mode="self"),
                                         sim.make_net(n), mesh)
        step = parallel.sharded_step(mesh, gossip=mode)
        sh, _ = step(sh, net, key, params)
        outs[mode] = np.asarray(sh.view_key)
    np.testing.assert_array_equal(outs["ring"], outs["gather"])
    with pytest.raises(ValueError, match="RINGPOP_GOSSIP"):
        pmesh.gossip_mode("carrier-pigeon")


# ---------------------------------------------------------------------------
# traffic plane from sharded membership truth
# ---------------------------------------------------------------------------


def test_sharded_serve_matches_unsharded():
    """Traffic lookups served from the row-sharded view table match
    ``serve_once`` counter for counter; host-``HashRing`` parity then
    rides on the oracle tests in test_traffic.py (transitivity)."""
    from ringpop_tpu.models.cluster import SimCluster
    from ringpop_tpu.traffic import engine as tengine

    c = SimCluster(32, sim.SwimParams(), seed=3)
    ct = c.compile_traffic({"keys_per_tick": 48, "pool": 128, "lookup_n": 3})
    base = tengine.serve_once(c.state.view_key, c.net.up, c.net.responsive,
                              ct.tensors, jnp.int32(0), static=ct.static)
    serve = pmesh.sharded_serve(parallel.make_mesh(2), static=ct.static)
    out = serve(c.state.view_key, c.net.up, c.net.responsive, ct.tensors,
                jnp.int32(0))
    assert set(out) == set(base)
    for k in base:
        np.testing.assert_array_equal(np.asarray(base[k]),
                                      np.asarray(out[k]), err_msg=k)


# ---------------------------------------------------------------------------
# sharding-spec completeness: new state fields must declare a layout
# ---------------------------------------------------------------------------


def test_field_specs_cover_every_state_field():
    """Walking the dataclass fields: a field added to any of the three
    sharded state types without an explicit entry in the FIELD_SPECS
    maps fails HERE (and at trace time with a named KeyError), never
    silently replicating an [N, N] plane."""
    assert set(pmesh.CLUSTER_FIELD_SPECS) == set(sim.ClusterState._fields)
    assert set(pmesh.NET_FIELD_SPECS) == set(sim.NetState._fields)
    assert set(pmesh.DELTA_FIELD_SPECS) == set(sd.DeltaState._fields)
    # every declared kind resolves to a real PartitionSpec
    for specs in (pmesh.CLUSTER_FIELD_SPECS, pmesh.NET_FIELD_SPECS,
                  pmesh.DELTA_FIELD_SPECS):
        for kind in specs.values():
            assert kind in pmesh._SPEC_PARTS or kind == pmesh._ADJ, kind


def test_unmapped_field_fails_loudly():
    mesh = parallel.make_mesh(2)
    with pytest.raises(KeyError, match="FIELD_SPECS"):
        pmesh._field_sharding(mesh, {}, "brand_new_plane", jnp.zeros((4, 4)))


# ---------------------------------------------------------------------------
# the audit fence (slow lane): every p2p entry censuses clean
# ---------------------------------------------------------------------------

P2P_ENTRIES = (
    ("sharded_step", "dense"),
    ("sharded_step@4", "dense"),
    ("sharded_delta_step", "delta"),
    ("run_sweep+shard", "dense"),
    ("run_sweep+shard", "delta"),
)


@pytest.mark.slow
@pytest.mark.allow_transfers
@pytest.mark.parametrize("name,backend", P2P_ENTRIES)
def test_p2p_entry_zero_member_gathers(name, backend):
    """The tentpole's fence: every entry that declares ``p2p_only``
    must hold ZERO member-plane all-gathers in its partitioned HLO,
    and its audit board must be error-free (budgets pinned)."""
    from ringpop_tpu.analysis.contracts import audit_entry
    from ringpop_tpu.analysis.partitioning import collective_counts
    from ringpop_tpu.analysis.registry import build_entry

    assert build_entry(name, backend).p2p_only
    r = audit_entry(name, backend)
    cc = collective_counts(r.collectives)
    assert cc.get("member-gather", 0) == 0, cc
    errors = [f for f in r.findings if f.severity == "error"]
    assert not errors, errors

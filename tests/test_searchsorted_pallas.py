"""Bit-parity of the Pallas row-searchsorted against jnp.searchsorted.

Runs the kernel in interpret mode so CPU CI covers it (the scheduled
on-hardware execution is exercised by benchmarks/profile_searchsorted.py
and the bench's device kernel checks).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ringpop_tpu.ops.searchsorted_pallas import row_searchsorted_pallas

SENTINEL = np.iinfo(np.int32).max


@pytest.mark.parametrize("side", ["left", "right"])
@pytest.mark.parametrize(
    "n,c,k",
    [(7, 16, 5), (64, 64, 16), (130, 32, 64), (256, 8, 3)],
)
def test_matches_jnp(side, n, c, k):
    rng = np.random.default_rng(n * 1000 + c + k)
    # duplicate-heavy tables with SENTINEL padding, like the delta tables
    table = np.sort(rng.integers(0, 50, (n, c)), axis=1).astype(np.int32)
    pad = rng.random((n, c)) < 0.3
    table = np.sort(np.where(pad, SENTINEL, table), axis=1).astype(np.int32)
    q = rng.integers(-5, 60, (n, k)).astype(np.int32)
    q[rng.random((n, k)) < 0.1] = SENTINEL  # query the pad value too
    want = jax.vmap(
        lambda ar, vr: jnp.searchsorted(ar, vr, side=side)
    )(jnp.asarray(table), jnp.asarray(q))
    got = row_searchsorted_pallas(
        jnp.asarray(table), jnp.asarray(q), side=side, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

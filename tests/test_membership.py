"""Membership semantics (reference: test/membership-test.js)."""

from ringpop_tpu.harness import test_ringpop
from ringpop_tpu.member import Status
from ringpop_tpu.ops.farmhash import farmhash32


def test_checksum_format_parity():
    """Checksum == farmhash32 of 'addr+status+inc' sorted, ';'-joined
    (membership.js:41-93)."""
    rp = test_ringpop(host_port="10.0.0.1:3000")
    rp.membership.make_alive("10.0.0.2:3000", 1414142122275)
    expected_str = rp.membership.generate_checksum_string()
    assert farmhash32(expected_str) == rp.membership.checksum
    # With the known two-member layout the string matches the documented
    # format example (membership.js:42-53).
    assert ";" in expected_str
    assert "alive" in expected_str


def test_checksum_changes_on_update_and_stable_otherwise():
    rp = test_ringpop()
    before = rp.membership.checksum
    rp.membership.make_alive("127.0.0.1:3001", 1)
    after = rp.membership.checksum
    assert before != after
    # Re-applying the same change is a no-op (no new incarnation).
    rp.membership.make_alive("127.0.0.1:3001", 1)
    assert rp.membership.checksum == after


def test_checksum_order_independent():
    rp1 = test_ringpop(host_port="127.0.0.1:3000", seed=1)
    rp2 = test_ringpop(host_port="127.0.0.1:3000", seed=99)
    for rp, order in ((rp1, [1, 2, 3]), (rp2, [3, 1, 2])):
        for i in order:
            rp.membership.make_alive(f"127.0.0.1:300{i}", 1000 + i)
    assert rp1.membership.checksum == rp2.membership.checksum


def test_update_precedence_applied_through_membership():
    rp = test_ringpop(host_port="10.0.0.1:3000")
    addr = "10.0.0.2:3000"
    rp.membership.make_alive(addr, 10)
    member = rp.membership.find_member_by_address(addr)

    # Same-incarnation suspect beats alive.
    rp.membership.update({"address": addr, "status": Status.suspect, "incarnationNumber": 10})
    assert member.status == Status.suspect
    # Same-incarnation alive does NOT beat suspect.
    rp.membership.update({"address": addr, "status": Status.alive, "incarnationNumber": 10})
    assert member.status == Status.suspect
    # Newer alive does.
    rp.membership.update({"address": addr, "status": Status.alive, "incarnationNumber": 11})
    assert member.status == Status.alive


def test_local_suspect_rumor_triggers_refutation():
    rp = test_ringpop(host_port="10.0.0.1:3000")
    local = rp.membership.local_member
    original_inc = local.incarnation_number
    applied = rp.membership.update(
        {"address": "10.0.0.1:3000", "status": Status.suspect, "incarnationNumber": original_inc}
    )
    assert local.status == Status.alive
    assert local.incarnation_number > original_inc
    assert applied and applied[0]["status"] == Status.alive


def test_stash_until_ready_and_atomic_set():
    rp = test_ringpop(make_alive=False)
    rp.is_ready = False
    rp.membership.update(
        [{"address": "127.0.0.1:3001", "status": Status.alive, "incarnationNumber": 1}]
    )
    rp.membership.update(
        [{"address": "127.0.0.1:3001", "status": Status.alive, "incarnationNumber": 5},
         {"address": "127.0.0.1:3002", "status": Status.alive, "incarnationNumber": 2}]
    )
    assert rp.membership.get_member_count() == 0  # stashed, not applied

    rp.membership.set()
    # Max-incarnation merge during set (membership.js:162-206).
    assert rp.membership.get_member_count() == 2
    assert rp.membership.find_member_by_address("127.0.0.1:3001").incarnation_number == 5
    assert rp.membership.checksum is not None
    # set() is once-only.
    rp.membership.set()
    assert rp.membership.get_member_count() == 2


def test_pingable_excludes_self_faulty_leave():
    rp = test_ringpop(host_port="10.0.0.1:3000")
    rp.membership.make_alive("10.0.0.2:3000", 1)
    rp.membership.make_suspect("10.0.0.2:3000", 1)
    rp.membership.make_alive("10.0.0.3:3000", 1)
    rp.membership.make_faulty("10.0.0.3:3000", 1)
    rp.membership.make_alive("10.0.0.4:3000", 1)

    pingable = [m.address for m in rp.membership.members if rp.membership.is_pingable(m)]
    assert "10.0.0.1:3000" not in pingable  # self
    assert "10.0.0.2:3000" in pingable  # suspect is pingable
    assert "10.0.0.3:3000" not in pingable  # faulty is not
    assert "10.0.0.4:3000" in pingable


def test_get_random_pingable_members_excludes():
    rp = test_ringpop(host_port="10.0.0.1:3000")
    for i in range(2, 8):
        rp.membership.make_alive(f"10.0.0.{i}:3000", 1)
    sample = rp.membership.get_random_pingable_members(3, ["10.0.0.2:3000"])
    assert len(sample) == 3
    assert all(m.address != "10.0.0.2:3000" for m in sample)

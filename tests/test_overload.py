"""Load-coupled gray degradation: the overload feedback loop.

The acceptance oracle is the per-tick host walk
(``_host_overload_walk``): the compiled scenario scan's serving
counters, latency histogram, overload telemetry, final state, final
net (pressure + gray bits included), and membership checksums must be
bit-identical to a host loop that steps the protocol with the same key
schedule, serves every tick's batch through ``ring_for`` host rings
with the same duty phases, counts the same per-node send loads, and
folds them through the SAME ``faults.overload_update`` arithmetic —
on both backends (PR 12's latency-oracle pattern; the update is exact
int32 algebra, so parity is equality, not tolerance).

Fast lane: pure-host update/validation units + the dense oracle (one
small scenario+traffic+overload compile — the tier-1 representative).
The delta twin, the streamed/resume bit-parity, and the no-feedback
control comparison ride the slow lane.
"""

from __future__ import annotations

from collections import defaultdict

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ringpop_tpu.models import swim_delta as sdelta
from ringpop_tpu.models import swim_sim as sim
from ringpop_tpu.models.cluster import SimCluster
from ringpop_tpu.models.swim_sim import SwimParams
from ringpop_tpu.ops import ring_ops
from ringpop_tpu.scenarios import compile as scompile
from ringpop_tpu.scenarios import faults as sfaults
from ringpop_tpu.scenarios.spec import ScenarioSpec
from ringpop_tpu.traffic import engine as tengine
from ringpop_tpu.traffic import latency as tlat

N = 10
LEAN = SwimParams(suspicion_ticks=8, ping_req_size=1)
B = 10
# exact-window workload: the host rings and the masked walk agree on
# every key, so the oracle is equality with no unresolved residue
OV_WL = {"kind": "zipf", "keys_per_tick": 24, "pool": 256, "zipf_s": 1.2,
         "window": N * ring_ops.DEFAULT_REPLICA_POINTS,
         "latency_buckets": B}

OV_SPEC = {
    "ticks": 12,
    "events": [
        # seed gray: two slow-but-alive nodes attract duty timeouts
        {"at": 1, "op": "gray", "nodes": [1, 2], "factor": 4, "until": 10},
        {"at": 3, "op": "kill", "node": 9},
        {"at": 1, "op": "overload", "until": 12, "capacity": 1,
         "threshold": 5, "recover": 1, "factor": 4},
    ],
}

SLO_COUNTERS = ("lookups", "dropped", "handled_local", "delivered",
                "proxy_retries", "proxy_failed", "send_errors",
                "retry_succeeded", "gray_timeouts", "lat_count",
                "lat_sum_ms", "lat_max_ms")


# ---------------------------------------------------------------------------
# fast: pure-host units
# ---------------------------------------------------------------------------


def test_overload_spec_validation():
    def bad(ev):
        with pytest.raises(ValueError):
            ScenarioSpec.from_dict({"ticks": 20, "events": [ev]}).validate(N)

    ok = {"at": 2, "op": "overload", "until": 18, "capacity": 4,
          "threshold": 12, "recover": 3, "factor": 5}
    ScenarioSpec.from_dict({"ticks": 20, "events": [ok]}).validate(N)
    bad(dict(ok, capacity=0))
    bad(dict(ok, threshold=0))
    bad(dict(ok, recover=12))  # recover must be < threshold
    bad(dict(ok, factor=1))
    bad(dict(ok, until=30))
    with pytest.raises(ValueError):  # at most one overload event
        ScenarioSpec.from_dict(
            {"ticks": 20, "events": [ok, dict(ok, at=3)]}
        ).validate(N)
    # JSON round trip keeps the overload fields
    spec = ScenarioSpec.from_dict({"ticks": 20, "events": [ok]})
    assert ScenarioSpec.from_json(spec.to_json()) == spec


def test_overload_config_lowering():
    spec = ScenarioSpec.from_dict(OV_SPEC)
    cfg = sfaults.overload_config(spec)
    assert cfg == sfaults.OverloadConfig(
        start=1, end=12, capacity=1, threshold=5, recover=1, factor=4
    )
    assert sfaults.overload_config(ScenarioSpec(ticks=5)) is None
    compiled = scompile.compile_spec(spec, N)
    assert compiled.overload == cfg
    # "until" defaults to the end of the run
    spec2 = ScenarioSpec.from_dict(
        {"ticks": 9, "events": [{"at": 2, "op": "overload", "capacity": 2,
                                 "threshold": 4, "factor": 3}]}
    )
    cfg2 = sfaults.overload_config(spec2)
    assert (cfg2.end, cfg2.recover) == (9, 0)


def test_overload_update_hysteresis():
    cfg = sfaults.OverloadConfig(start=0, end=100, capacity=2, threshold=6,
                                 recover=2, factor=4)
    p = np.zeros(3, np.int32)
    g = np.zeros(3, bool)
    # node 0 hammered, node 1 at capacity, node 2 idle
    for _ in range(3):
        p, g = sfaults.overload_update(cfg, True, p, g, np.array([5, 2, 0]))
    assert list(p) == [9, 0, 0] and list(g) == [True, False, False]
    # drain: pressure falls 2/tick; gray HOLDS until <= recover
    p, g = sfaults.overload_update(cfg, True, p, g, np.array([0, 0, 0]))
    assert list(p) == [7, 0, 0] and g[0]
    for _ in range(2):
        p, g = sfaults.overload_update(cfg, True, p, g, np.array([0, 0, 0]))
    assert list(p) == [3, 0, 0] and g[0]  # 3 > recover: still held
    p, g = sfaults.overload_update(cfg, True, p, g, np.array([0, 0, 0]))
    assert list(p) == [1, 0, 0] and not g[0]  # 1 <= recover: cleared
    # outside the window everything pins to zero
    p, g = sfaults.overload_update(cfg, False, np.array([9, 9, 9], np.int32),
                                   np.array([True, True, True]),
                                   np.array([9, 9, 9]))
    assert not p.any() and not g.any()


def test_overload_requires_traffic_and_clear():
    c = SimCluster(N, LEAN, seed=2)
    with pytest.raises(ValueError, match="traffic"):
        c.run_scenario(OV_SPEC)
    # host loop cannot drive the feedback (it serves no traffic)
    from ringpop_tpu.scenarios.runner import run_host_loop

    with pytest.raises(NotImplementedError):
        run_host_loop(c, ScenarioSpec.from_dict(OV_SPEC))


# ---------------------------------------------------------------------------
# the host walk (the latency walk of tests/test_latency.py + per-node
# send loads + the overload fold)
# ---------------------------------------------------------------------------


def _host_slo_tick_loads(cluster, ct, t):
    """One SLO traffic tick on the host: identical batch, forward
    chains over ``ring_for`` rings, latency-stream draws, backoff and
    duty phases — plus the per-node send loads the overload feedback
    meters (engine ``node_sends``: local handling at the viewer, every
    chain iteration's attempt at its holder, dead/off-duty included).
    Returns (counters, hist int64[B], loads int64[N])."""
    st = ct.static
    m = st.m
    idx, viewers = tengine.sample_tick(ct.tensors, jnp.int32(t), m)
    idx, viewers = np.asarray(idx), np.asarray(viewers)
    # the oracle spec has no delay rules, so the latency-stream jitter
    # draws all scale to zero legs — the walk never needs to draw them
    bo_ms = tlat.backoff_ms_schedule(st.max_retries)
    bo_ticks = tlat.backoff_tick_offsets(st.max_retries, st.period_ms)

    net = cluster.net
    period = (
        np.asarray(net.period) if net.period is not None
        else np.ones(cluster.n, np.int32)
    )

    def duty(h, te):
        per = max(int(period[h]), 1)
        return te % per == (h * (0x9E37 | 1)) % per

    live = set(int(i) for i in cluster.live_indices())
    keys = ct.spec.pool_keys()
    addr_index = cluster.book.index
    rings: dict[int, object] = {}

    def ring_of(node):
        if node not in rings:
            rings[node] = cluster.ring_for(node)
        return rings[node]

    counts = {k: 0 for k in SLO_COUNTERS}
    hist = np.zeros(st.latency_buckets, np.int64)
    loads = np.zeros(cluster.n, np.int64)

    def deliver(lat, retries):
        counts["delivered"] += 1
        counts["lat_count"] += 1
        counts["lat_sum_ms"] += lat
        counts["lat_max_ms"] = max(counts["lat_max_ms"], lat)
        if retries > 0:
            counts["retry_succeeded"] += 1
        hist[int(tlat.bucket_index(np.int64(lat), st.latency_buckets))] += 1

    for k in range(m):
        v = int(viewers[k])
        if v not in live:
            counts["dropped"] += 1
            continue
        counts["lookups"] += 1
        key = keys[int(idx[k])]
        owner0 = addr_index[ring_of(v).lookup(key)]
        if owner0 == v:
            counts["handled_local"] += 1
            loads[v] += 1
            deliver(0, 0)
            continue
        h, retries = owner0, 0
        lat = 0  # no delay rules in the oracle spec: zero link legs
        settled, final = False, -1
        for i in range(st.max_retries + 1):
            loads[h] += 1  # the attempt lands on h's inbox either way
            te = t + int(bo_ticks[min(retries, st.max_retries)])
            alive_h = h in live
            if not alive_h or not duty(h, te):
                counts["send_errors"] += 1
                if alive_h:
                    counts["gray_timeouts"] += 1
                if retries < st.max_retries:
                    lat += int(bo_ms[retries])
                    retries += 1
                    continue
                break
            nxt = addr_index[ring_of(h).lookup(key)]
            if nxt == h:
                settled, final = True, h
                break
            if retries < st.max_retries:
                lat += int(bo_ms[retries])
                h = nxt
                retries += 1
                continue
            break
        counts["proxy_retries"] += retries
        if settled:
            deliver(lat, retries)
        else:
            counts["proxy_failed"] += 1
    return counts, hist, loads


def _host_overload_walk(backend, spec_obj, wl, seed, **kw):
    """Step the protocol per tick exactly as the compiled scan does —
    events at tick start, the EFFECTIVE (overload-degraded) period row
    installed before the step, the schedule key — then serve the
    tick's batch on the host and fold its loads through
    ``faults.overload_update``.  Returns (cluster, per-tick rows)."""
    c = SimCluster(N, LEAN, seed=seed, backend=backend, **kw)
    ct = c.compile_traffic(wl)
    cfg = sfaults.overload_config(spec_obj)
    compiled = scompile.compile_spec(spec_obj, c.n, base_loss=c.params.loss)
    keys = scompile.key_schedule(c._split, compiled)
    switches = sfaults.period_switches(spec_obj, c.n)
    by_tick = defaultdict(list)
    for at, op, arg in scompile.expand_events(spec_obj, c.params.loss):
        by_tick[at].append((op, arg))
    pressure = np.zeros(c.n, np.int32)
    gray = np.zeros(c.n, bool)
    rows = []
    for t in range(spec_obj.ticks):
        ops = sorted(by_tick.get(t, ()), key=lambda x: scompile._OP_RANK[x[0]])
        for op, arg in ops:
            if op == "kill":
                c.kill(arg)
            elif op == "suspend":
                c.suspend(arg)
            elif op == "resume":
                c.resume(arg)
            elif op == "loss":
                c.set_loss(arg)
            # faultcfg (gray switches) handled via the period fold below
        row = np.ones(c.n, np.int32)
        for at, r in switches:
            if at <= t:
                row = r
        per_eff = np.where(gray, np.maximum(row, cfg.factor), row)
        c.net = c.net._replace(period=jnp.asarray(per_eff.astype(np.int32)))
        if backend == "delta":
            c.state, _ = sdelta.delta_step(
                c.state, c.net, keys[t], params=c.dparams
            )
        else:
            c.state, _ = sim.swim_step(c.state, c.net, keys[t], params=c.params)
        counts, hist, loads = _host_slo_tick_loads(c, ct, t)
        in_win = cfg.start <= t < cfg.end
        pressure, gray = sfaults.overload_update(
            cfg, in_win, pressure, gray, loads.astype(np.int32)
        )
        rows.append((counts, hist, int(gray.sum()), int(pressure.max())))
    return c, pressure, gray, rows


def _assert_overload_parity(backend, **kw):
    spec_obj = ScenarioSpec.from_dict(OV_SPEC)
    a = SimCluster(N, LEAN, seed=11, backend=backend, **kw)
    ct = a.compile_traffic(OV_WL)
    trace = a.run_scenario(spec_obj, traffic=ct)
    b, pressure, gray, rows = _host_overload_walk(
        backend, spec_obj, OV_WL, seed=11, **kw
    )
    for t, (counts, hist, gray_nodes, p_max) in enumerate(rows):
        for name, value in counts.items():
            got = int(trace.metrics[name][t])
            assert got == value, (t, name, got, value)
        np.testing.assert_array_equal(
            trace.planes["lat_hist_ms"][t], hist, err_msg=f"tick {t}"
        )
        assert int(trace.metrics["ov_gray_nodes"][t]) == gray_nodes, t
        assert int(trace.metrics["ov_pressure_max"][t]) == p_max, t
    # the feedback state itself round-trips onto the final net
    np.testing.assert_array_equal(np.asarray(a.net.ov_cnt), pressure)
    np.testing.assert_array_equal(np.asarray(a.net.ov_gray), gray)
    # state + net + checksum parity (the trajectory the degraded
    # periods steered is identical)
    for la, lb in zip(
        jax.tree_util.tree_leaves(a.state), jax.tree_util.tree_leaves(b.state)
    ):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    np.testing.assert_array_equal(np.asarray(a.net.up), np.asarray(b.net.up))
    np.testing.assert_array_equal(
        np.asarray(a.net.responsive), np.asarray(b.net.responsive)
    )
    assert a.checksums() == b.checksums()
    # the storm actually fired: pressure crossed the threshold and the
    # duty timeouts it causes are visible
    assert int(trace.metrics["ov_gray_nodes"].max()) > 0
    assert int(trace.metrics["gray_timeouts"].sum()) > 0


def test_overload_parity_dense():
    """Tier-1 acceptance oracle (dense arm): compiled scan ==
    per-tick host walk, bit for bit — counters, histogram, overload
    telemetry, final state/net/checksums."""
    _assert_overload_parity("dense")


@pytest.mark.slow
def test_overload_parity_delta():
    """The delta twin of the acceptance oracle (same machinery on the
    O(N*C) state; its scenario+traffic+overload program is its own XLA
    compile, so it rides the nightly lane)."""
    _assert_overload_parity(
        "delta", capacity=N, wire_cap=N, claim_grid=3 * N * N
    )


# ---------------------------------------------------------------------------
# slow: execution-strategy + control-arm contracts
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_overload_streamed_and_resume_bit_identical(tmp_path):
    """Streaming an overload run is an execution strategy (same trace,
    same final pressure), and a SIGKILL mid-incident resumes from the
    checkpoint v5 ov tensors to a bit-identical end state."""
    from ringpop_tpu.scenarios import stream as sstream

    spec = {
        "ticks": 24,
        "events": [
            {"at": 2, "op": "overload", "until": 24, "capacity": 1,
             "threshold": 5, "recover": 1, "factor": 4},
        ],
    }
    a = SimCluster(N, LEAN, seed=7)
    ta = a.run_scenario(spec, traffic=OV_WL)
    b = SimCluster(N, LEAN, seed=7)
    tb = b.run_scenario(spec, traffic=OV_WL, segment_ticks=7)
    for k in ta.metrics:
        np.testing.assert_array_equal(ta.metrics[k], tb.metrics[k], err_msg=k)
    np.testing.assert_array_equal(
        np.asarray(a.net.ov_cnt), np.asarray(b.net.ov_cnt)
    )
    assert int(np.asarray(a.net.ov_cnt).max()) > 0  # mid-window at the end

    # killed-after-first-checkpoint + resume == uninterrupted
    ckpt_path = str(tmp_path / "ov.npz")
    cv = SimCluster(N, LEAN, seed=7)
    with pytest.raises(sstream.StreamInterrupted):
        sstream.run_streamed(
            cv, spec, segment_ticks=7, traffic=OV_WL,
            checkpoint_path=ckpt_path, interrupt_after=1,
        )
    # the checkpoint carries nonzero mid-run pressure
    from ringpop_tpu import checkpoint as ckpt

    mid = ckpt.load(ckpt_path)
    assert mid.net.ov_cnt is not None
    cr, result = sstream.resume(ckpt_path)
    tr = result
    for k in ta.metrics:
        np.testing.assert_array_equal(ta.metrics[k], tr.metrics[k], err_msg=k)
    np.testing.assert_array_equal(
        np.asarray(a.net.ov_cnt), np.asarray(cr.net.ov_cnt)
    )
    np.testing.assert_array_equal(
        np.asarray(a.net.ov_gray), np.asarray(cr.net.ov_gray)
    )
    assert a.checksums() == cr.checksums()


@pytest.mark.slow
def test_overload_control_run_has_no_feedback():
    """The no-feedback CONTROL arm (the BASELINE comparison): same
    traffic, overload event stripped — the protocol trajectory matches
    the feedback run only until the first node degrades, and the
    control trace carries no overload series."""
    from ringpop_tpu.scenarios import library as ilib

    n = 16
    spec_fb, wl = ilib.build_incident("cascading_overload", n, ticks=60)
    spec_ctl, _ = ilib.build_incident(
        "cascading_overload", n, ticks=60, overload=False
    )
    assert any(e.op == "overload" for e in spec_fb.events)
    assert not any(e.op == "overload" for e in spec_ctl.events)
    a = SimCluster(n, LEAN, seed=5)
    tfb = a.run_scenario(spec_fb, traffic=wl)
    c = SimCluster(n, LEAN, seed=5)
    tctl = c.run_scenario(spec_ctl, traffic=wl)
    assert "ov_gray_nodes" in tfb.metrics
    assert "ov_gray_nodes" not in tctl.metrics
    assert int(tfb.metrics["ov_gray_nodes"].max()) > 0
    # gray degradation really steered serving: the feedback arm sees
    # duty timeouts the control arm cannot
    assert int(tfb.metrics["gray_timeouts"].sum()) > int(
        tctl.metrics["gray_timeouts"].sum()
    )


@pytest.mark.slow
def test_overload_sweep_parity_and_scorecards():
    """A traffic-coupled sweep replica is bit-identical to the
    standalone run from its replica key (the sweep parity contract now
    extended to serving + overload series), and serving_summary emits
    one scorecard per replica."""
    spec = {
        "ticks": 16,
        "events": [
            {"at": 1, "op": "overload", "until": 16, "capacity": 1,
             "threshold": 5, "recover": 1, "factor": 4},
        ],
    }
    c = SimCluster(N, LEAN, seed=9)
    ct = c.compile_traffic(OV_WL)
    strace = c.run_sweep(spec, 2, traffic=ct)
    rows = strace.serving_summary()
    assert rows is not None and len(rows) == 2
    assert all("ov_gray_peak" in r for r in rows)
    # replica 1 standalone: a cluster whose key IS replica key 1
    d = SimCluster(N, LEAN, seed=9)
    d.key = jnp.asarray(strace.replica_keys[1])
    td = d.run_scenario(spec, traffic=ct)
    rep = strace.replica(1)
    for k in td.metrics:
        np.testing.assert_array_equal(rep.metrics[k], td.metrics[k], err_msg=k)
    np.testing.assert_array_equal(
        rep.planes["lat_hist_ms"], td.planes["lat_hist_ms"]
    )

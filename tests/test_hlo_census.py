"""Dense-step HLO census regression: the op budget of the receiver
merge, pinned without a chip.

Lowers the dense ``swim_step`` for the TPU platform (jax.export cross-
platform lowering) and asserts the expensive-op tallies stay within
the measured budget — the guard that keeps future PRs from silently
re-materializing the permuted claim matrix, and the checkable form of
the pallas lowering's pass-count claim (ops/recv_merge_pallas.py):
under ``pallas`` the [N, N] row permutation (full-tensor gathers) and
the Hillis-Steele combine loops (whiles) attributable to
``_receiver_merge`` disappear into Mosaic custom calls.

Slow-marked: each lowering is a full trace + export of the step.
Ceilings were measured at n=256 on jax 0.4.37; they are upper bounds
(a jax upgrade may lower them — tighten, don't loosen).
"""

from __future__ import annotations

import pytest

from benchmarks import hlo_census as hc

N = 256

# measured budget at n=256 (see module docstring)
SORTED_NN_GATHERS = 53  # incl. 30 attributable to the 10 merge call sites
SORTED_WHILES = 11  # 10 merge combine loops + 1
PALLAS_NN_GATHERS = 23  # the non-merge call sites (reply/relay gathers)
PALLAS_WHILES = 1


def _counts(tallies):
    nn = f"{N}x{N}"
    full_gathers = sum(c for k, c in tallies.items() if k == f"gather [{nn}]")
    full_sorts = sum(c for k, c in tallies.items() if k.startswith(f"sort [{nn}"))
    whiles = tallies.get("while [?]", 0)
    mosaic = tallies.get("tpu_custom_call [mosaic]", 0)
    return full_gathers, full_sorts, whiles, mosaic


@pytest.mark.slow
def test_dense_census_sorted_budget():
    tallies, _ = hc.census_text(hc.lower_dense(N, "sorted"))
    full_gathers, full_sorts, whiles, _ = _counts(tallies)
    # no [N, N]-operand sort may ever appear (the [N] sender orderings
    # are the only sorts the dense step is allowed)
    assert full_sorts == 0
    assert full_gathers <= SORTED_NN_GATHERS
    assert whiles <= SORTED_WHILES
    # floors: the sorted form MUST show the permutation gathers and
    # combine loops — zero means census_text's regexes rotted against a
    # new StableHLO print form and the ceilings above are vacuous
    assert full_gathers > PALLAS_NN_GATHERS
    assert whiles > PALLAS_WHILES


@pytest.mark.slow
def test_dense_census_pallas_eliminates_merge_passes():
    tallies, _ = hc.census_text(hc.lower_dense(N, "pallas"))
    full_gathers, full_sorts, whiles, mosaic = _counts(tallies)
    assert mosaic >= 1, "expected the Mosaic receiver-merge custom call"
    assert full_sorts == 0
    # the merge-attributable [N, N] permutation gathers and combine
    # loops are gone; what remains are the reply/relay call sites
    assert full_gathers <= PALLAS_NN_GATHERS
    assert whiles <= PALLAS_WHILES
    assert full_gathers < SORTED_NN_GATHERS
    assert whiles < SORTED_WHILES


@pytest.mark.slow
def test_delta_census_still_lowers():
    # the --backend refactor must not break the original delta census
    tallies, elems = hc.census_text(hc.lower_delta(1024, 64))
    assert any(k.startswith("sort") for k in tallies)
    assert sum(elems.values()) > 0


def test_temp_rows_sort_top_and_packed_column():
    """Fast pin of the --sort/--top/packed-dtype temp-census flags
    (tiny fixture: a jaxpr trace, no lowering or compile)."""
    rows = hc.annotate_packed(
        hc.temp_rows("delta", 64, 16, min_elems=64 * 16)
    )
    assert rows, "tiny delta trace produced no [N, C]-class temps"
    for row in rows:
        assert "packed_dtype" in row and "packed_bytes_each" in row
        if row["dtype"] == "bool":
            assert row["packed_dtype"] == "uint32[bits]"
            # 1 bit/element in whole uint32 words: an 8x-class cut
            assert row["packed_bytes_each"] == -(-row["elems_each"] // 32) * 4
            assert row["packed_bytes_each"] < row["bytes_each"]
        else:
            assert row["packed_bytes_each"] == row["bytes_each"]

    by_bytes = hc.sort_temp_rows(rows, sort="bytes")
    totals = [r["bytes_each"] * r["count"] for r in by_bytes]
    assert totals == sorted(totals, reverse=True)

    by_count = hc.sort_temp_rows(rows, sort="count")
    counts = [r["count"] for r in by_count]
    assert counts == sorted(counts, reverse=True)

    k = min(3, len(rows))
    assert hc.sort_temp_rows(rows, sort="bytes", top=k) == by_bytes[:k]

"""SLO telemetry plane: request-latency histograms, retry backoff,
gray-timeout retry storms (traffic/latency.py + engine latency chain).

The load-bearing oracle is the host latency walk: every log2-histogram
bucket (and every SLO scalar) the compiled serve chain reports must be
bit-identical to a host loop that walks the same forward chains through
``ring_for`` host rings with the SAME RTT draws, backoff schedule, and
gray duty phases.

Fast lane: pure-host helpers (backoff/bucket arithmetic, trace-plane
round trips, checkpoint v5, bridge keys) plus ONE standalone
``serve_once`` oracle — the serve program is a small compile, so the
tier-1 representative lives here.  The full scenario-scan oracles
(delay/jitter x gray x flap compositions, both backends, streamed
bit-parity, the mem-census footprint pin) compile many programs on CPU
and ride the slow lane, like the PR 2/PR 10 parity grids.
"""

from __future__ import annotations

from collections import defaultdict

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ringpop_tpu.models import swim_delta as sdelta
from ringpop_tpu.models import swim_sim as sim
from ringpop_tpu.models.cluster import SimCluster
from ringpop_tpu.models.swim_sim import SwimParams
from ringpop_tpu.ops import ring_ops
from ringpop_tpu.scenarios import compile as scompile
from ringpop_tpu.scenarios import faults as sfaults
from ringpop_tpu.scenarios.spec import ScenarioSpec
from ringpop_tpu.scenarios.trace import Trace
from ringpop_tpu.traffic import engine as tengine
from ringpop_tpu.traffic import latency as tlat
from ringpop_tpu.traffic.workloads import WorkloadSpec

N = 10
LEAN = SwimParams(suspicion_ticks=8, ping_req_size=1)
B = 12
# the oracle workload: exact full-ring walk, latency plane on
SLO_WL = {"kind": "uniform", "keys_per_tick": 24, "pool": 256,
          "window": N * ring_ops.DEFAULT_REPLICA_POINTS,
          "latency_buckets": B}

SLO_COUNTERS = ("lookups", "dropped", "handled_local", "delivered",
                "proxy_retries", "proxy_failed", "send_errors",
                "retry_succeeded", "gray_timeouts", "lat_count",
                "lat_sum_ms", "lat_max_ms")


# ---------------------------------------------------------------------------
# fast: host-side helpers
# ---------------------------------------------------------------------------


def test_backoff_schedule_matches_reference():
    """RETRY_SCHEDULE = [0, 1, 3.5] s (send.js:49), last slot repeated
    past the schedule; tick offsets floor the cumulative ms."""
    np.testing.assert_array_equal(
        tlat.backoff_ms_schedule(3), [0, 1000, 3500]
    )
    np.testing.assert_array_equal(
        tlat.backoff_ms_schedule(5), [0, 1000, 3500, 3500, 3500]
    )
    # cumulative ticks at 200 ms: 0, 0, 5, 22 (0 / 1000 / 4500 ms)
    np.testing.assert_array_equal(
        tlat.backoff_tick_offsets(3, 200), [0, 0, 5, 22]
    )


def test_bucket_index_is_exact_log2():
    vals = np.array([0, 1, 2, 3, 4, 5, 7, 8, 1023, 1024, 10 ** 9])
    got = tlat.bucket_index(vals, 12)
    want = [0 if v == 0 else min(int(v).bit_length(), 11) for v in vals]
    np.testing.assert_array_equal(got, want)
    # jnp path agrees bit for bit
    np.testing.assert_array_equal(
        np.asarray(tlat.bucket_index(jnp.asarray(vals, jnp.int32), 12)), want
    )


def test_hist_stats_percentiles():
    counts = np.zeros(8, np.int64)
    counts[0] = 50  # 50 requests at 0 ms
    counts[4] = 49  # 49 in [8, 16)
    counts[7] = 1  # one in the top bucket
    s = tlat.hist_stats(counts)
    assert s["count"] == 100
    assert s["median"] == 0.0
    assert s["p95"] == 8.0
    assert s["p99"] == 8.0
    assert s["max"] == 64.0
    assert tlat.hist_stats(np.zeros(4, np.int64))["count"] == 0


def test_workload_spec_latency_validation():
    with pytest.raises(ValueError, match="latency_buckets"):
        WorkloadSpec.from_spec({"latency_buckets": 99}).validate(N)
    with pytest.raises(ValueError, match="period_ms"):
        WorkloadSpec.from_spec({"period_ms": 0}).validate(N)
    ws = WorkloadSpec.from_spec({"latency_buckets": B}).validate(N)
    assert ws.latency_buckets == B


# ---------------------------------------------------------------------------
# the host latency walk (the oracle; shared by fast + slow tests)
# ---------------------------------------------------------------------------


def _host_slo_tick(cluster, ct, t):
    """The reference-semantics host model of one SLO traffic tick:
    sample the identical batch, walk the identical forward chains
    through ``ring_for`` host rings, with the SAME latency-stream
    jitter draws, RETRY_SCHEDULE backoff, and gray duty phases the
    compiled chain uses.  Returns (counters dict, hist int64[B])."""
    st = ct.static
    m = st.m
    idx, viewers = tengine.sample_tick(ct.tensors, jnp.int32(t), m)
    idx, viewers = np.asarray(idx), np.asarray(viewers)
    kf, kr = jax.random.split(tlat.latency_key(ct.tensors.key, jnp.int32(t)))
    a_max = st.max_retries + 1
    u_fwd = np.asarray(jax.random.uniform(kf, (a_max, m)))
    u_ret = np.asarray(jax.random.uniform(kr, (m,)))
    bo_ms = tlat.backoff_ms_schedule(st.max_retries)
    bo_ticks = tlat.backoff_tick_offsets(st.max_retries, st.period_ms)

    net = cluster.net
    if net.link_d is not None:
        l_src = np.asarray(net.link_src)
        l_dst = np.asarray(net.link_dst)
        l_d = np.asarray(net.link_d)
        l_j = np.asarray(net.link_j)
    else:
        l_src = None
    period = (
        np.asarray(net.period) if net.period is not None
        else np.ones(cluster.n, np.int32)
    )

    def oneway(a, b, u):
        if l_src is None:
            return 0
        hit = l_src[:, a] & l_dst[:, b]
        base = int(l_d[hit].max(initial=0))
        bound = int(l_j[hit].max(initial=0))
        extra = min(int(np.float32(u) * np.float32(bound + 1)), bound)
        return (base + extra) * st.period_ms

    def duty(h, te):
        per = max(int(period[h]), 1)
        return te % per == (h * (0x9E37 | 1)) % per

    live = set(int(i) for i in cluster.live_indices())
    keys = ct.spec.pool_keys()
    addr_index = cluster.book.index
    rings: dict[int, object] = {}

    def ring_of(node):
        if node not in rings:
            rings[node] = cluster.ring_for(node)
        return rings[node]

    counts = {k: 0 for k in SLO_COUNTERS}
    hist = np.zeros(st.latency_buckets, np.int64)

    def deliver(lat, retries):
        counts["delivered"] += 1
        counts["lat_count"] += 1
        counts["lat_sum_ms"] += lat
        counts["lat_max_ms"] = max(counts["lat_max_ms"], lat)
        if retries > 0:
            counts["retry_succeeded"] += 1
        hist[int(tlat.bucket_index(np.int64(lat), st.latency_buckets))] += 1

    for k in range(m):
        v = int(viewers[k])
        if v not in live:
            counts["dropped"] += 1
            continue
        counts["lookups"] += 1
        key = keys[int(idx[k])]
        owner0 = addr_index[ring_of(v).lookup(key)]
        if owner0 == v:
            counts["handled_local"] += 1
            deliver(0, 0)
            continue
        h, sender, retries = owner0, v, 0
        lat = oneway(v, owner0, u_fwd[0, k])
        settled, final = False, -1
        for i in range(st.max_retries + 1):
            te = t + int(bo_ticks[min(retries, st.max_retries)])
            alive_h = h in live
            if not alive_h or not duty(h, te):
                counts["send_errors"] += 1
                if alive_h:
                    counts["gray_timeouts"] += 1
                if retries < st.max_retries:
                    lat += int(bo_ms[retries]) + oneway(
                        sender, h, u_fwd[i + 1, k]
                    )
                    retries += 1
                    continue
                break
            nxt = addr_index[ring_of(h).lookup(key)]
            if nxt == h:
                settled, final = True, h
                break
            if retries < st.max_retries:
                lat += int(bo_ms[retries]) + oneway(h, nxt, u_fwd[i + 1, k])
                sender, h = h, nxt
                retries += 1
                continue
            break
        counts["proxy_retries"] += retries
        if settled:
            deliver(lat + oneway(final, v, u_ret[k]), retries)
        else:
            counts["proxy_failed"] += 1
    return counts, hist


def _assert_slo_tick_equal(got: dict, t: int, counts: dict, hist) -> None:
    for name, value in counts.items():
        assert int(got[name]) == value, (t, name, int(got[name]), value)
    np.testing.assert_array_equal(
        np.asarray(got["lat_hist_ms"]), hist, err_msg=f"tick {t}"
    )


# ---------------------------------------------------------------------------
# fast: the tier-1 oracle representative (standalone serve program)
# ---------------------------------------------------------------------------


def test_serve_once_latency_oracle_fast():
    """Tier-1 representative of the histogram bit-parity contract: the
    standalone jitted serve chain — against a hand-built net carrying
    delay rules, a gray period row, and a kill — reports every latency
    bucket and SLO counter bit-identical to the host walk.  (The full
    scenario-scan oracles ride the slow grid below; the serve program
    is where all the latency arithmetic lives, so this compiles one
    small program instead of a whole scan.)"""
    c = SimCluster(N, LEAN, seed=6)
    c.tick(3)  # let views diverge a little
    c.kill(4)
    c.tick(2)  # some viewers now disagree about node 4
    ct = c.compile_traffic(SLO_WL)
    # hand-built failure state: one delay rule + two gray nodes
    src = np.zeros((1, N), bool)
    dst = np.zeros((1, N), bool)
    src[0, [0, 1, 2, 3, 4]] = True
    dst[0, [5, 6, 7, 8, 9]] = True
    period = np.ones(N, np.int32)
    period[[1, 2]] = 4
    net = c.net._replace(
        link_src=jnp.asarray(src),
        link_dst=jnp.asarray(dst),
        link_p=jnp.zeros(1, jnp.float32),
        link_d=jnp.asarray([2], jnp.int32),
        link_j=jnp.asarray([3], jnp.int32),
        period=jnp.asarray(period),
    )
    c.net = net  # the host walk reads rules/period from cluster.net
    for t in (0, 1, 2, 5):
        got = tengine.serve_once(
            c.state.view_key, net.up, net.responsive, ct.tensors,
            jnp.int32(t), static=ct.static, net=net,
            period=net.period,
        )
        counts, hist = _host_slo_tick(c, ct, t)
        _assert_slo_tick_equal(got, t, counts, hist)
    # the failure mix actually exercised the storm paths
    assert int(got["lat_hist_ms"].sum()) == int(got["delivered"])


def test_latency_plane_off_keeps_legacy_schema():
    """latency_buckets=0 keeps the exact legacy counter schema (no SLO
    scalars, no planes) — the static gate the bit-compatibility of
    every existing traffic program rests on."""
    off = WorkloadSpec.from_spec(dict(SLO_WL, latency_buckets=0))
    c = SimCluster(N, LEAN, seed=2)
    ct = c.compile_traffic(off)
    assert tengine.plane_names(ct.static) == ()
    names = tengine.counter_names(ct.static)
    assert "lat_count" not in names and "send_errors" not in names
    ct_on = c.compile_traffic(SLO_WL)
    on_names = tengine.counter_names(ct_on.static)
    assert set(names) < set(on_names)
    assert tengine.plane_names(ct_on.static) == (("lat_hist_ms", B),)


# ---------------------------------------------------------------------------
# fast: trace planes, checkpoint v5, bridge keys (pure host)
# ---------------------------------------------------------------------------


def _plane_trace(ticks=6, b=B, n=N, seed=0):
    rng = np.random.default_rng(seed)
    metrics = {
        "pings_sent": rng.integers(0, n, ticks).astype(np.int32),
        "delivered": rng.integers(0, 20, ticks).astype(np.int32),
        "lookups": rng.integers(0, 24, ticks).astype(np.int32),
        "proxy_sends": rng.integers(0, 9, ticks).astype(np.int32),
        "proxy_retries": rng.integers(0, 9, ticks).astype(np.int32),
        "proxy_failed": rng.integers(0, 3, ticks).astype(np.int32),
        "send_errors": rng.integers(0, 5, ticks).astype(np.int32),
        "retry_succeeded": rng.integers(0, 5, ticks).astype(np.int32),
    }
    return Trace(
        metrics=metrics,
        planes={"lat_hist_ms": rng.integers(0, 7, (ticks, b)).astype(np.int32)},
        converged=np.ones(ticks, bool),
        live=np.full(ticks, n, np.int32),
        loss=np.zeros(ticks, np.float32),
        n=n,
        backend="dense",
        spec={"ticks": ticks, "events": []},
    ).validate()


def test_trace_plane_npz_roundtrip_concat_and_summary(tmp_path):
    trace = _plane_trace()
    path = str(tmp_path / "t.npz")
    trace.save(path)
    back = Trace.load(path)
    np.testing.assert_array_equal(
        back.planes["lat_hist_ms"], trace.planes["lat_hist_ms"]
    )
    # slab split + concat is bit-identical (the streamed-drain contract)
    slabs = [
        Trace(
            metrics={k: v[a:b] for k, v in trace.metrics.items()},
            planes={k: v[a:b] for k, v in trace.planes.items()},
            converged=trace.converged[a:b],
            live=trace.live[a:b],
            loss=trace.loss[a:b],
            n=trace.n,
            backend=trace.backend,
            start_tick=a,
        )
        for a, b in ((0, 2), (2, 4), (4, 6))
    ]
    cat = Trace.concat(slabs, spec=trace.spec)
    np.testing.assert_array_equal(
        cat.planes["lat_hist_ms"], trace.planes["lat_hist_ms"]
    )
    # summary reports the aggregated histogram's percentile estimates
    s = trace.summary()["lat_hist_ms"]
    assert s["count"] == int(trace.planes["lat_hist_ms"].sum())
    # validate rejects a misshapen plane
    bad = _plane_trace()
    bad.planes["lat_hist_ms"] = bad.planes["lat_hist_ms"][:3]
    with pytest.raises(ValueError, match="plane"):
        bad.validate()


def test_checkpoint_v5_roundtrips_histogram_planes(tmp_path):
    """Trace planes ride the checkpoint via the existing optional-field
    protocol ('p.'-prefixed arrays next to the 'm.' metric series)."""
    from ringpop_tpu import checkpoint

    c = SimCluster(N, LEAN, seed=1)
    c.traces.append(_plane_trace())
    path = str(tmp_path / "ck.npz")
    checkpoint.save(c, path)
    back = checkpoint.load(path)
    assert len(back.traces) == 1
    np.testing.assert_array_equal(
        back.traces[0].planes["lat_hist_ms"],
        c.traces[0].planes["lat_hist_ms"],
    )
    # delta in-flight lanes round-trip as optional state tensors
    d = SimCluster(4, LEAN, seed=0, backend="delta", capacity=4)
    d.enable_delay(3)
    dpath = str(tmp_path / "ckd.npz")
    checkpoint.save(d, dpath)
    dback = checkpoint.load(dpath)
    np.testing.assert_array_equal(
        np.asarray(dback.state.pend_subj), np.asarray(d.state.pend_subj)
    )
    assert dback.state.pend_recv.shape == d.state.pend_recv.shape


def test_bridge_emits_latency_keys_and_timings():
    from ringpop_tpu.obs import bridge
    from ringpop_tpu.obs.emitters import CaptureEmitter

    trace = _plane_trace()
    cap = CaptureEmitter()
    bridge.replay_trace(trace, cap)
    suffixes = cap.suffixes(bridge.DEFAULT_PREFIX)
    for key in bridge.TRAFFIC_LATENCY_KEYS:
        assert key in suffixes, key
    # timing values are bucket-floor ms, capped per (tick, bucket)
    timings = cap.timings[f"{bridge.DEFAULT_PREFIX}.requestProxy.send"]
    hist = trace.planes["lat_hist_ms"]
    expect = sum(
        min(int(c), bridge.TIMING_REPLAY_CAP)
        for row in hist
        for c in row
        if c
    )
    assert len(timings) == expect
    edges = set(np.concatenate([[0], tlat.bucket_edges_ms(B)]).tolist())
    assert set(timings) <= edges
    # a latency-off traffic trace emits none of the latency namespace
    off = _plane_trace()
    off.planes = {}
    for name in ("send_errors", "retry_succeeded"):
        del off.metrics[name]
    cap2 = CaptureEmitter()
    bridge.replay_trace(off, cap2)
    suffixes2 = cap2.suffixes(bridge.DEFAULT_PREFIX)
    assert not (set(bridge.TRAFFIC_LATENCY_KEYS) & suffixes2)


# ---------------------------------------------------------------------------
# slow: scenario-scan oracles (both backends), streamed parity, census
# ---------------------------------------------------------------------------


def _host_scenario_slo(backend, spec_obj, ct, seed, **kw):
    """Step the protocol per tick exactly as the compiled scan does
    (events + faultcfg at tick start, then the step with the schedule
    key) and run the host latency walk against each tick's views.
    Returns per-tick (counts, hist) lists."""
    c = SimCluster(N, LEAN, seed=seed, backend=backend, **kw)
    plan = sfaults.HostPlan(spec_obj, c.n)
    plan.prepare(c)
    compiled = scompile.compile_spec(spec_obj, c.n, base_loss=c.params.loss)
    keys = scompile.key_schedule(c._split, compiled)
    by_tick = defaultdict(list)
    for at, op, arg in scompile.expand_events(spec_obj, c.params.loss):
        by_tick[at].append((op, arg))
    out = []
    for t in range(spec_obj.ticks):
        ops = sorted(by_tick.get(t, ()), key=lambda x: scompile._OP_RANK[x[0]])
        cfg_done = False
        for op, arg in ops:
            if op == "kill":
                c.kill(arg)
            elif op == "suspend":
                c.suspend(arg)
            elif op == "resume":
                c.resume(arg)
            elif op == "revive":
                c.revive(arg)
            elif op == "partition":
                c.partition([list(g) for g in arg])
            elif op == "heal":
                c.heal_partition()
            elif op == "loss":
                c.set_loss(arg)
            elif op == "faultcfg" and not cfg_done:
                plan.apply(c, t)
                cfg_done = True
        if backend == "delta":
            c.state, _ = sdelta.delta_step(
                c.state, c.net, keys[t], params=c.dparams
            )
        else:
            c.state, _ = sim.swim_step(c.state, c.net, keys[t], params=c.params)
        out.append(_host_slo_tick(c, ct, t))
    return out


SLO_SPECS = {
    "delay": {
        "ticks": 10,
        "events": [
            {"at": 1, "op": "delay", "src": [0, 1, 2], "dst": [5, 6, 7],
             "delay": 2, "jitter": 2, "until": 8},
            {"at": 2, "op": "kill", "node": 9},
        ],
    },
    "gray": {
        "ticks": 10,
        "events": [
            {"at": 1, "op": "gray", "nodes": [2, 3, 4], "factor": 5,
             "until": 9},
            {"at": 2, "op": "kill", "node": 9},
        ],
    },
    "delay+gray+flap": {
        "ticks": 12,
        "events": [
            {"at": 1, "op": "delay", "src": [0, 1], "dst": [6, 7],
             "delay": 1, "jitter": 1, "until": 10},
            {"at": 2, "op": "gray", "node": 3, "factor": 4, "until": 10},
            {"at": 3, "op": "flap", "node": 8, "until": 9, "down": 2,
             "up": 2},
        ],
    },
    "link_loss+delay": {
        "ticks": 10,
        "events": [
            {"at": 1, "op": "link_loss", "src": [0, 1], "dst": [4, 5],
             "p": 0.7, "until": 8},
            {"at": 2, "op": "delay", "src": [4, 5], "dst": [0, 1],
             "delay": 1, "jitter": 2, "until": 8},
        ],
    },
}


@pytest.mark.slow
@pytest.mark.parametrize("backend", ["dense", "delta"])
@pytest.mark.parametrize("name", sorted(SLO_SPECS))
def test_scenario_latency_oracle_grid(backend, name):
    """The acceptance grid: per-tick latency histograms and SLO
    counters from the compiled scenario+traffic scan bit-match the
    host walk, over delay/jitter/gray/flap compositions, on BOTH
    backends (the delta arm runs per-link delay through the in-flight
    claim lanes)."""
    spec_obj = ScenarioSpec.from_dict(SLO_SPECS[name])
    if backend == "delta" and any(
        e.op in ("flap", "rolling_restart", "revive")
        for e in spec_obj.events
    ):
        pytest.skip("in-scan revive is dense-only")
    kw = (
        {}
        if backend == "dense"
        else dict(capacity=N, wire_cap=N, claim_grid=3 * N * N)
    )
    a = SimCluster(N, LEAN, seed=11, backend=backend, **kw)
    ct = a.compile_traffic(SLO_WL)
    trace = a.run_scenario(spec_obj, traffic=ct)
    want = _host_scenario_slo(backend, spec_obj, ct, seed=11, **kw)
    assert trace.planes["lat_hist_ms"].shape == (spec_obj.ticks, B)
    for t, (counts, hist) in enumerate(want):
        got = {k: trace.metrics[k][t] for k in SLO_COUNTERS}
        got["lat_hist_ms"] = trace.planes["lat_hist_ms"][t]
        _assert_slo_tick_equal(got, t, counts, hist)
    if "gray" in name:
        assert int(trace.metrics["gray_timeouts"].sum()) > 0
    if "delay" in name:
        assert int(
            (trace.planes["lat_hist_ms"][:, 1:]).sum()
        ) > 0, "delay rules put no mass above the zero bucket"


@pytest.mark.slow
def test_latency_on_without_faults_matches_plain_chain():
    """With no gray/delay anywhere, the latency chain's routing
    decisions reduce to the plain chain exactly: every shared serving
    counter is bit-identical with the plane on vs off, and all the
    histogram mass sits in bucket 0."""
    spec = {"ticks": 8, "events": [{"at": 2, "op": "kill", "node": 3}]}
    a = SimCluster(N, LEAN, seed=4)
    ta = a.run_scenario(spec, traffic=SLO_WL)
    b = SimCluster(N, LEAN, seed=4)
    tb = b.run_scenario(spec, traffic=dict(SLO_WL, latency_buckets=0))
    for name in tb.metrics:
        np.testing.assert_array_equal(ta.metrics[name], tb.metrics[name], name)
    hist = ta.planes["lat_hist_ms"]
    assert int(hist[:, 1:].sum()) == 0
    np.testing.assert_array_equal(hist[:, 0], ta.metrics["delivered"])


@pytest.mark.slow
def test_streamed_latency_planes_bit_identical(tmp_path):
    """Streaming a latency-enabled scenario (O(segment) drains) is an
    execution strategy: planes, SLO counters, and the store round trip
    are bit-identical to the unsegmented run."""
    spec = SLO_SPECS["delay+gray+flap"]
    a = SimCluster(N, LEAN, seed=9)
    plain = a.run_scenario(spec, traffic=SLO_WL)
    b = SimCluster(N, LEAN, seed=9)
    store = str(tmp_path / "store")
    streamed = b.run_scenario(
        spec, traffic=SLO_WL, segment_ticks=5, store=store
    )
    np.testing.assert_array_equal(
        plain.planes["lat_hist_ms"], streamed.planes["lat_hist_ms"]
    )
    for name in plain.metrics:
        np.testing.assert_array_equal(
            plain.metrics[name], streamed.metrics[name], name
        )
    # the per-segment slabs carry the plane rows too
    from ringpop_tpu.scenarios.stream import SegmentStore

    slabs = list(SegmentStore.open(store).iter_traces())
    assert all("lat_hist_ms" in s.planes for s in slabs)
    np.testing.assert_array_equal(
        np.concatenate([s.planes["lat_hist_ms"] for s in slabs]),
        plain.planes["lat_hist_ms"],
    )


@pytest.mark.slow
def test_delta_delay_protocol_parity_and_maturity():
    """The delta in-flight lanes: compiled scan == host loop bit for
    bit on a delay+jitter spec (protocol level — the PR 10 parity
    contract extended to the delta backend), with claims actually
    delayed AND matured into applications."""
    from ringpop_tpu.scenarios import runner

    spec_obj = ScenarioSpec.from_dict(
        {
            "ticks": 20,
            "events": [
                {"at": 1, "op": "delay", "src": list(range(5)),
                 "dst": list(range(5, 10)), "delay": 2, "jitter": 1,
                 "until": 16},
                {"at": 3, "op": "kill", "node": 9},
            ],
        }
    )
    kw = dict(capacity=N, wire_cap=N, claim_grid=3 * N * N)
    a = SimCluster(N, LEAN, seed=7, backend="delta", **kw)
    trace = a.run_scenario(spec_obj)
    b = SimCluster(N, LEAN, seed=7, backend="delta", **kw)
    runner.run_host_loop(b, spec_obj)
    for f, x, y in zip(a.state._fields, a.state, b.state):
        if x is None or f == "tick":
            assert (x is None) == (y is None), f
            continue
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y), f)
    assert a.checksums() == b.checksums()
    assert int(trace.metrics["delayed_claims"].sum()) > 0
    assert int(trace.metrics["matured_applied"].sum()) > 0


@pytest.mark.slow
def test_delta_full_sync_flip_deviation_pinned():
    """The ONE documented delta delay deviation, pinned: the full-sync
    flip applies in-tick even over a delayed link (it is a structural
    base flip, not a claim payload the pending lanes can carry —
    swim_delta.py phase-4 ack path; docs/simulation.md delay row).

    The pin asserts (a) the deviation is actually exercised — full
    syncs fire on ticks whose links are delaying claims — and (b) its
    divergence stays BOUNDED: the early flip only accelerates
    convergence to the receiver's view, so the delta run re-converges
    within the same horizon as the dense run from the same seed and
    both end at one checksum group with equal live sets.  If a future
    change routes the flip through the lanes, this test's full-sync
    counts shift and the pin (plus the doc row) must be updated
    together."""
    spec = {
        "ticks": 40,
        "events": [
            {"at": 1, "op": "delay", "src": list(range(N)),
             "dst": list(range(N)), "delay": 1, "jitter": 1, "until": 36},
            {"at": 2, "op": "loss", "p": 0.25},
            {"at": 4, "op": "kill", "node": 9},
            {"at": 20, "op": "loss", "p": 0.0},
        ],
    }
    kw = dict(capacity=N, wire_cap=N, claim_grid=3 * N * N)
    d = SimCluster(N, LEAN, seed=3, backend="delta", **kw)
    td = d.run_scenario(spec)
    fs = td.metrics["full_syncs"]
    dc = td.metrics["delayed_claims"]
    # the deviation fired: full syncs landed while links were delaying
    assert int(fs.sum()) > 0
    assert int(((fs > 0) & (dc > 0)).sum()) > 0, (
        "no full sync overlapped an active delay window; the deviation "
        "was not exercised — strengthen the spec"
    )
    a = SimCluster(N, LEAN, seed=3, backend="dense")
    ta = a.run_scenario(spec)
    # bounded divergence: both backends heal inside the horizon
    assert bool(td.converged[-1]) and bool(ta.converged[-1])
    assert int(td.live[-1]) == int(ta.live[-1])
    assert len(set(d.checksums().values())) == 1
    assert len(set(a.checksums().values())) == 1


@pytest.mark.slow
def test_mem_census_latency_axis_linear_output_flat_segment():
    """The latency plane's footprint shape: the whole-horizon program's
    OUTPUT bytes grow with T (the [T, B] histogram rows), while the
    S-tick segment program's bytes are flat in total T — the
    O(segment) streaming contract extended to the planes."""
    from benchmarks import mem_census

    b = 16
    short = mem_census.census_scenario(
        "dense", 64, 8, 64, latency_buckets=b
    )
    long = mem_census.census_scenario(
        "dense", 64, 16, 64, latency_buckets=b
    )
    grown = long["output_bytes"] - short["output_bytes"]
    # 8 extra ticks of [B] int32 rows, plus the scalar series growth
    assert grown >= 8 * b * 4, (short["output_bytes"], long["output_bytes"])
    seg_short = mem_census.census_scenario(
        "dense", 64, 8, 64, segment_ticks=4, latency_buckets=b
    )
    seg_long = mem_census.census_scenario(
        "dense", 64, 16, 64, segment_ticks=4, latency_buckets=b
    )
    for field in ("temp_bytes", "argument_bytes"):
        assert seg_short[field] == seg_long[field], field

"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Mirrors the reference's strategy of testing multi-node behavior without a
real cluster (test/lib/test-ringpop-cluster.js): we test multi-chip sharding
without real chips via ``xla_force_host_platform_device_count``.

Must run before jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The environment may pre-register a TPU plugin and pin jax_platforms via
# sitecustomize, overriding the env var — force CPU at the config level too
# (before any backend initializes).
import jax

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: integration tests that spawn real worker processes"
    )

"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Mirrors the reference's strategy of testing multi-node behavior without a
real cluster (test/lib/test-ringpop-cluster.js): we test multi-chip sharding
without real chips via ``xla_force_host_platform_device_count``.

Must run before jax is imported anywhere.
"""

import os

# Force (not setdefault): the ambient environment may carry
# JAX_PLATFORMS=<tpu plugin>, and code under test consults the env var.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The environment may pre-register a TPU plugin and pin jax_platforms via
# sitecustomize, overriding the env var — force CPU at the config level too
# (before any backend initializes).
import jax

jax.config.update("jax_platforms", "cpu")
# The TPU plugin (registered at interpreter startup, before this file
# runs) routes bare get_backend() — e.g. the first jnp.asarray's
# device_put — to the TPU tunnel regardless of jax_platforms when
# JAX_PLATFORMS was not in the environment at process start.  Pinning the
# default device forces that path onto CPU too.
jax.config.update("jax_default_device", jax.devices("cpu")[0])


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: multi-minute soaks (alternate-lowering parity grids, "
        "profiling prefixes, real-process integration); deselected by "
        "default via pytest.ini addopts, run with -m slow",
    )

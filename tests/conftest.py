"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Mirrors the reference's strategy of testing multi-node behavior without a
real cluster (test/lib/test-ringpop-cluster.js): we test multi-chip sharding
without real chips via ``xla_force_host_platform_device_count``.

Must run before jax is imported anywhere.
"""

import os

# Force (not setdefault): the ambient environment may carry
# JAX_PLATFORMS=<tpu plugin>, and code under test consults the env var.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The environment may pre-register a TPU plugin and pin jax_platforms via
# sitecustomize, overriding the env var — force CPU at the config level too
# (before any backend initializes).
import jax

jax.config.update("jax_platforms", "cpu")
# The TPU plugin (registered at interpreter startup, before this file
# runs) routes bare get_backend() — e.g. the first jnp.asarray's
# device_put — to the TPU tunnel regardless of jax_platforms when
# JAX_PLATFORMS was not in the environment at process start.  Pinning the
# default device forces that path onto CPU too.
jax.config.update("jax_default_device", jax.devices("cpu")[0])


import pytest  # noqa: E402  (jax platform pin must precede any import)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: multi-minute soaks (alternate-lowering parity grids, "
        "profiling prefixes, real-process integration); deselected by "
        "default via pytest.ini addopts, run with -m slow",
    )
    config.addinivalue_line(
        "markers",
        "allow_transfers: opt out of the tier-1 disallow transfer "
        "guard (host-loop oracles and host<->device round-trip tests "
        "that transfer implicitly by design)",
    )


@pytest.fixture(autouse=True)
def _no_implicit_transfers(request):
    """Tier-1 compiled-path tests run under
    ``jax.transfer_guard_device_to_host("disallow")``: a silent
    IMPLICIT device->host sync — the direction that serializes dispatch
    pipelining — raises immediately instead of quietly stalling.  The
    host->device direction stays open (feeding a Python scalar to a
    jitted call is an implicit h2d and is ubiquitous + benign); the
    explicit transfers (``jax.device_get``, ``np.asarray`` on a
    concrete array) stay legal too — reading RESULTS is fine, it is the
    hidden mid-pipeline drain the guard bans.  On this CPU-only suite
    the guard is ~free; on a real accelerator it is the runtime
    tripwire for the trace-contract auditor's host-transfer contract
    (ringpop_tpu/analysis).  Opt out with
    ``@pytest.mark.allow_transfers`` for host-loop oracles that
    transfer implicitly by design."""
    if request.node.get_closest_marker("allow_transfers"):
        yield
        return
    with jax.transfer_guard_device_to_host("disallow"):
        yield

"""Property tests for the packed-plane layout (ops/bitpack.py).

The layout convention these tests pin — last-axis packing, bit j of
word i = element i*32+j, zero pad bits on ragged tails — is what
checkpoint v5 tensors and the pinned carry-dtype budgets rely on; a
layout change is a format break, not a refactor.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import pytest

from ringpop_tpu.ops import bitpack

# Ragged tails (L % 32 != 0) alongside exact multiples — claim capacity
# C=64 is the at-rest shape, the rest probe the pad-bit convention.
LENGTHS = (1, 31, 32, 33, 64, 100, 256)


def _cases(length: int, rng: np.random.Generator):
    yield np.zeros((3, length), dtype=bool)
    yield np.ones((3, length), dtype=bool)
    yield rng.random((3, length)) < 0.5
    yield rng.random((5, 3, length)) < 0.1  # 3-D: pend-style planes


@pytest.mark.parametrize("length", LENGTHS)
def test_roundtrip(length):
    rng = np.random.default_rng(length)
    for mask in _cases(length, rng):
        packed = bitpack.pack_bits(jnp.asarray(mask))
        assert packed.dtype == jnp.uint32
        assert packed.shape == (
            *mask.shape[:-1], bitpack.packed_width(length)
        )
        out = bitpack.unpack_bits(packed, length)
        assert out.dtype == bool
        np.testing.assert_array_equal(np.asarray(out), mask)


@pytest.mark.parametrize("length", LENGTHS)
def test_pad_bits_zero(length):
    """Ragged-tail pad bits are zero: packed planes of equal masks are
    bitwise equal, and popcount needs no tail masking."""
    rng = np.random.default_rng(1000 + length)
    mask = rng.random((4, length)) < 0.5
    packed = np.asarray(bitpack.pack_bits(jnp.asarray(mask)))
    tail = length % 32
    if tail:
        assert not np.any(packed[..., -1] >> tail)
    # all-ones plane: every pad bit still zero
    ones = np.asarray(bitpack.pack_bits(jnp.ones(length, dtype=bool)))
    total = int(ones.astype(np.uint64).sum())
    expect = sum(int(w) for w in _expected_ones_words(length))
    assert total == expect


def _expected_ones_words(length: int):
    words = bitpack.packed_width(length)
    for i in range(words):
        bits = min(32, length - i * 32)
        yield (1 << bits) - 1 if bits < 32 else 0xFFFFFFFF


def test_bit_layout_little_endian():
    """Bit j of word i holds element i*32 + j."""
    mask = np.zeros(70, dtype=bool)
    mask[0] = True     # word 0, bit 0
    mask[33] = True    # word 1, bit 1
    mask[69] = True    # word 2, bit 5
    packed = np.asarray(bitpack.pack_bits(jnp.asarray(mask)))
    assert packed.tolist() == [1, 2, 32]


@pytest.mark.parametrize("length", (33, 64, 100))
def test_bit_gather_matches_fancy_index(length):
    rng = np.random.default_rng(7 * length)
    mask = rng.random(length) < 0.5
    packed = bitpack.pack_bits(jnp.asarray(mask))
    idx = rng.integers(0, length, size=(6, 9))
    got = bitpack.bit_gather(packed, jnp.asarray(idx, dtype=jnp.int32))
    assert got.dtype == bool
    np.testing.assert_array_equal(np.asarray(got), mask[idx])


def test_bit_gather_sided():
    rng = np.random.default_rng(11)
    mask = rng.random((3, 40)) < 0.5
    packed = bitpack.pack_bits(jnp.asarray(mask))
    idx = rng.integers(0, 40, size=(5, 4))
    row = rng.integers(0, 3, size=(5, 4))
    got = bitpack.bit_gather(
        packed, jnp.asarray(idx, dtype=jnp.int32),
        jnp.asarray(row, dtype=jnp.int32),
    )
    np.testing.assert_array_equal(np.asarray(got), mask[row, idx])


@pytest.mark.parametrize("length", LENGTHS)
def test_popcount(length):
    rng = np.random.default_rng(13 * length + 1)
    mask = rng.random((4, length)) < 0.3
    packed = bitpack.pack_bits(jnp.asarray(mask))
    assert int(bitpack.popcount_bits(packed)) == int(mask.sum())
    per_row = bitpack.popcount_bits(packed, axis=-1)
    np.testing.assert_array_equal(
        np.asarray(per_row), mask.sum(axis=-1).astype(np.int32)
    )

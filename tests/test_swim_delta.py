"""Delta-from-base backend (models/swim_delta.py) vs the dense step.

Contract (swim_delta.py docstring): with ample caps (wire_cap /
claim_grid / capacity larger than any burst) the delta trajectory is
**bit-identical** to ``swim_step`` from the same PRNG key — through
loss, kills, suspends, joins, leaves and revives.  At production caps it
degrades to bounded-resource semantics (claims_dropped /
overflow_drops surfaced in metrics) but must still converge.

Regression anchored here: the claim-routing dedup left SENTINEL holes
mid-row, breaking the sortedness that ``_merge_claims``' binary search
relies on — claims after a duplicate subject were silently lost under
loss (first seen as a tick-14..33 divergence at loss=0.05).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ringpop_tpu.models import swim_delta as sd
from ringpop_tpu.models import swim_sim as sim
from ringpop_tpu.ops import bitpack

# jit without donation: tests keep references across steps
_dense_step = jax.jit(sim.swim_step_impl, static_argnames=("params",))
_delta_step = jax.jit(sd.delta_step_impl, static_argnames=("params",))


def assert_matches_dense(delta: sd.DeltaState, dense: sim.ClusterState, tick):
    dd = sd.densify(delta)
    np.testing.assert_array_equal(
        np.asarray(dd.view_key),
        np.asarray(dense.view_key),
        err_msg=f"view_key tick {tick}",
    )
    np.testing.assert_array_equal(
        np.asarray(dd.pb), np.asarray(dense.pb), err_msg=f"pb tick {tick}"
    )
    np.testing.assert_array_equal(
        np.asarray(dd.suspect_left),
        np.asarray(dense.suspect_left),
        err_msg=f"suspect_left tick {tick}",
    )


def run_both(n, ticks, params, *, capacity=None, events=(), seed=0):
    """Drive dense + delta from the same keys; yield each tick."""
    dparams = sd.DeltaParams(swim=params, wire_cap=n, claim_grid=3 * n * n)
    dense = sim.init_state(n)
    delta = sd.init_delta(n, capacity=capacity or n)
    net = sim.make_net(n)
    keys = jax.random.split(jax.random.PRNGKey(seed), ticks)
    for t in range(ticks):
        for when, op, arg in events:
            if when != t:
                continue
            if op == "kill":
                net = net._replace(up=net.up.at[arg].set(False))
            elif op == "suspend":
                net = net._replace(responsive=net.responsive.at[arg].set(False))
            elif op == "resume":
                net = net._replace(responsive=net.responsive.at[arg].set(True))
            elif op == "leave":
                dense = sim.admin_leave(dense, arg)
                delta = sd.admin_leave(delta, arg)
        dense, md = _dense_step(dense, net, keys[t], params)
        delta, me = _delta_step(delta, net, keys[t], dparams)
        yield t, dense, delta, md, me


METRIC_KEYS = (
    "pings_sent",
    "acks",
    "ping_changes_applied",
    "ack_changes_applied",
    "full_syncs",
    "ping_reqs",
    "suspects_declared",
    "faulty_declared",
)


def test_bit_identical_steady_state_with_loss():
    """5% loss on a converged cluster: suspects, refutations, duplicate
    concurrent claims, full syncs — every tick bit-for-bit (this is the
    routing-dedup regression scenario)."""
    n = 24
    params = sim.SwimParams(loss=0.05)
    for t, dense, delta, md, me in run_both(n, 50, params):
        assert_matches_dense(delta, dense, t)
        for k in METRIC_KEYS:
            assert int(md[k]) == int(me[k]), f"metric {k} tick {t}"
        assert int(me["claims_dropped"]) == 0
        assert int(me["overflow_drops"]) == 0


@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 1])
def test_bit_identical_kill_under_loss(seed):
    """Kill + 5% loss: the full suspect -> refute-race -> faulty chain
    with lossy rumor fronts must match bit-for-bit."""
    n = 32
    params = sim.SwimParams(loss=0.05, suspicion_ticks=10)
    last = None
    for t, dense, delta, _, _ in run_both(
        n, 45, params, events=[(0, "kill", 3), (12, "kill", 17)], seed=seed
    ):
        assert_matches_dense(delta, dense, t)
        last = dense
    vs = np.asarray(last.view_key) & 7
    live = [i for i in range(n) if i not in (3, 17)]
    assert all(vs[i, 3] == sim.FAULTY for i in live)


@pytest.mark.slow
def test_bit_identical_suspend_resume():
    """SIGSTOP analog: a suspended node neither probes nor answers; its
    timers fire on resume (tick-cluster.js:432-446 semantics)."""
    n = 16
    params = sim.SwimParams(loss=0.02, suspicion_ticks=6)
    for t, dense, delta, _, _ in run_both(
        n, 40, params, events=[(2, "suspend", 7), (25, "resume", 7)]
    ):
        assert_matches_dense(delta, dense, t)


@pytest.mark.slow
def test_bit_identical_leave():
    n = 16
    params = sim.SwimParams(loss=0.02)
    for t, dense, delta, _, _ in run_both(n, 30, params, events=[(3, "leave", 5)]):
        assert_matches_dense(delta, dense, t)


@pytest.mark.slow
def test_admin_join_and_revive_match_dense():
    """revive_and_join == dense revive + admin_join, then parity holds
    through the re-dissemination of the fresh incarnation."""
    n = 16
    params = sim.SwimParams(loss=0.0, suspicion_ticks=5)
    dparams = sd.DeltaParams(swim=params, wire_cap=n, claim_grid=3 * n * n)
    dense = sim.init_state(n)
    delta = sd.init_delta(n, capacity=n)
    net = sim.make_net(n)
    net = net._replace(up=net.up.at[4].set(False))
    keys = jax.random.split(jax.random.PRNGKey(7), 40)
    for t in range(20):  # node 4 goes suspect -> faulty everywhere
        dense, _ = _dense_step(dense, net, keys[t], params)
        delta, _ = _delta_step(delta, net, keys[t], dparams)
    assert_matches_dense(delta, dense, "pre-revive")

    inc = int(jnp.max(dense.view_key) >> 3) + 1000
    dense = sim.revive(dense, 4, inc)
    dense = sim.admin_join(dense, 4, 0)
    delta = sd.revive_and_join(delta, 4, inc, 0)
    net = net._replace(up=net.up.at[4].set(True))
    assert_matches_dense(delta, dense, "post-revive")

    for t in range(20, 40):
        dense, _ = _dense_step(dense, net, keys[t], params)
        delta, _ = _delta_step(delta, net, keys[t], dparams)
        assert_matches_dense(delta, dense, t)
    vs = np.asarray(dense.view_key) & 7
    assert all(vs[i, 4] == sim.ALIVE for i in range(n))


@pytest.mark.slow
def test_compact_and_rebase_preserve_views():
    """compact/rebase change the representation, never the views — and
    the post-maintenance trajectory stays on the dense trajectory."""
    n = 24
    params = sim.SwimParams(loss=0.05, suspicion_ticks=8)
    dparams = sd.DeltaParams(swim=params, wire_cap=n, claim_grid=3 * n * n)
    dense = sim.init_state(n)
    delta = sd.init_delta(n, capacity=n)
    net = sim.make_net(n)
    net = net._replace(up=net.up.at[5].set(False))
    keys = jax.random.split(jax.random.PRNGKey(3), 60)
    for t in range(60):
        dense, _ = _dense_step(dense, net, keys[t], params)
        delta, _ = _delta_step(delta, net, keys[t], dparams)
        if t % 15 == 14:
            before = sd.densify(delta)
            delta = sd.rebase(delta)  # rebase() compacts first
            after = sd.densify(delta)
            np.testing.assert_array_equal(
                np.asarray(before.view_key), np.asarray(after.view_key)
            )
            np.testing.assert_array_equal(np.asarray(before.pb), np.asarray(after.pb))
            np.testing.assert_array_equal(
                np.asarray(before.suspect_left), np.asarray(after.suspect_left)
            )
        assert_matches_dense(delta, dense, t)


def test_rebase_folds_converged_fault():
    """After the cluster converges on a kill, rebase folds the majority
    faulty entry into base_key: the 15 live viewers drop their slots and
    only the dead node keeps one compensating slot (its frozen stale
    view), so long-running simulations return to the near-all-base fast
    path.  Views must be unchanged by the fold."""
    n = 16
    params = sim.SwimParams(loss=0.0, suspicion_ticks=4)
    dparams = sd.DeltaParams(swim=params, wire_cap=n, claim_grid=3 * n * n)
    delta = sd.init_delta(n, capacity=n)
    net = sim.make_net(n)
    net = net._replace(up=net.up.at[2].set(False))
    key = jax.random.PRNGKey(0)
    # converge on the kill, then let piggyback counters evict
    for _ in range(280):
        key, sub = jax.random.split(key)
        delta, me = _delta_step(delta, net, sub, dparams)
        if int(jnp.sum(delta.d_pb >= 0)) == 0:
            break
    before = sd.densify(delta)
    delta = sd.rebase(delta)
    after = sd.densify(delta)
    np.testing.assert_array_equal(
        np.asarray(before.view_key), np.asarray(after.view_key)
    )
    occ = int(jnp.sum(delta.d_subj < sd.SENTINEL))
    assert occ == 1, f"rebase left {occ} slots (want 1: the dead node's)"
    assert int(delta.base_key[2]) & 7 == sim.FAULTY
    # the one remaining slot is the dead node's frozen self-view
    assert int(delta.d_subj[2, 0]) == 2


def test_capacity_overflow_drops_counted_and_converges():
    """capacity far below the divergence burst: insertions drop (counted
    in overflow_drops), but gossip + full sync still converge the views
    on the dense trajectory's *fixed point* (not its path)."""
    n = 32
    params = sim.SwimParams(loss=0.0, suspicion_ticks=4)
    dparams = sd.DeltaParams(swim=params, wire_cap=8, claim_grid=16)
    delta = sd.init_delta(n, capacity=4)
    net = sim.make_net(n)
    net = net._replace(up=net.up.at[9].set(False))
    key = jax.random.PRNGKey(1)
    for _ in range(200):
        key, sub = jax.random.split(key)
        delta, me = _delta_step(delta, net, sub, dparams)
        dd = sd.densify(delta)
        vk = np.asarray(dd.view_key)
        live = [i for i in range(n) if i != 9]
        if all((vk[i, 9] & 7) == sim.FAULTY for i in live) and (
            vk[live][:, live] == vk[live[0]][live]
        ).all():
            break
    else:
        pytest.fail("delta backend with tiny capacity failed to converge on the kill")


def test_wire_cap_window_ships_later():
    """Changes past the wire window neither bump nor evict — they ship on
    later pings; nothing is lost, convergence completes."""
    n = 24
    params = sim.SwimParams(loss=0.0, suspicion_ticks=4)
    dparams = sd.DeltaParams(swim=params, wire_cap=1, claim_grid=8)
    delta = sd.init_delta(n, capacity=n)
    net = sim.make_net(n)
    for victim in (3, 11):
        net = net._replace(up=net.up.at[victim].set(False))
    key = jax.random.PRNGKey(2)
    for _ in range(250):
        key, sub = jax.random.split(key)
        delta, _ = _delta_step(delta, net, sub, dparams)
        dd = sd.densify(delta)
        vk = np.asarray(dd.view_key)
        live = [i for i in range(n) if i not in (3, 11)]
        if all(
            (vk[i, v] & 7) == sim.FAULTY for i in live for v in (3, 11)
        ):
            return
    pytest.fail("wire_cap=1 failed to disseminate both faults")


@pytest.mark.slow
def test_delta_run_scan_matches_steps():
    """delta_run (lax.scan) == the same ticks stepped individually."""
    n = 16
    params = sim.SwimParams(loss=0.03)
    dparams = sd.DeltaParams(swim=params, wire_cap=n, claim_grid=3 * n * n)
    net = sim.make_net(n)
    key = jax.random.PRNGKey(5)
    stepped = sd.init_delta(n, capacity=n)
    keys = jax.random.split(key, 10)
    for t in range(10):
        stepped, _ = _delta_step(stepped, net, keys[t], dparams)
    scanned, _ = sd.delta_run_impl(
        sd.init_delta(n, capacity=n), net, key, dparams, 10
    )
    # delta_run splits the key the same way: jax.random.split(key, ticks)
    np.testing.assert_array_equal(
        np.asarray(sd.densify(stepped).view_key),
        np.asarray(sd.densify(scanned).view_key),
    )


def test_sweep_probe_parity_with_dense():
    """probe='sweep' routes through the delta selection's own sweep path;
    it must stay on the dense sweep trajectory."""
    n = 16
    params = sim.SwimParams(loss=0.02, probe="sweep", suspicion_ticks=6)
    for t, dense, delta, _, _ in run_both(n, 30, params, events=[(0, "kill", 2)]):
        assert_matches_dense(delta, dense, t)


def test_delta_rejects_dense_partition_masks():
    """bool[N, N] adjacency masks stay dense-only; the delta backend
    takes the int32[N] group-id form (test_bit_identical_partition)."""
    n = 8
    params = sim.SwimParams()
    dparams = sd.DeltaParams(swim=params)
    delta = sd.init_delta(n)
    net = sim.make_net(n, partitioned=True)
    with pytest.raises(NotImplementedError):
        sd.delta_step_impl(delta, net, jax.random.PRNGKey(0), dparams)


@pytest.mark.slow
def test_bit_identical_partition_split_and_heal():
    """Group-id netsplit: split at tick 10, heal at tick 40 (mid-
    transition, suspects still cross-pingable): the full divergence /
    spontaneous-remerge cycle must stay on the dense trajectory bit for
    bit.  Peak per-viewer divergence reaches ~n/2 (the netsplit's dense
    transition), so capacity is ample here.

    Nightly lane: ~42 s (the 3n² claim grid dominates compile) while
    tier-1 pushes the ROADMAP's 870 s watchdog; netsplit parity keeps
    tier-1 representatives (`test_sided_netsplit_bounded_capacity_
    heals`, `test_bit_identical_self_bootstrap`,
    `test_bit_identical_steady_state_with_loss`)."""
    n = 24
    params = sim.SwimParams(loss=0.02, suspicion_ticks=6)
    # ample caps for a netsplit mean claim_grid = 3 * n * n: the post-heal
    # refutation storm can concentrate every sender's full wire on one
    # receiver in a single tick (measured: 4n drops claims here)
    dparams = sd.DeltaParams(swim=params, wire_cap=n, claim_grid=3 * n * n)
    dense = sim.init_state(n)
    delta = sd.init_delta(n, capacity=n)
    gid_split = (jnp.arange(n) >= n // 2).astype(jnp.int32)
    gid_heal = jnp.zeros((n,), jnp.int32)
    net = sim.make_net(n)._replace(adj=gid_heal)
    keys = jax.random.split(jax.random.PRNGKey(3), 90)
    for t in range(90):
        if t == 10:
            net = net._replace(adj=gid_split)
        if t == 40:
            net = net._replace(adj=gid_heal)
        dense, md = _dense_step(dense, net, keys[t], params)
        delta, me = _delta_step(delta, net, keys[t], dparams)
        assert_matches_dense(delta, dense, t)
        for k in METRIC_KEYS:
            assert int(md[k]) == int(me[k]), f"metric {k} tick {t}"


def test_delta_rejects_sparse_cap():
    n = 8
    dparams = sd.DeltaParams(swim=sim.SwimParams(sparse_cap=4))
    delta = sd.init_delta(n)
    net = sim.make_net(n)
    with pytest.raises(ValueError):
        sd.delta_step_impl(delta, net, jax.random.PRNGKey(0), dparams)


# ---------------------------------------------------------------------------
# SimCluster wiring (models/cluster.py backend="delta")
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_simcluster_delta_matches_dense_checksums():
    """Same seed, same scenario: the two SimCluster backends must report
    identical reference-format checksums every step of the way."""
    from ringpop_tpu.models.cluster import SimCluster

    n = 16
    params = sim.SwimParams(loss=0.02, suspicion_ticks=6)
    dense = SimCluster(n, params, seed=11)
    delta = SimCluster(
        n, params, seed=11, backend="delta", capacity=n, wire_cap=n,
        claim_grid=3 * n * n,
    )
    dense.kill(3)
    delta.kill(3)
    for _ in range(30):
        dense.tick()
        delta.tick()
        assert dense.checksums() == delta.checksums()
        assert dense.converged() == delta.converged()


@pytest.mark.slow
def test_simcluster_delta_kill_revive_cycle():
    from ringpop_tpu.models.cluster import SimCluster

    n = 24
    c = SimCluster(
        n,
        sim.SwimParams(loss=0.0, suspicion_ticks=4),
        backend="delta",
        capacity=n,
    )
    c.kill(5)
    assert c.run_until_converged(max_ticks=200) > 0
    assert c.status_counts(0)["faulty"] == 1
    c.rebase()  # fold the converged fault; views must be unchanged
    assert c.status_counts(0)["faulty"] == 1
    c.revive(5)
    assert c.run_until_converged(max_ticks=200) > 0
    assert c.status_counts(0)["faulty"] == 0
    assert len(set(c.checksums().values())) == 1


def test_simcluster_delta_scope_guards():
    from ringpop_tpu.models.cluster import SimCluster

    c = SimCluster(8, backend="delta")
    with pytest.raises(NotImplementedError):
        c.partition([[0, 1, 2], [4, 5, 6, 7]])  # partial coverage: node 3
    with pytest.raises(ValueError):
        SimCluster(8, backend="delta", damping=True)


def test_bit_identical_self_bootstrap():
    """init='self' join wave: every node admin-joins against seed 0
    (tick-cluster 'j'), then gossip discovers the rest — bit-identical
    to the dense trajectory through the whole bootstrap, and the
    converged consensus folds into the base via rebase."""
    n = 20
    params = sim.SwimParams(loss=0.02, suspicion_ticks=6)
    dparams = sd.DeltaParams(swim=params, wire_cap=n, claim_grid=3 * n * n)
    dense = sim.init_state(n, mode="self")
    delta = sd.init_delta(n, capacity=n + 4, mode="self")
    np.testing.assert_array_equal(
        np.asarray(sd.densify(delta).view_key), np.asarray(dense.view_key)
    )
    for j in range(1, n):
        dense = sim.admin_join(dense, j, 0)
        delta = sd.admin_join(delta, j, 0)
    assert_matches_dense(delta, dense, "post-join")
    net = sim.make_net(n)
    keys = jax.random.split(jax.random.PRNGKey(17), 40)
    for t in range(40):
        dense, _ = _dense_step(dense, net, keys[t], params)
        delta, _ = _delta_step(delta, net, keys[t], dparams)
        assert_matches_dense(delta, dense, t)
    vs = np.asarray(dense.view_key)
    assert (vs == vs[0]).all(), "bootstrap failed to converge"
    delta = sd.rebase(delta)
    assert_matches_dense(delta, dense, "post-rebase")
    assert int(jnp.sum(delta.d_subj < sd.SENTINEL)) == 0  # folded to base


@pytest.mark.slow
def test_simcluster_delta_self_bootstrap_checksums():
    from ringpop_tpu.models.cluster import SimCluster

    n = 12
    dense = SimCluster(n, init="self", seed=5)
    delta = SimCluster(
        n, init="self", seed=5, backend="delta", capacity=n + 4,
        wire_cap=n, claim_grid=3 * n * n,
    )
    for c in (dense, delta):
        assert not c.converged()
        for j in range(1, n):
            c.join(j, 0)
    for _ in range(40):
        dense.tick()
        delta.tick()
        assert dense.checksums() == delta.checksums()
    assert dense.converged() and delta.converged()


@pytest.mark.slow
def test_simcluster_delta_partition_matches_dense_checksums():
    """SimCluster group-id netsplit on both backends: identical
    reference-format checksums through split, heal, and remerge."""
    from ringpop_tpu.models.cluster import SimCluster

    n = 16
    params = sim.SwimParams(loss=0.0, suspicion_ticks=5)
    dense = SimCluster(n, params, seed=13)
    delta = SimCluster(
        n, params, seed=13, backend="delta", capacity=n, wire_cap=n,
        claim_grid=3 * n * n,  # netsplit-ample: see _route_claims_multi
    )
    sides = [list(range(n // 2)), list(range(n // 2, n))]
    for c in (dense, delta):
        c.tick(3)
        c.partition(sides)
        c.tick(8)  # mid-transition: suspects exist, faulty not universal
        c.heal_partition()
    for _ in range(60):
        dense.tick()
        delta.tick()
        assert dense.checksums() == delta.checksums()
    assert dense.converged() and delta.converged()


@pytest.mark.slow
def test_simcluster_delta_device_checksums_match_host():
    from ringpop_tpu.models.cluster import SimCluster

    c = SimCluster(12, sim.SwimParams(loss=0.05), backend="delta", capacity=12)
    c.tick(10)
    assert c.checksums(backend="device") == c.checksums(backend="host")


def test_sparsify_densify_roundtrip():
    n = 12
    params = sim.SwimParams(loss=0.1)
    dense = sim.init_state(n)
    net = sim.make_net(n)
    key = jax.random.PRNGKey(9)
    for _ in range(15):
        key, sub = jax.random.split(key)
        dense, _ = _dense_step(dense, net, sub, params)
    base = jnp.full((n,), sim.ALIVE, jnp.int32)
    delta = sd.sparsify(dense, base, capacity=n)
    dd = sd.densify(delta)
    np.testing.assert_array_equal(np.asarray(dd.view_key), np.asarray(dense.view_key))
    np.testing.assert_array_equal(np.asarray(dd.pb), np.asarray(dense.pb))
    np.testing.assert_array_equal(
        np.asarray(dd.suspect_left), np.asarray(dense.suspect_left)
    )


@pytest.mark.slow
def test_upto_prefixes_compile_and_full_matches_default():
    """The profiling ``upto`` knob: every prefix executes, and the
    explicit full value (7) is the default step bit for bit."""
    n = 64
    params = sd.DeltaParams(swim=sim.SwimParams(loss=0.05), wire_cap=8, claim_grid=16)
    state = sd.init_delta(n, capacity=32)
    net = sim.make_net(n)
    key = jax.random.PRNGKey(11)
    step = jax.jit(sd.delta_step_impl, static_argnames=("params", "upto"))
    ref, _ = step(state, net, key, params)
    full, _ = step(state, net, key, params, upto=7)
    for a, b in zip(jax.tree_util.tree_leaves(ref), jax.tree_util.tree_leaves(full)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for u in range(7):
        st, m = step(state, net, key, params, upto=u)
        jax.block_until_ready(st.d_subj)


@pytest.mark.parametrize(
    "method",
    [
        pytest.param("sort", marks=pytest.mark.slow),
        "scan_unrolled",  # the default lowering stays in the default run
        pytest.param("pallas", marks=pytest.mark.slow),
    ],
)
def test_wide_lowerings_bit_identical(method, monkeypatch):
    """Every wide-query searchsorted lowering (_WIDE_METHOD) traces the
    same trajectory: the non-default choices stay tested fallbacks for
    hardware where the default regresses.  _WIDE_METHOD is read at
    trace time, so the module-level jitted steps must be retraced for
    the monkeypatch to reach them at all."""
    monkeypatch.setattr(sd, "_WIDE_METHOD", method)
    jax.clear_caches()
    params = sim.SwimParams(loss=0.05, suspicion_ticks=10)
    for t, dense, delta, _, _ in run_both(
        24, 25, params, events=[(0, "kill", 5)]
    ):
        assert_matches_dense(delta, dense, t)


@pytest.mark.slow
def test_long_horizon_occupancy_stays_bounded():
    """200 lossy ticks with a kill and a revive: divergence tables must
    not leak — after dissemination budgets expire and compact() runs,
    occupancy returns to the true-divergence floor and stays there."""
    n = 48
    params = sd.DeltaParams(
        swim=sim.SwimParams(loss=0.02, suspicion_ticks=8),
        wire_cap=8,
        claim_grid=16,
    )
    state = sd.init_delta(n, capacity=24)
    net = sim.make_net(n)
    key = jax.random.PRNGKey(21)
    occ_checkpoints = []
    for t in range(200):
        if t == 30:
            net = net._replace(up=net.up.at[7].set(False))
        if t == 120:
            inc = int(
                max(int(jnp.max(state.base_key)), int(jnp.max(state.d_key))) >> 3
            ) + 10
            state = sd.revive_and_join(state, 7, inc, seed=1)
            net = net._replace(up=net.up.at[7].set(True))
        key, sub = jax.random.split(key)
        state, m = _delta_step(state, net, sub, params)
        if t % 50 == 49:
            state = sd.compact(state)
            occ_checkpoints.append(int(jnp.max(jnp.sum(
                (state.d_subj < sd.SENTINEL).astype(jnp.int32), axis=1
            ))))
    assert int(m["overflow_drops"]) == 0
    # post-compact occupancy must not trend upward: only true divergence
    # from base survives a compact, so a leak shows as growth across
    # checkpoints; the kill+revive leaves at most a handful of
    # genuinely divergent subjects
    assert occ_checkpoints[-1] <= 8, occ_checkpoints
    assert occ_checkpoints[-1] <= occ_checkpoints[0] + 4, occ_checkpoints


# ---------------------------------------------------------------------------
# sided mode (make_sides / per-side rebase / fold_to_single)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_sided_trivial_matches_unsided():
    """All viewers on side 0 (G=1 + merge row): every trajectory must be
    bit-identical to the unsided single-base state — the sided machinery
    may not perturb the default path."""
    n = 24
    params = sim.SwimParams(loss=0.05, suspicion_ticks=8)
    dparams = sd.DeltaParams(swim=params, wire_cap=n, claim_grid=3 * n * n)
    a = sd.init_delta(n, capacity=n)
    b = sd.make_sides(sd.init_delta(n, capacity=n), np.zeros(n, np.int32))
    net = sim.make_net(n)._replace(up=jnp.ones(n, bool).at[3].set(False))
    keys = jax.random.split(jax.random.PRNGKey(0), 30)
    for t in range(30):
        a, _ = _delta_step(a, net, keys[t], dparams)
        b, _ = _delta_step(b, net, keys[t], dparams)
        da, db = sd.densify(a), sd.densify(b)
        np.testing.assert_array_equal(
            np.asarray(da.view_key), np.asarray(db.view_key), err_msg=str(t)
        )
        np.testing.assert_array_equal(
            np.asarray(da.pb), np.asarray(db.pb), err_msg=f"pb {t}"
        )


def test_sided_netsplit_bounded_capacity_heals():
    """The structured netsplit: sides split at capacity n/4 (far below
    the ~n/2 the unsided transition needs), each side's consensus folds
    into its base row via anti-entropy rebases, the mid-transition heal
    remerges to one view, and fold_to_single returns to a single base."""
    n = 64
    cap = 16
    params = sim.SwimParams(loss=0.0, suspicion_ticks=6)
    dparams = sd.DeltaParams(swim=params, wire_cap=8, claim_grid=64)
    st = sd.make_sides(
        sd.init_delta(n, capacity=cap), (np.arange(n) >= n // 2).astype(np.int32)
    )
    gid = (jnp.arange(n) >= n // 2).astype(jnp.int32)
    net = sim.make_net(n)._replace(adj=gid)
    key = jax.random.PRNGKey(1)
    for t in range(8):  # split; heal mid-transition
        key, sub = jax.random.split(key)
        st, _ = _delta_step(st, net, sub, dparams)
        if t % 4 == 3:
            st = sd.rebase(st, anti_entropy=True)
    net = net._replace(adj=jnp.zeros((n,), jnp.int32))
    conv = None
    for t in range(300):
        key, sub = jax.random.split(key)
        st, m = _delta_step(st, net, sub, dparams)
        if t % 10 == 9:
            st = sd.rebase(st, anti_entropy=True)
        if t > 3 and bool(sd._converged_impl(st, net.up, net.responsive)):
            # converged views may still agree on in-flight suspects;
            # the fixed point is all-alive once they refute/expire
            row0 = np.asarray(sd.materialize_rows(st, jnp.asarray([0])))[0]
            if set((row0 & 7).tolist()) == {sim.ALIVE}:
                conv = t
                break
    assert conv is not None, "sided heal failed to reach the all-alive fixed point"
    st = sd.rebase(st, anti_entropy=True)
    st = sd.fold_to_single(st)
    assert st.side is None
    # single base now carries the converged all-alive consensus
    assert set((np.asarray(st.base_key) & 7).tolist()) == {sim.ALIVE}


@pytest.mark.slow
def test_sided_split_consensus_folds_to_side_bases():
    """During the split each side converges on other-side-faulty INSIDE
    its base row with bounded tables (the whole point of sided mode)."""
    n = 32
    params = sim.SwimParams(loss=0.0, suspicion_ticks=5)
    dparams = sd.DeltaParams(swim=params, wire_cap=8, claim_grid=64)
    st = sd.make_sides(
        sd.init_delta(n, capacity=16), (np.arange(n) >= n // 2).astype(np.int32)
    )
    gid = (jnp.arange(n) >= n // 2).astype(jnp.int32)
    net = sim.make_net(n)._replace(adj=gid)
    key = jax.random.PRNGKey(1)
    for t in range(60):
        key, sub = jax.random.split(key)
        st, m = _delta_step(st, net, sub, dparams)
        if t % 10 == 9:
            st = sd.rebase(st, anti_entropy=True)
    base = np.asarray(st.base_key)
    assert set((base[0][n // 2:] & 7).tolist()) == {sim.FAULTY}
    assert set((base[1][: n // 2] & 7).tolist()) == {sim.FAULTY}
    assert set((base[0][: n // 2] & 7).tolist()) == {sim.ALIVE}
    # occupancy drained back to ~0 by the folds
    assert int(jnp.max(jnp.sum((st.d_subj < sd.SENTINEL).astype(jnp.int32), axis=1))) <= 4


@pytest.mark.slow
def test_simcluster_sided_scenario():
    from ringpop_tpu.models.cluster import SimCluster

    n = 32
    c = SimCluster(
        n, sim.SwimParams(loss=0.0, suspicion_ticks=5), seed=2,
        backend="delta", capacity=16, wire_cap=8, claim_grid=64,
    )
    c.split_sides([list(range(n // 2)), list(range(n // 2, n))])
    for _ in range(2):
        c.tick(4)
        c.rebase(anti_entropy=True)
    c.heal_partition()
    for t in range(60):
        c.tick()
        if t % 10 == 9:
            c.rebase(anti_entropy=True)
        if c.converged():
            break
    assert c.converged()
    c.rebase(anti_entropy=True)
    c.fold_sides()
    assert c.state.side is None
    assert len(set(c.checksums().values())) == 1


def _assert_carried_fresh(st, where):
    got = np.asarray(st.digest)
    want = np.asarray(sd.compute_digest(st))
    assert (got == want).all(), f"digest drift at {where}"
    if st.d_bpmask is not None:
        bpm, bpr = sd.compute_slot_base(st)
        got_bpm = bitpack.unpack_bits(st.d_bpmask, st.capacity)
        assert (np.asarray(got_bpm) == np.asarray(bpm)).all(), where
        assert (np.asarray(st.d_bprank) == np.asarray(bpr)).all(), where


def test_rolling_digest_invariant_unsided():
    """The carried digest (DeltaState.digest) must equal the
    compute_digest oracle after every mutation path: merges with
    insertions at a tiny capacity (drops), self refutations, phase-6
    expiry, the exchange, and the admin ops.  tools/smoke_digest.py is
    the longer soak; this is the suite pin."""
    n = 32
    params = sd.DeltaParams(
        swim=sim.SwimParams(loss=0.05, suspicion_ticks=4),
        wire_cap=4,
        claim_grid=16,
    )
    st = sd.init_delta(n, capacity=8)
    _assert_carried_fresh(st, "init")
    net = sim.make_net(n)._replace()
    net = net._replace(up=net.up.at[5].set(False))
    key = jax.random.PRNGKey(3)
    for t in range(16):
        key, sub = jax.random.split(key)
        st, _ = sd.delta_step(st, net, sub, params)
        _assert_carried_fresh(st, f"tick {t}")
    st = sd.revive_and_join(st, 5, inc=9, seed=2)
    _assert_carried_fresh(st, "revive_and_join")
    st = sd.rebase(st)
    _assert_carried_fresh(st, "rebase")


def test_rolling_digest_invariant_sided_flips():
    """Sided netsplit: flips + anti-entropy folds + heal exercise the
    wholesale in-step recompute (_refresh_in_step) and the host
    refreshes; the invariant must hold under both carry configurations
    of the slot-base snapshots (the state's, not the env's)."""
    n = 32
    params = sd.DeltaParams(
        swim=sim.SwimParams(loss=0.0, suspicion_ticks=4),
        wire_cap=8,
        claim_grid=32,
    )
    st = sd.init_delta(n, capacity=16)
    # force the slot-base carry on regardless of env: the step must key
    # the in-cond refresh on the state (review round-5 finding)
    bpm, bpr = sd.compute_slot_base(st)
    st = st._replace(d_bpmask=bitpack.pack_bits(bpm), d_bprank=bpr)
    net = sim.make_net(n)
    key = jax.random.PRNGKey(5)
    gid = (np.arange(n) >= n // 2).astype(np.int32)
    st = sd.make_sides(st, gid)
    assert st.d_bpmask is not None  # refresh_carried preserves the carry
    net = net._replace(adj=jnp.asarray(gid))
    for t in range(8):
        key, sub = jax.random.split(key)
        st, _ = sd.delta_step(st, net, sub, params)
        _assert_carried_fresh(st, f"split tick {t}")
    st = sd.rebase(st, anti_entropy=True)
    net = net._replace(adj=jnp.zeros((n,), jnp.int32))
    for t in range(12):
        key, sub = jax.random.split(key)
        st, _ = sd.delta_step(st, net, sub, params)
        _assert_carried_fresh(st, f"heal tick {t}")


# -- r06: insert-merge lowering grid + packed-plane pins ---------------------


def _delta_trajectory(method, monkeypatch, n=24, ticks=12):
    monkeypatch.setattr(sd, "_MERGE_METHOD", method)
    jax.clear_caches()
    params = sd.DeltaParams(
        swim=sim.SwimParams(loss=0.05, suspicion_ticks=10),
        wire_cap=8,
        claim_grid=16,
    )
    st = sd.init_delta(n, capacity=24)
    net = sim.make_net(n)._replace(up=jnp.ones(n, bool).at[5].set(False))
    key = jax.random.PRNGKey(3)
    out = []
    for _ in range(ticks):
        key, sub = jax.random.split(key)
        st, _ = sd.delta_step(st, net, sub, params)
        out.append(jax.tree_util.tree_map(np.asarray, st))
    return out


def test_merge_lowerings_bit_identical(monkeypatch):
    """RINGPOP_DELTA_MERGE="pallas" (the fused VMEM insert-merge,
    ops/delta_merge_pallas.py in interpret mode off-TPU) must trace the
    exact trajectory of the default searchsorted+gather lowering —
    every state leaf, every tick, under loss and a kill."""
    ref = _delta_trajectory("sorted", monkeypatch)
    got = _delta_trajectory("pallas", monkeypatch)
    for t, (a, b) in enumerate(zip(ref, got)):
        for la, lb in zip(
            jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
        ):
            np.testing.assert_array_equal(la, lb, err_msg=f"tick {t}")


def test_merge_pallas_streamed_bit_identical(monkeypatch):
    """The merge-method grid crossed with the streamed runner: a whole
    ``run_scenario`` under the sorted lowering == the same scenario
    streamed in segments under the pallas lowering (final checksums +
    trace)."""
    from ringpop_tpu.models.cluster import SimCluster

    n, ticks = 8, 8
    spec = {"ticks": ticks, "events": [{"at": 2, "op": "kill", "node": 7}]}

    def run(method, segment_ticks=None):
        monkeypatch.setattr(sd, "_MERGE_METHOD", method)
        jax.clear_caches()
        c = SimCluster(
            n, sim.SwimParams(suspicion_ticks=5), seed=3, backend="delta",
            capacity=n, wire_cap=n, claim_grid=2 * n,
        )
        kw = {} if segment_ticks is None else {"segment_ticks": segment_ticks}
        trace = c.run_scenario(spec, **kw)
        return c, trace

    a, ta = run("sorted")
    b, tb = run("pallas", segment_ticks=4)
    assert a.checksums() == b.checksums()
    np.testing.assert_array_equal(ta.converged, tb.converged)
    np.testing.assert_array_equal(ta.live, tb.live)
    for k in ta.metrics:
        np.testing.assert_array_equal(ta.metrics[k], tb.metrics[k], err_msg=k)
    for la, lb in zip(
        jax.tree_util.tree_leaves(a.state), jax.tree_util.tree_leaves(b.state)
    ):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_packed_scan_carry_matches_stepwise():
    """The bit-packed lattice planes ride delta_run's lax.scan carry:
    the scanned trajectory must equal the per-tick host loop from the
    same key split (the packed-vs-unpacked at-rest representation can
    not diverge through the scan boundary), and the packed base plane
    must stay a lossless encoding of the bool oracle."""
    n, ticks = 32, 8
    params = sd.DeltaParams(
        swim=sim.SwimParams(loss=0.05, suspicion_ticks=6),
        wire_cap=8,
        claim_grid=16,
    )
    st0 = sd.init_delta(n, capacity=16)
    net = sim.make_net(n)._replace(up=jnp.ones(n, bool).at[3].set(False))
    key = jax.random.PRNGKey(11)

    scanned, _ = sd.delta_run(st0, net, key, params, ticks)

    # delta_run donates its state argument — rebuild the (deterministic)
    # initial state for the host loop
    st = sd.init_delta(n, capacity=16)
    for sub in jax.random.split(key, ticks):
        st, _ = _delta_step(st, net, sub, params)
    for la, lb in zip(
        jax.tree_util.tree_leaves(scanned), jax.tree_util.tree_leaves(st)
    ):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))

    # packed plane == bool oracle, and the packed word count is the pin
    assert scanned.bp_mask.dtype == jnp.uint32
    assert scanned.bp_mask.shape == (bitpack.packed_width(n),)
    status = np.asarray(scanned.base_key) & 7
    want = (status == sd.ALIVE) | (status == sd.SUSPECT)
    got = np.asarray(bitpack.unpack_bits(scanned.bp_mask, n))
    np.testing.assert_array_equal(got, want)

"""Device-side checksum path vs the C/host kernel: bit parity.

The device path (ops/checksum_device.py) assembles the reference
checksum string (membership.js:70-93 format) and farmhash32's it without
leaving the device; the host path is the threaded C kernel
(models/checksum.py -> ops/_farmhash.c).  Both must agree byte-for-byte
and hash-for-hash on every view composition.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ringpop_tpu.models import checksum as cksum
from ringpop_tpu.models import swim_sim as sim
from ringpop_tpu.ops import checksum_device as ckdev

BASE = 1_400_000_000_000


def host_sums(addresses, view_key, base_inc, rows):
    book = cksum.AddressBook(addresses)
    keys = np.asarray(view_key[np.asarray(rows)])
    return cksum.view_checksums_packed(book, keys, base_inc)


def test_device_checksum_matches_c_kernel_converged():
    n = 33
    addresses = cksum.default_addresses(n)
    inc = jnp.arange(n, dtype=jnp.int32) * 17 + 3
    state = sim.init_state(n, inc)
    book = ckdev.DeviceBook(addresses, BASE)
    dev = np.asarray(ckdev.view_checksums_device(book, state.view_key))
    host = host_sums(addresses, state.view_key, BASE, list(range(n)))
    np.testing.assert_array_equal(dev, np.asarray(host, dtype=np.uint32))
    # converged views agree with each other too
    assert len(set(dev.tolist())) == 1


def test_device_checksum_mixed_statuses_and_absent_members():
    n = 12
    addresses = cksum.default_addresses(n)
    state = sim.init_state(n, mode="self")
    for j in range(1, 9):
        state = sim.admin_join(state, j, 0)
    # sprinkle every status + carry boundary incarnations
    vk = state.view_key
    vk = vk.at[0, 3].set(134_000_000 * 8 + sim.SUSPECT)  # near INC_MAX
    vk = vk.at[0, 4].set(5 * 8 + sim.FAULTY)
    vk = vk.at[0, 5].set(123_456 * 8 + sim.LEAVE)
    state = state._replace(view_key=vk)
    book = ckdev.DeviceBook(addresses, BASE)
    rows = list(range(n))
    dev = np.asarray(ckdev.view_checksums_device(book, state.view_key))
    host = host_sums(addresses, state.view_key, BASE, rows)
    np.testing.assert_array_equal(dev, np.asarray(host, dtype=np.uint32))


def test_device_checksum_small_base_inc():
    # base_inc < 1e9: the hi limb is zero and widths go fully dynamic
    n = 7
    addresses = cksum.default_addresses(n)
    inc = jnp.asarray([0, 1, 9, 99, 12345, 10**6, 5], dtype=jnp.int32)
    state = sim.init_state(n, inc)
    book = ckdev.DeviceBook(addresses, base_inc=7)
    dev = np.asarray(ckdev.view_checksums_device(book, state.view_key))
    host = host_sums(addresses, state.view_key, 7, list(range(n)))
    np.testing.assert_array_equal(dev, np.asarray(host, dtype=np.uint32))


def test_device_checksum_carry_across_1e9():
    # base_lo + inc crosses 1e9: the carry must propagate into hi
    n = 4
    addresses = cksum.default_addresses(n)
    base = 1_999_999_999_000  # lo = 999_999_999_000 % 1e9 = 999_999_000
    inc = jnp.asarray([0, 999, 1000, 2000], dtype=jnp.int32)
    state = sim.init_state(n, inc)
    book = ckdev.DeviceBook(addresses, base)
    dev = np.asarray(ckdev.view_checksums_device(book, state.view_key))
    host = host_sums(addresses, state.view_key, base, list(range(n)))
    np.testing.assert_array_equal(dev, np.asarray(host, dtype=np.uint32))


def test_device_row_string_exact_bytes():
    """The assembled string itself (not just its hash) matches the
    reference format."""
    addresses = ["b:2", "a:1", "c:3"]
    state = sim.init_state(3, jnp.asarray([5, 6, 7], dtype=jnp.int32))
    book = ckdev.DeviceBook(addresses, base_inc=100)
    bufs, lens = ckdev.row_strings(book, state.view_key)
    got = bytes(np.asarray(bufs[0][: int(lens[0])]))
    assert got == b"a:1alive106;b:2alive105;c:3alive107"


def test_simcluster_device_backend_matches_host():
    from ringpop_tpu.models.cluster import SimCluster

    simc = SimCluster(16, sim.SwimParams(loss=0.0), seed=3)
    simc.kill(5)
    simc.tick(40)
    host = simc.checksums()
    dev = simc.checksums(backend="device")
    assert dev == host and len(dev) == 15

"""Forwarding tests (reference: test/integration/proxy-test.js, 1058 LoC —
handleOrProxy/All, retries, checksum gates, reroutes)."""

import json

import pytest

from ringpop_tpu.harness import Cluster
from ringpop_tpu import errors
from ringpop_tpu.request_proxy.http import ProxyRequest, ProxyResponse


def converged_cluster(size=3, **kw):
    c = Cluster(size=size, **kw)
    c.bootstrap_all(run=False)
    assert c.run_until_converged(60000)
    return c


def key_owned_by(cluster, node):
    """Find a key that hashes to `node`."""
    for i in range(10000):
        key = f"key-{i}"
        if node.lookup(key) == node.whoami():
            return key
    raise AssertionError("no key found")


def key_not_owned_by(cluster, node):
    for i in range(10000):
        key = f"key-{i}"
        if node.lookup(key) != node.whoami():
            return key
    raise AssertionError("no key found")


def test_handle_or_proxy_local():
    c = converged_cluster()
    node = c.nodes[0]
    key = key_owned_by(c, node)
    req = ProxyRequest(url="/x", method="GET")
    res = ProxyResponse()
    assert node.handle_or_proxy(key, req, res) is True
    c.destroy_all()


def test_handle_or_proxy_remote_roundtrip():
    c = converged_cluster()
    node = c.nodes[0]
    key = key_not_owned_by(c, node)
    dest = node.lookup(key)
    dest_node = next(n for n in c.nodes if n.whoami() == dest)

    # Owner handles the forwarded request.
    def on_request(req, res, head):
        assert head["ringpopKeys"] == [key]
        assert req.url == "/resource"
        assert req.method == "POST"
        assert req.body == "hello"
        res.set_header("x-handled-by", dest)
        res.status_code = 201
        res.end("created")

    dest_node.on("request", on_request)

    req = ProxyRequest(url="/resource", method="POST", body="hello")
    done = []
    res = ProxyResponse(lambda err, resp: done.append((err, resp)))
    assert node.handle_or_proxy(key, req, res) is None
    c.run(1000)

    assert done, "no response"
    err, resp = done[0]
    assert err is None
    assert resp.status_code == 201
    assert resp.body == "hello"[:0] + "created"
    assert resp.headers["x-handled-by"] == dest
    c.destroy_all()


def test_checksum_mismatch_refused_and_allowed():
    """Receiver rejects when ringpopChecksum != ring checksum, unless
    enforceConsistency off (request-proxy/index.js:172-187)."""
    c = converged_cluster()
    sender, receiver = c.nodes[0], c.nodes[1]

    head = {
        "url": "/x",
        "headers": {},
        "method": "GET",
        "httpVersion": "1.1",
        "ringpopChecksum": 12345,  # wrong on purpose
        "ringpopKeys": ["k"],
    }
    out = []
    receiver.request_proxy.handle_request(head, b"", lambda err, *r: out.append(err))
    assert getattr(out[0], "type", None) == "ringpop.request-proxy.invalid-checksum"

    receiver.request_proxy.enforce_consistency = False
    got = []
    receiver.on("request", lambda req, res, h: (res.end("ok"), got.append(1)))
    out2 = []
    receiver.request_proxy.handle_request(head, b"", lambda err, *r: out2.append(err))
    assert out2[0] is None and got
    c.destroy_all()


def test_retry_reroutes_to_new_owner():
    """Dest dies; retry re-looks-up and reroutes (send.js:105-226)."""
    c = converged_cluster(3)
    node = c.nodes[0]
    key = key_not_owned_by(c, node)
    dest = node.lookup(key)
    dest_index = c.host_ports.index(dest)

    # Handler on every node; track who served it.
    served = []
    for n in c.nodes:
        n.on(
            "request",
            lambda req, res, head, who=n.whoami(): (served.append(who), res.end("ok")),
        )

    c.kill(dest_index)
    # Let failure detection declare the owner faulty so the ring updates.
    c.run(30000)

    done = []
    res = ProxyResponse(lambda err, resp: done.append((err, resp)))
    req = ProxyRequest(url="/y")
    ret = node.handle_or_proxy(key, req, res)

    if ret is True:
        # After ring shrink the key may now be local; that's a valid path:
        # caller handles it.
        return

    c.run(60000)  # cover the retry schedule [0, 1, 3.5]s
    assert done, "no response"
    err, resp = done[0]
    assert err is None
    assert resp.body == "ok"
    assert served and served[0] != dest
    c.destroy_all()


def test_max_retries_exceeded():
    c = converged_cluster(3, latency_ms=1.0)
    node = c.nodes[0]
    key = key_not_owned_by(c, node)
    dest = node.lookup(key)
    dest_index = c.host_ports.index(dest)
    # Kill the owner but DON'T let the ring recover: stop gossip everywhere
    # so the ring keeps pointing at the dead node.
    for n in c.nodes:
        n.gossip.stop()
    c.kill(dest_index)

    done = []
    res = ProxyResponse(lambda err, resp: done.append((err, resp)))
    node.proxy_req(
        {"keys": [key], "dest": dest, "req": ProxyRequest(url="/z"), "res": res,
         "maxRetries": 2, "retrySchedule": [0, 0.01]}
    )
    c.run(60000)
    assert done
    err, resp = done[0]
    assert err is None  # errors surface via res.status_code 500
    assert resp.status_code == 500
    c.destroy_all()


def test_no_retries_mode():
    """maxRetries 0: one shot, error surfaces immediately (send.js:264-283)."""
    c = converged_cluster(3)
    node = c.nodes[0]
    key = key_not_owned_by(c, node)
    dest = node.lookup(key)
    for n in c.nodes:
        n.gossip.stop()
    c.kill(c.host_ports.index(dest))

    done = []
    res = ProxyResponse(lambda err, resp: done.append((err, resp)))
    node.proxy_req(
        {"keys": [key], "dest": dest, "req": ProxyRequest(), "res": res, "maxRetries": 0}
    )
    c.run(10000)
    assert done and done[0][1].status_code == 500
    c.destroy_all()


def test_handle_or_proxy_all_groups_by_dest():
    c = converged_cluster(3)
    node = c.nodes[0]
    keys = [f"key-{i}" for i in range(20)]
    for n in c.nodes:
        n.on("request", lambda req, res, head: res.end(json.dumps(head["ringpopKeys"])))

    done = []
    node.handle_or_proxy_all({"keys": keys, "req": ProxyRequest(url="/all")},
                             lambda err, responses: done.append((err, responses)))
    c.run(5000)
    assert done
    err, responses = done[0]
    assert err is None
    all_keys = []
    for r in responses:
        all_keys.extend(r["keys"])
        assert r["dest"] == node.lookup(r["keys"][0])
    assert sorted(all_keys) == sorted(keys)
    c.destroy_all()


def test_proxy_req_validates_props():
    c = converged_cluster(1)
    with pytest.raises(errors.PropertyRequiredError):
        c.nodes[0].proxy_req({"keys": ["k"], "dest": "x"})
    with pytest.raises(errors.OptionsRequiredError):
        c.nodes[0].proxy_req(None)
    c.destroy_all()

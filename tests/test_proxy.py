"""Forwarding tests (reference: test/integration/proxy-test.js, 1058 LoC —
handleOrProxy/All, retries, checksum gates, reroutes)."""

import json

import pytest

from ringpop_tpu.harness import Cluster
from ringpop_tpu import errors
from ringpop_tpu.request_proxy.http import ProxyRequest, ProxyResponse


def converged_cluster(size=3, **kw):
    c = Cluster(size=size, **kw)
    c.bootstrap_all(run=False)
    assert c.run_until_converged(60000)
    return c


def key_owned_by(cluster, node):
    """Find a key that hashes to `node`."""
    for i in range(10000):
        key = f"key-{i}"
        if node.lookup(key) == node.whoami():
            return key
    raise AssertionError("no key found")


def key_not_owned_by(cluster, node):
    for i in range(10000):
        key = f"key-{i}"
        if node.lookup(key) != node.whoami():
            return key
    raise AssertionError("no key found")


def test_handle_or_proxy_local():
    c = converged_cluster()
    node = c.nodes[0]
    key = key_owned_by(c, node)
    req = ProxyRequest(url="/x", method="GET")
    res = ProxyResponse()
    assert node.handle_or_proxy(key, req, res) is True
    c.destroy_all()


def test_handle_or_proxy_remote_roundtrip():
    c = converged_cluster()
    node = c.nodes[0]
    key = key_not_owned_by(c, node)
    dest = node.lookup(key)
    dest_node = next(n for n in c.nodes if n.whoami() == dest)

    # Owner handles the forwarded request.
    def on_request(req, res, head):
        assert head["ringpopKeys"] == [key]
        assert req.url == "/resource"
        assert req.method == "POST"
        assert req.body == "hello"
        res.set_header("x-handled-by", dest)
        res.status_code = 201
        res.end("created")

    dest_node.on("request", on_request)

    req = ProxyRequest(url="/resource", method="POST", body="hello")
    done = []
    res = ProxyResponse(lambda err, resp: done.append((err, resp)))
    assert node.handle_or_proxy(key, req, res) is None
    c.run(1000)

    assert done, "no response"
    err, resp = done[0]
    assert err is None
    assert resp.status_code == 201
    assert resp.body == "hello"[:0] + "created"
    assert resp.headers["x-handled-by"] == dest
    c.destroy_all()


def test_checksum_mismatch_refused_and_allowed():
    """Receiver rejects when ringpopChecksum != ring checksum, unless
    enforceConsistency off (request-proxy/index.js:172-187)."""
    c = converged_cluster()
    sender, receiver = c.nodes[0], c.nodes[1]

    head = {
        "url": "/x",
        "headers": {},
        "method": "GET",
        "httpVersion": "1.1",
        "ringpopChecksum": 12345,  # wrong on purpose
        "ringpopKeys": ["k"],
    }
    out = []
    receiver.request_proxy.handle_request(head, b"", lambda err, *r: out.append(err))
    assert getattr(out[0], "type", None) == "ringpop.request-proxy.invalid-checksum"

    receiver.request_proxy.enforce_consistency = False
    got = []
    receiver.on("request", lambda req, res, h: (res.end("ok"), got.append(1)))
    out2 = []
    receiver.request_proxy.handle_request(head, b"", lambda err, *r: out2.append(err))
    assert out2[0] is None and got
    c.destroy_all()


def test_retry_reroutes_to_new_owner():
    """Dest dies; retry re-looks-up and reroutes (send.js:105-226)."""
    c = converged_cluster(3)
    node = c.nodes[0]
    key = key_not_owned_by(c, node)
    dest = node.lookup(key)
    dest_index = c.host_ports.index(dest)

    # Handler on every node; track who served it.
    served = []
    for n in c.nodes:
        n.on(
            "request",
            lambda req, res, head, who=n.whoami(): (served.append(who), res.end("ok")),
        )

    c.kill(dest_index)
    # Let failure detection declare the owner faulty so the ring updates.
    c.run(30000)

    done = []
    res = ProxyResponse(lambda err, resp: done.append((err, resp)))
    req = ProxyRequest(url="/y")
    ret = node.handle_or_proxy(key, req, res)

    if ret is True:
        # After ring shrink the key may now be local; that's a valid path:
        # caller handles it.
        return

    c.run(60000)  # cover the retry schedule [0, 1, 3.5]s
    assert done, "no response"
    err, resp = done[0]
    assert err is None
    assert resp.body == "ok"
    assert served and served[0] != dest
    c.destroy_all()


def test_max_retries_exceeded():
    c = converged_cluster(3, latency_ms=1.0)
    node = c.nodes[0]
    key = key_not_owned_by(c, node)
    dest = node.lookup(key)
    dest_index = c.host_ports.index(dest)
    # Kill the owner but DON'T let the ring recover: stop gossip everywhere
    # so the ring keeps pointing at the dead node.
    for n in c.nodes:
        n.gossip.stop()
    c.kill(dest_index)

    done = []
    res = ProxyResponse(lambda err, resp: done.append((err, resp)))
    node.proxy_req(
        {"keys": [key], "dest": dest, "req": ProxyRequest(url="/z"), "res": res,
         "maxRetries": 2, "retrySchedule": [0, 0.01]}
    )
    c.run(60000)
    assert done
    err, resp = done[0]
    assert err is None  # errors surface via res.status_code 500
    assert resp.status_code == 500
    c.destroy_all()


def test_no_retries_mode():
    """maxRetries 0: one shot, error surfaces immediately (send.js:264-283)."""
    c = converged_cluster(3)
    node = c.nodes[0]
    key = key_not_owned_by(c, node)
    dest = node.lookup(key)
    for n in c.nodes:
        n.gossip.stop()
    c.kill(c.host_ports.index(dest))

    done = []
    res = ProxyResponse(lambda err, resp: done.append((err, resp)))
    node.proxy_req(
        {"keys": [key], "dest": dest, "req": ProxyRequest(), "res": res, "maxRetries": 0}
    )
    c.run(10000)
    assert done and done[0][1].status_code == 500
    c.destroy_all()


def test_handle_or_proxy_all_groups_by_dest():
    c = converged_cluster(3)
    node = c.nodes[0]
    keys = [f"key-{i}" for i in range(20)]
    for n in c.nodes:
        n.on("request", lambda req, res, head: res.end(json.dumps(head["ringpopKeys"])))

    done = []
    node.handle_or_proxy_all({"keys": keys, "req": ProxyRequest(url="/all")},
                             lambda err, responses: done.append((err, responses)))
    c.run(5000)
    assert done
    err, responses = done[0]
    assert err is None
    all_keys = []
    for r in responses:
        all_keys.extend(r["keys"])
        assert r["dest"] == node.lookup(r["keys"][0])
    assert sorted(all_keys) == sorted(keys)
    c.destroy_all()


def test_proxy_req_validates_props():
    c = converged_cluster(1)
    with pytest.raises(errors.PropertyRequiredError):
        c.nodes[0].proxy_req({"keys": ["k"], "dest": "x"})
    with pytest.raises(errors.OptionsRequiredError):
        c.nodes[0].proxy_req(None)
    c.destroy_all()


def two_keys_that_diverge(cluster, owner):
    """Two keys owned by `owner` now that split to different survivors
    once the owner leaves the ring (computed on a scratch ring)."""
    from ringpop_tpu.hashring import HashRing

    scratch = HashRing()
    scratch.add_remove_servers(
        [n.whoami() for n in cluster.nodes if n is not owner], []
    )
    by_new_owner = {}
    for i in range(20000):
        key = f"div-{i}"
        if owner.lookup(key) != owner.whoami():
            continue
        new_owner = scratch.lookup(key)
        if new_owner not in by_new_owner:
            by_new_owner[new_owner] = key
        if len(by_new_owner) >= 2:
            return list(by_new_owner.values())[:2]
    raise AssertionError("no diverging key pair found")


def test_key_divergence_aborts_retry():
    """A multi-key proxied request whose keys re-resolve to more than one
    destination on retry aborts with KeysDivergedError
    (send.js:90-103; reference proxy-test.js 'aborts retry on key
    divergence')."""
    c = converged_cluster(3)
    sender = c.nodes[0]
    owner = c.nodes[1]
    k1, k2 = two_keys_that_diverge(c, owner)
    assert sender.lookup(k1) == owner.whoami() == sender.lookup(k2)

    events = []
    sender.on("requestProxy.retryAborted", lambda *a: events.append("aborted"))
    done = []
    req = ProxyRequest(url="/multi", method="POST", body="payload")
    res = ProxyResponse(lambda err, resp: done.append((err, resp)))
    # Owner dies first; the ring still routes both keys to it, so the
    # send times out.  The (single) retry fires only after the cluster
    # has declared the owner faulty — by then the two keys re-resolve to
    # two different survivors and the retry must abort.
    c.kill(1)
    sender.proxy_req(
        {
            "keys": [k1, k2],
            "dest": owner.whoami(),
            "req": req,
            "res": res,
            "timeout": 500,
            "retrySchedule": [30.0],
        }
    )
    c.run(120000)
    assert c.run_until_converged(60000)
    c.run(5000)

    assert done, "proxy response never fired"
    # Proxy errors surface as a 500 response to the app caller
    # (request-proxy/index.js sendError), not a transport error.
    err, resp = done[0]
    assert err is None
    assert resp.status_code == 500
    assert "diverged" in resp.body
    assert events == ["aborted"]
    # both keys now resolve away from the dead owner, to two nodes
    assert sender.lookup(k1) != sender.lookup(k2)
    c.destroy_all()


def test_endpoint_override():
    """proxyReq forwards to a custom endpoint instead of /proxy/req when
    opts.endpoint is set (reference proxy-test.js 'endpoint overridden')."""
    c = converged_cluster(3)
    sender = c.nodes[0]
    key = key_not_owned_by(c, sender)
    dest = sender.lookup(key)
    dest_node = next(n for n in c.nodes if n.whoami() == dest)

    hits = []

    def custom_handler(head, body, src, respond):
        hits.append((json.loads(head)["url"], body))
        respond(None, json.dumps({"statusCode": 299, "headers": {}}), "custom-body")

    dest_node.channel.register({"/custom/forward": custom_handler})

    done = []
    req = ProxyRequest(url="/x", method="GET", body="b")
    res = ProxyResponse(lambda err, resp: done.append((err, resp)))
    sender.proxy_req(
        {
            "keys": [key],
            "dest": dest,
            "req": req,
            "res": res,
            "endpoint": "/custom/forward",
        }
    )
    c.run(2000)
    err, resp = done[0]
    assert err is None
    assert hits and hits[0][0] == "/x"
    assert resp.status_code == 299
    assert resp.body == "custom-body"
    c.destroy_all()


def test_destroy_cancels_inflight_retries():
    """destroy() cancels scheduled proxy retries (request-proxy/index.js
    in-flight send tracking; reference proxy-test.js 'sends cleaned up')."""
    c = converged_cluster(3)
    sender = c.nodes[0]
    key = key_not_owned_by(c, sender)
    dest = sender.lookup(key)

    attempts = []
    sender.on("requestProxy.retryAttempted", lambda *a: attempts.append(1))
    done = []
    req = ProxyRequest(url="/x", method="GET")
    res = ProxyResponse(lambda err, resp: done.append(err))
    c.kill([n.whoami() for n in c.nodes].index(dest))
    sender.proxy_req(
        {
            "keys": [key],
            "dest": dest,
            "req": req,
            "res": res,
            "timeout": 500,
            "retrySchedule": [5.0],  # long enough to destroy before it fires
        }
    )
    c.run(1000)  # request times out -> retry scheduled at +5 s
    assert sender.request_proxy.sends, "send not tracked in-flight"
    sender.destroy()
    assert not sender.request_proxy.sends, "destroy left sends tracked"
    c.run(20000)  # past the retry deadline: canceled timer must not fire
    assert attempts == []
    c.destroy_all()

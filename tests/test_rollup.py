"""Membership-update rollup (reference:
test/membership-update-rollup-test.js): buffering per address, flush
after a quiet interval, flush-before-append when stale, destroy."""

from __future__ import annotations

from ringpop_tpu.harness import test_ringpop


def make():
    # make_alive=False: the local-member update would otherwise pre-seed
    # the buffer and start the flush timer.
    rp = test_ringpop(host_port="10.0.0.1:3000", make_alive=False)
    return rp, rp.membership_update_rollup


def upd(addr, status="alive", inc=1):
    return {"address": addr, "status": status, "incarnationNumber": inc}


def test_updates_buffered_by_address_with_timestamps():
    rp, rollup = make()
    rollup.track_updates([upd("a:1"), upd("b:1"), upd("a:1", "suspect")])
    assert rollup.get_num_updates() == 3
    assert len(rollup.buffer["a:1"]) == 2
    assert all("ts" in e for e in rollup.buffer["a:1"])


def test_flushes_after_quiet_interval():
    rp, rollup = make()
    flushed = []
    rollup.on("flushed", lambda *a: flushed.append(1))
    rollup.track_updates([upd("a:1")])
    rp.clock.advance(rollup.flush_interval - 1)
    assert not flushed  # still within the quiet window
    rp.clock.advance(2)
    assert flushed == [1]
    assert rollup.get_num_updates() == 0
    assert rollup.last_flush_time is not None


def test_activity_renews_the_flush_timer():
    rp, rollup = make()
    flushed = []
    rollup.on("flushed", lambda *a: flushed.append(1))
    for _ in range(3):
        rollup.track_updates([upd("a:1")])
        rp.clock.advance(rollup.flush_interval / 2)
    assert not flushed  # timer kept renewing
    rp.clock.advance(rollup.flush_interval)
    assert flushed == [1]


def test_stale_buffer_flushed_before_new_updates_tracked():
    rp, rollup = make()
    rollup.track_updates([upd("a:1")])
    # Simulate time passing beyond the interval without the timer firing
    # (the reference guards this path explicitly, rollup.js:105-122).
    rp.clock.cancel(rollup.flush_timer)
    rp.clock.advance(rollup.flush_interval + 1)
    flushed = []
    rollup.on("flushed", lambda *a: flushed.append(1))
    rollup.track_updates([upd("b:1")])
    assert flushed == [1]
    assert "a:1" not in rollup.buffer
    assert rollup.get_num_updates() == 1  # only the new update remains


def test_empty_updates_ignored_and_destroy_cancels_timer():
    rp, rollup = make()
    rollup.track_updates([])
    assert rollup.flush_timer is None
    rollup.track_updates([upd("a:1")])
    rollup.destroy()
    flushed = []
    rollup.on("flushed", lambda *a: flushed.append(1))
    rp.clock.advance(rollup.flush_interval * 2)
    assert not flushed  # cancelled

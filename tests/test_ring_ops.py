"""Device ring kernels vs the host HashRing (lib/ring.js contract)."""

from __future__ import annotations

import random

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.hashring import HashRing
from ringpop_tpu.ops import ring_ops
from ringpop_tpu.ops.farmhash import farmhash32

SERVERS = [f"10.0.0.{i}:{3000 + i}" for i in range(20)]


def host_ring() -> HashRing:
    ring = HashRing()
    ring.add_remove_servers(SERVERS, [])
    return ring


def test_lookup_matches_host_ring():
    host = host_ring()
    dev = ring_ops.build_ring(SERVERS)
    rng = random.Random(2)
    keys = [f"key-{rng.randrange(10 ** 12)}" for _ in range(1000)]
    hashes = jnp.asarray(np.array([farmhash32(k) for k in keys], dtype=np.uint32))
    owners = np.asarray(ring_ops.lookup_idx(dev, hashes))
    for key, owner in zip(keys, owners):
        assert SERVERS[owner] == host.lookup(key), key


def test_lookup_on_device_hashing_matches():
    host = host_ring()
    dev = ring_ops.build_ring(SERVERS)
    keys = [f"user:{i}" for i in range(257)]
    bufs, lens = ring_ops.encode_strings(keys)
    owners = np.asarray(
        jax.jit(ring_ops.lookup_keys)(dev, jnp.asarray(bufs), jnp.asarray(lens))
    )
    for key, owner in zip(keys, owners):
        assert SERVERS[owner] == host.lookup(key), key


def test_build_ring_on_device_bit_identical():
    dev_host = ring_ops.build_ring(SERVERS)
    bufs, lens = ring_ops.encode_strings(SERVERS)
    name_rank = np.argsort(np.argsort(np.array(SERVERS, dtype=object))).astype(np.int32)
    dev_dev = ring_ops.build_ring_on_device(
        jnp.asarray(bufs), jnp.asarray(lens), name_rank=jnp.asarray(name_rank)
    )
    assert np.array_equal(np.asarray(dev_host.hashes), np.asarray(dev_dev.hashes))
    assert np.array_equal(np.asarray(dev_host.owners), np.asarray(dev_dev.owners))


def test_lookup_n_matches_host_ring():
    host = host_ring()
    dev = ring_ops.build_ring(SERVERS)
    rng = random.Random(5)
    keys = [f"pref-{rng.randrange(10 ** 9)}" for _ in range(300)]
    hashes = jnp.asarray(np.array([farmhash32(k) for k in keys], dtype=np.uint32))
    n = 4
    prefs, complete = ring_ops.lookup_n_idx(dev, hashes, n)
    assert bool(np.asarray(complete).all())
    prefs = np.asarray(prefs)
    for key, row in zip(keys, prefs):
        expect = host.lookup_n(key, n)
        got = [SERVERS[i] for i in row if i >= 0]
        assert got == expect, (key, got, expect)


def test_exact_replica_hash_owns_itself():
    """A key hashing exactly onto a replica point must resolve to that
    replica's owner (equality-inclusive bound, rbtree.js:262-271)."""
    dev = ring_ops.build_ring(SERVERS)
    probe = jnp.asarray(np.asarray(dev.hashes)[7:8])
    owner = int(ring_ops.lookup_idx(dev, probe)[0])
    assert owner == int(np.asarray(dev.owners)[7])


def test_empty_device_ring_lookup_raises():
    """Host HashRing.lookup returns None on an empty ring; the fixed-shape
    device path raises instead of dividing by zero."""
    import pytest

    empty = ring_ops.build_ring([])
    key = jnp.zeros((1,), dtype=jnp.uint32)
    with pytest.raises(ValueError):
        ring_ops.lookup_idx(empty, key)
    with pytest.raises(ValueError):
        ring_ops.lookup_n_idx(empty, key, 3)

"""Device ring kernels vs the host HashRing (lib/ring.js contract)."""

from __future__ import annotations

import random

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.hashring import HashRing
from ringpop_tpu.ops import ring_ops
from ringpop_tpu.ops.farmhash import farmhash32

SERVERS = [f"10.0.0.{i}:{3000 + i}" for i in range(20)]


def host_ring() -> HashRing:
    ring = HashRing()
    ring.add_remove_servers(SERVERS, [])
    return ring


def test_lookup_matches_host_ring():
    host = host_ring()
    dev = ring_ops.build_ring(SERVERS)
    rng = random.Random(2)
    keys = [f"key-{rng.randrange(10 ** 12)}" for _ in range(1000)]
    hashes = jnp.asarray(np.array([farmhash32(k) for k in keys], dtype=np.uint32))
    owners = np.asarray(ring_ops.lookup_idx(dev, hashes))
    for key, owner in zip(keys, owners):
        assert SERVERS[owner] == host.lookup(key), key


def test_lookup_on_device_hashing_matches():
    host = host_ring()
    dev = ring_ops.build_ring(SERVERS)
    keys = [f"user:{i}" for i in range(257)]
    bufs, lens = ring_ops.encode_strings(keys)
    owners = np.asarray(
        jax.jit(ring_ops.lookup_keys)(dev, jnp.asarray(bufs), jnp.asarray(lens))
    )
    for key, owner in zip(keys, owners):
        assert SERVERS[owner] == host.lookup(key), key


def test_build_ring_on_device_bit_identical():
    dev_host = ring_ops.build_ring(SERVERS)
    bufs, lens = ring_ops.encode_strings(SERVERS)
    name_rank = np.argsort(np.argsort(np.array(SERVERS, dtype=object))).astype(np.int32)
    dev_dev = ring_ops.build_ring_on_device(
        jnp.asarray(bufs), jnp.asarray(lens), name_rank=jnp.asarray(name_rank)
    )
    assert np.array_equal(np.asarray(dev_host.hashes), np.asarray(dev_dev.hashes))
    assert np.array_equal(np.asarray(dev_host.owners), np.asarray(dev_dev.owners))


def test_lookup_n_matches_host_ring():
    host = host_ring()
    dev = ring_ops.build_ring(SERVERS)
    rng = random.Random(5)
    keys = [f"pref-{rng.randrange(10 ** 9)}" for _ in range(300)]
    hashes = jnp.asarray(np.array([farmhash32(k) for k in keys], dtype=np.uint32))
    n = 4
    prefs, complete = ring_ops.lookup_n_idx(dev, hashes, n)
    assert bool(np.asarray(complete).all())
    prefs = np.asarray(prefs)
    for key, row in zip(keys, prefs):
        expect = host.lookup_n(key, n)
        got = [SERVERS[i] for i in row if i >= 0]
        assert got == expect, (key, got, expect)


def test_exact_replica_hash_owns_itself():
    """A key hashing exactly onto a replica point must resolve to that
    replica's owner (equality-inclusive bound, rbtree.js:262-271)."""
    dev = ring_ops.build_ring(SERVERS)
    probe = jnp.asarray(np.asarray(dev.hashes)[7:8])
    owner = int(ring_ops.lookup_idx(dev, probe)[0])
    assert owner == int(np.asarray(dev.owners)[7])


def test_lookup_n_parity_across_churn():
    """lookup/lookupN bit-parity must survive membership churn: add a
    server, remove one, re-build the device ring each time (the traffic
    plane's ring lifecycle), and re-check against the mutated host ring."""
    host = host_ring()
    servers = list(SERVERS)
    rng = random.Random(17)
    keys = [f"churn-{rng.randrange(10 ** 9)}" for _ in range(150)]
    hashes = jnp.asarray(
        np.array([farmhash32(k) for k in keys], dtype=np.uint32)
    )
    mutations = [
        ("add", "10.0.1.99:4000"),
        ("remove", SERVERS[3]),
        ("remove", SERVERS[0]),
        ("add", "10.0.2.7:5000"),
    ]
    for op, server in mutations:
        if op == "add":
            host.add_server(server)
            servers.append(server)
        else:
            host.remove_server(server)
            servers.remove(server)
        dev = ring_ops.build_ring(servers)
        owners = np.asarray(ring_ops.lookup_idx(dev, hashes))
        prefs, complete = ring_ops.lookup_n_idx(dev, hashes, 3)
        assert bool(np.asarray(complete).all())
        prefs = np.asarray(prefs)
        for key, owner, row in zip(keys, owners, prefs):
            assert servers[owner] == host.lookup(key), (op, server, key)
            got = [servers[i] for i in row if i >= 0]
            assert got == host.lookup_n(key, 3), (op, server, key)


def test_lookup_wraparound_at_ring_minimum():
    """A key hashing past the LAST replica wraps to the ring minimum
    (ring.js:142-145), and a preference walk started there continues
    from the top of the table — for lookup, lookup_n, and the masked
    traffic kernels."""
    from ringpop_tpu.traffic import engine as tengine

    dev = ring_ops.build_ring(SERVERS)
    hashes_np = np.asarray(dev.hashes)
    owners_np = np.asarray(dev.owners)
    assert int(hashes_np[-1]) < 2 ** 32 - 1  # probe below is representable
    probes = jnp.asarray(
        np.array(
            [int(hashes_np[-1]) + 1, int(hashes_np[-1]), int(hashes_np[0])],
            dtype=np.uint32,
        )
    )
    got = np.asarray(ring_ops.lookup_idx(dev, probes))
    # past-the-end wraps to the minimum; exact hits own themselves
    assert got[0] == owners_np[0]
    assert got[1] == owners_np[-1]
    assert got[2] == owners_np[0]

    # lookupN from the wrap point: the first n distinct owners walking
    # from the top of the table
    n = 4
    expect = []
    for o in owners_np:
        if o not in expect:
            expect.append(int(o))
        if len(expect) == n:
            break
    prefs, complete = ring_ops.lookup_n_idx(dev, probes[:1], n)
    assert bool(np.asarray(complete).all())
    assert list(np.asarray(prefs)[0]) == expect

    # the masked kernel wraps identically (all-True mask == plain ring)
    mask = jnp.ones((3, len(SERVERS)), dtype=bool)
    mowner, mfound = tengine.lookup_masked_idx(
        dev.hashes, dev.owners, probes, mask, window=dev.size
    )
    assert bool(np.asarray(mfound).all())
    assert np.array_equal(np.asarray(mowner), got)


def test_empty_device_ring_lookup_raises():
    """Host HashRing.lookup returns None on an empty ring; the fixed-shape
    device path raises instead of dividing by zero."""
    import pytest

    empty = ring_ops.build_ring([])
    key = jnp.zeros((1,), dtype=jnp.uint32)
    with pytest.raises(ValueError):
        ring_ops.lookup_idx(empty, key)
    with pytest.raises(ValueError):
        ring_ops.lookup_n_idx(empty, key, 3)

"""Update-rule lattice tests (reference: membership precedence semantics in
lib/membership-update-rules.js, exercised by test/membership-test.js)."""

from ringpop_tpu.member import Member, Status
from ringpop_tpu import update_rules as rules


def member(status, inc=10):
    return Member("10.0.0.1:3000", status, inc)


def change(status, inc):
    return {"status": status, "incarnationNumber": inc}


def test_alive_override():
    # Alive beats anything only with strictly newer incarnation (:25-29).
    for status in Status.ALL:
        assert rules.is_alive_override(member(status), change(Status.alive, 11))
        assert not rules.is_alive_override(member(status), change(Status.alive, 10))
        assert not rules.is_alive_override(member(status), change(Status.alive, 9))


def test_suspect_override():
    # suspect vs alive: >=; vs suspect/faulty: >; vs leave: never (:54-59).
    assert rules.is_suspect_override(member(Status.alive), change(Status.suspect, 10))
    assert rules.is_suspect_override(member(Status.alive), change(Status.suspect, 11))
    assert not rules.is_suspect_override(member(Status.alive), change(Status.suspect, 9))
    assert not rules.is_suspect_override(member(Status.suspect), change(Status.suspect, 10))
    assert rules.is_suspect_override(member(Status.suspect), change(Status.suspect, 11))
    assert not rules.is_suspect_override(member(Status.faulty), change(Status.suspect, 10))
    assert rules.is_suspect_override(member(Status.faulty), change(Status.suspect, 11))
    assert not rules.is_suspect_override(member(Status.leave), change(Status.suspect, 99))


def test_faulty_override():
    assert rules.is_faulty_override(member(Status.alive), change(Status.faulty, 10))
    assert rules.is_faulty_override(member(Status.suspect), change(Status.faulty, 10))
    assert not rules.is_faulty_override(member(Status.faulty), change(Status.faulty, 10))
    assert rules.is_faulty_override(member(Status.faulty), change(Status.faulty, 11))
    assert not rules.is_faulty_override(member(Status.leave), change(Status.faulty, 99))
    assert not rules.is_faulty_override(member(Status.alive), change(Status.faulty, 9))


def test_leave_override():
    for status in (Status.alive, Status.suspect, Status.faulty):
        assert rules.is_leave_override(member(status), change(Status.leave, 10))
        assert not rules.is_leave_override(member(status), change(Status.leave, 9))
    # leave never re-applied over leave, regardless of incarnation
    assert not rules.is_leave_override(member(Status.leave), change(Status.leave, 99))


def test_local_overrides():
    local = "10.0.0.1:3000"
    other = "10.0.0.9:3000"
    m = member(Status.alive)
    assert rules.is_local_suspect_override(local, m, change(Status.suspect, 1))
    assert rules.is_local_faulty_override(local, m, change(Status.faulty, 1))
    assert not rules.is_local_suspect_override(other, m, change(Status.suspect, 1))
    assert not rules.is_local_faulty_override(other, m, change(Status.faulty, 1))
    assert not rules.is_local_suspect_override(local, m, change(Status.faulty, 1))

"""Red-black tree tests (reference: test/rbtree_test.js, 612 LoC —
insert/remove/bounds/iterator plus the 'RBTree payload copy bug'
regression at rbtree_test.js:594) and RBRing vs HashRing cross-checks."""

from __future__ import annotations

import random

from ringpop_tpu.hashring import HashRing
from ringpop_tpu.ops.farmhash import farmhash32
from ringpop_tpu.rbtree import RBRing, RBTree


def build(vals):
    tree = RBTree()
    for v in vals:
        tree.insert(v, f"s{v}")
    return tree


def test_insert_iterate_sorted():
    vals = random.Random(1).sample(range(10 ** 6), 500)
    tree = build(vals)
    assert tree.size == 500
    assert [n.val for n in tree] == sorted(vals)
    tree.check_invariants()


def test_duplicate_insert_rejected():
    tree = RBTree()
    assert tree.insert(5, "a") is True
    assert tree.insert(5, "b") is False
    assert tree.size == 1
    assert tree.find(5).name == "a"


def test_remove_with_oracle_and_invariants():
    rng = random.Random(7)
    vals = rng.sample(range(10 ** 6), 400)
    tree = build(vals)
    alive = set(vals)
    for v in rng.sample(vals, 300):
        assert tree.remove(v) is True
        alive.discard(v)
        assert tree.remove(v) is False  # already gone
    assert tree.size == len(alive)
    assert [n.val for n in tree] == sorted(alive)
    tree.check_invariants()


def test_payload_copy_on_two_child_removal():
    """Removing a node with two children replaces it with its successor's
    val AND name together — the reference's payload-copy regression."""
    tree = build([50, 25, 75, 10, 30, 60, 90])
    tree.remove(50)
    for node in tree:
        assert node.name == f"s{node.val}", (node.val, node.name)
    tree.check_invariants()


def test_min_and_empty():
    tree = RBTree()
    assert tree.min() is None
    assert tree.find(1) is None
    assert tree.remove(1) is False
    it = tree.iterator()
    assert it.next() is None and it.val() is None
    tree.insert(42, "x")
    assert tree.min().val == 42


def test_bounds_semantics():
    tree = build([10, 20, 30, 40])
    # Exact hit: equality-inclusive (ring.js lookup depends on this).
    assert tree.upper_bound(20).val() == 20
    assert tree.lower_bound(20).val() == 20
    # Between nodes: first greater.
    assert tree.upper_bound(21).val() == 30
    assert tree.lower_bound(5).val() == 10
    # Past the end: cursor is None (ring wraps to min).
    assert tree.upper_bound(41).val() is None
    # Iterator continues in order from a bound.
    it2 = tree.lower_bound(15)
    seen = [it2.val()]
    while it2.next() is not None:
        seen.append(it2.val())
    assert seen == [20, 30, 40]


def test_bounds_against_oracle():
    rng = random.Random(3)
    vals = sorted(rng.sample(range(100000), 200))
    tree = build(vals)
    for probe in rng.sample(range(100001), 300):
        expect = next((v for v in vals if v >= probe), None)
        assert tree.lower_bound(probe).val() == expect
        assert tree.upper_bound(probe).val() == expect


def test_rbring_matches_hashring():
    """The tree-backed ring and the sorted-array ring implement the same
    lookup/lookupN contract (ring.js:138-182)."""
    array_ring = HashRing()
    tree_ring = RBRing(farmhash32)
    servers = [f"10.0.0.{i}:3000" for i in range(12)]
    for server in servers:
        array_ring.add_server(server)
        tree_ring.add_server(server)

    rng = random.Random(11)
    keys = [f"key-{rng.randrange(10 ** 9)}" for _ in range(500)]
    for key in keys:
        assert array_ring.lookup(key) == tree_ring.lookup(key), key
        assert array_ring.lookup_n(key, 4) == tree_ring.lookup_n(key, 4), key

    # ... and still after churn.
    for server in servers[::3]:
        array_ring.remove_server(server)
        tree_ring.remove_server(server)
    for key in keys[:200]:
        assert array_ring.lookup(key) == tree_ring.lookup(key), key
        assert array_ring.lookup_n(key, 3) == tree_ring.lookup_n(key, 3), key

"""Scenario engine: compiled fault timelines, one dispatch, per-tick
telemetry, and bit-parity with the host-driven fault sequence
(the netsplit scripting the reference stubbed out,
test/lib/partition-cluster.js:59-61, finished and exceeded).

Fast lane: the spec/compiler/trace host logic plus ONE minimal
compiled run asserting the single-dispatch contract.  The full
acceptance grid — kill+partition+heal+loss-ramp parity against the
host loop, dense-vs-delta backend parity, the seeded golden trace,
in-scan revive — compiles several full-step scan programs on CPU and
rides the slow lane with the other parity soaks (module-scoped
fixtures pay each compile once).  tools/scenario.sh drives the CLI
end-to-end as the CI smoke.
"""

from __future__ import annotations

import numpy as np
import pytest

from ringpop_tpu.models import swim_delta as sdelta
from ringpop_tpu.models import swim_sim as sim
from ringpop_tpu.models.cluster import SimCluster
from ringpop_tpu.scenarios import compile as scompile
from ringpop_tpu.scenarios import runner
from ringpop_tpu.scenarios.spec import Event, ScenarioSpec, script_to_spec
from ringpop_tpu.scenarios.trace import Trace
from ringpop_tpu.stats import Histogram
from ringpop_tpu.utils.jaxpin import golden_skip_reason

FAST = sim.SwimParams(suspicion_ticks=8)
N = 12
TICKS = 40
# The acceptance scenario: kill + partition + heal + loss step/ramp.
SPEC = ScenarioSpec.from_dict(
    {
        "ticks": TICKS,
        "events": [
            {"at": 5, "op": "kill", "node": 3},
            {"at": 10, "op": "partition",
             "groups": [list(range(6)), list(range(6, 12))]},
            {"at": 10, "op": "loss", "p": 0.08},
            {"at": 20, "op": "heal"},
            {"at": 25, "op": "loss_ramp", "until": 30, "to": 0.0},
        ],
    }
)


def _states_equal(a, b) -> bool:
    return all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(a, b)
        if x is not None
    )


# -- fast: the single-dispatch contract on a minimal compiled run -----------


def test_single_dispatch_smoke(monkeypatch):
    """A compiled scenario is ONE jitted call: no swim_step / swim_run /
    delta_step dispatch at all, the scenario counter advances once, and
    the trace carries every tick (the per-tick series swim_run drops)."""

    def boom(*a, **k):  # pragma: no cover - would mean a host round-trip
        raise AssertionError("host-loop dispatch inside run_scenario")

    monkeypatch.setattr(sim, "swim_step", boom)
    monkeypatch.setattr(sim, "swim_run", boom)
    monkeypatch.setattr(sdelta, "delta_step", boom)
    monkeypatch.setattr(sdelta, "delta_run", boom)
    before = runner.dispatch_count()
    c = SimCluster(6, sim.SwimParams(suspicion_ticks=5), seed=1)
    trace = c.run_scenario(
        {"ticks": 4, "events": [{"at": 1, "op": "kill", "node": 5}]}
    )
    assert runner.dispatch_count() - before == 1
    assert trace.ticks == 4
    assert trace.live.tolist() == [6, 5, 5, 5]  # kill lands at tick 1
    assert all(arr.shape == (4,) for arr in trace.metrics.values())
    # run_scenario logs one aggregated entry spanning the whole run
    assert c.metrics_log[-1]["ticks"] == 4
    assert c.traces == [trace]


def test_metrics_log_records_tick_span():
    # same (n, params) as test_sim_core's metrics test: cache-warm
    c = SimCluster(6, sim.SwimParams(suspicion_ticks=5), seed=10)
    m = c.tick()
    assert m["ticks"] == 1
    assert c.metrics_log[0]["ticks"] == 1


# -- fast: spec + compiler (host-only) --------------------------------------


def test_spec_json_roundtrip(tmp_path):
    path = str(tmp_path / "spec.json")
    SPEC.save(path)
    assert ScenarioSpec.load(path) == SPEC


def test_spec_validation_errors():
    with pytest.raises(ValueError, match="unknown scenario op"):
        Event.from_dict({"at": 0, "op": "explode"})
    with pytest.raises(ValueError, match="outside"):
        ScenarioSpec(ticks=5, events=(Event(at=5, op="kill", node=0),)).validate(4)
    with pytest.raises(ValueError, match="cover every node"):
        ScenarioSpec(
            ticks=5, events=(Event(at=0, op="partition", groups=((0, 1),)),)
        ).validate(4)
    with pytest.raises(ValueError, match="conflicting node events"):
        ScenarioSpec(
            ticks=5,
            events=(
                Event(at=1, op="kill", node=2),
                Event(at=1, op="revive", node=2),
            ),
        ).validate(4)
    with pytest.raises(ValueError, match="loss_ramp needs at < until"):
        ScenarioSpec(
            ticks=5, events=(Event(at=3, op="loss_ramp", p=0.1, until=2),)
        ).validate(4)
    # same-tick revive + kill on DIFFERENT nodes is legal since the
    # failure-model PR defined the canonical intra-tick order (bit
    # edits, then revives) on both the scan and the host loop — flap
    # storms need the mix; see tests/test_faults.py for the positive
    # case and the remaining same-(tick, node) rejection.
    ScenarioSpec(
        ticks=5,
        events=(
            Event(at=1, op="revive", node=2),
            Event(at=1, op="kill", node=0),
        ),
    ).validate(4)


def test_compile_loss_schedule_and_boundaries():
    compiled = scompile.compile_spec(SPEC, N, base_loss=0.0)
    loss = np.asarray(compiled.loss)
    assert loss.shape == (TICKS,)
    assert loss[9] == 0.0 and loss[10] == np.float32(0.08)
    # stepwise-linear ramp reaches the target at until-1 and holds
    assert loss[29] == 0.0 and loss[39] == 0.0
    assert 0.0 < loss[26] < 0.08
    # every event tick is a key-schedule segment boundary (ramp ticks too)
    assert compiled.boundaries == (5, 10, 20, 25, 26, 27, 28, 29)
    assert not compiled.has_revive
    assert compiled.p_gid.shape == (2, N)  # partition + heal rows
    assert np.asarray(compiled.p_gid[1]).max() == 0  # heal = one group


def test_compile_ramp_interleaved_with_loss_event():
    """A loss event INSIDE a ramp's span must override only its own
    tick onward until the next ramp step — the timeline is written in
    tick order (matching the host loop's per-tick set_loss calls),
    not event order."""
    spec = ScenarioSpec.from_dict(
        {
            "ticks": 10,
            "events": [
                {"at": 2, "op": "loss_ramp", "until": 8, "to": 0.6},
                {"at": 5, "op": "loss", "p": 0.1},
            ],
        }
    )
    loss = np.asarray(scompile.compile_spec(spec, 4).loss)
    assert loss[5] == np.float32(0.1)  # the event wins its own tick
    assert loss[6] == np.float32(0.5)  # ...but the ramp resumes after
    assert loss[7] == np.float32(0.6)
    assert loss[9] == np.float32(0.6)


def test_key_schedule_is_segment_exact():
    """One cluster-key draw per segment, fanned per tick — byte-equal
    to what the host tick(1)/tick(k) calls of the same fault sequence
    consume (the basis of the scan/host-loop bit parity)."""
    import jax

    compiled = scompile.compile_spec(SPEC, N, base_loss=0.0)
    key = jax.random.PRNGKey(9)

    class Split:
        def __init__(self, key):
            self.key = key

        def __call__(self):
            self.key, sub = jax.random.split(self.key)
            return sub

    keys = scompile.key_schedule(Split(key), compiled)
    assert keys.shape == (TICKS, 2)
    # replay by hand: segment [0, 5) is one draw fanned into 5
    k2, sub = jax.random.split(key)
    np.testing.assert_array_equal(
        np.asarray(keys[:5]), np.asarray(jax.random.split(sub, 5))
    )
    # ...and the length-1 ramp segment [25, 26) is a bare draw
    s = Split(key)
    for _ in range(4):
        s()
    np.testing.assert_array_equal(np.asarray(keys[25]), np.asarray(s()))


def test_script_to_spec():
    spec = script_to_spec("j,w1000,t,k,t,l,t,L,K,w2000,t,q", 5)
    kills = [e for e in spec.events if e.op == "kill"]
    revives = [e for e in spec.events if e.op == "revive"]
    suspends = [e for e in spec.events if e.op == "suspend"]
    resumes = [e for e in spec.events if e.op == "resume"]
    assert [e.node for e in kills] == [4]  # highest live index
    assert [e.node for e in suspends] == [3]  # next-highest after the kill
    assert [e.node for e in resumes] == [3]
    assert [e.node for e in revives] == [4]
    assert kills[0].at == 6  # after w1000 (5 ticks @200ms) + t
    # the revive follows the same-tick resume, so it bumps one tick
    assert resumes[0].at == 8 and revives[0].at == 9
    assert spec.ticks == 9 + 10 + 1  # ...then w2000,t from the bumped clock
    spec.validate(5)


def test_script_to_spec_bumps_sametick_conflicts():
    """'k,K' with no intervening tick is legal in the live driver
    (instant apply) but needs an order in the compiled form: the
    revive lands one tick after the kill."""
    spec = script_to_spec("k,K,t,q", 4)
    assert spec.events == (
        Event(at=0, op="kill", node=3),
        Event(at=1, op="revive", node=3),
    )
    spec.validate(4)
    assert script_to_spec("l,L,t,q", 4).events == (
        Event(at=0, op="suspend", node=3),
        Event(at=1, op="resume", node=3),
    )


def test_script_to_spec_rejects_unknown():
    with pytest.raises(ValueError, match="unknown script command"):
        script_to_spec("j,x", 4)


def test_cli_script_to_scenario(tmp_path, capsys):
    from ringpop_tpu.cli.tick_cluster import main

    out = str(tmp_path / "spec.json")
    main(["--script", "t,k,w1000,t,q", "-n", "6", "--script-to-scenario", out])
    spec = ScenarioSpec.load(out)
    assert spec.events == (Event(at=1, op="kill", node=5),)
    assert "compiled 1 events" in capsys.readouterr().out


# -- fast: trace object (synthetic series; no compile) ----------------------


def _synthetic_trace(t: int = 5) -> Trace:
    return Trace(
        metrics={"pings_sent": np.arange(t, dtype=np.int32)},
        converged=np.array([False] * (t - 1) + [True]),
        live=np.full(t, 7, np.int32),
        loss=np.zeros(t, np.float32),
        n=8,
        backend="dense",
        start_tick=3,
        spec={"ticks": t, "events": []},
    )


def test_trace_npz_roundtrip(tmp_path):
    trace = _synthetic_trace()
    path = str(tmp_path / "trace.npz")
    trace.save(path)
    back = Trace.load(path).validate()
    assert back.ticks == trace.ticks
    assert back.backend == "dense" and back.n == 8 and back.start_tick == 3
    assert back.spec == trace.spec
    np.testing.assert_array_equal(back.converged, trace.converged)
    np.testing.assert_array_equal(back.live, trace.live)
    np.testing.assert_array_equal(back.loss, trace.loss)
    np.testing.assert_array_equal(
        back.metrics["pings_sent"], trace.metrics["pings_sent"]
    )


def test_trace_summary_is_stats_key_compatible():
    """Trace.summary() speaks the stats.Histogram.print_obj key shape,
    so stat consumers read a scenario like a meter dump."""
    trace = _synthetic_trace()
    summary = trace.summary()
    hist_keys = set(Histogram().print_obj().keys())
    for name in ("pings_sent", "live", "loss"):
        assert set(summary[name].keys()) == hist_keys, name
    assert summary["pings_sent"]["sum"] == 0 + 1 + 2 + 3 + 4
    assert summary["live"]["min"] == 7.0
    assert summary["converged"]["final"] is True
    assert summary["converged"]["first_tick"] == 4


def test_trace_validate_rejects_ragged():
    trace = _synthetic_trace()
    trace.metrics["pings_sent"] = np.zeros(3, np.int32)
    with pytest.raises(ValueError, match="not .*-shaped"):
        trace.validate()


def test_revive_rejected_on_delta_backend_without_key_burn():
    spec = ScenarioSpec(ticks=4, events=(Event(at=1, op="revive", node=0),))
    c = SimCluster(8, FAST, seed=0, backend="delta", capacity=8)
    key_before = np.asarray(c.key).copy()
    with pytest.raises(NotImplementedError, match="dense-backend-only"):
        c.run_scenario(spec)
    # the rejection fires BEFORE the key schedule draws: a failed call
    # must not silently desynchronize the cluster PRNG
    np.testing.assert_array_equal(np.asarray(c.key), key_before)


def test_scenario_accepts_healed_mask_partition():
    """A partial (mask-form) partition that was healed leaves an
    all-True bool[N, N] adj — semantically fully connected, so the
    scenario path lowers it to the group-id form instead of refusing;
    a genuine partial mask still raises."""
    c = SimCluster(6, sim.SwimParams(suspicion_ticks=5), seed=1)
    c.partition([[0, 1], [2, 3]])  # partial grouping -> mask form
    with pytest.raises(ValueError, match="group-id adjacency"):
        c.run_scenario({"ticks": 4, "events": []})
    c.heal_partition()  # keeps the mask layout (all ones) on purpose
    trace = c.run_scenario(
        {"ticks": 4, "events": [{"at": 1, "op": "kill", "node": 5}]}
    )
    assert trace.live.tolist() == [6, 5, 5, 5]
    assert c.net.adj.ndim == 1  # lowered to the scan's gid form


# -- slow: the acceptance grid (full-step scan compiles) --------------------


@pytest.fixture(scope="module")
def dense_run():
    before = runner.dispatch_count()
    c = SimCluster(N, FAST, seed=3)
    trace = c.run_scenario(SPEC)
    # the acceptance scenario is ONE dispatch on this backend too
    assert runner.dispatch_count() - before == 1
    return c, trace


@pytest.fixture(scope="module")
def host_run():
    c = SimCluster(N, FAST, seed=3)
    runner.run_host_loop(c, SPEC)
    return c


@pytest.fixture(scope="module")
def delta_run():
    # ample caps for a netsplit scenario (test_swim_delta convention:
    # the post-heal claim burst needs claim_grid = 3 * n * n)
    before = runner.dispatch_count()
    c = SimCluster(
        N, FAST, seed=3, backend="delta",
        capacity=N, wire_cap=N, claim_grid=3 * N * N,
    )
    trace = c.run_scenario(SPEC)
    assert runner.dispatch_count() - before == 1
    return c, trace


@pytest.mark.slow
def test_scan_matches_host_sequence(dense_run, host_run):
    """Bit-parity: the compiled one-call run equals the equivalent
    host-side kill()/partition()/tick() sequence — state, net, and
    reference-format checksums (the acceptance criterion)."""
    c, _ = dense_run
    h = host_run
    assert _states_equal(c.state, h.state)
    assert np.array_equal(np.asarray(c.net.up), np.asarray(h.net.up))
    assert np.array_equal(
        np.asarray(c.net.responsive), np.asarray(h.net.responsive)
    )
    assert c.checksums() == h.checksums()
    assert c.params.loss == h.params.loss


@pytest.mark.slow
def test_backend_parity(dense_run, delta_run):
    """The same spec on dense vs delta: identical per-tick converged /
    live series and final checksums (ample delta caps => bit parity)."""
    cd, td = dense_run
    cl, tl = delta_run
    np.testing.assert_array_equal(td.converged, tl.converged)
    np.testing.assert_array_equal(td.live, tl.live)
    np.testing.assert_array_equal(td.loss, tl.loss)
    assert cd.checksums() == cl.checksums()


@pytest.mark.slow
def test_scenario_telemetry_content(dense_run):
    _, trace = dense_run
    # the kill drops one node from the live count at tick 5
    assert int(trace.live[4]) == N
    assert int(trace.live[5]) == N - 1
    # the loss schedule: base 0 -> step 0.08 -> ramp back to 0
    assert trace.loss[0] == 0.0
    assert trace.loss[10] == np.float32(0.08)
    assert trace.loss[29] == 0.0
    # the partition + kill disrupt convergence; the run re-converges
    assert not trace.converged[12]
    assert trace.converged[-1]


@pytest.mark.slow
@pytest.mark.skipif(
    golden_skip_reason() is not None, reason=str(golden_skip_reason())
)
def test_golden_trace_stability(dense_run):
    """Seeded golden trace: the exact telemetry of the canonical spec
    at seed 3 (CPU, threefry).  A diff here means the protocol step,
    the event application, or the key schedule changed behavior — or
    an un-pinned jax (then this SKIPS with the re-pin instruction)."""
    _, trace = dense_run
    assert int(trace.metrics["pings_sent"].sum()) == 445
    assert int(trace.metrics["suspects_declared"].sum()) == 54
    assert int(trace.metrics["faulty_declared"].sum()) == 26
    assert trace.first_converged_tick() == 0  # starts converged
    assert int(trace.converged.sum()) == 22
    assert int(trace.live[-1]) == 11


@pytest.mark.slow
def test_revive_in_scan_matches_host():
    """kill -> revive inside ONE compiled call equals the host
    kill()/tick()/revive()/tick() sequence (fresh incarnation, wipe,
    bootstrap join against the first live node)."""
    spec = ScenarioSpec.from_dict(
        {
            "ticks": 30,
            "events": [
                {"at": 2, "op": "kill", "node": 5},
                {"at": 15, "op": "revive", "node": 5},
            ],
        }
    )
    a = SimCluster(10, FAST, seed=7)
    trace = a.run_scenario(spec)
    b = SimCluster(10, FAST, seed=7)
    runner.run_host_loop(b, spec)
    assert _states_equal(a.state, b.state)
    assert a.checksums() == b.checksums()
    assert int(trace.live[-1]) == 10  # the revived node is back


@pytest.mark.slow
def test_suspend_resume_in_scan():
    spec = ScenarioSpec.from_dict(
        {
            "ticks": 6,
            "events": [
                {"at": 1, "op": "suspend", "node": 2},
                {"at": 4, "op": "resume", "node": 2},
            ],
        }
    )
    c = SimCluster(6, FAST, seed=2)
    trace = c.run_scenario(spec)
    assert int(trace.live[1]) == 5  # suspended drops out of the live set
    assert int(trace.live[-1]) == 6  # resume restores it
    assert bool(np.asarray(c.net.responsive)[2])


@pytest.mark.slow
def test_live_trace_npz_roundtrip(dense_run, tmp_path):
    _, trace = dense_run
    path = str(tmp_path / "trace.npz")
    trace.save(path)
    back = Trace.load(path).validate()
    assert back.spec == SPEC.to_dict()
    np.testing.assert_array_equal(back.converged, trace.converged)
    for k in trace.metrics:
        np.testing.assert_array_equal(back.metrics[k], trace.metrics[k])


@pytest.mark.slow
def test_cli_scenario_end_to_end(tmp_path, capsys):
    """tick-cluster --backend tpu-sim --scenario FILE: one-dispatch
    run + npz trace export (the CI smoke job drives the same path via
    tools/scenario.sh)."""
    from ringpop_tpu.cli.tick_cluster import main

    spec_path = str(tmp_path / "spec.json")
    trace_path = str(tmp_path / "trace.npz")
    ScenarioSpec.from_dict(
        {"ticks": 10, "events": [{"at": 2, "op": "kill", "node": 3}]}
    ).save(spec_path)
    main([
        "--backend", "tpu-sim", "-n", "8",
        "--scenario", spec_path, "--trace-out", trace_path,
    ])
    out = capsys.readouterr().out
    assert "one dispatch" in out
    trace = Trace.load(trace_path).validate()
    assert trace.ticks == 10
    assert int(trace.live[-1]) == 7

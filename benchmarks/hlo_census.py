"""Census of expensive ops in the delta step's TPU StableHLO.

Lowers delta_step_impl for the TPU platform (no hardware needed —
``jax.export`` cross-platform lowering) and tallies every sort /
scatter / gather / while by operand shape, with a rough element count.
The per-tick fixed cost of the delta backend is sort-dominated; this
shows exactly which call sites pay for what before a chip is available
to time them (usage: python -m benchmarks.hlo_census [n] [capacity]).
"""

from __future__ import annotations

import collections
import re
import sys

import jax

from ringpop_tpu.utils import pin_cpu_if_requested

pin_cpu_if_requested()

import jax.numpy as jnp

from ringpop_tpu.models import swim_delta as sd
from ringpop_tpu.models import swim_sim as sim


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 65536
    cap = int(sys.argv[2]) if len(sys.argv) > 2 else 256
    params = sd.DeltaParams(swim=sim.SwimParams(loss=0.01), wire_cap=16,
                            claim_grid=64)
    state = sd.init_delta(n, capacity=cap)
    net = sim.make_net(n)
    key = jax.random.PRNGKey(0)

    exported = jax.export.export(
        jax.jit(sd.delta_step_impl, static_argnames=("params",)),
        platforms=["tpu"],
    )(state, net, key, params)
    txt = exported.mlir_module()

    tallies = collections.Counter()
    elems = collections.Counter()

    def _tally_sort(dims: str, nops: int) -> None:
        key_ = f"sort [{dims}] x{nops}ops"
        tallies[key_] += 1
        total = 1
        for d in dims.split("x"):
            total *= int(d)
        elems[key_] += total * nops

    # older jax: inline "stablehlo.sort"(...) ops
    for m in re.finditer(r'"stablehlo\.sort"\((.*?)\)', txt):
        shapes = re.findall(r"tensor<([0-9x]+)x[a-z0-9]+>", m.group(1))
        if shapes:
            _tally_sort(shapes[0], len(shapes))

    # newer jax: each sort call site lowers to a private func (named
    # @sort*, @argsort*, ...) whose body holds the stablehlo.sort —
    # tally CALLS to sort-bodied funcs by the call's operand signature,
    # skipping calls made from inside other sort-bodied funcs (an
    # argsort func calling its comparator must not double count).
    chunks = re.split(r"(?=func\.func)", txt)
    sort_funcs = set()
    for ch in chunks:
        m = re.match(r"func\.func(?: private)? @([\w$.]+)", ch)
        if m and "stablehlo.sort" in ch:
            sort_funcs.add(m.group(1))
    call_re = re.compile(r"(?:func\.)?call @([\w$.]+)\([^)]*\)\s*:\s*\(([^)]*)\)")
    for ch in chunks:
        m = re.match(r"func\.func(?: private)? @([\w$.]+)", ch)
        if m and m.group(1) in sort_funcs:
            continue
        for cm in call_re.finditer(ch):
            if cm.group(1) not in sort_funcs:
                continue
            shapes = re.findall(r"tensor<([0-9x]+)x[a-z0-9]+>", cm.group(2))
            if shapes:
                _tally_sort(shapes[0], len(shapes))
    for opname in ("scatter", "while", "dynamic_gather"):
        for m in re.finditer(rf'"stablehlo\.{opname}"\((.*?)\)', txt):
            shapes = re.findall(r"tensor<([0-9x]+)x[a-z0-9]+>", m.group(1))
            dims = shapes[0] if shapes else "?"
            tallies[f"{opname} [{dims}]"] += 1

    print(f"n={n} capacity={cap}  module: {len(txt) / 1e6:.1f} MB text")
    print(f"{'op [shape]':45s} {'count':>5s} {'Melems':>9s}")
    for key_, cnt in sorted(tallies.items(), key=lambda kv: -elems.get(kv[0], 0)):
        print(f"{key_:45s} {cnt:5d} {elems.get(key_, 0) / 1e6:9.1f}")
    total_sort = sum(v for k, v in elems.items() if k.startswith("sort"))
    print(f"total sorted elements/tick: {total_sort / 1e6:.1f} M")


if __name__ == "__main__":
    main()

"""Census of expensive ops in the SWIM step's TPU StableHLO.

Lowers a step for the TPU platform (no hardware needed —
``jax.export`` cross-platform lowering) and tallies every sort /
gather / scatter / while / Mosaic kernel by operand shape, with a
rough element count.  Two backends:

* ``--backend delta`` (default; the original census): the delta step's
  per-tick fixed cost is sort-dominated — this shows which call sites
  pay for what before a chip is available to time them.
* ``--backend dense``: the dense step's cost is the [N, N] HBM passes
  of the receiver merge — this makes the pass-count claim of
  ``RINGPOP_RECV_MERGE`` checkable without a chip.  With ``sorted``
  the census shows the full-tensor row permutation (an [N, N]-operand
  gather per merge call site) and the Hillis–Steele combine loop (a
  while per call site); with ``pallas`` both disappear into one
  ``tpu_custom_call`` per call site (ops/recv_merge_pallas.py), and
  the only remaining [N]-class sorts are the flat sender orderings.

Usage: python -m benchmarks.hlo_census [--backend dense|delta]
       [--recv-merge sorted|scatter|pallas]
       [--temps [--min-elems E] [--sort bytes|count|elems] [--top K]]
       [--collectives [--mesh D]] [n] [capacity]

``--temps`` switches to the temporary-tensor census (the trace-contract
auditor's contract 5, ringpop_tpu/analysis/contracts.py): one JSON row
per distinct (shape, dtype, producing primitive, jaxpr path) whose
intermediate is ``[N, N]``-shaped or at/above the element threshold —
the machine-readable target list for the footprint hunt (ROADMAP item
2a: which wide temporaries to bit-pack or fuse next).

``--collectives`` censuses the SHARDED step's partitioned HLO instead
(the partitioning auditor's contract 6, analysis/partitioning.py): one
JSON row per (collective op, dtype, shape, protocol phase) with
bytes-moved and the member-gather classification — which phases pay
replication for cross-shard gossip today, i.e. ROADMAP item 1's
remote-copy target list.  Runs on CPU virtual devices; ``--mesh D``
picks the mesh size (default 2).

``tests/test_hlo_census.py`` pins the dense tallies as a regression
guard (future PRs must not silently re-materialize the permuted claim
matrix).
"""

from __future__ import annotations

import argparse
import collections
import os
import re

import jax
import jax.export

from ringpop_tpu.utils import pin_cpu_if_requested

pin_cpu_if_requested()


_TENSOR_RE = re.compile(r"tensor<([0-9x]+)x[a-z0-9]+>")


def _dims_elems(dims: str) -> int:
    total = 1
    for d in dims.split("x"):
        total *= int(d)
    return total


def census_text(txt: str) -> tuple[collections.Counter, collections.Counter]:
    """Tally (op-kind [shape] -> count, -> element count) over one
    StableHLO module's text."""
    tallies: collections.Counter = collections.Counter()
    elems: collections.Counter = collections.Counter()

    def _tally_sort(dims: str, nops: int) -> None:
        key_ = f"sort [{dims}] x{nops}ops"
        tallies[key_] += 1
        elems[key_] += _dims_elems(dims) * nops

    # older jax: inline "stablehlo.sort"(...) ops
    for m in re.finditer(r'"stablehlo\.sort"\((.*?)\)', txt):
        shapes = _TENSOR_RE.findall(m.group(1))
        if shapes:
            _tally_sort(shapes[0], len(shapes))

    # newer jax: each sort call site lowers to a private func (named
    # @sort*, @argsort*, ...) whose body holds the stablehlo.sort —
    # tally CALLS to sort-bodied funcs by the call's operand signature,
    # skipping calls made from inside other sort-bodied funcs (an
    # argsort func calling its comparator must not double count).
    chunks = re.split(r"(?=func\.func)", txt)
    sort_funcs = set()
    for ch in chunks:
        m = re.match(r"func\.func(?: private)? @([\w$.]+)", ch)
        if m and "stablehlo.sort" in ch:
            sort_funcs.add(m.group(1))
    call_re = re.compile(r"(?:func\.)?call @([\w$.]+)\([^)]*\)\s*:\s*\(([^)]*)\)")
    for ch in chunks:
        m = re.match(r"func\.func(?: private)? @([\w$.]+)", ch)
        if m and m.group(1) in sort_funcs:
            continue
        for cm in call_re.finditer(ch):
            if cm.group(1) not in sort_funcs:
                continue
            shapes = _TENSOR_RE.findall(cm.group(2))
            if shapes:
                _tally_sort(shapes[0], len(shapes))

    # gathers print generic-form on one line with the full operand type
    # signature — shape = the gathered operand (the census's whole
    # point: a [N, N] first operand is a full-tensor row permutation)
    for m in re.finditer(r'"stablehlo\.gather"\([^\n]*?:\s*\(([^)]*)\)', txt):
        shapes = _TENSOR_RE.findall(m.group(1))
        dims = shapes[0] if shapes else "?"
        key_ = f"gather [{dims}]"
        tallies[key_] += 1
        if shapes:
            elems[key_] += _dims_elems(dims)

    # region-holding ops (scatter's update fn spans lines; while prints
    # pretty-form): count call sites, shapes best-effort
    for opname in ("scatter", "dynamic_gather"):
        for m in re.finditer(rf'"stablehlo\.{opname}"\((.*?)\)', txt):
            shapes = _TENSOR_RE.findall(m.group(1))
            dims = shapes[0] if shapes else "?"
            tallies[f"{opname} [{dims}]"] += 1
    n_while = len(re.findall(r"= stablehlo\.while\(", txt)) + len(
        re.findall(r'"stablehlo\.while"\(', txt)
    )
    if n_while:
        tallies["while [?]"] += n_while

    # Mosaic kernels (Pallas lowerings) arrive as tpu_custom_call
    n_mosaic = len(re.findall(r'custom_call[^\n]*@tpu_custom_call', txt)) + len(
        re.findall(r'call_target_name\s*=\s*"tpu_custom_call"', txt)
    )
    if n_mosaic:
        tallies["tpu_custom_call [mosaic]"] += n_mosaic

    return tallies, elems


def lower_delta(n: int, cap: int) -> str:
    """The delta step's TPU StableHLO module text."""
    from ringpop_tpu.models import swim_delta as sd
    from ringpop_tpu.models import swim_sim as sim

    params = sd.DeltaParams(
        swim=sim.SwimParams(loss=0.01), wire_cap=16, claim_grid=64
    )
    state = sd.init_delta(n, capacity=cap)
    net = sim.make_net(n)
    key = jax.random.PRNGKey(0)
    exported = jax.export.export(
        jax.jit(sd.delta_step_impl, static_argnames=("params",)),
        platforms=["tpu"],
    )(state, net, key, params)
    return exported.mlir_module()


def lower_dense(n: int, recv_merge: str | None = None) -> str:
    """The dense step's TPU StableHLO module text.

    ``recv_merge`` overrides the RINGPOP_RECV_MERGE lowering for this
    trace.  The Pallas form is lowered compiled (not interpret) so the
    census sees the real Mosaic kernel even on a CPU host."""
    from ringpop_tpu.models import swim_sim as sim

    params = sim.SwimParams(loss=0.01)
    state = sim.init_state(n)
    net = sim.make_net(n)
    key = jax.random.PRNGKey(0)

    def _export():
        exported = jax.export.export(
            jax.jit(sim.swim_step_impl, static_argnames=("params",)),
            platforms=["tpu"],
        )(state, net, key, params)
        return exported.mlir_module()

    prev = os.environ.get("RINGPOP_PALLAS_INTERPRET")
    os.environ["RINGPOP_PALLAS_INTERPRET"] = "0"
    try:
        jax.clear_caches()  # the lowering depends on the env knobs
        if recv_merge is None:
            return _export()
        with sim._force_recv_merge(recv_merge):
            return _export()
    finally:
        if prev is None:
            del os.environ["RINGPOP_PALLAS_INTERPRET"]
        else:
            os.environ["RINGPOP_PALLAS_INTERPRET"] = prev
        jax.clear_caches()


def temp_rows(
    backend: str,
    n: int,
    cap: int,
    recv_merge: str | None = None,
    min_elems: int | None = None,
) -> list[dict]:
    """Temporary-tensor census rows of one protocol STEP (the same
    program scope the op tallies cover), via the auditor's jaxpr
    census.  ``min_elems`` defaults to the [N, C]-class floor on delta
    and [N, N] on dense."""
    from ringpop_tpu.analysis.contracts import temp_census
    from ringpop_tpu.analysis.registry import _delta_fixture, _dense_fixture

    key = jax.random.PRNGKey(0)
    if backend == "delta":
        from ringpop_tpu.models import swim_delta as sd

        state, net, params = _delta_fixture(n, cap)
        closed = jax.make_jaxpr(
            sd.delta_step_impl, static_argnums=(3,)
        )(state, net, key, params)
        dims = dict(N=n, C=cap)
        floor = min_elems if min_elems is not None else n * cap
    else:
        from ringpop_tpu.models import swim_sim as sim

        state, net, params = _dense_fixture(n)

        def _trace():
            return jax.make_jaxpr(
                sim.swim_step_impl, static_argnums=(3,)
            )(state, net, key, params)

        if recv_merge is None:
            closed = _trace()
        else:
            with sim._force_recv_merge(recv_merge):
                closed = _trace()
        dims = dict(N=n)
        floor = min_elems if min_elems is not None else n * n
    entry = f"{backend}_step"
    return temp_census(closed, dims=dims, min_elems=floor, entry=entry)


def annotate_packed(rows: list[dict]) -> list[dict]:
    """Add the packed-dtype column to temp-census rows: what each
    temporary would cost as a bit-packed plane (``ops/bitpack.py``
    layout — bool at 1 bit/element in uint32 words; other dtypes are
    already at their packed width).  A before/after footprint diff is
    then one command: rows whose ``bytes_each`` exceeds their
    ``packed_bytes_each`` are the remaining packing entitlement."""
    for row in rows:
        if row["dtype"] == "bool":
            words = -(-row["elems_each"] // 32)
            row["packed_dtype"] = "uint32[bits]"
            row["packed_bytes_each"] = words * 4
        else:
            row["packed_dtype"] = row["dtype"]
            row["packed_bytes_each"] = row["bytes_each"]
    return rows


_TEMP_SORTS = {
    "bytes": lambda r: (-r["bytes_each"] * r["count"], r["primitive"]),
    "count": lambda r: (-r["count"], -r["bytes_each"], r["primitive"]),
    "elems": lambda r: (-r["elems_each"] * r["count"], r["primitive"]),
}


def sort_temp_rows(
    rows: list[dict], sort: str = "bytes", top: int | None = None
) -> list[dict]:
    """Order temp-census rows by ``sort`` (see _TEMP_SORTS) and keep
    the first ``top`` (None = all)."""
    rows = sorted(rows, key=_TEMP_SORTS[sort])
    return rows if top is None else rows[:top]


def collective_rows(n: int, mesh: int) -> list[dict]:
    """Collective-census rows of the mesh-sharded dense step at the
    given mesh size, via the partitioning auditor's walker.  Needs
    ``mesh`` local devices (the caller provisions CPU virtual devices
    before jax's backend initializes)."""
    from ringpop_tpu.analysis.contracts import _trace_and_lower
    from ringpop_tpu.analysis.partitioning import collective_census
    from ringpop_tpu.analysis.registry import _build_sharded_step

    built = _build_sharded_step("dense", n=n, mesh=mesh)
    _, _, _, compiled = _trace_and_lower(built, lower=False,
                                         compile_hlo=True)
    return collective_census(compiled.as_text(), dims=built.dims)


def report(txt: str, header: str) -> None:
    tallies, elems = census_text(txt)
    print(f"{header}  module: {len(txt) / 1e6:.1f} MB text")
    print(f"{'op [shape]':45s} {'count':>5s} {'Melems':>9s}")
    for key_, cnt in sorted(tallies.items(), key=lambda kv: -elems.get(kv[0], 0)):
        print(f"{key_:45s} {cnt:5d} {elems.get(key_, 0) / 1e6:9.1f}")
    total_sort = sum(v for k, v in elems.items() if k.startswith("sort"))
    print(f"total sorted elements/tick: {total_sort / 1e6:.1f} M")


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--backend", choices=("delta", "dense"), default="delta")
    ap.add_argument(
        "--recv-merge",
        choices=("sorted", "scatter", "pallas"),
        default=None,
        help="dense only: override the RINGPOP_RECV_MERGE lowering",
    )
    ap.add_argument(
        "--temps",
        action="store_true",
        help="emit the temporary-tensor census (one JSON row per "
             "distinct [N, N]-class intermediate: shape, dtype, "
             "producing primitive) instead of the op tallies",
    )
    ap.add_argument(
        "--min-elems",
        type=int,
        default=None,
        help="--temps threshold override (default: N*C on delta, "
             "N*N on dense)",
    )
    ap.add_argument(
        "--sort",
        choices=tuple(_TEMP_SORTS),
        default="bytes",
        help="--temps row order (default bytes: total footprint "
             "descending)",
    )
    ap.add_argument(
        "--top",
        type=int,
        default=None,
        metavar="K",
        help="--temps: emit only the first K rows after sorting",
    )
    ap.add_argument(
        "--collectives",
        action="store_true",
        help="emit the collective census of the mesh-sharded dense "
             "step's partitioned HLO (one JSON row per collective op x "
             "phase: count, bytes, member-gather flag)",
    )
    ap.add_argument("--mesh", type=int, default=2,
                    help="--collectives mesh size (CPU virtual devices)")
    ap.add_argument("n", nargs="?", type=int, default=None)
    ap.add_argument("capacity", nargs="?", type=int, default=256)
    args = ap.parse_args()

    if args.collectives:
        import json

        from ringpop_tpu.utils import provision_virtual_devices

        provision_virtual_devices(args.mesh)
        n = args.n if args.n is not None else 64
        for row in collective_rows(n, args.mesh):
            print(json.dumps(row), flush=True)
        return

    if args.temps:
        import json

        n = args.n if args.n is not None else (
            65536 if args.backend == "delta" else 8192
        )
        rows = temp_rows(
            args.backend, n, args.capacity, args.recv_merge, args.min_elems
        )
        for row in sort_temp_rows(
            annotate_packed(rows), sort=args.sort, top=args.top
        ):
            print(json.dumps(row), flush=True)
        return

    if args.backend == "delta":
        n = args.n if args.n is not None else 65536
        report(lower_delta(n, args.capacity), f"delta n={n} capacity={args.capacity}")
    else:
        n = args.n if args.n is not None else 8192
        form = args.recv_merge or "env default"
        report(lower_dense(n, args.recv_merge), f"dense n={n} recv_merge={form}")


if __name__ == "__main__":
    main()

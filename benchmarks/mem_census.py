"""AOT memory-footprint census of the compiled simulation programs.

The round-5 headline failure was a delta program killing the TPU
worker at n=65,536 with NO footprint instrumentation anywhere in the
repo — we optimized compiled programs we could not measure the memory
shape of.  This census is that instrument: it lowers and compiles each
program ahead of time (``jit(...).lower(...).compile()``) and reads
XLA's ``memory_analysis()`` — argument / output / temporary / aliased
bytes, and the peak — WITHOUT running anything, so an oversized
program is diagnosed on whatever host compiles it instead of
discovered as a dead worker.

Programs censused (one JSON line per (program, backend, n, R)):

* ``swim_run`` / ``delta_run``   — the plain multi-tick scans;
* ``run_scenario``               — the scenario engine's event scan;
* ``run_sweep``                  — the vmapped R-replica sweep, the
  check on sweep.py's memory model: peak grows ~R x state (the
  donated carry gains a replica axis), NOT R x program temporaries.

``peak_bytes`` is XLA's own peak when the backend reports one
(``peak_memory_in_bytes``, TPU) and otherwise the derived
``argument + output + temp - alias`` (donated buffers counted once) —
the field to watch when triaging a worker crash: it is the HBM the
program needs, not the HBM the arrays occupy.

Usage:  python -m benchmarks.mem_census [--backend dense|delta|both]
            [--n 1024[,4096,...]] [--replicas 8] [--ticks 8]
            [--capacity 64] [--programs run,scenario,sweep]
            [--segment-ticks S] [--mesh D] [--latency B]

``--segment-ticks S`` adds the streamed runner's S-tick segment
program (scenarios/stream.py) next to each whole-horizon
``run_scenario`` row: its footprint is a function of (backend, n, S)
only — flat in total ``--ticks`` — which is what makes million-tick
soaks compile- and memory-feasible.

``tests/test_mem_census.py`` pins the dense-vs-delta peak ordering at
a fixed shape as a slow regression test.
"""

from __future__ import annotations

import argparse
import json

from ringpop_tpu.utils import pin_cpu_if_requested

pin_cpu_if_requested()

import jax  # noqa: E402  (platform pin must precede backend init)
import jax.numpy as jnp  # noqa: E402

# the canonical census scenario: one kill + a loss step, so the event
# tensors and the loss schedule are non-degenerate without changing
# the program's asymptotic shape
def _spec_dict(ticks: int) -> dict:
    return {
        "ticks": ticks,
        "events": [
            {"at": ticks // 4, "op": "kill", "node": 0},
            {"at": ticks // 2, "op": "loss", "p": 0.05},
        ],
    }


# The memory_analysis flattening now lives in the dispatch ledger
# (obs/ledger.py) — the same field set every ledgered dispatch records,
# so a census row and a runtime ledger row diff key-for-key.
from ringpop_tpu.obs.ledger import memory_row  # noqa: E402


def _census(jitted, *args, **kwargs) -> dict[str, int]:
    return memory_row(jitted.lower(*args, **kwargs).compile())


def _dense_fixture(n: int):
    from ringpop_tpu.models import swim_sim as sim

    params = sim.SwimParams(loss=0.01)
    return sim.init_state(n), sim.make_net(n), params


def _delta_fixture(n: int, capacity: int):
    from ringpop_tpu.models import swim_delta as sd
    from ringpop_tpu.models import swim_sim as sim

    params = sd.DeltaParams(
        swim=sim.SwimParams(loss=0.01), wire_cap=16, claim_grid=64
    )
    return sd.init_delta(n, capacity=capacity), sim.make_net(n), params


def census_run(backend: str, n: int, ticks: int, capacity: int) -> dict:
    """swim_run / delta_run: the plain multi-tick scan."""
    key = jax.random.PRNGKey(0)
    if backend == "delta":
        from ringpop_tpu.models import swim_delta as sd

        state, net, params = _delta_fixture(n, capacity)
        row = _census(sd.delta_run, state, net, key, params, ticks)
        name = "delta_run"
    else:
        from ringpop_tpu.models import swim_sim as sim

        state, net, params = _dense_fixture(n)
        row = _census(sim.swim_run, state, net, key, params, ticks)
        name = "swim_run"
    return {"program": name, "backend": backend, "n": n, "replicas": 1,
            "ticks": ticks, **row}


def _compiled_scenario(n: int, ticks: int, base_loss: float):
    from ringpop_tpu.scenarios.compile import compile_spec
    from ringpop_tpu.scenarios.spec import ScenarioSpec

    spec = ScenarioSpec.from_dict(_spec_dict(ticks))
    return spec, compile_spec(spec, n, base_loss=base_loss)


def _traffic_fixture(n: int, buckets: int, m: int = 128):
    from ringpop_tpu.models import checksum as cksum
    from ringpop_tpu.traffic.workloads import compile_traffic

    return compile_traffic(
        {"keys_per_tick": m, "pool": 4 * m, "latency_buckets": buckets},
        n,
        cksum.default_addresses(n),
    )


def census_scenario(
    backend: str, n: int, ticks: int, capacity: int,
    segment_ticks: int | None = None,
    latency_buckets: int = 0,
) -> dict:
    """run_scenario: the event-applying scan (runner._scenario_scan).

    With ``segment_ticks=S`` the census covers the STREAMED runner's
    program instead (scenarios/stream.py): the S-shaped segment scan
    with a traced tick0 offset — the one executable a whole soak
    re-dispatches.  Its footprint depends only on (backend, n, S),
    never on the total tick count: the CPU-side deliverable of the
    streaming rework, pinned by tests/test_mem_census.py.

    ``latency_buckets=B`` co-compiles a traffic workload with the SLO
    latency plane on: the program stacks a [ticks, B] histogram plane
    next to the scalar telemetry, so the whole-horizon row's OUTPUT
    bytes grow linearly in T (B int32 counters per tick) while the
    S-shaped segment program's bytes stay flat — the pair the latency
    footprint pin asserts (tests/test_latency.py)."""
    from ringpop_tpu.scenarios import runner

    if backend == "delta":
        state, net, params = _delta_fixture(n, capacity)
    else:
        state, net, params = _dense_fixture(n)
    swim = params.swim if backend == "delta" else params
    _, compiled = _compiled_scenario(n, ticks, swim.loss)
    ct = _traffic_fixture(n, latency_buckets) if latency_buckets else None
    program = "run_scenario+latency" if latency_buckets else "run_scenario"
    traffic_kw = dict(
        traffic=ct.static if ct is not None else None,
    )
    tr_tensors = ct.tensors if ct is not None else None
    if segment_ticks is None:
        keys = jax.random.split(jax.random.PRNGKey(0), ticks)
        row = _census(
            runner._scenario_scan,
            state,
            net.up,
            net.responsive,
            jnp.zeros((n,), jnp.int32),
            None,  # period (no gray events in the census spec)
            compiled.ev_tick,
            compiled.ev_kind,
            compiled.ev_node,
            compiled.p_tick,
            compiled.p_gid,
            compiled.loss,
            keys,
            tr_tensors,
            params=params,
            has_revive=compiled.has_revive,
            **traffic_kw,
        )
        return {"program": program, "backend": backend, "n": n,
                "replicas": 1, "ticks": ticks, **row}
    s = min(segment_ticks, ticks)
    keys = jax.random.split(jax.random.PRNGKey(0), s)
    row = _census(
        runner._scenario_scan,
        state,
        net.up,
        net.responsive,
        jnp.zeros((n,), jnp.int32),
        None,  # period (no gray events in the census spec)
        compiled.ev_tick,
        compiled.ev_kind,
        compiled.ev_node,
        compiled.p_tick,
        compiled.p_gid,
        compiled.loss[:s],
        keys,
        tr_tensors,
        jnp.int32(0),  # tick0 (traced: any segment offset, same program)
        params=params,
        has_revive=compiled.has_revive,
        **traffic_kw,
    )
    return {"program": program, "backend": backend, "n": n,
            "replicas": 1, "ticks": ticks, "segment_ticks": s, **row}


def census_sharded_step(n: int, mesh: int) -> dict:
    """The mesh-sharded dense step (parallel/mesh.py) through the same
    memory_analysis lens: the per-chip footprint story row sharding is
    supposed to buy (argument bytes split across the mesh while the
    collective all-gathers keep full-plane temporaries alive — the
    partitioning auditor's census names which phases; this row prices
    them)."""
    from ringpop_tpu.analysis.contracts import _trace_and_lower
    from ringpop_tpu.analysis.registry import _build_sharded_step

    built = _build_sharded_step("dense", n=n, mesh=mesh)
    _, _, _, compiled = _trace_and_lower(built, lower=False,
                                         compile_hlo=True)
    return {"program": "sharded_step", "backend": "dense", "n": n,
            "replicas": 1, "mesh": mesh, **memory_row(compiled)}


def census_sweep(
    backend: str, n: int, ticks: int, capacity: int, replicas: int
) -> dict:
    """run_sweep: the vmapped R-replica scan (sweep._sweep_scan)."""
    from ringpop_tpu.scenarios import sweep as ssweep

    if backend == "delta":
        state, net, params = _delta_fixture(n, capacity)
    else:
        state, net, params = _dense_fixture(n)
    swim = params.swim if backend == "delta" else params
    spec, _ = _compiled_scenario(n, ticks, swim.loss)
    cs = ssweep.compile_sweep(
        spec, n, replicas=replicas, base_loss=swim.loss
    )
    key = jax.random.PRNGKey(0)
    rkeys = list(jax.random.split(key, replicas))
    keys = ssweep.sweep_key_schedule(rkeys, cs)
    row = _census(
        ssweep._sweep_scan,
        ssweep._broadcast_replicas(state, replicas),
        ssweep._broadcast_replicas(net.up, replicas),
        ssweep._broadcast_replicas(net.responsive, replicas),
        ssweep._broadcast_replicas(jnp.zeros((n,), jnp.int32), replicas),
        None,  # period (no gray events in the census spec)
        cs.ev_tick,
        cs.ev_kind,
        cs.ev_node,
        cs.base.p_tick,
        cs.base.p_gid,
        cs.loss,
        keys,
        params=params,
        has_revive=cs.base.has_revive,
    )
    return {"program": "run_sweep", "backend": backend, "n": n,
            "replicas": replicas, "ticks": ticks, **row}


def run(
    *,
    backends=("dense", "delta"),
    ns=(1024,),
    ticks: int = 8,
    capacity: int = 64,
    replicas: int = 8,
    programs=("run", "scenario", "sweep"),
    segment_ticks: int | None = None,
    latency_buckets: int = 0,
    mesh: int | None = None,
) -> list[dict]:
    """Every requested census row (the test entry point).

    ``segment_ticks`` adds the streamed segment program's row next to
    every whole-horizon ``run_scenario`` row — the pair that shows the
    segment footprint flat in total T while the whole-trace output
    grows with it.  ``latency_buckets=B`` additionally censuses the
    traffic+latency-plane variant of each scenario row (the
    ``run_scenario+latency`` program) — the compiled-bytes cost of the
    [ticks, B] histogram planes."""
    rows = []
    for backend in backends:
        for n in ns:
            if "run" in programs:
                rows.append(census_run(backend, n, ticks, capacity))
            if "scenario" in programs:
                rows.append(census_scenario(backend, n, ticks, capacity))
                if segment_ticks is not None:
                    rows.append(
                        census_scenario(
                            backend, n, ticks, capacity,
                            segment_ticks=segment_ticks,
                        )
                    )
                if latency_buckets:
                    rows.append(
                        census_scenario(
                            backend, n, ticks, capacity,
                            latency_buckets=latency_buckets,
                        )
                    )
                    if segment_ticks is not None:
                        rows.append(
                            census_scenario(
                                backend, n, ticks, capacity,
                                segment_ticks=segment_ticks,
                                latency_buckets=latency_buckets,
                            )
                        )
            if "sweep" in programs:
                rows.append(
                    census_sweep(backend, n, ticks, capacity, replicas)
                )
            if mesh is not None and backend == "dense":
                rows.append(census_sharded_step(n, mesh))
    for row in rows:
        row["platform"] = jax.default_backend()
    return rows


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--backend", choices=("dense", "delta", "both"),
                    default="both")
    ap.add_argument("--n", default="1024",
                    help="comma-separated cluster sizes")
    ap.add_argument("--ticks", type=int, default=8)
    ap.add_argument("--capacity", type=int, default=64,
                    help="delta divergence slots per viewer")
    ap.add_argument("--replicas", type=int, default=8,
                    help="sweep replica count (R)")
    ap.add_argument("--programs", default="run,scenario,sweep",
                    help="comma list of run,scenario,sweep")
    ap.add_argument("--segment-ticks", type=int, default=None, metavar="S",
                    help="also census the streamed S-tick segment program "
                         "next to each run_scenario row (its footprint is "
                         "flat in --ticks; scenarios/stream.py)")
    ap.add_argument("--mesh", type=int, default=None, metavar="D",
                    help="also census the mesh-sharded dense step at a "
                         "D-device mesh (parallel/mesh.py; needs D local "
                         "or virtual devices)")
    ap.add_argument("--latency", type=int, default=0, metavar="B",
                    help="also census the traffic + SLO-latency-plane "
                         "scenario program with B log2 buckets "
                         "(run_scenario+latency rows: the [ticks, B] "
                         "histogram planes' compiled-bytes cost; "
                         "traffic/latency.py)")
    args = ap.parse_args()

    backends = ("dense", "delta") if args.backend == "both" else (args.backend,)
    ns = tuple(int(x) for x in args.n.split(","))
    programs = tuple(args.programs.split(","))
    for row in run(backends=backends, ns=ns, ticks=args.ticks,
                   capacity=args.capacity, replicas=args.replicas,
                   programs=programs, segment_ticks=args.segment_ticks,
                   latency_buckets=args.latency, mesh=args.mesh):
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()

"""Failure-model sweeps: detection/heal distributions per family.

The SWIM paper's own evaluation method — multi-trial distributions of
detection and dissemination time — applied to the failure families
real deployments die from (scenarios/faults.py): one-way link loss,
flap storms, gray failures, rolling deploys, per-link latency.  Each
family runs as ONE vmapped ``run_sweep`` dispatch of R replicas
(per-replica PRNG seeds; the flap family also staggers its storm
phase via the ``flap_jitter`` batch axis) and prints the
detection-tick / heal-tick distributions — the tables BASELINE.md
records.

``--relay-ab`` runs the VERDICT-item-5 experiment instead: ticks to
re-convergence on a divergence-heavy scenario (kill + burst loss + a
one-way blackhole that forces probes through the ping-req relay) with
``SwimParams.relay_full_sync`` off vs on — bounding what the relay's
historical full-sync omission costs.
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def _fam_specs(n: int, ticks: int):
    half = list(range(n // 2, n))
    quarter = list(range(n // 4))
    # the rolling wave restarts as much of the upper half as fits the
    # horizon (last revive at 10 + (len-1)*2 + 2 must stay < ticks), so
    # run_all --sim-n overrides scale instead of failing validation
    wave = half[: max(1, (ticks - 14) // 2)]
    return {
        "link_loss": {
            "ticks": ticks,
            "events": [
                {"at": 10, "op": "kill", "node": n - 1},
                {"at": 12, "op": "link_loss", "src": quarter,
                 "dst": [n - 2, n - 3], "p": 0.9,
                 "until": int(ticks * 0.7)},
            ],
        },
        "flap_storm": {
            "ticks": ticks,
            "events": [
                {"at": 10, "op": "flap",
                 "nodes": [n - 2, n - 3, n - 4], "until": int(ticks * 0.6),
                 "down": 3, "up": 4, "stagger": 2},
            ],
        },
        "gray": {
            "ticks": ticks,
            "events": [
                {"at": 8, "op": "gray", "nodes": quarter, "factor": 6,
                 "until": int(ticks * 0.7)},
                {"at": 12, "op": "kill", "node": n - 1},
            ],
        },
        "rolling_restart": {
            "ticks": ticks,
            "events": [
                {"at": 10, "op": "rolling_restart", "nodes": wave,
                 "down": 2, "every": 2},
            ],
        },
        "delay": {
            "ticks": ticks,
            "events": [
                {"at": 8, "op": "delay", "src": quarter,
                 "dst": half, "delay": 2, "jitter": 3,
                 "until": int(ticks * 0.7)},
                {"at": 12, "op": "kill", "node": n - 1},
            ],
        },
    }


def run_family_sweeps(n: int, ticks: int, replicas: int, seed: int):
    from ringpop_tpu.models.cluster import SimCluster
    from ringpop_tpu.models.swim_sim import SwimParams

    rows = []
    for fam, spec in _fam_specs(n, ticks).items():
        c = SimCluster(n, SwimParams(suspicion_ticks=12), seed=seed)
        kw = {}
        if fam == "flap_storm":
            kw["flap_jitter"] = [2 * (r % 4) for r in range(replicas)]
        t0 = time.perf_counter()
        strace = c.run_sweep(spec, replicas, **kw)
        wall = time.perf_counter() - t0
        rep = strace.summary()
        det, heal = strace.detect_ticks(), strace.heal_ticks()
        # first-suspect tick: fast flaps (down < suspicion timeout)
        # never escalate to faulty — the suspect column is where a
        # storm that evades detection still shows up
        sus = strace.detect_ticks(metric="suspects_declared")
        row = {
            "family": fam,
            "n": n,
            "ticks": ticks,
            "replicas": replicas,
            "wall_s": round(wall, 2),
            "suspected": int((sus >= 0).sum()),
            "detected": rep["replicas"]["detected"],
            "healed": rep["replicas"]["healed"],
            "converged_final": rep["replicas"]["converged_final"],
            "suspect": _dist(sus),
            "detect": _dist(det),
            "heal": _dist(heal),
        }
        rows.append(row)
        print(json.dumps(row), flush=True)
    print("\n| family | suspect p50 | detected | detect p50/p95 | healed | "
          "heal p50/p95 | converged |")
    print("|---|---|---|---|---|---|---|")
    for r in rows:
        print(
            f"| {r['family']} | {r['suspect']['p50']} "
            f"| {r['detected']}/{r['replicas']} "
            f"| {r['detect']['p50']}/{r['detect']['p95']} "
            f"| {r['healed']}/{r['replicas']} "
            f"| {r['heal']['p50']}/{r['heal']['p95']} "
            f"| {r['converged_final']}/{r['replicas']} |"
        )
    return rows


def _dist(ticks: np.ndarray) -> dict:
    got = ticks[ticks >= 0]
    if not got.size:
        return {"p50": -1, "p95": -1, "min": -1, "max": -1}
    return {
        "min": int(got.min()),
        "p50": int(np.percentile(got, 50)),
        "p95": int(np.percentile(got, 95)),
        "max": int(got.max()),
    }


def run_traffic_scorecard(
    n: int,
    ticks: int,
    seed: int,
    segment_ticks: int | None = None,
    keys_per_tick: int = 256,
    buckets: int = 16,
):
    """Per-failure-family SERVING scorecard: goodput, request-latency
    p50/p95/p99, and retry amplification, per backend, streamed.

    Couples the PR-10 failure families to the SLO questions an operator
    asks of the serving plane (ROADMAP item 3): each family's scenario
    co-runs a zipf workload with the latency plane on
    (``traffic/latency.py`` — link RTTs + RETRY_SCHEDULE backoff + gray
    duty timeouts), streamed as S-tick segments (O(segment) host
    memory, PR 8), on the dense AND the delta backend (per-link delay
    rides the delta in-flight claim lanes).  Families whose scenario
    needs in-scan revive (flap storms, rolling deploys) stay
    dense-only — the delta revive is a host-side row op."""
    from ringpop_tpu.models.cluster import SimCluster
    from ringpop_tpu.models.swim_sim import SwimParams
    from ringpop_tpu.traffic.latency import plane_stats

    if segment_ticks is None:
        segment_ticks = max(ticks // 4, 1)
    wl = {
        "kind": "zipf",
        "keys_per_tick": keys_per_tick,
        "pool": 8 * keys_per_tick,
        "latency_buckets": buckets,
    }
    rows = []
    for fam, spec in _fam_specs(n, ticks).items():
        for backend in ("dense", "delta"):
            kw = {} if backend == "dense" else {"capacity": min(2 * n, 1024)}
            c = SimCluster(
                n, SwimParams(suspicion_ticks=12), seed=seed,
                backend=backend, **kw,
            )
            t0 = time.perf_counter()
            try:
                trace = c.run_scenario(
                    spec, traffic=dict(wl), segment_ticks=segment_ticks
                )
            except NotImplementedError as e:
                row = {"family": fam, "backend": backend, "n": n,
                       "skipped": str(e).splitlines()[0]}
                rows.append(row)
                print(json.dumps(row), flush=True)
                continue
            wall = time.perf_counter() - t0
            m = trace.metrics
            lookups = int(m["lookups"].sum())
            delivered = int(m["delivered"].sum())
            sends = (
                int(m["proxy_sends"].sum())
                + int(m["proxy_retries"].sum())
                + int(m["handled_local"].sum())
            )
            agg = plane_stats(trace)
            row = {
                "family": fam,
                "backend": backend,
                "n": n,
                "ticks": ticks,
                "segment_ticks": segment_ticks,
                "keys_per_tick": keys_per_tick,
                "wall_s": round(wall, 2),
                "goodput": round(delivered / max(lookups, 1), 4),
                "lat_ms": {k: agg[k] for k in ("median", "p95", "p99")},
                "lat_ticks_p99": round(agg["p99"] / 200.0, 2),
                "amplification": round(sends / max(delivered, 1), 3),
                "gray_timeouts": int(m["gray_timeouts"].sum()),
                "send_errors": int(m["send_errors"].sum()),
                "failed": int(m["proxy_failed"].sum()),
            }
            rows.append(row)
            print(json.dumps(row), flush=True)
    print("\n| family | backend | goodput | lat p50/p95/p99 ms "
          "| amplification | gray timeouts | failed |")
    print("|---|---|---|---|---|---|---|")
    for r in rows:
        if "skipped" in r:
            print(f"| {r['family']} | {r['backend']} | — (skipped: "
                  f"{r['skipped'][:40]}...) | | | | |")
            continue
        lm = r["lat_ms"]
        print(
            f"| {r['family']} | {r['backend']} | {r['goodput']:.3f} "
            f"| {lm['median']:.0f}/{lm['p95']:.0f}/{lm['p99']:.0f} "
            f"| {r['amplification']:.2f} | {r['gray_timeouts']} "
            f"| {r['failed']} |"
        )
    return rows


def run_relay_ab(n: int, ticks: int, seeds: int):
    """Heal-tick A/B of SwimParams.relay_full_sync on a scenario that
    drives probes through the relay while views diverge."""
    from ringpop_tpu.models.cluster import SimCluster
    from ringpop_tpu.models.swim_sim import SwimParams

    spec = {
        "ticks": ticks,
        "events": [
            {"at": 2, "op": "kill", "node": n - 1},
            {"at": 4, "op": "loss", "p": 0.3},
            {"at": 8, "op": "link_loss",
             "src": list(range(n // 3)),
             "dst": list(range(2 * (n // 3), n - 1)), "p": 0.95,
             "until": int(ticks * 0.66)},
            {"at": int(ticks * 0.66), "op": "loss", "p": 0.0},
        ],
    }
    out = {}
    for label, flag in (("off", False), ("on", True)):
        heals, fs = [], []
        for s in range(seeds):
            c = SimCluster(
                n,
                SwimParams(suspicion_ticks=12, relay_full_sync=flag),
                seed=100 + s,
            )
            trace = c.run_scenario(spec)
            conv = trace.converged
            # first tick from which converged holds through the end
            rev = conv[::-1]
            suffix = len(conv) if rev.all() else int(np.argmax(~rev))
            heals.append(ticks - suffix if suffix > 0 else -1)
            fs.append(int(trace.metrics["relay_full_syncs"].sum()))
        out[label] = {"heal_ticks": heals, "relay_full_syncs": fs}
        print(json.dumps({"relay_full_sync": label, "n": n, **out[label]}),
              flush=True)
    return out


def run(n: int = 32, ticks: int = 60, replicas: int = 4):
    """run_all entry point: the family sweeps at a CI-sized config."""
    for row in run_family_sweeps(n, ticks, replicas, seed=7):
        yield row


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-n", type=int, default=48)
    ap.add_argument("--ticks", type=int, default=80)
    ap.add_argument("--replicas", type=int, default=8)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--relay-ab", action="store_true",
                    help="run the relay full-sync A/B instead of the "
                         "family sweeps")
    ap.add_argument("--relay-seeds", type=int, default=3)
    ap.add_argument("--traffic", action="store_true",
                    help="run the per-family SERVING scorecard instead: "
                         "goodput / latency p50-p95-p99 / retry "
                         "amplification per backend, streamed "
                         "(SLO latency plane, traffic/latency.py)")
    ap.add_argument("--segment-ticks", type=int, default=None,
                    help="--traffic: stream segment size (default ticks/4)")
    ap.add_argument("--keys-per-tick", type=int, default=256)
    args = ap.parse_args(argv)
    if args.relay_ab:
        run_relay_ab(args.n, args.ticks, args.relay_seeds)
    elif args.traffic:
        run_traffic_scorecard(
            args.n, args.ticks, args.seed,
            segment_ticks=args.segment_ticks,
            keys_per_tick=args.keys_per_tick,
        )
    else:
        run_family_sweeps(args.n, args.ticks, args.replicas, args.seed)


if __name__ == "__main__":
    main()

"""Race the row-searchsorted lowerings on the ambient accelerator.

The delta step's fixed cost is dominated by vmapped searchsorted over
the [N, C] subject tables (see swim_delta._row_searchsorted and
benchmarks/hlo_census.py).  This times each candidate lowering at the
shapes the step actually uses, plus the batched row scatter that could
replace the slot->claim inverse search, so the _WIDE_METHOD choice is
a measurement, not a guess (usage:
python -m benchmarks.profile_searchsorted [n]).
"""

from __future__ import annotations

import sys
import time

import jax

from ringpop_tpu.utils import enable_compilation_cache, pin_cpu_if_requested

pin_cpu_if_requested()
enable_compilation_cache()

import jax.numpy as jnp
import numpy as np


def bench(name, fn, *args, reps=10):
    t0 = time.perf_counter()
    out = fn(*args)
    jax.block_until_ready(out)
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    leaves = jax.tree_util.tree_leaves(out)
    _ = jax.device_get(leaves[0].ravel()[0])  # unfakeable barrier
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / reps * 1e3
    print(f"{name:42s} {dt:8.2f} ms   (compile {compile_s:.1f}s)", flush=True)
    return dt


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 65536
    print(f"platform={jax.default_backend()} n={n}", flush=True)
    rng = np.random.default_rng(0)

    for c, k in ((256, 64), (256, 16), (64, 64), (64, 16), (256, 256)):
        a = jnp.asarray(np.sort(rng.integers(0, 1 << 20, (n, c)), axis=1))
        v = jnp.asarray(np.sort(rng.integers(0, 1 << 20, (n, k)), axis=1))
        print(f"-- tables [N,{c}] x queries [N,{k}]")
        for method in ("sort", "scan_unrolled"):
            f = jax.jit(jax.vmap(
                lambda ar, vr, m=method: jnp.searchsorted(ar, vr, method=m)))
            bench(f"searchsorted {method}", f, a, v)
        if c * k * n * 4 <= 2 << 30:
            f = jax.jit(jax.vmap(
                lambda ar, vr: jnp.searchsorted(ar, vr, method="compare_all")))
            bench("searchsorted compare_all", f, a, v)
        from ringpop_tpu.ops.searchsorted_pallas import row_searchsorted_pallas

        interp = jax.default_backend() == "cpu"
        label = "searchsorted pallas" + (" (interpret!)" if interp else "")
        bench(
            label,
            lambda ar, vr: row_searchsorted_pallas(ar, vr, interpret=interp),
            a, v,
        )

    # batched unique-index row scatter (candidate slot->claim inverse)
    c, k = 256, 64
    x = jnp.zeros((n, c), jnp.int32)
    pos = jnp.asarray(
        np.sort(rng.permuted(np.tile(np.arange(c), (n, 1)), axis=1)[:, :k],
                axis=1))
    val = jnp.asarray(rng.integers(0, 1 << 20, (n, k)), dtype=jnp.int32)
    rows = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32)[:, None], (n, k))

    def scat(x, rows, pos, val):
        return x.at[rows, pos].set(val, mode="drop", unique_indices=True)

    bench(f"row scatter [N,{k}] -> [N,{c}]", jax.jit(scat), x, rows, pos, val)

    # the row sort itself, for scale
    bench(f"row sort [N,{c}]", jax.jit(lambda t: jnp.sort(t, axis=1)), a)


if __name__ == "__main__":
    main()

"""Staggered vs lockstep protocol periods — the fidelity bound.

The reference's gossip loop is per-node self-scheduling: each node's
first tick lands randomly inside [0, minProtocolPeriod) and later ticks
re-arm per node with adaptive delay (gossip.js:38-51), so real protocol
periods are UNSYNCHRONIZED.  Both sim backends advance all nodes in
lockstep.  This bench measures what that costs: the dense step's
``phase_mod=P`` mode subdivides the protocol period into P sub-ticks
and lets only one residue class of nodes initiate probes per sub-tick
(timers/witness service stay per-sub-tick, i.e. wall-clock — exactly
the reference's semantics), which is the staggered model at offset
granularity 1/P.

Scenario per seed: converged n-node cluster at 1% loss, kill one node
after a 2-period warmup, then measure (in PERIODS, i.e. sub-ticks / P):

* detection: periods from the kill until the first faulty declaration;
* convergence: periods from the kill until every live view is
  identical again, sampled at period boundaries (the kill rumor has
  fully disseminated).

Identical wall-clock protocol constants: suspicion_ticks scales by P.

All S seeds run as ONE vmapped sweep dispatch per phase_mod
(``SimCluster.run_sweep`` — each replica draws its own key, so seeds
are independent trajectories), replacing the old one-dispatch-per-
tick-per-seed host loop.  The horizon is fixed (no early exit inside a
compiled scan); seeds that never detect/converge within it are
reported in ``undetected``/``unconverged``.

Usage: python benchmarks/bench_phase_offset.py [n] [--seeds S] [--P P]
       [--horizon PERIODS]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SUSPICION_PERIODS = 8
WARM_PERIODS = 2


def sweep_runs(
    n: int, phase_mod: int, seeds: int, horizon: int, loss: float = 0.01
) -> list[dict]:
    """All ``seeds`` replicas of the kill experiment in one dispatch."""
    import numpy as np

    from ringpop_tpu.models import swim_sim as sim
    from ringpop_tpu.models.cluster import SimCluster
    from ringpop_tpu.scenarios.spec import ScenarioSpec

    params = sim.SwimParams(
        loss=loss,
        suspicion_ticks=SUSPICION_PERIODS * phase_mod,
        phase_mod=phase_mod,
    )
    warm = WARM_PERIODS * phase_mod  # warm/converge under loss, in scan
    kill_tick = warm
    ticks = warm + horizon * phase_mod
    spec = ScenarioSpec.from_dict(
        {
            "ticks": ticks,
            "events": [{"at": kill_tick, "op": "kill", "node": n // 3}],
        }
    )
    cluster = SimCluster(n, params, seed=0, backend="dense")
    trace = cluster.run_sweep(spec, seeds)

    out = []
    fd = trace.metrics["faulty_declared"]
    for r in range(seeds):
        hits = np.flatnonzero(fd[r, kill_tick:] > 0)
        detect = int(hits[0]) + 1 if hits.size else None
        converge = None
        if detect is not None:
            # the old loop sampled convergence at period boundaries
            # ((ticks since kill) % P == 0) once detection had fired
            for t in range(kill_tick + detect - 1, ticks):
                since = t - kill_tick + 1
                if since % phase_mod == 0 and trace.converged[r, t]:
                    converge = since
                    break
        out.append(
            {
                "n": n,
                "phase_mod": phase_mod,
                "seed": r,
                "detect_periods": (
                    None if detect is None else detect / phase_mod
                ),
                "converge_periods": (
                    None if converge is None else converge / phase_mod
                ),
            }
        )
    return out


def main() -> None:
    from ringpop_tpu.utils import enable_compilation_cache, pin_cpu_if_requested

    pin_cpu_if_requested()
    enable_compilation_cache()

    n = int(sys.argv[1]) if len(sys.argv) > 1 and not sys.argv[1].startswith("-") else 1024
    seeds = 5
    if "--seeds" in sys.argv:
        seeds = int(sys.argv[sys.argv.index("--seeds") + 1])
    mods = [1, 4]
    if "--P" in sys.argv:
        mods = [1, int(sys.argv[sys.argv.index("--P") + 1])]
    horizon = 48  # periods after the kill (the old loop capped at 400
    # with early exit; a compiled scan has no early exit, so the
    # horizon is a knob — raise it if `unconverged` shows up)
    if "--horizon" in sys.argv:
        horizon = int(sys.argv[sys.argv.index("--horizon") + 1])

    for phase_mod in mods:
        t0 = time.perf_counter()
        det, conv, unconverged = [], [], 0
        for r in sweep_runs(n, phase_mod, seeds, horizon):
            print(f"# {r}", file=sys.stderr, flush=True)
            if r["detect_periods"] is not None:
                det.append(r["detect_periods"])
            if r["converge_periods"] is not None:
                conv.append(r["converge_periods"])
            else:
                unconverged += 1
        print(
            json.dumps(
                {
                    "metric": f"phase_offset_P{phase_mod}_n{n}",
                    "detect_periods_mean": round(sum(det) / max(len(det), 1), 2),
                    "converge_periods_mean": round(
                        sum(conv) / max(len(conv), 1), 2
                    ),
                    "seeds": seeds,
                    "detected": len(det),
                    "unconverged": unconverged,
                    "dispatches_per_P": 1,
                    "wall_s": round(time.perf_counter() - t0, 1),
                }
            ),
            flush=True,
        )


if __name__ == "__main__":
    main()

"""Staggered vs lockstep protocol periods — the fidelity bound.

The reference's gossip loop is per-node self-scheduling: each node's
first tick lands randomly inside [0, minProtocolPeriod) and later ticks
re-arm per node with adaptive delay (gossip.js:38-51), so real protocol
periods are UNSYNCHRONIZED.  Both sim backends advance all nodes in
lockstep.  This bench measures what that costs: the dense step's
``phase_mod=P`` mode subdivides the protocol period into P sub-ticks
and lets only one residue class of nodes initiate probes per sub-tick
(timers/witness service stay per-sub-tick, i.e. wall-clock — exactly
the reference's semantics), which is the staggered model at offset
granularity 1/P.

Scenario per seed: converged n-node cluster at 1% loss, kill one node,
then measure (in PERIODS, i.e. sub-ticks / P):

* detection: periods from the kill until the first faulty declaration;
* convergence: periods from the kill until every live view is
  identical again (the kill rumor has fully disseminated).

Identical wall-clock protocol constants: suspicion_ticks scales by P.

Usage: python benchmarks/bench_phase_offset.py [n] [--seeds S] [--P P]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SUSPICION_PERIODS = 8


def one_run(n: int, phase_mod: int, seed: int, loss: float = 0.01) -> dict:
    from ringpop_tpu.models import swim_sim as sim
    from ringpop_tpu.models.cluster import SimCluster

    params = sim.SwimParams(
        loss=loss,
        suspicion_ticks=SUSPICION_PERIODS * phase_mod,
        phase_mod=phase_mod,
    )
    cluster = SimCluster(n, params, seed=seed, backend="dense")
    cluster.tick(2 * phase_mod)  # warm/converge under loss

    victim = n // 3
    cluster.kill(victim)
    detect = None
    ticks = 0
    max_ticks = 400 * phase_mod
    while ticks < max_ticks:
        m = cluster.tick(1)
        ticks += 1
        if detect is None and int(m.get("faulty_declared", 0)) > 0:
            detect = ticks
        if detect is not None and ticks % phase_mod == 0 and cluster.converged():
            break
    return {
        "n": n,
        "phase_mod": phase_mod,
        "seed": seed,
        "detect_periods": None if detect is None else detect / phase_mod,
        "converge_periods": ticks / phase_mod,
    }


def main() -> None:
    from ringpop_tpu.utils import enable_compilation_cache, pin_cpu_if_requested

    pin_cpu_if_requested()
    enable_compilation_cache()

    n = int(sys.argv[1]) if len(sys.argv) > 1 and not sys.argv[1].startswith("-") else 1024
    seeds = 5
    if "--seeds" in sys.argv:
        seeds = int(sys.argv[sys.argv.index("--seeds") + 1])
    mods = [1, 4]
    if "--P" in sys.argv:
        mods = [1, int(sys.argv[sys.argv.index("--P") + 1])]

    for phase_mod in mods:
        t0 = time.perf_counter()
        det, conv = [], []
        for seed in range(seeds):
            r = one_run(n, phase_mod, seed)
            print(f"# {r}", file=sys.stderr, flush=True)
            if r["detect_periods"] is not None:
                det.append(r["detect_periods"])
            conv.append(r["converge_periods"])
        print(
            json.dumps(
                {
                    "metric": f"phase_offset_P{phase_mod}_n{n}",
                    "detect_periods_mean": round(sum(det) / max(len(det), 1), 2),
                    "converge_periods_mean": round(sum(conv) / len(conv), 2),
                    "seeds": seeds,
                    "detected": len(det),
                    "wall_s": round(time.perf_counter() - t0, 1),
                }
            ),
            flush=True,
        )


if __name__ == "__main__":
    main()

"""Benchmark suite (reference: benchmarks/ — SURVEY §2.3).

Each module exposes ``run() -> list[dict]`` where every dict is one result:
``{"metric": str, "value": float, "unit": str, ...}``.  ``run_all.py``
aggregates them (the reference globs bench_*.js, benchmarks/index.js).

Mirrors of the reference harnesses:
  bench_membership_update   large-membership-update.js (1332-member fixture)
  bench_compute_checksum    compute-checksum.js (@100 / @1000 members)
  bench_hashring_churn      add-remove-hashring.js (individual vs bulk)
  bench_find_member         find-member-by-address.js
  bench_join_merge          join-response-merge.js (± same checksum)
  bench_stat_keys           bench_ringpop_stat_{cached,new}_keys.js

TPU simulation configs (BASELINE.md targets):
  bench_sim_convergence     config 3: 10k nodes, 1% loss, suspect→faulty
  bench_partition_heal      config 4: 50/50 netsplit then merge
  bench_ring_rebalance      config 5: churn key-movement
"""

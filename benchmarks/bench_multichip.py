"""Multi-chip gossip-plane race: ring remote-copy vs all-gather vs 1 chip.

Three lowerings of the SAME protocol step, raced at matched (n, ticks)
on the virtual CPU mesh (2 and 4 devices):

* ``unsharded``  — the single-device ``swim_run`` scan (the baseline
  every sharded arm must justify itself against);
* ``gather``     — ``sharded_run(mesh, gossip="gather")``, the PR-15
  lowering whose sorted receiver-merge XLA partitions into **75 full
  member-plane all-gathers per step** at mesh 2;
* ``ring``       — ``sharded_run(mesh)`` (the default), inter-shard
  claims/acks as neighbor-exchange hops (ops/gossip_remote_copy.py),
  member-gather count 0 by construction.

Wall time alone is a weak signal on a CPU host where the device
threads time-share cores, so the race rows ride with a CENSUS row: the
collective byte traffic of each partitioned step program (count x
bytes_each over the audited HLO, the same rows COLLECTIVE_BUDGETS
pins), split into member-plane bytes vs total.  That is the
census-backed bytes-moved-per-step comparison against the 75-plane
all-gather baseline — the number ICI would carry per step on real
hardware, measured without owning a pod.

The MULTICHIP flagship row (``--flagship``) runs the delta backend —
the scale flagship — ring-sharded at n=32,768 (the single-chip dense
peak; see BASELINE.md) for a couple of ticks: an existence-plus-rate
proof that the p2p plane executes at/above the largest n one chip has
carried, not just at test sizes.

    python -m benchmarks.run_all --only multichip     # race + census
    python benchmarks/bench_multichip.py --flagship   # + n=32,768 row
"""

from __future__ import annotations

import os
import sys

# Own-process entry: provision the virtual mesh before jax
# initializes.  Under run_all the aggregator owns the device layout.
if __name__ == "__main__" and "jax" not in sys.modules:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags = f"{flags} --xla_force_host_platform_device_count=8".strip()
    os.environ["XLA_FLAGS"] = flags
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import time  # noqa: E402

import jax  # noqa: E402


def _census_row(n: int, mesh: int) -> dict:
    """Collective byte traffic of the ring vs gather partitioned step.

    Audits the registry's own entries (``sharded_step`` /
    ``sharded_step+gather``) so the numbers are exactly the pinned
    COLLECTIVE_BUDGETS rows' underlying HLO, not a parallel trace."""
    from ringpop_tpu.analysis.contracts import audit_entry
    from ringpop_tpu.analysis.partitioning import collective_counts

    out: dict = {"metric": f"multichip_census_n{n}_mesh{mesh}",
                 "unit": "bytes_per_step"}
    for arm, entry in (("ring", "sharded_step"),
                       ("gather", "sharded_step+gather")):
        r = audit_entry(entry, "dense", n=n, mesh=mesh)
        rows = r.collectives
        cc = collective_counts(rows)
        out[f"{arm}_bytes_per_step"] = int(
            sum(row["count"] * row["bytes_each"] for row in rows))
        out[f"{arm}_member_plane_bytes"] = int(
            sum(row["count"] * row["bytes_each"] for row in rows
                if row["member"]))
        out[f"{arm}_member_gathers"] = int(cc.get("member-gather", 0))
    out["value"] = out["ring_bytes_per_step"]
    return out


def _time_arm(build, ticks: int, warm_reps: int) -> tuple[float, float]:
    """(cold seconds incl. compile, best warm seconds) for one arm.

    ``build`` returns a zero-arg thunk over FRESH state each call —
    the scans donate their state argument, so every rep re-inits."""
    t0 = time.perf_counter()
    jax.block_until_ready(build()())
    cold = time.perf_counter() - t0
    best = float("inf")
    for _ in range(warm_reps):
        thunk = build()
        t0 = time.perf_counter()
        jax.block_until_ready(thunk())
        best = min(best, time.perf_counter() - t0)
    return cold, best


def run(n: int = 256, ticks: int = 16, meshes=(2, 4), census_n: int = 64,
        warm_reps: int = 2, flagship: bool = False) -> list[dict]:
    from ringpop_tpu import parallel
    from ringpop_tpu.models import swim_sim as sim

    params = sim.SwimParams()
    key = jax.random.PRNGKey(42)
    results: list[dict] = []

    avail = len(jax.devices())
    usable = [d for d in meshes if d <= avail]

    def unsharded():
        state, net = sim.init_state(n), sim.make_net(n)
        return lambda: sim.swim_run(state, net, key, params, ticks)

    cold, warm = _time_arm(unsharded, ticks, warm_reps)
    results.append({
        "metric": f"multichip_race_n{n}_unsharded",
        "value": round(warm / ticks * 1e3, 3), "unit": "ms_per_tick",
        "cold_s": round(cold, 2), "ticks": ticks, "devices": 1,
    })

    for d in usable:
        mesh = parallel.make_mesh(d)
        for arm in ("gather", "ring"):
            run_fn = parallel.sharded_run(
                mesh, gossip=None if arm == "ring" else arm)

            def sharded(run_fn=run_fn, mesh=mesh):
                state, net = parallel.shard_cluster(
                    sim.init_state(n), sim.make_net(n), mesh)
                return lambda: run_fn(state, net, key, params, ticks)

            cold, warm = _time_arm(sharded, ticks, warm_reps)
            results.append({
                "metric": f"multichip_race_n{n}_mesh{d}_{arm}",
                "value": round(warm / ticks * 1e3, 3),
                "unit": "ms_per_tick",
                "cold_s": round(cold, 2), "ticks": ticks, "devices": d,
            })

    if 2 <= avail:
        results.append(_census_row(census_n, 2))

    if flagship:
        results.append(flagship_row())
    return results


def flagship_row(n: int = 32768, d: int = 2, ticks: int = 2,
                 capacity: int = 64) -> dict:
    """The MULTICHIP row: delta backend, ring gossip, n at the
    single-chip dense peak, executed over a real device mesh."""
    from ringpop_tpu import parallel
    from ringpop_tpu.models import swim_delta as sd
    from ringpop_tpu.models import swim_sim as sim

    params = sd.DeltaParams()
    mesh = parallel.make_mesh(d)
    t0 = time.perf_counter()
    state = parallel.shard_delta(sd.init_delta(n, capacity=capacity), mesh)
    net = sim.make_net(n)
    run_fn = parallel.sharded_delta_run(mesh)
    state, _ = run_fn(state, net, jax.random.PRNGKey(7), params, ticks)
    jax.block_until_ready(state)
    cold = time.perf_counter() - t0
    t0 = time.perf_counter()
    state2, _ = run_fn(
        parallel.shard_delta(sd.init_delta(n, capacity=capacity), mesh),
        net, jax.random.PRNGKey(7), params, ticks)
    jax.block_until_ready(state2)
    warm = time.perf_counter() - t0
    import numpy as np

    digest = int(np.asarray(state.digest).sum(dtype=np.int64))
    return {
        "metric": f"MULTICHIP_delta_ring_n{n}_dev{d}",
        "value": round(warm / ticks, 2), "unit": "s_per_tick",
        "ticks": ticks, "cold_s": round(cold, 1), "gossip": "ring",
        "capacity": capacity, "digest_sum": digest,
        "compiled_and_ran": True,
    }


def main(argv: list[str]) -> None:
    import json

    n = 256
    if "--n" in argv:
        n = int(argv[argv.index("--n") + 1])
    kwargs = {"n": n, "flagship": "--flagship" in argv}
    if "--flagship-only" in argv:
        print(json.dumps({"bench": "bench_multichip", **flagship_row()}),
              flush=True)
        return
    for row in run(**kwargs):
        print(json.dumps({"bench": "bench_multichip", **row}), flush=True)


if __name__ == "__main__":
    main(sys.argv)

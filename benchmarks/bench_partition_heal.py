"""BASELINE config 4: 50/50 netsplit then merge, checksum agreement.

The reference *documents* partition-merge (faulty members retained so
split-brains can merge, docs/architecture_design.md:19) but its netsplit
test helper was never implemented (test/lib/partition-cluster.js:59-61).
Here a partition is a block-structured adjacency mask.

Default N is sized for one chip's HBM; the 65k-node target needs the
row-sharded multi-chip path (ringpop_tpu/parallel) on a pod slice —
the same code, a larger mesh ("partition_heal" at any N is shape-
polymorphic)."""

from __future__ import annotations

import time

from ringpop_tpu.models import swim_sim as sim
from ringpop_tpu.models.cluster import SimCluster


def run(n: int = 8192, loss: float = 0.0) -> list[dict]:
    cluster = SimCluster(n, sim.SwimParams(loss=loss), seed=4)
    cluster.tick(5)  # warm up / compile

    half = n // 2
    sides = [list(range(half)), list(range(half, n))]
    cluster.partition(sides)
    # Let each side declare the other faulty (suspicion must expire).
    split_ticks = cluster.params.suspicion_ticks + 20
    t0 = time.perf_counter()
    cluster.tick(split_ticks)

    cluster.heal_partition()
    heal_ticks = 0
    while heal_ticks < 600:
        cluster.tick(5)
        heal_ticks += 5
        if cluster.converged():
            break
    wall = time.perf_counter() - t0
    groups = cluster.checksum_groups()
    return [
        {
            "metric": f"sim_partition_heal_n{n}",
            "value": heal_ticks,
            "unit": "ticks_to_remerge",
            "split_ticks": split_ticks,
            "wall_s": round(wall, 3),
            "checksum_groups": len(groups),
            "converged": cluster.converged(),
        }
    ]

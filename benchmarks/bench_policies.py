#!/usr/bin/env python
"""The remediation-policy A/B tables (BASELINE.md round 9).

Two measurements over the ``cascading_overload`` incident family and
the remediation policy plane (``ringpop_tpu/policies``), all exact
ints off ``incident_summary``:

* ``--headline`` — the round-8 configuration (n=64, T=120, 512
  keys/tick zipf, streamed segments of 32, seed 3) under every policy
  at its default operating point, against the no-fault control arm
  (overload feedback stripped) and the unremediated feedback arm.
  This is the acceptance table: the winning policy must put goodput
  within ~5% of the control's and amplification under 1.5.
* ``--scorecards`` — every golden incident (n=16 pinned
  configuration) under every policy: the no-regression grid proving a
  policy does not win cascading_overload by tanking a different
  outage (detect/heal/goodput/amplification deltas vs the bare run).

    JAX_PLATFORMS=cpu python benchmarks/bench_policies.py --headline
    JAX_PLATFORMS=cpu python benchmarks/bench_policies.py --scorecards
"""

from __future__ import annotations

import argparse
import time

from ringpop_tpu.models.cluster import SimCluster
from ringpop_tpu.models.swim_sim import SwimParams
from ringpop_tpu.policies import core as pol
from ringpop_tpu.scenarios import library as lib

HEADLINE_N = 64
HEADLINE_SEED = 3
SEGMENT = 32


def _delta_kw(n: int) -> dict:
    return dict(capacity=n, wire_cap=n, claim_grid=3 * n * n)


def _run(n, seed, backend, spec, wl, policy):
    kw = {} if backend == "dense" else _delta_kw(n)
    c = SimCluster(n, SwimParams(), seed=seed, backend=backend, **kw)
    trace = c.run_scenario(
        spec, traffic=wl, segment_ticks=min(SEGMENT, spec.ticks),
        policy=policy,
    )
    return lib.incident_summary(trace)


def _row(s):
    goodput = s["delivered"] / max(s["lookups"], 1)
    amp = s["sends"] / max(s["delivered"], 1)
    return goodput, amp


def headline() -> None:
    spec, wl = lib.build_incident("cascading_overload", HEADLINE_N)
    spec_ctl, _ = lib.build_incident(
        "cascading_overload", HEADLINE_N, overload=False
    )
    arms = [("control", "dense", spec_ctl, None),
            ("feedback", "dense", spec, None)]
    for p in pol.list_policies():
        arms.append((p, "dense", spec, p))
    arms += [("feedback", "delta", spec, None),
             ("combined", "delta", spec, "combined")]
    print("| backend | arm | goodput | amplification | lat p99 ms "
          "| gray timeouts | failed | peak gray | shed | peak quar "
          "| cap min |")
    print("|---|---|---|---|---|---|---|---|---|---|---|")
    for name, backend, sp, policy in arms:
        t0 = time.time()
        s = _run(HEADLINE_N, HEADLINE_SEED, backend, sp, wl, policy)
        goodput, amp = _row(s)
        gray = s.get("ov_gray_peak", 0)
        shed = s.get("policy_shed", "—")
        quar = s.get("policy_quar_peak", "—")
        capm = s.get("policy_retry_cap_min", "—")
        print(f"| {backend} | {name} | {goodput:.3f} | {amp:.2f} "
              f"| {s['lat_p99_ms']} | {s['gray_timeouts']} "
              f"| {s['proxy_failed']} | {gray}/{HEADLINE_N} | {shed} "
              f"| {quar} | {capm} |   ({time.time() - t0:.0f}s)")


def scorecards() -> None:
    policies = pol.list_policies()
    print("| incident | arm | detect | heal | goodput | amplification "
          "| gray timeouts |")
    print("|---|---|---|---|---|---|---|")
    for name in lib.incident_names():
        for policy in [None] + policies:
            if policy is not None and "dense" not in lib.INCIDENTS[name].backends:
                continue
            s = lib.run_golden(name, "dense", policy=policy)
            goodput, amp = _row(s)
            print(f"| {name} | {policy or 'bare'} | {s['detect_tick']} "
                  f"| {s['heal_tick']} | {goodput:.3f} | {amp:.2f} "
                  f"| {s['gray_timeouts']} |")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--headline", action="store_true")
    ap.add_argument("--scorecards", action="store_true")
    args = ap.parse_args()
    if args.headline:
        headline()
    if args.scorecards:
        scorecards()
    if not (args.headline or args.scorecards):
        ap.error("pick --headline and/or --scorecards")


if __name__ == "__main__":
    main()

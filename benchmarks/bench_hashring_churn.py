"""Hash ring add/remove of 1,000 servers, individual vs bulk
(reference: benchmarks/add-remove-hashring.js — bulk amortizes the
checksum recompute, ring.js:60-94)."""

from __future__ import annotations

import time

from ringpop_tpu.hashring import HashRing

SERVERS = [f"10.0.{i // 250}.{i % 250}:3000" for i in range(1000)]


def run(repeats: int = 3) -> list[dict]:
    best_individual = float("inf")
    best_bulk = float("inf")
    for _ in range(repeats):
        ring = HashRing()
        t0 = time.perf_counter()
        for server in SERVERS:
            ring.add_server(server)
        for server in SERVERS:
            ring.remove_server(server)
        best_individual = min(best_individual, time.perf_counter() - t0)

        ring = HashRing()
        t0 = time.perf_counter()
        ring.add_remove_servers(SERVERS, [])
        ring.add_remove_servers([], SERVERS)
        best_bulk = min(best_bulk, time.perf_counter() - t0)
    return [
        {
            "metric": "hashring_add_remove_1000_individual",
            "value": round(1.0 / best_individual, 3),
            "unit": "ops/sec",
        },
        {
            "metric": "hashring_add_remove_1000_bulk",
            "value": round(1.0 / best_bulk, 3),
            "unit": "ops/sec",
        },
    ]

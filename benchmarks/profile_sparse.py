"""Timing of the sparse-dissemination step vs dense on the live backend.

    python benchmarks/profile_sparse.py [n] [cap]
"""

from __future__ import annotations

import sys
import time

import jax

sys.path.insert(0, ".")

from ringpop_tpu.utils import enable_compilation_cache, pin_cpu_if_requested

pin_cpu_if_requested()
enable_compilation_cache()

from ringpop_tpu.models import swim_sim as sim

REPS = 16


def run_cfg(n: int, params: sim.SwimParams, label: str) -> float:
    state = sim.init_state(n)
    net = sim.make_net(n)
    keys = jax.random.split(jax.random.PRNGKey(1), 3 * REPS)
    it = iter(keys)
    state, m = sim.swim_step(state, net, next(it), params)
    int(m["pings_sent"])
    for _ in range(REPS - 1):
        state, m = sim.swim_step(state, net, next(it), params)
    int(m["pings_sent"])
    best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        for _ in range(REPS):
            state, m = sim.swim_step(state, net, next(it), params)
        int(m["pings_sent"])
        best = min(best, (time.perf_counter() - t0) / REPS)
    print(f"  {label:<24} {best * 1e3:8.2f} ms/tick  "
          f"({n / best:,.0f} node-rounds/s)")
    return best


def main(n: int, cap: int) -> None:
    print(f"n={n}")
    run_cfg(n, sim.SwimParams(loss=0.01), "dense")
    run_cfg(n, sim.SwimParams(loss=0.01, sparse_cap=cap), f"sparse cap={cap}")


if __name__ == "__main__":
    main(
        int(sys.argv[1]) if len(sys.argv) > 1 else 16384,
        int(sys.argv[2]) if len(sys.argv) > 2 else 16,
    )

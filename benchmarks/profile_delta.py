"""Per-piece timing of delta_step on the ambient accelerator.

Times jitted sub-functions of the delta backend at a given n to locate
which phase dominates a tick (usage: python -m benchmarks.profile_delta
[n] [capacity]).  Pieces overlap deliberately — the goal is attribution,
not an exact decomposition.
"""

from __future__ import annotations

import sys
import time

import jax

from ringpop_tpu.utils import pin_cpu_if_requested

pin_cpu_if_requested()

import jax.numpy as jnp

from ringpop_tpu.models import swim_delta as sd
from ringpop_tpu.models import swim_sim as sim


def timeit(name, fn, *args, reps=5):
    out = fn(*args)
    jax.block_until_ready(out)
    # host transfer as an unfakeable barrier (see bench.py _sync)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    leaves = jax.tree_util.tree_leaves(out)
    _ = jax.device_get(leaves[0].ravel()[0] if hasattr(leaves[0], "ravel") else leaves[0])
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / reps
    print(f"{name:35s} {dt * 1000:9.2f} ms")
    return out


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 65536
    cap = int(sys.argv[2]) if len(sys.argv) > 2 else 256
    w, grid = 16, 64
    params = sd.DeltaParams(
        swim=sim.SwimParams(loss=0.01), wire_cap=w, claim_grid=grid
    )
    print(f"platform={jax.default_backend()} n={n} capacity={cap}")
    state = sd.init_delta(n, capacity=cap)
    net = sim.make_net(n)
    key = jax.random.PRNGKey(0)

    # a few steps to produce a realistic (non-empty) divergence state
    step_nodon = jax.jit(sd.delta_step_impl, static_argnames=("params",))
    for i in range(5):
        key, sub = jax.random.split(key)
        state, m = step_nodon(state, net, sub, params)
    print("occupancy:", int(m["max_occupancy"]))

    timeit("full delta_step", step_nodon, state, net, key, params)

    stats = timeit(
        "phase0 stats",
        jax.jit(sd._phase0_stats),
        state,
    )

    k_sel = jax.random.PRNGKey(1)
    sel = timeit(
        "selection (phase 1)",
        jax.jit(sd._selection, static_argnames=("params",)),
        state, stats, net, k_sel, params,
    )

    # claim routing: realistic shapes
    send_subj = jnp.where(
        jnp.arange(w)[None, :] < 2, jnp.arange(n, dtype=jnp.int32)[:, None] % n,
        sd.SENTINEL,
    )
    send_key = jnp.full((n, w), 9, jnp.int32)
    send_valid = send_subj < sd.SENTINEL
    recv = jax.random.randint(jax.random.PRNGKey(2), (n,), 0, n, dtype=jnp.int32)
    timeit(
        "route_claims (sort+align)",
        jax.jit(sd._route_claims, static_argnames=("n", "grid")),
        n, send_subj, send_key, send_valid, recv, grid,
    )

    g_subj = jnp.where(jnp.arange(grid)[None, :] < 2,
                       jnp.arange(n, dtype=jnp.int32)[:, None], sd.SENTINEL)
    g_key = jnp.full((n, grid), 9, jnp.int32)
    g_valid = g_subj < sd.SENTINEL
    timeit(
        "merge_claims (grid)",
        jax.jit(sd._merge_claims, static_argnames=("sl_start",)),
        state, g_subj, g_key, g_valid, 26,
    )

    timeit(
        "compact_true [N,C]->W",
        jax.jit(lambda m: sd._compact_true(m, w)),
        state.d_pb >= -1,
    )

    timeit(
        "sort_claim_rows [N,W]",
        jax.jit(sd._sort_claim_rows),
        send_subj, send_key, send_valid,
    )

    timeit(
        "row sort [N,C] (jnp.sort)",
        jax.jit(lambda x: jnp.sort(x, axis=1)),
        state.d_subj,
    )

    timeit(
        "row searchsorted [N,C]x[N,W]",
        jax.jit(lambda a, q: sd._lookup_pos(a, q)[1]),
        state.d_subj, jnp.clip(send_subj, 0, n - 1),
    )

    timeit(
        "lax.sort 3x[N*W] num_keys=2",
        jax.jit(lambda a, b, c: jax.lax.sort((a, b, c), num_keys=2)),
        jnp.arange(n * w, dtype=jnp.int32) % n,
        jnp.arange(n * w, dtype=jnp.int32) % 7,
        jnp.zeros(n * w, jnp.int32),
    )

    timeit(
        "view_lookup [N]",
        jax.jit(sd.view_lookup),
        state, jnp.arange(n, dtype=jnp.int32),
    )

    # phase bisect: each prefix of the step compiles as ONE executable
    # (delta_step_impl's static ``upto``), so consecutive differences
    # attribute genuine device time per phase with no dispatch noise —
    # the sub-function timings above can't separate launch overhead
    # from compute on the tunneled platform.
    print("-- phase bisect (upto=k: step truncated after phase k) --")
    key2 = jax.random.PRNGKey(7)
    prev = 0.0
    names = {
        0: "stats+digest", 1: "selection", 2: "send window",
        3: "ping merge", 4: "ack merge (+full sync)", 5: "ping-req",
        7: "suspicion+metrics (full)",
    }
    for u in (0, 1, 2, 3, 4, 5, 7):
        fn = jax.jit(
            lambda st, nt, kk, u=u: sd.delta_step_impl(st, nt, kk, params, upto=u)
        )
        out = fn(state, net, key2)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(5):
            out = fn(state, net, key2)
        leaves = jax.tree_util.tree_leaves(out)
        _ = jax.device_get(leaves[0].ravel()[0])
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / 5 * 1e3
        print(f"upto={u} ({names[u]:<24}) {dt:9.2f} ms  (+{dt - prev:8.2f})")
        prev = dt


if __name__ == "__main__":
    main()

"""Phase bisect of delta_step_impl only — the lean on-chip attribution.

benchmarks/profile_delta.py times standalone sub-functions too; on the
tunneled TPU each jit compile costs minutes, so this script compiles
ONLY the 7 step prefixes (delta_step_impl's static ``upto``), with the
persistent compilation cache on so re-runs after a code edit only pay
for the phases the edit touched.

usage: python -m benchmarks.profile_delta_bisect [n] [capacity] [loss]
"""

from __future__ import annotations

import sys
import time

import jax

from ringpop_tpu.utils import enable_compilation_cache, pin_cpu_if_requested

pin_cpu_if_requested()
enable_compilation_cache()


from ringpop_tpu.models import swim_delta as sd
from ringpop_tpu.models import swim_sim as sim


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 32768
    cap = int(sys.argv[2]) if len(sys.argv) > 2 else 256
    loss = float(sys.argv[3]) if len(sys.argv) > 3 else 0.01
    params = sd.DeltaParams(swim=sim.SwimParams(loss=loss), wire_cap=16,
                            claim_grid=64)
    print(f"platform={jax.default_backend()} n={n} capacity={cap} loss={loss}",
          flush=True)
    state = sd.init_delta(n, capacity=cap)
    net = sim.make_net(n)
    key = jax.random.PRNGKey(0)

    step = jax.jit(sd.delta_step_impl, static_argnames=("params", "upto"))
    t0 = time.perf_counter()
    for i in range(3):  # realistic non-empty divergence
        key, sub = jax.random.split(key)
        state, m = step(state, net, sub, params)
    jax.block_until_ready(state)
    print(f"warmup (incl. full-step compile): {time.perf_counter()-t0:.1f}s "
          f"occupancy={int(m['max_occupancy'])}", flush=True)

    names = {0: "stats+digest", 1: "selection", 2: "send window",
             3: "ping merge", 4: "ack merge (+full sync)", 5: "ping-req",
             7: "suspicion+metrics (full)"}
    key2 = jax.random.PRNGKey(7)
    prev = 0.0
    for u in (0, 1, 2, 3, 4, 5, 7):
        t0 = time.perf_counter()
        out = step(state, net, key2, params, upto=u)
        jax.block_until_ready(out)
        print(f"  upto={u} compile+1st: {time.perf_counter()-t0:.1f}s",
              flush=True)
        t0 = time.perf_counter()
        reps = 5
        for _ in range(reps):
            out = step(state, net, key2, params, upto=u)
        leaves = jax.tree_util.tree_leaves(out)
        _ = jax.device_get(leaves[0].ravel()[0])
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / reps * 1e3
        print(f"upto={u} ({names[u]:<24}) {dt:9.2f} ms  (+{dt - prev:8.2f})",
              flush=True)
        prev = dt


if __name__ == "__main__":
    main()

"""Regression check: sim-vs-host ping-req piggyback agreement.

The reference ships piggybacked changes with the ping-req and applies
them at every relay hop (lib/swim/ping-req-sender.js:80-86,138,
server/ping-req-handler.js:37-59).  Both implementations here now carry
the full exchange — the host library over real message passing, the
tensor backends as phase-5 stage merges (swim_sim._phase5_pingreq) —
so the sim/host detection-latency ratio this harness measures is a
REGRESSION CHECK expected near 1.0, not a deviation bound.  (Rounds
1-3 measured the bound for the then-omitted sim-side exchange: 0.99 @
1% loss, 0.95 @ 5% at n=256 — BASELINE.md keeps the history.)

Metric: failure-detection-and-dissemination latency — protocol periods
from killing one node of a converged cluster until EVERY live node has
declared it faulty (suspect -> suspicion timeout -> faulty rumor
spread, SURVEY §3.3), lossy networks (where failed pings make
ping-reqs frequent).

* host = the full library over the in-process transport with
  per-request loss, deterministic virtual time;
* sim  = the tensor backend at iid per-message loss.

Prints one JSON line per (loss, backend) with mean/max periods over
SEEDS runs, then a summary ratio.  Run: python benchmarks/bench_pingreq_deviation.py
"""

from __future__ import annotations

import json
import statistics
import sys

import jax
import numpy as np

sys.path.insert(0, ".")

# n=8 protocol-behavior measurement: CPU-only by design, and pinned at
# the config level — the env var alone still lets the ambient TPU plugin
# contact the (possibly hung) tunnel on backend init.
jax.config.update("jax_platforms", "cpu")

from ringpop_tpu.harness import Cluster
from ringpop_tpu.models import swim_sim as sim
from ringpop_tpu.models.cluster import SimCluster
from ringpop_tpu.models.swim_sim import SwimParams

import os

# Cluster size: n=8 is the quick CI-class default; VERDICT round 2
# (weak #4) asks for the bound at n >= 256, where dissemination fanout
# actually shapes detection latency — run with PINGREQ_DEV_N=256.
N = int(os.environ.get("PINGREQ_DEV_N", "8"))
VICTIM = 2
SEEDS = int(os.environ.get("PINGREQ_DEV_SEEDS", "5"))
PERIOD_MS = 200.0
LOSSES = (0.01, 0.05)
MAX_PERIODS = 2000


def host_periods_to_detect(loss: float, seed: int) -> float:
    cluster = Cluster(size=N, seed=seed)
    cluster.bootstrap_all()
    assert cluster.run_until_converged(), "host bootstrap did not converge"
    cluster.network.set_drop_rate(loss)
    victim_addr = cluster.host_ports[VICTIM]
    t0 = cluster.scheduler.now()
    cluster.kill(VICTIM)
    live = [n for i, n in enumerate(cluster.nodes) if i != VICTIM]
    for _ in range(MAX_PERIODS):
        if all(
            (m := n.membership.find_member_by_address(victim_addr)) is not None
            and m.status == "faulty"
            for n in live
        ):
            return (cluster.scheduler.now() - t0) / PERIOD_MS
        cluster.run(PERIOD_MS)
    raise AssertionError(f"host never detected the death (loss={loss})")


def sim_ticks_to_detect(loss: float, seed: int) -> float:
    # probe pinned to "uniform": every recorded row (n=8 round 2, n=256
    # round 3 — BASELINE.md) was measured under it, and this bench
    # compares ping-req piggyback behavior, not probe policy.
    simc = SimCluster(N, SwimParams(loss=loss, probe="uniform"), seed=seed)
    simc.kill(VICTIM)
    live = [i for i in range(N) if i != VICTIM]
    for tick in range(1, MAX_PERIODS + 1):
        simc.tick()
        status = np.asarray(simc.state.view_status[:, VICTIM])
        if all(status[i] == sim.FAULTY for i in live):
            return float(tick)
    raise AssertionError(f"sim never detected the death (loss={loss})")


def _sweep(loss: float, seeds: int) -> tuple[list[float], list[float]]:
    host = [host_periods_to_detect(loss, s) for s in range(seeds)]
    simv = [sim_ticks_to_detect(loss, s) for s in range(seeds)]
    return host, simv


def _run_records(seeds: int) -> list[dict]:
    out = []
    for loss in LOSSES:
        host, simv = _sweep(loss, seeds)
        out.append(
            {
                "metric": f"pingreq_piggyback_deviation_loss{loss}",
                "value": round(statistics.mean(simv) / statistics.mean(host), 2),
                "unit": "sim/host mean detection latency",
                "host_mean_periods": round(statistics.mean(host), 1),
                "sim_mean_ticks": round(statistics.mean(simv), 1),
            }
        )
    return out


def run(seeds: int = 2) -> list[dict]:
    """run_all interface.  Executes in a FRESH subprocess: the CPU pin at
    the top of this module only takes effect before any JAX backend
    initializes, and run_all's earlier sim benches have already
    initialized one (possibly the TPU this bench must avoid)."""
    import os
    import subprocess

    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--run-json", str(seeds)],
        capture_output=True,
        text=True,
        timeout=1800,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"deviation sweep failed rc={proc.returncode}: "
            + (proc.stderr.strip().splitlines() or ["?"])[-1]
        )
    return json.loads(proc.stdout.strip().splitlines()[-1])


def main() -> None:
    summary = {}
    for loss in LOSSES:
        host, simv = _sweep(loss, SEEDS)
        for name, vals in (("host", host), ("sim", simv)):
            print(
                json.dumps(
                    {
                        "metric": f"death_detect_periods_{name}_loss{loss}",
                        "mean": round(statistics.mean(vals), 1),
                        "max": round(max(vals), 1),
                        "unit": "protocol-periods",
                    }
                ),
                flush=True,
            )
        summary[loss] = statistics.mean(simv) / statistics.mean(host)
    print(
        json.dumps(
            {
                "metric": "pingreq_piggyback_deviation_ratio",
                "value": {str(k): round(v, 2) for k, v in summary.items()},
                "unit": "sim/host mean detection latency (1.0 = no deviation)",
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    if len(sys.argv) > 2 and sys.argv[1] == "--run-json":
        print(json.dumps(_run_records(int(sys.argv[2]))))
    else:
        main()

"""Membership checksum computation @ 100 / 1,000 members
(reference: benchmarks/compute-checksum.js)."""

from __future__ import annotations

import time

from benchmarks.fixtures import large_membership
from ringpop_tpu.harness import test_ringpop


def _bench(n_members: int, duration_s: float) -> dict:
    rp = test_ringpop(host_port="10.30.0.1:30000")
    rp.membership.update(large_membership(n_members))
    iterations = 0
    t0 = time.perf_counter()
    deadline = t0 + duration_s
    while time.perf_counter() < deadline:
        rp.membership.compute_checksum()
        iterations += 1
    elapsed = time.perf_counter() - t0
    return {
        "metric": f"compute_checksum_{n_members}",
        "value": round(iterations / elapsed, 2),
        "unit": "ops/sec",
    }


def run(duration_s: float = 1.0) -> list[dict]:
    return [_bench(100, duration_s), _bench(1000, duration_s)]

"""BASELINE config 3: 10k-node SWIM sim, 1% packet loss, suspect→faulty
convergence after a node dies.

Measures (a) protocol ticks until every live node has declared the dead
node faulty and views re-agree, and (b) wall-clock per simulated
protocol round.  The reference equivalent would be 10,000 real processes
at one 200 ms protocol period each — a rate the ``realtime_speedup``
field compares against (rounds simulated per second / rounds a real
cluster executes per second)."""

from __future__ import annotations

import time

import numpy as np

from ringpop_tpu.models import swim_sim as sim
from ringpop_tpu.models.cluster import SimCluster


def run(n: int = 10240, loss: float = 0.01) -> list[dict]:
    cluster = SimCluster(n, sim.SwimParams(loss=loss), seed=3)
    cluster.tick(5)  # warm up / compile

    victim = 7
    cluster.kill(victim)
    t0 = time.perf_counter()
    ticks = 0
    while ticks < 400:
        cluster.tick(5)
        ticks += 5
        status = np.asarray(cluster.state.view_status[:, victim])
        live = cluster.live_indices()
        if (status[live] == sim.FAULTY).all() and cluster.converged():
            break
    wall = time.perf_counter() - t0
    rounds_per_sec = ticks * n / wall
    realtime_speedup = rounds_per_sec / (n / (cluster.params.period_ms / 1000.0))
    return [
        {
            "metric": f"sim_suspect_to_faulty_convergence_n{n}_loss{loss}",
            "value": ticks,
            "unit": "ticks",
            "wall_s": round(wall, 3),
            "node_rounds_per_sec": round(rounds_per_sec, 1),
            "realtime_speedup": round(realtime_speedup, 1),
        }
    ]

"""stat() key-cache fast path: cached vs always-new keys
(reference: benchmarks/bench_ringpop_stat_{cached,new}_keys.js;
the cache is index.js:561-575)."""

from __future__ import annotations

import time

from ringpop_tpu.harness import test_ringpop


def run(duration_s: float = 1.0) -> list[dict]:
    results = []
    for cached in (True, False):
        rp = test_ringpop(host_port="10.30.0.1:30000")
        iterations = 0
        t0 = time.perf_counter()
        deadline = t0 + duration_s
        while time.perf_counter() < deadline:
            key = "ping.send" if cached else f"ping.send.{iterations}"
            rp.stat("increment", key, 1)
            iterations += 1
        elapsed = time.perf_counter() - t0
        results.append(
            {
                "metric": f"stat_{'cached' if cached else 'new'}_keys",
                "value": round(iterations / elapsed, 2),
                "unit": "ops/sec",
            }
        )
    return results

"""BASELINE config 4 on the delta backend: 50/50 netsplit -> heal ->
one checksum group, at sizes the dense backend cannot reach.

The netsplit uses the int32[N] group-id adjacency (swim_sim._adj) — the
only partition form the delta step takes.  A netsplit's *transition* is
dense by construction (every viewer accumulates other-side
suspicion/faulty records, peak divergence ~N/2 per viewer), so
``capacity`` is sized N/2 + slack: at 32,768 nodes the state fits one
16 GB chip (5.4 GB); 65,536 (21.5 GB) runs on the host or the
row-sharded mesh.

Two merge paths, both reference-faithful:

* heal mid-transition (default): cross-side members still suspect are
  still pingable, so probes cross the healed link, checksums mismatch,
  full syncs + refutations remerge the views spontaneously.
* bridge join: if the split fully converged (all cross-entries faulty,
  no cross-probing — the reference behaves identically: faulty members
  are not pingable, membership.js:135-139), a single admin rejoin
  bridges the sides (admin-join-handler.js:36-45 — the operational
  merge path; tick-cluster's 'j').  Used automatically if the sim
  stalls at 2 checksum groups.

Usage: python benchmarks/bench_partition_heal_delta.py [n] [--heal-at T]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run(
    n: int = 4096,
    loss: float = 0.0,
    suspicion_ticks: int = 8,
    heal_at: int | None = None,
    capacity: int | None = None,
    max_heal_ticks: int = 800,
    check_every: int = 5,
    sided: bool = False,
    backend: str = "delta",
    wire_cap: int = 64,
) -> list[dict]:
    from ringpop_tpu.models import swim_sim as sim
    from ringpop_tpu.models.cluster import SimCluster

    if backend == "dense":
        # the unbounded-wire control (bench_sided_bound): same
        # trajectory shape, reference piggyback semantics, no caps
        # (capacity/wire are delta knobs the dense backend ignores)
        capacity = None
    elif sided:
        # Sided mode (swim_delta.make_sides): per-side base rows absorb
        # each side's consensus via anti-entropy rebase folds, so the
        # capacity only has to hold the in-flight rumor front — n/16
        # measured ample at 1024 (converges in ~30 post-heal ticks);
        # 65,536 at C=4096 is a 2.7 GB state on one chip (vs 21.5 GB
        # for the unsided ~n capacity).
        capacity = capacity or max(256, n // 16)
    else:
        # Peak divergence is ~n per viewer, not n/2: the post-heal
        # refutation storm bumps EVERY member's incarnation (both sides
        # held the other faulty, every subject refutes on hearing it),
        # so every column diverges from the pre-split base until rebase
        # folds the re-converged columns back in (the periodic rebase).
        capacity = capacity or (n + 64)
    params = sim.SwimParams(loss=loss, suspicion_ticks=suspicion_ticks)
    # Storm-grade wire: the post-heal refutation wave refreshes ~n
    # entries per viewer; the rotating wire window cycles the backlog in
    # ~capacity/wire_cap-tick rounds, so wire 64 keeps the remerge in
    # the low hundreds of ticks without blowing up the routed-sort cost.
    cluster = SimCluster(
        n,
        params,
        seed=4,
        backend=backend,
        capacity=capacity or 256,
        wire_cap=wire_cap,
        claim_grid=512,
    )
    if sided and backend != "delta":
        raise ValueError("sided mode is a delta-backend representation")
    cluster.tick(2)  # warm up / compile

    half = n // 2
    sides = [list(range(half)), list(range(half, n))]
    if sided:
        cluster.split_sides(sides)
    else:
        cluster.partition(sides)
    # Heal mid-transition: suspicion has begun everywhere (the rumor
    # front saturates in ~log2(n) ticks) but cross-side suspects are
    # still pingable, so the healed link carries probes again.  (A
    # FULLY converged split-brain cannot remerge spontaneously in any
    # backend — faulty members are not pingable, membership.js:135-139
    # — that variant needs the bridge join below, and at equal
    # incarnations even a bridge spreads the faulty consensus; the
    # reference's operational answer is refreshed incarnations.)
    split_ticks = heal_at if heal_at is not None else suspicion_ticks + 4
    t0 = time.perf_counter()
    done = 0
    while done < split_ticks:
        step_t = min(5, split_ticks - done)
        cluster.tick(step_t)
        done += step_t
        if sided:
            cluster.rebase(anti_entropy=True)
    groups_at_heal = len(cluster.checksum_groups())

    print(
        f"# split done: {groups_at_heal} checksum groups at heal "
        f"({time.perf_counter() - t0:.0f}s)",
        file=sys.stderr,
        flush=True,
    )
    cluster.heal_partition()
    heal_ticks = 0
    bridged = False
    while heal_ticks < max_heal_ticks:
        cluster.tick(check_every)
        heal_ticks += check_every
        if heal_ticks % 20 == 0 or heal_ticks == check_every:
            # long-run progress evidence (the 65k config runs for hours)
            print(
                f"# heal tick {heal_ticks}: "
                f"{len(cluster.checksum_groups())} groups "
                f"({time.perf_counter() - t0:.0f}s)",
                file=sys.stderr,
                flush=True,
            )
        if heal_ticks % (10 if sided else 20) == 0:
            # fold re-converged columns back into the base so the
            # divergence tables drain as the merge progresses (the
            # unsided cadence stays at 20 — the round-3/4 recorded
            # trajectories depend on it)
            cluster.rebase(anti_entropy=sided)
        if cluster.converged():
            break
        if not bridged and heal_ticks >= 8 * suspicion_ticks:
            groups = cluster.checksum_groups()
            if len(groups) == 2:
                # fully-converged split-brain: no cross-probing remains
                # (faulty members are not pingable) — bridge with one
                # admin rejoin, the reference's operational merge path
                cluster.join(half, 0)
                bridged = True
    wall = time.perf_counter() - t0
    if sided and cluster.converged():
        cluster.rebase(anti_entropy=True)
        cluster.fold_sides()  # leave sided mode: single base again
    groups = cluster.checksum_groups()
    m = cluster.metrics_log[-1] if cluster.metrics_log else {}
    prefix = "dense" if backend == "dense" else "delta"
    return [
        {
            "metric": f"{prefix}_partition_heal{'_sided' if sided else ''}_n{n}",
            "value": heal_ticks,
            "unit": "ticks_to_remerge",
            "split_ticks": split_ticks,
            "groups_at_heal": groups_at_heal,
            "bridged": bridged,
            "wall_s": round(wall, 3),
            "capacity": capacity,
            "overflow_drops": int(m.get("overflow_drops", 0)),
            "checksum_groups": len(groups),
            "converged": cluster.converged(),
        }
    ]


if __name__ == "__main__":
    from ringpop_tpu.utils import enable_compilation_cache, pin_cpu_if_requested

    pin_cpu_if_requested()
    enable_compilation_cache()
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
    heal_at = None
    if "--heal-at" in sys.argv:
        heal_at = int(sys.argv[sys.argv.index("--heal-at") + 1])
    for row in run(n, heal_at=heal_at, sided="--sided" in sys.argv):
        print(row)

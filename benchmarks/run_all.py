"""Benchmark aggregator (reference: benchmarks/index.js globs bench_*.js;
benchmarks/run.js is the cross-ref harness — here a flat runner).

Usage:  python -m benchmarks.run_all [--fast] [--only SUBSTR]
Prints one JSON line per result; host-library benches first, then the
TPU simulation configs (slow: one XLA compile each)."""

from __future__ import annotations

import argparse
import importlib
import json
import sys
import traceback

HOST_BENCHES = [
    "bench_membership_update",
    "bench_compute_checksum",
    "bench_hashring_churn",
    "bench_find_member",
    "bench_join_merge",
    "bench_stat_keys",
    "bench_ring_rebalance",  # config 5 is host-side (no XLA compile)
]
SIM_BENCHES = [
    "bench_sim_convergence",
    "bench_partition_heal",
    "bench_pingreq_deviation",
    "bench_scenario",  # one-call compiled scenario vs the host loop
    "bench_sweep",  # one vmapped R-replica dispatch vs R sequential
    "bench_lookup",  # batched device ring lookups vs the host loop
    "bench_stream",  # pipelined segmented soak vs the blocking loop
    "bench_faults",  # failure-model family sweeps: detect/heal tables
    "bench_multichip",  # gossip-plane race: ring remote-copy vs all-gather
    "bench_dissemination",  # infection-time ladder vs the log2(N) bound
]


def main(argv=None) -> int:
    from ringpop_tpu.utils import pin_cpu_if_requested

    pin_cpu_if_requested()

    parser = argparse.ArgumentParser()
    parser.add_argument("--fast", action="store_true",
                        help="host benches only (skip XLA compiles)")
    parser.add_argument("--only", default=None,
                        help="substring filter on bench module name")
    parser.add_argument("--sim-n", type=int, default=None,
                        help="override N for the simulation configs")
    args = parser.parse_args(argv)

    names = HOST_BENCHES + ([] if args.fast else SIM_BENCHES)
    if args.only:
        names = [n for n in names if args.only in n]
    failed = 0
    for name in names:
        module = importlib.import_module(f"benchmarks.{name}")
        kwargs = {}
        if args.sim_n and name in (
            "bench_sim_convergence", "bench_partition_heal",
            "bench_scenario", "bench_sweep", "bench_stream",
            "bench_faults", "bench_dissemination",
        ):
            kwargs["n"] = args.sim_n
        try:
            for result in module.run(**kwargs):
                print(json.dumps({"bench": name, **result}), flush=True)
        except Exception:
            failed += 1
            traceback.print_exc()
            print(json.dumps({"bench": name, "error": "failed"}), flush=True)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())

"""BASELINE config 5: hash-ring rebalance under churn — 10k servers,
5%/tick join/leave, key-movement count.

Measures consistent hashing's defining property (how few keys move under
churn, ring.js replica-point design) and the ring update throughput.

The key re-resolution after every churn tick runs on BOTH paths and
cross-checks them:
* host: per-key rbtree-equivalent lookup (hashring.py);
* device: one batched ``lookup_keys`` over the ``DeviceRing`` —
  farmhash on device + one searchsorted for the whole key batch
  (ops/ring_ops.py), asserted bit-identical to the host owners.
"""

from __future__ import annotations

import random
import time

import numpy as np

from ringpop_tpu.hashring import HashRing
from ringpop_tpu.ops import ring_ops


def run(n: int = 10000, churn: float = 0.05, ticks: int = 5,
        n_keys: int = 2000) -> list[dict]:
    rng = random.Random(5)
    servers = [f"10.{i // 65536 % 256}.{i // 256 % 256}.{i % 256}:3000"
               for i in range(n)]
    ring = HashRing()
    ring.add_remove_servers(servers, [])
    keys = [f"key-{rng.randrange(10 ** 12)}" for _ in range(n_keys)]
    key_bufs, key_lens = ring_ops.encode_strings(keys)
    owners = {k: ring.lookup(k) for k in keys}

    in_ring = set(servers)
    spare = [f"10.200.{i // 256}.{i % 256}:3000" for i in range(n)]
    moved_total = 0
    churn_count = int(n * churn)
    device_lookup_s = 0.0
    wall = 0.0  # host-path churn+lookup only (the pre-existing metric)
    for _ in range(ticks):
        t0 = time.perf_counter()
        leavers = rng.sample(sorted(in_ring), churn_count)
        joiners = [spare.pop() for _ in range(churn_count)]
        ring.add_remove_servers(joiners, leavers)
        in_ring.difference_update(leavers)
        in_ring.update(joiners)
        new_owners = {k: ring.lookup(k) for k in keys}
        moved_total += sum(1 for k in keys if new_owners[k] != owners[k])
        owners = new_owners
        wall += time.perf_counter() - t0

        # Device path (untimed by wall_s_per_tick): one batched lookup of
        # every key, cross-checked bit-identical against the host
        # rbtree-equivalent path.
        server_list = sorted(in_ring)
        dring = ring_ops.build_ring(server_list)
        t1 = time.perf_counter()
        dev_idx = np.asarray(ring_ops.lookup_keys(dring, key_bufs, key_lens))
        device_lookup_s += time.perf_counter() - t1
        dev_owners = [server_list[i] for i in dev_idx]
        mismatches = sum(
            1 for k, o in zip(keys, dev_owners) if owners[k] != o
        )
        assert mismatches == 0, f"device ring diverged on {mismatches} keys"

    moved_frac = moved_total / (n_keys * ticks)
    return [
        {
            "metric": f"ring_rebalance_n{n}_churn{churn}",
            "value": round(moved_frac, 4),
            "unit": "fraction_keys_moved_per_tick",
            "expected_fraction": round(2 * churn, 4),  # leave + join movement
            "wall_s_per_tick": round(wall / ticks, 3),
            "device_lookups_per_s": round(n_keys * ticks / device_lookup_s),
            "device_vs_host": "bit-identical",
        }
    ]

"""BASELINE config 5: hash-ring rebalance under churn — 10k servers,
5%/tick join/leave, key-movement count.

Measures consistent hashing's defining property (how few keys move under
churn, ring.js replica-point design) and the ring update throughput."""

from __future__ import annotations

import random
import time

from ringpop_tpu.hashring import HashRing


def run(n: int = 10000, churn: float = 0.05, ticks: int = 5,
        n_keys: int = 2000) -> list[dict]:
    rng = random.Random(5)
    servers = [f"10.{i // 65536 % 256}.{i // 256 % 256}.{i % 256}:3000"
               for i in range(n)]
    ring = HashRing()
    ring.add_remove_servers(servers, [])
    keys = [f"key-{rng.randrange(10 ** 12)}" for _ in range(n_keys)]
    owners = {k: ring.lookup(k) for k in keys}

    in_ring = set(servers)
    spare = [f"10.200.{i // 256}.{i % 256}:3000" for i in range(n)]
    moved_total = 0
    churn_count = int(n * churn)
    t0 = time.perf_counter()
    for _ in range(ticks):
        leavers = rng.sample(sorted(in_ring), churn_count)
        joiners = [spare.pop() for _ in range(churn_count)]
        ring.add_remove_servers(joiners, leavers)
        in_ring.difference_update(leavers)
        in_ring.update(joiners)
        new_owners = {k: ring.lookup(k) for k in keys}
        moved_total += sum(1 for k in keys if new_owners[k] != owners[k])
        owners = new_owners
    wall = time.perf_counter() - t0

    moved_frac = moved_total / (n_keys * ticks)
    return [
        {
            "metric": f"ring_rebalance_n{n}_churn{churn}",
            "value": round(moved_frac, 4),
            "unit": "fraction_keys_moved_per_tick",
            "expected_fraction": round(2 * churn, 4),  # leave + join movement
            "wall_s_per_tick": round(wall / ticks, 3),
        }
    ]

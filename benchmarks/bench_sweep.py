"""Vmapped scenario sweep: ONE dispatch for R replicas vs R dispatches.

The sweep engine's reason to exist, measured: the statistical
experiment every multi-seed benchmark in this repo runs — R replicas
of the same chaos scenario, differing only in PRNG seed — used to pay
the dispatch + host-sync + per-replica bookkeeping tax R times in a
host loop.  Both arms below run the COMPLETE experiment through the
public API (cluster construction, the run, and the detection/heal
statistics), from the same spec:

* sweep arm: one ``SimCluster`` + ``run_sweep(R)`` — one vmapped
  jitted dispatch (counted via both scan dispatch counters), one
  ``SweepTrace``; when more than one device is visible the replica
  axis is sharded across them (replicas are data-parallel by
  construction, so a multi-chip mesh runs R / n_devices per chip).
* sequential arm: R x (``SimCluster`` + ``run_scenario``) — R scan
  dispatches, each fully host-synced (the Trace pull), then the same
  statistics from the R traces.

The trajectories are NOT pairwise identical across arms (different
seeds by design — it's a statistical experiment; per-replica
bit-parity against run_scenario from the same key is pinned in
tests/test_sweep.py), so the benchmark also cross-checks both arms'
converged-replica counts as a sanity signal, not a parity claim.
"""

from __future__ import annotations

import os
import sys

# More than one XLA host device lets the sweep arm shard the replica
# axis (real thread-level parallelism on CPU; the multi-chip story on
# TPU).  Only when this module is the entry point AND jax is not yet
# initialized — under run_all the process-wide device layout belongs
# to the aggregator, and the bench reports whatever count it got.
if (
    __name__ == "__main__"
    and "jax" not in sys.modules
    and "--no-devices" not in sys.argv
):
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        count = min(8, os.cpu_count() or 1)
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={count}".strip()
        )

import time  # noqa: E402

import jax  # noqa: E402


def _experiment_spec(n: int, ticks: int):
    from ringpop_tpu.scenarios.spec import ScenarioSpec

    return ScenarioSpec.from_dict(
        {
            "ticks": ticks,
            "events": [
                {"at": ticks // 8, "op": "kill", "node": n - 1},
                {"at": ticks // 4, "op": "loss", "p": 0.05},
                {"at": ticks // 2, "op": "loss_ramp",
                 "until": ticks // 2 + 10, "to": 0.0},
            ],
        }
    )


def run(n: int = 256, ticks: int = 60, replicas: int = 8) -> list[dict]:
    from ringpop_tpu.models import swim_sim as sim
    from ringpop_tpu.models.cluster import SimCluster
    from ringpop_tpu.scenarios import runner as srunner
    from ringpop_tpu.scenarios import sweep as ssweep

    spec = _experiment_spec(n, ticks)
    params = sim.SwimParams()
    shard = len(jax.devices()) > 1 and replicas % len(jax.devices()) == 0

    def sweep_arm():
        before = (ssweep.dispatch_count(), srunner.dispatch_count())
        t0 = time.perf_counter()
        cluster = SimCluster(n, params, seed=11)
        trace = cluster.run_sweep(spec, replicas, shard=shard)
        stats = trace.summary()
        wall = time.perf_counter() - t0
        dispatches = (
            ssweep.dispatch_count() - before[0],
            srunner.dispatch_count() - before[1],
        )
        return wall, dispatches, stats

    def sequential_arm():
        before = (ssweep.dispatch_count(), srunner.dispatch_count())
        t0 = time.perf_counter()
        detect, converged_final = [], 0
        for r in range(replicas):
            cluster = SimCluster(n, params, seed=100 + r)
            trace = cluster.run_scenario(spec)
            fd = trace.metrics["faulty_declared"]
            hits = (fd > 0).nonzero()[0]
            if hits.size:
                detect.append(int(hits[0]))
            converged_final += int(trace.converged[-1])
        wall = time.perf_counter() - t0
        dispatches = (
            ssweep.dispatch_count() - before[0],
            srunner.dispatch_count() - before[1],
        )
        return wall, dispatches, detect, converged_final

    # cold (compile) then warm (executable cached); interleaved so a
    # machine-load swing hits both arms alike
    cold_sweep, sweep_disp, _ = sweep_arm()
    cold_seq, seq_disp, _, _ = sequential_arm()
    warm_sweep, warm_seq = [], []
    stats = detect = conv_seq = None
    for _ in range(3):
        w, _, d, c = sequential_arm()
        warm_seq.append(w)
        detect, conv_seq = d, c
        w, _, s = sweep_arm()
        warm_sweep.append(w)
        stats = s
    best_sweep, best_seq = min(warm_sweep), min(warm_seq)
    return [
        {
            "metric": f"sweep_vmapped_n{n}_t{ticks}_R{replicas}",
            "value": round(replicas / best_sweep, 3),
            "unit": "replicas_per_s_warm",
            "wall_s": round(best_sweep, 3),
            "cold_s": round(cold_sweep, 2),
            "dispatches": sweep_disp[0] + sweep_disp[1],
            "devices": len(jax.devices()),
            "sharded": shard,
            "converged": stats["replicas"]["converged_final"],
            "detected": stats["replicas"]["detected"],
        },
        {
            "metric": f"sweep_sequential_n{n}_t{ticks}_R{replicas}",
            "value": round(replicas / best_seq, 3),
            "unit": "replicas_per_s_warm",
            "wall_s": round(best_seq, 3),
            "cold_s": round(cold_seq, 2),
            "dispatches": seq_disp[0] + seq_disp[1],
            "converged": conv_seq,
            "detected": len(detect),
            "speedup_vmapped": round(best_seq / max(best_sweep, 1e-9), 3),
        },
    ]


if __name__ == "__main__":
    import json

    n = 256
    for a in sys.argv[1:]:
        if a.isdigit():
            n = int(a)
    for row in run(n=n):
        print(json.dumps(row), flush=True)

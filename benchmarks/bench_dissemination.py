"""Dissemination-time ladder + per-incident provenance scorecard.

The SWIM/ringpop pitch is O(log N) dissemination: a rumor originated
anywhere reaches every member in about log2(N) protocol periods.  The
provenance plane (obs/provenance.py) measures that claim directly —
per-rumor infection wavefronts recorded inside the compiled scan — so
this bench is the paper's Figure-style evaluation run against our own
simulator instead of being asserted from the math.

Two modes:

* the RUNG LADDER (default): n = 64 -> 4096, dense and delta, one
  kill per rung with ``trace_rumors`` armed; reports the infection-
  time distribution of the auto-armed suspect rumor (p50/p95/p99 in
  ticks) against the ceil(log2 n) bound, plus tree depth and
  straggler count.  ``p99/log2n`` near 1.0 is the paper's claim
  holding; >>1 means piggyback capacity, loss, or topology is
  throttling the wavefront.

* ``--scorecard``: every golden incident (scenarios/library.py) at
  the golden configuration with 8 rumor slots armed — the
  per-incident provenance scorecard BASELINE.md records: how many
  rumors each outage originates, confirmed vs refuted, wavefront
  reach, depth, and infection percentiles under that incident's
  loss/partition/overload regime.
"""

from __future__ import annotations

import argparse
import json
import math
import time

import numpy as np

LADDER = (64, 256, 1024, 4096)


def _rung_spec(n: int, ticks: int, k: int) -> dict:
    # one kill early; the suspect rumor it originates auto-arms a
    # tracked slot, and its wavefront is the dissemination measurement
    return {
        "ticks": ticks,
        "trace_rumors": k,
        "events": [{"at": 4, "op": "kill", "node": n - 1}],
    }


def _rumor_stats(report: dict) -> dict:
    """Aggregate the per-rumor wavefront stats a report carries."""
    rumors = report["rumors"]
    if not rumors:
        return {"rumors": 0}
    return {
        "rumors": len(rumors),
        "infected_min": min(r["infected"] for r in rumors),
        "infected_max": max(r["infected"] for r in rumors),
        "depth_max": max(r["depth_max"] for r in rumors),
        "p50_max": max(r["infection_p50"] for r in rumors),
        "p95_max": max(r["infection_p95"] for r in rumors),
        "p99_max": max(r["infection_p99"] for r in rumors),
        "stragglers": sum(r["stragglers"] for r in rumors),
        "unattributed": sum(r["unattributed"] for r in rumors),
    }


def run_ladder(
    ns=LADDER, ticks: int = 48, seed: int = 7, rumors: int = 4,
    backends=("dense", "delta"),
):
    from ringpop_tpu.models.cluster import SimCluster
    from ringpop_tpu.models.swim_sim import SwimParams

    rows = []
    for n in ns:
        for backend in backends:
            kw = {} if backend == "dense" else {
                "capacity": min(2 * n, 1024)
            }
            c = SimCluster(
                n, SwimParams(suspicion_ticks=8), seed=seed,
                backend=backend, **kw,
            )
            t0 = time.perf_counter()
            c.run_scenario(_rung_spec(n, ticks, rumors))
            wall = time.perf_counter() - t0
            rep = c.provenance_report()
            bound = max(1, math.ceil(math.log2(n)))
            row = {
                "mode": "ladder",
                "n": n,
                "backend": backend,
                "ticks": ticks,
                "wall_s": round(wall, 2),
                "log2_n": bound,
                **_rumor_stats(rep),
            }
            if row["rumors"]:
                row["p99_vs_log2n"] = round(row["p99_max"] / bound, 2)
            rows.append(row)
            print(json.dumps(row), flush=True)
    print("\n| n | backend | rumors | infected | depth | "
          "infect p50/p95/p99 | log2(n) | p99/bound | stragglers |")
    print("|---|---|---|---|---|---|---|---|---|")
    for r in rows:
        if not r["rumors"]:
            print(f"| {r['n']} | {r['backend']} | 0 | — | | | "
                  f"{r['log2_n']} | | |")
            continue
        print(
            f"| {r['n']} | {r['backend']} | {r['rumors']} "
            f"| {r['infected_max']}/{r['n']} | {r['depth_max']} "
            f"| {r['p50_max']}/{r['p95_max']}/{r['p99_max']} "
            f"| {r['log2_n']} | {r['p99_vs_log2n']} "
            f"| {r['stragglers']} |"
        )
    return rows


def run_scorecard(rumors: int = 8):
    """Every golden incident at the golden configuration, provenance-
    armed: the per-incident dissemination scorecard."""
    from ringpop_tpu.obs import provenance as pvn
    from ringpop_tpu.scenarios import library as ilib

    rows = []
    for name in ilib.INCIDENTS:
        spec, wl = ilib.build_incident(name, ilib.GOLDEN_N)
        spec = spec._replace(trace_rumors=rumors)
        cluster = ilib.golden_cluster()
        t0 = time.perf_counter()
        trace = cluster.run_scenario(
            spec, traffic=wl,
            segment_ticks=min(ilib.GOLDEN_SEGMENT, spec.ticks),
        )
        wall = time.perf_counter() - t0
        rep = cluster.provenance_report()
        block = pvn.summary_block(rep)
        summary = ilib.incident_summary(trace, prov=rep)
        row = {
            "mode": "scorecard",
            "incident": name,
            "n": ilib.GOLDEN_N,
            "slots": rumors,
            "wall_s": round(wall, 2),
            **{f"pv_{k}": int(v) for k, v in block.items()},
            "detect_tick": summary.get("detect_tick", -1),
            "suspects_declared": summary.get("suspects_declared", 0),
        }
        rows.append(row)
        print(json.dumps(row), flush=True)
    print("\n| incident | rumors | confirmed/refuted | infected "
          "| depth | infect p50/p95/p99 | stragglers | unattributed |")
    print("|---|---|---|---|---|---|---|---|")
    for r in rows:
        if not r["pv_rumors"]:
            print(f"| {r['incident']} | 0 | — | | | | | |")
            continue
        print(
            f"| {r['incident']} | {r['pv_rumors']} "
            f"| {r['pv_confirmed']}/{r['pv_refuted']} "
            f"| {r['pv_infected_min']}-{r['pv_infected_max']}/{r['n']} "
            f"| {r['pv_depth_max']} "
            f"| {r['pv_p50_max']}/{r['pv_p95_max']}/{r['pv_p99_max']} "
            f"| {r['pv_stragglers']} | {r['pv_unattributed']} |"
        )
    return rows


def run(n: int | None = None):
    """run_all entry point: a CI-sized ladder (two rungs, both
    backends) plus the golden scorecard."""
    ns = (n,) if n else (64, 256)
    for row in run_ladder(ns=ns, ticks=48):
        yield row
    for row in run_scorecard():
        yield row


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ladder", type=int, nargs="*", default=None,
                    help=f"rung sizes (default {list(LADDER)})")
    ap.add_argument("--ticks", type=int, default=48)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--rumors", type=int, default=4)
    ap.add_argument("--backend", choices=("dense", "delta"), default=None,
                    help="restrict the ladder to one backend")
    ap.add_argument("--scorecard", action="store_true",
                    help="run the golden-incident provenance scorecard "
                         "instead of the ladder")
    args = ap.parse_args(argv)
    if args.scorecard:
        run_scorecard()
        return
    run_ladder(
        ns=tuple(args.ladder) if args.ladder else LADDER,
        ticks=args.ticks,
        seed=args.seed,
        rumors=args.rumors,
        backends=(args.backend,) if args.backend else ("dense", "delta"),
    )


if __name__ == "__main__":
    main()

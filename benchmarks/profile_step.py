"""Component-level timing of the SWIM step on the current backend.

The tunneled TPU has ~70 ms dispatch/sync latency, so single-call timings
are useless: each component is iterated REPS times inside one jitted
lax.scan with a carried data dependency, and the marginal per-iteration
cost is reported (sync overhead amortized to noise).

    python benchmarks/profile_step.py [n]
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp

sys.path.insert(0, ".")

from ringpop_tpu.utils import pin_cpu_if_requested

pin_cpu_if_requested()

from ringpop_tpu.models import swim_sim as sim

REPS = 16


def timed_scan(make_body, init_carry, label):
    """Scan make_body REPS times; print marginal ms/iteration."""

    @jax.jit
    def run(carry, keys):
        def body(c, k):
            return make_body(c, k), None

        out, _ = jax.lax.scan(body, carry, keys)
        return out

    keys = jax.random.split(jax.random.PRNGKey(1), REPS)
    out = run(init_carry, keys)
    leaves = [x for x in jax.tree_util.tree_leaves(out) if hasattr(x, "shape")]
    float(jnp.sum(leaves[0][..., :1].astype(jnp.float32)).item())
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        out = run(init_carry, keys)
        leaves = [x for x in jax.tree_util.tree_leaves(out) if hasattr(x, "shape")]
        float(jnp.sum(leaves[0][..., :1].astype(jnp.float32)).item())
        best = min(best, time.perf_counter() - t0)
    print(f"  {label:<24} {best / REPS * 1e3:8.2f} ms/iter")
    return best / REPS


def main(n: int) -> None:
    params = sim.SwimParams(loss=0.01)
    state = sim.init_state(n)
    net = sim.make_net(n)
    eye = jnp.eye(n, dtype=bool)
    status = state.view_key & 7
    pingable = ((status == sim.ALIVE) | (status == sim.SUSPECT)) & ~eye
    target = jnp.zeros((n,), jnp.int32)

    print(f"n={n}")

    def full_body(st, k):
        return sim.swim_step_impl(st, net, k, params)[0]

    timed_scan(full_body, state, "FULL STEP")

    def sel_body(p, k):
        t, has, w, wv = sim._choose_targets_and_witnesses(p, 3, k)
        return p ^ (t[:, None] == 0)

    timed_scan(sel_body, pingable, "targets+witnesses")

    def hash_body(vk, k):
        h = sim._view_hash(state._replace(view_key=vk))
        return vk + h[:, None].astype(jnp.int32)

    timed_scan(hash_body, state.view_key, "view_hash (x2)")

    def mpb_body(p, k):
        m = sim._max_piggyback(p, 15)
        return p ^ (m[:, None] == 0)

    timed_scan(mpb_body, pingable, "max_piggyback")

    in_key = jnp.broadcast_to(jnp.int32(8 + sim.ALIVE), (n, n))
    active = jnp.ones((n,), bool)

    def merge_body(st, k):
        return sim._merge_incoming(st, in_key ^ (st.tick & 1), active, 26).state

    timed_scan(merge_body, state, "merge_incoming (x2)")

    def scatter_body(ko, k):
        out = jnp.zeros((n, n), dtype=jnp.int32).at[target].max(ko)
        return ko + (out & 1)

    timed_scan(scatter_body, jnp.ones((n, n), jnp.int32), "row-scatter (x1)")

    # The RINGPOP_RECV_MERGE candidates, raced on identical inputs: a
    # realistic colliding receiver assignment with 90% delivery.  Off
    # TPU the pallas form would run in interpret mode (orders of
    # magnitude slow; it exists there for parity, not speed), so the
    # race covers it only on the live backend.
    t_rand = jax.random.randint(jax.random.PRNGKey(2), (n,), 0, n)
    fwd = jax.random.uniform(jax.random.PRNGKey(3), (n,)) < 0.9
    forms = ["sorted", "scatter"]
    if jax.default_backend() == "tpu":
        forms.append("pallas")
    else:
        print("  recv_merge[pallas]       skipped (interpret mode off-TPU)")
    for form in forms:
        with sim._force_recv_merge(form):

            def merge_form_body(ko, k):
                in_key, _ = sim._receiver_merge(t_rand, fwd, ko)
                return ko ^ (in_key & 1)

            timed_scan(
                merge_form_body,
                jnp.ones((n, n), jnp.int32),
                f"recv_merge[{form}]",
            )

    def gather_body(vk, k):
        g = vk[target]
        return vk + (g & 1)

    timed_scan(gather_body, state.view_key, "row-gather (x~2)")

    def bern1d_body(c, k):
        return c ^ (jax.random.uniform(k, (n,)) < 0.01)

    timed_scan(bern1d_body, jnp.zeros((n,), bool), "n bernoulli (x2)")


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 8192)

"""AOT lower/compile timing of delta_step phase prefixes on the ambient backend.

The delta backend's 65k program is compile-heavy on the tunneled TPU
platform (remote compile); this tool attributes that cost per phase the
same way benchmarks/profile_delta.py attributes run time — each static
``upto`` prefix compiles as one executable, so consecutive differences
localize the compile-time hog.

Usage: python -m benchmarks.profile_compile [n] [upto,upto,...]
"""

from __future__ import annotations

import sys
import time

import jax

from ringpop_tpu.utils import pin_cpu_if_requested

pin_cpu_if_requested()

from ringpop_tpu.models import swim_delta as sd
from ringpop_tpu.models import swim_sim as sim


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 65536
    uptos = [int(x) for x in (sys.argv[2].split(",") if len(sys.argv) > 2 else ["7"])]

    params = sd.DeltaParams(swim=sim.SwimParams(loss=0.01), wire_cap=16, claim_grid=64)
    state = sd.init_delta(n, capacity=256)
    net = sim.make_net(n)
    key = jax.random.PRNGKey(0)
    print(f"platform={jax.default_backend()} n={n}", flush=True)

    for u in uptos:
        fn = jax.jit(
            lambda st, nt, kk, u=u: sd.delta_step_impl(st, nt, kk, params, upto=u)
        )
        t0 = time.perf_counter()
        lowered = fn.lower(state, net, key)
        t1 = time.perf_counter()
        lowered.compile()
        t2 = time.perf_counter()
        print(f"upto={u}: lower {t1 - t0:.1f}s compile {t2 - t1:.1f}s", flush=True)


if __name__ == "__main__":
    main()

"""Scenario engine: one compiled dispatch vs the host-driven loop.

The scenario subsystem's reason to exist, measured: a chaos experiment
(kill + partition + heal + loss-ramp) whose every fault boundary used
to force the host loop to end the jitted run, mutate ``NetState`` and
re-dispatch, now runs as ONE ``lax.scan`` — and stacks the per-tick
telemetry the host loop never had.  Both arms replay the identical
fault sequence from the same seed (segment-exact key schedule), so the
final states are bit-identical and the delta is pure dispatch/compile
overhead.  Warm wall time is the headline; the cold (compile-included)
times are reported for context.
"""

from __future__ import annotations

import time

from ringpop_tpu.models import swim_sim as sim
from ringpop_tpu.models.cluster import SimCluster
from ringpop_tpu.scenarios import runner
from ringpop_tpu.scenarios.spec import ScenarioSpec


def _spec(n: int, ticks: int) -> ScenarioSpec:
    half = n // 2
    return ScenarioSpec.from_dict(
        {
            "ticks": ticks,
            "events": [
                {"at": ticks // 8, "op": "kill", "node": n - 1},
                {"at": ticks // 4, "op": "partition",
                 "groups": [list(range(half)), list(range(half, n))]},
                {"at": ticks // 4, "op": "loss", "p": 0.05},
                {"at": ticks // 2, "op": "heal"},
                {"at": ticks // 2 + 5, "op": "loss_ramp",
                 "until": ticks // 2 + 15, "to": 0.0},
            ],
        }
    )


def run(n: int = 2048, ticks: int = 120) -> list[dict]:
    spec = _spec(n, ticks)
    params = sim.SwimParams()

    def one_call():
        c = SimCluster(n, params, seed=11)
        before = runner.dispatch_count()
        t0 = time.perf_counter()
        trace = c.run_scenario(spec)
        wall = time.perf_counter() - t0
        return c, wall, runner.dispatch_count() - before, trace

    # cold (compile) then warm (executable cached)
    _, cold_one, dispatches, _ = one_call()
    c1, warm_one, _, trace = one_call()

    def host_loop():
        c = SimCluster(n, params, seed=11)
        t0 = time.perf_counter()
        runner.run_host_loop(c, spec)
        return c, time.perf_counter() - t0

    _, cold_host = host_loop()
    c2, warm_host = host_loop()

    match = c1.checksums() == c2.checksums()
    return [
        {
            "metric": f"scenario_one_call_n{n}_t{ticks}",
            "value": round(warm_one, 4),
            "unit": "s_warm",
            "cold_s": round(cold_one, 3),
            "dispatches": dispatches,
            "converged": bool(trace.converged[-1]),
        },
        {
            "metric": f"scenario_host_loop_n{n}_t{ticks}",
            "value": round(warm_host, 4),
            "unit": "s_warm",
            "cold_s": round(cold_host, 3),
            "segments": len({0, *spec_boundaries(spec)}),
            "speedup_one_call": round(warm_host / max(warm_one, 1e-9), 2),
            "checksums_match": match,
        },
    ]


def spec_boundaries(spec: ScenarioSpec) -> list[int]:
    from ringpop_tpu.scenarios.compile import compile_spec

    # n is only used for validation/gid rows; the boundary set is n-free
    flat = [m for e in spec.events if e.groups for g in e.groups for m in g]
    n = (max(flat) + 1) if flat else 2
    return list(compile_spec(spec, n).boundaries)


if __name__ == "__main__":
    import json

    for row in run(n=512, ticks=80):
        print(json.dumps(row))

"""Streamed chunked-scan soak: pipelined dispatch/drain vs the
blocking segment loop, at equal total ticks.

The streaming runner's reason to exist, measured end to end through
the public API on BOTH backends.  All arms run the SAME scenario from
the SAME seed — and because the streamed runner derives the identical
key schedule the one-dispatch run uses, every arm's final checksums
are bit-identical (asserted below; it is a correctness cross-check,
not a statistical accident):

* **pipelined** — ``run_scenario(segment_ticks=S)`` in the full soak
  configuration (segment store + PR 5 stats emitter): segment k+1 is
  dispatched before segment k's telemetry is pulled to host, so device
  compute overlaps trace conversion + npz store writes + per-tick
  stats bridging (``scenarios/stream.py``; the per-soak drain overlap
  is in the bench's own ledger, summarized by ``obs-ledger``).
* **blocking whole-trace loop** — the pre-streaming pattern for a
  memory-bounded long run: chop the spec into S-tick sub-scenarios
  and call ``run_scenario`` per chunk, saving each chunk's trace npz
  (the "one terminal npz" persistence a soak needs either way).
  Every chunk blocks on its dispatch, derives its own key schedule,
  materializes + validates a whole chunk ``Trace``, replays it
  through the emitter, saves it, and pulls a checksum row — and
  chunks with different event counts are different compiled shapes
  (several cold compiles, where the streamed runner has exactly one
  per segment shape).
* **unpipelined** — ablation: the streamed runner with
  ``pipeline=False`` (drain fully before the next dispatch), isolating
  what dispatch/drain overlap alone contributes.
* **whole** — the original one-dispatch ``run_scenario`` (same
  emitter; its trace replays in one terminal drain), for reference:
  competitive wall-clock at small T but O(T) host trace memory and no
  checkpoint/resume; the streamed arms are the ones that scale to
  1M-tick soaks.

The pipelined/unpipelined/whole arms are bit-identical trajectories
(same key schedule — asserted); the chunk loop draws keys per chunk,
so it is the same experiment (equal T, same faults) but not the same
bits, like any pre-streaming long run was.  Exactly one cold compile
serves every segment of a streamed arm; warm timings are best-of-4 —
on a shared CPU host the drain/compute interleaving is noisy, and the
minimum is the contention-free reading of each arm.
"""

from __future__ import annotations

import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _spec(n: int, ticks: int) -> dict:
    return {
        "ticks": ticks,
        "events": [
            {"at": ticks // 8, "op": "kill", "node": n - 1},
            {"at": ticks // 4, "op": "loss", "p": 0.05},
            {"at": ticks // 2, "op": "loss", "p": 0.0},
        ],
    }


def _chunk_specs(spec: dict, segment_ticks: int) -> list[dict]:
    """The spec chopped into S-tick sub-scenarios (events shifted to
    chunk-relative ticks) — what running a long scenario in bounded
    memory looked like before the streaming runner.  Loss persistence
    across chunks is free: ``run_scenario`` mirrors the final loss
    into the cluster params, which seeds the next chunk's base."""
    ticks = spec["ticks"]
    out = []
    for a in range(0, ticks, segment_ticks):
        b = min(a + segment_ticks, ticks)
        out.append(
            {
                "ticks": b - a,
                "events": [
                    {**e, "at": e["at"] - a}
                    for e in spec["events"]
                    if a <= e["at"] < b
                ],
            }
        )
    return out


def run(
    n: int = 128,
    ticks: int = 240,
    segment_ticks: int = 48,
    backends: tuple[str, ...] = ("dense", "delta"),
) -> list[dict]:
    from ringpop_tpu.models import swim_sim as sim
    from ringpop_tpu.models.cluster import SimCluster
    from ringpop_tpu.obs.emitters import make_emitter
    from ringpop_tpu.obs.ledger import default_ledger, summarize_runs

    spec = _spec(n, ticks)
    params = sim.SwimParams(suspicion_ticks=8)
    segments = -(-ticks // segment_ticks)
    rows = []
    for backend in backends:
        kw: dict = {"backend": backend}
        if backend == "delta":
            kw.update(capacity=min(256, n), wire_cap=16, claim_grid=64)

        workdir = tempfile.mkdtemp(prefix=f"bench-stream-{backend}-")
        ledger = default_ledger()
        ledger_was = ledger.enabled
        ledger.enable(os.path.join(workdir, "ledger.jsonl"))
        ledger.clear()

        def streamed(pipeline: bool, tag: str) -> tuple[float, dict]:
            store = os.path.join(workdir, f"store-{tag}")
            shutil.rmtree(store, ignore_errors=True)
            emitter = make_emitter(os.path.join(workdir, f"stats-{tag}.jsonl"))
            c = SimCluster(n, params, seed=11, stats_emitter=emitter, **kw)
            t0 = time.perf_counter()
            c.run_scenario(
                spec, segment_ticks=segment_ticks, store=store,
                assemble=False, pipeline=pipeline,
            )
            wall = time.perf_counter() - t0
            emitter.close()
            return wall, c.checksums()

        def whole() -> tuple[float, dict]:
            emitter = make_emitter(os.path.join(workdir, "stats-whole.jsonl"))
            c = SimCluster(n, params, seed=11, stats_emitter=emitter, **kw)
            t0 = time.perf_counter()
            c.run_scenario(spec)
            wall = time.perf_counter() - t0
            emitter.close()
            return wall, c.checksums()

        chunks = _chunk_specs(spec, segment_ticks)

        def chunk_loop() -> tuple[float, int]:
            emitter = make_emitter(os.path.join(workdir, "stats-loop.jsonl"))
            c = SimCluster(n, params, seed=11, stats_emitter=emitter, **kw)
            t0 = time.perf_counter()
            for i, chunk in enumerate(chunks):
                trace = c.run_scenario(chunk)
                trace.save(os.path.join(workdir, f"loop-chunk-{i:05d}.npz"))
            wall = time.perf_counter() - t0
            emitter.close()
            return wall, int(trace.converged[-1])

        # cold pass compiles the segment program (shared by both
        # streamed arms — same signature), the whole-run program, and
        # the chunk loop's one-shape-per-event-count programs
        cold_pipe, sums_pipe = streamed(True, "pipe")
        cold_block, sums_block = streamed(False, "block")
        cold_whole, sums_whole = whole()
        cold_loop, loop_conv = chunk_loop()
        assert sums_pipe == sums_block == sums_whole, (
            "streamed arms diverged from the one-dispatch run"
        )
        warm = {"pipelined": [], "unpipelined": [], "whole": [], "loop": []}
        for _ in range(4):
            warm["loop"].append(chunk_loop()[0])
            warm["unpipelined"].append(streamed(False, "block")[0])
            warm["pipelined"].append(streamed(True, "pipe")[0])
            warm["whole"].append(whole()[0])
        best = {k: min(v) for k, v in warm.items()}
        runs = summarize_runs(ledger.rows)
        cold_rows = [
            r for r in ledger.rows
            if r.get("run_id") and r.get("cold")
        ]
        # one cold compile per (backend, segment shape): the full-S
        # segment plus the ragged tail when S does not divide T
        shapes = {r["ticks"] for r in ledger.rows if r.get("run_id")}
        assert len(cold_rows) == len(shapes), (cold_rows, shapes)
        overlap = max((g["overlap_pct"] for g in runs), default=0.0)
        if not ledger_was:
            ledger.disable()
            ledger.clear()
        rows.append(
            {
                "metric": (
                    f"stream_pipelined_{backend}_n{n}_t{ticks}"
                    f"_s{segment_ticks}"
                ),
                "value": round(ticks / best["pipelined"], 1),
                "unit": "ticks_per_s_warm",
                "wall_s": round(best["pipelined"], 3),
                "cold_s": round(cold_pipe, 2),
                "segments": segments,
                "cold_compiles": len(cold_rows),
                "drain_overlap_pct_max": overlap,
                "speedup_vs_blocking_loop": round(
                    best["loop"] / max(best["pipelined"], 1e-9), 3
                ),
                "speedup_vs_unpipelined": round(
                    best["unpipelined"] / max(best["pipelined"], 1e-9), 3
                ),
                "ledger": os.path.join(workdir, "ledger.jsonl"),
            }
        )
        rows.append(
            {
                "metric": (
                    f"stream_blocking_loop_{backend}_n{n}_t{ticks}"
                    f"_s{segment_ticks}"
                ),
                "value": round(ticks / best["loop"], 1),
                "unit": "ticks_per_s_warm",
                "wall_s": round(best["loop"], 3),
                "cold_s": round(cold_loop, 2),
                "segments": segments,
                "converged": loop_conv,
            }
        )
        rows.append(
            {
                "metric": (
                    f"stream_unpipelined_{backend}_n{n}_t{ticks}"
                    f"_s{segment_ticks}"
                ),
                "value": round(ticks / best["unpipelined"], 1),
                "unit": "ticks_per_s_warm",
                "wall_s": round(best["unpipelined"], 3),
                "cold_s": round(cold_block, 2),
                "segments": segments,
            }
        )
        rows.append(
            {
                "metric": f"stream_whole_{backend}_n{n}_t{ticks}",
                "value": round(ticks / best["whole"], 1),
                "unit": "ticks_per_s_warm",
                "wall_s": round(best["whole"], 3),
                "cold_s": round(cold_whole, 2),
                "segments": 1,
            }
        )
    return rows


if __name__ == "__main__":
    import json

    kwargs: dict = {}
    args = [a for a in sys.argv[1:] if a.isdigit()]
    if args:
        kwargs["n"] = int(args[0])
    if len(args) > 1:
        kwargs["ticks"] = int(args[1])
    for row in run(**kwargs):
        print(json.dumps(row), flush=True)

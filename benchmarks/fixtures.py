"""Benchmark fixtures (reference: benchmarks/large-membership.json —
1,332 members with realistic 10.x addresses, status alive, wall-clock
incarnation numbers).  Generated deterministically instead of stored."""

from __future__ import annotations

LARGE_MEMBERSHIP_SIZE = 1332


def large_membership(n: int = LARGE_MEMBERSHIP_SIZE) -> list[dict]:
    members = []
    for i in range(n):
        address = f"10.{30 + i // 2500}.{(i // 25) % 100}.{i % 25 + 1}:{31000 + i % 1000}"
        members.append(
            {
                "address": address,
                "status": "alive",
                "incarnationNumber": 1414143508000 + i,
            }
        )
    return members

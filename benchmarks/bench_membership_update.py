"""membership.update() of a large changeset
(reference: benchmarks/large-membership-update.js — applies the
1,332-member fixture as one changeset, reports ops/sec)."""

from __future__ import annotations

import time

from benchmarks.fixtures import large_membership
from ringpop_tpu.harness import test_ringpop


def run(duration_s: float = 2.0) -> list[dict]:
    changes = large_membership()
    iterations = 0
    elapsed = 0.0
    deadline = time.perf_counter() + duration_s
    while time.perf_counter() < deadline:
        rp = test_ringpop(host_port="10.30.0.1:30000")
        t0 = time.perf_counter()
        rp.membership.update(changes)
        elapsed += time.perf_counter() - t0
        iterations += 1
    return [
        {
            "metric": "membership_update_1332",
            "value": round(iterations / elapsed, 2),
            "unit": "ops/sec",
            "iterations": iterations,
        }
    ]

"""BASELINE config 4 at scale: 50/50 netsplit + heal on a device mesh.

Runs the row-sharded SWIM simulation (ringpop_tpu/parallel) over all
available devices — on real hardware a pod slice; in CI/judging a
virtual 8-device CPU mesh (XLA_FLAGS=--xla_force_host_platform_device_count=8)
— through a full partition lifecycle:

  converged cluster -> 50/50 block netsplit -> each side declares the
  other faulty (suspicion expiry) -> heal -> refutations + gossip
  re-merge -> every live node one view again.

Correctness target (VERDICT round 1, item 7): the sharded shapes and
collectives must compile, execute, and *converge* at large N — perf
stays a single-chip metric (bench.py).

    python benchmarks/bench_partition_heal_sharded.py [n] [--ticks-only T]
                                                      [--sparse-cap C]

``--ticks-only`` runs T ticks of the split phase and exits (existence
proof for sizes whose full heal exceeds the host's RAM/time budget).
``--sparse-cap`` switches to the sparse dissemination path — required
past ~32k on a 125 GB host (the recorded 65,536 run used
``--ticks-only 2 --sparse-cap 64``; dense [N, N] int32 claim matrices
alone would need ~370 GB there, see BASELINE.md).
"""

from __future__ import annotations

import json
import sys
import time

sys.path.insert(0, ".")


def main(argv: list[str]) -> None:
    n = int(argv[1]) if len(argv) > 1 and not argv[1].startswith("-") else 65536
    ticks_only = 0
    if "--ticks-only" in argv:
        ticks_only = int(argv[argv.index("--ticks-only") + 1])
    # sparse dissemination (SwimParams.sparse_cap): the dense phase-3/4
    # claim matrices are N x N int32 (17 GB each at 65k) and the step's
    # transient footprint is ~14x the state (measured via peak RSS on the
    # 8-device CPU mesh) — past ~32k the dense tick cannot fit a 125 GB
    # host.  The capped claim lists keep the step's temporaries at
    # O(N * cap), which is what makes the 65,536 existence run possible.
    sparse_cap = 0
    if "--sparse-cap" in argv:
        sparse_cap = int(argv[argv.index("--sparse-cap") + 1])

    import os

    # On the virtual CPU mesh the 8 device threads time-share the host
    # cores; heavy ticks make some of them miss XLA's default 40 s
    # collective rendezvous deadline, which *aborts the process* (fatal
    # rendezvous.cc check).  Raise it before jax initializes.
    flags = os.environ.get("XLA_FLAGS", "")
    if "collective_call_terminate_timeout" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_cpu_collective_call_terminate_timeout_seconds=7200"
        ).strip()

    import jax

    from ringpop_tpu.utils import pin_cpu_if_requested

    pin_cpu_if_requested()

    if jax.default_backend() == "cpu" and len(jax.devices()) < 8:
        raise SystemExit(
            "need a multi-device mesh: set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu"
        )
    import jax.numpy as jnp

    from ringpop_tpu import parallel
    from ringpop_tpu.models import swim_sim as sim

    params = sim.SwimParams(sparse_cap=sparse_cap)
    mesh = parallel.make_mesh()
    d = len(mesh.devices.ravel())

    t0 = time.time()
    state = jax.jit(
        lambda: sim.init_state(n), out_shardings=parallel.state_sharding(mesh)
    )()
    half = n // 2

    # group-id adjacency: a 50/50 block netsplit as an int32[N] vector
    # (connected iff same group, swim_sim._adj) — the N x N mask form
    # costs 4 GB at 32k / 17 GB at 65k for a block structure the
    # kernels only ever evaluate at gathered index pairs.
    gid_split = (jnp.arange(n, dtype=jnp.int32) >= half).astype(jnp.int32)
    net = sim.NetState(
        up=jnp.ones((n,), bool), responsive=jnp.ones((n,), bool), adj=gid_split
    )
    step = parallel.sharded_step(mesh, net_like=net)
    print(f"# n={n} mesh={d}dev init {time.time() - t0:.0f}s", file=sys.stderr, flush=True)

    @jax.jit
    def probe(st):
        """(all views equal, per-row alive counts) over the sharded state.

        Counts reduce per row (int32[n], each <= n) and finish as Python
        ints on the host: a full int32 scalar reduction overflows at
        n=65536 where n*n/2 = 2**31 (and x64 is disabled)."""
        same = jnp.all(st.view_key == st.view_key[0][None, :])
        alive_rows = jnp.sum(
            (st.view_key & 7) == sim.ALIVE, axis=1, dtype=jnp.int32
        )
        return same, alive_rows

    key = jax.random.PRNGKey(0)
    split_ticks = params.suspicion_ticks + 15
    t0 = time.time()
    total = ticks_only if ticks_only else split_ticks
    for i in range(total):
        key, sub = jax.random.split(key)
        state, m = step(state, net, sub, params)
        if i == 0:
            int(m["pings_sent"])
            print(f"# first tick {time.time() - t0:.0f}s", file=sys.stderr, flush=True)
    import numpy as np

    faulty = int(
        np.asarray(
            jax.jit(
                lambda st: jnp.sum(
                    (st.view_key & 7) == sim.FAULTY, axis=1, dtype=jnp.int32
                )
            )(state)
        ).sum(dtype=np.int64)
    )
    print(
        f"# split phase done {time.time() - t0:.0f}s, faulty pairs {faulty}",
        file=sys.stderr,
        flush=True,
    )
    if ticks_only:
        print(
            json.dumps(
                {
                    "metric": f"sharded_split_n{n}_dev{d}",
                    "value": ticks_only,
                    "unit": "ticks_executed",
                    "faulty_pairs": faulty,
                    "sparse_cap": sparse_cap,
                    "compiled_and_ran": True,
                }
            )
        )
        return
    # each side should have declared (at least most of) the other faulty
    assert faulty > 0.9 * (n * n / 2), f"split did not take: {faulty}"

    # heal: one group for everyone, SAME pytree structure as the split net
    net = net._replace(adj=jnp.zeros((n,), jnp.int32))
    heal_ticks = 0
    t0 = time.time()
    while heal_ticks < 400:
        for _ in range(5):
            key, sub = jax.random.split(key)
            state, _ = step(state, net, sub, params)
        heal_ticks += 5
        same, alive_rows = probe(state)
        alive = int(np.asarray(alive_rows).sum(dtype=np.int64))
        print(
            f"# heal tick {heal_ticks}: views_equal={bool(same)} "
            f"alive_pairs={alive} ({time.time() - t0:.0f}s)",
            file=sys.stderr,
            flush=True,
        )
        if bool(same) and alive == n * n:
            break
    print(
        json.dumps(
            {
                "metric": f"sharded_partition_heal_n{n}_dev{d}",
                "value": heal_ticks,
                "unit": "ticks_to_remerge",
                "split_ticks": split_ticks,
                "sparse_cap": sparse_cap,
                "converged": bool(same) and alive == n * n,
            }
        )
    )


if __name__ == "__main__":
    main(sys.argv)

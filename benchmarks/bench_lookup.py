"""Batched device ring lookups vs the host one-key loop.

The serving question behind ROADMAP's "millions of users" axis: how
many consistent-hash lookups per second does each path sustain?

* **host loop** — the status quo before the traffic plane: one
  ``HashRing.lookup(key)`` per key (farmhash + bisect per call), the
  way every serving-layer call site worked (``models/cluster.py``'s
  old ``lookup`` loop).
* **device batch** — ``ops/ring_ops.lookup_idx``: one ``searchsorted``
  over the whole pre-hashed key tensor (the workload contract:
  traffic/workloads.py pools are hashed once, up front).
* **device masked** — the traffic engine's actual hot path
  (``traffic.engine.lookup_masked_idx``): the same batch resolved
  through a per-viewer membership mask over the GLOBAL ring, i.e. a
  per-viewer ring that never materializes.

Device arms dispatch through the obs ledger, so each rung leaves a
compile-vs-execute forensics row; every JSON line carries the ledger
path (bench.py convention).  A final scenario-coupled config runs a
kill under load (SimCluster.run_scenario + traffic) and reports the
misroute-vs-ring-divergence correlation from the trace the stats
bridge streams.

Usage: python -m benchmarks.bench_lookup  (or via benchmarks.run_all)
"""

from __future__ import annotations

import os
import time

import numpy as np

DEFAULT_LEDGER_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "bench_lookup_ledger.jsonl"
)


def _best_keys_per_sec(fn, m: int, repeats: int) -> float:
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return m / best


def run(n: int = 64, repeats: int = 3, batches=(1024, 16384)) -> list[dict]:
    import jax
    import jax.numpy as jnp

    from ringpop_tpu.hashring import HashRing
    from ringpop_tpu.obs.ledger import default_ledger
    from ringpop_tpu.ops import ring_ops
    from ringpop_tpu.traffic import engine as tengine
    from ringpop_tpu.traffic.workloads import WorkloadSpec

    led = default_ledger()
    if not led.enabled:
        open(DEFAULT_LEDGER_PATH, "w").close()  # fresh forensics per run
        led.enable(DEFAULT_LEDGER_PATH)
    ledger_path = led.path

    addrs = [f"10.0.{i // 250}.{i % 250}:{3000 + i}" for i in range(n)]
    host = HashRing()
    host.add_remove_servers(addrs, [])
    ring = ring_ops.build_ring(addrs)
    platform = jax.devices()[0].platform

    lookup_jit = jax.jit(ring_ops.lookup_idx)
    masked_jit = jax.jit(
        lambda rh, ro, kh, mask: tengine.lookup_masked_idx(
            rh, ro, kh, mask, window=256
        )
    )

    results: list[dict] = []
    pool = WorkloadSpec(pool=max(batches)).pool_keys()
    for m in batches:
        keys = pool[:m]
        khash_np = np.array([host.hash_func(k) for k in keys], dtype=np.uint32)
        khash = jnp.asarray(khash_np)
        mask = jnp.ones((m, n), dtype=bool)

        def host_loop():
            for k in keys:
                host.lookup(k)

        # timed arms are the BARE compiled calls: the ledger's
        # per-dispatch bookkeeping (signature hash + JSON row) would be
        # a fixed overhead comparable to the kernel at small batches
        def device_batch():
            lookup_jit(ring, khash).block_until_ready()

        def device_masked():
            masked_jit(ring.hashes, ring.owners, khash, mask)[
                0
            ].block_until_ready()

        # one ledgered dispatch per arm, outside the measurement loop:
        # the compile-vs-execute forensics row without polluting timings
        led.dispatch(
            "bench_lookup_batch", lookup_jit, ring, khash,
            _meta={"backend": "device", "n": n, "ticks": 1, "replicas": m},
        )
        led.dispatch(
            "bench_lookup_masked", masked_jit,
            ring.hashes, ring.owners, khash, mask,
            _meta={"backend": "device", "n": n, "ticks": 1, "replicas": m},
        )
        device_batch()  # compile outside the timed region
        device_masked()
        host_rate = _best_keys_per_sec(host_loop, m, repeats)
        dev_rate = _best_keys_per_sec(device_batch, m, repeats)
        masked_rate = _best_keys_per_sec(device_masked, m, repeats)
        base = {
            "unit": "keys/sec",
            "n": n,
            "batch": m,
            "platform": platform,
            "ledger": ledger_path,
        }
        results += [
            {**base, "metric": "lookup_host_loop",
             "value": round(host_rate, 1)},
            {**base, "metric": "lookup_device_batch",
             "value": round(dev_rate, 1),
             "speedup_vs_host": round(dev_rate / host_rate, 2)},
            {**base, "metric": "lookup_device_masked",
             "value": round(masked_rate, 1),
             "speedup_vs_host": round(masked_rate / host_rate, 2)},
        ]
    results += _scenario_coupled(ledger_path, platform)
    return results


def _scenario_coupled(ledger_path: str | None, platform: str) -> list[dict]:
    """A kill under load: one compiled scenario+traffic dispatch, the
    trace replayed through the stats bridge, and the headline number —
    how tightly per-tick misroutes track ring divergence."""
    from ringpop_tpu.models.cluster import SimCluster
    from ringpop_tpu.models.swim_sim import SwimParams
    from ringpop_tpu.obs.emitters import CaptureEmitter

    cap = CaptureEmitter()
    cluster = SimCluster(16, SwimParams(), seed=3, stats_emitter=cap)
    spec = {
        "ticks": 40,
        "events": [
            {"at": 5, "op": "kill", "node": 3},
            {"at": 25, "op": "revive", "node": 3},
        ],
    }
    t0 = time.perf_counter()
    trace = cluster.run_scenario(spec, traffic="uniform:256")
    wall = time.perf_counter() - t0
    mis = trace.metrics["misroutes"].astype(np.float64)
    div = trace.metrics["ring_divergence"].astype(np.float64)
    if mis.std() > 0 and div.std() > 0:
        corr = float(np.corrcoef(mis, div)[0, 1])
    else:
        corr = 0.0
    bridged = sum(1 for _, key, _ in cap.calls if "lookup" in key)
    return [{
        "metric": "scenario_traffic_misroute_divergence_corr",
        "value": round(corr, 3),
        "unit": "pearson-r",
        "n": 16,
        "ticks": 40,
        "misroutes_total": int(mis.sum()),
        "divergence_ticks": int((div > 0).sum()),
        "bridged_lookup_stats": bridged,
        "wall_s": round(wall, 2),
        "platform": platform,
        "ledger": ledger_path,
    }]


if __name__ == "__main__":
    import json

    for row in run():
        print(json.dumps(row), flush=True)

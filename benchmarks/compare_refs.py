"""Cross-ref benchmark regression harness.

The reference's ``benchmarks/run.js:83-102`` runs a benchmark file at
every git ref in a range and diffs the ``ops/sec`` lines.  This is that
tool for this repo: run the benchmark suite at two (or more) refs in
throwaway worktrees, join results by metric name, and print the delta
table — the perf-regression gate for ring/membership/simulation changes.

Usage:
    python benchmarks/compare_refs.py REF [REF2] [-- run_all args...]

With one REF, compares it against the working tree.  Extra args after
``--`` pass through to ``benchmarks.run_all`` (default: ``--fast``).
Exit code 1 when any shared metric regressed by more than REGRESS_PCT.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

REGRESS_PCT = 20.0  # noise floor for the 1-core CI box

# metrics where higher is better and gate the exit code; run_all's other
# units (fractions, tick counts) are informational
RATE_UNITS = {"ops/sec"}


def run_suite(tree: str, label: str, extra: list[str]) -> dict[str, dict]:
    """Run the suite; a nonzero exit or an empty result FAILS the gate
    loudly (a silently-shrunken metric set would pass regressions)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "benchmarks.run_all", *extra],
        cwd=tree,
        capture_output=True,
        text=True,
        timeout=3600,
        env=env,
    )
    out: dict[str, dict] = {}
    for line in proc.stdout.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if "metric" in rec:
            out[rec["metric"]] = rec
    if proc.returncode != 0 or not out:
        tail = proc.stderr.strip().splitlines()[-3:]
        raise SystemExit(
            f"suite at {label} failed (rc={proc.returncode}, "
            f"{len(out)} metrics): " + " | ".join(tail)
        )
    return out


def at_ref(ref: str, extra: list[str]) -> dict[str, dict]:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with tempfile.TemporaryDirectory(prefix=f"bench-{ref.replace('/', '_')}-") as tmp:
        subprocess.run(
            ["git", "worktree", "add", "--detach", tmp, ref],
            cwd=repo,
            check=True,
            capture_output=True,
        )
        try:
            return run_suite(tmp, ref, extra)
        finally:
            subprocess.run(
                ["git", "worktree", "remove", "--force", tmp],
                cwd=repo,
                capture_output=True,
            )


def main(argv: list[str]) -> int:
    args = argv[1:]
    extra = ["--fast"]
    if "--" in args:
        split = args.index("--")
        args, extra = args[:split], args[split + 1 :]
    if not args:
        print(__doc__)
        return 2
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    refs: list[tuple[str, dict[str, dict]]] = []
    for ref in args:
        print(f"# running suite at {ref} ...", file=sys.stderr, flush=True)
        refs.append((ref, at_ref(ref, extra)))
    if len(refs) == 1:  # single REF: compare against the working tree
        print("# running suite at working tree ...", file=sys.stderr, flush=True)
        refs.append(("worktree", run_suite(repo, "worktree", extra)))

    base_name, base = refs[0]
    regressed = []
    for name, results in refs[1:]:
        print(f"\n== {base_name} -> {name} ==")
        for metric in sorted(set(base) & set(results)):
            v0, v1 = base[metric].get("value"), results[metric].get("value")
            if not isinstance(v0, (int, float)) or not isinstance(v1, (int, float)):
                continue
            delta = (v1 - v0) / v0 * 100 if v0 else float("nan")
            unit = results[metric].get("unit", "")
            flag = ""
            if unit in RATE_UNITS and delta < -REGRESS_PCT:
                flag = "  <-- REGRESSION"
                regressed.append((metric, delta))
            print(f"{metric:<48} {v0:>14.4g} -> {v1:>14.4g}  {delta:+7.1f}%{flag}")
        only_base = set(base) - set(results)
        only_new = set(results) - set(base)
        for m in sorted(only_base):
            print(f"{m:<48} (removed)")
        for m in sorted(only_new):
            print(f"{m:<48} (new)")
    if regressed:
        print(f"\n{len(regressed)} regression(s) beyond {REGRESS_PCT}%", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))

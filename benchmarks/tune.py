#!/usr/bin/env python
"""Sweep-driven Pareto tuner over the incident suite (BASELINE round 10).

The compile-once knob plane (``run_sweep(param_axes=...)`` /
``policy_axes=...``) turns protocol tuning from a recompile-per-point
grid search into a handful of vmapped dispatches: every knob value is
a traced int32/float scalar batched along the replica axis, so one
compiled signature serves the whole grid.  This script runs the full
incident x traffic x knob grid in FIVE dispatches (declared budget:
``DISPATCH_BUDGET = 10``) and reports:

* the Pareto frontier of detection latency vs false-faulty count vs
  gossip bytes (proxy) vs serve p99 over a shared
  ``suspicion_ticks x piggyback_factor`` grid, measured on the two
  incidents that pull those objectives in opposite directions
  (``thundering_rejoin`` wants fast detection and cheap mass rejoin;
  ``brownout_loss_ramp`` punishes trigger-happy detectors with
  false-faulty declarations — nothing there is actually down);
* the auto-located flap/suspicion regime boundary on the PR 10 flap
  storm (down=3/up=4): the suspicion_ticks value below which flapping
  nodes stop evading declaration, found in one dispatch instead of
  the hand-bisection BASELINE round 6 recorded;
* the ping-req fanout curve (capacity-padded ``ping_req_size`` knob)
  under the cross-rack-delay incident;
* a tuned operating point for the admission policy's shed hysteresis
  on the n=64 ``cascading_overload`` headline — the round-9 table
  showed default admission over-shedding (goodput 0.392, 37k sheds).

Each arm runs under its own ledger ``program_tag``, so the in-memory
dispatch ledger proves the compile-once contract directly: the script
asserts the dispatch count stays within ``DISPATCH_BUDGET`` and that
the ledger holds ZERO ``recompile_cause`` rows.

    JAX_PLATFORMS=cpu python benchmarks/tune.py
    JAX_PLATFORMS=cpu python benchmarks/tune.py --micro   # CI smoke grid
    JAX_PLATFORMS=cpu python benchmarks/tune.py --json /tmp/tune.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from ringpop_tpu.models.cluster import SimCluster  # noqa: E402
from ringpop_tpu.models.swim_sim import SwimParams  # noqa: E402
from ringpop_tpu.obs import ledger as obs_ledger  # noqa: E402
from ringpop_tpu.scenarios import library as lib  # noqa: E402
from ringpop_tpu.scenarios import sweep as ssweep  # noqa: E402

DISPATCH_BUDGET = 10  # hard ceiling; the planned grid uses 5
SEED = 3  # the BASELINE pin seed

# nominal wire weights for the gossip-bytes proxy: a probe/ack/ping-req
# envelope (addr + incarnation + sequence) and one piggybacked change
# entry.  Applied-change counters are the per-dispatch observable;
# shipped entries scale with them at fixed loss, so the proxy preserves
# the frontier ORDERING even though the absolute byte counts are
# nominal.  A full sync ships the whole n-entry table.
HDR_BYTES = 32
CHANGE_BYTES = 24


def wire_bytes_proxy(m: dict[str, np.ndarray], n: int) -> int:
    """Gossip bytes shipped by one replica, from its [ticks] counters."""
    msgs = m["pings_sent"].sum() + m["acks"].sum()
    msgs += m.get("ping_reqs", np.zeros(1)).sum()
    changes = m["ping_changes_applied"].sum() + m["ack_changes_applied"].sum()
    changes += m.get("pingreq_changes_applied", np.zeros(1)).sum()
    syncs = m.get("full_syncs", np.zeros(1)).sum() * n
    return int(HDR_BYTES * msgs + CHANGE_BYTES * (changes + syncs))


def pareto_front(rows: list[dict], keys: tuple[str, ...]) -> list[dict]:
    """Non-dominated subset of ``rows`` minimizing every key at once."""
    front = []
    for a in rows:
        dominated = any(
            all(b[k] <= a[k] for k in keys)
            and any(b[k] < a[k] for k in keys)
            for b in rows
        )
        if not dominated:
            front.append(a)
    return front


def knee_point(front: list[dict], keys: tuple[str, ...]) -> dict:
    """The frontier point minimizing the normalized objective sum —
    the single recommended operating point when no objective is
    privileged."""
    lo = {k: min(r[k] for r in front) for k in keys}
    hi = {k: max(r[k] for r in front) for k in keys}

    def score(r):
        return sum(
            (r[k] - lo[k]) / (hi[k] - lo[k]) if hi[k] > lo[k] else 0.0
            for k in keys
        )

    return min(front, key=score)


def _replica_metrics(tr, r: int) -> dict[str, np.ndarray]:
    return {k: np.asarray(v[r]) for k, v in tr.metrics.items()}


def _p99(tr, r: int) -> int:
    rows = tr.serving_summary()
    if rows is None:
        return 0
    return int(rows[r].get("lat_p99_ms", 0))


# ---------------------------------------------------------------------------
# the five arms
# ---------------------------------------------------------------------------


def arm_frontier(cfg) -> tuple[list[dict], dict]:
    """Arms 1+2 (one dispatch each): the shared suspicion x piggyback
    grid on thundering_rejoin (detect latency + bytes + p99) and
    brownout_loss_ramp (false-faulty + p99), joined per grid index."""
    grid = [(s, p) for s in cfg.suspicion for p in cfg.piggyback]
    axes = {
        "suspicion_ticks": [s for s, _ in grid],
        "piggyback_factor": [p for _, p in grid],
    }
    r_count = len(grid)

    spec_a, wl_a = lib.build_incident(
        "thundering_rejoin", cfg.n, ticks=cfg.ticks
    )
    kill_at = min(e.at for e in spec_a.events if e.op == "kill")
    c = SimCluster(cfg.n, SwimParams(), seed=SEED)
    tr_a = c.run_sweep(
        spec_a, r_count, traffic=wl_a, param_axes=axes,
        program_tag="frontier-rejoin",
    )

    spec_b, wl_b = lib.build_incident(
        "brownout_loss_ramp", cfg.n, ticks=cfg.ticks
    )
    c = SimCluster(cfg.n, SwimParams(), seed=SEED)
    tr_b = c.run_sweep(
        spec_b, r_count, traffic=wl_b, param_axes=axes,
        program_tag="frontier-brownout",
    )

    det = tr_a.detect_ticks()
    rows = []
    for i, (s, p) in enumerate(grid):
        m_a = _replica_metrics(tr_a, i)
        m_b = _replica_metrics(tr_b, i)
        rows.append({
            "suspicion_ticks": s,
            "piggyback_factor": p,
            # detection latency after the mass kill; undetected grid
            # points get a past-the-end penalty so they sort last
            "detect_latency": (
                int(det[i]) - kill_at if det[i] >= 0 else spec_a.ticks
            ),
            # brownout declares are ALL false-faulty: nothing is down
            "false_faulty": int(m_b["faulty_declared"].sum()),
            "gossip_kb": (
                wire_bytes_proxy(m_a, cfg.n)
                + wire_bytes_proxy(m_b, cfg.n)
            ) // 1024,
            "serve_p99_ms": max(_p99(tr_a, i), _p99(tr_b, i)),
        })
    objectives = (
        "detect_latency", "false_faulty", "gossip_kb", "serve_p99_ms"
    )
    front = pareto_front(rows, objectives)
    return rows, {
        "objectives": objectives,
        "front": front,
        "knee": knee_point(front, objectives),
        "kill_at": kill_at,
    }


def arm_boundary(cfg) -> dict:
    """One dispatch: the PR 10 flap storm (down=3/up=4) with
    suspicion_ticks swept along the replica axis — the regime boundary
    is the smallest suspicion value whose flapping nodes evade
    declaration for the whole run."""
    n, ticks = cfg.boundary_n, cfg.boundary_ticks
    spec = {
        "ticks": ticks,
        "events": [{
            "at": 10, "op": "flap", "nodes": [n - 2, n - 3, n - 4],
            "until": int(ticks * 0.6), "down": 3, "up": 4, "stagger": 2,
        }],
    }
    c = SimCluster(n, SwimParams(), seed=SEED)
    tr = c.run_sweep(
        spec, len(cfg.boundary_suspicion),
        param_axes={"suspicion_ticks": list(cfg.boundary_suspicion)},
        program_tag="flap-boundary",
    )
    det = tr.detect_ticks()
    detected = {
        s: bool(det[i] >= 0) for i, s in enumerate(cfg.boundary_suspicion)
    }
    evading = [s for s, hit in detected.items() if not hit]
    return {
        "suspicion_axis": list(cfg.boundary_suspicion),
        "detected": detected,
        # None when every sweep point still declares (boundary above
        # the axis) — the full axis tops out at the PR 10 pin of 12
        "boundary": min(evading) if evading else None,
        "hand_found": "suspicion 12 with down=3 never declares (round 6)",
    }


def arm_pingreq(cfg) -> list[dict]:
    """One dispatch: effective ping-req fanout k swept 1..k_max under
    the brownout loss ramp — the capacity-padded knob (compiled at
    k_max, witnesses masked to the traced k).  Loss is what fires the
    indirect-probe path, so this is the incident where fanout earns
    its bytes: more witnesses, fewer false suspicions."""
    spec, wl = lib.build_incident(
        "brownout_loss_ramp", cfg.n, ticks=cfg.ticks
    )
    c = SimCluster(cfg.n, SwimParams(), seed=SEED)
    tr = c.run_sweep(
        spec, len(cfg.pingreq_axis), traffic=wl,
        param_axes={"ping_req_size": list(cfg.pingreq_axis)},
        program_tag="pingreq-fanout",
    )
    det = tr.detect_ticks()
    rows = []
    for i, k in enumerate(cfg.pingreq_axis):
        m = _replica_metrics(tr, i)
        row = {
            "ping_req_size": k,
            "detect_tick": int(det[i]),
            "ping_reqs": int(m.get("ping_reqs", np.zeros(1)).sum()),
            "false_faulty": int(m["faulty_declared"].sum()),
            "serve_p99_ms": _p99(tr, i),
        }
        serving = tr.serving_summary()
        if serving is not None:
            row["gray_timeouts"] = int(serving[i].get("gray_timeouts", 0))
        rows.append(row)
    return rows


def arm_admission(cfg) -> tuple[list[dict], dict]:
    """One dispatch: the admission policy's shed hysteresis swept on
    the n=64 cascading_overload headline.  Round 9 pinned the default
    point (shed_hi = 2*base) over-shedding: goodput 0.392 vs the
    quarantine arms' 1.000.  The sweep raises the latch threshold
    until shedding stops eating deliverable traffic."""
    spec, wl = lib.build_incident(
        "cascading_overload", cfg.admission_n, ticks=cfg.admission_ticks
    )
    shed_hi = list(cfg.shed_hi_axis)
    # keep the hysteresis width proportional: release at half the latch
    shed_lo = [max(1, v // 2) for v in shed_hi]
    c = SimCluster(cfg.admission_n, SwimParams(), seed=SEED)
    tr = c.run_sweep(
        spec, len(shed_hi), traffic=wl, policy="admission",
        policy_axes={"shed_hi": shed_hi, "shed_lo": shed_lo},
        program_tag="admission-shed",
    )
    serving = tr.serving_summary()
    rows = []
    for i, hi in enumerate(shed_hi):
        s = serving[i]
        rows.append({
            "shed_hi": hi,
            "shed_lo": shed_lo[i],
            "goodput": round(s["goodput"], 3),
            "amplification": round(s["amplification"], 2),
            "shed": s.get("policy_shed", 0),
            "gray_timeouts": s.get("gray_timeouts", 0),
            "serve_p99_ms": s.get("lat_p99_ms", 0),
        })
    # recommended = best goodput among the points that keep the gray
    # cascade fully closed (minimum gray timeouts) — raw max-goodput
    # would buy a few points of goodput by letting the cascade leak
    min_gray = min(r["gray_timeouts"] for r in rows)
    best = max(
        (r for r in rows if r["gray_timeouts"] == min_gray),
        key=lambda r: r["goodput"],
    )
    return rows, best


# ---------------------------------------------------------------------------
# grid configuration (full vs --micro)
# ---------------------------------------------------------------------------


class Config:
    def __init__(self, micro: bool):
        if micro:
            self.n = 16
            self.ticks = 40
            self.suspicion = [6, 12]
            self.piggyback = [15]
            self.boundary_n = 16
            self.boundary_ticks = 40
            self.boundary_suspicion = [2, 12]
            self.pingreq_axis = [1, 3]
            self.admission_n = 16
            self.admission_ticks = 40
            self.shed_hi_axis = [6, 24]
        else:
            self.n = 32
            self.ticks = None  # incident defaults
            self.suspicion = [6, 12, 25, 40]
            self.piggyback = [6, 15]
            # PR 10 flap-storm configuration (bench_faults, round 6)
            self.boundary_n = 48
            self.boundary_ticks = 80
            self.boundary_suspicion = [1, 2, 3, 4, 6, 8, 10, 12]
            self.pingreq_axis = [1, 2, 3]
            self.admission_n = 64
            self.admission_ticks = None
            # default admission point for n=64 @ 512 keys/tick is
            # base=12 -> shed_hi=24; sweep upward from there
            self.shed_hi_axis = [24, 36, 48, 64, 96, 128, 192, 256]


def _table(rows: list[dict]) -> str:
    keys = list(rows[0])
    lines = ["| " + " | ".join(keys) + " |",
             "|" + "---|" * len(keys)]
    for r in rows:
        lines.append("| " + " | ".join(str(r[k]) for k in keys) + " |")
    return "\n".join(lines)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--micro", action="store_true",
                    help="CI smoke grid: tiny n/ticks, 2-point axes")
    ap.add_argument("--json", metavar="PATH",
                    help="also dump the result object as JSON")
    args = ap.parse_args()
    cfg = Config(args.micro)

    led = obs_ledger.default_ledger().enable(None)  # in-memory rows
    d0 = ssweep.dispatch_count()
    t0 = time.time()

    out: dict = {"micro": args.micro, "dispatch_budget": DISPATCH_BUDGET}

    grid_rows, frontier = arm_frontier(cfg)
    out["grid"] = grid_rows
    out["frontier"] = frontier
    print(f"## Knob frontier ({len(grid_rows)} grid points, 2 dispatches)")
    print(_table(grid_rows))
    print(f"\nPareto frontier ({len(frontier['front'])} points) on "
          f"{', '.join(frontier['objectives'])}; recommended knee:")
    print(_table([frontier["knee"]]))

    out["boundary"] = arm_boundary(cfg)
    b = out["boundary"]
    print("\n## Flap/suspicion regime boundary (1 dispatch)")
    print(f"axis {b['suspicion_axis']} -> detected {b['detected']}")
    print(f"auto-located boundary: suspicion_ticks >= "
          f"{b['boundary'] if b['boundary'] is not None else '(above axis)'}"
          f" evades; hand-found pin: {b['hand_found']}")

    out["pingreq"] = arm_pingreq(cfg)
    print("\n## Ping-req fanout (capacity-padded knob, 1 dispatch)")
    print(_table(out["pingreq"]))

    adm_rows, adm_best = arm_admission(cfg)
    out["admission"] = {"rows": adm_rows, "recommended": adm_best}
    print("\n## Admission shed hysteresis (1 dispatch)")
    print(_table(adm_rows))
    print("recommended operating point:")
    print(_table([adm_best]))

    # -- the compile-once contract, asserted ---------------------------------
    dispatches = ssweep.dispatch_count() - d0
    recompiles = [r for r in led.rows if r.get("recompile_cause")]
    out["dispatches"] = dispatches
    out["recompile_rows"] = len(recompiles)
    print(f"\ndispatches: {dispatches} (budget {DISPATCH_BUDGET}), "
          f"recompile rows: {len(recompiles)}, "
          f"wall: {time.time() - t0:.0f}s")
    if dispatches > DISPATCH_BUDGET:
        raise SystemExit(
            f"dispatch budget blown: {dispatches} > {DISPATCH_BUDGET}"
        )
    if recompiles:
        raise SystemExit(
            "recompile_cause rows in the ledger: "
            + json.dumps(recompiles[:3], default=str)
        )

    if args.json:
        with open(args.json, "w") as fh:
            json.dump(out, fh, indent=1, default=str)
        print(f"wrote {args.json}")


if __name__ == "__main__":
    main()

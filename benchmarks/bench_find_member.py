"""findMemberByAddress out of 1,000 members
(reference: benchmarks/find-member-by-address.js)."""

from __future__ import annotations

import random
import time

from benchmarks.fixtures import large_membership
from ringpop_tpu.harness import test_ringpop


def run(duration_s: float = 1.0) -> list[dict]:
    members = large_membership(1000)
    rp = test_ringpop(host_port="10.30.0.1:30000")
    rp.membership.update(members)
    addresses = [m["address"] for m in members]
    rng = random.Random(1)
    iterations = 0
    t0 = time.perf_counter()
    deadline = t0 + duration_s
    while time.perf_counter() < deadline:
        addr = addresses[rng.randrange(len(addresses))]
        assert rp.membership.find_member_by_address(addr) is not None
        iterations += 1
    elapsed = time.perf_counter() - t0
    return [
        {
            "metric": "find_member_by_address_1000",
            "value": round(iterations / elapsed, 2),
            "unit": "ops/sec",
        }
    ]

"""Delta-backend scale bench: 256k-1M virtual nodes on one chip.

Substantiates swim_delta.py's "a 1,048,576-node cluster still fits one
chip" claim (BASELINE configs 3/5 family) with a measured churn
scenario, exercising the maintenance path in the loop:

  converged cluster at n -> steady 0.5% loss -> kill a node, let the
  cluster converge on it (suspect -> faulty), revive+rejoin it -> rebase
  folds the healed divergence back into the base -> repeat.

Prints one JSON line per size:
  {"metric": "delta_scale_node_rounds_per_sec_n<N>", "value": ...,
   "unit": "node-rounds/s", "vs_baseline": ..., "occupancy": ...,
   "overflow_drops": ..., "converged_on_kill": ...}

``vs_baseline``: speedup over the real-time protocol rate at equal N
(5 * N node-rounds/s, gossip.js:127-129) — same definition as bench.py.

Run: python benchmarks/bench_delta_scale.py [sizes_csv] [ticks_per_batch] [capacity]
Defaults: sizes 262144,1048576; 20 ticks per timed batch; capacity 256.
"""

from __future__ import annotations

import json
import sys
import time

sys.path.insert(0, ".")

REFERENCE_ROUNDS_PER_NODE_SEC = 5.0
CAPACITY = 256
LOSS = 0.005


def run_size(n: int, ticks: int, capacity: int = CAPACITY) -> dict:
    import jax

    from ringpop_tpu.utils import enable_compilation_cache, pin_cpu_if_requested

    pin_cpu_if_requested()
    enable_compilation_cache()

    from ringpop_tpu.models import swim_delta as sd
    from ringpop_tpu.models import swim_sim as sim

    params = sd.DeltaParams(
        swim=sim.SwimParams(loss=LOSS, suspicion_ticks=25),
        wire_cap=16,
        claim_grid=64,
    )
    state = sd.init_delta(n, capacity=capacity)
    net = sim.make_net(n)
    key = jax.random.PRNGKey(0)

    victim = n // 3
    net = net._replace(up=net.up.at[victim].set(False))

    print(f"# compiling delta n={n}", file=sys.stderr, flush=True)
    t0 = time.perf_counter()
    key, sub = jax.random.split(key)
    state, m = sd.delta_run(state, net, sub, params, ticks)
    _ = int(m["pings_sent"])  # host sync
    print(
        f"# n={n}: first batch (compile + {ticks} ticks) "
        f"{time.perf_counter() - t0:.0f}s",
        file=sys.stderr,
        flush=True,
    )

    # drive until the kill has fully converged (suspect->faulty everywhere)
    converged_on_kill = False
    for _ in range(12):  # <= 240 ticks; suspicion is 25
        key, sub = jax.random.split(key)
        state, m = sd.delta_run(state, net, sub, params, ticks)
        if int(m["faulty_declared"]) == 0 and int(m["suspects_declared"]) == 0:
            ids = jax.numpy.asarray([0, 1, n - 1])
            rows = sd.materialize_rows(state, ids)
            if all(int(r) & 7 == sim.FAULTY for r in rows[:, victim]):
                converged_on_kill = True
                break

    # revive + rejoin, then rebase folds the healed divergence
    inc = int(
        max(
            jax.numpy.max(state.base_key), jax.numpy.max(state.d_key)
        )
        >> 3
    ) + 1000
    state = sd.revive_and_join(state, victim, inc, seed=0)
    net = net._replace(up=net.up.at[victim].set(True))
    for _ in range(6):
        key, sub = jax.random.split(key)
        state, m = sd.delta_run(state, net, sub, params, ticks)
    occ_before = int(m["max_occupancy"])
    state = sd.rebase(state)
    occ_after = int(
        jax.numpy.max(
            jax.numpy.sum((state.d_subj < sd.SENTINEL).astype(jax.numpy.int32), axis=1)
        )
    )
    print(
        f"# n={n}: rebase occupancy {occ_before} -> {occ_after}",
        file=sys.stderr,
        flush=True,
    )

    # steady-state timing (best of 3 batches)
    best = 0.0
    for _ in range(3):
        key, sub = jax.random.split(key)
        t0 = time.perf_counter()
        state, m = sd.delta_run(state, net, sub, params, ticks)
        _ = int(m["pings_sent"])
        dt = time.perf_counter() - t0
        best = max(best, ticks * n / dt)
        print(f"# n={n}: {best:.0f} node-rounds/s", file=sys.stderr, flush=True)

    return {
        "metric": f"delta_scale_node_rounds_per_sec_n{n}",
        "value": round(best, 1),
        "unit": "node-rounds/s",
        "vs_baseline": round(best / (REFERENCE_ROUNDS_PER_NODE_SEC * n), 2),
        "occupancy_after_rebase": occ_after,
        "overflow_drops": int(m["overflow_drops"]),
        "converged_on_kill": converged_on_kill,
    }


def main() -> None:
    sizes = (
        [int(s) for s in sys.argv[1].split(",")]
        if len(sys.argv) > 1
        else [262144, 1048576]
    )
    ticks = int(sys.argv[2]) if len(sys.argv) > 2 else 20
    capacity = int(sys.argv[3]) if len(sys.argv) > 3 else CAPACITY
    for n in sizes:
        print(json.dumps(run_size(n, ticks, capacity)), flush=True)


if __name__ == "__main__":
    main()

"""Merging 3 x 1,000-member join responses, with and without identical
checksums (reference: benchmarks/join-response-merge.js — same checksum
short-circuits to the first response, join-response-merge.js:24-47)."""

from __future__ import annotations

import time

from benchmarks.fixtures import large_membership
from ringpop_tpu.swim.join_response_merge import merge_join_responses

LOCAL = "10.99.0.1:3000"


def _bench(same_checksum: bool, duration_s: float) -> dict:
    members = large_membership(1000)
    responses = [
        {"checksum": 12345 if same_checksum else 12345 + i, "members": members}
        for i in range(3)
    ]
    iterations = 0
    t0 = time.perf_counter()
    deadline = t0 + duration_s
    while time.perf_counter() < deadline:
        merged = merge_join_responses(LOCAL, responses)
        assert len(merged) == 1000
        iterations += 1
    elapsed = time.perf_counter() - t0
    suffix = "same_checksum" if same_checksum else "diff_checksum"
    return {
        "metric": f"join_response_merge_3x1000_{suffix}",
        "value": round(iterations / elapsed, 2),
        "unit": "ops/sec",
    }


def run(duration_s: float = 1.0) -> list[dict]:
    return [_bench(True, duration_s), _bench(False, duration_s)]

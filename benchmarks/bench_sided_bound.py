"""Bound the sided-mode dissemination deviation (config-4 family).

Sided mode deviates from the reference's per-message piggyback
semantics (/root/reference/lib/dissemination.js:138-177): flip-adopted
entries carry no dissemination records, and the anti-entropy fold is
bulk delivery on a maintenance schedule rather than per-ping piggyback
(documented in swim_delta.py).  This bench separates the deviation's
two candidate costs at matched n by running THREE configurations of the
identical 50/50-netsplit trajectory:

* ``dense`` — unbounded wire, reference piggyback semantics: the
  protocol-fidelity control.
* ``delta unsided`` at wire W — per-message piggyback kept, wire
  bounded: (dense - unsided) is the WIRE-CAP cost.
* ``delta sided`` at the SAME wire W — adds the flip/fold schedule:
  (unsided - sided) is the FOLD-SCHEDULE cost (negative = the bulk
  fold is a speedup over wire-capped per-message piggyback).

Two metrics per configuration (both tick counts — load-immune):

* detection: post-split ticks until the cluster reads exactly 2
  checksum groups (each side internally converged on the other side
  faulty) — the netsplit twin of the kill-detection latency bound.
* heal: the config-4 metric (tick-cluster.js:88-115): heal the link
  mid-transition at the same tick in every configuration, count
  post-heal ticks to ONE checksum group.

Usage: python benchmarks/bench_sided_bound.py [n] [--wire W]
       [--configs dense,unsided,sided] [--skip-detection]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.bench_partition_heal_delta import run as heal_run


def measure_detection(
    n: int,
    backend: str,
    sided: bool,
    wire_cap: int,
    loss: float = 0.0,
    suspicion_ticks: int = 8,
    max_ticks: int = 400,
) -> dict:
    """Post-split ticks until exactly 2 checksum groups."""
    from ringpop_tpu.models import swim_sim as sim
    from ringpop_tpu.models.cluster import SimCluster

    if sided:
        capacity = max(256, n // 16)
    elif backend == "delta":
        capacity = n + 64
    else:
        capacity = 256  # ignored by the dense backend
    params = sim.SwimParams(loss=loss, suspicion_ticks=suspicion_ticks)
    cluster = SimCluster(
        n,
        params,
        seed=4,
        backend=backend,
        capacity=capacity,
        wire_cap=wire_cap,
        claim_grid=512,
    )
    cluster.tick(2)
    half = n // 2
    sides = [list(range(half)), list(range(half, n))]
    if sided:
        cluster.split_sides(sides)
    else:
        cluster.partition(sides)
    t0 = time.perf_counter()
    ticks = 0
    groups = -1
    while ticks < max_ticks:
        cluster.tick(1)
        ticks += 1
        if sided and ticks % 5 == 0:
            # same 5-tick fold cadence as the heal bench's split phase
            cluster.rebase(anti_entropy=True)
        # every tick: the bench differences detection ticks between
        # configurations, so a sampling quantization would bias the
        # wire-cap/fold-schedule deltas it exists to measure
        groups = len(cluster.checksum_groups())
        if groups == 2:
            break
    m = cluster.metrics_log[-1] if cluster.metrics_log else {}
    return {
        "metric": f"netsplit_detection_{backend}{'_sided' if sided else ''}_n{n}",
        "value": ticks,
        "unit": "ticks_to_2_groups",
        "checksum_groups": groups,
        "wire_cap": None if backend == "dense" else wire_cap,
        "overflow_drops": int(m.get("overflow_drops", 0)),
        "wall_s": round(time.perf_counter() - t0, 3),
    }


CONFIGS = {
    "dense": dict(backend="dense", sided=False),
    "unsided": dict(backend="delta", sided=False),
    "sided": dict(backend="delta", sided=True),
}


def main() -> None:
    from ringpop_tpu.utils import enable_compilation_cache, pin_cpu_if_requested

    pin_cpu_if_requested()
    enable_compilation_cache()

    n = int(sys.argv[1]) if len(sys.argv) > 1 and not sys.argv[1].startswith("-") else 1024
    wire = 64
    if "--wire" in sys.argv:
        wire = int(sys.argv[sys.argv.index("--wire") + 1])
    names = ["dense", "unsided", "sided"]
    if "--configs" in sys.argv:
        names = sys.argv[sys.argv.index("--configs") + 1].split(",")

    for name in names:
        cfg = CONFIGS[name]
        if not ("--skip-detection" in sys.argv):
            row = measure_detection(n, cfg["backend"], cfg["sided"], wire)
            print(json.dumps(row), flush=True)
        for row in heal_run(
            n,
            backend=cfg["backend"],
            sided=cfg["sided"],
            wire_cap=wire,
        ):
            row["config"] = name
            print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()

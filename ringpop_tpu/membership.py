"""Authoritative membership list + SWIM update evaluation.

Reference: lib/membership.js.  Checksum format parity is load-bearing:
farmhash32 of ``addr + status + incarnation`` per member, members sorted by
address, entries joined with ';' (membership.js:41-93).  The same format is
produced on-device by ops/checksum.py.
"""

from __future__ import annotations

import uuid
from typing import Any

from ringpop_tpu.changeset_merge import merge_membership_changesets
from ringpop_tpu.member import Member, Status
from ringpop_tpu.ops import farmhash
from ringpop_tpu import update_rules
from ringpop_tpu.utils.events import EventEmitter


class Membership(EventEmitter):
    def __init__(self, ringpop: Any):
        super().__init__()
        self.ringpop = ringpop
        self.members: list[Member] = []
        self.members_by_address: dict[str, Member] = {}
        self.checksum: int | None = None
        self.stashed_updates: list[list[dict[str, Any]]] | None = []
        self.local_member: Member | None = None

    # -- checksum (membership.js:41-93) -------------------------------------

    def compute_checksum(self) -> int:
        start = self.ringpop.clock.now()
        self.checksum = farmhash.membership_checksum_packed(
            self._packed_checksum_string(), len(self.members)
        )
        self.emit("checksumComputed")
        self.ringpop.stat("timing", "compute-checksum", self.ringpop.clock.now() - start)
        self.ringpop.stat("gauge", "checksum", self.checksum)
        return self.checksum

    def _packed_checksum_string(self) -> bytes:
        members = sorted(self.members, key=lambda m: m.address)
        return b"".join(
            f"{m.address}\x00{m.status}\x00{_format_incarnation(m.incarnation_number)}\x00".encode()
            for m in members
        )

    def generate_checksum_string(self) -> str:
        members = sorted(self.members, key=lambda m: m.address)
        return ";".join(
            f"{m.address}{m.status}{_format_incarnation(m.incarnation_number)}"
            for m in members
        )

    # -- accessors ----------------------------------------------------------

    def find_member_by_address(self, address: str) -> Member | None:
        return self.members_by_address.get(address)

    def get_incarnation_number(self) -> int | None:
        return self.local_member.incarnation_number if self.local_member else None

    def get_join_position(self) -> int:
        return int(self.ringpop.rng.random() * len(self.members))

    def get_member_at(self, index: int) -> Member:
        return self.members[index]

    def get_member_count(self) -> int:
        return len(self.members)

    def get_random_pingable_members(self, n: int, excluding: list[str]) -> list[Member]:
        candidates = [
            m
            for m in self.members
            if m.address not in excluding and self.is_pingable(m)
        ]
        self.ringpop.rng.shuffle(candidates)
        return candidates[:n]

    def get_stats(self) -> dict[str, Any]:
        return {
            "checksum": self.checksum,
            "members": [
                m.to_change() for m in sorted(self.members, key=lambda m: m.address)
            ],
        }

    def has_member(self, member: Member) -> bool:
        return self.find_member_by_address(member.address) is not None

    def is_pingable(self, member: Member) -> bool:
        return member.address != self.ringpop.whoami() and member.status in (
            Status.alive,
            Status.suspect,
        )

    # -- declarations (membership.js:141-156) -------------------------------

    def make_alive(self, address: str, incarnation_number: int) -> list[dict[str, Any]]:
        return self._make_update(
            address,
            incarnation_number,
            Status.alive,
            is_local=address == self.ringpop.whoami(),
        )

    def make_faulty(self, address: str, incarnation_number: int) -> list[dict[str, Any]]:
        return self._make_update(address, incarnation_number, Status.faulty)

    def make_leave(self, address: str, incarnation_number: int) -> list[dict[str, Any]]:
        return self._make_update(address, incarnation_number, Status.leave)

    def make_suspect(self, address: str, incarnation_number: int) -> list[dict[str, Any]]:
        return self._make_update(address, incarnation_number, Status.suspect)

    def _make_update(
        self, address: str, incarnation_number: int, status: str, is_local: bool = False
    ) -> list[dict[str, Any]]:
        local = self.local_member
        source = local.address if local else address
        source_inc = local.incarnation_number if local else incarnation_number
        update_id = str(uuid.uuid4())
        updates = self.update(
            {
                "id": update_id,
                "source": source,
                "sourceIncarnationNumber": source_inc,
                "address": address,
                "status": status,
                "incarnationNumber": incarnation_number,
                "timestamp": self.ringpop.clock.now(),
            },
            is_local=is_local,
        )
        if updates:
            self.ringpop.logger.debug(
                f"ringpop member declares other member {status}",
                {"local": self.ringpop.whoami(), status: address, "updateId": update_id},
            )
        return updates

    # -- bootstrap stash + atomic set (membership.js:162-206) ---------------

    def set(self) -> None:
        if self.ringpop.is_ready or self.stashed_updates is None:
            return
        if not self.stashed_updates:
            return

        updates = merge_membership_changesets(
            self.ringpop.whoami(), self.stashed_updates
        )

        for update in updates:
            member = Member(
                update["address"], update["status"], update["incarnationNumber"]
            )
            self.members.append(member)
            self.members_by_address[member.address] = member

        self.stashed_updates = None
        self.compute_checksum()
        self.emit("set", updates)

    # -- SWIM update evaluation (membership.js:208-313) ---------------------

    def update(
        self, changes: dict[str, Any] | list[dict[str, Any]], is_local: bool = False
    ) -> list[dict[str, Any]]:
        if isinstance(changes, dict):
            changes = [changes]

        self.ringpop.stat("gauge", "changes.apply", len(changes))

        if not changes:
            return []

        # Buffer updates until ready (applied atomically by set()).
        if not is_local and not self.ringpop.is_ready:
            if isinstance(self.stashed_updates, list):
                self.stashed_updates.append(changes)
            return []

        local_address = self.ringpop.whoami()
        updates: list[dict[str, Any]] = []

        for change in changes:
            member = self.find_member_by_address(change.get("address"))

            # First time seeing member: take change wholesale.
            if member is None:
                self._apply_update(change)
                updates.append(change)
                continue

            # Rumor about self being suspect/faulty: refute by re-asserting
            # alive with a newer incarnation (membership.js:243-254).  The
            # reference uses Date.now(); we additionally guarantee strict
            # monotonicity under sub-ms activity.
            if update_rules.is_local_suspect_override(
                local_address, member, change
            ) or update_rules.is_local_faulty_override(local_address, member, change):
                change = dict(change)
                change["status"] = Status.alive
                change["incarnationNumber"] = _next_incarnation(
                    self.ringpop.clock.now(), member.incarnation_number
                )
                self._apply_update(change)
                updates.append(change)
                continue

            if (
                update_rules.is_alive_override(member, change)
                or update_rules.is_suspect_override(member, change)
                or update_rules.is_faulty_override(member, change)
                or update_rules.is_leave_override(member, change)
            ):
                self._apply_update(change)
                updates.append(change)

        if updates:
            self.compute_checksum()
            self.emit("updated", updates)

        return updates

    def _apply_update(self, update: dict[str, Any]) -> Member | None:
        address = update.get("address")
        incarnation_number = update.get("incarnationNumber")
        if address is None or incarnation_number is None:
            return None

        member = self.find_member_by_address(address)
        if member is None:
            member = Member(address, update.get("status"), incarnation_number)
            if member.address == self.ringpop.whoami():
                self.local_member = member
            # Random join position (membership.js:99-101,296)
            self.members.insert(self.get_join_position(), member)
            self.members_by_address[member.address] = member

        member.status = update.get("status")
        member.incarnation_number = incarnation_number
        return member

    def shuffle(self) -> None:
        self.ringpop.rng.shuffle(self.members)

    def __str__(self) -> str:
        import json

        return json.dumps([m.address for m in self.members])


def _format_incarnation(inc: Any) -> str:
    """Decimal rendering matching JS number stringification for the
    integer-ms incarnation values the protocol uses."""
    if isinstance(inc, float) and inc.is_integer():
        inc = int(inc)
    return str(inc)


def _next_incarnation(now_ms: float, current_inc: int) -> int:
    return max(int(now_ms), int(current_inc) + 1)

"""Stat sinks behind the reference's injected-statsd interface.

The reference treats the statsd client as first-class (index.js:561-605
routes every ``stat()`` through an injected ``options.statsd``); our
port's default is ``NullStatsd``.  These emitters are the real
implementations of the same three-method contract
(``increment/gauge/timing``), so they drop into ``RingPop(statsd=...)``,
``SimCluster(stats_emitter=...)`` and the Trace→stats bridge unchanged:

* ``StatsdEmitter`` — UDP statsd line protocol (``key:v|c`` / ``|g`` /
  ``|ms``), fire-and-forget, one datagram per stat;
* ``CaptureEmitter`` — in-memory record with aggregation helpers (the
  test double, and the backing store for key-namespace assertions);
* ``JsonlEmitter`` — one JSON object per stat appended to a file (or
  stdout), the ``tick-cluster --stats-out`` default;
* ``MultiEmitter`` — fan-out to several sinks.

``make_emitter(spec)`` parses the CLI string forms:
``statsd://HOST:PORT`` (or ``udp://``), ``capture``, ``-`` (stdout
JSON lines), anything else = a JSON-lines file path.
"""

from __future__ import annotations

import json
import socket
import sys
import time
from collections import Counter
from typing import Any, IO


def _num(value: Any, default: float = 1) -> float:
    """Statsd line values must be numeric; None means 'count one'."""
    if value is None:
        return default
    return float(value)


def _fmt(value: float) -> str:
    """Integral values print as ints (``3`` not ``3.0``): the wire form
    the reference's node-statsd client produces."""
    return str(int(value)) if float(value).is_integer() else repr(float(value))


class StatsdEmitter:
    """UDP statsd line-protocol sink (fire-and-forget, never raises
    after construction — a dead collector must not take gossip down)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8125):
        self.host = host
        self.port = int(port)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sent = 0
        self.dropped = 0

    def _send(self, line: str) -> None:
        try:
            self._sock.sendto(line.encode(), (self.host, self.port))
            self.sent += 1
        except OSError:
            self.dropped += 1

    def increment(self, key: str, value: Any = None) -> None:
        self._send(f"{key}:{_fmt(_num(value))}|c")

    def gauge(self, key: str, value: Any = None) -> None:
        self._send(f"{key}:{_fmt(_num(value, 0))}|g")

    def timing(self, key: str, value: Any = None) -> None:
        self._send(f"{key}:{_fmt(_num(value, 0))}|ms")

    def close(self) -> None:
        self._sock.close()


class CaptureEmitter:
    """In-memory sink with the aggregations tests and CLIs read back:
    raw calls, per-key increment totals, last gauge, timing lists."""

    def __init__(self) -> None:
        self.calls: list[tuple[str, str, Any]] = []
        self.counters: Counter[str] = Counter()
        self.gauges: dict[str, float] = {}
        self.timings: dict[str, list[float]] = {}

    def increment(self, key: str, value: Any = None) -> None:
        self.calls.append(("increment", key, value))
        self.counters[key] += int(_num(value))

    def gauge(self, key: str, value: Any = None) -> None:
        self.calls.append(("gauge", key, value))
        self.gauges[key] = _num(value, 0)

    def timing(self, key: str, value: Any = None) -> None:
        self.calls.append(("timing", key, value))
        self.timings.setdefault(key, []).append(_num(value, 0))

    def keys(self) -> set[str]:
        return {key for _, key, _ in self.calls}

    def suffixes(self, prefix: str) -> set[str]:
        """Emitted keys with ``prefix.`` stripped (the reference's
        ``ringpop.<host_port>.`` namespace), for parity assertions."""
        dot = prefix + "."
        return {
            key[len(dot):] if key.startswith(dot) else key
            for key in self.keys()
        }

    def close(self) -> None:
        pass


class JsonlEmitter:
    """One JSON object per stat, appended to a file or stream — the
    greppable form ``tick-cluster --stats-out`` writes by default."""

    def __init__(self, path_or_stream: str | IO[str]):
        if isinstance(path_or_stream, str):
            self.path: str | None = path_or_stream
            self._f: IO[str] = open(path_or_stream, "a")
            self._owned = True
        else:
            self.path = None
            self._f = path_or_stream
            self._owned = False
        self.emitted = 0

    def _write(self, type_: str, key: str, value: Any) -> None:
        row = {"ts": round(time.time(), 3), "type": type_, "key": key}
        if value is not None:
            row["value"] = value
        self._f.write(json.dumps(row) + "\n")
        # flush per stat: this emitter exists for forensics, so a
        # SIGKILLed worker must not take its buffered lines with it,
        # and `tail -f` on a --stats-out file must stream live
        self._f.flush()
        self.emitted += 1

    def increment(self, key: str, value: Any = None) -> None:
        self._write("increment", key, value)

    def gauge(self, key: str, value: Any = None) -> None:
        self._write("gauge", key, value)

    def timing(self, key: str, value: Any = None) -> None:
        self._write("timing", key, value)

    def close(self) -> None:
        # idempotent: one emitter is commonly shared by every node of a
        # harness cluster, and each node's destroy() closes it
        if self._f.closed:
            return
        self._f.flush()
        if self._owned:
            self._f.close()


class MultiEmitter:
    """Fan one stat stream out to several sinks."""

    def __init__(self, *emitters: Any):
        self.emitters = list(emitters)

    def increment(self, key: str, value: Any = None) -> None:
        for e in self.emitters:
            e.increment(key, value)

    def gauge(self, key: str, value: Any = None) -> None:
        for e in self.emitters:
            e.gauge(key, value)

    def timing(self, key: str, value: Any = None) -> None:
        for e in self.emitters:
            e.timing(key, value)

    def close(self) -> None:
        for e in self.emitters:
            close = getattr(e, "close", None)
            if close:
                close()


def make_emitter(spec: str) -> Any:
    """Build an emitter from a CLI spec string (see module docstring)."""
    if spec == "capture":
        return CaptureEmitter()
    if spec == "-":
        return JsonlEmitter(sys.stdout)
    for scheme in ("statsd://", "udp://"):
        if spec.startswith(scheme):
            hostport = spec[len(scheme):]
            host, _, port = hostport.rpartition(":")
            if not host or not port.isdigit():
                raise ValueError(
                    f"statsd emitter spec needs HOST:PORT, got {hostport!r}"
                )
            return StatsdEmitter(host, int(port))
    return JsonlEmitter(spec)

"""Trace→stats bridge: replay simulated telemetry as reference metrics.

A real ringpop deployment is observed through its statsd namespace
(``ringpop.<host_port>.ping.send``, ``.membership-update.suspect``,
``.full-sync`` ...).  The compiled simulation stacks the same protocol
facts into per-tick ``Trace`` counters — this bridge replays them into
any emitter under the SAME key names, so a simulated 10k-node chaos
scenario produces the metric namespace a production cluster would, and
every downstream consumer (dashboards, alert rules, the CI namespace
assertion) works unchanged.

Key table (trace series → reference stat; the suffixes are asserted
against the host facade's own emissions in tests/test_obs.py):

| trace series                 | type      | reference key               |
|------------------------------|-----------|-----------------------------|
| pings_sent                   | increment | ping.send                   |
| acks                         | increment | ping.recv                   |
| ping_reqs                    | increment | ping-req.send               |
| full_syncs                   | increment | full-sync                   |
| suspects_declared            | increment | membership-update.suspect   |
| faulty_declared              | increment | membership-update.faulty    |
| live (tick-0 baseline + ups) | increment | membership-update.alive     |
| *_changes_applied (summed)   | gauge     | changes.apply               |
| live                         | gauge     | num-members                 |
| checksum (caller-provided)   | gauge     | checksum                    |

Traffic-coupled traces (scenarios co-run with a ``traffic`` workload)
additionally carry the serving plane's counters:

| lookups                      | increment | lookup                       |
| lookupns                     | increment | lookupn                      |
| proxy_sends                  | increment | requestProxy.send.success    |
| proxy_retries                | increment | requestProxy.retry.attempted |
| proxy_failed                 | increment | requestProxy.retry.failed    |

SLO-latency-enabled workloads (``WorkloadSpec.latency_buckets > 0``)
add the request-latency namespace — the failed-send / succeeded-retry
counters of proxy.py:59 / send.py:90, and the per-tick latency
histogram rows replayed as timing samples:

| send_errors                  | increment | requestProxy.send.error      |
| retry_succeeded              | increment | requestProxy.retry.succeeded |
| lat_hist_ms (trace plane)    | timing    | requestProxy.send            |

with the rest of the traffic series (misroutes, delivered_misroutes,
ring_divergence, hops0..hopsK, unresolved, dropped ...) flowing as
``sim.``-prefixed gauges like every other sim-only series.

Increments carry the tick's count as the statsd count value (``:N|c``);
zero-count ticks emit nothing (the reference increments per event, so
an eventless tick is silence there too).  ``membership-update.alive``
is emitted at tick 0 with the starting live count — the simulation's
analog of every node's bootstrap ``make_alive`` — and afterwards with
the positive live-count delta (revives re-entering the gossip set).
Sim-only series that have no reference analog keep a ``sim.`` prefix
(``sim.converged``, ``sim.loss``, ``sim.claims_dropped`` ...), so the
reference namespace stays exactly reference-shaped.
"""

from __future__ import annotations

from typing import Any

import numpy as np

# trace counter -> reference increment key (per tick, count as value)
PROTOCOL_COUNTER_KEYS: dict[str, str] = {
    "pings_sent": "ping.send",
    "acks": "ping.recv",
    "ping_reqs": "ping-req.send",
    "full_syncs": "full-sync",
    "suspects_declared": "membership-update.suspect",
    "faulty_declared": "membership-update.faulty",
}

# traffic-plane counters (traffic/engine.counter_names) -> the serving
# layer's reference keys: lookup/lookupn are the index.js lookup stats,
# the requestProxy.* entries are request_proxy send.py/proxy.py retry
# and send accounting.  Kept out of REFERENCE_KEYS: a scenario without
# traffic emits none of these (the host stack only emits them when
# lookups/proxies happen).  The last two flow only from SLO-latency-
# enabled workloads (WorkloadSpec.latency_buckets > 0) — the bridge is
# presence-gated per series, so a latency-off trace emits exactly the
# base set.
TRAFFIC_COUNTER_KEYS: dict[str, str] = {
    "lookups": "lookup",
    "lookupns": "lookupn",
    "proxy_sends": "requestProxy.send.success",
    "proxy_retries": "requestProxy.retry.attempted",
    "proxy_failed": "requestProxy.retry.failed",
    # SLO latency plane (traffic/latency.py): failed send attempts
    # (dead holders + gray timeouts -> proxy.py:59) and
    # delivered-after-retry (send.py:90)
    "send_errors": "requestProxy.send.error",
    "retry_succeeded": "requestProxy.retry.succeeded",
}

# the serving timing stat: each tick's latency-histogram row replays as
# ``requestProxy.send`` timing values (bucket-floor ms, at most
# TIMING_REPLAY_CAP emissions per bucket per tick — statsd timing
# streams are sampled anyway; exact percentiles come from the trace
# plane itself, scenarios/trace.py summary / traffic/latency.hist_stats)
TRAFFIC_TIMING_KEYS: dict[str, str] = {
    "lat_hist_ms": "requestProxy.send",
}
TIMING_REPLAY_CAP = 8

COUNTER_KEYS: dict[str, str] = {
    **PROTOCOL_COUNTER_KEYS,
    **TRAFFIC_COUNTER_KEYS,
}

# the changes-applied trio folds into the reference's changes.apply gauge
CHANGES_APPLIED = (
    "ping_changes_applied",
    "ack_changes_applied",
    "pingreq_changes_applied",
)

# every reference-parity key the bridge emits for ANY scenario — the
# namespace the CI smoke asserts a scenario's --stats-out stream is a
# superset of (traffic keys join only when a workload co-ran)
REFERENCE_KEYS: tuple[str, ...] = (
    *PROTOCOL_COUNTER_KEYS.values(),
    "membership-update.alive",
    "changes.apply",
    "num-members",
    "checksum",
)

# the additional keys an SLO-latency-enabled workload emits
TRAFFIC_LATENCY_KEYS: tuple[str, ...] = (
    TRAFFIC_COUNTER_KEYS["send_errors"],
    TRAFFIC_COUNTER_KEYS["retry_succeeded"],
    *TRAFFIC_TIMING_KEYS.values(),
)

# the serving-plane keys EVERY traffic-coupled scenario emits — derived
# so a future base counter lands here automatically; the latency-gated
# keys stay out (the smoke/namespace assertions over this tuple must
# hold for latency-off runs)
TRAFFIC_KEYS: tuple[str, ...] = tuple(
    v for v in TRAFFIC_COUNTER_KEYS.values() if v not in TRAFFIC_LATENCY_KEYS
)

DEFAULT_PREFIX = "ringpop.sim"


class StatSink:
    """``RingPop.stat``'s prefix + key-cache fast path (index.js:561-575)
    over a bare emitter: fully-qualified keys are built once per key,
    not per call."""

    def __init__(self, emitter: Any, prefix: str = DEFAULT_PREFIX):
        self.emitter = emitter
        self.prefix = prefix
        self._keys: dict[str, str] = {}

    def _fq(self, key: str) -> str:
        fq = self._keys.get(key)
        if fq is None:
            fq = self._keys[key] = f"{self.prefix}.{key}"
        return fq

    def increment(self, key: str, value: Any = None) -> None:
        self.emitter.increment(self._fq(key), value)

    def gauge(self, key: str, value: Any = None) -> None:
        self.emitter.gauge(self._fq(key), value)

    def timing(self, key: str, value: Any = None) -> None:
        self.emitter.timing(self._fq(key), value)


def emit_counters(
    metrics: dict[str, Any], sink: StatSink, *, live: int | None = None
) -> int:
    """Bridge ONE tick's counter dict (a ``SimCluster.tick`` metrics
    entry, or one row of a trace) into the sink.  Returns the number of
    stat calls made.

    A multi-tick entry (``metrics["ticks"] > 1`` — ``swim_run`` reports
    only the LAST tick's counters) emits gauges only: gauges are
    last-write-wins so the latest tick's value is exactly right, but
    replaying a one-tick sample as the whole span's increments would
    understate protocol traffic by up to ticks× (use ``run_scenario``
    for an exact per-tick stream)."""
    calls = 0
    changes = 0
    one_tick = int(metrics.get("ticks", 1)) == 1
    for name, value in metrics.items():
        v = int(value)
        key = COUNTER_KEYS.get(name)
        if key is not None:
            if v and one_tick:
                sink.increment(key, v)
                calls += 1
        elif name in CHANGES_APPLIED:
            changes += v
        elif name not in ("converged", "live", "loss", "ticks"):
            # always emitted, zeros included: a statsd gauge holds its
            # last write, so suppressing zeros would freeze a spike
            # (e.g. claims-dropped) on the dashboard forever
            sink.gauge(f"sim.{name.replace('_', '-')}", v)
            calls += 1
    sink.gauge("changes.apply", changes)
    calls += 1
    if live is not None:
        sink.gauge("num-members", int(live))
        calls += 1
    return calls


def replay_trace(
    trace: Any,
    emitter: Any,
    *,
    prefix: str = DEFAULT_PREFIX,
    checksum: int | None = None,
    declare_namespace: bool = True,
    prev_live: int | None = None,
    checksum_pending: bool = False,
) -> int:
    """Replay a ``scenarios.Trace`` tick by tick into ``emitter`` under
    reference-parity keys (see the module key table).  ``checksum``
    (the cluster's post-run membership checksum) emits one final
    ``checksum`` gauge — the reference recomputes-and-gauges it on
    every membership update; the simulation computes it on demand.

    ``declare_namespace`` (default) first touches every counter key
    with a zero-count increment (``key:0|c`` — a legal statsd no-op),
    so the emitted key set is the full reference namespace even for a
    quiet scenario whose run produced no faulty/full-sync events —
    the deterministic superset the CI smoke asserts.  With no
    ``checksum`` available (e.g. every node dead) the declaration also
    touches the ``checksum`` gauge with 0 (documented sentinel for
    "not computed"), keeping the namespace guarantee total.

    ``checksum_pending`` declares the namespace WITHOUT the checksum
    sentinel: the caller promises to gauge the real checksum itself
    after the run (the streamed runner, which replays slab by slab
    with ``checksum=None`` and gauges once at completion — emitting
    the sentinel here would put a spurious ``checksum:0`` at soak
    start that the whole-trace replay never emits).

    ``prev_live`` marks a CONTINUATION replay — ``trace`` is a
    per-segment slab of a streamed run (scenarios/stream.py), not the
    start of one: the first tick's ``membership-update.alive`` emits
    the positive delta against the previous segment's final live count
    instead of the bootstrap baseline, so replaying every slab in
    order (with ``declare_namespace`` only on the first) produces the
    exact stat stream the whole-trace replay would.

    Returns the total number of stat calls."""
    sink = StatSink(emitter, prefix)
    calls0 = 0
    if declare_namespace:
        declared = [*PROTOCOL_COUNTER_KEYS.values(), "membership-update.alive"]
        if "lookups" in trace.metrics:  # a traffic-coupled trace
            declared += [
                TRAFFIC_COUNTER_KEYS[s]
                for s in TRAFFIC_COUNTER_KEYS
                if s in trace.metrics
            ]
        for key in declared:
            sink.increment(key, 0)
            calls0 += 1
        if checksum is None and not checksum_pending:
            sink.gauge("checksum", 0)
            calls0 += 1
    live = np.asarray(trace.live, dtype=np.int64)
    converged = np.asarray(trace.converged, dtype=bool)
    loss = np.asarray(trace.loss, dtype=np.float64)
    # latency-histogram planes replay as timing stats: each nonzero
    # bucket emits its bucket-floor ms value up to TIMING_REPLAY_CAP
    # times per tick (bounded call volume; the trace plane keeps the
    # exact counts)
    timing_planes = []
    planes = getattr(trace, "planes", None) or {}
    for name, key in TRAFFIC_TIMING_KEYS.items():
        if name in planes:
            from ringpop_tpu.traffic.latency import bucket_edges_ms

            arr = np.asarray(planes[name], dtype=np.int64)
            reps = np.concatenate([[0], bucket_edges_ms(arr.shape[1])])
            timing_planes.append((key, arr, reps))
    calls = calls0
    for t in range(trace.ticks):
        tick_metrics = {k: v[t] for k, v in trace.metrics.items()}
        calls += emit_counters(tick_metrics, sink, live=int(live[t]))
        for key, arr, reps in timing_planes:
            row = arr[t]
            for b in np.flatnonzero(row):
                for _ in range(min(int(row[b]), TIMING_REPLAY_CAP)):
                    sink.timing(key, int(reps[b]))
                    calls += 1
        if t == 0:
            alive = (
                int(live[0]) if prev_live is None
                else int(live[0]) - int(prev_live)
            )
        else:
            alive = int(live[t]) - int(live[t - 1])
        if alive > 0:
            sink.increment("membership-update.alive", alive)
            calls += 1
        sink.gauge("sim.converged", int(converged[t]))
        sink.gauge("sim.loss", float(loss[t]))
        calls += 2
    if checksum is not None:
        sink.gauge("checksum", int(checksum))
        calls += 1
    return calls


def emit_provenance(
    report: dict[str, Any], emitter: Any, *, prefix: str = DEFAULT_PREFIX
) -> int:
    """Gauge the provenance plane's summary block (one value per
    ``obs.provenance.summary_block`` field, ``sim.provenance.*`` keys —
    sim-only: the reference has no rumor-level tracing namespace).
    Returns the number of stat calls."""
    from ringpop_tpu.obs.provenance import summary_block

    sink = StatSink(emitter, prefix)
    calls = 0
    for name, value in summary_block(report).items():
        sink.gauge(f"sim.provenance.{name.replace('_', '-')}", int(value))
        calls += 1
    return calls

"""Observability: emitters, dispatch ledger, profiler scopes, bridge.

The flight recorder for the compiled SWIM stack (ISSUE 5):

* ``obs.emitters`` — real sinks behind the reference's injected-statsd
  ``increment/gauge/timing`` interface (statsd UDP line protocol,
  in-memory capture, JSON lines), so ``RingPop(statsd=...)`` finally
  records somewhere at runtime;
* ``obs.ledger`` — per-dispatch compile-vs-execute wall time plus the
  AOT ``memory_analysis`` footprint of every jitted entry point
  (``swim_run``/``delta_run``/``run_scenario``/``run_sweep``/the
  recv-merge forms), persisted as JSON lines with a summarizer CLI;
* ``obs.annotate`` — ``jax.named_scope`` protocol-phase scopes and the
  ``--profile-dir`` trace bracket (TensorBoard / Perfetto);
* ``obs.bridge`` — replays per-tick ``Trace`` counters into any
  emitter under reference-parity key names (``ping.send``,
  ``full-sync``, ``membership-update.*`` ...).

``annotate`` is NOT imported eagerly: it needs jax, and the bench
parent process (bench.py's orchestrator) must be able to record ledger
rows without ever initializing a backend.
"""

from __future__ import annotations

from ringpop_tpu.obs.emitters import (
    CaptureEmitter,
    JsonlEmitter,
    MultiEmitter,
    StatsdEmitter,
    make_emitter,
)
from ringpop_tpu.obs.ledger import DispatchLedger, default_ledger, memory_row

__all__ = [
    "CaptureEmitter",
    "JsonlEmitter",
    "MultiEmitter",
    "StatsdEmitter",
    "make_emitter",
    "DispatchLedger",
    "default_ledger",
    "memory_row",
]

"""Profiler scopes and trace brackets for the compiled protocol.

``jax.named_scope`` pushes a name onto jax's tracing name stack, so
every op traced inside carries the scope in its HLO ``op_name``
metadata — which is what TensorBoard's trace viewer and Perfetto group
by.  The models wrap each protocol phase (phase-0/1 select, receiver
merge, the ping-req 5a–5c exchange, delta absorb/compact) so a
device trace reads as protocol phases instead of a fused-op soup.

``profile_trace(dir)`` brackets a run with
``jax.profiler.start_trace/stop_trace`` — the implementation behind
``tick-cluster --profile-dir`` and ``bench.py --profile-dir``; the
directory is TensorBoard-loadable (``plugins/profile/<run>/`` with
``.xplane.pb`` + ``.trace.json.gz``).

jax is imported lazily so that importing ``ringpop_tpu.obs`` never
initializes a backend (bench.py's parent process contract).
"""

from __future__ import annotations

import contextlib
import functools
import os
from typing import Any, Callable, Iterator


def scope(name: str) -> Any:
    """Context manager: a ``jax.named_scope`` for one protocol phase."""
    import jax

    return jax.named_scope(name)


def scoped(name: str) -> Callable[[Callable[..., Any]], Callable[..., Any]]:
    """Decorator form of ``scope`` (wraps the whole function body)."""

    def deco(fn: Callable[..., Any]) -> Callable[..., Any]:
        @functools.wraps(fn)
        def wrapper(*args: Any, **kwargs: Any) -> Any:
            import jax

            with jax.named_scope(name):
                return fn(*args, **kwargs)

        return wrapper

    return deco


@contextlib.contextmanager
def profile_trace(directory: str) -> Iterator[str]:
    """Bracket a block with a jax profiler trace written to
    ``directory`` (created if missing).  ``stop_trace`` runs even when
    the block raises, so a crashed run still ships its trace."""
    import jax

    os.makedirs(directory, exist_ok=True)
    jax.profiler.start_trace(directory)
    try:
        yield directory
    finally:
        jax.profiler.stop_trace()

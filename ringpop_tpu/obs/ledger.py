"""Dispatch ledger: per-dispatch forensics for the jitted entry points.

The round-4/5 failures (a 65k worker crash, a 240 s accelerator-probe
timeout) were diagnosed after the fact by building instrumentation; the
ledger records the same facts as they happen.  Every dispatch routed
through it gets one JSON line:

    {"ts": ..., "program": "run_scenario", "backend": "dense",
     "platform": "cpu", "n": 16, "ticks": 60, "replicas": 1,
     "cold": true, "trace_s": ..., "compile_s": ..., "execute_s": ...,
     "argument_bytes": ..., "output_bytes": ..., "temp_bytes": ...,
     "alias_bytes": ..., "generated_code_bytes": ...,
     "peak_bytes": ..., "peak_is_derived": ...}

Cold/warm discrimination is structural, not guessed: the ledger owns an
AOT executable cache (``jit(...).lower(...).compile()``) keyed by the
abstract signature, so the first dispatch of a shape pays (and records)
trace + compile separately from execute, and warm dispatches reuse the
compiled executable — exactly one XLA compile per shape, same as plain
``jax.jit``.  The footprint fields come from the same
``memory_analysis`` read ``benchmarks/mem_census.py`` pioneered
(``memory_row`` below is that machinery, now shared).

The ledger is OFF by default and adds nothing to the hot path
(``dispatch`` is a plain call-through when disabled).  Enable it with
``default_ledger().enable(path)`` or ``RINGPOP_LEDGER=/path/to.jsonl``
in the environment; ``path=None`` keeps rows in memory only (tests).

This module never imports jax at the top level: bench.py's parent
orchestrator records probe rows without initializing any backend.

Summarizer CLI:  python -m ringpop_tpu.obs.ledger LEDGER.jsonl
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable

ENV_VAR = "RINGPOP_LEDGER"

# In-memory row cap (the JSONL file keeps everything): a long-lived
# worker dispatching for days must not leak one dict per dispatch —
# the in-process consumers (/admin/ledger, summary()) want aggregates
# and recency, not unbounded history.
MAX_ROWS_IN_MEMORY = 10_000

_MEM_FIELDS = (
    "argument_bytes",
    "output_bytes",
    "temp_bytes",
    "alias_bytes",
    "generated_code_bytes",
    "peak_bytes",
    "peak_is_derived",
)


def memory_row(compiled: Any) -> dict[str, int | bool]:
    """XLA ``memory_analysis`` of an AOT-compiled executable, flattened
    to the census field set.  ``peak_bytes`` is the backend's own peak
    when reported (TPU) and otherwise the derived
    ``argument + output + temp - alias`` (donated buffers counted once).
    Defensive: a backend without the analysis yields zeros, not a crash.
    """
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 — forensics must not kill the run
        ma = None
    if ma is None:
        return {f: (False if f == "peak_is_derived" else 0) for f in _MEM_FIELDS}
    arg = int(getattr(ma, "argument_size_in_bytes", 0) or 0)
    out = int(getattr(ma, "output_size_in_bytes", 0) or 0)
    temp = int(getattr(ma, "temp_size_in_bytes", 0) or 0)
    alias = int(getattr(ma, "alias_size_in_bytes", 0) or 0)
    explicit_peak = int(getattr(ma, "peak_memory_in_bytes", 0) or 0)
    return {
        "argument_bytes": arg,
        "output_bytes": out,
        "temp_bytes": temp,
        "alias_bytes": alias,
        "generated_code_bytes": int(
            getattr(ma, "generated_code_size_in_bytes", 0) or 0
        ),
        "peak_bytes": explicit_peak or (arg + out + temp - alias),
        "peak_is_derived": not explicit_peak,
    }


def _signature(args: tuple, statics: dict) -> tuple:
    """Hashable abstract signature of a dispatch: pytree structure plus
    (shape, dtype) per array leaf, and the STATIC kwargs as a separate
    name-keyed component — so when a second cold compile happens the
    ledger can name exactly which static argument forced it (the
    trace-contract auditor's recompile-source attribution; a static
    that should have been a traced batch axis shows up here by name).
    Matches jit's recompile granularity closely enough to reuse
    executables."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(args)
    parts = []
    for leaf in leaves:
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            # placement is part of the executable contract: an AOT
            # program compiled for one device/sharding must not be fed
            # differently-placed arrays (plain jit would recompile)
            placement = str(getattr(leaf, "sharding", None))
            parts.append((tuple(leaf.shape), str(leaf.dtype), placement))
        else:
            parts.append(repr(leaf))
    static_items = tuple(sorted((k, repr(v)) for k, v in statics.items()))
    return (str(treedef), tuple(parts), static_items)


def _sig_hash(sig: tuple) -> str:
    """Short stable digest of a signature — rows carry it so a ledger
    reader can assert "exactly one cold compile per signature" without
    reconstructing the signature itself (tools/obs_smoke.sh)."""
    import hashlib

    return hashlib.sha1(repr(sig).encode()).hexdigest()[:12]


def _clip(s: str, width: int = 90) -> str:
    return s if len(s) <= width else s[: width - 1] + "…"


def _sig_diff(old: tuple, new: tuple) -> list[str]:
    """Human-readable causes of a recompile: which components of the
    abstract signature changed between two dispatches of one program."""
    causes: list[str] = []
    old_tree, old_parts, old_statics = old
    new_tree, new_parts, new_statics = new
    if old_tree != new_tree:
        causes.append("argument pytree structure changed")
    if len(old_parts) != len(new_parts):
        causes.append(
            f"argument leaf count {len(old_parts)} -> {len(new_parts)}"
        )
    else:
        for i, (a, b) in enumerate(zip(old_parts, new_parts)):
            if a == b:
                continue
            if isinstance(a, tuple) and isinstance(b, tuple):
                what = (
                    "shape" if a[0] != b[0]
                    else "dtype" if a[1] != b[1] else "placement"
                )
                causes.append(
                    f"arg leaf {i} {what} changed: "
                    f"{a[0] if what == 'shape' else a[1] if what == 'dtype' else a[2]}"
                    f" -> "
                    f"{b[0] if what == 'shape' else b[1] if what == 'dtype' else b[2]}"
                )
            else:
                causes.append(f"arg leaf {i} changed: {_clip(repr(a))} -> "
                              f"{_clip(repr(b))}")
    od, nd = dict(old_statics), dict(new_statics)
    for k in sorted(set(od) | set(nd)):
        if od.get(k) != nd.get(k):
            causes.append(
                f"static '{k}' changed: {_clip(od.get(k, '<absent>'))} -> "
                f"{_clip(nd.get(k, '<absent>'))}"
            )
    return causes


class DispatchLedger:
    """JSON-lines flight recorder for jitted dispatches (see module
    docstring).  Thread-safe appends; one instance is process-global
    (``default_ledger``) so every entry point shares a file."""

    def __init__(self, path: str | None = None):
        self.rows: list[dict[str, Any]] = []
        self._path = path
        self._explicit = path is not None
        self._enabled = path is not None
        self._compiled: dict[tuple, tuple[Any, dict[str, Any]]] = {}
        # per-program signatures seen, in arrival order: the recompile
        # attribution diffs a new cold signature against these
        self._sigs: dict[str, list[tuple]] = {}
        self._lock = threading.Lock()

    # -- lifecycle ----------------------------------------------------------

    @property
    def path(self) -> str | None:
        self._maybe_enable_from_env()
        return self._path

    @property
    def enabled(self) -> bool:
        self._maybe_enable_from_env()
        return self._enabled

    def _maybe_enable_from_env(self) -> None:
        if not self._explicit and not self._enabled and os.environ.get(ENV_VAR):
            self.enable(os.environ[ENV_VAR])

    def enable(self, path: str | None = None) -> "DispatchLedger":
        """Start recording; ``path=None`` keeps rows in memory only."""
        self._path = path
        self._explicit = True
        self._enabled = True
        return self

    def disable(self) -> None:
        self._explicit = True
        self._enabled = False

    def clear(self) -> None:
        with self._lock:
            self.rows.clear()
            self._compiled.clear()
            self._sigs.clear()

    # -- recording ----------------------------------------------------------

    def record(self, row: dict[str, Any]) -> dict[str, Any]:
        """Append a pre-built row (bench.py's probe/rung entries use
        this directly — their timings come from the bench's own
        watchdogged measurement, not an AOT replay).  A no-op while the
        ledger is disabled; in-memory rows are capped at
        ``MAX_ROWS_IN_MEMORY`` (oldest dropped — the file keeps all)."""
        if not self.enabled:
            return row
        row = dict(row)
        row.setdefault("ts", round(time.time(), 3))
        with self._lock:
            self.rows.append(row)
            if len(self.rows) > MAX_ROWS_IN_MEMORY:
                del self.rows[: -MAX_ROWS_IN_MEMORY]
            if self._path:
                with open(self._path, "a") as f:
                    f.write(json.dumps(row) + "\n")
        return row

    def dispatch(
        self,
        program: str,
        jitted: Callable[..., Any],
        *args: Any,
        _meta: dict[str, Any] | None = None,
        **static_kwargs: Any,
    ) -> Any:
        """Run ``jitted(*args, **static_kwargs)`` and record one row.

        Disabled (the default): a plain call-through — zero overhead,
        bit-identical behavior.  Enabled: the call goes through the
        ledger's AOT cache (lower → compile → execute, each timed; the
        executable is reused on warm dispatches, so there is still
        exactly one XLA compile per abstract signature).  Static
        arguments MUST be passed as keywords.
        """
        if not self.enabled:
            return jitted(*args, **static_kwargs)
        import jax

        t0 = time.perf_counter()
        out, row = self.launch(
            program, jitted, *args, _meta=_meta, **static_kwargs
        )
        out = jax.block_until_ready(out)
        total = time.perf_counter() - t0
        # execute_s is enqueue + drain, net of the cold compile phases
        row["execute_s"] = round(
            max(total - row["trace_s"] - row["compile_s"], 0.0), 6
        )
        self.record(row)
        return out

    def launch(
        self,
        program: str,
        jitted: Callable[..., Any],
        *args: Any,
        _meta: dict[str, Any] | None = None,
        **static_kwargs: Any,
    ) -> tuple[Any, dict[str, Any] | None]:
        """``dispatch`` minus the blocking drain: AOT-compile through
        the same executable cache (cold rows still record trace/compile
        and the memory footprint — exactly one XLA compile per
        signature), enqueue the execution WITHOUT ``block_until_ready``,
        and return ``(out, row)`` with the row NOT yet recorded.

        The caller owns the drain: it converts the outputs at its own
        pace — typically after dispatching the NEXT program, so device
        compute and host-side conversion overlap — then ``record``\\ s
        the row with its ``dispatch_s`` / ``drain_s`` /
        ``drain_overlap_s`` fields added (the streaming soak runner,
        scenarios/stream.py).  Disabled: a plain call-through and a
        ``None`` row.
        """
        if not self.enabled:
            return jitted(*args, **static_kwargs), None
        import jax

        sig = _signature(args, static_kwargs)
        key = (program, sig)
        cold = key not in self._compiled
        trace_s = compile_s = 0.0
        recompile_cause: list[str] | None = None
        if cold:
            # recompile-source attribution: a SECOND cold compile for a
            # program means some signature component drifted — name it
            # (the closest prior signature's diff), so "which static arg
            # forced this" is answered by the row, not by a bisection
            prior = self._sigs.setdefault(program, [])
            if prior:
                recompile_cause = min(
                    (_sig_diff(p, sig) for p in prior), key=len
                ) or ["signature hash collision (identical components)"]
            t0 = time.perf_counter()
            lowered = jitted.lower(*args, **static_kwargs)
            t1 = time.perf_counter()
            compiled = lowered.compile()
            t2 = time.perf_counter()
            trace_s, compile_s = t1 - t0, t2 - t1
            self._compiled[key] = (compiled, memory_row(compiled))
            prior.append(sig)
        compiled, mem = self._compiled[key]
        out = compiled(*args)
        row = {
            "program": program,
            "platform": jax.default_backend(),
            "cold": cold,
            "sig": _sig_hash(sig),
            "trace_s": round(trace_s, 6),
            "compile_s": round(compile_s, 6),
            **mem,
        }
        if recompile_cause is not None:
            row["recompile_cause"] = recompile_cause
        if _meta:
            row.update(_meta)
        return out, row

    # -- reading back -------------------------------------------------------

    @staticmethod
    def load_rows(path: str) -> list[dict[str, Any]]:
        rows = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    rows.append(json.loads(line))
        return rows

    def summary(self) -> list[dict[str, Any]]:
        return summarize(self.rows)


def summarize(rows: list[dict[str, Any]]) -> list[dict[str, Any]]:
    """Aggregate ledger rows by (program, backend, platform, n, ticks,
    replicas): dispatch/cold counts, total compile seconds, execute
    percentiles (stats.py Histogram — the repo's one reservoir), and
    the peak-bytes high-water mark."""
    from ringpop_tpu.stats import Histogram

    groups: dict[tuple, dict[str, Any]] = {}
    hists: dict[tuple, Histogram] = {}
    for row in rows:
        key = tuple(
            row.get(k) for k in ("program", "backend", "platform", "n",
                                 "ticks", "replicas")
        )
        g = groups.setdefault(
            key,
            {
                "program": row.get("program"),
                "backend": row.get("backend"),
                "platform": row.get("platform"),
                "n": row.get("n"),
                "ticks": row.get("ticks"),
                "replicas": row.get("replicas"),
                "dispatches": 0,
                "cold": 0,
                "compile_s_total": 0.0,
                "peak_bytes_max": 0,
            },
        )
        g["dispatches"] += 1
        g["cold"] += int(bool(row.get("cold")))
        g["compile_s_total"] += float(row.get("compile_s") or 0.0)
        g["peak_bytes_max"] = max(
            g["peak_bytes_max"], int(row.get("peak_bytes") or 0)
        )
        if row.get("execute_s") is not None:
            hists.setdefault(key, Histogram(seed=0)).update(
                float(row["execute_s"])
            )
    out = []
    for key, g in groups.items():
        hist = hists.get(key)
        if hist is not None:
            pct = hist.percentiles([0.5, 0.95, 0.99])
            g["execute_s"] = {
                "count": hist._count,
                "p50": pct["0.5"],
                "p95": pct["0.95"],
                "p99": pct["0.99"],
            }
        g["compile_s_total"] = round(g["compile_s_total"], 6)
        out.append(g)
    out.sort(key=lambda g: (str(g["program"]), str(g["backend"]),
                            g["n"] or 0))
    return out


def summarize_runs(rows: list[dict[str, Any]]) -> list[dict[str, Any]]:
    """Per-soak pipelining summary: segment rows sharing a ``run_id``
    (one streamed scenario/sweep each, scenarios/stream.py) aggregate
    into segment/cold counts, total compile/dispatch/drain seconds, and
    the pipelining efficiency — the share of drain work that ran while
    the next segment was already in flight (``drain_overlap_s`` /
    ``drain_s``; 100% means trace conversion was fully hidden behind
    device compute)."""
    runs: dict[str, dict[str, Any]] = {}
    for row in rows:
        rid = row.get("run_id")
        if rid is None:
            continue
        g = runs.setdefault(
            rid,
            {
                "run_id": rid,
                "program": row.get("program"),
                "backend": row.get("backend"),
                "platform": row.get("platform"),
                "n": row.get("n"),
                "segment_ticks": row.get("segment_ticks"),
                "segments": 0,
                "cold": 0,
                "ticks": 0,
                "compile_s_total": 0.0,
                "dispatch_s_total": 0.0,
                "drain_s_total": 0.0,
                "drain_overlap_s_total": 0.0,
            },
        )
        g["segments"] += 1
        g["cold"] += int(bool(row.get("cold")))
        g["ticks"] += int(row.get("ticks") or 0)
        for src, dst in (
            ("compile_s", "compile_s_total"),
            ("dispatch_s", "dispatch_s_total"),
            ("drain_s", "drain_s_total"),
            ("drain_overlap_s", "drain_overlap_s_total"),
        ):
            g[dst] += float(row.get(src) or 0.0)
    out = []
    for g in runs.values():
        g["overlap_pct"] = (
            round(100.0 * g["drain_overlap_s_total"] / g["drain_s_total"], 1)
            if g["drain_s_total"]
            else 0.0
        )
        for f in ("compile_s_total", "dispatch_s_total", "drain_s_total",
                  "drain_overlap_s_total"):
            g[f] = round(g[f], 6)
        out.append(g)
    out.sort(key=lambda g: str(g["run_id"]))
    return out


_default = DispatchLedger()


def default_ledger() -> DispatchLedger:
    """The process-global ledger every instrumented call site shares."""
    return _default


def main(argv: list[str] | None = None) -> None:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m ringpop_tpu.obs.ledger",
        description="Summarize a dispatch-ledger JSON-lines file.",
    )
    ap.add_argument("path", help="ledger .jsonl written via RINGPOP_LEDGER "
                                 "or DispatchLedger.enable(path)")
    ap.add_argument("--json", action="store_true",
                    help="print one JSON summary row per group")
    args = ap.parse_args(argv)
    rows = DispatchLedger.load_rows(args.path)
    groups = summarize(rows)
    runs = summarize_runs(rows)
    if args.json:
        for g in groups:
            print(json.dumps(g))
        for g in runs:
            print(json.dumps({"kind": "run", **g}))
        return
    print(f"{len(rows)} dispatches in {args.path}")
    for g in groups:
        shape = f"n={g['n']} T={g['ticks']} R={g['replicas']}"
        ex = g.get("execute_s") or {}
        peak = g["peak_bytes_max"]
        peak_str = f"{peak / 1e6:.1f} MB" if peak >= 1e6 else f"{peak:,} B"
        print(
            f"  {g['program']} [{g['backend']}/{g['platform']}] {shape}: "
            f"{g['dispatches']} dispatches ({g['cold']} cold, "
            f"compile {g['compile_s_total']:.3f}s), "
            f"execute p50={ex.get('p50', 0):.4f}s p99={ex.get('p99', 0):.4f}s, "
            f"peak {peak_str}"
        )
    if runs:
        print(f"{len(runs)} streamed soaks:")
        for g in runs:
            print(
                f"  {g['run_id']} {g['program']} [{g['backend']}/"
                f"{g['platform']}] n={g['n']} S={g['segment_ticks']}: "
                f"{g['segments']} segments ({g['cold']} cold, compile "
                f"{g['compile_s_total']:.3f}s) over {g['ticks']} ticks, "
                f"drain {g['drain_s_total']:.3f}s "
                f"({g['overlap_pct']:.0f}% overlapped with dispatch)"
            )


if __name__ == "__main__":
    main()

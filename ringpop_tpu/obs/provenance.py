"""Gossip provenance plane: rumor-level dissemination tracing.

The scenario scan can track up to K *rumors* — a rumor is a
``(subject, view_key)`` lattice point, e.g. "node 3 is SUSPECT at
incarnation 2" — and record, per node, WHEN it first heard the rumor
and WHO (plausibly) told it, entirely inside the jitted scan.  The
answer to the operator question "why was node X declared faulty, and
how long did that rumor take to reach the stragglers?" falls out as a
propagation tree plus a detection-causality chain per tracked rumor.

Semantics (the pinned conventions; tests/test_provenance.py holds the
per-tick host oracle to them bit-for-bit):

* **knows** is lattice dominance: node v knows rumor ``(s, k)`` iff its
  post-tick view key of s is ``>= k``.  Hearing STRONGER news (the
  faulty escalation ``k+1``, or a refutation at a higher incarnation)
  counts as having heard — first_heard is a pure function of the view
  trajectory, not of any payload bookkeeping.
* **first_heard[v]** is the first tick at which v knows (int16 ticks;
  the plane rejects runs of >= 32768 ticks).  -1 = never heard.
  Knowledge that predates a slot's arming collapses to the arming
  tick (a second, later-armed rumor may find believers on day one).
* **parent[v]** is a deterministic "canonical plausible infector":
  among this tick's *delivered* protocol edges whose sender knew the
  rumor at the START of the tick, the first edge in intra-tick phase
  order — direct ping (phase 3), ack/full-sync reply (phase 4), then
  the four ping-req relay hops (5a source->witness, 5b witness->
  target, 5c target->witness ack, 5d witness->source response) —
  breaking ties inside a phase by minimum sender index.  The
  attribution is payload-blind by design: the simulator's piggyback
  budgets decide what a message CARRIES, but any delivered edge from a
  knower is a plausible infection path, and the convention is exact,
  cheap, and identical on both backends.  Sentinels: -1 = origin
  (the declarer itself, or the subject — its own authority for
  refute/revive news), -2 = heard but unattributed (delayed-lane
  arrival, or a same-tick relay chain whose sender only learned this
  tick), -3 = never heard.
* **arming**: a slot arms on a *suspect declaration that stuck* (the
  declarer's post-tick view of its target is SUSPECT/FAULTY at the
  declared incarnation).  Faulty escalations are not separately
  tracked — every FAULTY is preceded by the suspect rumor the slot
  already holds, and the escalation is the slot's *resolution*.
  ``track`` scenario ops reserve slot j for a named subject (armed by
  the first qualifying declaration about it at tick >= ``at``); the
  remaining free slots auto-arm, assigning same-tick new subjects in
  ascending subject order.  Duplicate (subject, key) pairs never
  double-arm.
* **resolution** (the detection-causality chain): the slot records the
  origin declarer, its probe tick (= declaration tick; the failed
  probe, its witness set and the declaration share one tick by the
  step's phase layout), the ping-req witness set, and the first tick
  the cluster-wide view maximum of the subject escapes the suspect
  key: ``>= key+7`` (= alive at the next incarnation) is a REFUTATION,
  else ``>= key+1`` (faulty — or leave) is a CONFIRMATION.  A tick
  where both appear resolves as refuted (the lattice winner).

State rides the scan carry bit-packed: the knows planes are uint32
words (``ops/bitpack``), and no leaf is bool (the carry-budget pin).
``prov_update`` is the ONE int-exact update shared by the scan fold
and the eager per-tick host oracle — the policy-plane precedent that
makes bit-parity a property of the call graph instead of a test's
luck.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ringpop_tpu.ops import bitpack

# status bits of a view key (mirrors swim_sim; re-declared to keep this
# module import-light for the host-side exporters)
_SUSPECT = 2
_FAULTY = 3

# first_heard / parent sentinels (module docstring)
UNHEARD = -1  # first_heard: never heard
P_ORIGIN = -1  # parent: the rumor's own origin / the subject itself
P_UNATTRIBUTED = -2  # parent: heard, but no in-tick edge explains it
P_UNHEARD = -3  # parent: never heard

# slot resolution states (pv_slot[:, 3])
RES_PENDING = 0
RES_REFUTED = 1
RES_CONFIRMED = 2

# pv_slot columns
_C_SUBJ, _C_KEY, _C_ORG, _C_RES = 0, 1, 2, 3

# the evidence keys both backend steps export when prov is armed
EVIDENCE_KEYS = (
    "pv_tgt", "pv_send", "pv_ping", "pv_ack", "pv_wit", "pv_witv",
    "pv_req", "pv_rping", "pv_rack", "pv_resp", "pv_decl",
)

MAX_RUMORS = 64  # static slot cap (K*N int16+int32 planes ride the carry)
MAX_TICKS = 32767  # int16 first_heard/tick range


class ProvCarry(NamedTuple):
    """The provenance scan carry — zero bool leaves (budget pin).

    ``knows`` stays PACKED at rest (uint32 words, 1 bit per node) and is
    unpacked only inside ``prov_update``; everything else is already
    int.  K = tracked-rumor slots, N = nodes, kk = ping_req_size.
    """

    slot: jax.Array  # int32[K, 4]: subject(-1 unarmed), key, origin, res
    tickv: jax.Array  # int16[K, 2]: (origin_tick, resolution_tick); -1
    wits: jax.Array  # int32[K, kk]: origin's ping-req witness set; -1 pad
    first: jax.Array  # int16[K, N]: first_heard ticks; -1 unheard
    parent: jax.Array  # int32[K, N]: first infector; -3/-1/-2 sentinels
    knows: jax.Array  # uint32[K, W]: packed knows plane


def init_carry(n: int, k: int, k_wit: int) -> ProvCarry:
    """A fresh all-unarmed carry for K rumor slots over N nodes."""
    w = bitpack.packed_width(n)
    return ProvCarry(
        slot=jnp.concatenate(
            [
                jnp.full((k, 3), -1, jnp.int32),
                jnp.zeros((k, 1), jnp.int32),
            ],
            axis=1,
        ),
        tickv=jnp.full((k, 2), -1, jnp.int16),
        wits=jnp.full((k, k_wit), -1, jnp.int32),
        first=jnp.full((k, n), UNHEARD, jnp.int16),
        parent=jnp.full((k, n), P_UNHEARD, jnp.int32),
        knows=jnp.zeros((k, w), jnp.uint32),
    )


def track_tensors(tracks: tuple, k: int) -> tuple[jax.Array, jax.Array]:
    """``track`` op reservations as (pv_at, pv_node) int32[K] tensors.

    ``tracks`` is the compiled tuple of (at, node) pairs; slot j holds
    reservation j and unreserved slots pad with node -1 (free for
    auto-arming)."""
    at = np.full(k, 0, np.int32)
    node = np.full(k, -1, np.int32)
    for j, (a, m) in enumerate(tracks):
        at[j] = a
        node[j] = m
    return jnp.asarray(at), jnp.asarray(node)


def _attribute(ks: jax.Array, ev: dict[str, jax.Array], n: int) -> jax.Array:
    """Canonical plausible infector per node for one rumor.

    ``ks`` is the knows-at-tick-START plane; returns int32[N] sender
    indices with ``n`` as the no-candidate sentinel.  Phase precedence
    and min-sender tie-break per the module docstring; every scatter is
    a ``.min`` onto the sentinel so the order is data-independent."""
    ids = jnp.arange(n, dtype=jnp.int32)
    sent = jnp.int32(n)
    tgt = ev["pv_tgt"]
    w = ev["pv_wit"]
    tgt_b = jnp.broadcast_to(tgt[:, None], w.shape)
    # phase 3: prober v -> its target (in-tick payload deliveries only)
    c3 = jnp.full((n,), sent).at[tgt].min(
        jnp.where(ev["pv_ping"] & ks, ids, sent)
    )
    # phase 4: the target's ack/full-sync reply back to v (elementwise)
    c4 = jnp.where(ev["pv_ack"] & ks[tgt], tgt, sent)
    # phase 5a: ping-req source v -> witness
    c5a = jnp.full((n,), sent).at[w].min(
        jnp.where(ev["pv_req"] & ks[:, None], ids[:, None], sent)
    )
    # phase 5b: witness -> target relay ping
    c5b = jnp.full((n,), sent).at[tgt_b].min(
        jnp.where(ev["pv_rping"] & ks[w], w, sent)
    )
    # phase 5c: target -> witness relay ack
    c5c = jnp.full((n,), sent).at[w].min(
        jnp.where(ev["pv_rack"] & ks[tgt][:, None], tgt_b, sent)
    )
    # phase 5d: witness -> source response
    c5d = jnp.min(jnp.where(ev["pv_resp"] & ks[w], w, sent), axis=1)
    out = c3
    for c in (c4, c5a, c5b, c5c, c5d):
        out = jnp.where(out < sent, out, c)
    return out


def prov_update(
    pvc: ProvCarry,
    ev: dict[str, jax.Array],
    tick: jax.Array,
    view_post: Callable[[jax.Array], jax.Array],
    pv_at: jax.Array,
    pv_node: jax.Array,
    n: int,
) -> tuple[ProvCarry, jax.Array]:
    """One tick of the provenance fold (scan body AND host oracle).

    ``ev`` is the step's delivery-evidence bundle (EVIDENCE_KEYS);
    ``view_post`` maps viewer-major subject queries int32[N, M] to the
    POST-tick view keys int32[N, M] (dense: a take_along_axis of
    view_key; delta: ``view_lookup``).  Returns the next carry and the
    per-slot heard count int32[K] (the ``pv_heard`` telemetry plane).
    """
    k = pvc.slot.shape[0]
    ids = jnp.arange(n, dtype=jnp.int32)
    tick = jnp.asarray(tick, jnp.int32)
    t16 = tick.astype(jnp.int16)

    # -- origin gate: suspect declarations that stuck -------------------
    # The declared key is recovered from the declarer's post-tick view:
    # an applied declaration leaves (inc*8+SUSPECT) — or its same-tick
    # FAULTY escalation at suspicion_ticks=0, which shares the inc — so
    # (view >> 3) * 8 + SUSPECT IS the declared key; a declaration the
    # lattice refused (already refuted at a higher incarnation) leaves
    # an ALIVE status and is filtered here.
    tgt = ev["pv_tgt"]
    post_t = view_post(tgt[:, None])[:, 0]
    st8 = post_t & 7
    dkey = (post_t >> 3) * 8 + jnp.int32(_SUSPECT)
    decl = ev["pv_decl"] & ((st8 == _SUSPECT) | (st8 == _FAULTY)) & (tgt != ids)

    # -- arming ---------------------------------------------------------
    armed = pvc.slot[:, _C_SUBJ] >= 0
    dup = jnp.any(
        armed[None, :]
        & (tgt[:, None] == pvc.slot[None, :, _C_SUBJ])
        & (dkey[:, None] == pvc.slot[None, :, _C_KEY]),
        axis=1,
    )
    cand = decl & ~dup
    # per-subject aggregation: the rumor key is the max declared key and
    # the origin the min declarer index (simultaneous declarers)
    s_idx = jnp.where(cand, tgt, n)
    key_by = jnp.full((n,), -1, jnp.int32).at[s_idx].max(dkey, mode="drop")
    org_by = jnp.full((n,), n, jnp.int32).at[s_idx].min(ids, mode="drop")
    has_subj = key_by >= 0
    # reserved slots fire first (track ops pin slot j to a subject)
    rsv_subj = jnp.clip(pv_node, 0, n - 1)
    rsv_fire = (
        (~armed) & (pv_node >= 0) & (tick >= pv_at) & has_subj[rsv_subj]
    )
    consumed = (
        jnp.zeros((n,), bool)
        .at[jnp.where(rsv_fire, rsv_subj, n)]
        .set(True, mode="drop")
    )
    # free slots auto-arm the remaining new subjects in ascending order
    rem = has_subj & ~consumed
    s_rank = jnp.cumsum(rem.astype(jnp.int32)) - 1
    subj_by_rank = (
        jnp.full((k,), -1, jnp.int32)
        .at[jnp.where(rem, s_rank, k)]
        .set(ids, mode="drop")
    )
    free = (~armed) & (pv_node < 0)
    f_rank = jnp.cumsum(free.astype(jnp.int32)) - 1
    auto_subj = jnp.where(free, subj_by_rank[jnp.clip(f_rank, 0, k - 1)], -1)
    new_subj = jnp.where(rsv_fire, rsv_subj, auto_subj)
    arm_now = new_subj >= 0
    safe_new = jnp.clip(new_subj, 0, n - 1)
    new_org = org_by[safe_new]
    org_safe = jnp.clip(new_org, 0, n - 1)
    new_wits = jnp.where(ev["pv_witv"][org_safe], ev["pv_wit"][org_safe], -1)
    slot = jnp.where(
        arm_now[:, None],
        jnp.stack(
            [new_subj, key_by[safe_new], new_org, jnp.zeros((k,), jnp.int32)],
            axis=1,
        ),
        pvc.slot,
    )
    tickv = jnp.where(
        arm_now[:, None],
        jnp.stack([jnp.full((k,), 1, jnp.int16) * t16,
                   jnp.full((k,), -1, jnp.int16)], axis=1),
        pvc.tickv,
    )
    wits = jnp.where(arm_now[:, None], new_wits, pvc.wits)

    # -- knows / first_heard / parent -----------------------------------
    subj = slot[:, _C_SUBJ]
    keyv = slot[:, _C_KEY]
    armed2 = subj >= 0
    q = jnp.broadcast_to(jnp.clip(subj, 0, n - 1)[None, :], (n, k))
    col = view_post(q)  # [N, K] viewer-major post views of each subject
    knows_new = (armed2[None, :] & (col >= keyv[None, :])).T  # [K, N]
    knows_old = bitpack.unpack_bits(pvc.knows, n)  # [K, N]
    newly = knows_new & ~knows_old
    cand_p = jax.vmap(lambda ks: _attribute(ks, ev, n))(knows_old)  # [K, N]
    origin_sig = (ids[None, :] == subj[:, None]) | (
        decl[None, :]
        & (tgt[None, :] == subj[:, None])
        & (dkey[None, :] == keyv[:, None])
    )
    parent_new = jnp.where(
        origin_sig,
        jnp.int32(P_ORIGIN),
        jnp.where(cand_p < n, cand_p, jnp.int32(P_UNATTRIBUTED)),
    )
    parent = jnp.where(newly, parent_new, pvc.parent)
    first = jnp.where(newly, t16, pvc.first)

    # -- resolution ------------------------------------------------------
    mx = jnp.max(jnp.where(armed2[None, :], col, -1), axis=0)  # [K]
    pend = armed2 & (slot[:, _C_RES] == RES_PENDING)
    res_new = jnp.where(
        mx >= keyv + 7,
        jnp.int32(RES_REFUTED),
        jnp.where(mx >= keyv + 1, jnp.int32(RES_CONFIRMED),
                  jnp.int32(RES_PENDING)),
    )
    fire = pend & (res_new != RES_PENDING)
    slot = slot.at[:, _C_RES].set(
        jnp.where(fire, res_new, slot[:, _C_RES])
    )
    tickv = tickv.at[:, 1].set(jnp.where(fire, t16, tickv[:, 1]))

    heard = jnp.sum(knows_new, axis=1, dtype=jnp.int32)
    return (
        ProvCarry(slot, tickv, wits, first, parent,
                  bitpack.pack_bits(knows_new)),
        heard,
    )


# ---------------------------------------------------------------------------
# host-side report
# ---------------------------------------------------------------------------


def _pct(times: np.ndarray, q: float) -> int:
    """All-int lower-percentile over a nonempty int array."""
    s = np.sort(times)
    idx = min(len(s) - 1, max(0, int(np.ceil(q * len(s))) - 1))
    return int(s[idx])


def build_report(
    pv_slot: Any,
    pv_tickv: Any,
    pv_wits: Any,
    pv_first: Any,
    pv_parent: Any,
    pv_knows: Any,
    n: int,
) -> dict[str, Any]:
    """The host-side provenance report from the final net's pv tensors.

    Per armed slot: the rumor identity, its causality chain, the full
    propagation tree (tick-ordered parent edges — a parent always heard
    strictly earlier, so one pass assigns depths), infection-time
    percentiles vs the paper's log2(N) bound, and straggler counts.
    Everything is an int (golden-pinnable)."""
    slot = np.asarray(pv_slot)
    tickv = np.asarray(pv_tickv).astype(np.int32)
    wits = np.asarray(pv_wits)
    first = np.asarray(pv_first).astype(np.int32)
    parent = np.asarray(pv_parent)
    del pv_knows  # knows == (first >= 0) by construction
    log2n = int(np.ceil(np.log2(max(2, n))))
    rumors = []
    for j in range(slot.shape[0]):
        if slot[j, _C_SUBJ] < 0:
            continue
        fh = first[j]
        par = parent[j]
        heard = fh >= 0
        origin_tick = int(tickv[j, 0])
        times = (fh[heard] - origin_tick).astype(np.int64)
        # depth: process heard nodes in first_heard order; parents heard
        # strictly earlier (knows-at-start attribution), origins depth 0
        depth = np.full(n, -1, np.int64)
        for v in np.lexsort((np.arange(n), np.where(heard, fh, 1 << 30))):
            if not heard[v]:
                break
            p = par[v]
            if p == P_ORIGIN:
                depth[v] = 0
            elif p >= 0 and depth[p] >= 0:
                depth[v] = depth[p] + 1
        infected = int(heard.sum())
        rumors.append(
            {
                "slot": j,
                "subject": int(slot[j, _C_SUBJ]),
                "key": int(slot[j, _C_KEY]),
                "origin": int(slot[j, _C_ORG]),
                "origin_tick": origin_tick,
                "resolution": int(slot[j, _C_RES]),
                "resolution_tick": int(tickv[j, 1]),
                "witnesses": [int(w) for w in wits[j] if w >= 0],
                "infected": infected,
                "unheard": n - infected,
                "unattributed": int((par[heard] == P_UNATTRIBUTED).sum()),
                "depth_max": int(depth.max()) if infected else -1,
                "infection_p50": _pct(times, 0.50) if infected else -1,
                "infection_p95": _pct(times, 0.95) if infected else -1,
                "infection_p99": _pct(times, 0.99) if infected else -1,
                "stragglers": int((times > 2 * log2n).sum()),
                "first_heard": fh.tolist(),
                "parent": par.tolist(),
            }
        )
    return {"n": n, "log2_n": log2n, "rumors": rumors}


def summary_block(report: dict[str, Any]) -> dict[str, int]:
    """The all-int aggregate block ``library.incident_summary`` embeds
    (worst-case over rumors, so the pin catches any slot regressing)."""
    rs = report["rumors"]
    if not rs:
        return {"rumors": 0}
    return {
        "rumors": len(rs),
        "confirmed": sum(1 for r in rs if r["resolution"] == RES_CONFIRMED),
        "refuted": sum(1 for r in rs if r["resolution"] == RES_REFUTED),
        "infected_min": min(r["infected"] for r in rs),
        "infected_max": max(r["infected"] for r in rs),
        "depth_max": max(r["depth_max"] for r in rs),
        "p50_max": max(r["infection_p50"] for r in rs),
        "p95_max": max(r["infection_p95"] for r in rs),
        "p99_max": max(r["infection_p99"] for r in rs),
        "stragglers": sum(r["stragglers"] for r in rs),
        "unattributed": sum(r["unattributed"] for r in rs),
    }

"""Provenance report → Chrome trace-event JSON (Perfetto-openable).

The provenance plane (``obs.provenance``) answers "who told whom,
when, and what did the detector conclude" as flat int tensors; this
module renders its host-side report as the trace-event format both
``chrome://tracing`` and https://ui.perfetto.dev open directly:

* one **track** (tid) per tracked rumor, under one "gossip provenance"
  process — the rumor's identity (subject / incarnation / status) is
  the thread name;
* one **complete event** ("X") per rumor spanning origination →
  resolution: the suspect→faulty (or suspect→refute) detection-
  causality window, carrying the origin prober, the ping-req witness
  set, and the resolution verdict as args;
* one **complete event** per infected node at its ``first_heard``
  tick (1-tick wide), with **flow arrows** ("s"/"f") along the
  propagation-tree edges — the dissemination wavefront reads as a
  cascade of arrows fanning out from the origin;
* the all-int summary block riding in ``otherData`` so a trace file is
  self-describing without the npz it came from.

Ticks map to microseconds at ``tick_us`` per tick (default 1000, so
one protocol tick renders as 1 ms and Perfetto's time ruler reads as
"protocol milliseconds").  Everything here is host-side numpy/JSON —
no jax import, usable from the bench parent process.
"""

from __future__ import annotations

import json
import os
from typing import Any

from ringpop_tpu.obs import provenance as pvn

# trace-event phase codes (the Chrome trace-event format spec)
_COMPLETE = "X"
_META = "M"
_FLOW_START = "s"
_FLOW_END = "f"

_STATUS_NAME = {1: "alive", 2: "suspect", 3: "faulty", 4: "leave"}
_RES_NAME = {
    pvn.RES_PENDING: "pending",
    pvn.RES_REFUTED: "refuted",
    pvn.RES_CONFIRMED: "confirmed",
}


def _rumor_label(r: dict[str, Any]) -> str:
    status = _STATUS_NAME.get(r["key"] & 7, f"status{r['key'] & 7}")
    return (
        f"rumor {r['slot']}: n{r['subject']} {status} "
        f"inc{r['key'] >> 3}"
    )


def trace_events(
    report: dict[str, Any], *, tick_us: int = 1000
) -> list[dict[str, Any]]:
    """The report's rumors as a flat trace-event list (see module doc).

    Deterministic: events are emitted in slot order, infections in node
    order — two runs of the same report serialize identically."""
    pid = 1
    ev: list[dict[str, Any]] = [
        {
            "ph": _META, "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": "gossip provenance"},
        }
    ]
    for r in report["rumors"]:
        tid = r["slot"] + 1  # tid 0 is the process-meta row
        ev.append(
            {
                "ph": _META, "name": "thread_name", "pid": pid, "tid": tid,
                "args": {"name": _rumor_label(r)},
            }
        )
        t0 = r["origin_tick"]
        t_res = r["resolution_tick"]
        # the detection-causality window: origination → resolution (an
        # unresolved rumor spans to the last infection instead, so the
        # track still shows how far the run got)
        fh = r["first_heard"]
        last = max((t for t in fh if t >= 0), default=t0)
        end = t_res if t_res >= 0 else max(last, t0)
        verdict = _RES_NAME.get(r["resolution"], "?")
        ev.append(
            {
                "ph": _COMPLETE,
                "name": f"{_STATUS_NAME.get(r['key'] & 7, '?')}→{verdict}",
                "cat": "detection",
                "pid": pid,
                "tid": tid,
                "ts": t0 * tick_us,
                "dur": max(end - t0, 1) * tick_us,
                "args": {
                    "subject": r["subject"],
                    "key": r["key"],
                    "origin_prober": r["origin"],
                    "witnesses": r["witnesses"],
                    "resolution": verdict,
                    "resolution_tick": t_res,
                    "infected": r["infected"],
                    "depth_max": r["depth_max"],
                },
            }
        )
        # the infection wavefront: one 1-tick slice per heard node,
        # with a flow arrow from its parent's slice (the propagation
        # tree); unattributed/origin nodes just get the slice
        par = r["parent"]
        for v, t in enumerate(fh):
            if t < 0:
                continue
            ev.append(
                {
                    "ph": _COMPLETE,
                    "name": f"n{v}",
                    "cat": "infection",
                    "pid": pid,
                    "tid": tid,
                    "ts": t * tick_us,
                    "dur": tick_us,
                    "args": {"node": v, "parent": par[v]},
                }
            )
        for v, t in enumerate(fh):
            p = par[v]
            if t < 0 or p < 0:
                continue  # unheard, origin, or unattributed: no edge
            flow = {
                "cat": "gossip",
                "name": "heard-from",
                "id": r["slot"] * (len(fh) + 1) + v + 1,
                "pid": pid,
                "tid": tid,
            }
            # the parent heard strictly earlier (knows-at-start
            # attribution), so its slice encloses ts = fh[p] and the
            # arrow lands inside the child's slice at ts = t
            ev.append({**flow, "ph": _FLOW_START, "ts": fh[p] * tick_us})
            ev.append(
                {**flow, "ph": _FLOW_END, "bp": "e", "ts": t * tick_us}
            )
    return ev


def write_spans(
    report: dict[str, Any], path: str, *, tick_us: int = 1000
) -> int:
    """Write the report as a trace-event JSON file (the object form,
    with the summary block in ``otherData``).  Returns the event
    count.  Atomic like every other writer here (tmp + rename)."""
    events = trace_events(report, tick_us=tick_us)
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "source": "ringpop_tpu gossip provenance plane",
            "tick_us": tick_us,
            "n": report["n"],
            "summary": pvn.summary_block(report),
        },
    }
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, separators=(",", ":"))
    os.replace(tmp, path)
    return len(events)

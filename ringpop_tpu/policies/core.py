"""The remediation policy plane: one int-exact update, two executors.

A policy is a per-tick fold over the same load signal the overload
feedback loop reads (``node_sends``, the per-holder landed-send count):

* a **pressure meter** per node — the leaky bucket
  ``press' = max(0, press + sends - admit_capacity)``, the exact shape
  of ``faults.overload_update``'s counter so the two planes are
  comparable tick-for-tick;
* an **admission (shedding) flag** per node with hysteresis — requests
  whose first resolved holder is shedding are dropped at arrival (one
  landed send, zero retries) instead of burning duty-phase timeouts;
* a **quarantine flag** per node with hysteresis — served rings are
  steered away from pressured nodes via the PR 7 ``damped``-mask
  mechanism (membership truth untouched; misroutes-vs-truth inflate by
  design while a node is steered around);
* an **adaptive retry budget** — a trailing ``amp_window``-tick ring of
  (total sends, delivered) whose ratio is the observed amplification in
  x16 fixed point; when it crosses ``amp_threshold_x16`` the per-origin
  retry cap collapses to ``retry_floor`` until the storm quenches.

Everything is int32 arithmetic with no data-dependent shapes, so the
SAME ``policy_update`` body executes under ``lax.scan`` (jnp arrays)
and in the host oracle (np arrays) — the bit-parity tests call this
one function twice.

Mechanism enablement is **not** a compile-time static: a disabled
mechanism gets an ``INF`` threshold (never fires) so every named
policy shares one compiled program per ``amp_window``, and every knob
is a traced scalar that `run_sweep` can batch per replica without a
recompile (pre-paying ROADMAP item 4's frozen-knob refactor).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import numpy as np

# A threshold no int32 meter can reach: the OFF position for any
# mechanism (press < INF always, so the flag can never latch).
INF = 2**31 - 1


class PolicyConfig(NamedTuple):
    """The jit-static part of a policy (hashable; shapes only)."""

    amp_window: int = 8  # trailing window (ticks) for the amp ratio


class PolicyKnobs(NamedTuple):
    """The traced part: int32 scalars on device, [R] axes in a sweep.

    Every field is an operating point, not a shape — changing one
    never recompiles, and `run_sweep` batches them per replica.
    """

    admit_capacity: Any  # sends/tick a holder absorbs before pressure
    shed_hi: Any  # press >= shed_hi latches the shedding flag
    shed_lo: Any  # hysteresis: shed holds while press > shed_lo
    quar_hi: Any  # press >= quar_hi latches ring quarantine
    quar_lo: Any  # hysteresis: quarantine holds while press > quar_lo
    amp_threshold_x16: Any  # amp (x16 fixed point) that cuts retries
    retry_floor: Any  # the cut retry cap (0 = no retries at all)


class CompiledPolicy(NamedTuple):
    """A named operating point: static config + concrete int knobs."""

    name: str
    config: PolicyConfig
    knobs: PolicyKnobs  # plain python ints (device-ified per executor)


def policy_update(cfg, knobs, press, shed, quar, sends_w, deliv_w,
                  node_sends, tick_sends, tick_delivered, t, max_retries):
    """One policy tick. Works on jnp arrays (scan) and np arrays (host).

    Reads tick ``t``'s serve outputs, returns the plane the serve at
    ``t+1`` must consult — the same post-serve causality as
    ``overload_update``.  Returns
    ``(press, shed, quar, sends_w, deliv_w, retry_cap, amp_x16)``.
    """
    if isinstance(press, np.ndarray):
        np_like = np
    else:
        import jax.numpy as jnp

        np_like = jnp
    i32 = np_like.int32
    press = np_like.maximum(
        press + node_sends - knobs.admit_capacity, 0
    ).astype(i32)
    shed = (press >= knobs.shed_hi) | (shed & (press > knobs.shed_lo))
    quar = (press >= knobs.quar_hi) | (quar & (press > knobs.quar_lo))
    lanes = np_like.arange(cfg.amp_window)
    slot = t % cfg.amp_window
    sends_w = np_like.where(lanes == slot, tick_sends, sends_w).astype(i32)
    deliv_w = np_like.where(lanes == slot, tick_delivered, deliv_w).astype(i32)
    ssum = np_like.sum(sends_w)
    dsum = np_like.sum(deliv_w)
    amp_x16 = ((16 * ssum) // np_like.maximum(dsum, 1)).astype(i32)
    cut = amp_x16 >= knobs.amp_threshold_x16
    retry_cap = np_like.where(
        cut, knobs.retry_floor, max_retries
    ).astype(i32)
    return press, shed, quar, sends_w, deliv_w, retry_cap, amp_x16


def init_policy_state(n: int, cfg: PolicyConfig, max_retries: int,
                      net=None):
    """Fresh (or NetState-resumed) policy carry, unpacked form:
    ``(press i32[N], shed bool[N], quar bool[N], sends_w i32[W],
    deliv_w i32[W], retry_cap i32 scalar)``."""
    import jax.numpy as jnp

    if net is not None and getattr(net, "po_press", None) is not None:
        return (
            jnp.asarray(net.po_press, jnp.int32),
            jnp.asarray(net.po_shed, bool),
            jnp.asarray(net.po_quar, bool),
            jnp.asarray(net.po_sends_w, jnp.int32),
            jnp.asarray(net.po_deliv_w, jnp.int32),
            jnp.asarray(net.po_retry_cap, jnp.int32),
        )
    w = cfg.amp_window
    return (
        jnp.zeros((n,), jnp.int32),
        jnp.zeros((n,), bool),
        jnp.zeros((n,), bool),
        jnp.zeros((w,), jnp.int32),
        jnp.zeros((w,), jnp.int32),
        jnp.asarray(max_retries, jnp.int32),
    )


def knob_arrays(cp: CompiledPolicy) -> PolicyKnobs:
    """The knobs as int32 device scalars (the traced scan arguments)."""
    import jax.numpy as jnp

    return PolicyKnobs(*(jnp.asarray(v, jnp.int32) for v in cp.knobs))


# name -> (doc line, enabled mechanisms)
POLICIES: dict[str, tuple[str, tuple[str, ...]]] = {
    "admission": (
        "load-shedding at hot holders: drop excess arrivals at the "
        "pressured owner before a duty-phase timeout burns retries",
        ("admission",),
    ),
    "retry_budget": (
        "adaptive retry budgets: collapse RETRY_SCHEDULE consumption "
        "to retry_floor while trailing amplification >= threshold",
        ("retry_budget",),
    ),
    "quarantine": (
        "serve-side quarantine: steer served rings away from "
        "pressured nodes before suspicion fires (damped-mask reuse)",
        ("quarantine",),
    ),
    "combined": (
        "all three mechanisms at their default operating points",
        ("admission", "retry_budget", "quarantine"),
    ),
}


def default_knobs(name: str, n: int, m: int) -> dict[str, int]:
    """Scale-aware defaults: ``base`` mirrors the incident builder's
    per-holder capacity ``max(3, 3m/2n)`` so a policy engages at the
    same pressure scale the cascading_overload meter does."""
    base = max(3, (3 * m) // (2 * n))
    knobs = dict(
        admit_capacity=base,
        shed_hi=INF, shed_lo=INF,
        quar_hi=INF, quar_lo=INF,
        amp_threshold_x16=INF, retry_floor=0,
    )
    _, mechs = POLICIES[name]
    if "admission" in mechs:
        knobs.update(shed_hi=2 * base, shed_lo=max(1, base // 2))
    if "quarantine" in mechs:
        # engage well below the incident's gray threshold (6x base):
        # steer the ring before the overload meter grays the node
        knobs.update(quar_hi=base, quar_lo=max(1, base // 4))
    if "retry_budget" in mechs:
        # 1.5x sends/delivered (x16 fixed point) — the acceptance bar
        knobs.update(amp_threshold_x16=24, retry_floor=0)
    return knobs


def parse_policy_arg(arg: str) -> tuple[str, dict[str, int]]:
    """``NAME[:k=v,...]`` -> (name, integer overrides)."""
    name, _, rest = arg.partition(":")
    name = name.strip()
    if name not in POLICIES:
        raise ValueError(
            f"unknown policy {name!r} (have {', '.join(sorted(POLICIES))})"
        )
    overrides: dict[str, int] = {}
    if rest.strip():
        for item in rest.split(","):
            key, eq, val = item.partition("=")
            key = key.strip()
            if not eq or key not in set(PolicyKnobs._fields) | {"amp_window"}:
                raise ValueError(
                    f"bad policy knob {item!r} (knobs: "
                    f"{', '.join(PolicyKnobs._fields)}, amp_window)"
                )
            overrides[key] = int(val)
    return name, overrides


def compile_policy(policy, *, n: int, m: int,
                   **overrides: int) -> CompiledPolicy:
    """Resolve a policy argument (name string with optional ``:k=v``
    knobs, dict from a stream cursor, or an already-compiled policy)
    into a concrete ``CompiledPolicy`` at cluster scale (n, m)."""
    if isinstance(policy, CompiledPolicy):
        return policy
    if isinstance(policy, dict):
        return from_dict(policy)
    name, parsed = parse_policy_arg(str(policy))
    parsed.update(overrides)
    amp_window = int(parsed.pop("amp_window", PolicyConfig().amp_window))
    if amp_window < 1:
        raise ValueError("amp_window must be >= 1")
    knobs = default_knobs(name, n, m)
    for key, val in parsed.items():
        knobs[key] = int(val)
    return CompiledPolicy(
        name=name,
        config=PolicyConfig(amp_window=amp_window),
        knobs=PolicyKnobs(**knobs),
    )


def to_dict(cp: CompiledPolicy) -> dict:
    """JSON-able form for stream cursors and golden metadata; round
    trips bit-exactly through ``from_dict`` (no scale rederivation)."""
    return {
        "name": cp.name,
        "amp_window": cp.config.amp_window,
        "knobs": {k: int(v) for k, v in cp.knobs._asdict().items()},
    }


def from_dict(d: dict) -> CompiledPolicy:
    return CompiledPolicy(
        name=str(d["name"]),
        config=PolicyConfig(amp_window=int(d["amp_window"])),
        knobs=PolicyKnobs(**{k: int(v) for k, v in d["knobs"].items()}),
    )


def format_catalog(n: int | None = None, m: int | None = None) -> str:
    """The ``--list-policies`` text: catalog + knob table (with the
    concrete defaults when a cluster scale is given)."""
    lines = ["policies (tick-cluster --policy NAME[:k=v,...]):", ""]
    for name, (doc, mechs) in POLICIES.items():
        lines.append(f"  {name:<14} {doc}")
        lines.append(f"  {'':<14} mechanisms: {', '.join(mechs)}")
        if n is not None and m is not None:
            knobs = default_knobs(name, n, m)
            shown = ", ".join(
                f"{k}={v}" for k, v in knobs.items() if v != INF
            )
            lines.append(f"  {'':<14} defaults @ n={n}, m={m}: {shown}")
        lines.append("")
    lines.append(
        "knobs: admit_capacity (pressure leak/tick), shed_hi/shed_lo "
        "(admission hysteresis), quar_hi/quar_lo (quarantine "
        "hysteresis), amp_threshold_x16 (x16 fixed-point amplification "
        "that cuts retries), retry_floor (the cut cap), amp_window "
        "(trailing ticks, compile-time)."
    )
    return "\n".join(lines)


def list_policies() -> list[str]:
    return sorted(POLICIES)

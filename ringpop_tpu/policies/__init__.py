"""Remediation policy plane: compiled operator actions riding the scan.

The subsystem makes remediation a first-class, sweepable plane next to
the overload feedback loop (ROADMAP item 3): admission control /
load-shedding at hot holders, adaptive retry budgets keyed on observed
amplification, and serve-side quarantine that steers rings away from
pressured nodes before suspicion fires.  One int-exact per-tick update
(`core.policy_update`) is shared verbatim between the jitted scenario
scan and the host oracle the tests replay.
"""

from ringpop_tpu.policies.core import (  # noqa: F401
    INF,
    CompiledPolicy,
    PolicyConfig,
    PolicyKnobs,
    POLICIES,
    compile_policy,
    format_catalog,
    from_dict,
    init_policy_state,
    knob_arrays,
    list_policies,
    parse_policy_arg,
    policy_update,
    to_dict,
)

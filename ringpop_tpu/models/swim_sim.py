"""TPU SWIM simulation backend: the membership + dissemination layers as
vmapped epidemic-broadcast kernels over dense N x N view/state tensors.

This is the tensorized re-design of the reference's L3+L4
(lib/membership.js, lib/dissemination.js, lib/swim/*): instead of one
process per node exchanging JSON change lists over TChannel, every virtual
node's *view* of the cluster is one row of a dense tensor, and one jitted
``swim_step`` advances every node through one protocol period
simultaneously.  The "network" is a boolean delivery mask — packet loss,
partitions and suspended processes are all mask edits (the fault-injection
surface replacing tick-cluster.js signals).

State layout (6 bytes per (viewer, subject) pair — sized by HBM):

* ``view_key: int32`` — the incarnation-precedence lattice key itself,
  ``inc * 8 + status`` (0 = member unknown/nonexistent).  Storing the key
  instead of (status int8, inc int32) makes every merge a plain int32
  ``max``/compare with no unpacking on the hot path and drops a byte.
* ``pb: int8`` — piggyback count (-1: no recorded change).  The budget
  ``factor * ceil(log10(count+1))`` is <= 75 for N <= 99,999
  (dissemination.js:38-55), clamped to 126 for safety.
* ``suspect_left: int8`` — suspicion countdown in ticks (-1: no timer),
  the tensor form of per-node Suspicion.timers (suspicion.js:27).

Semantics parity map (reference file:line -> here):

* membership-update-rules.js:25-59  -> ``_apply_mask`` over stored keys:
  the incarnation-precedence lattice is the total order of ``view_key``
  plus two masks for the non-total corners (leave is only overridden by
  alive; a first-sighted member takes any change).
* membership.js:243-254             -> refutation: any suspect/faulty rumor
  about self re-asserts alive with ``max(self_inc, rumor_inc) + 1``.
* dissemination.js:125-177          -> per-(viewer, subject) piggyback
  counts; a recorded change is issued while ``pb < max_piggyback`` and
  evicted past it.  A change's payload is always the viewer's current
  lattice key for the subject — the reference's change buffer is keyed by
  address and overwritten on every applied update.
* dissemination.js:86-98            -> anti-echo, value-form: a reply
  omits claims identical to what the ping sender itself delivered this
  tick.  The reference filters by (source, sourceIncarnation); the value
  form suppresses exactly the claims the sender provably already holds,
  so it cannot lose information — it trades the 8 bytes/pair of
  (src, src_inc) for a bounded amount of redundant steady-state traffic
  (claims learned from elsewhere that happen to equal the sender's).
* dissemination.js:61-76,100-118    -> full sync: a receiver with nothing to
  piggyback but a checksum mismatch answers with its entire view row.
* swim/ping-sender.js, ping-handler -> phase 2/3/4 of ``swim_step``.
* swim/ping-req-sender.js:153-296   -> phase 5: k random witnesses, two-hop
  reachability, all-definite-failures => suspect.
* swim/suspicion.js                 -> ``suspect_left`` countdown; expiry
  declares faulty; any applied non-suspect status stops the timer (the
  reference stops only on alive and lets a post-faulty fire no-op —
  same behavior); re-suspect restarts it.  The
  countdown keeps running for suspended processes but only *fires* while
  the viewer gossips (held at 0) — a SIGSTOPped node's timers fire on
  resume, like real setTimeouts (tick-cluster.js:432-446).
* membership-iterator.js            -> probe-target selection; the reference
  uses a reshuffled round-robin; the simulation's default ``probe="sweep"``
  is a deterministic staggered rotation preserving the iterator's
  probe-every-member-per-round guarantee (``probe="uniform"`` samples
  uniformly instead — marginally equivalent, but with a
  coupon-collector detection tail).

Time model: one call to ``swim_step`` == one protocol period
(gossip.js:127-129, 200 ms) for every node at once.  Wall-clock timeouts
become tick counts (suspicion 5000 ms -> 25 ticks).  The reference's ping
timeout (1500 ms) spans periods; the simulation compresses
ping + ping-req + suspect-declaration into the probing tick.  Convergence
measured in ticks maps to wall-clock via ``period_ms``.

Documented intra-tick conventions (where the async reference has no
defined order):

* Concurrent inbound pings at one receiver are merged by the lattice's
  total-order key (the reference applies them in arrival order; both end
  at the lattice maximum except for contrived leave/suspect mixes).
* A receiver's reply piggyback counter advances by the number of inbound
  pings it served that tick, but all probers of the tick see the same
  issued set.
* The piggyback budget and the probe-target/witness pool are computed
  from the period-start view (the reference recomputes the budget on ring
  change mid-period; one-tick lag, convergence-neutral).
* The ping-req path carries the full piggyback exchange at all four
  hops (source->witness, witness->target, target->witness,
  witness->source — ping-req-sender.js:80-86,138,
  ping-req-handler.js:37-59), as four sequential stage merges inside
  the probing tick; see ``_phase5_pingreq`` for the stage conventions
  (one issue set per stage, counters advance by requests served,
  anti-echo on the reply hops, no full-sync inside the relay unless
  ``relay_full_sync`` is set).
  ``benchmarks/bench_pingreq_deviation.py`` pins kill-detection-latency
  agreement with the host library (which runs the same exchange over
  real sockets) as a regression check.

Incarnation numbers are stored as non-negative int32 offsets from a
host-side base (``SimCluster`` keeps the absolute int ms base) so all
device arithmetic is x64-free; the lattice key needs ``inc * 8`` to fit
int32, so relative incarnations must stay below 2**27 (~37 hours of ms) —
``init_state``/``revive`` validate this at the host boundary.
"""

from __future__ import annotations

import contextlib
import math
import os
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from ringpop_tpu.obs import annotate
from ringpop_tpu.ops import gossip_remote_copy as _grc


# Status encoding: lattice rank == code (alive < suspect < faulty < leave,
# matching equal-incarnation precedence in membership-update-rules.js).
NONE = 0
ALIVE = 1
SUSPECT = 2
FAULTY = 3
LEAVE = 4

STATUS_NAMES = {ALIVE: "alive", SUSPECT: "suspect", FAULTY: "faulty", LEAVE: "leave"}

INC_MAX = (1 << 27) - 1  # inc * 8 + status must fit int32


class SwimParams(NamedTuple):
    """Protocol constants (reference defaults cited per field)."""

    period_ms: int = 200  # gossip.js:127-129 minProtocolPeriod
    suspicion_ticks: int = 25  # suspicion.js:110-112 (5000 ms / period)
    piggyback_factor: int = 15  # dissemination.js:133-136
    ping_req_size: int = 3  # index.js:99
    loss: float = 0.0  # iid per-message drop probability
    # Flap damping (EXTENSION; active only when the state carries damp
    # tensors — init_state(damping=True)).  Mirrors damping.py: penalty
    # per flap, exponential decay, suppress/reuse hysteresis.  Default
    # decay 0.5 ** (tick / half-life) with 60 s half-life @ 200 ms ticks.
    damp_penalty: float = 500.0
    damp_suppress: float = 2500.0
    damp_reuse: float = 500.0
    damp_decay_per_tick: float = 0.5 ** (0.2 / 60.0)
    # Sparse dissemination (0 = dense).  When > 0, each ping/ack carries
    # at most ``sparse_cap`` changes as a compact (subject, key) list
    # applied by point scatters — the steady-state fast path.  The whole
    # step (views AND piggyback counters) is bit-identical to the dense
    # step whenever no row has more than ``sparse_cap`` active changes
    # (steady state); under churn bursts it degrades to bounded-message
    # semantics: overflowed changes neither send nor consume budget and
    # ship on later pings.  Full syncs always take the exact dense reply
    # path via lax.cond.
    sparse_cap: int = 0
    # Probe-target policy.  "sweep" (default): deterministic rotation
    # ``(start_i + tick // phase_mod) mod n`` — the index advances once
    # per protocol PERIOD, so staggered nodes (phase_mod > 1) still
    # cover every member instead of a coset — with a uniform fallback
    # when the swept
    # slot is not pingable — restores the reference iterator's guarantee
    # that every stable member is probed once per n-tick round
    # (membership-iterator.js:33-40), bounding worst-case detection
    # latency without the coupon-collector tail.  "uniform": sample
    # among pingable members (marginally matches the reference's
    # reshuffled round-robin, but a member can go unprobed for many
    # rounds — the coupon-collector tail the reference iterator avoids).
    probe: str = "sweep"
    # Relay full-sync (VERDICT item 5 — the one knowing omission in the
    # ping-req relay): when True, stage 5c's ack from the target to a
    # witness falls back to the target's ENTIRE view row when the
    # target has no non-echo claims to issue but its post-5b view hash
    # differs from the witness's period-start hash — the same
    # nothing-to-say-but-checksums-disagree rule the regular ping reply
    # applies (dissemination.js:100-118 via server/ping-req-handler.js:
    # 43-50, whose inner ping goes through the full receiver path).
    # Off (the historical convention) the relay only carries changes
    # and phase-4 pings repair the divergence; benchmarks/bench_faults
    # A/Bs the heal-time cost and BASELINE.md records the bound.
    # Dense backend only.
    relay_full_sync: bool = False
    # Per-node staggered protocol periods (gossip.js:38-51: each node's
    # first tick lands randomly in [0, minProtocolPeriod) and periods
    # self-schedule per node; the sims' default is lockstep).  When
    # phase_mod = P > 1, one tick models 1/P of a protocol period: node
    # i initiates its probe only on ticks with tick % P == phase_i (a
    # fixed pseudo-random assignment), while timers, deliveries, and
    # relay/witness service run every tick — matching the reference,
    # where suspicion is wall-clock and a node answers RPCs at any
    # offset.  Callers must scale tick-denominated knobs by P
    # (suspicion_ticks, detection-latency readouts) to keep wall-clock
    # semantics.  Dense backend only (the fidelity experiment,
    # benchmarks/bench_phase_offset.py); 1 = lockstep, bit-identical to
    # the previous behavior.
    phase_mod: int = 1


class SwimKnobs(NamedTuple):
    """Traced protocol knobs — the value-like ``SwimParams`` fields as
    device scalars, so a knob change (or a whole per-replica knob grid,
    ``run_sweep(param_axes=...)``) reuses ONE compiled program instead
    of forcing a recompile per point (the dispatch ledger's
    ``recompile_cause`` names exactly these statics).  The policy plane
    established the idiom (policies/core.py PolicyKnobs): every field is
    a 0-d array here and an [R] axis-0 batch under the vmapped sweep.

    Traced-vs-static split (docs/simulation.md has the full matrix):

    * Value knobs trace directly: ``suspicion_ticks``,
      ``piggyback_factor``, ``phase_mod`` (the gossip-cadence divisor),
      ``relay_full_sync`` (a 0/1 scalar masking the always-built 5c
      full-sync machinery — the damping/quarantine masked-mechanism
      precedent), and the damp knobs.
    * ``ping_req_size`` is shape-bearing, so it capacity-pads: the
      program compiles at the static ``SwimParams.ping_req_size``
      (= k_max, fixing every PRNG draw shape) and the traced effective
      k masks witness slots ``>= k``.  Bit-parity with the legacy
      program is therefore pinned at effective k == capacity.
    * ``period_ms`` stays compile-time: it never enters the protocol
      step — it is the tick -> wall-clock scale the traffic plane's
      host-side backoff quantization consumes (traffic/latency.py).

    Dtypes follow each knob's legacy consumption site: the damp
    hysteresis thresholds compare against the float16 damp plane under
    weak scalar promotion, so they ride as float16 (a float32 knob
    would promote the compare and break bit-parity); decay/penalty feed
    float32 arithmetic.  ``knobs=None`` everywhere compiles the exact
    legacy program — the None path changes nothing.
    """

    suspicion_ticks: Any  # int32[] — countdown start is this + 1
    piggyback_factor: Any  # int32[]
    phase_mod: Any  # int32[] — stagger divisor (1 = lockstep)
    relay_full_sync: Any  # int32[] 0/1 — dense-only mechanism gate
    ping_req_size: Any  # int32[] — effective k <= static capacity
    damp_penalty: Any  # float32[]
    damp_decay_per_tick: Any  # float32[]
    damp_suppress: Any  # float16[] — compared against the f16 damp plane
    damp_reuse: Any  # float16[]


# knob name -> target dtype (shared with scenarios/sweep.py's
# param_knob_axes, which builds the [R]-batched form of the same tuple)
SWIM_KNOB_DTYPES = {
    "suspicion_ticks": jnp.int32,
    "piggyback_factor": jnp.int32,
    "phase_mod": jnp.int32,
    "relay_full_sync": jnp.int32,
    "ping_req_size": jnp.int32,
    "damp_penalty": jnp.float32,
    "damp_decay_per_tick": jnp.float32,
    "damp_suppress": jnp.float16,
    "damp_reuse": jnp.float16,
}


def swim_knob_values(params: SwimParams) -> dict[str, float | int]:
    """Host-side knob values implied by ``params`` (the defaults every
    un-swept knob pins to, so traced and legacy programs agree)."""
    return {
        "suspicion_ticks": int(params.suspicion_ticks),
        "piggyback_factor": int(params.piggyback_factor),
        "phase_mod": int(params.phase_mod),
        "relay_full_sync": int(bool(params.relay_full_sync)),
        "ping_req_size": int(params.ping_req_size),
        "damp_penalty": float(params.damp_penalty),
        "damp_decay_per_tick": float(params.damp_decay_per_tick),
        "damp_suppress": float(params.damp_suppress),
        "damp_reuse": float(params.damp_reuse),
    }


def check_knob_value(name: str, v: float | int, params: SwimParams) -> None:
    """Host-side range guard for one traced-knob value (the digit-budget
    check additionally needs ``n`` — ``_validate_params`` owns it)."""
    if name == "suspicion_ticks" and not 0 <= int(v) <= 126:
        raise ValueError(
            f"suspicion_ticks knob {v} outside the int8 countdown "
            "range [0, 126]"
        )
    if name == "ping_req_size" and not 1 <= int(v) <= int(params.ping_req_size):
        raise ValueError(
            f"ping_req_size knob {v} outside the compiled capacity "
            f"[1, {params.ping_req_size}] (capacity-padded knob: raise "
            "SwimParams.ping_req_size to widen the compiled k_max)"
        )
    if name == "phase_mod" and int(v) < 1:
        raise ValueError(f"phase_mod knob must be >= 1, got {v}")
    if name == "relay_full_sync" and int(v) not in (0, 1):
        raise ValueError(f"relay_full_sync knob is 0/1, got {v}")
    if name == "piggyback_factor" and int(v) < 0:
        raise ValueError(f"piggyback_factor knob must be >= 0, got {v}")


def swim_knob_arrays(
    params: SwimParams, overrides: dict[str, float | int] | None = None
) -> SwimKnobs:
    """Device-ify the traced knobs (0-d scalars) for one run.

    ``overrides`` replaces individual knob values (host numbers) before
    the cast; unknown names and out-of-range values fail loudly here,
    on the host, before any trace sees them."""
    vals = swim_knob_values(params)
    if overrides:
        bad = sorted(set(overrides) - set(vals))
        if bad:
            raise ValueError(
                f"unknown traced swim knob(s) {bad}; valid: {sorted(vals)}"
            )
        for k, v in overrides.items():
            check_knob_value(k, v, params)
            vals[k] = v
    return SwimKnobs(
        **{k: jnp.asarray(v, SWIM_KNOB_DTYPES[k]) for k, v in vals.items()}
    )


class ClusterState(NamedTuple):
    """Per-(viewer i, subject j) membership views + dissemination buffers.

    ``view_key[i, j]``: node i's belief about j as a lattice key (see
    module docstring).  ``pb[i, j]``: piggyback count of i's recorded
    change about j (-1: none).  ``suspect_left[i, j]``: ticks until i
    declares j faulty (-1: no timer running).
    """

    view_key: jax.Array  # int32[N, N]
    pb: jax.Array  # int8[N, N]
    suspect_left: jax.Array  # int8[N, N]
    tick: jax.Array  # int32[]
    # Flap-damping extension (None = disabled, zero cost): viewer i's damp
    # score for j and the hysteresis "currently damped" bit (damping.py).
    damp: jax.Array | None = None  # float16[N, N]
    damped: jax.Array | None = None  # bool[N, N]
    # Latency extension (None = disabled, zero cost): the in-flight
    # claim ring buffer for per-link delay (NetState.link_d/link_j —
    # scenarios/faults.py).  Slot ``tick % D`` matures at the START of
    # tick ``tick`` (merged at every up-and-responsive receiver, then
    # cleared); a claim row delayed by d scatters into slot
    # ``(tick + d) % D`` keyed by its receiver, folding colliding
    # senders by the lattice max exactly like the in-tick receiver
    # merge.  Presence also widens the per-tick key split (two jitter
    # streams), so it is installed from tick 0 of a delayed run on both
    # the compiled-scan and host-loop sides (runner.run_compiled /
    # SimCluster.enable_delay).  Network-resident: kill/revive do NOT
    # clear it — messages already in flight still land.
    pending: jax.Array | None = None  # int32[D, N, N]

    @property
    def n(self) -> int:
        return self.view_key.shape[0]

    # Unpacked views (host/test convenience; kernels use view_key directly).

    @property
    def view_status(self) -> jax.Array:
        """int8[N, N] status codes (NONE where the member is unknown)."""
        return (self.view_key & 7).astype(jnp.int8)

    @property
    def view_inc(self) -> jax.Array:
        """int32[N, N] relative incarnations (0 where unknown)."""
        return self.view_key >> 3


class NetState(NamedTuple):
    """The simulated network: the fault-injection surface.

    ``up``: process exists (kill -> False).  ``responsive``: process
    scheduled (SIGSTOP analog -> False; state is retained, the node just
    neither probes nor answers — tick-cluster.js:432-446).  ``adj``:
    directed connectivity — a full bool[N, N] mask (arbitrary
    topologies) or an int32[N] group-id vector (connected iff same
    group: the memory-free form for block netsplits, see ``_adj``).
    ``adj=None`` means fully connected — the healthy-network case never
    ships an all-ones N x N mask through HBM (1 GB at 32k nodes).

    Failure-model extension (all None-default, zero cost when absent;
    scenarios/faults.py):

    * ``link_src``/``link_dst``/``link_p`` — K DIRECTED block loss
      rules: a message from s to r is additionally dropped with the
      composed probability ``1 - prod_k(1 - link_p[k])`` over rules
      with ``link_src[k, s] & link_dst[k, r]``.  O(K * N) memory —
      never an [N, N] matrix — evaluated at the same gathered index
      pairs as ``adj`` (``_drop_net``).  Asymmetry is the point: a
      rule drops src->dst while dst->src flows freely.
    * ``link_d``/``link_j`` — per-rule base delay and jitter bound in
      ticks: claims on a hit link land ``max_k(link_d) + U{0..max_k(
      link_j)}`` ticks later via ``ClusterState.pending``.  Their
      PRESENCE (not value) routes the step through the delay path, so
      they stay None unless the run really delays.
    * ``period`` — int32[N] per-node protocol period: node i initiates
      its probe only on ticks with ``tick % period[i] == phase_i``
      (the gray-failure / phase_mod generalization; timers, witness
      service and deliveries stay per-tick).
    """

    up: jax.Array  # bool[N]
    responsive: jax.Array  # bool[N]
    adj: jax.Array | None = None  # bool[N, N] | int32[N] gid | None
    link_src: jax.Array | None = None  # bool[K, N]
    link_dst: jax.Array | None = None  # bool[K, N]
    link_p: jax.Array | None = None  # float32[K]
    link_d: jax.Array | None = None  # int32[K]
    link_j: jax.Array | None = None  # int32[K]
    period: jax.Array | None = None  # int16[N] | int32[N] (scan carries int16)
    # Load-coupled gray degradation (scenarios/faults.OverloadConfig;
    # None unless an ``overload`` scenario ran/is running): the
    # per-node overload pressure counter accumulated from serve-plane
    # sends vs the capacity knob, and the hysteresis "currently
    # degraded" bit that pins ``period`` to the gray factor.  The step
    # itself never reads these — the scenario scan carries them and
    # applies the EFFECTIVE period; they live here so checkpoints and
    # the final net round-trip the feedback state (stream resume).
    ov_cnt: jax.Array | None = None  # int32[N]
    ov_gray: jax.Array | None = None  # bool[N]
    # Remediation policy plane (ringpop_tpu/policies; None unless a
    # policy-armed run ran/is running): the per-node pressure meter,
    # the admission (shed) and ring-quarantine hysteresis flags, the
    # trailing amplification window rings (total sends / delivered per
    # tick, [amp_window] slots), and the adaptive retry cap.  Same
    # contract as ov_*: the scan carries them, checkpoints and the
    # final net round-trip them bit-exactly (stream resume), and the
    # None default keeps checkpoint format v5 backward-compatible.
    po_press: jax.Array | None = None  # int32[N]
    po_shed: jax.Array | None = None  # bool[N]
    po_quar: jax.Array | None = None  # bool[N]
    po_sends_w: jax.Array | None = None  # int32[W]
    po_deliv_w: jax.Array | None = None  # int32[W]
    po_retry_cap: jax.Array | None = None  # int32 scalar
    # Gossip provenance plane (ringpop_tpu/obs/provenance; None unless
    # a rumor-traced run ran/is running): the K tracked-rumor slots
    # (subject/key/origin/resolution), their origin+resolution ticks,
    # the origin's ping-req witness sets, the per-node first_heard and
    # parent planes, and the packed knows bitplanes.  Same contract as
    # ov_*/po_*: the step never reads these — the scenario scan carries
    # them — and the None default keeps checkpoint v5 compatible.
    pv_slot: jax.Array | None = None  # int32[K, 4]
    pv_tickv: jax.Array | None = None  # int16[K, 2]
    pv_wits: jax.Array | None = None  # int32[K, ping_req_size]
    pv_first: jax.Array | None = None  # int16[K, N]
    pv_parent: jax.Array | None = None  # int32[K, N]
    pv_knows: jax.Array | None = None  # uint32[K, ceil(N/32)] packed


def make_net(n: int, *, partitioned: bool = False) -> NetState:
    """Healthy network; ``partitioned=True`` materializes the adjacency
    mask up front (callers that will edit it per-tick)."""
    return NetState(
        up=jnp.ones((n,), dtype=bool),
        responsive=jnp.ones((n,), dtype=bool),
        adj=jnp.ones((n, n), dtype=bool) if partitioned else None,
    )


def _adj(net: NetState, rows, cols) -> jax.Array | bool:
    """Connectivity lookup that treats ``adj=None`` as all-connected.

    ``adj`` may be the full bool[N, N] mask (arbitrary topologies) or a
    1-D int32[N] *group id* vector — connected iff same group.  The
    kernels only ever evaluate connectivity at [N]- or [N, k]-shaped
    gathered index pairs, so a block partition (the netsplit case,
    BASELINE config 4) never needs the N x N mask materialized: 4 GB
    saved at n=32k, 17 GB at 65k."""
    if net.adj is None:
        return True
    if net.adj.ndim == 1:
        return net.adj[rows] == net.adj[cols]
    return net.adj[rows, cols]


def _check_inc(inc: Any) -> None:
    """Host-boundary validation of relative incarnations (see docstring)."""
    try:
        lo, hi = int(jnp.min(inc)), int(jnp.max(inc))
    except jax.errors.ConcretizationTypeError:
        return  # traced: caller is responsible
    if lo < 0 or hi > INC_MAX:
        raise ValueError(
            f"relative incarnations must be in [0, {INC_MAX}] (got [{lo}, {hi}]); "
            "rebase against a larger base_inc"
        )


def init_state(
    n: int,
    inc: jax.Array | None = None,
    *,
    mode: str = "converged",
    damping: bool = False,
) -> ClusterState:
    """Fresh cluster state.

    ``mode='converged'``: every node already knows every node alive (the
    post-bootstrap fixture for churn/fault benchmarks).  ``mode='self'``:
    each node knows only itself (pre-join; discover via ``admin_join``).
    ``inc``: initial incarnation per node (relative ms), default 0.
    """
    if inc is None:
        inc = jnp.zeros((n,), dtype=jnp.int32)
    inc = jnp.asarray(inc, dtype=jnp.int32)
    _check_inc(inc)
    alive_key = inc * 8 + ALIVE
    eye = jnp.eye(n, dtype=bool)
    if mode == "converged":
        view_key = jnp.broadcast_to(alive_key[None, :], (n, n)).astype(jnp.int32)
    elif mode == "self":
        view_key = jnp.where(eye, alive_key[None, :], 0).astype(jnp.int32)
    else:
        raise ValueError(f"unknown init mode: {mode}")
    return ClusterState(
        view_key=view_key,
        pb=jnp.full((n, n), -1, dtype=jnp.int8),
        suspect_left=jnp.full((n, n), -1, dtype=jnp.int8),
        tick=jnp.zeros((), dtype=jnp.int32),
        damp=jnp.zeros((n, n), dtype=jnp.float16) if damping else None,
        damped=jnp.zeros((n, n), dtype=bool) if damping else None,
    )


# ---------------------------------------------------------------------------
# lattice (membership-update-rules.js over stored keys)
# ---------------------------------------------------------------------------


def _apply_mask(cur_key: jax.Array, in_key: jax.Array) -> jax.Array:
    """Does the incoming claim override the current view entry?

    key-greater, except: an existing ``leave`` entry is only overridden by
    ``alive`` (is_leave/suspect/faulty_override exclude leave members —
    membership-update-rules.js:31-42,54-59), while a first-sighted member
    (cur == 0) takes any change wholesale (membership.js:230-247).
    """
    beats = in_key > cur_key
    leave_guard = ((cur_key & 7) == LEAVE) & ((in_key & 7) != ALIVE)
    return beats & ~leave_guard & (in_key > 0)


def _view_hash(state: ClusterState) -> jax.Array:
    """Cheap commutative per-node view digest, uint32[N].

    Stands in for the membership checksum *inside the protocol* (the
    full-sync trigger needs only equality, dissemination.js:100-118).
    Reported/parity checksums are the real farmhash over the reference's
    string format — see models/checksum.py.
    """
    k = state.view_key.astype(jnp.uint32)
    h = (k * jnp.uint32(0x85EBCA6B)) ^ (k >> jnp.uint32(7))
    h = (h ^ (h >> jnp.uint32(13))) * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> jnp.uint32(16))
    idx = jnp.arange(state.n, dtype=jnp.uint32) * jnp.uint32(0x27D4EB2F)
    h = jnp.where(state.view_key > 0, h ^ idx, jnp.uint32(0))
    return jnp.sum(h, axis=1, dtype=jnp.uint32)


def _max_piggyback(status_ok: jax.Array, factor: int) -> jax.Array:
    """``factor * ceil(log10(server_count + 1))`` per node, exactly
    (dissemination.js:38-55); server count ~ members the node would have
    in its ring (alive + suspect — suspects stay in the ring,
    membership-update-listener.js:34-45).  Clamped to 126 so counts fit
    the int8 ``pb`` store."""
    sc = jnp.sum(status_ok, axis=1, dtype=jnp.int32)
    x = sc + 1
    digits = jnp.zeros_like(x)
    p = jnp.int32(1)
    for _ in range(10):
        digits = digits + (x > p).astype(jnp.int32)
        p = p * 10
    return jnp.minimum(factor * digits, 126)


# Row-length threshold for the memory-lean large-N lowerings: an int32
# row prefix is an extra 6-byte-per-pair-class tensor (17 GB at
# n=65536), which is what pushed the 65k sharded run past a 125 GB
# host.  Tests lower this to exercise the block paths at small n.
_SPARSE_SMALL_N = 32767
_PREFIX_BLOCK = 64  # int8-safe inner prefix width (inner <= 64 < 127)


def _block_prefix(mask: jax.Array) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Two-level row-prefix decomposition of a bool [N, M] mask.

    Returns ``(mb, inner, offs)``: the mask False-padded to a multiple
    of ``_PREFIX_BLOCK`` and reshaped to [N, nb, B]; the *inclusive*
    int8 within-block prefix counts (<= B, int8-safe); and the int32
    *exclusive* per-block offsets [N, nb].  The global inclusive prefix
    of element (i, j) is ``offs[i, j // B] + inner[i, j // B, j % B]`` —
    one int8 [N, M] tensor plus an [N, M/B] int32 instead of an int32
    [N, M] cumsum.  Shared by every large-N lowering below; the int8
    bound, False padding, and exclusive-offset convention are the
    invariants their bit-parity contracts rest on."""
    b = _PREFIX_BLOCK
    pad = (-mask.shape[1]) % b
    m = jnp.pad(mask, ((0, 0), (0, pad))) if pad else mask
    mb = m.reshape(mask.shape[0], -1, b)
    inner = jnp.cumsum(mb.astype(jnp.int8), axis=2)
    block_tot = inner[:, :, -1].astype(jnp.int32)
    offs = jnp.cumsum(block_tot, axis=1) - block_tot
    return mb, inner, offs


def _distinct_ranks(
    count: jax.Array, m: int, key: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """``m`` distinct uniform ranks in ``[0, count)`` per row.

    Sequential shifted-uniform sampling: the t-th draw is uniform over
    ``count - t`` slots, then shifted past each previously-taken rank in
    ascending order — exact sampling without replacement using only
    O(N * m^2) scalar work (no N x N permutation/score tensor).
    Returns (ranks int32[N, m], valid bool[N, m]); rank t is valid iff
    ``count > t``.
    """
    n = count.shape[0]
    u = jax.random.uniform(key, (n, m))
    ranks: list[jax.Array] = []
    valids = []
    for t in range(m):
        space = jnp.maximum(count - t, 1)
        r = jnp.minimum((u[:, t] * space).astype(jnp.int32), space - 1)
        # shift past taken ranks, ascending (insertion into the gap list)
        for taken in sorted_all(ranks):
            r = r + (r >= taken).astype(jnp.int32)
        ranks.append(r)
        valids.append(count > t)
    return jnp.stack(ranks, axis=1), jnp.stack(valids, axis=1)


def sorted_all(xs: list[jax.Array]) -> list[jax.Array]:
    """Elementwise-sorted copies of up to 3 equal-shaped int arrays."""
    if len(xs) <= 1:
        return list(xs)
    if len(xs) == 2:
        a, b = xs
        return [jnp.minimum(a, b), jnp.maximum(a, b)]
    if len(xs) == 3:
        a, b, c = xs
        lo = jnp.minimum(jnp.minimum(a, b), c)
        hi = jnp.maximum(jnp.maximum(a, b), c)
        mid = a + b + c - lo - hi
        return [lo, mid, hi]
    stacked = jnp.sort(jnp.stack(xs, axis=1), axis=1)
    return [stacked[:, i] for i in range(len(xs))]


def _choose_targets_and_witnesses(
    pingable: jax.Array, k: int, key: jax.Array
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Probe target + ``k`` ping-req witnesses per node, by exact rank.

    Draws ``k + 1`` distinct uniform ranks among each row's pingable
    members and locates them in one row cumsum: pick 0 is the probe
    target (uniform among pingable — membership-iterator.js semantics),
    picks 1..k are the witnesses (uniform among the rest, exactly
    getRandomPingableMembers excluding the target,
    ping-req-sender.js:292-295).  The cumsum is int16 when the member
    count fits (half the HBM of an int32 score matrix, and no
    ties/argmax-bias questions — ranks are exact)."""
    n = pingable.shape[0]
    count = jnp.sum(pingable, axis=1, dtype=jnp.int32)
    ranks, valid = _distinct_ranks(count, k + 1, key)
    if n - 1 <= _SPARSE_SMALL_N:
        csum = jnp.cumsum(pingable.astype(jnp.int16), axis=1)
        picks = []
        for t in range(k + 1):
            want = (ranks[:, t] + 1).astype(jnp.int16)
            hit = pingable & (csum == want[:, None])
            picks.append(jnp.argmax(hit, axis=1).astype(jnp.int32))
        target = jnp.where(valid[:, 0], picks[0], -1)
        wit = jnp.stack(picks[1:], axis=1)
        return target, valid[:, 0], wit, valid[:, 1:]
    # Large rows: an int32 [N, N] cumsum is 17 GB at 65k.  Two-level
    # rank lookup over the block-prefix decomposition instead (same
    # picks bit for bit): block by offset binary search, column by
    # within-block prefix binary search.
    b = _PREFIX_BLOCK
    _, inner, offs = _block_prefix(pingable)
    want = ranks + 1  # int32 [N, k+1], 1-based inclusive target
    blk = (
        jax.vmap(lambda o, w: jnp.searchsorted(o, w, side="left"))(offs, want)
        - 1
    )
    blk = jnp.clip(blk, 0, offs.shape[1] - 1)
    residual = want - jnp.take_along_axis(offs, blk, axis=1)  # 1..64
    # gather the int8 blocks FIRST, widen the [N, k+1, b] slice after —
    # widening ``inner`` itself is an int32 [N, nb, 64] copy (17 GB)
    inner_blk = jnp.take_along_axis(
        inner, blk[:, :, None], axis=1
    ).astype(jnp.int32)  # [N, k+1, b]
    within = jax.vmap(
        lambda rows_i, res_i: jax.vmap(
            lambda r, q: jnp.searchsorted(r, q, side="left", method="compare_all")
        )(rows_i, res_i)
    )(inner_blk, residual)
    # invalid ranks (masked by ``valid``) would index past the row; the
    # small-n argmax yields 0 there — clamp for in-bounds gathers only
    picks_all = jnp.minimum((blk * b + within).astype(jnp.int32), n - 1)
    target = jnp.where(valid[:, 0], picks_all[:, 0], -1)
    return target, valid[:, 0], picks_all[:, 1:], valid[:, 1:]


def _drop(key: jax.Array, shape: tuple, loss: float | jax.Array) -> jax.Array:
    """Per-message Bernoulli loss draw (True = dropped).

    ``loss`` is normally a static python float (zero compiles away the
    draw entirely); a traced scalar (the scenario engine's per-tick
    loss schedule, scenarios/runner.py) always draws — value-equal at
    every loss, since ``uniform < 0.0`` is identically False."""
    if isinstance(loss, jax.Array):
        return jax.random.uniform(key, shape) < loss
    if loss <= 0.0:
        return jnp.zeros(shape, dtype=bool)
    return jax.random.uniform(key, shape) < loss


def _link_hit_p(net: NetState, rows, cols) -> jax.Array:
    """float32 per-message extra drop probability from the directed
    link rules, evaluated at gathered (sender, receiver) index pairs
    (the ``_adj`` convention — O(K) per pair, no [N, N] tensor).
    Overlapping rules compose independently: keep = prod(1 - p_k)."""
    hit = net.link_src[:, rows] & net.link_dst[:, cols]  # [K, *shape]
    pk = net.link_p.reshape((-1,) + (1,) * (hit.ndim - 1))
    keep = jnp.prod(jnp.where(hit, 1.0 - pk, 1.0), axis=0)
    return (1.0 - keep).astype(jnp.float32)


def _drop_net(
    key: jax.Array,
    shape: tuple,
    loss: float | jax.Array,
    net: NetState,
    rows,
    cols,
) -> jax.Array:
    """``_drop`` composed with the per-link rules: ONE uniform draw per
    message compared against ``loss + (1 - loss) * p_link``.  With no
    rules installed this IS ``_drop`` (same draw from the same key), so
    rule-free programs and rules-with-zero-p ticks make bit-identical
    decisions — the basis of the host-loop parity for link scenarios
    (the host installs the full masked rule table per segment,
    scenarios/faults.py HostPlan)."""
    if net.link_src is None:
        return _drop(key, shape, loss)
    lp = _link_hit_p(net, rows, cols)
    base = loss if isinstance(loss, jax.Array) else jnp.float32(loss)
    return jax.random.uniform(key, shape) < base + (1.0 - base) * lp


def _link_delay_bounds(
    net: NetState, rows, cols
) -> tuple[jax.Array, jax.Array]:
    """(base int32, jitter bound int32) per message: the maxima over
    the rules hitting the (sender, receiver) pair (inactive rules are
    masked to zero by the caller's schedule, so they contribute 0)."""
    if net.link_d is None:
        z = jnp.zeros(jnp.broadcast_shapes(jnp.shape(rows), jnp.shape(cols)),
                      jnp.int32)
        return z, z
    hit = net.link_src[:, rows] & net.link_dst[:, cols]
    dk = net.link_d.reshape((-1,) + (1,) * (hit.ndim - 1))
    jk = net.link_j.reshape((-1,) + (1,) * (hit.ndim - 1))
    base = jnp.max(jnp.where(hit, dk, 0), axis=0)
    bound = jnp.max(jnp.where(hit, jk, 0), axis=0)
    return base, bound


def _message_delay(
    net: NetState, key: jax.Array, rows, cols, shape: tuple
) -> jax.Array:
    """int32 per-message latency: rule base + uniform in {0..jitter}.
    One uniform draw per message regardless of rule activity, so the
    delayed program's PRNG consumption is schedule-independent (the
    draw exists iff ``ClusterState.pending`` exists)."""
    base, bound = _link_delay_bounds(net, rows, cols)
    u = jax.random.uniform(key, shape)
    extra = jnp.minimum(
        (u * (bound + 1).astype(jnp.float32)).astype(jnp.int32), bound
    )
    return base + extra


def _sweep_divisor(
    phase_mod: int | jax.Array, per: jax.Array | None
) -> jax.Array | None:
    """Per-node sweep-advance divisor for staggered protocol periods,
    or None for the literal lockstep path.  ONE definition shared by
    both backends' selections: the bit-for-bit phase_mod-subsumption
    contract (a period row of P == phase_mod=P, VERDICT item 4) rests
    on the dense and delta arms staying value-identical.

    A TRACED phase_mod (the knob plane) always takes the divide path:
    ``max(pm, 1)`` at pm=1 divides by (traced) one — value-identical to
    the lockstep expression, so the traced program pins bit-equal
    outputs against the legacy compile-time one.  Scenarios with a
    per-node period tensor keep it (the period row subsumes the
    stagger); host-side validation pins the traced knob to 1 there."""
    if per is not None:
        return per
    if isinstance(phase_mod, jax.Array):
        return jnp.maximum(phase_mod, jnp.int32(1))
    if phase_mod > 1:
        return jnp.int32(phase_mod)
    return None


def _stagger_send_gate(
    sends: jax.Array, tick: jax.Array, n: int, phase_mod: int | jax.Array,
    per: jax.Array | None,
) -> jax.Array:
    """Probe-initiation gate for staggered periods (both backends):
    node i initiates only when ``tick mod divisor`` hits its affine
    phase — the same ``(i * 0x9E37|1) mod d`` assignment for the
    static phase_mod and the per-node period tensor, which is what
    makes a row of P reproduce phase_mod=P bit for bit.  Everything
    else (timers, witness service, deliveries) stays per-tick."""
    div = _sweep_divisor(phase_mod, per)
    if div is None:
        return sends
    ids_p = jnp.arange(n, dtype=jnp.int32)
    phase = (ids_p * jnp.int32(0x9E37 | 1)) % div
    return sends & (tick % div == phase)


class _Merge(NamedTuple):
    """Result of applying a batch of incoming changes at each receiver."""

    state: ClusterState
    applied: jax.Array  # bool[N, N] — change applied (incl. refutations)
    refuted: jax.Array  # bool[N] — receiver re-asserted itself alive
    flapped: jax.Array  # bool[N, N] — applied status transition touching alive


@annotate.scoped("swim.merge_incoming")
def _merge_incoming(
    state: ClusterState,
    in_key: jax.Array,  # int32[N, N]: claim about j arriving at receiver r (0 = none)
    active: jax.Array,  # bool[N]: receiver r processes input this tick
    sl_start: int | jax.Array,  # suspicion countdown start value (ticks + 1)
) -> _Merge:
    """Apply one batch of incoming changes at every receiver.

    Implements membership.update's per-change evaluation
    (membership.js:208-313) vectorized: first-sight wholesale, the
    refutation fast-path for self rumors, then the override lattice.
    Applied changes are recorded into the receiver's dissemination buffer
    with piggyback count 0 (membership-update-listener.js:47 ->
    dissemination.recordChange).
    """
    n = state.n
    eye = jnp.eye(n, dtype=bool)
    cur_key = state.view_key
    in_status = in_key & 7

    # Refutation (membership.js:243-254): any suspect/faulty rumor about
    # self — regardless of incarnation — re-asserts alive with an
    # incarnation beating both the rumor and the current self view.
    rumor_self = (
        eye & active[:, None] & ((in_status == SUSPECT) | (in_status == FAULTY))
    )
    refuted = jnp.any(rumor_self, axis=1)
    self_inc = _diag(cur_key) >> 3
    rumor_inc = jnp.where(rumor_self, in_key >> 3, -1).max(axis=1)
    new_self_inc = jnp.maximum(self_inc, rumor_inc) + 1

    apply = (
        _apply_mask(cur_key, in_key)
        & active[:, None]
        & ~eye  # self entries only change via refutation / local ops
    )

    # Flap: an applied transition between alive and suspect/faulty in
    # either direction (damping.py _FLAP_SET semantics; extension).
    flapped = jnp.zeros((), dtype=bool)
    if state.damp is not None:
        was = cur_key & 7
        flapped = apply & (
            ((was == ALIVE) & ((in_status == SUSPECT) | (in_status == FAULTY)))
            | (((was == SUSPECT) | (was == FAULTY)) & (in_status == ALIVE))
        )

    view_key = jnp.where(apply, in_key, cur_key)
    pb = jnp.where(apply, jnp.int8(0), state.pb)

    # Refutation writes the diagonal and records a self-sourced alive change.
    ids = jnp.arange(n, dtype=jnp.int32)
    diag_key = jnp.where(
        refuted, new_self_inc * 8 + ALIVE, _diag(view_key)
    ).astype(jnp.int32)
    view_key = _row_update(view_key, ids, diag_key)
    pb = _row_update(pb, ids, jnp.where(refuted, jnp.int8(0), _diag(pb)))

    applied = apply | (eye & refuted[:, None])

    # Suspicion timers (suspicion.js:45-69 via update-listener:34-45):
    # applied suspect (re)starts the countdown; any other applied status
    # stops it.  (The reference stops only on alive and lets the timer
    # fire as a no-op after a faulty/leave update — same behavior, but
    # clearing it keeps the record inactive so the delta backend's
    # compact/rebase can drop the slot.)
    new_status = view_key & 7
    suspect_left = jnp.where(
        applied & (new_status == SUSPECT),
        jnp.int8(sl_start),
        state.suspect_left,
    )
    suspect_left = jnp.where(
        applied & (new_status != SUSPECT), jnp.int8(-1), suspect_left
    )

    return _Merge(
        state._replace(
            view_key=view_key,
            pb=pb,
            suspect_left=suspect_left,
        ),
        applied,
        refuted,
        flapped,
    )


def _declare(
    state: ClusterState,
    viewer_mask: jax.Array,  # bool[N]
    subject: jax.Array,  # int32[N] (index per viewer; clipped where invalid)
    new_status: int,
    sl_start: int | jax.Array,
) -> tuple[ClusterState, jax.Array]:
    """Local declaration (makeSuspect / makeFaulty, membership.js:141-156):
    viewer i re-labels ``subject[i]`` with its currently-known incarnation,
    applying only where the lattice admits it, and records a self-sourced
    change."""
    n = state.n
    ids = jnp.arange(n, dtype=jnp.int32)
    subj = jnp.clip(subject, 0, n - 1)
    cur = _row_at(state.view_key, subj)
    in_key = jnp.where(cur > 0, (cur >> 3) * 8 + new_status, 0)
    ok = viewer_mask & (subj != ids) & _apply_mask(cur, in_key)
    vk = _row_update(state.view_key, subj, jnp.where(ok, in_key, cur))
    pb = _row_update(
        state.pb, subj, jnp.where(ok, jnp.int8(0), _row_at(state.pb, subj))
    )
    sus = state.suspect_left
    if new_status == SUSPECT:
        sus = _row_update(
            sus, subj, jnp.where(ok, jnp.int8(sl_start), _row_at(sus, subj))
        )
    return state._replace(view_key=vk, pb=pb, suspect_left=sus), ok


# ---------------------------------------------------------------------------
# the protocol period
# ---------------------------------------------------------------------------


class _Selection(NamedTuple):
    """Phases 0-1: period-start views + probe/witness selection (shared
    by the dense and sparse steps so they cannot drift)."""

    gossiping: jax.Array  # bool[N]
    sends: jax.Array  # bool[N]
    t_safe: jax.Array  # int32[N]
    wit: jax.Array  # int32[N, k]
    wit_valid: jax.Array  # bool[N, k]
    maxpb8: jax.Array  # int8[N, 1]
    h_pre: jax.Array  # uint32[N]


def _validate_params(
    n: int,
    params: SwimParams,
    knob_values: dict[str, Any] | None = None,
) -> int:
    """Host-side int8-range guards; returns the suspicion countdown start.

    ``knob_values`` maps a traced-knob name to every host value it will
    take — a one-element list for a single traced run, the full sweep
    axis for ``run_sweep(param_axes=...)``.  The int8 budgets must hold
    at the axis MAXIMUM, not at the ``params`` default the trace-entry
    call sees (the scalar default is all this function ever checked
    before the knob plane), so each axis value is checked individually
    and the error names the offending one."""
    sus_vals = [(int(params.suspicion_ticks), None)]
    fac_vals = [(int(params.piggyback_factor), None)]
    if knob_values:
        if "suspicion_ticks" in knob_values:
            sus_vals = [(int(v), i) for i, v in
                        enumerate(knob_values["suspicion_ticks"])]
        if "piggyback_factor" in knob_values:
            fac_vals = [(int(v), i) for i, v in
                        enumerate(knob_values["piggyback_factor"])]

    def _where(i):
        return "" if i is None else f" (param_axes replica {i})"

    for v, i in sus_vals:
        if v > 126:
            raise ValueError(
                f"suspicion_ticks={v}{_where(i)} exceeds the int8 "
                "countdown range (max 126); raise period_ms instead"
            )
    # _max_piggyback's digit count maxes at len(str(n)): x = count+1 <= n+1
    # and the strict '>' comparisons give ceil(log10(x)) = len(str(x-1)).
    max_digits = len(str(n))
    for v, i in fac_vals:
        if v * max_digits > 126:
            raise ValueError(
                f"piggyback_factor={v}{_where(i)} can exceed the "
                f"int8 piggyback budget at n={n} "
                f"(factor * {max_digits} digits > 126)"
            )
    return int(params.suspicion_ticks) + 1


@annotate.scoped("swim.phase01_select")
def _phase01_select(
    state: ClusterState,
    net: NetState,
    k_sel: jax.Array,
    params: SwimParams,
    knobs: SwimKnobs | None = None,
) -> _Selection:
    """Phase 0 (derived views) + phase 1 (probe targets and witnesses)."""
    n = state.n
    eye = jnp.eye(n, dtype=bool)
    status = state.view_key & 7
    status_ok = (status == ALIVE) | (status == SUSPECT)
    pingable = status_ok & ~eye
    pb_factor = (
        params.piggyback_factor if knobs is None else knobs.piggyback_factor
    )
    phase_mod = params.phase_mod if knobs is None else knobs.phase_mod
    maxpb = _max_piggyback(status_ok, pb_factor)
    h_pre = _view_hash(state)

    own_status = _diag(status)
    gossiping = (
        net.up & net.responsive & ((own_status == ALIVE) | (own_status == SUSPECT))
    )
    if net.period is not None and params.phase_mod > 1:
        raise ValueError(
            "per-node periods (NetState.period, the gray-failure model) "
            "do not compose with the static phase_mod stagger: a row of "
            "P in the period tensor subsumes phase_mod=P exactly"
        )
    per = jnp.maximum(net.period, 1) if net.period is not None else None
    target, has_target, wit, wit_valid = _choose_targets_and_witnesses(
        pingable, params.ping_req_size, k_sel
    )
    if knobs is not None:
        # capacity-padding: the selection (and every phase-5 PRNG draw)
        # runs at the static k_max; the traced effective k masks the
        # tail witness slots out of every downstream delivery column —
        # at k == k_max the mask is all-True and the program is
        # value-identical to the legacy one.
        wit_valid = wit_valid & (
            jnp.arange(params.ping_req_size, dtype=jnp.int32)[None, :]
            < knobs.ping_req_size
        )
    if params.probe == "sweep":
        # Deterministic rotation restores the reference iterator's
        # probe-every-member-per-round guarantee; the rank-picked target
        # remains the fallback when the swept slot is not pingable (and
        # the witness source either way).
        ids = jnp.arange(n, dtype=jnp.int32)
        # static stagger: the multiplier must be coprime to n or whole
        # residue classes share a start and probe the same slot forever
        mult = 0x9E37
        while math.gcd(mult, n) != 1:
            mult += 1
        start = (ids * jnp.int32(mult)) % jnp.int32(n)
        # With staggered periods the sweep index advances once per
        # PROTOCOL PERIOD (tick // P), not per sub-tick: node i only
        # probes on sub-ticks with tick % P == phase_i, and a per-sub-
        # tick sweep would restrict it to the coset {start_i + phase_i
        # + kP} forever — worse, phase_i and start_i share the affine
        # i*mult map, so (start+phase) mod P covered only the subgroup
        # generated by 2*mult mod P and members in the other residue
        # classes were NEVER swept (observed: undetectable victims at
        # P=4).  Per-period advance is the reference iterator's
        # semantics (one target per period per node) and is
        # bit-identical at P=1.
        # per-node periods (gray model) generalize the static divisor:
        # a node with period f advances its sweep once per f ticks —
        # per = full(P) IS phase_mod = P, value for value.  The dense
        # step always divides (P=1 divides by 1, the historical
        # program); the delta selection keeps its literal lockstep
        # expression at div=None — both via the shared _sweep_divisor.
        div = _sweep_divisor(phase_mod, per)
        swept = (
            start + state.tick // (div if div is not None else jnp.int32(1))
        ) % jnp.int32(n)
        ok = _row_at(pingable, swept)
        target = jnp.where(ok, swept, target)
        has_target = has_target | ok
        # witnesses were drawn excluding the rank-picked target; also
        # drop any that collide with the swept one (ping-req-sender.js
        # excludes the probe target from the witness pool)
        wit_valid = wit_valid & (wit != target[:, None])
    elif params.probe != "uniform":
        raise ValueError(f"unknown probe policy: {params.probe!r}")
    # Barrier: the N x N selection cumsum must be dead before phase 3
    # allocates its own N x N buffers — without it XLA's scheduler
    # overlaps their lifetimes and a 32k-node step blows past HBM.
    target, has_target, wit, wit_valid = jax.lax.optimization_barrier(
        (target, has_target, wit, wit_valid)
    )
    sends = _stagger_send_gate(
        gossiping & has_target, state.tick, n, phase_mod, per
    )
    t_safe = jnp.where(sends, target, 0)
    return _Selection(
        gossiping, sends, t_safe, wit, wit_valid, maxpb.astype(jnp.int8)[:, None], h_pre
    )


class _PingReq(NamedTuple):
    """Phase-5 results (dense/sparse shared)."""

    state: ClusterState
    failed: jax.Array  # bool[N]
    declare_suspect: jax.Array  # bool[N]
    declared: jax.Array  # bool[N]
    was_alive_at_target: jax.Array  # bool[N]
    changes_applied: jax.Array  # int32[] — exchange merges, all 4 stages
    flapped: jax.Array  # bool[N, N] | bool[] — exchange flaps (damping)
    relay_full_syncs: jax.Array  # int32[] — 5c full rows (relay_full_sync)


def _stage_issue(
    st: ClusterState, nserve: jax.Array, maxpb8: jax.Array
) -> tuple[ClusterState, jax.Array]:
    """One exchange stage's issue bookkeeping (the phase-4 convention):
    a node serving ``nserve`` requests issues its active in-budget
    changes once (all peers of the stage see the same set), advances
    each issued counter by ``nserve``, and evicts past the budget.
    Returns (state, issued bool[N, N])."""
    has = st.pb >= 0
    ns8 = jnp.minimum(nserve, 127).astype(jnp.int8)[:, None]
    issued = has & (ns8 > 0) & (st.pb + jnp.int8(1) <= maxpb8)
    served = has & (ns8 > 0)
    evict = served & (st.pb > maxpb8 - ns8)
    pb = jnp.where(evict, jnp.int8(-1), jnp.where(served, st.pb + ns8, st.pb))
    return st._replace(pb=pb), issued


@annotate.scoped("swim.pingreq")
def _phase5_pingreq(
    state: ClusterState,
    net: NetState,
    k_loss3: jax.Array,
    sel: _Selection,
    ack: jax.Array,
    sl_start: int | jax.Array,
    params: SwimParams,
    knobs: SwimKnobs | None = None,
) -> _PingReq:
    """Phase 5: failed probes -> ping-req relay with the full piggyback
    exchange -> suspect (ping-req-sender.js, ping-req-handler.js).

    The reference's relay applies membership changes at all four hops:
    the witness applies the source's changes (ping-req-handler.js:37),
    the target applies the witness's ping changes and replies with its
    own (ping-handler.js:34-39 via the handler's sendPing), the witness
    applies the target's reply (ping-req-handler.js:49-50), and the
    source applies every witness response (ping-req-sender.js:138) —
    reachability is then proven *implicitly* by those piggybacked
    updates (ping-req-sender.js:201-204).  Tick-model conventions
    (mirroring phase 4's receiver convention):

    * Each stage computes ONE issue set from its entry state; all peers
      of the stage receive that same set; counters advance by the
      number of requests attempted/served; eviction past the budget.
    * Slot claims fold by lattice max into a single merge per stage
      (the reference applies witness responses in arrival order; both
      end at the lattice maximum).
    * Reply stages apply the value-form anti-echo (drop claims equal to
      what the peer provably already delivered this stage).
    * By default the relay's inner ping omits the full-sync fallback —
      regular pings (phase 4) repair checksum divergence; the relay
      only carries changes.  ``params.relay_full_sync`` closes the
      omission: stage 5c's ack answers a witness with the target's
      entire row when the target has nothing non-echo to issue but its
      post-5b hash differs from the witness's period-start hash (the
      phase-4 rule at the relay hop; measured cost bound in
      BASELINE.md round 6).

    The exchange runs under ``lax.cond``: a tick with every probe acked
    pays nothing for it.
    """
    n = state.n
    ids = jnp.arange(n, dtype=jnp.int32)
    resp = net.up & net.responsive
    t_safe = sel.t_safe
    failed = sel.sends & ~ack
    k_a, k_b, k_c, k_d = jax.random.split(k_loss3, 4)
    kshape = (n, params.ping_req_size)
    wit_safe = jnp.clip(sel.wit, 0, n - 1)
    # hop deliveries: source->witness request, witness->target ping,
    # target->witness ack, witness->source response
    req_del = (
        failed[:, None]
        & sel.wit_valid
        & _adj(net, ids[:, None], wit_safe)
        & ~_drop_net(k_a, kshape, params.loss, net, ids[:, None], wit_safe)
        & resp[wit_safe]
    )
    ping_del = (
        req_del
        & _adj(net, wit_safe, t_safe[:, None])
        & ~_drop_net(k_b, kshape, params.loss, net, wit_safe, t_safe[:, None])
        & resp[t_safe][:, None]
    )
    ack_del = (
        ping_del
        & _adj(net, t_safe[:, None], wit_safe)
        & ~_drop_net(k_c, kshape, params.loss, net, t_safe[:, None], wit_safe)
    )
    resp_del = (
        req_del
        & _adj(net, wit_safe, ids[:, None])
        & ~_drop_net(k_d, kshape, params.loss, net, wit_safe, ids[:, None])
    )
    any_success = jnp.any(ack_del & resp_del, axis=1)
    # all witnesses answered "target unreachable" and none succeeded ->
    # suspect (ping-req-sender.js:238-267); no witness response at all is
    # inconclusive (:268-282)
    definite_fail = jnp.any(req_del & ~ack_del & resp_del, axis=1)
    declare_suspect = failed & ~any_success & definite_fail

    maxpb8 = sel.maxpb8
    kk = params.ping_req_size
    damp_on = state.damp is not None
    # Traced relay_full_sync (the masked-mechanism form): the 5c
    # full-sync machinery is always BUILT when knobs ride along, and a
    # 0/1 scalar masks its slots — fs_slots all-False at 0 reproduces
    # the legacy off program's values, fs_slots unmasked at 1 the
    # legacy on program's (no PRNG lives in the machinery, so the two
    # pin bit-identical either way).
    rfs_knob = None if knobs is None else knobs.relay_full_sync
    rfs_on = None if rfs_knob is None else rfs_knob > 0
    build_fs = params.relay_full_sync or rfs_knob is not None

    def _slot_counts(recv_idx: jax.Array, masks: jax.Array) -> jax.Array:
        """int32[N]: delivered-request count per receiver over all slots."""
        total = jnp.zeros((n,), jnp.int32)
        for m in range(kk):
            total = total + _inbound_counts(recv_idx[:, m], masks[:, m])
        return total

    def _stage_merge(st, acc, pred, build_in, active, name):
        """One exchange stage's merge under a has-claims cond: in the
        converged steady state failed probes happen every tick but
        nobody holds an active change, so every stage's claim matrix is
        zero and the [N, N] gathers/sort-merges must cost nothing.
        ``pred`` (any issued change at a participant) is conservative —
        claims only shrink from there — so a skipped stage is a no-op.
        ``name`` labels the stage in profiler traces (obs.annotate)."""
        applied_total, flapped = acc

        def go(st2):
            with annotate.scope(name):
                mrg = _merge_incoming(st2, build_in(st2), active, sl_start)
            return mrg.state, jnp.sum(mrg.applied, dtype=jnp.int32), mrg.flapped

        def skip(st2):
            return (
                st2,
                jnp.int32(0),
                jnp.zeros((n, n), dtype=bool)
                if damp_on
                else jnp.zeros((), dtype=bool),
            )

        st, ap, fl = jax.lax.cond(pred, go, skip, st)
        st, ap = jax.lax.optimization_barrier((st, ap))
        return st, (applied_total + ap, flapped | fl)

    def exchange(st: ClusterState):
        acc = (
            jnp.int32(0),
            jnp.zeros((n, n), dtype=bool) if damp_on else jnp.zeros((), dtype=bool),
        )

        # -- 5a: the ping-req body carries the source's changes ----------
        nreq = jnp.sum(failed[:, None] & sel.wit_valid, axis=1, dtype=jnp.int32)
        st, issue_src = _stage_issue(st, nreq, maxpb8)
        deliv_src = issue_src & jnp.any(req_del, axis=1)[:, None]
        nsrv = _slot_counts(wit_safe, req_del)

        def in_a(st2):
            claims_src = jnp.where(issue_src, st2.view_key, 0)
            acc_in = jnp.zeros((n, n), jnp.int32)
            for m in range(kk):
                slot_in, _ = _receiver_merge(
                    wit_safe[:, m],
                    req_del[:, m],
                    jnp.where(req_del[:, m][:, None], claims_src, 0),
                )
                acc_in = jnp.maximum(acc_in, slot_in)
            return acc_in

        st, acc = _stage_merge(
            st, acc, jnp.any(issue_src), in_a, nsrv > 0, "swim.pingreq_5a"
        )

        # -- 5b: the witness relay-pings the target with its changes -----
        st, issue_wit = _stage_issue(st, nsrv, maxpb8)
        nping_del = _slot_counts(wit_safe, ping_del)
        deliv_wit = issue_wit & (nping_del > 0)[:, None]
        ntgt = _slot_counts(
            jnp.broadcast_to(t_safe[:, None], kshape), ping_del
        )

        def in_b(st2):
            claims_wit = jnp.where(issue_wit, st2.view_key, 0)
            acc_in = jnp.zeros((n, n), jnp.int32)
            for m in range(kk):
                slot_in, _ = _receiver_merge(
                    t_safe,
                    ping_del[:, m],
                    jnp.where(
                        ping_del[:, m][:, None],
                        _gather_rows(claims_wit, wit_safe[:, m]),
                        0,
                    ),
                )
                acc_in = jnp.maximum(acc_in, slot_in)
            return acc_in

        st, acc = _stage_merge(
            st, acc, jnp.any(issue_wit), in_b, ntgt > 0, "swim.pingreq_5b"
        )

        # -- 5c: the target's ack carries its changes back ----------------
        st, issue_tgt = _stage_issue(st, ntgt, maxpb8)
        nwit_ack = _slot_counts(wit_safe, ack_del)

        fs_slots = None
        relay_fs = jnp.int32(0)
        if build_fs:
            # the relay's inner full sync (SwimParams.relay_full_sync):
            # a target with nothing non-echo to issue to a witness but a
            # diverged view hash answers that witness with its ENTIRE
            # row — the exact phase-4 nothing-to-say rule, evaluated at
            # the ack hop (post-5b views vs the witness's period-start
            # hash, mirroring h_post vs the sender's h_pre)
            h_mid = _view_hash(st)
            rows0 = _gather_rows(jnp.where(issue_tgt, st.view_key, 0), t_safe)
            issue_tgt_t = _gather_rows(issue_tgt, t_safe)
            fs_cols = []
            for m in range(kk):
                w_m = wit_safe[:, m]
                echo0 = _gather_rows(deliv_wit, w_m) & (
                    rows0 == _gather_rows(st.view_key, w_m)
                )
                has_claim = jnp.any(
                    ack_del[:, m][:, None] & issue_tgt_t & ~echo0,
                    axis=1,
                )
                col = (
                    ack_del[:, m]
                    & ~has_claim
                    & (h_mid[t_safe] != sel.h_pre[w_m])
                )
                if rfs_on is not None:
                    col = col & rfs_on
                fs_cols.append(col)
            fs_slots = jnp.stack(fs_cols, axis=1)  # bool[N, kk]
            relay_fs = jnp.sum(fs_slots, dtype=jnp.int32)

        def in_c(st2):
            claims_tgt = jnp.where(issue_tgt, st2.view_key, 0)
            full_rows = _gather_rows(st2.view_key, t_safe)
            rows = _gather_rows(claims_tgt, t_safe)
            acc_in = jnp.zeros((n, n), jnp.int32)
            for m in range(kk):
                w_m = wit_safe[:, m]
                # anti-echo: drop claims equal to what the witness itself
                # delivered to this target in 5b
                echo = _gather_rows(deliv_wit, w_m) & (
                    rows == _gather_rows(st2.view_key, w_m)
                )
                send = jnp.where(ack_del[:, m][:, None] & ~echo, rows, 0)
                if fs_slots is not None:
                    send = jnp.where(
                        fs_slots[:, m][:, None] & (full_rows > 0),
                        full_rows,
                        send,
                    )
                slot_in, _ = _receiver_merge(w_m, ack_del[:, m], send)
                acc_in = jnp.maximum(acc_in, slot_in)
            return acc_in

        pred_c = jnp.any(issue_tgt)
        if fs_slots is not None:
            pred_c = pred_c | jnp.any(fs_slots)
        st, acc = _stage_merge(
            st, acc, pred_c, in_c, nwit_ack > 0, "swim.pingreq_5c"
        )

        # -- 5d: the witness response carries its (fresh) changes ---------
        # issue set from the post-5c state: what the witness just learned
        # from the target (pb 0) ships here — the implicit-alive path
        st, issue_wit2 = _stage_issue(st, nsrv, maxpb8)
        any_resp = jnp.any(resp_del, axis=1)

        def in_d(st2):
            claims_wit2 = jnp.where(issue_wit2, st2.view_key, 0)
            acc_in = jnp.zeros((n, n), jnp.int32)
            for m in range(kk):
                rows = _gather_rows(claims_wit2, wit_safe[:, m])
                echo = deliv_src & (rows == st2.view_key)
                acc_in = jnp.maximum(
                    acc_in,
                    jnp.where(resp_del[:, m][:, None] & ~echo, rows, 0),
                )
            return acc_in

        st, acc = _stage_merge(
            st, acc, jnp.any(issue_wit2), in_d, any_resp, "swim.pingreq_5d"
        )
        return st, acc[0], acc[1], relay_fs

    def no_exchange(st: ClusterState):
        return (
            st,
            jnp.int32(0),
            jnp.zeros((n, n), dtype=bool) if damp_on else jnp.zeros((), dtype=bool),
            jnp.int32(0),
        )

    # With zero active changes cluster-wide the whole exchange is a
    # proven no-op (no claims -> no merges -> no refutations) — the
    # converged-steady-state common case skips even the bookkeeping.
    # (Under relay_full_sync the no-claims shortcut is unsound: a
    # diverged-but-quiet target must still answer full rows.)
    xch_pred = jnp.any(req_del)
    if rfs_on is not None:
        # knob form of the shortcut: sound exactly when the knob is off
        # (value-equal to both legacy programs at the matching value)
        xch_pred = xch_pred & (rfs_on | jnp.any(state.pb >= 0))
    elif not params.relay_full_sync:
        xch_pred = xch_pred & jnp.any(state.pb >= 0)
    state, xch_applied, xch_flapped, relay_fs_total = jax.lax.cond(
        xch_pred, exchange, no_exchange, state
    )

    # the declaration sees the post-exchange view (the reference's
    # makeSuspect runs after every witness response was applied)
    was_alive_at_target = (state.view_key[ids, t_safe] & 7) == ALIVE
    state, declared = _declare(state, declare_suspect, t_safe, SUSPECT, sl_start)
    return _PingReq(
        state,
        failed,
        declare_suspect,
        declared,
        was_alive_at_target,
        xch_applied,
        xch_flapped,
        relay_fs_total,
    )


@annotate.scoped("swim.expiry")
def _phase6_expiry(
    state: ClusterState, gossiping: jax.Array
) -> tuple[ClusterState, jax.Array]:
    """Phase 6: suspicion countdowns fire -> faulty (suspicion.js:66-69)."""
    sl = state.suspect_left
    sl1 = jnp.where(sl > 0, sl - 1, sl)
    expired = (sl1 == 0) & ((state.view_key & 7) == SUSPECT) & gossiping[:, None]
    vk = jnp.where(expired, (state.view_key >> 3) * 8 + FAULTY, state.view_key)
    pb = jnp.where(expired, jnp.int8(0), state.pb)
    sl1 = jnp.where(expired, jnp.int8(-1), sl1)
    return state._replace(view_key=vk, pb=pb, suspect_left=sl1), expired



# Receiver-merge lowering for the dense step (phase 3 plus every
# ping-req slot of stages 5a-5c, all routed through _receiver_merge;
# 5d's response returns to its own source, so it needs no routing).  The
# scatter form (.at[t_safe].max) is the direct expression, but the
# receiver indices collide (several senders ping one receiver) so the
# TPU lowering cannot vectorize it.  The sorted form is exact and
# scatter-free: sort senders by receiver (a flat [N] argsort), permute
# the claim rows once, then run a Hillis-Steele max-doubling within
# equal-receiver runs — the number of [N, N] combine passes is
# ceil(log2(max inbound pings)) (~4 at 32k), bounded dynamically by a
# while_loop, and each receiver's merged row is a final row gather at
# its run start.  The pallas form (ops/recv_merge_pallas.py) keeps the
# flat sort but streams the merge in ONE pass: each claim row is read
# from HBM exactly once and each merged row written once, versus the
# sorted form's permute + log combine passes + gather (4-6 full [N, N]
# HBM passes at 32k).  RINGPOP_RECV_MERGE picks the form at import
# (read again at every trace, so tests can monkeypatch); the
# trajectory-parity grid in tests/test_sim_core.py pins all three
# bit-identical, and benchmarks/hlo_census.py --backend dense shows
# the per-form op budget without a chip.
_RECV_MERGE = os.environ.get("RINGPOP_RECV_MERGE", "sorted")
if _RECV_MERGE not in ("sorted", "scatter", "pallas", "ring"):
    raise ValueError(
        f"RINGPOP_RECV_MERGE={_RECV_MERGE!r}: sorted|scatter|pallas|ring"
    )

# Trace-time override stack for program builders whose lowering needs
# differ from the env default: the sharded mesh path (parallel/mesh.py)
# wraps its jitted calls in _force_recv_merge("ring") — the merge runs
# as shard_map ring hops (ops/gossip_remote_copy.py) so no member plane
# is ever all-gathered — or "sorted" for its gather fallback (the
# single-chip pallas kernel's tpu_custom_call has no SPMD partitioning
# rule either way).  A stack (not a flag) so nested builders compose.
_RECV_MERGE_FORCE: list[str] = []


def _recv_merge_form() -> str:
    return _RECV_MERGE_FORCE[-1] if _RECV_MERGE_FORCE else _RECV_MERGE


@contextlib.contextmanager
def _force_recv_merge(form: str):
    """Force a receiver-merge lowering for programs traced in scope."""
    _RECV_MERGE_FORCE.append(form)
    try:
        yield
    finally:
        _RECV_MERGE_FORCE.pop()


def _pallas_interpret() -> bool:
    """Trace-time interpret-mode decision for the Pallas lowering:
    off-TPU backends degrade to interpret mode, like swim_delta's
    pallas routing, so the env knob (and tier-1 CI) exercise the
    kernel everywhere.  RINGPOP_PALLAS_INTERPRET=0|1 overrides — the
    HLO census forces 0 to lower the real Mosaic kernel for the TPU
    platform from a CPU host (benchmarks/hlo_census.py)."""
    mode = os.environ.get("RINGPOP_PALLAS_INTERPRET", "auto")
    if mode in ("0", "false"):
        return False
    if mode in ("1", "true"):
        return True
    return jax.default_backend() != "tpu"


def _inbound_counts(t_safe: jax.Array, fwd_ok: jax.Array) -> jax.Array:
    """int32[N] delivered-ping count per receiver, scatter-free (sorted
    receivers + run bounds)."""
    n = t_safe.shape[0]
    recv_sorted = jnp.sort(jnp.where(fwd_ok, t_safe, n))
    bounds = jnp.searchsorted(recv_sorted, jnp.arange(n + 1, dtype=jnp.int32))
    return bounds[1:] - bounds[:-1]


@annotate.scoped("swim.recv_merge")
def _receiver_merge(
    t_safe: jax.Array, fwd_ok: jax.Array, claim_rows: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """(in_key int32[N, N], inbound int32[N]): per-receiver lattice max
    of the delivered claim rows, and the delivered-ping count."""
    n = t_safe.shape[0]
    form = _recv_merge_form()
    if form == "ring":
        if _grc.active_ring() is not None:
            return _grc.ring_recv_merge(t_safe, fwd_ok, claim_rows)
        form = "sorted"  # no ambient ring: exact single-device fallback
    if form == "scatter":
        in_key = jnp.zeros((n, n), dtype=jnp.int32).at[t_safe].max(claim_rows)
        inbound = jnp.zeros((n,), jnp.int32).at[t_safe].add(
            fwd_ok.astype(jnp.int32)
        )
        return in_key, inbound
    if form == "pallas":
        from ringpop_tpu.ops.recv_merge_pallas import recv_merge_pallas

        return recv_merge_pallas(
            t_safe, fwd_ok, claim_rows, interpret=_pallas_interpret()
        )

    recv = jnp.where(fwd_ok, t_safe, n)  # n sorts silent senders last
    order = jnp.argsort(recv)
    recv_s = recv[order]
    rows_s = claim_rows[order]
    starts = jnp.searchsorted(recv_s, jnp.arange(n + 1, dtype=jnp.int32))
    inbound = starts[1:] - starts[:-1]
    max_run = jnp.max(inbound, initial=1)

    def cond(carry):
        _, span = carry
        return span < max_run

    def body(carry):
        rows_c, span = carry
        # element i combines with i+span when both are in the same run
        idx = jnp.minimum(jnp.arange(n, dtype=jnp.int32) + span, n - 1)
        same = (recv_s[idx] == recv_s) & (
            jnp.arange(n, dtype=jnp.int32) + span < n
        )
        rows_c = jnp.where(
            same[:, None], jnp.maximum(rows_c, rows_c[idx]), rows_c
        )
        return rows_c, span * 2

    rows_s, _ = jax.lax.while_loop(cond, body, (rows_s, jnp.int32(1)))
    start_c = jnp.minimum(starts[:-1], n - 1)
    in_key = jnp.where((inbound > 0)[:, None], rows_s[start_c], 0)
    return in_key, inbound


def _gather_rows(plane: jax.Array, idx: jax.Array) -> jax.Array:
    """``plane[idx]`` for a member plane indexed across rows.

    On the p2p gossip plane (ring merge form + an ambient
    ``ring_mesh``), the rows are fetched as neighbor-exchange hops so
    the row-sharded plane is never all-gathered; everywhere else this
    is a plain gather.  Exact either way."""
    if _recv_merge_form() == "ring" and _grc.active_ring() is not None:
        return _grc.ring_fetch_rows(plane, idx)
    return plane[idx]


def _on_ring() -> bool:
    return _recv_merge_form() == "ring" and _grc.active_ring() is not None


def _row_at(plane: jax.Array, col: jax.Array) -> jax.Array:
    """``plane[arange(N), col]`` (viewer i's view of column col[i]) —
    shard-local on the p2p gossip plane, where the fused gather's
    [N, 2] index tensor would otherwise be all-gathered."""
    if _on_ring():
        return _grc.ring_take_per_row(plane, col)
    n = plane.shape[0]
    return plane[jnp.arange(n, dtype=jnp.int32), col]


def _diag(plane: jax.Array) -> jax.Array:
    """``jnp.diagonal(plane)`` routed like ``_row_at``."""
    if _on_ring():
        n = plane.shape[0]
        return _grc.ring_take_per_row(plane, jnp.arange(n, dtype=jnp.int32))
    return jnp.diagonal(plane)


def _row_update(
    plane: jax.Array, col: jax.Array, values: jax.Array, op: str = "set"
) -> jax.Array:
    """``plane.at[arange(N), col].set/max(values)`` routed like
    ``_row_at`` (the scatter twin)."""
    if _on_ring():
        return _grc.ring_update_per_row(plane, col, values, op=op)
    n = plane.shape[0]
    upd = plane.at[jnp.arange(n, dtype=jnp.int32), col]
    if op == "set":
        return upd.set(values, unique_indices=True)
    return upd.max(values, unique_indices=True)


def converged_impl(state: ClusterState, net: NetState) -> jax.Array:
    """Exact view agreement among live (gossiping) nodes — the
    convergence predicate ``SimCluster.converged`` jits, shared with
    the scenario scan's per-tick telemetry (scenarios/runner.py).
    Fixed-shape masked compare: no live-set gather, no recompiles as
    the live count changes."""
    own = jnp.diagonal(state.view_key) & 7
    live = net.up & net.responsive & ((own == ALIVE) | (own == SUSPECT))
    ref = jnp.argmax(live)  # first live node's view is the reference view
    # (status, inc) equal iff the packed lattice key is equal.
    row_same = jnp.all(state.view_key == state.view_key[ref][None, :], axis=1)
    return jnp.all(jnp.where(live, row_same, True)) | (jnp.sum(live) <= 1)


def swim_step_impl(
    state: ClusterState,
    net: NetState,
    key: jax.Array,
    params: SwimParams,
    knobs: SwimKnobs | None = None,
    prov: bool = False,
) -> tuple[ClusterState, dict[str, jax.Array]]:
    """One synchronized protocol period for every virtual node.

    Phases (intra-tick order convention, see module docstring):
      1. probe-target + witness selection   (membership-iterator.js)
      2. sender piggyback issue             (dissemination.issueAsSender)
      3. ping delivery + receiver merge     (ping-handler.js:34)
      4. receiver reply (+ full sync) + sender merge  (ping-handler.js:36-39)
      5. failed probes -> ping-req two-hop -> suspect  (ping-req-sender.js)
      6. suspicion countdowns fire -> faulty  (suspicion.js:66-69)

    ``knobs`` (SwimKnobs, optional) replaces the value-like params with
    traced scalars — one compiled program serves every knob value (and
    every replica of a ``param_axes`` sweep); None compiles the exact
    legacy program.

    ``prov`` (static) additionally exports the delivery-evidence bundle
    the provenance plane folds (``obs.provenance.EVIDENCE_KEYS``):
    which protocol edges DELIVERED a payload in-tick, the witness sets,
    and the applied suspect declarations.  The flag changes only the
    metrics dict — the state trajectory and every PRNG draw are
    bit-identical to the off program (the ping-req relay masks are
    state-independent, so re-deriving them here from the same
    ``k_loss3`` stream costs one CSE'd recompute, not a new draw).
    """
    if params.sparse_cap:
        if knobs is not None:
            raise ValueError(
                "sparse_cap selects the sparse-dissemination program, "
                "which keeps its knobs compile-time; run knob sweeps "
                "with sparse_cap=0"
            )
        if state.pending is not None:
            raise NotImplementedError(
                "sparse_cap does not compose with the latency model "
                "(ClusterState.pending); run delay scenarios dense"
            )
        if prov:
            raise NotImplementedError(
                "the provenance plane needs the dense delivery evidence; "
                "run traced scenarios with sparse_cap=0"
            )
        return _swim_step_sparse(state, net, key, params)
    n = state.n
    has_delay = state.pending is not None
    if has_delay:
        # the two extra streams draw the per-message jitter; the split
        # width is keyed on the BUFFER's presence (not rule activity),
        # so every tick of a delayed run — host-loop or compiled scan —
        # consumes keys identically (scenarios/faults.py HostPlan)
        k_sel, k_loss1, k_loss2, k_loss3, k_j1, k_j2 = jax.random.split(key, 6)
    else:
        k_sel, k_loss1, k_loss2, k_loss3 = jax.random.split(key, 4)
    ids = jnp.arange(n, dtype=jnp.int32)
    sl_start: int | jax.Array = _validate_params(n, params)
    if knobs is not None:
        # traced countdown start: int32 scalar, cast to int8 at every
        # write site (jnp.int8(traced) is a cast) — value-equal to the
        # legacy weak-int8 constant whenever the host guard held
        sl_start = knobs.suspicion_ticks + jnp.int32(1)

    # -- in-flight claims mature (latency model) ----------------------------
    # Slot ``tick % D`` lands at the START of the tick, before the
    # period-start views are derived: matured claims are "arrivals
    # overnight" — they shape this tick's selection, hashes, and
    # refutations exactly like claims merged last tick.
    mat_applied = jnp.int32(0)
    mat_flapped: jax.Array | None = None
    if has_delay:
        dd = state.pending.shape[0]
        slot0 = state.tick % jnp.int32(dd)
        mature = state.pending[slot0]
        can_recv = net.up & net.responsive

        def _arrive(st):
            mrg = _merge_incoming(st, mature, can_recv, sl_start)
            return mrg.state, jnp.sum(mrg.applied, dtype=jnp.int32), mrg.flapped

        def _no_arrive(st):
            return (
                st,
                jnp.int32(0),
                jnp.zeros((n, n), dtype=bool)
                if st.damp is not None
                else jnp.zeros((), dtype=bool),
            )

        state, mat_applied, mat_flapped = jax.lax.cond(
            jnp.any(mature > 0), _arrive, _no_arrive, state
        )
        # the slot is consumed either way (a suspended receiver's
        # matured claims are lost, like any packet at a stopped process)
        state = state._replace(pending=state.pending.at[slot0].set(0))

    # -- phases 0-1: derived views + probe/witness selection ----------------
    sel = _phase01_select(state, net, k_sel, params, knobs)
    gossiping, sends, t_safe = sel.gossiping, sel.sends, sel.t_safe
    maxpb8, h_pre = sel.maxpb8, sel.h_pre

    # -- phase 2: sender issues its active changes --------------------------
    # All piggyback arithmetic stays in int8: stored pb <= 126 (the budget
    # clamp), so pb + 1 <= 127 never overflows, and no N x N int32 pb
    # temporary ever materializes (4 GB at n=32k).
    has_change = state.pb >= 0
    bump = has_change & sends[:, None]
    pb_next = jnp.where(bump, state.pb + jnp.int8(1), state.pb)
    issued_s = bump & (pb_next <= maxpb8)
    # eviction past the budget, only on issue attempts (dissemination.js:
    # 147-151; counted even if the packet is then lost in the network)
    pb_next = jnp.where(bump & (pb_next > maxpb8), jnp.int8(-1), pb_next)
    state = state._replace(pb=pb_next)

    # -- phase 3: delivery + receiver-side merge ----------------------------
    resp = net.up & net.responsive
    fwd_ok = (
        sends
        & _adj(net, ids, t_safe)
        & ~_drop_net(k_loss1, (n,), params.loss, net, ids, t_safe)
        & resp[t_safe]
    )
    # delivered[s, j]: sender s issued-and-delivered a claim about j this
    # tick (the anti-echo reference — a pred, not a 4 GB key snapshot).
    # A delayed claim still counts as delivered: it is in the network,
    # and the value-form anti-echo only needs "the sender provably sent
    # this exact value".
    delivered = issued_s & fwd_ok[:, None]
    if has_delay:
        # Latency convention (docs/simulation.md): the ping/ack RTT
        # completes in-tick regardless of delay — the simulation
        # compresses probe round-trips into the probing tick, and
        # latency models slow INFORMATION, not lost liveness — so
        # ``inbound``/acks keep counting every delivered ping, while
        # the claim payload of a delayed link detours through the
        # in-flight buffer and merges d ticks later.
        d3 = _message_delay(net, k_j1, ids, t_safe, (n,))
        dly3 = fwd_ok & (d3 > 0)
        imm3 = fwd_ok & ~dly3
        in_key, _ = _receiver_merge(
            t_safe, imm3, jnp.where(issued_s & imm3[:, None], state.view_key, 0)
        )
        inbound = _inbound_counts(t_safe, fwd_ok)
        dd = state.pending.shape[0]
        slot3 = jnp.where(dly3, (state.tick + d3) % jnp.int32(dd), jnp.int32(dd))
        state = state._replace(
            pending=state.pending.at[slot3, t_safe].max(
                jnp.where(issued_s & dly3[:, None], state.view_key, 0),
                mode="drop",
            )
        )
    else:
        dly3 = jnp.zeros((n,), dtype=bool)
        in_key, inbound = _receiver_merge(
            t_safe, fwd_ok, jnp.where(delivered, state.view_key, 0)
        )
    got_ping = inbound > 0

    merged = _merge_incoming(state, in_key, got_ping, sl_start)
    state = merged.state
    ping_applied = jnp.sum(merged.applied, dtype=jnp.int32)
    # Barrier: in_key (N x N int32) dies here, before phase 4's reply
    # gather allocates (see phase-1 barrier comment).
    state, ping_applied = jax.lax.optimization_barrier((state, ping_applied))

    # -- phase 4: receiver replies; sender merges the ack -------------------
    has_change2 = state.pb >= 0
    # issue-as-receiver: one issued set per tick; counter advances by the
    # number of pings served (documented tick-model convention).
    rep_issuable = (
        has_change2 & got_ping[:, None] & (state.pb + jnp.int8(1) <= maxpb8)
    )
    # pb + inbound could exceed int8, but anything past the budget evicts
    # to -1 anyway — test the eviction bound BEFORE adding (both sides
    # int8-safe: maxpb <= 126, inbound clamps to 127) so the whole update
    # stays int8 with no wider N x N temporary.
    inb8 = jnp.minimum(inbound, 127).astype(jnp.int8)[:, None]
    served = got_ping[:, None] & has_change2
    evict = served & (state.pb > maxpb8 - inb8)
    pb_after = jnp.where(
        evict, jnp.int8(-1), jnp.where(served, state.pb + inb8, state.pb)
    )
    state = state._replace(pb=pb_after)

    h_post = _view_hash(state)
    # per-(sender s, receiver t) view of the reply: the receiver's current
    # claims; anti-echo (value form, see module docstring) drops claims
    # equal to what s itself holds now — s delivered the claim this tick,
    # so equality means s provably already has it.
    reply_key = _gather_rows(state.view_key, t_safe)  # int32[N(snd), N(subj)]
    rep_row = _gather_rows(rep_issuable, t_safe) & ~(
        delivered & (reply_key == state.view_key)
    )
    # full sync (dissemination.js:100-118): nothing to say but checksums
    # disagree -> entire view row
    full_sync = fwd_ok & ~jnp.any(rep_row, axis=1) & (h_post[t_safe] != h_pre)
    send_row = jnp.where(full_sync[:, None], reply_key > 0, rep_row)

    ack = (
        fwd_ok
        & _adj(net, t_safe, ids)
        & ~_drop_net(k_loss2, (n,), params.loss, net, t_safe, ids)
    )

    in2_key = jnp.where(send_row & ack[:, None], reply_key, 0)
    if has_delay:
        # the reply claims ride the receiver->sender link: a delayed
        # reply (full syncs included) detours through the buffer keyed
        # by its sender row; the ack itself still lands in-tick
        d4 = _message_delay(net, k_j2, t_safe, ids, (n,))
        dly4 = ack & (d4 > 0)
        imm4 = ack & ~dly4
        merged2 = _merge_incoming(
            state, jnp.where(imm4[:, None], in2_key, 0), imm4, sl_start
        )
        dd = state.pending.shape[0]
        slot4 = jnp.where(dly4, (state.tick + d4) % jnp.int32(dd), jnp.int32(dd))
        state = merged2.state._replace(
            pending=merged2.state.pending.at[slot4, ids].max(
                jnp.where(dly4[:, None], in2_key, 0), mode="drop"
            )
        )
    else:
        dly4 = jnp.zeros((n,), dtype=bool)
        merged2 = _merge_incoming(state, in2_key, ack, sl_start)
        state = merged2.state
    ack_applied = jnp.sum(merged2.applied, dtype=jnp.int32)

    # -- phase 5: ping-req for failed probes --------------------------------
    pr = _phase5_pingreq(state, net, k_loss3, sel, ack, sl_start, params, knobs)
    state = pr.state
    failed, declare_suspect = pr.failed, pr.declare_suspect
    declared, was_alive_at_target = pr.declared, pr.was_alive_at_target

    # -- phase 6: suspicion countdowns fire -> faulty -----------------------
    state, expired = _phase6_expiry(state, gossiping)

    # -- damping extension (active only with damp tensors present) ----------
    n_damped = jnp.int32(0)
    if state.damp is not None:
        flaps = merged.flapped | merged2.flapped | pr.flapped
        if mat_flapped is not None:
            flaps = flaps | mat_flapped
        # a viewer that itself declares alive->suspect flaps too (the host
        # library scores these via the membership 'updated' event)
        declare_flap = declared & was_alive_at_target
        flaps = _row_update(flaps, t_safe, declare_flap, op="max")
        if knobs is None:
            decay = params.damp_decay_per_tick
            penalty = jnp.float32(params.damp_penalty)
            suppress, reuse = params.damp_suppress, params.damp_reuse
        else:
            # f32 knobs feed the f32 accumulate; the f16 threshold knobs
            # keep the f16-vs-weak-scalar compare dtype (see SwimKnobs)
            decay, penalty = knobs.damp_decay_per_tick, knobs.damp_penalty
            suppress, reuse = knobs.damp_suppress, knobs.damp_reuse
        damp = (
            state.damp.astype(jnp.float32) * decay
            + jnp.where(flaps, penalty, 0.0)
        ).astype(jnp.float16)
        damped = jnp.where(
            damp > suppress,
            True,
            jnp.where(damp < reuse, False, state.damped),
        )
        state = state._replace(damp=damp, damped=damped)
        n_damped = jnp.sum(damped, dtype=jnp.int32)

    state = state._replace(tick=state.tick + 1)
    metrics = {
        "pings_sent": jnp.sum(sends, dtype=jnp.int32),
        "acks": jnp.sum(ack, dtype=jnp.int32),
        "ping_changes_applied": ping_applied,
        "ack_changes_applied": ack_applied,
        "full_syncs": jnp.sum(full_sync, dtype=jnp.int32),
        "ping_reqs": jnp.sum(failed, dtype=jnp.int32),
        "pingreq_changes_applied": pr.changes_applied,
        "suspects_declared": jnp.sum(declare_suspect, dtype=jnp.int32),
        "faulty_declared": jnp.sum(expired, dtype=jnp.int32),
        "damped_pairs": n_damped,
        "relay_full_syncs": pr.relay_full_syncs,
    }
    if has_delay:
        metrics["delayed_claims"] = jnp.sum(dly3, dtype=jnp.int32) + jnp.sum(
            dly4, dtype=jnp.int32
        )
        metrics["matured_applied"] = mat_applied
    if prov:
        # Delivery evidence for the provenance plane.  The four relay
        # hop masks depend only on (net, sel, ack, k_loss3, params) —
        # never on membership state — so re-deriving them from the same
        # k_loss3 stream reproduces _phase5_pingreq's masks bit-for-bit
        # (XLA CSEs the duplicate; the off-path program is untouched).
        k_a, k_b, k_c, k_d = jax.random.split(k_loss3, 4)
        kshape = (n, params.ping_req_size)
        wit_safe = jnp.clip(sel.wit, 0, n - 1)
        req_del = (
            failed[:, None]
            & sel.wit_valid
            & _adj(net, ids[:, None], wit_safe)
            & ~_drop_net(k_a, kshape, params.loss, net, ids[:, None], wit_safe)
            & resp[wit_safe]
        )
        ping_del = (
            req_del
            & _adj(net, wit_safe, t_safe[:, None])
            & ~_drop_net(
                k_b, kshape, params.loss, net, wit_safe, t_safe[:, None]
            )
            & resp[t_safe][:, None]
        )
        ack_del = (
            ping_del
            & _adj(net, t_safe[:, None], wit_safe)
            & ~_drop_net(
                k_c, kshape, params.loss, net, t_safe[:, None], wit_safe
            )
        )
        resp_del = (
            req_del
            & _adj(net, wit_safe, ids[:, None])
            & ~_drop_net(k_d, kshape, params.loss, net, wit_safe, ids[:, None])
        )
        metrics.update(
            pv_tgt=t_safe,
            pv_send=sends,
            # in-tick payload deliveries only: a delayed phase-3 claim
            # (and the dense backend's delayed phase-4 reply, full
            # syncs included) parks in the in-flight buffer — its
            # eventual arrival has no attributable in-tick edge
            pv_ping=fwd_ok & ~dly3,
            pv_ack=ack & ~dly4,
            pv_wit=wit_safe,
            pv_witv=sel.wit_valid,
            pv_req=req_del,
            pv_rping=ping_del,
            pv_rack=ack_del,
            pv_resp=resp_del,
            # APPLIED suspect declarations (the lattice accepted them);
            # prov_update's post-view status gate makes the delta
            # backend's attempted-mask export land on the same set
            pv_decl=declared,
        )
    return state, metrics


# ---------------------------------------------------------------------------
# sparse dissemination (the steady-state fast path, SwimParams.sparse_cap)
# ---------------------------------------------------------------------------


def _capped_within(mask: jax.Array, cap: jax.Array | int) -> jax.Array:
    """``mask & (row-prefix-count(mask) <= cap)`` — the first ``cap``
    True entries per row — without materializing an int32 [N, N] prefix.

    Small rows: plain int16 cumsum.  Large rows: the block-prefix
    decomposition (``_block_prefix``); the compare stays int8 via a
    per-block threshold instead of widening ``inner`` (an int32
    [N, nb, 64] copy is 17 GB at n=65536 even as a temporary).
    """
    n = mask.shape[1]
    if n <= _SPARSE_SMALL_N:
        return mask & (jnp.cumsum(mask.astype(jnp.int16), axis=1) <= cap)
    mb, inner, offs = _block_prefix(mask)
    # inner >= 1 at every True position, so a clip floor of -1 makes
    # exhausted blocks compare False; ceiling 127 = "all fit".
    thr = jnp.clip(cap - offs, -1, 127).astype(jnp.int8)
    within = mb & (inner <= thr[:, :, None])
    within = within.reshape(mask.shape[0], -1)
    return within[:, :n]


def _compact_rows(mask: jax.Array, cap: int) -> jax.Array:
    """Column indices of the first ``cap`` True entries per row, -1 padded.

    int32[N, cap].  Small rows: int16 prefix + one scatter.  Large rows:
    ``lax.scan`` over the ``_block_prefix`` blocks scattering into the
    output — per-iteration temporaries are [N, 64], so no [N, N] int32
    position tensor ever materializes (the scan is sequential, but the
    sparse large-N path is memory-bound, not compute-bound)."""
    n = mask.shape[1]
    rows = jnp.arange(mask.shape[0], dtype=jnp.int32)[:, None]
    if n <= _SPARSE_SMALL_N:
        cidx = jnp.cumsum(mask.astype(jnp.int16), axis=1)
        pos = jnp.where(mask & (cidx <= cap), (cidx - 1).astype(jnp.int32), cap)
        cols = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32)[None, :], mask.shape)
        out = jnp.full((mask.shape[0], cap), -1, dtype=jnp.int32)
        return out.at[jnp.broadcast_to(rows, mask.shape), pos].set(cols, mode="drop")
    b = _PREFIX_BLOCK
    mb, inner, offs = _block_prefix(mask)
    xs = (
        jnp.moveaxis(mb, 1, 0),  # bool[nb, N, b]
        jnp.moveaxis(inner, 1, 0),  # int8[nb, N, b]
        offs.T,  # int32[nb, N]
        jnp.arange(mb.shape[1], dtype=jnp.int32) * b,  # block base column
    )

    def body(out, xs_i):
        blk, inner_b, offs_b, c0 = xs_i
        pos = jnp.where(blk, offs_b[:, None] + inner_b.astype(jnp.int32) - 1, cap)
        pos = jnp.minimum(pos, cap)  # mode="drop" guard stays exact
        cols = c0 + jnp.broadcast_to(
            jnp.arange(b, dtype=jnp.int32)[None, :], blk.shape
        )
        out = out.at[jnp.broadcast_to(rows, blk.shape), pos].set(
            cols, mode="drop"
        )
        return out, None

    out0 = jnp.full((mask.shape[0], cap), -1, dtype=jnp.int32)
    out, _ = jax.lax.scan(body, out0, xs)
    return out


def _point_merge(
    state: ClusterState,
    r_idx: jax.Array,  # int32[B, C] receiver per claim
    subj: jax.Array,  # int32[B, C] subject per claim (-1 = none)
    claim_key: jax.Array,  # int32[B, C]
    valid: jax.Array,  # bool[B, C]
    sl_start: int,
) -> tuple[ClusterState, jax.Array, jax.Array]:
    """Apply compact claim lists by point scatters (the sparse analog of
    ``_merge_incoming``; same lattice, refutation, and bookkeeping, but
    touching only the claimed (receiver, subject) points plus masked int8
    passes — no N x N int32 claim matrix).

    Intra-tick convention difference vs the dense merge (documented): the
    dense path evaluates the override mask on the per-point lattice
    *maximum* claim, the sparse path per claim — they differ only when
    simultaneous claims about one subject straddle a ``leave`` guard,
    where the reference itself is arrival-order-dependent.

    Returns (state, applied bool[N, N], refuted bool[N]).
    """
    n = state.n
    ids = jnp.arange(n, dtype=jnp.int32)
    subj_safe = jnp.clip(subj, 0, n - 1)
    r_safe = jnp.clip(r_idx, 0, n - 1)
    cur = state.view_key[r_safe, subj_safe]
    self_claim = valid & (subj_safe == r_safe)
    normal = valid & (subj_safe != r_safe) & _apply_mask(cur, claim_key)

    # The claim points collide (several senders claim one (receiver,
    # subject) in a tick), and the TPU lowering serializes a scatter it
    # cannot prove conflict-free.  Route like the delta backend instead:
    # flat-sort the claims by (receiver, subject), fold each run to its
    # lattice max with log-step suffix-max doubling, and scatter only
    # run FIRSTS — masked entries get distinct out-of-bounds rows so
    # every index is globally unique and mode="drop" discards them.
    # The apply mask stays evaluated per claim against the pre-merge
    # view (the documented sparse convention), so the fold preserves
    # trajectories bit for bit.
    m = r_safe.size
    fi = jnp.arange(m, dtype=jnp.int32)
    fr = jnp.where(valid, r_safe, n).reshape(-1)
    fs = jnp.where(valid, subj_safe, 0).reshape(-1)
    v_norm = jnp.where(normal, claim_key, 0).reshape(-1)
    v_self = jnp.where(self_claim, claim_key, 0).reshape(-1)
    v_app = normal.reshape(-1).astype(jnp.int32)
    fr, fs, v_norm, v_self, v_app = jax.lax.sort(
        (fr, fs, v_norm, v_self, v_app), num_keys=2
    )
    # a (receiver, subject) run is a sub-run of its receiver's fr-run,
    # so the doubling pass count is bounded dynamically by the largest
    # per-receiver claim count (a couple of passes in realistic ticks),
    # exactly like _receiver_merge's fold — not by the flat length
    fr_bounds = jnp.searchsorted(fr, jnp.arange(n + 1, dtype=jnp.int32))
    max_run = jnp.max(fr_bounds[1:] - fr_bounds[:-1], initial=1)

    def fold_cond(carry):
        return carry[-1] < max_run

    def fold_body(carry):
        v_n, v_s, v_a, span = carry
        idx = jnp.minimum(fi + span, m - 1)
        same = (fr[idx] == fr) & (fs[idx] == fs) & (fi + span < m)
        v_n = jnp.where(same, jnp.maximum(v_n, v_n[idx]), v_n)
        v_s = jnp.where(same, jnp.maximum(v_s, v_s[idx]), v_s)
        v_a = jnp.where(same, jnp.maximum(v_a, v_a[idx]), v_a)
        return v_n, v_s, v_a, span * 2

    v_norm, v_self, v_app, _ = jax.lax.while_loop(
        fold_cond, fold_body, (v_norm, v_self, v_app, jnp.int32(1))
    )
    prev_same = (jnp.pad(fr, (1, 0), constant_values=-1)[:-1] == fr) & (
        jnp.pad(fs, (1, 0), constant_values=-1)[:-1] == fs
    )
    first = ~prev_same & (fr < n)
    # distinct OOB rows for every non-first/invalid entry keep the
    # index set globally unique (n + fi never collides in int32 here)
    u_r = jnp.where(first, fr, n + fi)
    vk = state.view_key.at[u_r, fs].max(
        v_norm, mode="drop", unique_indices=True
    )

    # Refutation (membership.js:243-254), matching the dense convention:
    # the lattice-maximum self-claim decides; a rumor re-asserts alive.
    self_first = first & (fs == fr)
    self_key = (
        jnp.zeros((n,), jnp.int32)
        .at[jnp.where(self_first, fr, n + fi)]
        .max(v_self, mode="drop", unique_indices=True)
    )
    rumor_status = self_key & 7
    refuted = (rumor_status == SUSPECT) | (rumor_status == FAULTY)
    self_inc = jnp.diagonal(state.view_key) >> 3
    new_self_inc = jnp.maximum(self_inc, self_key >> 3) + 1
    vk = vk.at[ids, ids].set(
        jnp.where(refuted, new_self_inc * 8 + ALIVE, jnp.diagonal(vk)),
        unique_indices=True,
    )

    applied = (
        jnp.zeros((n, n), dtype=bool)
        .at[u_r, fs]
        .max(v_app > 0, mode="drop", unique_indices=True)
        .at[ids, ids]
        .max(refuted, unique_indices=True)
    )
    pb = jnp.where(applied, jnp.int8(0), state.pb)
    new_status = vk & 7
    sl = jnp.where(
        applied & (new_status == SUSPECT), jnp.int8(sl_start), state.suspect_left
    )
    sl = jnp.where(applied & (new_status != SUSPECT), jnp.int8(-1), sl)
    return state._replace(view_key=vk, pb=pb, suspect_left=sl), applied, refuted


def _swim_step_sparse(
    state: ClusterState, net: NetState, key: jax.Array, params: SwimParams
) -> tuple[ClusterState, dict[str, jax.Array]]:
    """The protocol period with compact change lists (see SwimParams.sparse_cap).

    Phases 0-2, 5, 6 are the dense code paths (cheap int8/pred work);
    phases 3-4 move the claim traffic onto [N, cap] lists.
    """
    if state.damp is not None:
        raise NotImplementedError("sparse_cap does not support damping tensors")
    n = state.n
    cap = int(params.sparse_cap)
    k_sel, k_loss1, k_loss2, k_loss3 = jax.random.split(key, 4)
    ids = jnp.arange(n, dtype=jnp.int32)
    sl_start = _validate_params(n, params)

    # -- phases 0-1: shared with the dense step -----------------------------
    sel = _phase01_select(state, net, k_sel, params)
    gossiping, sends, t_safe = sel.gossiping, sel.sends, sel.t_safe
    maxpb8, h_pre = sel.maxpb8, sel.h_pre

    # -- phase 2: capped issue; only SENT changes consume budget ------------
    # Entries that would be sent but fall past the cap window neither bump
    # nor evict — they stay active and ship on later pings (otherwise a
    # churn burst of > cap changes would age out entirely unsent).
    has_change = state.pb >= 0
    bump = has_change & sends[:, None]
    pb1 = jnp.where(bump, state.pb + jnp.int8(1), state.pb)
    issue_ok = bump & (pb1 <= maxpb8)
    within = _capped_within(issue_ok, cap)
    overflow_send = issue_ok & ~within
    bump_eff = bump & ~overflow_send
    pb_next = jnp.where(bump_eff, state.pb + jnp.int8(1), state.pb)
    issued_s = within
    pb_next = jnp.where(bump_eff & (pb_next > maxpb8), jnp.int8(-1), pb_next)
    state = state._replace(pb=pb_next)

    # -- phase 3: compact delivery + point merge ----------------------------
    resp = net.up & net.responsive
    fwd_ok = (
        sends
        & _adj(net, ids, t_safe)
        & ~_drop_net(k_loss1, (n,), params.loss, net, ids, t_safe)
        & resp[t_safe]
    )
    subj = _compact_rows(issued_s, cap)  # int32[N, cap], -1 padded
    subj_safe = jnp.clip(subj, 0, n - 1)
    claim_key = state.view_key[ids[:, None], subj_safe]
    valid_claim = (subj >= 0) & fwd_ok[:, None]
    # the sent set as a bitmap (anti-echo reference; capped, unlike the
    # dense `delivered`, because only these entries were actually sent)
    # pad claims (subj < 0, clipped to 0) would collide at column 0;
    # distinct out-of-bounds columns keep the index pairs unique so the
    # TPU scatter vectorizes (mode="drop" discards them)
    delivered = (
        jnp.zeros((n, n), dtype=bool)
        .at[
            ids[:, None],
            jnp.where(
                subj >= 0, subj_safe, n + jnp.arange(cap, dtype=jnp.int32)[None, :]
            ),
        ]
        .max(valid_claim, mode="drop", unique_indices=True)
    )
    inbound = _inbound_counts(t_safe, fwd_ok)
    got_ping = inbound > 0

    r_idx = jnp.broadcast_to(t_safe[:, None], (n, cap))
    state, applied3, _ = _point_merge(
        state, r_idx, subj, claim_key, valid_claim, sl_start
    )
    ping_applied = jnp.sum(applied3, dtype=jnp.int32)
    state, delivered, ping_applied = jax.lax.optimization_barrier(
        (state, delivered, ping_applied)
    )

    # -- phase 4a: receiver piggyback bookkeeping ---------------------------
    # Dense semantics except the cap: issuable entries past the cap window
    # are not sent this tick, so they keep their budget (see phase 2).
    has_change2 = state.pb >= 0
    rep_issuable = (
        has_change2 & got_ping[:, None] & (state.pb + jnp.int8(1) <= maxpb8)
    )
    within_rep = _capped_within(rep_issuable, cap)
    overflow_rep = rep_issuable & ~within_rep
    inb8 = jnp.minimum(inbound, 127).astype(jnp.int8)[:, None]
    served = got_ping[:, None] & has_change2 & ~overflow_rep
    evict = served & (state.pb > maxpb8 - inb8)
    pb_after = jnp.where(
        evict, jnp.int8(-1), jnp.where(served, state.pb + inb8, state.pb)
    )
    state = state._replace(pb=pb_after)
    h_post = _view_hash(state)

    # -- phase 4b: full-sync detection without a dense reply matrix ---------
    # any non-echo issuable claim for sender s = receiver's issuable count
    # minus the issuable-and-echo entries among s's sent subjects.
    rep_count = jnp.sum(within_rep, axis=1, dtype=jnp.int32)
    rcv_key_at = state.view_key[r_idx, subj_safe]
    snd_key_at = state.view_key[ids[:, None], subj_safe]
    echo_issuable = (
        valid_claim
        & within_rep[r_idx, subj_safe]
        & (rcv_key_at == snd_key_at)
    )
    rep_any = rep_count[t_safe] > jnp.sum(echo_issuable, axis=1, dtype=jnp.int32)
    full_sync = fwd_ok & ~rep_any & (h_post[t_safe] != h_pre)
    ack = (
        fwd_ok
        & _adj(net, t_safe, ids)
        & ~_drop_net(k_loss2, (n,), params.loss, net, t_safe, ids)
    )

    def dense_reply(st):
        reply_key = st.view_key[t_safe]
        rep_row = within_rep[t_safe] & ~(delivered & (reply_key == st.view_key))
        send_row = jnp.where(full_sync[:, None], reply_key > 0, rep_row)
        in2_key = jnp.where(send_row & ack[:, None], reply_key, 0)
        merged2 = _merge_incoming(st, in2_key, ack, sl_start)
        return merged2.state, jnp.sum(merged2.applied, dtype=jnp.int32)

    def sparse_reply(st):
        rsubj = _compact_rows(within_rep, cap)  # per receiver
        subj2 = rsubj[t_safe]  # [N(sender), cap]
        subj2_safe = jnp.clip(subj2, 0, n - 1)
        key2 = st.view_key[t_safe[:, None], subj2_safe]
        echo2 = delivered[ids[:, None], subj2_safe] & (
            key2 == st.view_key[ids[:, None], subj2_safe]
        )
        valid2 = (subj2 >= 0) & ack[:, None] & ~echo2
        sidx = jnp.broadcast_to(ids[:, None], (n, cap))
        st2, applied4, _ = _point_merge(st, sidx, subj2, key2, valid2, sl_start)
        return st2, jnp.sum(applied4, dtype=jnp.int32)

    state, ack_applied = jax.lax.cond(
        jnp.any(full_sync), dense_reply, sparse_reply, state
    )

    # -- phase 5: ping-req (shared with the dense step) ---------------------
    pr = _phase5_pingreq(state, net, k_loss3, sel, ack, sl_start, params)
    state, failed, declare_suspect = pr.state, pr.failed, pr.declare_suspect

    # -- phase 6: suspicion countdowns (shared) -----------------------------
    state, expired = _phase6_expiry(state, gossiping)

    state = state._replace(tick=state.tick + 1)
    metrics = {
        "pings_sent": jnp.sum(sends, dtype=jnp.int32),
        "acks": jnp.sum(ack, dtype=jnp.int32),
        "ping_changes_applied": ping_applied,
        "ack_changes_applied": ack_applied,
        "full_syncs": jnp.sum(full_sync, dtype=jnp.int32),
        "ping_reqs": jnp.sum(failed, dtype=jnp.int32),
        "pingreq_changes_applied": pr.changes_applied,
        "suspects_declared": jnp.sum(declare_suspect, dtype=jnp.int32),
        "faulty_declared": jnp.sum(expired, dtype=jnp.int32),
        "damped_pairs": jnp.int32(0),
        "relay_full_syncs": pr.relay_full_syncs,
    }
    return state, metrics



def swim_run_impl(
    state: ClusterState,
    net: NetState,
    key: jax.Array,
    params: SwimParams,
    ticks: int,
    knobs: SwimKnobs | None = None,
) -> tuple[ClusterState, dict[str, jax.Array]]:
    """``ticks`` protocol periods under lax.scan (one compiled program).

    Traced knobs close over the scan body as loop constants — they do
    NOT join the carry, so the pinned carry-dtype multisets are knob-
    invariant (analysis/budgets.py CARRY_BUDGETS)."""

    def body(st, subkey):
        return swim_step_impl(st, net, subkey, params, knobs)

    keys = jax.random.split(key, ticks)
    # Carry is the state alone (scalar metrics stack as scan outputs): a
    # (state, metrics) carry made XLA double-buffer the 4 GB view tensor
    # inside the loop, the difference between fitting 32k nodes and OOM.
    state, ms = jax.lax.scan(body, state, keys)
    metrics = jax.tree_util.tree_map(lambda x: x[-1], ms)
    return state, metrics


# Jitted entry points; ``state`` is donated so long scans run in-place in HBM.
swim_step = jax.jit(
    swim_step_impl, static_argnames=("params", "prov"), donate_argnums=(0,)
)
swim_run = jax.jit(
    swim_run_impl, static_argnames=("params", "ticks"), donate_argnums=(0,)
)


# ---------------------------------------------------------------------------
# host-side membership ops (join / leave / revive — the admin surface)
# ---------------------------------------------------------------------------


def admin_join(state: ClusterState, joiner: int, seed: int) -> ClusterState:
    """Bootstrap join against a seed (join-sender.js + join-handler.js):
    the seed marks the joiner alive and answers with a full membership
    sync; the joiner adopts it wholesale and both record the changes."""
    vk = state.view_key
    j_key = vk[joiner, joiner]
    j_inc = j_key >> 3

    # seed: makeAlive(joiner) (join-handler.js:90)
    in_key = j_inc * 8 + ALIVE
    ok = _apply_mask(vk[seed, joiner], in_key)
    vk = vk.at[seed, joiner].set(jnp.where(ok, in_key, vk[seed, joiner]))
    pb = state.pb.at[seed, joiner].set(
        jnp.where(ok, 0, state.pb[seed, joiner]).astype(jnp.int8)
    )

    # joiner: adopt the seed's row (full sync), keep own self entry, and
    # record everything learned (membership-set-listener.js:33-47)
    row = vk[seed]
    learned = (row > 0) & (jnp.arange(state.n) != joiner)
    vk = vk.at[joiner].set(jnp.where(learned, row, vk[joiner]))
    vk = vk.at[joiner, joiner].set(jnp.where(j_key == 0, jnp.int32(ALIVE), j_key))
    pb = pb.at[joiner].set(jnp.where(learned, 0, pb[joiner]).astype(jnp.int8))
    return state._replace(view_key=vk, pb=pb)


def admin_leave(state: ClusterState, node: int) -> ClusterState:
    """makeLeave(self) (admin-leave-handler.js:48-52): the node marks
    itself leave (stopping its gossip via the own-status gate) and records
    the change for dissemination by peers that ping it."""
    self_inc = state.view_key[node, node] >> 3
    vk = state.view_key.at[node, node].set(self_inc * 8 + LEAVE)
    pb = state.pb.at[node, node].set(0)
    return state._replace(view_key=vk, pb=pb)


def revive(state: ClusterState, node: int, inc: int) -> ClusterState:
    """A killed process restarts fresh (tick-cluster.js:418-430): wipe its
    row to self-only with a new (higher) incarnation; re-entry to the
    cluster is an ``admin_join``."""
    _check_inc(inc)
    n = state.n
    row = jnp.where(
        jnp.arange(n) == node, jnp.int32(inc) * 8 + ALIVE, 0
    ).astype(jnp.int32)
    state = state._replace(
        view_key=state.view_key.at[node].set(row),
        pb=state.pb.at[node].set(-1),
        suspect_left=state.suspect_left.at[node].set(-1),
    )
    if state.damp is not None:  # a fresh process has no damp memory
        state = state._replace(
            damp=state.damp.at[node].set(jnp.float16(0)),
            damped=state.damped.at[node].set(False),
        )
    return state
